// libhs_native — native Parquet column-chunk decoder.
//
// The TPU framework's one ground-up native component (SURVEY.md §7 design
// stance: "a C++ Parquet column-chunk decode path into device-feedable
// buffers"; the reference is 100% JVM and delegates scans to Spark executors,
// SURVEY.md §0). Decodes flat Parquet columns — PLAIN or RLE_DICTIONARY
// encoded; UNCOMPRESSED, SNAPPY, GZIP, or ZSTD — from an mmap'd file straight into
// caller-allocated buffers (numpy arrays on the Python side) with zero copies
// for uncompressed pages, so index scans feed jax.device_put without
// pyarrow/JVM row pivoting.
//
// The framework's own index files are written uncompressed (zero-copy fast
// path); SNAPPY (Spark's default codec, own decompressor), GZIP (system
// zlib), and ZSTD (system libzstd) keep externally-written lake files on the
// native path too. Anything
// outside this dialect returns an error and the Python caller falls back to
// pyarrow.
//
// Build: make -C native  (g++ -O3 -shared -fPIC, links -lz -lzstd)

#include <fcntl.h>
#ifndef HS_NO_ZLIB
#include <zlib.h>
#endif
#if defined(HS_ZSTD_COMPAT)
// Header-less build against a runtime libzstd.so.1 (dev package absent).
// These four symbols are ZSTD's stable public ABI since 1.0 — declaring them
// by hand keeps the codec alive on hosts that ship the library but not zstd.h.
extern "C" {
typedef struct ZSTD_DCtx_s ZSTD_DCtx;
ZSTD_DCtx* ZSTD_createDCtx(void);
size_t ZSTD_freeDCtx(ZSTD_DCtx* dctx);
size_t ZSTD_decompressDCtx(ZSTD_DCtx* dctx, void* dst, size_t dst_capacity,
                           const void* src, size_t src_size);
unsigned ZSTD_isError(size_t code);
}
#elif !defined(HS_NO_ZSTD)
#include <zstd.h>
#endif
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "thrift_compact.h"

namespace hsn {

// ---------------------------------------------------------------------------
// parquet footer model (subset of parquet.thrift)
// ---------------------------------------------------------------------------

enum PhysicalType : int32_t {
  T_BOOLEAN = 0,
  T_INT32 = 1,
  T_INT64 = 2,
  T_INT96 = 3,
  T_FLOAT = 4,
  T_DOUBLE = 5,
  T_BYTE_ARRAY = 6,
  T_FIXED_LEN_BYTE_ARRAY = 7,
};

enum Encoding : int32_t {
  E_PLAIN = 0,
  E_PLAIN_DICTIONARY = 2,
  E_RLE = 3,
  E_RLE_DICTIONARY = 8,
};

enum PageType : int32_t {
  P_DATA_PAGE = 0,
  P_INDEX_PAGE = 1,
  P_DICTIONARY_PAGE = 2,
  P_DATA_PAGE_V2 = 3,
};

struct SchemaElement {
  std::string name;
  int32_t type = -1;             // PhysicalType; -1 for group nodes
  int32_t repetition = 0;        // 0=REQUIRED 1=OPTIONAL 2=REPEATED
  int32_t num_children = 0;
  int32_t type_length = 0;
};

struct ColumnMeta {
  int32_t type = -1;
  std::vector<std::string> path;
  int32_t codec = -1;            // 0 = UNCOMPRESSED
  int64_t num_values = 0;
  int64_t data_page_offset = -1;
  int64_t dictionary_page_offset = -1;
  int64_t total_compressed_size = 0;
};

struct RowGroup {
  std::vector<ColumnMeta> columns;
  int64_t num_rows = 0;
};

struct FileMeta {
  int64_t num_rows = 0;
  std::vector<SchemaElement> schema;
  std::vector<RowGroup> row_groups;
};

static SchemaElement parse_schema_element(Reader& r) {
  SchemaElement e;
  int16_t last = 0;
  Reader::FieldHeader f;
  while (r.read_field(last, f)) {
    switch (f.id) {
      case 1: e.type = static_cast<int32_t>(r.zigzag()); break;
      case 2: e.type_length = static_cast<int32_t>(r.zigzag()); break;
      case 3: e.repetition = static_cast<int32_t>(r.zigzag()); break;
      case 4: e.name = r.binary(); break;
      case 5: e.num_children = static_cast<int32_t>(r.zigzag()); break;
      default: r.skip(f.type);
    }
  }
  return e;
}

static ColumnMeta parse_column_meta(Reader& r) {
  ColumnMeta m;
  int16_t last = 0;
  Reader::FieldHeader f;
  while (r.read_field(last, f)) {
    switch (f.id) {
      case 1: m.type = static_cast<int32_t>(r.zigzag()); break;
      case 3: {
        auto lh = r.read_list();
        for (uint32_t i = 0; i < lh.size; i++) m.path.push_back(r.binary());
        break;
      }
      case 4: m.codec = static_cast<int32_t>(r.zigzag()); break;
      case 5: m.num_values = r.zigzag(); break;
      case 9: m.data_page_offset = r.zigzag(); break;
      case 11: m.dictionary_page_offset = r.zigzag(); break;
      case 7: m.total_compressed_size = r.zigzag(); break;
      default: r.skip(f.type);
    }
  }
  return m;
}

static RowGroup parse_row_group(Reader& r) {
  RowGroup g;
  int16_t last = 0;
  Reader::FieldHeader f;
  while (r.read_field(last, f)) {
    switch (f.id) {
      case 1: {  // columns: list<ColumnChunk>
        auto lh = r.read_list();
        for (uint32_t i = 0; i < lh.size; i++) {
          // ColumnChunk struct
          int16_t cl = 0;
          Reader::FieldHeader cf;
          ColumnMeta m;
          bool have_meta = false;
          while (r.read_field(cl, cf)) {
            if (cf.id == 3 && cf.type == CType::STRUCT) {
              m = parse_column_meta(r);
              have_meta = true;
            } else {
              r.skip(cf.type);
            }
          }
          if (!have_meta) throw ThriftError("column chunk without metadata");
          g.columns.push_back(std::move(m));
        }
        break;
      }
      case 3: g.num_rows = r.zigzag(); break;
      default: r.skip(f.type);
    }
  }
  return g;
}

static FileMeta parse_file_meta(const uint8_t* buf, size_t len) {
  Reader r(buf, len);
  FileMeta fm;
  int16_t last = 0;
  Reader::FieldHeader f;
  while (r.read_field(last, f)) {
    switch (f.id) {
      case 2: {
        auto lh = r.read_list();
        for (uint32_t i = 0; i < lh.size; i++) fm.schema.push_back(parse_schema_element(r));
        break;
      }
      case 3: fm.num_rows = r.zigzag(); break;
      case 4: {
        auto lh = r.read_list();
        for (uint32_t i = 0; i < lh.size; i++) fm.row_groups.push_back(parse_row_group(r));
        break;
      }
      default: r.skip(f.type);
    }
  }
  return fm;
}

// ---------------------------------------------------------------------------
// page headers
// ---------------------------------------------------------------------------

struct PageHeader {
  int32_t type = -1;
  int32_t uncompressed_size = 0;
  int32_t compressed_size = 0;
  // v1
  int32_t num_values = 0;
  int32_t encoding = -1;
  int32_t def_encoding = -1;
  int32_t rep_encoding = -1;
  // v2
  int32_t num_nulls = 0;
  int32_t num_rows = 0;
  int32_t def_bytes = 0;
  int32_t rep_bytes = 0;
  // dictionary
  int32_t dict_num_values = 0;
  int32_t dict_encoding = -1;
  bool v2_is_compressed = true;  // DataPageHeaderV2.is_compressed (default true)
};

// Parses the header and advances *pos past it.
static PageHeader parse_page_header(const uint8_t* base, size_t file_len, size_t* pos) {
  Reader r(base + *pos, file_len - *pos);
  PageHeader h;
  int16_t last = 0;
  Reader::FieldHeader f;
  while (r.read_field(last, f)) {
    switch (f.id) {
      case 1: h.type = static_cast<int32_t>(r.zigzag()); break;
      case 2: h.uncompressed_size = static_cast<int32_t>(r.zigzag()); break;
      case 3: h.compressed_size = static_cast<int32_t>(r.zigzag()); break;
      case 5: {  // DataPageHeader
        int16_t l2 = 0;
        Reader::FieldHeader f2;
        while (r.read_field(l2, f2)) {
          switch (f2.id) {
            case 1: h.num_values = static_cast<int32_t>(r.zigzag()); break;
            case 2: h.encoding = static_cast<int32_t>(r.zigzag()); break;
            case 3: h.def_encoding = static_cast<int32_t>(r.zigzag()); break;
            case 4: h.rep_encoding = static_cast<int32_t>(r.zigzag()); break;
            default: r.skip(f2.type);
          }
        }
        break;
      }
      case 7: {  // DictionaryPageHeader
        int16_t l2 = 0;
        Reader::FieldHeader f2;
        while (r.read_field(l2, f2)) {
          switch (f2.id) {
            case 1: h.dict_num_values = static_cast<int32_t>(r.zigzag()); break;
            case 2: h.dict_encoding = static_cast<int32_t>(r.zigzag()); break;
            default: r.skip(f2.type);
          }
        }
        break;
      }
      case 8: {  // DataPageHeaderV2
        int16_t l2 = 0;
        Reader::FieldHeader f2;
        while (r.read_field(l2, f2)) {
          switch (f2.id) {
            case 1: h.num_values = static_cast<int32_t>(r.zigzag()); break;
            case 2: h.num_nulls = static_cast<int32_t>(r.zigzag()); break;
            case 3: h.num_rows = static_cast<int32_t>(r.zigzag()); break;
            case 4: h.encoding = static_cast<int32_t>(r.zigzag()); break;
            case 5: h.def_bytes = static_cast<int32_t>(r.zigzag()); break;
            case 6: h.rep_bytes = static_cast<int32_t>(r.zigzag()); break;
            case 7: h.v2_is_compressed = f2.bool_value; break;
            default: r.skip(f2.type);
          }
        }
        break;
      }
      default: r.skip(f.type);
    }
  }
  *pos += r.pos(base + *pos);
  return h;
}

// ---------------------------------------------------------------------------
// RLE / bit-packed hybrid (definition levels, dictionary indices)
// ---------------------------------------------------------------------------

static void decode_rle_hybrid(const uint8_t* p, const uint8_t* end, int bit_width,
                              int64_t n, int32_t* out) {
  if (bit_width == 0) {
    std::memset(out, 0, n * sizeof(int32_t));
    return;
  }
  int64_t i = 0;
  const int byte_width = (bit_width + 7) / 8;
  const uint32_t mask = bit_width == 32 ? 0xFFFFFFFFu : ((1u << bit_width) - 1);
  while (i < n) {
    if (p >= end) throw ThriftError("rle: unexpected end of data");
    // varint header
    uint64_t header = 0;
    int shift = 0;
    while (true) {
      if (p >= end) throw ThriftError("rle: truncated header");
      uint8_t b = *p++;
      header |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    if ((header & 1) == 0) {
      // RLE run
      int64_t run = static_cast<int64_t>(header >> 1);
      if (end - p < byte_width) throw ThriftError("rle: truncated run value");
      uint32_t v = 0;
      for (int b = 0; b < byte_width; b++) v |= static_cast<uint32_t>(p[b]) << (8 * b);
      p += byte_width;
      v &= mask;
      int64_t take = std::min(run, n - i);
      for (int64_t k = 0; k < take; k++) out[i + k] = static_cast<int32_t>(v);
      i += take;
    } else {
      // bit-packed run: groups of 8 values
      int64_t groups = static_cast<int64_t>(header >> 1);
      int64_t vals = groups * 8;
      int64_t bytes = groups * bit_width;
      if (end - p < bytes) throw ThriftError("rle: truncated bit-packed run");
      int64_t take = std::min(vals, n - i);
      uint64_t bitpos = 0;
      int64_t k = 0;
      // bit_width <= 32 and bit offset <= 7, so one unaligned 8-byte load
      // always covers a value; run the run body branch-free while a full
      // load stays inside the run, then finish the tail byte-exactly
      if (bytes >= 8) {
        const int64_t fast = std::min(take, ((bytes - 8) * 8) / bit_width + 1);
        for (; k < fast; k++) {
          uint64_t word;
          std::memcpy(&word, p + (bitpos >> 3), 8);
          out[i + k] = static_cast<int32_t>((word >> (bitpos & 7)) & mask);
          bitpos += bit_width;
        }
      }
      for (; k < take; k++) {
        uint64_t byte_idx = bitpos >> 3;
        int bit_off = static_cast<int>(bitpos & 7);
        uint64_t word = 0;
        int avail = static_cast<int>(std::min<int64_t>(8, bytes - static_cast<int64_t>(byte_idx)));
        std::memcpy(&word, p + byte_idx, avail);
        out[i + k] = static_cast<int32_t>((word >> bit_off) & mask);
        bitpos += bit_width;
      }
      p += bytes;
      i += take;
    }
  }
}

// ---------------------------------------------------------------------------
// reader handle
// ---------------------------------------------------------------------------

struct Handle {
  const uint8_t* map = nullptr;
  size_t len = 0;
  int fd = -1;
  FileMeta meta;
  std::vector<int> leaf_schema_idx;  // schema index of each leaf column
  std::string error;

  ~Handle() {
    if (map) munmap(const_cast<uint8_t*>(map), len);
    if (fd >= 0) close(fd);
  }
};

static bool build_leaves(Handle* h) {
  // flat files only: root at schema[0] with N children, each a leaf
  auto& s = h->meta.schema;
  if (s.empty()) { h->error = "empty schema"; return false; }
  size_t idx = 1;
  for (int32_t c = 0; c < s[0].num_children; c++) {
    if (idx >= s.size()) { h->error = "truncated schema"; return false; }
    if (s[idx].num_children > 0) { h->error = "nested schema unsupported"; return false; }
    if (s[idx].repetition == 2) { h->error = "repeated field unsupported"; return false; }
    h->leaf_schema_idx.push_back(static_cast<int>(idx));
    idx++;
  }
  return true;
}

// ---------------------------------------------------------------------------
// snappy decompression (raw format; the one codec Spark writes by default, so
// externally-written lake files stay on this native path instead of falling
// back to pyarrow. Format: google/snappy format_description.txt)
// ---------------------------------------------------------------------------

static bool snappy_varint(const uint8_t* src, size_t n, size_t* val, size_t* used) {
  size_t v = 0;
  int shift = 0;
  size_t i = 0;
  while (i < n && i < 5) {
    uint8_t b = src[i++];
    v |= static_cast<size_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *val = v;
      *used = i;
      return true;
    }
    shift += 7;
  }
  return false;
}

// Decompresses `src[0..n)` into `dst[0..dst_len)`; throws on malformed input
// or any length mismatch.
static void snappy_decompress(const uint8_t* src, size_t n, uint8_t* dst, size_t dst_len) {
  size_t ulen = 0, hdr = 0;
  if (!snappy_varint(src, n, &ulen, &hdr)) throw ThriftError("snappy: bad length header");
  if (ulen != dst_len) throw ThriftError("snappy: uncompressed length mismatch");
  size_t ip = hdr, op = 0;
  while (ip < n) {
    const uint8_t tag = src[ip++];
    uint64_t len;  // 64-bit end to end: a 0xFFFFFFFF extra-byte length must
                   // not wrap on the +1 (or on the narrowing) and desync the parse
    size_t offset = 0;
    switch (tag & 3) {
      case 0: {  // literal; length-1 in high 6 bits (60-63 = extra LE bytes)
        len = (tag >> 2) + 1;
        if (len > 60) {
          const uint32_t extra = static_cast<uint32_t>(len) - 60;
          if (ip + extra > n) throw ThriftError("snappy: truncated literal length");
          len = 0;
          for (uint32_t k = 0; k < extra; k++) len |= static_cast<uint64_t>(src[ip + k]) << (8 * k);
          len += 1;
          ip += extra;
        }
        if (ip + len > n || op + len > dst_len) throw ThriftError("snappy: literal overrun");
        std::memcpy(dst + op, src + ip, len);
        ip += len;
        op += len;
        continue;
      }
      case 1:  // copy, 1-byte offset
        if (ip >= n) throw ThriftError("snappy: truncated copy");
        len = 4 + ((tag >> 2) & 0x7);
        offset = (static_cast<size_t>(tag >> 5) << 8) | src[ip++];
        break;
      case 2:  // copy, 2-byte offset
        if (ip + 2 > n) throw ThriftError("snappy: truncated copy");
        len = (tag >> 2) + 1;
        offset = src[ip] | (static_cast<size_t>(src[ip + 1]) << 8);
        ip += 2;
        break;
      default:  // copy, 4-byte offset
        if (ip + 4 > n) throw ThriftError("snappy: truncated copy");
        len = (tag >> 2) + 1;
        offset = src[ip] | (static_cast<size_t>(src[ip + 1]) << 8) |
                 (static_cast<size_t>(src[ip + 2]) << 16) |
                 (static_cast<size_t>(src[ip + 3]) << 24);
        ip += 4;
        break;
    }
    if (offset == 0 || offset > op || op + len > dst_len)
      throw ThriftError("snappy: bad copy");
    if (offset >= len) {
      std::memcpy(dst + op, dst + op - offset, len);
      op += len;
    } else {
      // overlapping copy replicates a period-`offset` pattern; chunked
      // memcpy with the largest safe multiple of the period (doubles each
      // round) instead of a byte-wise loop
      uint8_t* d = dst + op;
      size_t done = 0;
      while (done < len) {
        const size_t D = offset * ((done + offset) / offset);
        const size_t chunk = std::min(static_cast<size_t>(len) - done, D);
        std::memcpy(d + done, d + done - D, chunk);
        done += chunk;
      }
      op += len;
    }
  }
  if (op != dst_len) throw ThriftError("snappy: short output");
}

enum Codec : int32_t { C_UNCOMPRESSED = 0, C_SNAPPY = 1, C_GZIP = 2, C_ZSTD = 6 };

#ifndef HS_NO_ZSTD
// zstd (parquet codec 6): system libzstd, one reusable decompression context
// per decode thread (context setup is the per-page overhead worth amortizing)
static void zstd_decompress(const uint8_t* src, size_t n, uint8_t* dst, size_t dst_len) {
  if (dst_len == 0) return;  // empty values section (all-null v2 page)
  struct TlsDctx {
    ZSTD_DCtx* ctx;
    TlsDctx() : ctx(ZSTD_createDCtx()) {}
    ~TlsDctx() {
      if (ctx) ZSTD_freeDCtx(ctx);
    }
  };
  thread_local TlsDctx tls;
  if (!tls.ctx) throw ThriftError("zstd: context init failed");
  const size_t got = ZSTD_decompressDCtx(tls.ctx, dst, dst_len, src, n);
  if (ZSTD_isError(got) || got != dst_len)
    throw ThriftError("zstd: malformed or short frame");
}
#endif

#ifndef HS_NO_ZLIB
// gzip (parquet codec 2): zlib inflate with gzip-header wrapping. One inflate
// state per thread, reset per page (reinitializing the ~40KB window for every
// page would dominate small-page decode); decode threads release the GIL, so
// thread_local is the right scope.
static void gzip_decompress(const uint8_t* src, size_t n, uint8_t* dst, size_t dst_len) {
  if (dst_len == 0) return;  // empty values section (all-null v2 page)
  struct TlsInflate {
    z_stream zs;
    bool ok;
    TlsInflate() : zs(), ok(false) {
      // 16+MAX_WBITS: accept a gzip wrapper (parquet-mr writes gzip members)
      ok = inflateInit2(&zs, 16 + MAX_WBITS) == Z_OK;
    }
    ~TlsInflate() {
      if (ok) inflateEnd(&zs);
    }
  };
  thread_local TlsInflate tls;
  if (!tls.ok) throw ThriftError("gzip: init failed");
  if (inflateReset(&tls.zs) != Z_OK) throw ThriftError("gzip: reset failed");
  tls.zs.next_in = const_cast<uint8_t*>(src);
  tls.zs.avail_in = static_cast<uInt>(n);
  tls.zs.next_out = dst;
  tls.zs.avail_out = static_cast<uInt>(dst_len);
  const int rc = inflate(&tls.zs, Z_FINISH);
  const size_t produced = dst_len - tls.zs.avail_out;
  if (rc != Z_STREAM_END || produced != dst_len)
    throw ThriftError("gzip: malformed or short stream");
}
#endif

static bool codec_supported(int32_t codec) {
#ifndef HS_NO_ZLIB
  if (codec == C_GZIP) return true;
#endif
#ifndef HS_NO_ZSTD
  if (codec == C_ZSTD) return true;
#endif
  return codec == C_UNCOMPRESSED || codec == C_SNAPPY;
}

// decompress a page body with the chunk's codec into scratch
static void page_decompress(int32_t codec, const uint8_t* src, size_t n, uint8_t* dst,
                            size_t dst_len) {
  switch (codec) {
    case C_SNAPPY:
      if (dst_len == 0) {
        size_t ulen = 0, hdr = 0;
        if (!snappy_varint(src, n, &ulen, &hdr) || ulen != 0)
          throw ThriftError("snappy: length mismatch on empty page");
        return;
      }
      snappy_decompress(src, n, dst, dst_len);
      return;
#ifndef HS_NO_ZLIB
    case C_GZIP:
      gzip_decompress(src, n, dst, dst_len);
      return;
#endif
#ifndef HS_NO_ZSTD
    case C_ZSTD:
      zstd_decompress(src, n, dst, dst_len);
      return;
#endif
    default:  // keep codec_supported and this switch decoupled-safe
      throw ThriftError("page_decompress: unsupported codec " + std::to_string(codec));
  }
}

// Per-chunk decode state shared by fixed-width and byte-array paths.
struct ChunkCursor {
  const Handle* h;
  const ColumnMeta* cm;
  size_t pos;        // current byte offset in file
  size_t end;        // end of chunk region
  // dictionary (raw PLAIN-encoded dictionary page payload)
  const uint8_t* dict = nullptr;
  size_t dict_len = 0;  // payload length — the bound for parsing dict entries
  int64_t dict_count = 0;
  bool optional;
  // decompressed page bodies (snappy/gzip); dict buffer outlives data pages
  std::vector<uint8_t> page_scratch;
  std::vector<uint8_t> dict_scratch;

  ChunkCursor(const Handle* h_, const ColumnMeta* cm_, bool opt) : h(h_), cm(cm_), optional(opt) {
    int64_t start = cm->data_page_offset;
    if (cm->dictionary_page_offset > 0 && cm->dictionary_page_offset < start)
      start = cm->dictionary_page_offset;
    pos = static_cast<size_t>(start);
    end = pos + static_cast<size_t>(cm->total_compressed_size);
    if (end > h->len) throw ThriftError("column chunk extends past EOF");
  }
};

struct PageData {
  const uint8_t* values;     // start of encoded values
  size_t values_len;
  int32_t num_values;        // rows in page (incl nulls)
  int32_t encoding;
  std::vector<int32_t> defs; // empty if required
};

// Reads the next data page (resolving any dictionary page first); returns
// false at end of chunk.
static bool next_data_page(ChunkCursor& c, PageData& out) {
  while (c.pos < c.end) {
    size_t pos = c.pos;
    PageHeader ph = parse_page_header(c.h->map, c.h->len, &pos);
    const uint8_t* body = c.h->map + pos;
    if (pos + static_cast<size_t>(ph.compressed_size) > c.h->len)
      throw ThriftError("page body extends past EOF");
    c.pos = pos + static_cast<size_t>(ph.compressed_size);
    const int32_t codec = c.cm->codec;
    if (codec == C_UNCOMPRESSED && ph.compressed_size != ph.uncompressed_size)
      throw ThriftError("compressed pages unsupported (codec mismatch)");

    if (ph.type == P_DICTIONARY_PAGE) {
      if (ph.dict_encoding != E_PLAIN && ph.dict_encoding != E_PLAIN_DICTIONARY)
        throw ThriftError("non-PLAIN dictionary page");
      if (codec != C_UNCOMPRESSED) {
        c.dict_scratch.resize(ph.uncompressed_size);
        page_decompress(codec, body, ph.compressed_size, c.dict_scratch.data(),
                        ph.uncompressed_size);
        c.dict = c.dict_scratch.data();
        c.dict_len = static_cast<size_t>(ph.uncompressed_size);
      } else {
        c.dict = body;
        c.dict_len = static_cast<size_t>(ph.compressed_size);
      }
      c.dict_count = ph.dict_num_values;
      continue;
    }
    if (ph.type == P_INDEX_PAGE) continue;

    if (ph.type == P_DATA_PAGE) {
      // v1: the whole body (levels + values) is compressed as one block
      const uint8_t* p = body;
      const uint8_t* bend = body + ph.compressed_size;
      if (codec != C_UNCOMPRESSED) {
        c.page_scratch.resize(ph.uncompressed_size);
        page_decompress(codec, body, ph.compressed_size, c.page_scratch.data(),
                        ph.uncompressed_size);
        p = c.page_scratch.data();
        bend = p + ph.uncompressed_size;
      }
      out.defs.clear();
      if (c.optional) {
        if (ph.def_encoding != E_RLE) throw ThriftError("non-RLE definition levels");
        if (bend - p < 4) throw ThriftError("truncated def level block");
        uint32_t dlen;
        std::memcpy(&dlen, p, 4);
        p += 4;
        if (static_cast<size_t>(bend - p) < dlen) throw ThriftError("truncated def levels");
        out.defs.resize(ph.num_values);
        decode_rle_hybrid(p, p + dlen, 1, ph.num_values, out.defs.data());
        p += dlen;
      }
      out.values = p;
      out.values_len = static_cast<size_t>(bend - p);
      out.num_values = ph.num_values;
      out.encoding = ph.encoding;
      return true;
    }
    if (ph.type == P_DATA_PAGE_V2) {
      const uint8_t* p = body;
      const uint8_t* bend = body + ph.compressed_size;
      if (ph.rep_bytes > 0) throw ThriftError("repetition levels unsupported");
      out.defs.clear();
      if (ph.def_bytes < 0 || ph.rep_bytes < 0 ||
          static_cast<int64_t>(ph.def_bytes) + ph.rep_bytes > ph.compressed_size ||
          static_cast<int64_t>(ph.def_bytes) + ph.rep_bytes > ph.uncompressed_size)
        throw ThriftError("v2 page level sizes exceed page body");
      if (c.optional) {
        out.defs.resize(ph.num_values);
        decode_rle_hybrid(p, p + ph.def_bytes, 1, ph.num_values, out.defs.data());
      }
      p += ph.def_bytes;
      if (codec != C_UNCOMPRESSED && ph.v2_is_compressed) {
        // v2 keeps rep/def levels uncompressed; only the values section is
        // a compressed block
        const size_t vals_unc = static_cast<size_t>(ph.uncompressed_size) -
                                static_cast<size_t>(ph.def_bytes) -
                                static_cast<size_t>(ph.rep_bytes);
        c.page_scratch.resize(vals_unc);
        page_decompress(codec, p, static_cast<size_t>(bend - p), c.page_scratch.data(), vals_unc);
        out.values = c.page_scratch.data();
        out.values_len = vals_unc;
        out.num_values = ph.num_values;
        out.encoding = ph.encoding;
        return true;
      }
      out.values = p;
      out.values_len = static_cast<size_t>(bend - p);
      out.num_values = ph.num_values;
      out.encoding = ph.encoding;
      return true;
    }
    throw ThriftError("unknown page type " + std::to_string(ph.type));
  }
  return false;
}

static int physical_width(int32_t t, int32_t type_length) {
  switch (t) {
    case T_INT32: return 4;
    case T_INT64: return 8;
    case T_FLOAT: return 4;
    case T_DOUBLE: return 8;
    case T_INT96: return 12;
    case T_FIXED_LEN_BYTE_ARRAY: return type_length;
    default: return -1;
  }
}

// ---------------------------------------------------------------------------
// per-chunk decoders (one row group × one column). These are the shared
// bodies behind both the whole-file readers and the row-group-granular ABI:
// they touch only caller-provided buffers and throw on malformed input, so
// concurrent calls on one read-only Handle are thread-safe.
// ---------------------------------------------------------------------------

// Fixed-width chunk into `dst` (chunk-local row 0 at dst[0]). Returns rows.
static int64_t decode_fixed_chunk(const Handle* h, const SchemaElement& se,
                                  const ColumnMeta& cm, int width, uint8_t* dst,
                                  uint8_t* validity) {
  if (!codec_supported(cm.codec))
    throw ThriftError("unsupported codec " + std::to_string(cm.codec));
  ChunkCursor cur(h, &cm, se.repetition == 1);
  PageData pd;
  std::vector<int32_t> idx;
  int64_t row = 0;
  while (next_data_page(cur, pd)) {
    const int64_t n = pd.num_values;
    int64_t present = n;
    if (!pd.defs.empty()) {
      present = 0;
      for (int32_t d : pd.defs) present += (d != 0);
    }
    if (pd.encoding == E_PLAIN) {
      if (se.type == T_BOOLEAN) {
        // bit-packed LSB-first
        std::vector<uint8_t> vals(present);
        if (pd.values_len * 8 < static_cast<size_t>(present))
          throw ThriftError("truncated boolean page");
        for (int64_t k = 0; k < present; k++)
          vals[k] = (pd.values[k >> 3] >> (k & 7)) & 1;
        if (pd.defs.empty()) {
          std::memcpy(dst + row * width, vals.data(), present);
          if (validity) std::memset(validity + row, 1, n);
        } else {
          int64_t vi = 0;
          for (int64_t k = 0; k < n; k++) {
            bool v = pd.defs[k] != 0;
            dst[(row + k)] = v ? vals[vi++] : 0;
            if (validity) validity[row + k] = v;
          }
        }
        row += n;
        continue;
      }
      if (pd.values_len < static_cast<size_t>(present) * width)
        throw ThriftError("truncated PLAIN page");
      if (pd.defs.empty()) {
        std::memcpy(dst + row * width, pd.values, static_cast<size_t>(n) * width);
        if (validity) std::memset(validity + row, 1, n);
      } else {
        int64_t vi = 0;
        for (int64_t k = 0; k < n; k++) {
          if (pd.defs[k] != 0) {
            std::memcpy(dst + (row + k) * width, pd.values + vi * width, width);
            vi++;
          } else {
            std::memset(dst + (row + k) * width, 0, width);
          }
          if (validity) validity[row + k] = pd.defs[k] != 0;
        }
      }
      row += n;
    } else if (pd.encoding == E_RLE && se.type == T_BOOLEAN) {
      // RLE boolean values (data page v2 writes booleans this way):
      // 4-byte LE length prefix, then RLE/bit-packed hybrid at width 1
      if (pd.values_len < 4) throw ThriftError("truncated RLE boolean page");
      uint32_t rlen;
      std::memcpy(&rlen, pd.values, 4);
      if (pd.values_len < 4 + static_cast<size_t>(rlen))
        throw ThriftError("truncated RLE boolean page body");
      idx.assign(present, 0);
      decode_rle_hybrid(pd.values + 4, pd.values + 4 + rlen, 1, present, idx.data());
      int64_t vi = 0;
      for (int64_t k = 0; k < n; k++) {
        bool v = pd.defs.empty() || pd.defs[k] != 0;
        dst[row + k] = v ? static_cast<uint8_t>(idx[vi++]) : 0;
        if (validity) validity[row + k] = v;
      }
      row += n;
    } else if (pd.encoding == E_RLE_DICTIONARY || pd.encoding == E_PLAIN_DICTIONARY) {
      if (!cur.dict) throw ThriftError("dictionary page missing");
      if (pd.values_len < 1) throw ThriftError("empty dictionary-encoded page");
      int bw = pd.values[0];
      if (bw < 0 || bw > 32) throw ThriftError("bad dictionary bit width");
      if (static_cast<uint64_t>(cur.dict_count) * width > cur.dict_len)
        throw ThriftError("truncated dictionary");  // header claims more entries than payload holds
      idx.assign(present, 0);
      decode_rle_hybrid(pd.values + 1, pd.values + pd.values_len, bw, present, idx.data());
      // hoist the bounds check out of the gather: one pass over the codes,
      // then width-specialized branch-free copies (the per-value check +
      // variable-width memcpy pair dominated dict-coded decode)
      int32_t lo = 0, hi = -1;
      for (int64_t k = 0; k < present; k++) {
        lo = std::min(lo, idx[k]);
        hi = std::max(hi, idx[k]);
      }
      if (present > 0 && (lo < 0 || hi >= cur.dict_count))
        throw ThriftError("dictionary index out of range");
      if (pd.defs.empty()) {
        uint8_t* d = dst + row * width;
        if (width == 8) {
          for (int64_t k = 0; k < n; k++)
            std::memcpy(d + k * 8, cur.dict + static_cast<int64_t>(idx[k]) * 8, 8);
        } else if (width == 4) {
          for (int64_t k = 0; k < n; k++)
            std::memcpy(d + k * 4, cur.dict + static_cast<int64_t>(idx[k]) * 4, 4);
        } else {
          for (int64_t k = 0; k < n; k++)
            std::memcpy(d + k * width, cur.dict + static_cast<int64_t>(idx[k]) * width, width);
        }
        if (validity) std::memset(validity + row, 1, n);
      } else {
        int64_t vi = 0;
        for (int64_t k = 0; k < n; k++) {
          bool v = pd.defs[k] != 0;
          if (v) {
            std::memcpy(dst + (row + k) * width,
                        cur.dict + static_cast<int64_t>(idx[vi++]) * width, width);
          } else {
            std::memset(dst + (row + k) * width, 0, width);
          }
          if (validity) validity[row + k] = v;
        }
      }
      row += n;
    } else {
      throw ThriftError("unsupported encoding " + std::to_string(pd.encoding));
    }
  }
  return row;
}

// BYTE_ARRAY chunk. `offsets` points at this chunk's first row slot and
// `offsets[0]` must already hold *nbytes (the running payload offset in the
// shared `data` buffer, which is NOT pre-offset). With data == NULL only
// offsets/validity are filled (sizing pass). Returns rows; advances *nbytes.
static int64_t decode_binary_chunk(const Handle* h, const SchemaElement& se,
                                   const ColumnMeta& cm, int64_t* offsets,
                                   uint8_t* data, uint8_t* validity,
                                   int64_t* nbytes) {
  if (!codec_supported(cm.codec))
    throw ThriftError("unsupported codec " + std::to_string(cm.codec));
  ChunkCursor cur(h, &cm, se.repetition == 1);
  PageData pd;
  std::vector<int32_t> idx;
  // dictionary spans: resolved lazily per chunk
  std::vector<std::pair<const uint8_t*, uint32_t>> dict_spans;
  bool dict_resolved = false;
  int64_t row = 0;
  while (next_data_page(cur, pd)) {
    const int64_t n = pd.num_values;
    int64_t present = n;
    if (!pd.defs.empty()) {
      present = 0;
      for (int32_t d : pd.defs) present += (d != 0);
    }
    if (pd.encoding == E_PLAIN) {
      const uint8_t* p = pd.values;
      const uint8_t* bend = pd.values + pd.values_len;
      int64_t vi = 0;
      for (int64_t k = 0; k < n; k++) {
        bool v = pd.defs.empty() || pd.defs[k] != 0;
        uint32_t len = 0;
        if (v) {
          if (bend - p < 4) throw ThriftError("truncated byte array length");
          std::memcpy(&len, p, 4);
          p += 4;
          if (static_cast<size_t>(bend - p) < len) throw ThriftError("truncated byte array");
          if (data) std::memcpy(data + *nbytes, p, len);
          p += len;
          vi++;
        }
        *nbytes += len;
        offsets[row + k + 1] = *nbytes;
        if (validity) validity[row + k] = v;
      }
      row += n;
    } else if (pd.encoding == E_RLE_DICTIONARY || pd.encoding == E_PLAIN_DICTIONARY) {
      if (!cur.dict) throw ThriftError("dictionary page missing");
      if (!dict_resolved) {
        dict_spans.clear();
        const uint8_t* p = cur.dict;
        // bound by the dictionary PAYLOAD length: a decompressed dict
        // lives in heap scratch, so any file-offset bound (h->map +
        // cur.end) is meaningless for it — comparing heap pointers
        // against mmap offsets made decode fail or pass depending on
        // address-space layout
        const uint8_t* dend = cur.dict + cur.dict_len;
        for (int64_t d = 0; d < cur.dict_count; d++) {
          if (dend - p < 4) throw ThriftError("truncated dictionary");
          uint32_t len;
          std::memcpy(&len, p, 4);
          p += 4;
          if (static_cast<size_t>(dend - p) < len) throw ThriftError("truncated dictionary");
          dict_spans.emplace_back(p, len);
          p += len;
        }
        dict_resolved = true;
      }
      if (pd.values_len < 1) throw ThriftError("empty dictionary-encoded page");
      int bw = pd.values[0];
      if (bw < 0 || bw > 32) throw ThriftError("bad dictionary bit width");
      idx.assign(present, 0);
      decode_rle_hybrid(pd.values + 1, pd.values + pd.values_len, bw, present, idx.data());
      int64_t vi = 0;
      for (int64_t k = 0; k < n; k++) {
        bool v = pd.defs.empty() || pd.defs[k] != 0;
        uint32_t len = 0;
        if (v) {
          int32_t di = idx[vi++];
          if (di < 0 || di >= (int32_t)dict_spans.size())
            throw ThriftError("dictionary index out of range");
          len = dict_spans[di].second;
          if (data) std::memcpy(data + *nbytes, dict_spans[di].first, len);
        }
        *nbytes += len;
        offsets[row + k + 1] = *nbytes;
        if (validity) validity[row + k] = v;
      }
      row += n;
    } else {
      throw ThriftError("unsupported encoding " + std::to_string(pd.encoding));
    }
  }
  return row;
}

// Dictionary codes for a fully dictionary-encoded chunk: codes[k] is the
// dictionary index of row k, -1 for nulls. Any PLAIN page (dictionary
// fallback overflow) throws — the caller falls back to value decode.
static int64_t decode_codes_chunk(const Handle* h, const SchemaElement& se,
                                  const ColumnMeta& cm, int32_t* codes) {
  if (!codec_supported(cm.codec))
    throw ThriftError("unsupported codec " + std::to_string(cm.codec));
  ChunkCursor cur(h, &cm, se.repetition == 1);
  PageData pd;
  std::vector<int32_t> idx;
  int64_t row = 0;
  while (next_data_page(cur, pd)) {
    const int64_t n = pd.num_values;
    int64_t present = n;
    if (!pd.defs.empty()) {
      present = 0;
      for (int32_t d : pd.defs) present += (d != 0);
    }
    if (pd.encoding != E_RLE_DICTIONARY && pd.encoding != E_PLAIN_DICTIONARY)
      throw ThriftError("page not dictionary-encoded");
    if (!cur.dict) throw ThriftError("dictionary page missing");
    if (pd.values_len < 1) throw ThriftError("empty dictionary-encoded page");
    int bw = pd.values[0];
    if (bw < 0 || bw > 32) throw ThriftError("bad dictionary bit width");
    if (pd.defs.empty()) {
      // required column: unpack straight into the caller's codes slab (no
      // staging copy), then validate the whole page in one pass
      decode_rle_hybrid(pd.values + 1, pd.values + pd.values_len, bw, n, codes + row);
      int32_t lo = 0, hi = -1;
      for (int64_t k = 0; k < n; k++) {
        lo = std::min(lo, codes[row + k]);
        hi = std::max(hi, codes[row + k]);
      }
      if (n > 0 && (lo < 0 || hi >= cur.dict_count))
        throw ThriftError("dictionary index out of range");
    } else {
      idx.assign(present, 0);
      decode_rle_hybrid(pd.values + 1, pd.values + pd.values_len, bw, present, idx.data());
      int32_t lo = 0, hi = -1;
      for (int64_t k = 0; k < present; k++) {
        lo = std::min(lo, idx[k]);
        hi = std::max(hi, idx[k]);
      }
      if (present > 0 && (lo < 0 || hi >= cur.dict_count))
        throw ThriftError("dictionary index out of range");
      int64_t vi = 0;
      for (int64_t k = 0; k < n; k++)
        codes[row + k] = pd.defs[k] != 0 ? idx[vi++] : -1;
    }
    row += n;
  }
  return row;
}

// Per-call error reporting for the row-group ABI: concurrent workers share
// one Handle, so Handle::error (a std::string) is off limits there.
static void fill_err(char* err, int32_t cap, const char* msg) {
  if (!err || cap <= 0) return;
  std::snprintf(err, static_cast<size_t>(cap), "%s", msg);
}

static const ColumnMeta* rg_column(Handle* h, int32_t rg, int32_t col,
                                   const SchemaElement** se_out, char* err,
                                   int32_t err_cap) {
  if (col < 0 || col >= (int32_t)h->leaf_schema_idx.size()) {
    fill_err(err, err_cap, "column index out of range");
    return nullptr;
  }
  if (rg < 0 || rg >= (int32_t)h->meta.row_groups.size()) {
    fill_err(err, err_cap, "row group index out of range");
    return nullptr;
  }
  const auto& g = h->meta.row_groups[rg];
  if (col >= (int32_t)g.columns.size()) {
    fill_err(err, err_cap, "row group missing column");
    return nullptr;
  }
  *se_out = &h->meta.schema[h->leaf_schema_idx[col]];
  return &g.columns[col];
}

}  // namespace hsn

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

using namespace hsn;

extern "C" {

void* hsn_open(const char* path) {
  auto h = std::make_unique<Handle>();
  h->fd = open(path, O_RDONLY);
  if (h->fd < 0) return nullptr;
  struct stat st;
  if (fstat(h->fd, &st) != 0 || st.st_size < 12) return nullptr;
  h->len = static_cast<size_t>(st.st_size);
  void* m = mmap(nullptr, h->len, PROT_READ, MAP_PRIVATE, h->fd, 0);
  if (m == MAP_FAILED) return nullptr;
  h->map = static_cast<const uint8_t*>(m);
  if (std::memcmp(h->map + h->len - 4, "PAR1", 4) != 0) return nullptr;
  uint32_t flen;
  std::memcpy(&flen, h->map + h->len - 8, 4);
  if (flen + 8 > h->len) return nullptr;
  try {
    h->meta = parse_file_meta(h->map + h->len - 8 - flen, flen);
    if (!build_leaves(h.get())) {
      // keep the handle alive so the caller can read the error
      return h.release();
    }
  } catch (const std::exception& e) {
    return nullptr;
  }
  return h.release();
}

void hsn_close(void* hp) { delete static_cast<Handle*>(hp); }

const char* hsn_error(void* hp) {
  auto* h = static_cast<Handle*>(hp);
  return h->error.empty() ? nullptr : h->error.c_str();
}

int64_t hsn_num_rows(void* hp) { return static_cast<Handle*>(hp)->meta.num_rows; }

int32_t hsn_num_columns(void* hp) {
  return static_cast<int32_t>(static_cast<Handle*>(hp)->leaf_schema_idx.size());
}

const char* hsn_column_name(void* hp, int32_t i) {
  auto* h = static_cast<Handle*>(hp);
  if (i < 0 || i >= (int32_t)h->leaf_schema_idx.size()) return nullptr;
  return h->meta.schema[h->leaf_schema_idx[i]].name.c_str();
}

int32_t hsn_column_type(void* hp, int32_t i) {
  auto* h = static_cast<Handle*>(hp);
  if (i < 0 || i >= (int32_t)h->leaf_schema_idx.size()) return -1;
  return h->meta.schema[h->leaf_schema_idx[i]].type;
}

int32_t hsn_column_optional(void* hp, int32_t i) {
  auto* h = static_cast<Handle*>(hp);
  if (i < 0 || i >= (int32_t)h->leaf_schema_idx.size()) return -1;
  return h->meta.schema[h->leaf_schema_idx[i]].repetition == 1 ? 1 : 0;
}

// Decode a fixed-width column (INT32/INT64/FLOAT/DOUBLE/BOOLEAN) across all
// row groups into `out` (num_rows elements of the physical width; BOOLEAN
// decodes to one byte per value). `validity` (nullable) receives 1/0 per row.
// Null slots in `out` are zero-filled. Returns rows decoded, or -1 (see
// hsn_error).
int64_t hsn_read_fixed(void* hp, int32_t col, void* out, uint8_t* validity) {
  auto* h = static_cast<Handle*>(hp);
  if (col < 0 || col >= (int32_t)h->leaf_schema_idx.size()) {
    h->error = "column index out of range";
    return -1;
  }
  const auto& se = h->meta.schema[h->leaf_schema_idx[col]];
  const int width = se.type == T_BOOLEAN ? 1 : physical_width(se.type, se.type_length);
  if (width <= 0) {
    h->error = "not a fixed-width column";
    return -1;
  }
  uint8_t* dst = static_cast<uint8_t*>(out);
  int64_t row = 0;
  try {
    for (const auto& rg : h->meta.row_groups) {
      if (col >= (int32_t)rg.columns.size()) throw ThriftError("row group missing column");
      row += decode_fixed_chunk(h, se, rg.columns[col], width, dst + row * width,
                                validity ? validity + row : nullptr);
    }
  } catch (const std::exception& e) {
    h->error = e.what();
    return -1;
  }
  return row;
}

// BYTE_ARRAY decode. offsets must hold num_rows+1 int64s. If data == NULL the
// function only fills offsets (so the caller can allocate exactly); otherwise
// data must hold offsets[num_rows] bytes. Null rows get empty spans.
// Returns rows decoded or -1.
int64_t hsn_read_binary(void* hp, int32_t col, int64_t* offsets, uint8_t* data,
                        uint8_t* validity) {
  auto* h = static_cast<Handle*>(hp);
  if (col < 0 || col >= (int32_t)h->leaf_schema_idx.size()) {
    h->error = "column index out of range";
    return -1;
  }
  const auto& se = h->meta.schema[h->leaf_schema_idx[col]];
  if (se.type != T_BYTE_ARRAY) {
    h->error = "not a BYTE_ARRAY column";
    return -1;
  }
  int64_t row = 0;
  int64_t nbytes = 0;
  offsets[0] = 0;
  try {
    for (const auto& rg : h->meta.row_groups) {
      if (col >= (int32_t)rg.columns.size()) throw ThriftError("row group missing column");
      row += decode_binary_chunk(h, se, rg.columns[col], offsets + row, data,
                                 validity ? validity + row : nullptr, &nbytes);
    }
  } catch (const std::exception& e) {
    h->error = e.what();
    return -1;
  }
  return row;
}

// ---------------------------------------------------------------------------
// Row-group-granular ABI. One call decodes one (row group × column) chunk
// into caller-provided buffers; the Python side offsets the output pointers
// to the chunk's row slot, so a thread pool fans out across (file, row group,
// column) tasks writing disjoint slices of shared per-column buffers. These
// entry points never touch Handle::error — errors go to the per-call `err`
// buffer (err_cap bytes) — so concurrent calls on one handle are safe.
// ---------------------------------------------------------------------------

int32_t hsn_num_row_groups(void* hp) {
  return static_cast<int32_t>(static_cast<Handle*>(hp)->meta.row_groups.size());
}

int64_t hsn_rg_num_rows(void* hp, int32_t rg) {
  auto* h = static_cast<Handle*>(hp);
  if (rg < 0 || rg >= (int32_t)h->meta.row_groups.size()) return -1;
  return h->meta.row_groups[rg].num_rows;
}

// Parquet codec id (0=uncompressed 1=snappy 2=gzip 6=zstd) of one chunk;
// -1 when out of range. Feeds the hs_native_decode_total{codec} label.
int32_t hsn_rg_codec(void* hp, int32_t rg, int32_t col) {
  auto* h = static_cast<Handle*>(hp);
  if (rg < 0 || rg >= (int32_t)h->meta.row_groups.size()) return -1;
  const auto& g = h->meta.row_groups[rg];
  if (col < 0 || col >= (int32_t)g.columns.size()) return -1;
  return g.columns[col].codec;
}

// Fixed-width chunk decode; `out`/`validity` point at the chunk's first row.
// Returns rows decoded or -1 (message in `err`).
int64_t hsn_read_fixed_rg(void* hp, int32_t rg, int32_t col, void* out,
                          uint8_t* validity, char* err, int32_t err_cap) {
  auto* h = static_cast<Handle*>(hp);
  const SchemaElement* se = nullptr;
  const ColumnMeta* cm = rg_column(h, rg, col, &se, err, err_cap);
  if (!cm) return -1;
  const int width = se->type == T_BOOLEAN ? 1 : physical_width(se->type, se->type_length);
  if (width <= 0) {
    fill_err(err, err_cap, "not a fixed-width column");
    return -1;
  }
  try {
    return decode_fixed_chunk(h, *se, *cm, width, static_cast<uint8_t*>(out), validity);
  } catch (const std::exception& e) {
    fill_err(err, err_cap, e.what());
    return -1;
  }
}

// BYTE_ARRAY chunk decode with chunk-local offsets (offsets[0] = 0; must hold
// chunk rows + 1 int64s). data == NULL sizes only. Returns rows or -1.
int64_t hsn_read_binary_rg(void* hp, int32_t rg, int32_t col, int64_t* offsets,
                           uint8_t* data, uint8_t* validity, char* err,
                           int32_t err_cap) {
  auto* h = static_cast<Handle*>(hp);
  const SchemaElement* se = nullptr;
  const ColumnMeta* cm = rg_column(h, rg, col, &se, err, err_cap);
  if (!cm) return -1;
  if (se->type != T_BYTE_ARRAY) {
    fill_err(err, err_cap, "not a BYTE_ARRAY column");
    return -1;
  }
  int64_t nbytes = 0;
  offsets[0] = 0;
  try {
    return decode_binary_chunk(h, *se, *cm, offsets, data, validity, &nbytes);
  } catch (const std::exception& e) {
    fill_err(err, err_cap, e.what());
    return -1;
  }
}

// Dictionary codes for a fully dictionary-encoded chunk (codes[k] = dict
// index, -1 = null). Fails — distinct "page not dictionary-encoded" message —
// if any data page fell back to PLAIN, so callers can retry as values.
int64_t hsn_read_codes_rg(void* hp, int32_t rg, int32_t col, int32_t* codes,
                          char* err, int32_t err_cap) {
  auto* h = static_cast<Handle*>(hp);
  const SchemaElement* se = nullptr;
  const ColumnMeta* cm = rg_column(h, rg, col, &se, err, err_cap);
  if (!cm) return -1;
  try {
    return decode_codes_chunk(h, *se, *cm, codes);
  } catch (const std::exception& e) {
    fill_err(err, err_cap, e.what());
    return -1;
  }
}

// Dictionary entry count for a chunk: 0 when the chunk has no dictionary
// page, -1 on error. Cheap — parses page headers up to the first data page.
int64_t hsn_rg_dict_count(void* hp, int32_t rg, int32_t col, char* err,
                          int32_t err_cap) {
  auto* h = static_cast<Handle*>(hp);
  const SchemaElement* se = nullptr;
  const ColumnMeta* cm = rg_column(h, rg, col, &se, err, err_cap);
  if (!cm) return -1;
  if (!codec_supported(cm->codec)) {
    fill_err(err, err_cap, "unsupported codec");
    return -1;
  }
  try {
    ChunkCursor cur(h, cm, se->repetition == 1);
    PageData pd;
    next_data_page(cur, pd);  // resolves a leading dictionary page if present
    return cur.dict ? cur.dict_count : 0;
  } catch (const std::exception& e) {
    fill_err(err, err_cap, e.what());
    return -1;
  }
}

// BYTE_ARRAY dictionary payload for one chunk. `offsets` must hold
// dict_count + 1 int64s; with data == NULL only offsets are filled (sizing
// pass). Returns the entry count or -1.
int64_t hsn_read_dict_binary_rg(void* hp, int32_t rg, int32_t col,
                                int64_t* offsets, uint8_t* data, char* err,
                                int32_t err_cap) {
  auto* h = static_cast<Handle*>(hp);
  const SchemaElement* se = nullptr;
  const ColumnMeta* cm = rg_column(h, rg, col, &se, err, err_cap);
  if (!cm) return -1;
  if (se->type != T_BYTE_ARRAY) {
    fill_err(err, err_cap, "not a BYTE_ARRAY column");
    return -1;
  }
  if (!codec_supported(cm->codec)) {
    fill_err(err, err_cap, "unsupported codec");
    return -1;
  }
  try {
    ChunkCursor cur(h, cm, se->repetition == 1);
    PageData pd;
    next_data_page(cur, pd);
    if (!cur.dict) {
      fill_err(err, err_cap, "no dictionary page");
      return -1;
    }
    const uint8_t* p = cur.dict;
    const uint8_t* dend = cur.dict + cur.dict_len;
    int64_t nbytes = 0;
    offsets[0] = 0;
    for (int64_t d = 0; d < cur.dict_count; d++) {
      if (dend - p < 4) throw ThriftError("truncated dictionary");
      uint32_t len;
      std::memcpy(&len, p, 4);
      p += 4;
      if (static_cast<size_t>(dend - p) < len) throw ThriftError("truncated dictionary");
      if (data) std::memcpy(data + nbytes, p, len);
      p += len;
      nbytes += len;
      offsets[d + 1] = nbytes;
    }
    return cur.dict_count;
  } catch (const std::exception& e) {
    fill_err(err, err_cap, e.what());
    return -1;
  }
}

// ---------------------------------------------------------------------------
// Sorted-merge join kernels (host side of the shuffle-free bucketed SMJ).
// Both key arrays must be ascending (the index dialect guarantees per-bucket
// sortedness). One O(n+m) walk replaces two O(n log m) binary-search passes,
// and pair expansion fills the gather indices without intermediate arrays.
// ---------------------------------------------------------------------------

// Per left row, the [lo, hi) span of equal keys on the right.
void hsn_merge_spans(const int64_t* lk, int64_t n, const int64_t* rk, int64_t m,
                     int32_t* lo, int32_t* hi) {
  int64_t r = 0;
  int64_t i = 0;
  while (i < n) {
    const int64_t key = lk[i];
    while (r < m && rk[r] < key) r++;
    int64_t r2 = r;
    while (r2 < m && rk[r2] == key) r2++;
    int64_t i2 = i;
    while (i2 < n && lk[i2] == key) i2++;
    for (int64_t j = i; j < i2; j++) {
      lo[j] = static_cast<int32_t>(r);
      hi[j] = static_cast<int32_t>(r2);
    }
    i = i2;
    r = r2;
  }
}

// Expand spans into (left row, right row) gather indices. `lidx`/`ridx` must
// hold sum(hi-lo) elements; returns the number written.
int64_t hsn_expand_pairs(const int32_t* lo, const int32_t* hi, int64_t n,
                         int32_t* lidx, int32_t* ridx) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; i++) {
    const int32_t a = lo[i], b = hi[i];
    for (int32_t r = a; r < b; r++) {
      lidx[off] = static_cast<int32_t>(i);
      ridx[off] = r;
      off++;
    }
  }
  return off;
}

// Standalone raw-snappy decompression (used by the Python Avro codec for
// snappy-compressed blocks; Avro frames carry the uncompressed size via the
// snappy preamble). Returns 0 on success, -1 on malformed input.
int32_t hsn_snappy_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                              int64_t dst_len) {
  try {
    hsn::snappy_decompress(src, static_cast<size_t>(src_len), dst,
                           static_cast<size_t>(dst_len));
    return 0;
  } catch (const std::exception&) {
    return -1;
  }
}

// Uncompressed length from a raw-snappy preamble; -1 on malformed input.
int64_t hsn_snappy_uncompressed_length(const uint8_t* src, int64_t src_len) {
  size_t val = 0, used = 0;
  if (!hsn::snappy_varint(src, static_cast<size_t>(src_len), &val, &used)) return -1;
  return static_cast<int64_t>(val);
}

}  // extern "C"
