// Minimal Thrift Compact Protocol reader — just what the Parquet footer and
// page headers need. Hand-written against the thrift compact spec; the
// reference framework has no native code at all (SURVEY.md §2 "Native
// components: none"), so this file has no reference counterpart: it exists to
// feed TPU HBM from Parquet without a JVM or even pyarrow in the hot loop.
#pragma once

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace hsn {

struct ThriftError : std::runtime_error {
  explicit ThriftError(const std::string& m) : std::runtime_error(m) {}
};

// compact-protocol wire types
enum class CType : uint8_t {
  STOP = 0,
  TRUE_ = 1,
  FALSE_ = 2,
  BYTE = 3,
  I16 = 4,
  I32 = 5,
  I64 = 6,
  DOUBLE = 7,
  BINARY = 8,
  LIST = 9,
  SET = 10,
  MAP = 11,
  STRUCT = 12,
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}

  size_t pos(const uint8_t* base) const { return static_cast<size_t>(p_ - base); }
  const uint8_t* cursor() const { return p_; }

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (true) {
      need(1);
      uint8_t b = *p_++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) throw ThriftError("varint overflow");
    }
  }

  int64_t zigzag() {
    uint64_t v = varint();
    return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
  }

  std::string binary() {
    uint64_t n = varint();
    need(n);
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }

  void skip_binary() {
    uint64_t n = varint();
    need(n);
    p_ += n;
  }

  double f64() {
    need(8);
    double d;
    std::memcpy(&d, p_, 8);  // compact protocol: little-endian
    p_ += 8;
    return d;
  }

  struct FieldHeader {
    int16_t id;
    CType type;
    bool bool_value;  // booleans are encoded in the type nibble
  };

  // Returns false at STOP. last_id threads the running field-id delta.
  bool read_field(int16_t& last_id, FieldHeader& out) {
    need(1);
    uint8_t b = *p_++;
    if (b == 0) return false;
    uint8_t delta = b >> 4;
    auto type = static_cast<CType>(b & 0x0F);
    int16_t id = delta ? static_cast<int16_t>(last_id + delta)
                       : static_cast<int16_t>(zigzag());
    last_id = id;
    out.id = id;
    out.type = type;
    out.bool_value = (type == CType::TRUE_);
    return true;
  }

  struct ListHeader {
    uint32_t size;
    CType elem_type;
  };

  ListHeader read_list() {
    need(1);
    uint8_t b = *p_++;
    uint32_t size = b >> 4;
    auto et = static_cast<CType>(b & 0x0F);
    if (size == 15) size = static_cast<uint32_t>(varint());
    return {size, et};
  }

  void skip(CType t) {
    switch (t) {
      case CType::TRUE_:
      case CType::FALSE_:
        return;  // value was in the field header
      case CType::BYTE:
        need(1);
        p_++;
        return;
      case CType::I16:
      case CType::I32:
      case CType::I64:
        varint();
        return;
      case CType::DOUBLE:
        need(8);
        p_ += 8;
        return;
      case CType::BINARY:
        skip_binary();
        return;
      case CType::LIST:
      case CType::SET: {
        ListHeader lh = read_list();
        for (uint32_t i = 0; i < lh.size; i++) skip_elem(lh.elem_type);
        return;
      }
      case CType::MAP: {
        uint64_t n = varint();
        if (n == 0) return;
        need(1);
        uint8_t kv = *p_++;
        auto kt = static_cast<CType>(kv >> 4);
        auto vt = static_cast<CType>(kv & 0x0F);
        for (uint64_t i = 0; i < n; i++) {
          skip_elem(kt);
          skip_elem(vt);
        }
        return;
      }
      case CType::STRUCT: {
        int16_t last = 0;
        FieldHeader fh;
        while (read_field(last, fh)) skip(fh.type);
        return;
      }
      default:
        throw ThriftError("cannot skip thrift type " + std::to_string(int(t)));
    }
  }

  // list/map elements encode bools as full bytes, unlike struct fields
  void skip_elem(CType t) {
    if (t == CType::TRUE_ || t == CType::FALSE_) {
      need(1);
      p_++;
      return;
    }
    skip(t);
  }

  bool elem_bool(CType t) {
    (void)t;
    need(1);
    return *p_++ == 1;
  }

 private:
  void need(uint64_t n) {
    if (static_cast<uint64_t>(end_ - p_) < n) throw ThriftError("thrift: unexpected EOF");
  }
  const uint8_t* p_;
  const uint8_t* end_;
};

}  // namespace hsn
