"""Delta Lake source + explain/whyNot tests
(ref: src/test/scala/.../DeltaLakeIntegrationTest.scala (599),
ExplainTest.scala (240), CandidateIndexAnalyzerTest)."""

import numpy as np
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.sources.delta import delete_delta_files, list_versions, write_delta_table

from tests.test_e2e_rules import assert_batches_equal


def make_table(seed=0, n=500):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.standard_normal(n),
        }
    )


@pytest.fixture()
def delta_root(tmp_path):
    root = str(tmp_path / "delta_tbl")
    write_delta_table(make_table(0), root)
    write_delta_table(make_table(1), root)
    return root


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestDeltaSource:
    def test_read_and_versions(self, session, delta_root):
        df = session.read_delta(delta_root)
        assert df.count() == 1000
        assert list_versions(delta_root) == [0, 1]
        df_v0 = session.read_delta(delta_root, version=0)
        assert df_v0.count() == 500

    def test_remove_action(self, session, delta_root):
        rel = session.read_delta(delta_root).plan.relation
        first = sorted(p for p in rel._adds)[0]
        delete_delta_files(delta_root, [first])
        assert session.read_delta(delta_root).count() == 500
        # time travel still sees the removed file
        assert session.read_delta(delta_root, version=1).count() == 1000

    def test_index_on_delta_and_query(self, session, hs, delta_root):
        df = session.read_delta(delta_root)
        hs.create_index(df, hst.CoveringIndexConfig("deltaIdx", ["k"], ["v"]))
        q = df.filter(hst.col("k") == 7).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        assert_batches_equal(q.collect(), baseline)

    def test_delta_version_change_invalidates_index(self, session, hs, delta_root):
        df = session.read_delta(delta_root)
        hs.create_index(df, hst.CoveringIndexConfig("deltaStale", ["k"], ["v"]))
        write_delta_table(make_table(2), delta_root)
        session.enable_hyperspace()
        df2 = session.read_delta(delta_root)
        plan = df2.filter(hst.col("k") == 7).select("v").optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_delta_hybrid_scan_over_new_version(self, session, hs, delta_root):
        df = session.read_delta(delta_root)
        hs.create_index(df, hst.CoveringIndexConfig("deltaHybrid", ["k"], ["v"]))
        write_delta_table(make_table(2), delta_root)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        df2 = session.read_delta(delta_root)
        q = df2.filter(hst.col("k") == 7).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        assert any(isinstance(p, L.BucketUnion) for p in L.collect(plan, lambda p: True)), plan.pretty()
        assert_batches_equal(q.collect(), baseline)

    def test_time_travel_picks_closest_index_version(self, session, hs, delta_root):
        """closest_index: querying an older table version must use the index
        log version recorded for that delta version, not the latest
        (ref: DeltaLakeRelation.scala:179-251 deltaVersions history)."""
        from hyperspace_tpu.sources.delta import DELTA_VERSIONS_PROPERTY

        df0 = session.read_delta(delta_root)
        v0 = df0.plan.relation.version
        hs.create_index(df0, hst.CoveringIndexConfig("deltaTT", ["k"], ["v"]))
        write_delta_table(make_table(11), delta_root)
        hs.refresh_index("deltaTT", "incremental")
        entry = session.index_manager.get_index("deltaTT")
        history = entry.properties.get(DELTA_VERSIONS_PROPERTY)
        assert history and len(history) >= 2  # create + refresh recorded

        session.enable_hyperspace()
        # latest query -> latest index log version
        q_latest = session.read_delta(delta_root).filter(hst.col("k") == 7).select("v")
        latest_scans = [p for p in L.collect(q_latest.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]
        assert latest_scans
        # time travel -> the older index log version covering v0
        q_old = session.read_delta(delta_root, version=v0).filter(hst.col("k") == 7).select("v")
        old_scans = [p for p in L.collect(q_old.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]
        assert old_scans, q_old.optimized_plan().pretty()
        assert old_scans[0].entry.id < latest_scans[0].entry.id
        on = q_old.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q_old.collect())
        session.enable_hyperspace()

    def test_maintenance_entries_do_not_pollute_time_travel(self, session, hs, delta_root):
        """optimize/delete/restore copy their predecessor entry: they must
        carry the deltaVersions history forward without recording new ids,
        and latest-version queries must use the latest entry (not reach back
        to the superseded pre-optimize log)."""
        from hyperspace_tpu.sources.delta import DELTA_VERSIONS_PROPERTY

        df0 = session.read_delta(delta_root)
        v0 = df0.plan.relation.version
        hs.create_index(df0, hst.CoveringIndexConfig("deltaMnt", ["k"], ["v"]))
        write_delta_table(make_table(12), delta_root)
        hs.refresh_index("deltaMnt", "incremental")
        hs.optimize_index("deltaMnt", "full")
        hs.delete_index("deltaMnt")
        hs.restore_index("deltaMnt")
        entry = session.index_manager.get_index("deltaMnt")
        history = entry.properties.get(DELTA_VERSIONS_PROPERTY)
        assert set(history.values()) == {v0, v0 + 1}
        assert len(history) == 2  # only create + incremental refresh recorded

        session.enable_hyperspace()
        q = session.read_delta(delta_root).filter(hst.col("k") == 7).select("v")
        scans = [p for p in L.collect(q.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans and scans[0].entry.id == entry.id  # latest, post-optimize
        q_old = session.read_delta(delta_root, version=v0).filter(hst.col("k") == 7).select("v")
        old_scans = [p for p in L.collect(q_old.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]
        assert old_scans and old_scans[0].entry.id < entry.id

    def test_refresh_delta_index(self, session, hs, delta_root):
        df = session.read_delta(delta_root)
        hs.create_index(df, hst.CoveringIndexConfig("deltaRef", ["k"], ["v"]))
        write_delta_table(make_table(3), delta_root)
        hs.refresh_index("deltaRef", "incremental")
        session.enable_hyperspace()
        df2 = session.read_delta(delta_root)
        q = df2.filter(hst.col("k") == 7).select("v")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())


class TestExplainWhyNot:
    def test_explain_shows_index_and_diff(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("expIdx", ["c1"], ["c2"]))
        q = df.filter(hst.col("c1") == 7).select("c2")
        text = hs.explain(q, verbose=True)
        assert "Plan with indexes" in text
        assert "expIdx" in text
        assert "IndexScan" in text
        assert "Plan without indexes" in text

    def test_why_not_reports_reasons(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("wnIdx", ["c1"], ["c2"]))
        # query needs c3 -> index can't cover it
        q = df.filter(hst.col("c1") == 7).select("c3")
        text = hs.why_not(q)
        assert "wnIdx" in text
        assert "MISSING_REQUIRED_COL" in text

    def test_why_not_applied_index(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("wnOk", ["c1"], ["c2"]))
        q = df.filter(hst.col("c1") == 7).select("c2")
        text = hs.why_not(q)
        assert "(applied)" in text

    def test_why_not_wrong_first_col(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("wnFirst", ["c1"], ["c2"]))
        q = df.filter(hst.col("c2") == 7).select("c1")
        text = hs.why_not(q, extended=True)
        assert "NO_FIRST_INDEXED_COL_COND" in text


class TestDataSkippingIndexBuild:
    def test_create_and_stats(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        entry = hs.create_index(
            df,
            hst.DataSkippingIndexConfig("dsIdx", hst.MinMaxSketch("c1"), hst.BloomFilterSketch("c2")),
        )
        assert entry.state == "ACTIVE"
        assert entry.kind == "DataSkippingIndex"

        import pyarrow.dataset as pads

        sketch_table = pads.dataset(entry.content.files, format="parquet").to_table()
        assert sketch_table.num_rows == 4  # one row per source file
        assert "MinMax_c1__min" in sketch_table.column_names
        assert "BloomFilter_c2__bits" in sketch_table.column_names

    def test_bloom_filter_membership(self):
        sk = hst.BloomFilterSketch("x", fpp=0.01, expected_items=1000)
        values = np.arange(0, 1000, 2)
        (bits,) = sk.aggregate(values)
        hits = sum(sk.might_contain(bits, v) for v in range(0, 1000, 2))
        assert hits == 500  # no false negatives
        misses = sum(sk.might_contain(bits, v) for v in range(1, 1000, 2))
        assert misses < 50  # fpp ~ 1%
