"""Multi-device production index build.

The reference's build is cluster-wide: ``repartition(numBuckets, cols)`` fans
the whole table across executors before the bucketed sorted write
(ref: HS/index/covering/CoveringIndex.scala:54-69,
HS/index/DataFrameWriterExtensions.scala:50-68). Here the equivalent is the
distributed exchange inside ``write_bucketed``: rows shard over the session
mesh, hash on device, one ``all_to_all`` routes each row to its owner device
(bucket % n_devices), and each device sorts and writes its buckets.

These tests go through the REAL API (``create_index`` / ``refreshIndex`` /
``optimizeIndex``) on the 8-device virtual CPU mesh (conftest.py), asserting
the index content is IDENTICAL to the single-device build's.
"""

import glob
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.indexes.covering import bucket_of_file, write_bucketed


def _single_device_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:1]), ("buckets",))


def _read_buckets(paths):
    """bucket id -> concatenated table (multi-run buckets concatenated in
    file order; run order is deterministic for a fixed chunking)."""
    out = {}
    for p in sorted(paths):
        out.setdefault(bucket_of_file(p), []).append(pq.read_table(p))
    return {b: pa.concat_tables(ts) for b, ts in out.items()}


def _read_buckets_runs(paths):
    """bucket id -> sorted list of per-run serialized contents (run file
    order is uuid-random; each run's content is deterministic)."""
    out = {}
    for p in paths:
        t = pq.read_table(p)
        out.setdefault(bucket_of_file(p), []).append(
            tuple(tuple(col.to_pylist()) for col in t.columns)
        )
    return {b: sorted(rs) for b, rs in out.items()}


def _index_files(session, name):
    sysp = session.conf.get(hst.keys.SYSTEM_PATH)
    files = glob.glob(os.path.join(sysp, name, "v__=*", "*.parquet"))
    assert files, f"no index data files for {name}"
    return files


@pytest.fixture()
def data(tmp_path):
    d = tmp_path / "src"
    d.mkdir()
    rng = np.random.default_rng(3)
    for i in range(3):
        n = 4000
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 300, n).astype(np.int64),
                    "name": np.array([f"n_{v}" for v in rng.integers(0, 50, n)]),
                    "amount": np.round(rng.uniform(0, 1000, n), 4),
                }
            ),
            d / f"part-{i}.parquet",
        )
    return str(d)


def _fresh_session(tmp_path, tag, num_buckets=16, **conf):
    sysp = tmp_path / f"idx_{tag}"
    sysp.mkdir()
    merged = {
        hst.keys.SYSTEM_PATH: str(sysp),
        hst.keys.NUM_BUCKETS: num_buckets,
        # the distributed build sits behind the default-off parallel master
        # switch; these tests exist to exercise the mesh path, so opt in
        hst.keys.PARALLEL_ENABLED: True,
    }
    merged.update(conf)
    return hst.Session(conf=merged)


class TestCreateIndexMultiDevice:
    def test_multi_device_build_matches_single_device(self, tmp_path, data):
        """create_index over the 8-device mesh writes byte-identical bucket
        content to the 1-device build (VERDICT round-1 item 1)."""
        import jax

        assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"

        s_multi = _fresh_session(tmp_path, "multi")
        hst.Hyperspace(s_multi).create_index(
            s_multi.read_parquet(data), hst.CoveringIndexConfig("idx", ["k"], ["amount", "name"])
        )
        multi = _read_buckets(_index_files(s_multi, "idx"))

        s_single = _fresh_session(tmp_path, "single")
        s_single.set_mesh(_single_device_mesh())
        hst.Hyperspace(s_single).create_index(
            s_single.read_parquet(data), hst.CoveringIndexConfig("idx", ["k"], ["amount", "name"])
        )
        single = _read_buckets(_index_files(s_single, "idx"))

        assert set(multi) == set(single)
        for b in single:
            assert multi[b].equals(single[b]), f"bucket {b} differs"

    def test_multi_device_query_correct(self, tmp_path, data):
        session = _fresh_session(tmp_path, "q")
        hs = hst.Hyperspace(session)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("qidx", ["k"], ["amount"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 42).select("amount")
        assert "IndexScan" in q.optimized_plan().pretty()
        on = np.sort(q.collect()["amount"])
        session.disable_hyperspace()
        off = np.sort(q.collect()["amount"])
        assert np.array_equal(on, off)

    def test_min_rows_threshold_gates_distribution(self, tmp_path, data, monkeypatch):
        """Below distributedMinRows the single-device program runs even on a
        multi-device mesh."""
        import hyperspace_tpu.ops.bucketize as bz

        called = {"n": 0}
        real = bz.distributed_bucket_sort_build

        def spy(*a, **k):
            called["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(bz, "distributed_bucket_sort_build", spy)
        session = _fresh_session(
            tmp_path, "gate", **{hst.keys.TPU_BUILD_DISTRIBUTED_MIN_ROWS: 10**9}
        )
        hst.Hyperspace(session).create_index(
            session.read_parquet(data), hst.CoveringIndexConfig("g", ["k"], ["amount"])
        )
        assert called["n"] == 0

    def test_chunked_multi_device_build(self, tmp_path, data):
        """Chunked (batchRows-capped) distributed build: one sorted run per
        bucket per chunk, identical to the chunked single-device build."""
        conf = {hst.keys.TPU_BUILD_BATCH_ROWS: 4096}
        s_multi = _fresh_session(tmp_path, "cm", **conf)
        hst.Hyperspace(s_multi).create_index(
            s_multi.read_parquet(data), hst.CoveringIndexConfig("c", ["k"], ["amount"])
        )

        s_single = _fresh_session(tmp_path, "cs", **conf)
        s_single.set_mesh(_single_device_mesh())
        hst.Hyperspace(s_single).create_index(
            s_single.read_parquet(data), hst.CoveringIndexConfig("c", ["k"], ["amount"])
        )
        single = _read_buckets_runs(_index_files(s_single, "c"))
        multi = _read_buckets_runs(_index_files(s_multi, "c"))
        assert set(multi) == set(single)
        for b in single:
            # file names are uuid-random, so compare the bucket's sorted RUNS
            # (each chunk writes one deterministic run per bucket)
            assert multi[b] == single[b], f"bucket {b} runs differ"


class TestSkewAndOverflow:
    def test_skewed_keys_capacity_retry(self, tmp_path):
        """Every row hashing to one bucket overflows the initial exchange
        capacity; the build retries with doubled slots and succeeds with
        identical content (VERDICT round-1 item: skew/overflow policy)."""
        n = 6000
        skew = pa.table({"k": np.zeros(n, dtype=np.int64), "v": np.arange(float(n))})
        session = _fresh_session(tmp_path, "skew")
        d_multi, d_single = str(tmp_path / "om"), str(tmp_path / "os")
        write_bucketed(skew, ["k"], 16, d_multi, session=session)
        write_bucketed(skew, ["k"], 16, d_single, session=None)
        multi = _read_buckets(glob.glob(os.path.join(d_multi, "*.parquet")))
        single = _read_buckets(glob.glob(os.path.join(d_single, "*.parquet")))
        assert list(multi) == list(single) and len(multi) == 1
        (bm,) = multi.values()
        (bs,) = single.values()
        assert bm.equals(bs)

    def test_two_heavy_buckets_on_same_device(self, tmp_path):
        """Two hot keys whose buckets both live on one device (b % n_dev
        equal) still exchange correctly after retry."""
        session = _fresh_session(tmp_path, "two")
        nb = 16
        # craft two key values; whatever buckets they hash to, content parity
        # with the single-device build is the invariant
        keys = np.repeat(np.array([11, 397], dtype=np.int64), 3000)
        t = pa.table({"k": keys, "v": np.arange(float(keys.size))})
        d_multi, d_single = str(tmp_path / "tm"), str(tmp_path / "ts")
        write_bucketed(t, ["k"], nb, d_multi, session=session)
        write_bucketed(t, ["k"], nb, d_single, session=None)
        multi = _read_buckets(glob.glob(os.path.join(d_multi, "*.parquet")))
        single = _read_buckets(glob.glob(os.path.join(d_single, "*.parquet")))
        assert set(multi) == set(single)
        for b in single:
            assert multi[b].equals(single[b])


class TestRefreshOptimizeMultiDevice:
    def test_incremental_refresh_distributed(self, tmp_path, data):
        session = _fresh_session(tmp_path, "rf", **{hst.keys.LINEAGE_ENABLED: True})
        hs = hst.Hyperspace(session)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("r", ["k"], ["amount"]))
        # append a file, refresh incrementally (delta rides the mesh too)
        rng = np.random.default_rng(9)
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 300, 2000).astype(np.int64),
                    "name": np.array([f"n_{v}" for v in rng.integers(0, 50, 2000)]),
                    "amount": np.round(rng.uniform(0, 1000, 2000), 4),
                }
            ),
            os.path.join(data, "part-9.parquet"),
        )
        hs.refresh_index("r", "incremental")
        session.enable_hyperspace()
        q = session.read_parquet(data).filter(hst.col("k") == 10).select("amount")
        assert "IndexScan" in q.optimized_plan().pretty()
        on = np.sort(q.collect()["amount"])
        session.disable_hyperspace()
        off = np.sort(q.collect()["amount"])
        assert np.array_equal(on, off)

    def test_optimize_distributed(self, tmp_path, data):
        session = _fresh_session(tmp_path, "op")
        hs = hst.Hyperspace(session)
        df = session.read_parquet(data)
        session.conf.set(hst.keys.TPU_BUILD_BATCH_ROWS, 4096)  # multi-run buckets
        hs.create_index(df, hst.CoveringIndexConfig("o", ["k"], ["amount"]))
        session.conf.set(hst.keys.TPU_BUILD_BATCH_ROWS, 2_000_000)
        hs.optimize_index("o", "full")
        files = _index_files(session, "o")
        latest = max(files, key=lambda p: p.split("v__=")[1])
        # after full optimize the latest version has one file per bucket
        latest_dir = os.path.dirname(latest)
        by_bucket = {}
        for p in glob.glob(os.path.join(latest_dir, "*.parquet")):
            by_bucket.setdefault(bucket_of_file(p), []).append(p)
        assert all(len(v) == 1 for v in by_bucket.values())
        session.enable_hyperspace()
        q = session.read_parquet(data).filter(hst.col("k") == 10).select("amount")
        on = np.sort(q.collect()["amount"])
        session.disable_hyperspace()
        off = np.sort(q.collect()["amount"])
        assert np.array_equal(on, off)
