"""Sharded ≡ single-device oracle tests for the mesh-sharded execution engine.

``hyperspace.parallel.enabled`` switches the fused filter and grouped-agg
programs from GSPMD jit to explicit shard_map over an 8-way emulated host
mesh (conftest.py forces ``--xla_force_host_platform_device_count=8``). The
invariant these tests pin: the sharded path is BYTE-IDENTICAL to the
single-device path wherever the math is order-independent (bool masks, int
counts/sums/min/max, keys, exactly-representable float sums), and within
1e-9 where cross-shard summation order legitimately differs (messy floats —
same bar the single-device groupagg oracle uses).

Also covered: the default-off conf gate, the distributed index-build gate,
``make_mesh``/``make_mesh_2d`` error paths, and mesh fingerprints.
"""

import glob
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import trace

pytestmark = pytest.mark.mesh

FLOAT_RTOL = 1e-9

N = 24_000  # rows; large enough that 8-way shards stay non-trivial


@pytest.fixture()
def dataset(tmp_path):
    """q1-shaped data with an exact-float twist: ``price4`` holds quarter
    units (k/4 — dyadic rationals whose sums are exact in float64 regardless
    of order, so sharded sums must match byte-for-byte), ``messy`` holds
    arbitrary uniforms (tolerance only), and ``fkey`` is a float group key
    with NULLs (NaN keys form one group)."""
    d = tmp_path / "mesh_src"
    d.mkdir()
    rng = np.random.default_rng(7)
    rf = rng.choice(["A", "N", "R"], N).astype(object)
    ls = rng.choice(["O", "F"], N).astype(object)
    rf[5] = None
    rf[777] = None
    qty = rng.integers(1, 51, N).astype(np.int64)
    price4 = rng.integers(0, 400_000, N).astype(np.float64) / 4.0
    messy = rng.uniform(900.0, 105_000.0, N)
    messy[rng.choice(N, 200, replace=False)] = np.nan
    fkey = rng.integers(0, 5, N).astype(np.float64)
    fkey[rng.choice(N, 300, replace=False)] = np.nan
    ship = rng.integers(0, 2500, N).astype(np.int64)
    per = N // 4
    for i in range(4):
        sl = slice(i * per, (i + 1) * per)
        pq.write_table(
            pa.table(
                {
                    "rf": rf[sl], "ls": ls[sl], "qty": qty[sl],
                    "price4": price4[sl], "messy": messy[sl],
                    "fkey": fkey[sl], "ship": ship[sl],
                }
            ),
            d / f"p{i}.parquet",
        )
    return str(d)


def _session(tmp_path, tag, **conf):
    sysp = tmp_path / f"sys_{tag}"
    sysp.mkdir(exist_ok=True)
    merged = {
        hst.keys.SYSTEM_PATH: str(sysp),
        hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 0,
        hst.keys.PARALLEL_MIN_ROWS: 0,
    }
    merged.update(conf)
    return hst.Session(conf=merged)


def _prepared(tmp_path, dataset, tag, index=None, **conf):
    """Session + dataframe; with ``index=(indexed, included)`` a covering
    index is built and hyperspace enabled, so filters land on an IndexScan
    (the plan shape the device filter/grouped-agg paths require)."""
    s = _session(tmp_path, tag, **conf)
    df = s.read_parquet(dataset)
    if index is not None:
        indexed, included = index
        hst.Hyperspace(s).create_index(
            df, hst.CoveringIndexConfig(f"mIdx_{tag}", list(indexed), list(included))
        )
        s.enable_hyperspace()
    return s, df


def _collect_modes(tmp_path, dataset, make_query, index=None, **conf):
    """(sharded result, single-device result, sharded trace summary)."""
    _, df_on = _prepared(
        tmp_path, dataset, "on", index=index,
        **{hst.keys.PARALLEL_ENABLED: True, **conf},
    )
    with trace.recording() as events:
        got = make_query(df_on).collect()
    _, df_off = _prepared(tmp_path, dataset, "off", index=index, **conf)
    want = make_query(df_off).collect()
    return got, want, trace.summarize(events)


def assert_tables_equal(got, want, float_cols=()):
    assert sorted(got.keys()) == sorted(want.keys())
    for k in got:
        a, b = np.asarray(got[k]), np.asarray(want[k])
        assert a.shape == b.shape, k
        if k in float_cols:
            np.testing.assert_allclose(a, b, rtol=FLOAT_RTOL, equal_nan=True, err_msg=k)
        elif a.dtype == object or b.dtype == object:
            assert all(
                (not isinstance(x, str) and not isinstance(y, str)) or x == y
                for x, y in zip(a, b)
            ), k
        else:
            assert a.dtype == b.dtype, k
            np.testing.assert_array_equal(a, b, err_msg=k)


class TestShardedFilterScan:
    def test_filter_scan_byte_identical(self, tmp_path, dataset):
        got, want, summary = _collect_modes(
            tmp_path, dataset,
            lambda df: df.filter(hst.col("ship") <= 1200).select("qty", "price4"),
            index=(["ship"], ["qty", "price4"]),
        )
        assert_tables_equal(got, want)
        assert "filter: device-sharded" in summary, summary

    def test_filter_metrics_attributed(self, tmp_path, dataset):
        from hyperspace_tpu.obs.metrics import REGISTRY

        before = REGISTRY.counter(
            "hs_mesh_sharded_ops_total", op="filter"
        ).value
        got, want, _ = _collect_modes(
            tmp_path, dataset,
            lambda df: df.filter(hst.col("qty") > 25).select("ship"),
            index=(["qty"], ["ship"]),
        )
        assert_tables_equal(got, want)
        after = REGISTRY.counter("hs_mesh_sharded_ops_total", op="filter").value
        assert after > before


class TestShardedGroupedAgg:
    def q1(self, df):
        return (
            df.filter(hst.col("ship") <= 2400)
            .group_by("rf", "ls")
            .agg(
                sum_qty=("qty", "sum"),
                sum_price=("price4", "sum"),
                avg_qty=("qty", "avg"),
                sd_messy=("messy", "stddev_samp"),
                avg_messy=("messy", "avg"),
                n=("*", "count"),
                nm=("messy", "count"),
                lo=("price4", "min"),
                hi=("qty", "max"),
            )
        )

    def test_q1_shape_multi_key(self, tmp_path, dataset):
        """Multi-key q1 shape: keys, counts, int sums/max, float min, and the
        dyadic-rational float sum are byte-identical; messy-float reductions
        agree to 1e-9 (cross-shard summation order)."""
        got, want, summary = _collect_modes(
            tmp_path, dataset, self.q1,
            index=(["ship"], ["rf", "ls", "qty", "price4", "messy"]),
        )
        assert_tables_equal(
            got, want, float_cols=("sd_messy", "avg_messy", "avg_qty")
        )
        assert "device-grouped" in summary, summary

    def test_null_float_group_keys(self, tmp_path, dataset):
        # no filter -> no index rewrite; stream the chunks so the grouped
        # device (and sharded) path still runs over FileScan subsets
        got, want, summary = _collect_modes(
            tmp_path, dataset,
            lambda df: df.group_by("fkey").agg(
                n=("*", "count"), s=("qty", "sum"), m=("messy", "avg")
            ),
            **{
                hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1,
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
            },
        )
        assert "sharded" in summary, summary
        assert_tables_equal(got, want, float_cols=("m",))
        # NaN keys form exactly one group on both paths
        fk = np.asarray(got["fkey"], dtype=np.float64)
        assert int(np.isnan(fk).sum()) == 1

    def test_streamed_grouped_agg(self, tmp_path, dataset):
        """The streaming (chunk-at-a-time) grouped path with sharded chunk
        programs: per-shard partials merge on device via all-gather, then
        chunk partials merge pairwise — result identical to single-device."""
        conf = {
            hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1,
            hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
        }
        got, want, summary = _collect_modes(tmp_path, dataset, self.q1, **conf)
        assert_tables_equal(
            got, want, float_cols=("sd_messy", "avg_messy", "avg_qty")
        )
        assert "device-grouped-stream-sharded" in summary, summary


class TestDefaultOffGate:
    def test_gate_is_off_by_default(self, tmp_path, dataset):
        from hyperspace_tpu.exec.executor import _maybe_parallel
        from hyperspace_tpu.parallel import ShardedExecutor

        s = _session(tmp_path, "gate")
        assert s.conf.parallel_enabled is False
        assert ShardedExecutor.maybe(s) is None
        assert _maybe_parallel(s) is None
        with trace.recording() as events:
            s.read_parquet(dataset).filter(hst.col("qty") > 25).select("ship").collect()
        summary = trace.summarize(events)
        assert "sharded" not in summary, summary

    def test_min_rows_gates_one_shot_ops(self, tmp_path, dataset):
        from hyperspace_tpu.exec.executor import _maybe_parallel

        s = _session(
            tmp_path, "minrows",
            **{hst.keys.PARALLEL_ENABLED: True, hst.keys.PARALLEL_MIN_ROWS: 10**9},
        )
        assert _maybe_parallel(s) is not None
        assert _maybe_parallel(s, 1000) is None


class TestShardedIndexBuild:
    def test_build_parity_behind_parallel_gate(self, tmp_path, dataset):
        """write_bucketed with the parallel gate on (8-device exchange) is
        byte-identical to the host/single-device build, and the gate keeps
        the exchange off by default."""
        from hyperspace_tpu.indexes.covering import bucket_of_file, write_bucketed
        import hyperspace_tpu.ops.bucketize as bz

        t = pq.read_table(glob.glob(os.path.join(dataset, "*.parquet"))[0])
        t = t.select(["ship", "qty", "price4"])

        s_on = _session(tmp_path, "bon", **{hst.keys.PARALLEL_ENABLED: True})
        d_mesh, d_host = str(tmp_path / "bm"), str(tmp_path / "bh")
        write_bucketed(t, ["ship"], 16, d_mesh, session=s_on)
        write_bucketed(t, ["ship"], 16, d_host, session=None)

        def buckets(d):
            out = {}
            for p in sorted(glob.glob(os.path.join(d, "*.parquet"))):
                out.setdefault(bucket_of_file(p), []).append(pq.read_table(p))
            return {b: pa.concat_tables(ts) for b, ts in out.items()}

        mesh_b, host_b = buckets(d_mesh), buckets(d_host)
        assert set(mesh_b) == set(host_b)
        for b in host_b:
            assert mesh_b[b].equals(host_b[b]), f"bucket {b} differs"

    def test_build_gate_default_off(self, tmp_path, dataset, monkeypatch):
        from hyperspace_tpu.indexes.covering import write_bucketed
        import hyperspace_tpu.ops.bucketize as bz

        called = {"n": 0}
        real = bz.distributed_bucket_sort_build

        def spy(*a, **k):
            called["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(bz, "distributed_bucket_sort_build", spy)
        t = pq.read_table(glob.glob(os.path.join(dataset, "*.parquet"))[0]).select(
            ["ship", "qty"]
        )
        s = _session(tmp_path, "boff")  # parallel.enabled defaults to False
        write_bucketed(t, ["ship"], 16, str(tmp_path / "bo"), session=s)
        assert called["n"] == 0


class TestMeshHelpers:
    def test_make_mesh_rejects_oversubscription(self):
        from hyperspace_tpu.parallel import make_mesh

        with pytest.raises(ValueError, match="9-device mesh but only 8"):
            make_mesh(9)

    def test_make_mesh_rejects_nonpositive(self):
        from hyperspace_tpu.parallel import make_mesh

        with pytest.raises(ValueError, match=">= 1"):
            make_mesh(0)
        with pytest.raises(ValueError, match=">= 1"):
            make_mesh(-2)

    def test_make_mesh_2d_rejects_nondivisible(self):
        from hyperspace_tpu.parallel import make_mesh_2d

        with pytest.raises(ValueError, match="divide evenly"):
            make_mesh_2d(n_slices=3)

    def test_make_mesh_2d_rejects_oversubscription(self):
        from hyperspace_tpu.parallel import make_mesh_2d

        with pytest.raises(ValueError, match="only 8 devices"):
            make_mesh_2d(n_slices=4, per_slice=4)

    def test_fingerprint_distinguishes_mesh_shapes(self):
        from hyperspace_tpu.parallel import make_mesh, make_mesh_2d, mesh_fingerprint

        fp8 = mesh_fingerprint(make_mesh(8))
        assert fp8 == mesh_fingerprint(make_mesh(8))  # stable
        assert fp8 != mesh_fingerprint(make_mesh(4))
        assert fp8 != mesh_fingerprint(make_mesh_2d(n_slices=2, per_slice=4))
        assert fp8.startswith("cpu:8:")
