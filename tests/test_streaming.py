"""Out-of-core / streaming execution (round-5: the SF100 memory-wall work).

The reference runs at any scale because Spark's executors stream
(ref: HS/index/covering/JoinIndexRule.scala:604-705 works unchanged at
SF100); this framework owns its execution layer, so boundedness is a
property these tests pin explicitly:

- the covering-index BUILD decodes source files in ~batchRows groups and
  never materializes the full table (indexes/covering.py write());
- the bucketed JOIN streams bucket-by-bucket (exec/device.py);
- scan->filter->aggregate streams file chunks with partial-agg merge;
- the generic join spills to disk partitions above a byte threshold.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst


def _write_files(d, num_files=6, rows_per=1000, seed=7):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        t = pa.table(
            {
                "k": rng.integers(0, 500, rows_per).astype(np.int64),
                "v": np.round(rng.uniform(0, 100, rows_per), 3),
                "name": np.array([f"row_{i}_{j % 37}" for j in range(rows_per)]),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def _mk_session(tmp_path, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
        hst.keys.NUM_BUCKETS: 8,
    }
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


class TestStreamingBuild:
    def test_grouped_build_matches_one_shot(self, tmp_path):
        """A build chunked to ~1.5 files per group must index the same rows
        (same per-bucket multiset, same query answers) as a one-shot build."""
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1500})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("s_idx", ["k"], ["v", "name"]))

        sess2 = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: str(tmp_path / "indexes2"),
                hst.keys.NUM_BUCKETS: 8,
                hst.keys.TPU_BUILD_BATCH_ROWS: 10_000_000,
            }
        )
        hst.set_session(sess2)
        hs2 = hst.Hyperspace(sess2)
        df2 = sess2.read_parquet(data)
        hs2.create_index(df2, hst.CoveringIndexConfig("s_idx", ["k"], ["v", "name"]))

        def bucket_rows(sysdir):
            from hyperspace_tpu.indexes.covering import bucket_of_file

            out = {}
            for root, _, files in os.walk(sysdir):
                for f in files:
                    if not f.endswith(".parquet"):
                        continue
                    b = bucket_of_file(os.path.join(root, f))
                    if b is None:
                        continue
                    t = pq.read_table(os.path.join(root, f))
                    out.setdefault(b, []).append(t)
            return {
                b: sorted(
                    zip(
                        *[
                            pa.concat_tables(ts).column(c).to_pylist()
                            for c in ("k", "v", "name")
                        ]
                    )
                )
                for b, ts in out.items()
            }

        chunked = bucket_rows(str(tmp_path / "indexes"))
        oneshot = bucket_rows(str(tmp_path / "indexes2"))
        assert set(chunked) == set(oneshot)
        for b in oneshot:
            assert chunked[b] == oneshot[b]

    def test_build_never_decodes_all_files_at_once(self, tmp_path):
        """Bounded-memory proxy: with batchRows below the table size, no
        single arrow_dataset() call during the build covers every file."""
        from hyperspace_tpu.sources.default import DefaultFileBasedRelation

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1500})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)

        decodes = []  # files covered by each actual to_table() decode
        orig = DefaultFileBasedRelation.arrow_dataset

        class _DSProxy:
            def __init__(self, ds, nfiles):
                self._ds, self._nfiles = ds, nfiles

            def to_table(self, columns=None):
                decodes.append(self._nfiles)
                return self._ds.to_table(columns=columns)

            def __getattr__(self, a):
                return getattr(self._ds, a)

        def spy(self, files=None):
            return _DSProxy(orig(self, files), len(files) if files is not None else 6)

        DefaultFileBasedRelation.arrow_dataset = spy
        try:
            hs.create_index(df, hst.CoveringIndexConfig("b_idx", ["k"], ["v"]))
        finally:
            DefaultFileBasedRelation.arrow_dataset = orig
        assert decodes, "build never decoded the relation"
        assert max(decodes) < 6, f"a single decode covered all files: {decodes}"

    def test_schema_drift_across_files(self, tmp_path):
        """Per-file streaming reads must conform to the unified schema the
        one-shot dataset scan applied implicitly: older files with a
        narrower dtype (int32 vs int64) or a missing payload column still
        build one consistent index."""
        d = str(tmp_path / "data")
        os.makedirs(d)
        # the relation's unified schema resolves from the leading file, so
        # the evolved (wider) file sorts first; the trailing file predates
        # column v and stores k narrower (int32)
        new = pa.table(
            {
                "k": pa.array([2, 3, 4], type=pa.int64()),
                "v": pa.array([1.5, 2.5, 3.5]),
            }
        )
        pq.write_table(new, os.path.join(d, "part-00000.parquet"))
        old = pa.table({"k": pa.array([1, 2, 3], type=pa.int32())})
        pq.write_table(old, os.path.join(d, "part-00001.parquet"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 2})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("drift_idx", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.filter(hst.col("k") == 2).select("v")
        assert "IndexScan" in q.optimized_plan().pretty()
        got = q.collect()["v"]
        # k==2 appears in both files: one NULL v (old file), one 1.5
        assert sorted(x for x in got if x == x) == [1.5]
        assert sum(1 for x in got if x != x) == 1

    def test_indexed_query_after_streaming_build(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1100})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("q_idx", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.filter(hst.col("k") == 123).select("v")
        assert "IndexScan" in q.optimized_plan().pretty()
        got = np.sort(q.collect()["v"])
        sess.disable_hyperspace()
        want = np.sort(q.collect()["v"])
        np.testing.assert_allclose(got, want)
