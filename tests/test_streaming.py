"""Out-of-core / streaming execution (round-5: the SF100 memory-wall work).

The reference runs at any scale because Spark's executors stream
(ref: HS/index/covering/JoinIndexRule.scala:604-705 works unchanged at
SF100); this framework owns its execution layer, so boundedness is a
property these tests pin explicitly:

- the covering-index BUILD decodes source files in ~batchRows groups and
  never materializes the full table (indexes/covering.py write());
- the bucketed JOIN streams bucket-by-bucket (exec/device.py);
- scan->filter->aggregate streams file chunks with partial-agg merge;
- the generic join spills to disk partitions above a byte threshold.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst


def _write_files(d, num_files=6, rows_per=1000, seed=7):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        t = pa.table(
            {
                "k": rng.integers(0, 500, rows_per).astype(np.int64),
                "v": np.round(rng.uniform(0, 100, rows_per), 3),
                "name": np.array([f"row_{i}_{j % 37}" for j in range(rows_per)]),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def _mk_session(tmp_path, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
        hst.keys.NUM_BUCKETS: 8,
    }
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


class TestStreamingBuild:
    def test_grouped_build_matches_one_shot(self, tmp_path):
        """A build chunked to ~1.5 files per group must index the same rows
        (same per-bucket multiset, same query answers) as a one-shot build."""
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1500})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("s_idx", ["k"], ["v", "name"]))

        sess2 = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: str(tmp_path / "indexes2"),
                hst.keys.NUM_BUCKETS: 8,
                hst.keys.TPU_BUILD_BATCH_ROWS: 10_000_000,
            }
        )
        hst.set_session(sess2)
        hs2 = hst.Hyperspace(sess2)
        df2 = sess2.read_parquet(data)
        hs2.create_index(df2, hst.CoveringIndexConfig("s_idx", ["k"], ["v", "name"]))

        def bucket_rows(sysdir):
            from hyperspace_tpu.indexes.covering import bucket_of_file

            out = {}
            for root, _, files in os.walk(sysdir):
                for f in files:
                    if not f.endswith(".parquet"):
                        continue
                    b = bucket_of_file(os.path.join(root, f))
                    if b is None:
                        continue
                    t = pq.read_table(os.path.join(root, f))
                    out.setdefault(b, []).append(t)
            return {
                b: sorted(
                    zip(
                        *[
                            pa.concat_tables(ts).column(c).to_pylist()
                            for c in ("k", "v", "name")
                        ]
                    )
                )
                for b, ts in out.items()
            }

        chunked = bucket_rows(str(tmp_path / "indexes"))
        oneshot = bucket_rows(str(tmp_path / "indexes2"))
        assert set(chunked) == set(oneshot)
        for b in oneshot:
            assert chunked[b] == oneshot[b]

    def test_build_never_decodes_all_files_at_once(self, tmp_path):
        """Bounded-memory proxy: with batchRows below the table size, no
        single arrow_dataset() call during the build covers every file."""
        from hyperspace_tpu.sources.default import DefaultFileBasedRelation

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1500})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)

        decodes = []  # files covered by each actual to_table() decode
        orig = DefaultFileBasedRelation.arrow_dataset

        class _DSProxy:
            def __init__(self, ds, nfiles):
                self._ds, self._nfiles = ds, nfiles

            def to_table(self, columns=None):
                decodes.append(self._nfiles)
                return self._ds.to_table(columns=columns)

            def __getattr__(self, a):
                return getattr(self._ds, a)

        def spy(self, files=None):
            return _DSProxy(orig(self, files), len(files) if files is not None else 6)

        DefaultFileBasedRelation.arrow_dataset = spy
        try:
            hs.create_index(df, hst.CoveringIndexConfig("b_idx", ["k"], ["v"]))
        finally:
            DefaultFileBasedRelation.arrow_dataset = orig
        assert decodes, "build never decoded the relation"
        assert max(decodes) < 6, f"a single decode covered all files: {decodes}"

    def test_schema_drift_across_files(self, tmp_path):
        """Per-file streaming reads must conform to the unified schema the
        one-shot dataset scan applied implicitly: older files with a
        narrower dtype (int32 vs int64) or a missing payload column still
        build one consistent index."""
        d = str(tmp_path / "data")
        os.makedirs(d)
        # the relation's unified schema resolves from the leading file, so
        # the evolved (wider) file sorts first; the trailing file predates
        # column v and stores k narrower (int32)
        new = pa.table(
            {
                "k": pa.array([2, 3, 4], type=pa.int64()),
                "v": pa.array([1.5, 2.5, 3.5]),
            }
        )
        pq.write_table(new, os.path.join(d, "part-00000.parquet"))
        old = pa.table({"k": pa.array([1, 2, 3], type=pa.int32())})
        pq.write_table(old, os.path.join(d, "part-00001.parquet"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 2})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("drift_idx", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.filter(hst.col("k") == 2).select("v")
        assert "IndexScan" in q.optimized_plan().pretty()
        got = q.collect()["v"]
        # k==2 appears in both files: one NULL v (old file), one 1.5
        assert sorted(x for x in got if x == x) == [1.5]
        assert sum(1 for x in got if x != x) == 1

    def test_indexed_query_after_streaming_build(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.TPU_BUILD_BATCH_ROWS: 1100})
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("q_idx", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.filter(hst.col("k") == 123).select("v")
        assert "IndexScan" in q.optimized_plan().pretty()
        got = np.sort(q.collect()["v"])
        sess.disable_hyperspace()
        want = np.sort(q.collect()["v"])
        np.testing.assert_allclose(got, want)


def _join_fixture(tmp_path, how_many_left=4000, seed=11, skew_side=False):
    """Two parquet dirs with overlapping int keys; the right side's keys are
    restricted to a sub-range so some buckets are one-sided (exercising the
    streaming join's dtype hints on absent-side buckets)."""
    rng = np.random.default_rng(seed)
    ld = str(tmp_path / "left")
    rd = str(tmp_path / "right")
    os.makedirs(ld), os.makedirs(rd)
    for i in range(4):
        t = pa.table(
            {
                "lk": rng.integers(0, 400, how_many_left // 4).astype(np.int64),
                "lv": np.round(rng.uniform(0, 10, how_many_left // 4), 3),
                "ls": np.array([f"L{j % 13}" for j in range(how_many_left // 4)]),
            }
        )
        pq.write_table(t, os.path.join(ld, f"part-{i:05d}.parquet"))
    for i in range(2):
        hi = 60 if skew_side else 400  # narrow key range -> one-sided buckets
        t = pa.table(
            {
                "rk": rng.integers(0, hi, 900).astype(np.int64),
                "rv": np.round(rng.uniform(0, 5, 900), 3),
            }
        )
        pq.write_table(t, os.path.join(rd, f"part-{i:05d}.parquet"))
    return ld, rd


def _sorted_rows(batch):
    cols = sorted(batch)
    return sorted(
        zip(*[["\0N" if v != v else v for v in batch[c].tolist()] for c in cols])
    ), cols


class TestStreamingJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "outer"])
    def test_streamed_equals_materialized(self, tmp_path, how):
        ld, rd = _join_fixture(tmp_path, skew_side=(how != "inner"))
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        hs.create_index(left, hst.CoveringIndexConfig("l_idx", ["lk"], ["lv", "ls"]))
        hs.create_index(right, hst.CoveringIndexConfig("r_idx", ["rk"], ["rv"]))
        sess.enable_hyperspace()
        q = left.join(right, on=hst.col("lk") == hst.col("rk"), how=how).select(
            "lk", "lv", "ls", "rv"
        )
        want = q.collect()
        from hyperspace_tpu.exec import trace

        sess.conf.set(hst.keys.EXEC_STREAM_JOIN_MIN_BYTES, 1)
        with trace.recording() as rec:
            got = q.collect()
        assert any("stream" in v for _, v in rec), rec
        grows, gcols = _sorted_rows(got)
        wrows, wcols = _sorted_rows(want)
        assert gcols == wcols
        assert grows == wrows

    def test_streamed_join_bounded_reads(self, tmp_path):
        """Memory-bound proxy: while streaming, no single parquet read spans
        more than one bucket's files of one side."""
        ld, rd = _join_fixture(tmp_path)
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        hs.create_index(left, hst.CoveringIndexConfig("lb_idx", ["lk"], ["lv"]))
        hs.create_index(right, hst.CoveringIndexConfig("rb_idx", ["rk"], ["rv"]))
        sess.enable_hyperspace()
        sess.conf.set(hst.keys.EXEC_STREAM_JOIN_MIN_BYTES, 1)
        q = left.join(right, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")

        import hyperspace_tpu.exec.io as io_mod
        from hyperspace_tpu.indexes.covering import bucket_of_file

        spans = []
        orig = io_mod.read_parquet_batch

        def spy(files, columns=None):
            spans.append({bucket_of_file(f) for f in files})
            return orig(files, columns)

        io_mod.read_parquet_batch = spy
        try:
            q.collect()
        finally:
            io_mod.read_parquet_batch = orig
        multi = [s for s in spans if len(s - {None}) > 1]
        assert not multi, f"a read spanned several buckets: {multi}"


class TestStreamingAggregate:
    def _fixture(self, tmp_path, with_nulls=True):
        d = str(tmp_path / "agg")
        os.makedirs(d, exist_ok=True)
        rng = np.random.default_rng(3)
        for i in range(6):
            v = rng.uniform(0, 100, 800)
            if with_nulls:
                v[rng.integers(0, 800, 60)] = np.nan
            t = pa.table(
                {
                    "g": np.array([f"grp_{x}" for x in rng.integers(0, 7, 800)]),
                    "k": rng.integers(0, 50, 800).astype(np.int64),
                    "v": v,
                }
            )
            pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1,
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # every file its own chunk
            },
        )
        return sess, sess.read_parquet(d)

    def _ab(self, sess, q):
        from hyperspace_tpu.exec import trace

        with trace.recording() as rec:
            got = q.collect()
        assert ("agg", "streamed-partial") in rec, trace.summarize(rec)
        sess.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 60)
        want = q.collect()
        sess.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1)
        return got, want

    def test_global_aggregates(self, tmp_path):
        sess, df = self._fixture(tmp_path)
        q = df.agg(
            n=("*", "count"),
            s=("v", "sum"),
            mn=("v", "min"),
            mx=("v", "max"),
            a=("v", "avg"),
            cd=("k", "count_distinct"),
            sd=("v", "stddev_samp"),
        )
        got, want = self._ab(sess, q)
        for c in got:
            np.testing.assert_allclose(
                np.asarray(got[c], dtype=np.float64),
                np.asarray(want[c], dtype=np.float64),
                rtol=1e-9,
            )

    def test_grouped_aggregates(self, tmp_path):
        sess, df = self._fixture(tmp_path)
        q = df.group_by("g").agg(
            n=("*", "count"),
            s=("v", "sum"),
            a=("v", "avg"),
            mn=("v", "min"),
            mx=("v", "max"),
            cd=("k", "count_distinct"),
        )
        got, want = self._ab(sess, q)

        def keyed(b):
            cols = [c for c in b if c != "g"]
            return {
                g: tuple(round(float(b[c][i]), 6) for c in cols)
                for i, g in enumerate(b["g"])
            }

        assert keyed(got) == keyed(want)

    def test_filtered_grouped_sum_with_all_null_group(self, tmp_path):
        d = str(tmp_path / "agg2")
        os.makedirs(d)
        for i in range(3):
            t = pa.table(
                {
                    "g": np.array(["a", "b", "b"]),
                    "v": np.array(
                        [np.nan, np.nan, np.nan] if i < 2 else [np.nan, 2.0, 3.0]
                    ),
                }
            )
            pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
        sess = _mk_session(
            tmp_path,
            **{hst.keys.EXEC_STREAM_AGG_MIN_BYTES: 1, hst.keys.EXEC_STREAM_CHUNK_BYTES: 1},
        )
        df = sess.read_parquet(d)
        q = df.group_by("g").agg(s=("v", "sum"))
        got, want = self._ab(sess, q)
        gm = dict(zip(got["g"], got["s"]))
        wm = dict(zip(want["g"], want["s"]))
        assert set(gm) == set(wm)
        for g in gm:  # all-NULL groups must stay NULL (SQL), not 0
            assert (gm[g] != gm[g]) == (wm[g] != wm[g])
            if gm[g] == gm[g]:
                assert round(float(gm[g]), 9) == round(float(wm[g]), 9)


class TestLocalIterator:
    def test_scan_chain_streams_chunks(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_STREAM_CHUNK_BYTES: 1})
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 100).select("k", "v")
        chunks = list(q.to_local_iterator())
        assert len(chunks) > 1  # one per file group
        got = np.sort(np.concatenate([c["v"] for c in chunks]))
        want = np.sort(q.collect()["v"])
        np.testing.assert_allclose(got, want)

    def test_bucketed_join_streams_per_bucket(self, tmp_path):
        ld, rd = _join_fixture(tmp_path)
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        hs.create_index(left, hst.CoveringIndexConfig("li_idx", ["lk"], ["lv"]))
        hs.create_index(right, hst.CoveringIndexConfig("ri_idx", ["rk"], ["rv"]))
        sess.enable_hyperspace()
        q = left.join(right, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        chunks = list(q.to_local_iterator())
        assert len(chunks) > 1  # per participating bucket
        got = np.sort(np.concatenate([c["rv"] for c in chunks]))
        want = np.sort(q.collect()["rv"])
        np.testing.assert_allclose(got, want)


class TestValueConsistentHashing:
    """A nullable int64 parquet column decodes as float64; bucket hashing
    must be VALUE-consistent across the two representations or the bucketed
    SMJ silently drops every match whose sides disagree (found by the
    TPC-DS q48 parity ratchet: 63 vs 216)."""

    def test_host_hash_int_float_consistency(self):
        from hyperspace_tpu.ops.hashing import numeric_hash32

        ints = np.array([0, 1, 3, -7, 2**40], dtype=np.int64)
        floats = ints.astype(np.float64)
        np.testing.assert_array_equal(numeric_hash32(ints), numeric_hash32(floats))
        # -0.0 == 0.0 under SQL/pandas equality: same hash
        assert numeric_hash32(np.array([-0.0]))[0] == numeric_hash32(np.array([0.0]))[0]
        # non-integral floats keep distinct hashes from nearby ints
        assert numeric_hash32(np.array([3.5]))[0] != numeric_hash32(np.array([3.0]))[0]

    def test_device_hash_matches_host_on_floats(self):
        import jax
        from hyperspace_tpu.ops.encode import encode_sort_columns
        from hyperspace_tpu.ops.hashing import numeric_hash32
        from hyperspace_tpu.ops.sort import _device_hash32
        from hyperspace_tpu.utils.x64 import ensure_x64

        ensure_x64()
        vals = np.array([0.0, -0.0, 1.0, 3.0, -7.0, 3.5, np.nan, 2.0**40], dtype=np.float64)
        keys, kinds, _ = encode_sort_columns([vals])
        got = np.asarray(jax.jit(lambda k: _device_hash32("f", k))(jax.numpy.asarray(keys[0])))
        want = numeric_hash32(vals)
        np.testing.assert_array_equal(got, want)

    def test_nullable_int_key_bucketed_join_parity(self, tmp_path):
        """End-to-end q48 shape: fact side with NULLs in the join key
        (decodes float64) joined to a dense int dimension key; indexed ==
        non-indexed."""
        ld = str(tmp_path / "fact")
        rd = str(tmp_path / "dim")
        os.makedirs(ld), os.makedirs(rd)
        rng = np.random.default_rng(48)
        fk = rng.integers(0, 12, 4000).astype(np.float64)
        fk[rng.integers(0, 4000, 300)] = np.nan  # NULL FKs
        pq.write_table(
            pa.table({"fk": fk, "qty": rng.integers(1, 100, 4000).astype(np.int64)}),
            os.path.join(ld, "part-00000.parquet"),
        )
        pq.write_table(
            pa.table(
                {
                    "dk": np.arange(12, dtype=np.int64),
                    "dv": np.array([f"d{i}" for i in range(12)]),
                }
            ),
            os.path.join(rd, "part-00000.parquet"),
        )
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        fact = sess.read_parquet(ld)
        dim = sess.read_parquet(rd)
        hs.create_index(fact, hst.CoveringIndexConfig("f_idx", ["fk"], ["qty"]))
        hs.create_index(dim, hst.CoveringIndexConfig("d_idx", ["dk"], ["dv"]))
        sess.enable_hyperspace()
        q = fact.join(dim, on=hst.col("fk") == hst.col("dk")).select("qty", "dv")
        assert "IndexScan" in q.optimized_plan().pretty()
        on = q.collect()
        sess.disable_hyperspace()
        off = q.collect()
        assert len(on["qty"]) == len(off["qty"])
        assert sorted(zip(on["qty"], on["dv"])) == sorted(zip(off["qty"], off["dv"]))

    def test_bucket_pruning_int_literal_on_nullable_column(self, tmp_path):
        """FilterIndexRule bucket pruning: an int literal must land in the
        same bucket the (float-decoded) stored values were hashed into."""
        d = str(tmp_path / "data")
        os.makedirs(d)
        rng = np.random.default_rng(9)
        k = rng.integers(0, 50, 5000).astype(np.float64)
        k[rng.integers(0, 5000, 400)] = np.nan
        pq.write_table(
            pa.table({"k": k, "v": rng.uniform(0, 1, 5000)}),
            os.path.join(d, "part-00000.parquet"),
        )
        sess = _mk_session(
            tmp_path, **{hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True}
        )
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("p_idx", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.filter(hst.col("k") == 7).select("v")
        got = np.sort(q.collect()["v"])
        sess.disable_hyperspace()
        want = np.sort(q.collect()["v"])
        assert got.shape == want.shape and len(want) > 0
        np.testing.assert_allclose(got, want)


class TestBucketHashVersioning:
    def test_stale_hash_version_untrusts_layout(self, tmp_path):
        """An index stamped with an OLDER bucket-hash version must stop
        advertising its bucket layout (no SMJ, no pruning) while still
        serving correct index scans; a full refresh re-buckets and restores
        trust. (The round-5 value-consistent hash fix is version 2; v1
        indexes' placements are untrustworthy by construction.)"""
        import glob
        import json

        ld, rd = _join_fixture(tmp_path)
        sess = _mk_session(tmp_path, **{hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True})
        hs = hst.Hyperspace(sess)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        hs.create_index(left, hst.CoveringIndexConfig("vl_idx", ["lk"], ["lv"]))
        hs.create_index(right, hst.CoveringIndexConfig("vr_idx", ["rk"], ["rv"]))
        sess.enable_hyperspace()
        q = left.join(right, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        from hyperspace_tpu.exec import trace

        with trace.recording() as r0:
            want = q.collect()
        assert any("smj" in v for k, v in r0 if k == "join"), trace.summarize(r0)

        # doctor the LEFT index's log to claim the pre-fix hash version
        logs = glob.glob(
            os.path.join(str(tmp_path / "indexes"), "vl_idx", "_hyperspace_log", "*")
        )
        for p in logs:
            with open(p) as f:
                text = f.read()
            if "bucketHashVersion" in text:
                with open(p, "w") as f:
                    f.write(text.replace('"bucketHashVersion": "2"', '"bucketHashVersion": "1"'))

        sess2 = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
                hst.keys.NUM_BUCKETS: 8,
                hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True,
            }
        )
        hst.set_session(sess2)
        sess2.enable_hyperspace()
        left2 = sess2.read_parquet(ld)
        right2 = sess2.read_parquet(rd)
        q2 = left2.join(right2, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        with trace.recording() as r1:
            got = q2.collect()
        assert not any("smj" in v for k, v in r1 if k == "join"), trace.summarize(r1)
        assert sorted(zip(got["lv"], got["rv"])) == sorted(zip(want["lv"], want["rv"]))
        # bucket-pruned filters must also stop pruning (results stay right)
        qf = left2.filter(hst.col("lk") == 7).select("lv")
        with trace.recording() as r2:
            fon = np.sort(qf.collect()["lv"])
        assert not any("bucket-pruned" in v for _, v in r2), trace.summarize(r2)
        sess2.disable_hyperspace()
        np.testing.assert_allclose(fon, np.sort(qf.collect()["lv"]))
        sess2.enable_hyperspace()

        # full refresh re-buckets with the current hash: trust restored
        # (refresh refuses no-op source sets, so append one small file)
        rng = np.random.default_rng(77)
        pq.write_table(
            pa.table(
                {
                    "lk": rng.integers(0, 400, 50).astype(np.int64),
                    "lv": np.round(rng.uniform(0, 10, 50), 3),
                    "ls": np.array([f"R{j}" for j in range(50)]),
                }
            ),
            os.path.join(ld, "part-late.parquet"),
        )
        hs2 = hst.Hyperspace(sess2)
        hs2.refresh_index("vl_idx", "full")
        sess2.disable_hyperspace()
        left_w = sess2.read_parquet(ld)
        qw = left_w.join(right2, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        want = qw.collect()
        sess2.enable_hyperspace()
        left3 = sess2.read_parquet(ld)
        q3 = left3.join(right2, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        with trace.recording() as r3:
            got3 = q3.collect()
        assert any("smj" in v for k, v in r3 if k == "join"), trace.summarize(r3)
        assert sorted(zip(got3["lv"], got3["rv"])) == sorted(
            zip(want["lv"], want["rv"])
        )


class TestRebucketCache:
    def test_hybrid_appends_rebucket_once(self, tmp_path):
        """Hybrid scan re-buckets the appended files on the first query;
        repeats hit the cache; a NEW append invalidates (round-5 VERDICT
        item 4; ref: CoveringIndexRuleUtils.scala:357-417)."""
        ld, rd = _join_fixture(tmp_path)
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        hs.create_index(left, hst.CoveringIndexConfig("hl_idx", ["lk"], ["lv"]))
        hs.create_index(right, hst.CoveringIndexConfig("hr_idx", ["rk"], ["rv"]))
        # append AFTER indexing -> hybrid scan with a Repartition side
        rng = np.random.default_rng(5)
        pq.write_table(
            pa.table(
                {
                    "lk": rng.integers(0, 400, 200).astype(np.int64),
                    "lv": np.round(rng.uniform(0, 10, 200), 3),
                    "ls": np.array([f"A{j}" for j in range(200)]),
                }
            ),
            os.path.join(ld, "part-appended.parquet"),
        )
        sess.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        sess.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        sess.enable_hyperspace()
        left2 = sess.read_parquet(ld)
        q = left2.join(right, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec import trace

        D.clear_device_cache()
        with trace.recording() as r1:
            want = q.collect()
        assert ("rebucket", "computed") in r1, trace.summarize(r1)
        with trace.recording() as r2:
            got = q.collect()
        assert ("rebucket", "cached") in r2, trace.summarize(r2)
        assert ("rebucket", "computed") not in r2
        assert sorted(zip(got["lv"], got["rv"])) == sorted(zip(want["lv"], want["rv"]))
        # a second append must invalidate
        pq.write_table(
            pa.table(
                {
                    "lk": np.array([7, 7, 7], dtype=np.int64),
                    "lv": np.array([1.0, 2.0, 3.0]),
                    "ls": np.array(["x", "y", "z"]),
                }
            ),
            os.path.join(ld, "part-appended2.parquet"),
        )
        left3 = sess.read_parquet(ld)
        q3 = left3.join(right, on=hst.col("lk") == hst.col("rk")).select("lv", "rv")
        with trace.recording() as r3:
            got3 = q3.collect()
        assert ("rebucket", "computed") in r3, trace.summarize(r3)
        sess.disable_hyperspace()
        want3 = q3.collect()
        assert sorted(zip(got3["lv"], got3["rv"])) == sorted(
            zip(want3["lv"], want3["rv"])
        )


class TestPartitionedGenericJoin:
    @pytest.mark.parametrize("how", ["inner", "left", "outer"])
    def test_matches_unpartitioned(self, tmp_path, how):
        ld, rd = _join_fixture(tmp_path, skew_side=(how == "outer"))
        sess = _mk_session(tmp_path)  # no indexes -> generic merge path
        # the broadcast hash join would claim these small sides first; this
        # test targets the partitioned generic merge specifically
        sess.conf.set(hst.keys.EXEC_JOIN_BROADCAST_MAX_BYTES, 0)
        left = sess.read_parquet(ld)
        right = sess.read_parquet(rd)
        q = left.join(right, on=hst.col("lk") == hst.col("rk"), how=how).select(
            "lk", "lv", "rv"
        )
        want = q.collect()
        from hyperspace_tpu.exec import trace

        sess.conf.set(hst.keys.EXEC_JOIN_SPILL_MIN_ROWS, 500)
        with trace.recording() as rec:
            got = q.collect()
        assert any("partitioned" in v for _, v in rec), trace.summarize(rec)
        grows, _ = _sorted_rows(got)
        wrows, _ = _sorted_rows(want)
        assert grows == wrows
