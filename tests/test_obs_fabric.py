"""Distributed observability across the serving fabric: trace-context
propagation over FrontDoor hops, cross-process span stitching (one
end-to-end tree per routed request, per-process attribution, valid Chrome
export), byte-identical wire format when disabled, federated profile/SLO
merging with its documented error model, per-node staleness gauges, build
identity in every exposition, and device-program timing hooks."""

import json
import os
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.fabric import FrontDoor
from hyperspace_tpu.fabric.frontdoor import (
    WorkerEndpoint,
    WorkerError,
    merge_prometheus_texts,
)
from hyperspace_tpu.obs import spans
from hyperspace_tpu.obs.history import ProfileHistory, merge_history_snapshots
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.serving import QueryServer
from hyperspace_tpu.version import __version__
from test_obs import _validate_chrome

pytestmark = [pytest.mark.obs, pytest.mark.fabric]

N_THREADS = 8
REQS_PER_THREAD = 3


@pytest.fixture()
def traced_sess(tmp_path):
    """A small table + a session with tracing AND fabric stitching on."""
    n = 400
    d = tmp_path / "t"
    d.mkdir()
    pq.write_table(
        pa.table(
            {
                "c1": np.arange(n, dtype=np.int64),
                "m": np.arange(n, dtype=np.int64) % 3,
            }
        ),
        str(d / "part-0.parquet"),
    )
    sysp = tmp_path / "_indexes"
    sysp.mkdir()
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: str(sysp),
            hst.keys.NUM_BUCKETS: 4,
            hst.keys.OBS_TRACING_ENABLED: True,
            hst.keys.OBS_FABRIC_STITCH_ENABLED: True,
            hst.keys.OBS_PROFILE_HISTORY: 64,
        }
    )
    sess.enable_hyperspace()
    df = sess.read_parquet(str(d))
    df.create_or_replace_temp_view("t")
    sess.test_dataframe = df  # for tests that need to index the table
    return sess


# --- trace context (wire-format units) ---------------------------------------


class TestTraceContext:
    def test_traceparent_round_trip(self):
        ctx = spans.TraceContext.new()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        back = spans.parse_traceparent(ctx.to_traceparent())
        assert back is not None
        assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id
        assert back.sampled

    def test_child_keeps_trace_id_fresh_span_id(self):
        ctx = spans.TraceContext.new()
        hop = ctx.child()
        assert hop.trace_id == ctx.trace_id
        assert hop.span_id != ctx.span_id

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-deadbeef-cafe-01",  # bad lengths
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
            "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
            "00-" + "x" * 32 + "-" + "1" * 16 + "-01",  # non-hex
        ],
    )
    def test_malformed_traceparent_degrades_to_none(self, header):
        assert spans.parse_traceparent(header) is None

    def test_wire_round_trip_and_budgets(self):
        root = spans.start_trace("request", cat="query", max_spans=1000)
        with spans.attach(root):
            for i in range(6):
                with spans.span(f"step-{i}", cat="exec"):
                    pass
        root.finish()

        wire = spans.to_wire(root)
        rebuilt = spans.from_wire(wire, pid=4242)
        names = {sp.name for sp in rebuilt.walk()}
        assert names == {"request"} | {f"step-{i}" for i in range(6)}
        assert all(sp.pid == 4242 for sp in rebuilt.walk())

        # span budget: tree-prefix truncation, dropped count reported
        small = spans.to_wire(root, max_spans=3)
        assert small["droppedSpans"] == 4
        assert sum(1 for _ in spans.from_wire(small).walk()) == 3

        # byte budget: degrade to root-only, flagged
        tiny = spans.to_wire(root, max_bytes=10)
        assert tiny["truncated"] is True
        assert sum(1 for _ in spans.from_wire(tiny).walk()) == 1


# --- stitched routing --------------------------------------------------------


class TestStitchedRouting:
    def test_single_request_yields_one_stitched_tree(self, traced_sess):
        with QueryServer(traced_sess, workers=1, name="qsA") as a, QueryServer(
            traced_sess, workers=1, name="qsB"
        ) as b:
            with WorkerEndpoint(a) as ea, WorkerEndpoint(b) as eb:
                fd = FrontDoor([ea.url, eb.url], conf=traced_sess.conf)
                res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant="alice")
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                prof = fd.last_query_profile()

        root = prof.root
        assert root.name == "frontdoor-request"
        assert root.attrs["worker"] is not None
        assert root.attrs["retries"] == 0 and root.attrs["hedged"] is False
        routes = [c for c in root.children if c.name == "route"]
        assert len(routes) == 1
        assert routes[0].attrs["outcome"] == "ok"

        # the worker's whole tree hangs under the route attempt, parented by
        # the hop context: route.span_id == worker root.parent_span_id, one
        # trace id end to end
        grafted = [c for c in routes[0].children if c.name == "request"]
        assert len(grafted) == 1
        wroot = grafted[0]
        assert wroot.attrs["trace_id"] == root.attrs["trace_id"]
        assert wroot.attrs["parent_span_id"] == routes[0].attrs["span_id"]
        assert wroot.pid == os.getpid()  # in-process endpoint: same pid
        names = {sp.name for sp in wroot.walk()}
        assert names & {"resolve-plan", "resolve", "parse"}
        assert names & {"execute", "execute-shared-scan"}
        # the stitched copy lives in the ROUTER's trace budget
        assert all(sp.trace is root.trace for sp in root.walk())

        _validate_chrome(prof.chrome_trace())

    def test_concurrent_storm_one_disjoint_stitched_tree_each(self, traced_sess):
        with QueryServer(traced_sess, workers=4, name="qsA") as a, QueryServer(
            traced_sess, workers=4, name="qsB"
        ) as b:
            with WorkerEndpoint(a) as ea, WorkerEndpoint(b) as eb:
                fd = FrontDoor([ea.url, eb.url], conf=traced_sess.conf)
                errors = []
                start = threading.Barrier(N_THREADS)

                def client(k):
                    try:
                        start.wait()
                        for j in range(REQS_PER_THREAD):
                            fd.query(
                                f"SELECT m FROM t WHERE c1 >= {k + j}",
                                tenant=f"tenant-{k}",
                            )
                    except Exception as exc:
                        errors.append(exc)

                threads = [
                    threading.Thread(target=client, args=(k,))
                    for k in range(N_THREADS)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                assert not errors
                profiles = fd.last_profiles()

        assert len(profiles) == N_THREADS * REQS_PER_THREAD
        trace_ids = set()
        seen_spans = set()
        for prof in profiles:
            root = prof.root
            assert root.name == "frontdoor-request"
            grafted = [
                c for r in root.children if r.name == "route"
                for c in r.children if c.name == "request"
            ]
            # exactly one stitched worker tree per routed request
            assert len(grafted) == 1
            assert grafted[0].attrs["trace_id"] == root.attrs["trace_id"]
            trace_ids.add(root.attrs["trace_id"])
            ids = {id(sp) for sp in root.walk()}
            assert not (ids & seen_spans)  # no cross-request span leakage
            seen_spans |= ids
        assert len(trace_ids) == len(profiles)  # disjoint trace ids

    def test_worker_failure_yields_router_error_span_no_leak(self, traced_sess):
        with QueryServer(traced_sess, workers=1, name="qsA") as a:
            with WorkerEndpoint(a) as ea:
                fd = FrontDoor([ea.url], conf=traced_sess.conf)
                with pytest.raises(WorkerError):
                    fd.query("SELECT nope FROM missing_table")
                assert spans.current_span() is None  # nothing left attached
                prof = fd.last_query_profile()

        assert prof.error == "WorkerError"
        routes = [c for c in prof.root.children if c.name == "route"]
        assert len(routes) == 1
        assert routes[0].attrs["outcome"] == "error"
        assert routes[0].attrs["error"] == "WorkerError"
        # no attempt succeeded, so no worker is credited with the answer
        assert prof.root.attrs["worker"] is None

    def test_chrome_export_attributes_remote_pids(self):
        root = spans.start_trace("frontdoor-request", cat="fabric")
        with spans.attach(root):
            with spans.span("route", cat="fabric") as att:
                remote = spans.start_trace("request", cat="query", server="qsZ")
                with spans.attach(remote):
                    with spans.span("execute", cat="serving"):
                        pass
                remote.finish()
                wire = spans.to_wire(remote)
                wire["pid"] = 99_999
                wire["server"] = "qsZ"
                spans.graft_remote(att, wire, pid=99_999)
        root.finish()

        doc = spans.to_chrome_trace(root)
        _validate_chrome(doc)
        pids = {e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert os.getpid() in pids and 99_999 in pids
        process_names = {
            e["pid"]: e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert process_names[99_999] == "hyperspace_tpu worker qsZ"


# --- byte-identical wire when disabled ---------------------------------------


class _RecordingWorker:
    """A stub /query HTTP server that records request headers verbatim."""

    def __init__(self):
        self.headers = []
        recorder = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                recorder.headers.append(dict(self.headers))
                body = json.dumps({"columns": {"m": [0, 1, 2]}}).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)

    def header_names(self, i=-1):
        return {k.lower() for k in self.headers[i]}


class TestDisabledIsByteIdentical:
    def test_untraced_frontdoor_sends_no_trace_headers(self, session):
        stub = _RecordingWorker()
        try:
            fd = FrontDoor([stub.url])  # no conf: untraced legacy router
            fd.query("SELECT 1")
            assert "traceparent" not in stub.header_names()
            assert "x-hs-stitch" not in stub.header_names()
        finally:
            stub.close()

    def test_propagate_off_sends_no_trace_headers(self, session):
        session.conf.set(hst.keys.OBS_TRACING_ENABLED, True)
        session.conf.set(hst.keys.OBS_FABRIC_PROPAGATE, False)
        stub = _RecordingWorker()
        try:
            fd = FrontDoor([stub.url], conf=session.conf)
            fd.query("SELECT 1")
            assert "traceparent" not in stub.header_names()
            assert "x-hs-stitch" not in stub.header_names()
        finally:
            stub.close()
            session.conf.set(hst.keys.OBS_TRACING_ENABLED, False)
            session.conf.set(hst.keys.OBS_FABRIC_PROPAGATE, True)

    def test_propagation_on_stitch_off_sends_only_traceparent(self, session):
        session.conf.set(hst.keys.OBS_TRACING_ENABLED, True)
        stub = _RecordingWorker()
        try:
            fd = FrontDoor([stub.url], conf=session.conf)
            fd.query("SELECT 1")
            assert "traceparent" in stub.header_names()
            assert "x-hs-stitch" not in stub.header_names()
        finally:
            stub.close()
            session.conf.set(hst.keys.OBS_TRACING_ENABLED, False)

    def test_response_without_header_carries_no_trace_key(self, traced_sess):
        # even on a tracing+stitching worker, a request without the
        # x-hs-stitch header gets the exact legacy body shape
        with QueryServer(traced_sess, workers=1, name="qsA") as srv:
            with WorkerEndpoint(srv) as ep:
                with urllib.request.urlopen(
                    f"{ep.url}/query?sql=SELECT%20m%20FROM%20t%20WHERE%20c1%20%3E%3D%200",
                    timeout=30,
                ) as resp:
                    body = json.loads(resp.read().decode("utf-8"))
        assert set(body) == {"columns"}


# --- federation --------------------------------------------------------------


class TestFederation:
    def test_merge_history_snapshots_error_model(self):
        a, b = ProfileHistory(), ProfileHistory()
        for _ in range(100):
            a.record("fp1", 0.010, rows=10)
            b.record("fp1", 0.030, rows=30)
        b.record("fp2", 0.5)
        merged = merge_history_snapshots([a.snapshot(), b.snapshot()])

        assert merged["federated"] is True
        assert merged["fingerprints"] == 2
        by_fp = {e["fingerprint"]: e for e in merged["entries"]}
        lat = by_fp["fp1"]["latencySeconds"]
        # exact: counts, extrema; n-weighted exact: mean
        assert by_fp["fp1"]["count"] == 200
        assert lat["min"] == pytest.approx(0.010)
        assert lat["max"] == pytest.approx(0.030)
        assert lat["mean"] == pytest.approx(0.020, rel=0.05)
        # approximate: federated p50 is the n-weighted average of per-node
        # P² estimates — bounded by the cross-node spread
        assert 0.010 <= lat["p50"] <= 0.030
        assert by_fp["fp2"]["count"] == 1

    def test_frontdoor_profilez_and_statusz_federation(self, traced_sess):
        with QueryServer(traced_sess, workers=1, name="qsA") as a, QueryServer(
            traced_sess, workers=1, name="qsB"
        ) as b:
            with WorkerEndpoint(a) as ea, WorkerEndpoint(b) as eb:
                fd = FrontDoor([ea.url, eb.url], conf=traced_sess.conf)
                for t in range(6):
                    fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=f"t-{t}")
                fed = fd.profilez()
                statusz = fd.federated_statusz()

        assert fed["federated"] is True and fed["fingerprints"] >= 1
        assert sum(e["count"] for e in fed["entries"]) == 6
        assert set(fed["workers"]) == set(fd.worker_ids)
        assert all(w is not None for w in fed["workers"].values())

        assert set(statusz["workers"]) == set(fd.worker_ids)
        tenants = statusz["slo"]["tenants"]
        assert sum(t["good"] + t["bad"] for t in tenants.values()) == 6
        assert all(t["compliance"] is not None for t in tenants.values())


# --- identity, staleness gauges, flight route info ---------------------------


class TestFleetIdentity:
    def test_build_info_and_commit_seq_in_exposition(self, session):
        with QueryServer(session, workers=1, name="qsBld") as srv:
            text = srv.prometheus_text()
        assert "hs_build_info" in text
        # the registry is shared, so pick THIS server's line
        line = next(
            l
            for l in text.splitlines()
            if l.startswith("hs_build_info{") and 'server="qsBld"' in l
        )
        assert f'version="{__version__}"' in line
        assert 'node="' in line
        assert line.endswith(" 1.0") or line.endswith(" 1")

    def test_commit_seq_exported_only_when_fabric_on(self, tmp_system_path):
        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: tmp_system_path,
                hst.keys.FABRIC_ENABLED: True,
                hst.keys.FABRIC_NODE_ID: "nodeSeq",
                hst.keys.FABRIC_WATCHER_ENABLED: False,
            }
        )
        with QueryServer(sess, workers=1, name="qsSeq") as srv:
            text = srv.prometheus_text()
        line = next(
            l
            for l in text.splitlines()
            if l.startswith("hs_fabric_commit_seq{") and 'server="qsSeq"' in l
        )
        assert 'node="nodeSeq"' in line

    def test_merged_exposition_one_header_per_family(self, session):
        with QueryServer(session, workers=1, name="qsM1") as s1, QueryServer(
            session, workers=1, name="qsM2"
        ) as s2:
            merged = merge_prometheus_texts(
                [s1.prometheus_text(), s2.prometheus_text()]
            )
        assert merged.count("# HELP hs_build_info ") == 1
        assert merged.count("# TYPE hs_build_info ") == 1
        assert merged.count('server="qsM1"') > 0
        assert merged.count('server="qsM2"') > 0

    def test_watcher_staleness_gauges(self, tmp_system_path):
        from hyperspace_tpu.fabric.watcher import CommitWatcher

        sess = hst.Session(conf={hst.keys.SYSTEM_PATH: tmp_system_path})
        w = CommitWatcher(sess, node_id="nodeT", interval=3600.0)
        poll_ts = REGISTRY.gauge(
            "hs_fabric_watcher_last_poll_seconds", server="nodeT"
        )
        assert poll_ts.value == -1.0  # never polled
        w.poll_once()
        # a stable unixtime (age is computed scraper-side), not a live age
        import time

        assert abs(time.time() - poll_ts.value) < 60.0
        lag = REGISTRY.gauge("hs_fabric_commit_lag_seconds", server="nodeT")
        assert lag.value == 0.0  # nothing left to replay == caught up

    def test_flight_recorder_captures_route_outcomes(self, traced_sess):
        traced_sess.conf.set(hst.keys.OBS_SLOW_QUERY_MS, 0.001)
        with QueryServer(traced_sess, workers=1, name="qsA") as a:
            with WorkerEndpoint(a) as ea:
                fd = FrontDoor([ea.url], conf=traced_sess.conf)
                fd.query("SELECT m FROM t WHERE c1 >= 0")
                entries = fd.last_slow_queries()
        assert entries, "every request is slower than 1 microsecond"
        j = entries[-1].to_json()
        assert j["route"] == {
            "retries": 0,
            "hedged": False,
            "worker": fd.worker_ids[0],
        }
        # the captured profile is the stitched end-to-end tree
        assert entries[-1].profile is not None
        assert any(
            sp.name == "request" for sp in entries[-1].profile.root.walk()
        )


# --- device-program timing hooks ---------------------------------------------


class TestDeviceProgramTiming:
    def test_observe_program_metrics_and_span_event(self):
        import time

        from hyperspace_tpu.exec.device import _note_compile, _observe_program

        family = f"test-family-{os.getpid()}"
        sig = ("unit", (7, 3))
        assert _note_compile(family, sig) is True  # first sight compiles
        assert _note_compile(family, sig) is False

        root = spans.start_trace("request", cat="query")
        with spans.attach(root):
            t0 = time.perf_counter()
            _observe_program(family, True, t0)
            _observe_program(family, False, t0)
        root.finish()

        hist = REGISTRY.histogram("hs_device_program_seconds", program=family)
        assert hist.count == 2
        total = REGISTRY.counter("hs_device_compile_seconds_total", program=family)
        assert total.value > 0.0  # only the first-seen call contributed
        events = [ev for sp in root.walk() for ev in (sp.events or [])]
        kinds = [k for k, _ in events]
        assert kinds.count("device-program") == 2
        assert any("(compile)" in detail for _, detail in events)

    def test_fused_programs_observed_end_to_end(self, traced_sess):
        # the device filter only engages over index/file scans — give the
        # optimizer a covering index so the predicate runs as a device program
        hst.Hyperspace(traced_sess).create_index(
            traced_sess.test_dataframe, hst.CoveringIndexConfig("obsFab", ["c1"], ["m"])
        )
        base = REGISTRY.histogram(
            "hs_device_program_seconds", program="fused-filter"
        ).count
        traced_sess.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        try:
            res = traced_sess.sql(
                "SELECT m FROM t WHERE c1 > 10 AND c1 < 300"
            ).collect()
        finally:
            traced_sess.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        assert len(res["m"]) > 0
        got = REGISTRY.histogram(
            "hs_device_program_seconds", program="fused-filter"
        ).count
        assert got > base
