"""Seeded io-error-swallow violations: broad excepts around lake IO that
neither re-raise nor route through the reliability taxonomy."""


def read_footer(path, pq):
    try:
        return pq.read_metadata(path)
    except Exception:
        return None


def load_entry(path):
    try:
        with open(path, "rb") as f:
            return f.read()
    except:  # noqa: E722
        return b""
