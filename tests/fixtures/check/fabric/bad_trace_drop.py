"""Seeded trace-context-drop violations: a request-handling function that
spawns a bare thread, and a /query fetch with no traceparent header."""

import json
import threading
import urllib.request


def hedged_dispatch(workers, sql, tenant):
    results = []

    def run(worker):
        results.append(worker.query(sql, tenant=tenant))

    for worker in workers:
        threading.Thread(target=run, args=(worker,), daemon=True).start()
    return results


def fetch_remote(base, sql):
    url = f"{base}/query?sql={sql}"
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read().decode("utf-8"))
