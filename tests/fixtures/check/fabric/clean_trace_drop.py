"""Clean counterpart to bad_trace_drop.py: the same seams with the trace
context carried across them — spans.attach on the spawned thread, a
traceparent header on the /query hop — plus a lifecycle thread that
handles no request state and needs no marker."""

import json
import threading
import urllib.request

from hyperspace_tpu.obs import spans


def hedged_dispatch(workers, sql, tenant):
    results = []
    parent = spans.current_span()
    ctx = spans.current_context()

    def run(worker):
        with spans.attach(parent), spans.bind_context(ctx):
            results.append(worker.query(sql, tenant=tenant))

    for worker in workers:
        threading.Thread(target=run, args=(worker,), daemon=True).start()
    return results


def fetch_remote(base, sql, ctx):
    url = f"{base}/query?sql={sql}"
    request = urllib.request.Request(
        url, headers={"traceparent": ctx.to_traceparent()}
    )
    with urllib.request.urlopen(request, timeout=30) as resp:
        return json.loads(resp.read().decode("utf-8"))


class Poller:
    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass
