"""Seeded snapshot-pin violations: log versions resolved past the pin."""


def serves(self, session, name):
    log_m, _, _ = session.index_manager._managers(name)
    entry = log_m.get_latest_stable_log()  # bypasses the SnapshotHandle pin
    latest = log_m.get_latest_log()  # so does the unstable variant
    return entry, latest
