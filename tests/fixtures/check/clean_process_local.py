"""Clean ``process-local-state`` fixture: every escape hatch in one file."""

import itertools


class StrikeRegistry:
    pass


# fabric-published: listed in __fabric_published__ below
STRIKES = StrikeRegistry()

# explicitly process-local
_seq = itertools.count()  # hscheck: disable=process-local-state

# immutable module constants are never flagged
KINDS = ("transient", "corrupt")
LIMIT = 8
ENABLED = False

# dunders are exempt (mutable list or not)
__all__ = ["STRIKES"]

__fabric_published__ = ("STRIKES",)


def handler():
    cache = {}  # function-local mutables are instance/local state, fine
    return cache


class Holder:
    slots = {}  # class-body state is per-instance policy, out of scope
