"""Seeded metric-families violation: dynamically built family name."""


def register(registry, kind):
    return registry.counter("hs_" + kind + "_total", "dynamic name escapes drift checks")
