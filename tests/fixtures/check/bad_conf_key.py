"""Seeded conf-keys violation: unregistered key literal at a conf call."""


def misuse(conf):
    return conf.get("hyperspace.serving.quueDepth")  # typo'd, unregistered
