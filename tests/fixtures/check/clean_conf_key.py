"""Clean twin of bad_conf_key.py: registered key, non-conf receivers."""


def fine(conf, options):
    v = conf.get("hyperspace.exec.agg.enabled")
    # dict .get with a hyperspace-looking string is NOT a conf call
    w = options.get("hyperspace.anything.goes")
    return v, w
