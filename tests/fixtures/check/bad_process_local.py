"""Seeded ``process-local-state`` violations — every binding must fire."""

import itertools
from collections import defaultdict

BREAKERS = {}
HISTORY = defaultdict(list)
_request_seq = itertools.count()
SEEN: set = set()
ROUTES = FrontDoorRegistry()  # noqa: F821 — lint parses, never imports
