"""Clean twin of bad_branding.py: branding passed explicitly (None is fine)."""


def threads(self, session, batch, plan, child, condition, key, **kw):
    from hyperspace_tpu.exec.device import device_filter_mask, stage_filter_columns

    mask = self._filter_mask(plan, child, pruned_by=None)
    m2 = device_filter_mask(session, batch, condition, scan_key=key)
    stage_filter_columns(session, batch, condition, key)  # positional is fine
    m3 = device_filter_mask(session, batch, condition, **kw)  # forwarded
    return mask, m2, m3
