"""Clean twin of bad_snapshot_pin.py: resolution through the pin-aware API."""


def serves(self, session, name, snapshot):
    entry = session.index_manager.get_index(name)  # consults current_snapshot()
    pinned = snapshot.get_index(name)  # or the handle directly
    suppressed = self.log_manager.get_latest_stable_log()  # hscheck: disable=snapshot-pin
    return entry, pinned, suppressed
