"""Clean io-error-swallow fixture: narrow handlers, classified swallows,
re-raises, pragma'd deliberate swallows, and broad excepts away from lake
IO all pass."""

from hyperspace_tpu.reliability.errors import classify, count_io_error


def narrow(path, pq):
    # a specific failure mode with a specific fallback is the designed shape
    try:
        return pq.read_metadata(path)
    except OSError:
        return None


def reraises(path, pq):
    try:
        return pq.read_metadata(path)
    except Exception as exc:
        raise classify(exc, path=path) from exc


def counted_fallback(path):
    try:
        with open(path, "rb") as f:
            return f.read()
    except Exception as exc:
        count_io_error("fixture.read", exc, swallowed=True)
        return b""


def deliberate(path):
    try:
        with open(path, "rb") as f:
            return f.read()
    except Exception:  # hscheck: disable=io-error-swallow
        return b""


def not_lake_io(values):
    # broad except is fine when the try body never touches the lake
    try:
        return sum(values) / len(values)
    except Exception:
        return 0.0
