"""Clean twin of bad_metric.py: literal family name, non-registry receiver."""


def register(registry, accumulator):
    c = registry.counter("hs_events_total", "a literal, statically findable family")
    # .counter on a non-registry-looking receiver is not a registration site
    accumulator.counter("whatever" + "_dynamic")
    return c
