"""Seeded cache-branding violations: branding kwargs silently dropped."""


def leaks(self, session, batch, plan, child, condition):
    from hyperspace_tpu.exec.device import device_filter_mask, stage_filter_columns

    mask = self._filter_mask(plan, child)  # drops pruned_by
    m2 = device_filter_mask(session, batch, condition)  # drops scan_key
    stage_filter_columns(session, batch, condition)  # drops scan_key
    return mask, m2
