"""Clean native-fallback fixture: re-raises, classified swallows, counted
fallbacks, pragma'd deliberate swallows, and excepts away from native
decode all pass."""

from hyperspace_tpu.reliability.errors import classify, count_io_error


def _native_fallback_counter(reason):
    class _C:
        def inc(self, n=1):
            pass

    return _C()


def reraises(native, path, cols, hints):
    try:
        return native.read_columns(path, cols, hints)
    except Exception as exc:
        raise classify(exc, path=path) from exc


def counted_reroute(handle, g, c, dst):
    try:
        handle.read_fixed_rg_into(g, c, dst)
        return True
    except Exception:
        _native_fallback_counter("dialect").inc()
        return False


def classified_swallow(handle, g, c):
    try:
        return handle.read_codes_rg(g, c)
    except OSError as exc:
        count_io_error("io.decode", exc, swallowed=True)
        return None


def inline_counter(registry, handle, g, c):
    try:
        return handle.read_dict_rg(g, c)
    except Exception:
        registry.counter(
            "hs_native_fallback_total",
            "decodes rerouted to pyarrow",
            reason="dialect",
        ).inc()
        return None


def deliberate(handle, g, c):
    try:
        return handle.read_codes_rg(g, c)
    except Exception:  # hscheck: disable=native-fallback
        return None


def not_native(values):
    # read_columns on a non-native receiver is out of scope
    try:
        return values.read_columns()
    except Exception:
        return None
