"""Clean twin of bad_jit.py: jnp inside jit; np dtypes/constants are fine."""

import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated(x):
    return jnp.sum(x.astype(np.float64)) + np.float32(1.5)  # dtypes whitelisted


def helper(x):
    # NOT jitted anywhere: host numpy and time are fine here
    time.sleep(0)
    return np.sum(x)
