"""Clean twin of bad_lock.py: IO outside the lock, nested defs don't count."""

import threading
import time

_lock = threading.Lock()


def disciplined(arr):
    with _lock:
        snapshot = list(arr)

        def later():
            # runs AFTER the with-block, on some other thread
            time.sleep(0.01)

    time.sleep(0.0)  # outside the lock: fine
    with open("/tmp/hscheck-fixture", "w") as f:  # outside the lock: fine
        f.write("x")
    return snapshot, later
