"""Seeded lock-blocking violations: sleep/file IO/device sync under a mutex."""

import threading
import time

_lock = threading.Lock()


def convoy(arr):
    with _lock:
        time.sleep(0.01)
        with open("/tmp/hscheck-fixture", "w") as f:
            f.write("x")
        arr.block_until_ready()
