"""Seeded native-fallback violations: excepts around native decode calls
that neither re-raise, classify, nor count hs_native_fallback_total —
unaccounted pyarrow fallbacks."""


def whole_file(native, path, cols, hints):
    try:
        return native.read_columns(path, cols, hints)
    except Exception:
        return None


def per_chunk(handle, g, c, dst):
    # narrow handlers are flagged too: the fallback itself must be counted
    try:
        handle.read_fixed_rg_into(g, c, dst)
    except ValueError:
        dst[...] = 0


def dict_codes(handle, g, c):
    try:
        return handle.read_codes_rg(g, c)
    except:  # noqa: E722
        return None
