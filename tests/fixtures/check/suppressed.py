"""Pragma fixture: the same violation as bad_conf_key.py, suppressed."""


def misuse(conf):
    bare = conf.get("hyperspace.not.registered.a")  # hscheck: disable
    named = conf.get("hyperspace.not.registered.b")  # hscheck: disable=conf-keys
    other = conf.get("hyperspace.not.registered.c")  # hscheck: disable=some-other-rule
    return bare, named, other
