"""Clean donation idioms: rebind-before-reuse, non-donated args read
freely, starred calls conservatively skipped."""

import jax


def compile_stage(skeleton, fn, *, donate_argnums=()):
    return jax.jit(fn, donate_argnums=donate_argnums)


def fold_rebinds_state(state, chunk):
    jitted = compile_stage("fuse[F>G]", lambda s, c: s + c, donate_argnums=(0,))
    state = jitted(state, chunk)  # rebound to the call's result: fine
    return state.sum()


def non_donated_arg_read_is_fine(state, chunk):
    jitted = compile_stage("fuse[F>G]", lambda s, c: s + c, donate_argnums=(0,))
    out = jitted(state, chunk)
    return out + chunk.sum()  # chunk (argnum 1) was not donated


def starred_call_is_skipped(args):
    jitted = compile_stage("fuse[F>G]", lambda s, c: s + c, donate_argnums=(0,))
    out = jitted(*args)
    return out, args
