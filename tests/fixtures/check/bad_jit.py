"""Seeded jit-purity violations: host numpy/time/random in jitted code."""

import random
import time

import jax
import numpy as np


@jax.jit
def decorated(x):
    t = time.time()  # freezes at trace time
    return np.sum(x) + t  # host numpy on a tracer


def by_name(x):
    return x * random.random()  # freezes at trace time


jitted = jax.jit(by_name)


def wrapped(key, fn):
    return fn


def cached(x):
    return np.mean(x)  # host numpy; jitted via the *jit*-named wrapper below


program = wrapped("k", cached)
compiled = _cached_predicate_jit = None


def _fake_jit(key, fn):
    return fn


_cached_predicate_jit = _fake_jit
built = _cached_predicate_jit("skeleton", cached)
