"""Seeded donated-buffer-reuse violations: reads of donated buffers after
the jitted call that deleted them."""

import jax


def compile_stage(skeleton, fn, *, donate_argnums=()):
    return jax.jit(fn, donate_argnums=donate_argnums)


def fold_reads_dead_state(state, chunk):
    jitted = compile_stage("fuse[F>G]", lambda s, c: s + c, donate_argnums=(0,))
    out = jitted(state, chunk)
    return out + state.sum()  # VIOLATION: state's buffer was donated


def direct_jit_form(state, x):
    out = jax.jit(lambda s, v: s * v, donate_argnums=0)(state, x)
    total = state.mean()  # VIOLATION: donated via the inline jit call
    return out, total
