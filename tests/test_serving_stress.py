"""Concurrency, throughput, and backpressure tests for the serving runtime.

The acceptance bar (ISSUE): N concurrent clients over one QueryServer lose no
requests and get answers identical to direct ``Session.sql().collect()``;
repeated-query throughput with the plan cache is >= 3x the cache-disabled
runtime on the CPU mesh; a full queue rejects explicitly instead of
deadlocking or buffering unboundedly. The soak test (marked slow+soak, out of
tier-1) runs a longer mixed workload and asserts every bound stays bounded.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.serving import AdmissionRejected, QueryServer


def _build_env(root):
    """Indexed single-table session: 8 covering indexes make plan compilation
    meaningfully more expensive than executing the (small) query — the cost
    profile the plan cache exists for."""
    import os

    n = 4000
    d = os.path.join(root, "sales")
    os.makedirs(d)
    pq.write_table(
        pa.table(
            {
                "k": np.arange(n, dtype=np.int64) % 997,
                "v": (np.arange(n, dtype=np.int64) * 31) % 1000,
                "w": np.arange(n, dtype=np.int64),
                "a": np.arange(n, dtype=np.int64) % 13,
                "b": np.arange(n, dtype=np.int64) % 7,
            }
        ),
        os.path.join(d, "part-0.parquet"),
    )
    sysp = os.path.join(root, "_idx")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(d)
    df.create_or_replace_temp_view("sales")
    rosters = [
        (["v"], ["k", "w"]), (["k"], ["v"]), (["w"], ["a"]), (["a"], ["b"]),
        (["b"], ["k"]), (["v", "k"], ["w"]), (["k", "a"], ["w"]), (["a", "b"], ["v"]),
    ]
    for i, (indexed, included) in enumerate(rosters):
        hs.create_index(df, hst.CoveringIndexConfig(f"idx{i}", indexed, included))
    sess.enable_hyperspace()
    return sess


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    sess = _build_env(str(tmp_path_factory.mktemp("serving_stress")))
    yield sess
    hst.set_session(None)


@pytest.fixture()
def perf_env(tmp_path):
    """Dedicated session for the throughput bar: a wide index roster (24
    covering indexes) and a compound predicate make each compile ~4x the
    execute cost, which is the regime the plan cache targets. Function-scoped
    so other tests' cache warming can't flatten the measured contrast."""
    import os

    n = 2000
    d = str(tmp_path / "sales")
    os.makedirs(d)
    names = list("abcdefgh")
    cols = {c: (np.arange(n, dtype=np.int64) * (3 + i)) % (97 + 13 * i) for i, c in enumerate(names)}
    cols["v"] = (np.arange(n, dtype=np.int64) * 31) % 1000
    pq.write_table(pa.table(cols), os.path.join(d, "part-0.parquet"))
    sysp = str(tmp_path / "_idx")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(d)
    df.create_or_replace_temp_view("sales")
    k = 0
    for i in range(8):
        for j in range(3):
            indexed = [names[i]] if j == 0 else [names[i], names[(i + j) % 8]]
            hs.create_index(df, hst.CoveringIndexConfig(f"ix{k}", indexed, ["v"]))
            k += 1
    sess.enable_hyperspace()
    yield sess
    hst.set_session(None)


def _rows(batch):
    """Order-insensitive row multiset for result comparison."""
    cols = sorted(batch)
    return sorted(zip(*(batch[c].tolist() for c in cols)))


# --- correctness under concurrency ------------------------------------------


def test_concurrent_clients_lose_nothing_and_agree_with_collect(env):
    texts = [
        "SELECT k, w FROM sales WHERE v > 250",
        "SELECT k, w FROM sales WHERE v > 500",
        "SELECT k, w FROM sales WHERE v > 750",
        "SELECT v FROM sales WHERE k = 13",
        "SELECT v FROM sales WHERE k = 700",
        "SELECT w AS row_id FROM sales WHERE a = 5 AND b = 2",
        "SELECT count(*) AS c FROM sales WHERE v > 100",
        "SELECT a, count(*) AS c FROM sales WHERE v > 400 GROUP BY a ORDER BY a",
    ]
    expected = {q: _rows(env.sql(q).collect()) for q in texts}
    n_threads, per_thread = 8, 25
    results, errors = {}, []
    lock = threading.Lock()

    with QueryServer(env, workers=4, queue_depth=4096) as srv:

        def client(tid):
            try:
                for i in range(per_thread):
                    q = texts[(tid + i) % len(texts)]
                    got = srv.query(q, timeout=60)
                    with lock:
                        results[(tid, i)] = (q, _rows(got))
            except Exception as exc:  # pragma: no cover - failure reporting
                with lock:
                    errors.append((tid, exc))

        threads = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = srv.stats()

    assert errors == []
    # zero lost, zero duplicated: every (thread, i) slot resolved exactly once
    assert len(results) == n_threads * per_thread
    for (tid, i), (q, rows) in results.items():
        assert rows == expected[q], f"thread {tid} req {i}: {q!r} diverged"
    assert stats["completed"] == n_threads * per_thread
    assert stats["errors"] == 0 and stats["queue"]["rejected"] == 0
    # the workload repeats 8 structures: the cache must be earning hits
    assert stats["planCache"]["hitRate"] > 0.5


def test_hyperspace_toggle_racing_serving_is_safe(env):
    """Satellite (b): enable/disable toggles racing in-flight queries must
    never corrupt results — each request pins the flag it was admitted under,
    and on/off answers are identical anyway (index-parity invariant)."""
    q = "SELECT k, w FROM sales WHERE v > 333"
    expected = _rows(env.sql(q).collect())
    stop = threading.Event()
    errors = []

    def toggler():
        while not stop.is_set():
            with env.with_hyperspace_disabled():
                time.sleep(0.0005)
            time.sleep(0.0005)

    with QueryServer(env, workers=3, queue_depth=4096) as srv:
        tg = threading.Thread(target=toggler)
        tg.start()
        try:
            def client():
                try:
                    for _ in range(40):
                        assert _rows(srv.query(q, timeout=60)) == expected
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

            threads = [threading.Thread(target=client) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            stop.set()
            tg.join()
    assert errors == []
    assert env.hyperspace_enabled is True  # toggler's scopes never leaked


# --- throughput --------------------------------------------------------------


def _serve_qps(sess, plans, enabled, reps):
    srv = QueryServer(sess, workers=2, plan_cache_enabled=enabled, queue_depth=8192).start()
    try:
        for p in plans:  # warm: compile once, fill the io cache
            srv.submit(p)
        srv.stats()
        futs = []
        t0 = time.perf_counter()
        for _ in range(reps):
            for p in plans:
                futs.append(srv.submit(p))
        for f in futs:
            f.result(timeout=120)
        dt = time.perf_counter() - t0
        return len(futs) / dt, srv.stats()
    finally:
        srv.shutdown()


def test_plan_cache_throughput_3x(perf_env):
    """ISSUE acceptance: repeated same-structure queries >= 3x faster with the
    plan cache than without (measured at ~4.7-4.9x on the dev CPU mesh)."""
    plans = [
        perf_env.sql(f"SELECT a, v FROM sales WHERE b > {30 + i} AND c > 5 AND d < 90").plan
        for i in range(16)
    ]
    # thread-scheduler noise swings a single measurement by 2x, so re-measure
    # (up to 3 rounds) before declaring the bar missed: a real cache
    # regression shows ~1x on EVERY round, never a lucky 3x
    best, detail = 0.0, ""
    for _ in range(3):
        qps_off, _ = _serve_qps(perf_env, plans, enabled=False, reps=20)
        qps_on, stats_on = _serve_qps(perf_env, plans, enabled=True, reps=20)
        assert stats_on["planCache"]["hitRate"] > 0.9
        assert stats_on["errors"] == 0
        ratio = qps_on / qps_off
        if ratio > best:
            best, detail = ratio, f"on={qps_on:.0f}/s off={qps_off:.0f}/s"
        if best >= 3.0:
            break
    assert best >= 3.0, f"plan cache speedup {best:.2f}x ({detail})"


# --- backpressure -------------------------------------------------------------


def test_flood_rejects_explicitly_and_loses_nothing(env):
    """A tiny queue under a submit flood: overflow must reject at submit time
    (never deadlock, never buffer past the bound) while every ADMITTED
    request still completes correctly."""
    q = "SELECT k, w FROM sales WHERE v > 123"
    expected = _rows(env.sql(q).collect())
    plan = env.sql(q).plan
    # cache+batching off so the single worker stays busy enough to overflow
    srv = QueryServer(
        env, workers=1, queue_depth=4, plan_cache_enabled=False,
        micro_batch_enabled=False, prefetch_enabled=False,
    ).start()
    accepted, rejected = [], 0
    try:
        for _ in range(200):
            try:
                accepted.append(srv.submit(plan, timeout=120))
            except AdmissionRejected:
                rejected += 1
        for f in accepted:
            assert _rows(f.result(timeout=120)) == expected
        stats = srv.stats()
    finally:
        srv.shutdown()
    assert rejected > 0, "flood never overflowed a depth-4 queue"
    assert stats["queue"]["rejected"] == rejected
    assert stats["queue"]["submitted"] == len(accepted) == 200 - rejected
    assert stats["completed"] == len(accepted)


# --- soak --------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.soak
def test_serving_soak_bounded(env):
    """~15s mixed workload: repeated + novel structures, toggles, floods.
    Every resource must stay inside its configured bound the whole time."""
    base = [f"SELECT k, w FROM sales WHERE v > {i % 900}" for i in range(32)]
    expected = {q: _rows(env.sql(q).collect()) for q in base[:8]}
    deadline = time.monotonic() + 15.0
    errors = []
    with QueryServer(
        env, workers=3, queue_depth=64, plan_cache_max_entries=16,
        bucket_cache_bytes=1 << 22,
    ) as srv:
        i = 0
        while time.monotonic() < deadline:
            i += 1
            batch = []
            for j in range(24):
                q = base[(i * 7 + j) % len(base)] if j % 3 else (
                    f"SELECT w FROM sales WHERE k = {i % 997} AND a = {j % 13}"
                )
                try:
                    batch.append((q, srv.submit(q, timeout=60)))
                except AdmissionRejected:
                    pass  # explicit backpressure is the contract
            for q, f in batch:
                try:
                    got = _rows(f.result(timeout=60))
                    if q in expected and got != expected[q]:
                        errors.append(f"divergence on {q!r}")
                except Exception as exc:
                    errors.append(f"{q!r}: {exc!r}")
            if i % 10 == 0:
                with env.with_hyperspace_disabled():
                    time.sleep(0.001)
            stats = srv.stats(emit=True)
            assert stats["planCache"]["entries"] <= 16
            assert stats["bucketCache"]["bytes"] <= stats["bucketCache"]["capBytes"]
            assert stats["queue"]["queued"] <= 64
        final = srv.stats()
    assert errors == []
    assert final["errors"] == 0
    assert final["completed"] > 0 and final["planCache"]["hitRate"] > 0.3


@pytest.mark.check
def test_lock_order_acyclic_under_concurrency(env):
    """hscheck lock watcher over the real serving stack: build the server
    with the watcher ON (locks instrument at construction) and hammer it from
    8 threads — the observed cross-module acquisition graph must be acyclic,
    i.e. no ABBA deadlock is reachable on the paths this workload drives."""
    from hyperspace_tpu.check.locks import WatchedLock, watcher

    texts = [
        "SELECT k, w FROM sales WHERE v > 250",
        "SELECT v FROM sales WHERE k = 13",
        "SELECT a, count(*) AS c FROM sales WHERE v > 400 GROUP BY a ORDER BY a",
    ]
    watcher.enable()
    watcher.reset()
    try:
        with QueryServer(env, workers=4, queue_depth=256) as srv:
            # locks instrument at construction: the server was built under an
            # enabled watcher, so its serving-layer locks must be watched
            assert isinstance(srv._sql_memo_lock, WatchedLock)
            errors = []

            def client(tid):
                try:
                    for i in range(10):
                        srv.query(texts[(tid + i) % len(texts)], timeout=60)
                except Exception as exc:  # pragma: no cover - failure reporting
                    errors.append((tid, exc))

            threads = [threading.Thread(target=client, args=(t,)) for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            srv.stats(emit=True)
        assert errors == []
        # an empty edge set is the ideal outcome (no lock ever nests another);
        # any edges that DID appear must not form a cycle
        cycles = watcher.report()
        assert cycles == [], f"lock-order cycles observed: {cycles}"
    finally:
        watcher.disable()
        watcher.reset()
