"""Long refresh-while-serving endurance runs (out of tier-1; the fast
deterministic variant lives in test_lifecycle.py).

Drives a QueryServer with continuous traffic while the lifecycle refresh
manager commits appends (and, in the second test, deletes through lineage)
and asserts the serving invariant from docs/lifecycle.md over many rounds:
no torn results, no stale results, deletions invisible once committed."""

import os
import threading
import time

import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.lifecycle import RefreshManager

from tests.test_e2e_rules import assert_batches_equal
from tests.test_lifecycle import run_refresh_serving_soak, write_marked_part

pytestmark = [pytest.mark.lifecycle, pytest.mark.soak, pytest.mark.slow]


def test_soak_long_append_refresh_under_traffic(session, tmp_path):
    out = run_refresh_serving_soak(
        session, tmp_path, rounds=20, workers=4, initial_files=4, n=200
    )
    assert out["violations"] == [], out["violations"][:20]
    assert out["commits"] == 20
    assert out["queries"] >= 20  # sustained traffic actually overlapped commits

    q = session.read_parquet(str(tmp_path / "soak")).filter(hst.col("c1") >= 0).select("m")
    on = q.collect()
    session.disable_hyperspace()
    assert_batches_equal(on, q.collect())


def test_soak_appends_and_deletes_with_lineage(session, tmp_path):
    from hyperspace_tpu.serving import QueryServer

    n = 150
    root = tmp_path / "soakdel"
    root.mkdir()
    files = {}
    for i in range(4):
        files[i] = write_marked_part(str(root), i, n=n)

    session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    session.conf.set(hst.keys.LINEAGE_ENABLED, True)
    session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.95)
    session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.95)
    session.conf.set(hst.keys.LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS, 1)
    hs_api = hst.Hyperspace(session)
    df = session.read_parquet(str(root))
    hs_api.create_index(df, hst.CoveringIndexConfig("soakDelIdx", ["c1"], ["m"]))
    session.enable_hyperspace()

    rm = RefreshManager(session)
    state_lock = threading.Lock()
    committed = set(range(4))  # markers visible via a refresh commit
    deleted = set()            # markers whose deletion has committed
    violations = []
    stop = threading.Event()
    queries_done = [0]

    def query_loop():
        while not stop.is_set():
            with state_lock:
                need, gone = set(committed), set(deleted)
            try:
                q = session.read_parquet(str(root)).filter(hst.col("c1") >= 0).select("m")
                res = server.submit(q).result(timeout=120)
            except Exception as exc:
                violations.append(("query-error", repr(exc)))
                continue
            vals, cnts = np.unique(res["m"], return_counts=True)
            seen = dict(zip(vals.tolist(), cnts.tolist()))
            for mk, c in seen.items():
                if c != n:
                    violations.append(("torn", mk, c))
            for mk in need:
                if seen.get(mk) != n:
                    violations.append(("stale", mk, seen.get(mk)))
            for mk in gone:
                if mk in seen:
                    violations.append(("undead", mk, seen[mk]))
            queries_done[0] += 1

    with QueryServer(session, workers=4) as server:
        threads = [threading.Thread(target=query_loop) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            next_marker = 4
            for r in range(16):
                if r % 4 == 3 and len(committed - deleted) > 2:
                    # delete the oldest still-visible marker, then commit
                    victim = min(committed - deleted)
                    with state_lock:
                        committed.discard(victim)  # in-limbo until proven gone
                    os.remove(files[victim])
                    outcome = rm.refresh_index("soakDelIdx", "incremental")
                    if outcome != "committed":
                        violations.append(("refresh-del", victim, outcome))
                        continue
                    with state_lock:
                        deleted.add(victim)
                else:
                    marker = next_marker
                    next_marker += 1
                    files[marker] = write_marked_part(str(root), marker, n=n)
                    outcome = rm.refresh_index("soakDelIdx", "incremental")
                    if outcome != "committed":
                        violations.append(("refresh-add", marker, outcome))
                        continue
                    with state_lock:
                        committed.add(marker)
                time.sleep(0.02)
        finally:
            stop.set()
            for t in threads:
                t.join(60)

    assert violations == [], violations[:20]
    assert queries_done[0] >= 16

    q = session.read_parquet(str(root)).filter(hst.col("c1") >= 0).select("m")
    on = q.collect()
    session.disable_hyperspace()
    assert_batches_equal(on, q.collect())
    assert sorted(np.unique(on["m"]).tolist()) == sorted(committed - deleted)
