"""Refresh + optimize + hybrid-scan tests
(ref: src/test/scala/.../index/RefreshIndexTest.scala (494),
HybridScanSuite.scala (743), actions/OptimizeActionTest)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.actions.base import HyperspaceActionException, NoChangesException
from hyperspace_tpu.plan import logical as L

from tests.test_e2e_rules import assert_batches_equal


def write_part(root, idx, n=250, seed=0):
    rng = np.random.default_rng(seed + idx)
    t = pa.table(
        {
            "c1": rng.integers(0, 100, n).astype(np.int64),
            "c2": rng.integers(0, 1000, n).astype(np.int64),
        }
    )
    pq.write_table(t, os.path.join(root, f"part-{idx:05d}.parquet"))


@pytest.fixture()
def mutable_data(tmp_path):
    root = tmp_path / "mutable"
    root.mkdir()
    for i in range(3):
        write_part(str(root), i)
    return str(root)


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestRefresh:
    def test_refresh_no_changes_raises(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rIdx", ["c1"], ["c2"]))
        with pytest.raises(NoChangesException):
            hs.refresh_index("rIdx", "incremental")

    def test_refresh_full_after_append(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rFull", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=99)

        entry = hs.refresh_index("rFull", "full")
        assert entry.state == "ACTIVE"
        # refreshed index must be applied to queries over the new data
        df2 = session.read_parquet(mutable_data)
        session.enable_hyperspace()
        q = df2.filter(hst.col("c1") == 7).select("c2")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())

    def test_refresh_incremental_appended_only(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rInc", ["c1"], ["c2"]))
        old_entry = hs._manager.get_index("rInc")
        write_part(mutable_data, 3, seed=123)

        entry = hs.refresh_index("rInc", "incremental")
        # merge mode keeps old index files and adds delta files
        assert set(old_entry.content.files) <= set(entry.content.files)
        assert len(entry.content.files) > len(old_entry.content.files)

        df2 = session.read_parquet(mutable_data)
        session.enable_hyperspace()
        q = df2.filter(hst.col("c1") == 7).select("c2")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())

    def test_refresh_incremental_deletes_require_lineage(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rDel", ["c1"], ["c2"]))
        os.remove(os.path.join(mutable_data, "part-00002.parquet"))
        with pytest.raises(HyperspaceActionException, match="lineage"):
            hs.refresh_index("rDel", "incremental")

    def test_refresh_incremental_with_deletes_and_lineage(self, session, hs, mutable_data):
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rDelL", ["c1"], ["c2"]))
        os.remove(os.path.join(mutable_data, "part-00002.parquet"))
        write_part(mutable_data, 3, seed=55)

        hs.refresh_index("rDelL", "incremental")
        df2 = session.read_parquet(mutable_data)
        session.enable_hyperspace()
        q = df2.filter(hst.col("c1") == 7).select("c2")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())

    def test_refresh_quick_records_update(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rQuick", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=77)
        entry = hs.refresh_index("rQuick", "quick")
        assert len(entry.appended_files()) == 1
        assert entry.appended_files()[0].name.endswith("part-00003.parquet")


class TestHybridScan:
    def _enable_hybrid(self, session):
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.9)

    def test_hybrid_scan_appended(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("hIdx", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=31)

        self._enable_hybrid(session)
        df2 = session.read_parquet(mutable_data)
        q = df2.filter(hst.col("c1") == 7).select("c2")
        baseline = q.collect()

        session.enable_hyperspace()
        plan = q.optimized_plan()
        nodes = L.collect(plan, lambda p: True)
        assert any(isinstance(p, L.BucketUnion) for p in nodes), plan.pretty()
        assert any(isinstance(p, L.IndexScan) for p in nodes)
        assert any(isinstance(p, L.Repartition) for p in nodes)
        # the appended-file scan reads ONLY the appended file
        fscans = [p for p in nodes if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1
        assert fscans[0].files[0].endswith("part-00003.parquet")
        assert_batches_equal(q.collect(), baseline)

    def test_hybrid_scan_deleted_rows_filtered(self, session, hs, mutable_data):
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("hDel", ["c1"], ["c2"]))
        os.remove(os.path.join(mutable_data, "part-00001.parquet"))

        self._enable_hybrid(session)
        df2 = session.read_parquet(mutable_data)
        q = df2.filter(hst.col("c1") == 7).select("c2")
        baseline = q.collect()

        session.enable_hyperspace()
        plan = q.optimized_plan()
        nodes = L.collect(plan, lambda p: True)
        # deleted-row filtering: a NOT-IN filter over the lineage column
        assert any(isinstance(p, L.IndexScan) and "_data_file_id" in p.columns for p in nodes), plan.pretty()
        assert_batches_equal(q.collect(), baseline)

    def test_hybrid_scan_threshold_rejects(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("hThresh", ["c1"], ["c2"]))
        # append as much data as existed -> ratio 0.5 > 0.3 default
        for i in range(3, 6):
            write_part(mutable_data, i, seed=i)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        df2 = session.read_parquet(mutable_data)
        session.enable_hyperspace()
        plan = df2.filter(hst.col("c1") == 7).select("c2").optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))


class TestOptimize:
    def test_optimize_compacts_buckets(self, session, hs, mutable_data):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("oIdx", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=13)
        hs.refresh_index("oIdx", "incremental")
        before = hs._manager.get_index("oIdx")
        # incremental refresh -> multiple files per bucket
        assert len(before.content.files) > 4

        entry = hs.optimize_index("oIdx", "quick")
        assert len(entry.content.files) <= 4

        df2 = session.read_parquet(mutable_data)
        session.enable_hyperspace()
        q = df2.filter(hst.col("c1") == 7).select("c2")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())

    def test_optimize_single_files_no_changes(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("oNc", ["c1"], ["c2"]))
        with pytest.raises(NoChangesException):
            hs.optimize_index("oNc", "quick")

    def test_cancel_recovers_stuck_index(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("cIdx", ["c1"], ["c2"]))
        # simulate a stuck REFRESHING state by writing a transient log
        from hyperspace_tpu.models.log_manager import IndexLogManager
        from hyperspace_tpu.models.path_resolver import PathResolver

        path = PathResolver(session.conf).get_index_path("cIdx")
        log_m = IndexLogManager(path)
        stuck = log_m.get_latest_log()
        stuck.state = "REFRESHING"
        assert log_m.write_log(log_m.get_latest_id() + 1, stuck)
        hs._manager.clear_cache()

        hs.cancel("cIdx")
        assert hs._manager.get_index("cIdx").state == "ACTIVE"
