"""Working set > cache capacity: the >HBM spill story (SURVEY §7 hard part,
round-2 VERDICT item 4's mechanism half).

When the index working set exceeds the byte-capped caches (HBM column cache
in exec/device.py, host batch cache in exec/io.py), BytesLRU evicts
least-recently-used entries and queries keep returning correct results —
re-decoding/re-uploading on demand rather than failing or growing without
bound. These tests pin that behavior by shrinking the caps far below the
index size and checking correctness + cap enforcement across repeated and
rotating queries. (Chip timing of the same path at SF10 is the hardware
half, gated on the TPU tunnel.)
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import device as D
from hyperspace_tpu.exec import io as hs_io


class _CountingLRU:
    """BytesLRU wrapper recording cumulative inserted bytes, so tests can
    prove the working set really exceeded the cap (eviction happened) rather
    than just re-asserting the cap invariant."""

    def __init__(self, cap_bytes: int):
        from hyperspace_tpu.utils.lru import BytesLRU

        self._inner = BytesLRU(cap_bytes)
        self.inserted_bytes = 0

    def put(self, key, value, nbytes):
        self.inserted_bytes += nbytes
        self._inner.put(key, value, nbytes)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __len__(self):
        return len(self._inner)


@pytest.fixture()
def tiny_caches(monkeypatch):
    """Shrink every byte-capped cache far below the index working set."""
    dev = _CountingLRU(256 * 1024)
    io_ = _CountingLRU(256 * 1024)
    rank = _CountingLRU(64 * 1024)
    monkeypatch.setattr(D, "_device_cache", dev)
    monkeypatch.setattr(D, "_RANK_CACHE", rank)
    monkeypatch.setattr(hs_io, "_io_cache", io_)
    return dev, io_, rank


@pytest.fixture()
def big_indexed(session, tmp_path):
    """Two tables whose covering indexes total ~8 MB — 30x the shrunken
    caps — so every query cycles entries through eviction."""
    hs = hst.Hyperspace(session)
    rng = np.random.default_rng(0)
    n = 200_000
    f = pa.table(
        {
            "k": rng.integers(0, 50_000, n).astype(np.int64),
            "v": rng.standard_normal(n),
            "w": rng.standard_normal(n),
        }
    )
    g = pa.table(
        {
            "gk": np.arange(50_000, dtype=np.int64),
            "gv": rng.standard_normal(50_000),
        }
    )
    for name, t in (("f", f), ("g", g)):
        root = tmp_path / name
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
    fdf = session.read_parquet(str(tmp_path / "f"))
    gdf = session.read_parquet(str(tmp_path / "g"))
    hs.create_index(fdf, hst.CoveringIndexConfig("f_k_cp", ["k"], ["v", "w"]))
    hs.create_index(gdf, hst.CoveringIndexConfig("g_gk_cp", ["gk"], ["gv"]))
    session.enable_hyperspace()
    return fdf, gdf, f.to_pandas(), g.to_pandas()


class TestCachePressure:
    def test_filter_correct_under_eviction(self, session, tiny_caches, big_indexed):
        dev, io_, _ = tiny_caches
        fdf, _, fpd, _ = big_indexed
        for key in (7, 4321, 49_000, 7):  # repeat 7: hits after eviction too
            q = fdf.filter(hst.col("k") == key).select("v")
            assert "IndexScan" in q.optimized_plan().pretty()
            got = np.sort(q.collect()["v"])
            want = np.sort(fpd[fpd.k == key].v.to_numpy())
            np.testing.assert_allclose(got, want)
        assert io_.total_bytes <= io_.cap
        assert dev.total_bytes <= dev.cap

    def test_join_correct_under_eviction(self, session, tiny_caches, big_indexed):
        dev, io_, rank = tiny_caches
        fdf, gdf, fpd, gpd = big_indexed
        q = fdf.join(gdf, on=hst.col("k") == hst.col("gk")).select("v", "gv")
        for _ in range(2):  # second run re-loads whatever was evicted
            got = q.collect()
            merged = fpd.merge(gpd, left_on="k", right_on="gk")
            assert len(got["v"]) == len(merged)
            np.testing.assert_allclose(np.sort(got["gv"]), np.sort(merged.gv.to_numpy()))
        assert io_.total_bytes <= io_.cap
        assert dev.total_bytes <= dev.cap
        assert rank.total_bytes <= rank.cap

    def test_eviction_actually_happened(self, session, tiny_caches, big_indexed):
        """The working set really exceeds the caps: cumulative bytes offered
        to the cache are many times the cap, yet the residency invariant
        holds — i.e. entries were actually evicted under pressure."""
        _, io_, _ = tiny_caches
        fdf, _, fpd, _ = big_indexed
        got = fdf.filter(hst.col("k") >= 0).select("v").collect()
        assert len(got["v"]) == len(fpd)
        assert 0 < io_.total_bytes <= io_.cap
        # the scan pushed far more bytes through than fit: eviction proven
        assert io_.inserted_bytes > 4 * io_.cap
        evicted = io_.inserted_bytes - io_.total_bytes
        assert evicted > 0
