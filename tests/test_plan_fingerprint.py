"""Canonical plan-fingerprint properties over the TPC-H corpus.

The serving plan cache keys on ``plan_fingerprint``: its correctness story is
(a) *invariance* — alias-renamed and literal-varied plans share a structure
hash so a template compiled once serves the whole family, and (b)
*separation* — structurally different plans never collide, so a cache hit can
never return the wrong program. Both directions are checked here against the
same TPC-H fixture the gold-standard parity suite plans (all 22 query texts),
plus targeted unit cases for the slot-alignment machinery.
"""

import itertools

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.serving.fingerprint import (
    Fingerprint,
    Unparameterizable,
    bind_literals,
    canonical_form,
    plan_fingerprint,
    slot_mapping,
)
from test_tpch_queries import build_tpch_env
from tpch_queries import TPCH_QUERIES


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("fp_tpch"))
    sess, frames = build_tpch_env(root)
    yield sess
    hst.set_session(None)


@pytest.fixture(scope="module")
def simple(tmp_path_factory):
    root = tmp_path_factory.mktemp("fp_simple")
    n = 100
    pq.write_table(
        pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "name": np.array([f"n{i % 7}" for i in range(n)]),
                "price": (np.arange(n, dtype=np.int64) * 13) % 50,
            }
        ),
        str(root / "t.parquet"),
    )
    sess = hst.Session()
    sess.read_parquet(str(root / "t.parquet")).create_or_replace_temp_view("t")
    return sess


# --- invariance --------------------------------------------------------------


def test_literal_variation_shares_structure(simple):
    f5 = plan_fingerprint(simple.sql("SELECT name FROM t WHERE price > 5").plan)
    f9 = plan_fingerprint(simple.sql("SELECT name FROM t WHERE price > 9").plan)
    assert f5.structure == f9.structure
    assert f5.slot_sigs == f9.slot_sigs
    assert f5.literals == (5,) and f9.literals == (9,)
    # exact keys still separate them — verbatim repeats hit the exact tier
    assert f5.exact != f9.exact


def test_alias_renaming_shares_structure(simple):
    plain = plan_fingerprint(simple.sql("SELECT name FROM t WHERE price > 5").plan)
    alias = plan_fingerprint(simple.sql("SELECT name AS x FROM t WHERE price > 5").plan)
    alias2 = plan_fingerprint(simple.sql("SELECT name AS y FROM t WHERE price > 5").plan)
    assert plain.structure == alias.structure == alias2.structure
    assert plain.exact == alias.exact  # aliases don't even perturb the exact key
    # ...but the output labels (used to relabel results) track each request
    assert plain.output_columns == ("name",)
    assert alias.output_columns == ("x",)
    assert alias2.output_columns == ("y",)


def test_alias_and_literal_combined(simple):
    a = plan_fingerprint(simple.sql("SELECT name AS a, id FROM t WHERE price > 45").plan)
    b = plan_fingerprint(simple.sql("SELECT name AS b, id FROM t WHERE price > 40").plan)
    assert a.structure == b.structure
    assert a.literals != b.literals


def test_in_list_same_arity_shares_structure(simple):
    a = plan_fingerprint(simple.sql("SELECT id FROM t WHERE price IN (1, 2)").plan)
    b = plan_fingerprint(simple.sql("SELECT id FROM t WHERE price IN (3, 4)").plan)
    c = plan_fingerprint(simple.sql("SELECT id FROM t WHERE price IN (1, 2, 3)").plan)
    assert a.structure == b.structure
    assert a.literals == (1, 2) and b.literals == (3, 4)
    # arity is structural: a 3-element IN is a different program
    assert a.structure != c.structure


def test_fingerprint_deterministic(simple):
    q = "SELECT name FROM t WHERE price > 5 AND id < 90"
    f1 = plan_fingerprint(simple.sql(q).plan)
    f2 = plan_fingerprint(simple.sql(q).plan)
    assert f1 == f2
    assert canonical_form(simple.sql(q).plan) == canonical_form(simple.sql(q).plan)


# --- separation --------------------------------------------------------------


def test_distinct_shapes_do_not_collide(simple):
    queries = [
        "SELECT name FROM t WHERE price > 5",
        "SELECT id FROM t WHERE price > 5",
        "SELECT name FROM t WHERE price < 5",
        "SELECT name FROM t WHERE id > 5",
        "SELECT name FROM t WHERE price > 5 AND id > 5",
        "SELECT name FROM t",
        "SELECT name, price FROM t WHERE price > 5",
        "SELECT count(*) AS c FROM t WHERE price > 5",
        "SELECT name FROM t WHERE price > 5 ORDER BY name",
        "SELECT name FROM t WHERE price > 5 LIMIT 10",
        "SELECT name FROM t WHERE price IN (5)",
    ]
    fps = [plan_fingerprint(simple.sql(q).plan) for q in queries]
    for (qa, fa), (qb, fb) in itertools.combinations(zip(queries, fps), 2):
        assert fa.structure != fb.structure, f"collision: {qa!r} vs {qb!r}"


def test_tpch_corpus_no_collisions(env):
    """All 22 TPC-H texts must land on 22 distinct structure hashes — the
    whole benchmark family disagrees pairwise, so a plan-cache hit can never
    cross queries."""
    fps = {}
    for qname, text in TPCH_QUERIES.items():
        fps[qname] = plan_fingerprint(env.sql(text).plan)
    structures = [f.structure for f in fps.values()]
    assert len(set(structures)) == len(TPCH_QUERIES)
    # exact keys are at least as fine-grained as structures
    assert len({f.exact for f in fps.values()}) == len(TPCH_QUERIES)


def test_tpch_fingerprints_stable_across_replans(env):
    for qname, text in TPCH_QUERIES.items():
        f1 = plan_fingerprint(env.sql(text).plan)
        f2 = plan_fingerprint(env.sql(text).plan)
        assert f1.structure == f2.structure, qname
        assert f1.exact == f2.exact, qname


# --- slot alignment + binding ------------------------------------------------


def _fp(sigs, lits):
    return Fingerprint(
        structure="s",
        literals=tuple(lits),
        slot_sigs=tuple(sigs),
        output_columns=("c",),
        has_subquery=False,
    )


def test_slot_mapping_aligns_by_signature():
    template = _fp(["F/a", "F/b"], [1, 2])
    request = _fp(["F/b", "F/a"], [20, 10])  # reordered by the optimizer
    assert slot_mapping(template, request) == [1, 0]


def test_slot_mapping_rejects_ambiguity_and_gaps():
    with pytest.raises(Unparameterizable):
        slot_mapping(_fp(["F/a", "F/a"], [1, 2]), _fp(["F/a", "F/a"], [3, 4]))
    with pytest.raises(Unparameterizable):  # template slot absent from request
        slot_mapping(_fp(["F/a", "F/b"], [1, 2]), _fp(["F/a"], [3]))
    with pytest.raises(Unparameterizable):  # request literal the template dropped
        slot_mapping(_fp(["F/a"], [1]), _fp(["F/a", "F/b"], [3, 4]))


def test_bind_literals_round_trip(simple):
    p5 = simple.sql("SELECT name FROM t WHERE price > 5 AND id < 90").plan
    p9 = simple.sql("SELECT name FROM t WHERE price > 9 AND id < 70").plan
    f5, f9 = plan_fingerprint(p5), plan_fingerprint(p9)
    mapping = slot_mapping(f5, f9)
    bound = bind_literals(p5, [f9.literals[j] for j in mapping])
    assert plan_fingerprint(bound).exact == f9.exact
    # and the bound plan executes to the other query's answer
    from hyperspace_tpu.exec.executor import Executor

    got = Executor(simple).execute(bound, required_columns=["name"])
    want = simple.sql("SELECT name FROM t WHERE price > 9 AND id < 70").collect()
    assert np.array_equal(got["name"], want["name"])


def test_bind_literals_count_mismatch_raises(simple):
    p = simple.sql("SELECT name FROM t WHERE price > 5").plan
    with pytest.raises(Unparameterizable):
        bind_literals(p, [1, 2, 3])


def test_subquery_plans_are_exact_only(env):
    # q17-style scalar subquery: literals inside the inner plan are structural
    text = (
        "SELECT s_name FROM supplier WHERE s_acctbal > "
        "(SELECT avg(s_acctbal) FROM supplier WHERE s_suppkey < 20)"
    )
    text2 = (
        "SELECT s_name FROM supplier WHERE s_acctbal > "
        "(SELECT avg(s_acctbal) FROM supplier WHERE s_suppkey < 30)"
    )
    f1 = plan_fingerprint(env.sql(text).plan)
    f2 = plan_fingerprint(env.sql(text2).plan)
    assert f1.has_subquery and f2.has_subquery
    # differing inner literals => different structures (no unsound sharing)
    assert f1.structure != f2.structure
