"""XLA profiler hooks — the framework's runtime-profiling surface
(SURVEY.md §5.1: the reference delegates to the Spark UI; here traces come
from the XLA profiler)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import hyperspace_tpu as hst


def test_profile_context_captures_trace(session, tmp_path):
    d = tmp_path / "d"
    d.mkdir()
    pq.write_table(
        pa.table({"k": np.arange(500, dtype=np.int64), "v": np.arange(500.0)}),
        d / "p.parquet",
    )
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    hs = hst.Hyperspace(session)
    df = session.read_parquet(str(d))
    prof_dir = str(tmp_path / "prof")
    with session.profile(prof_dir):
        hs.create_index(df, hst.CoveringIndexConfig("profIdx", ["k"], ["v"]))
    files = [f for _, _, fs in os.walk(prof_dir) for f in fs]
    assert files, "profiler produced no trace files"
