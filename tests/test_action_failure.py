"""Failure-path safety of the action FSM (ref: actions/Action.scala:84-105;
recovery semantics SURVEY.md §5.3): a failure AFTER the final log entry is
committed must not delete the data version that entry references — readers
fall back to scanning the log for the latest stable entry."""

import os

import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.models.log_manager import IndexLogManager


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def test_late_failure_keeps_committed_data(session, hs, sample_parquet, monkeypatch):
    df = session.read_parquet(sample_parquet)

    real = IndexLogManager.create_latest_stable_log

    def boom(self, log_id):
        raise OSError("disk hiccup writing latestStable")

    monkeypatch.setattr(IndexLogManager, "create_latest_stable_log", boom)
    with pytest.raises(OSError):
        hs.create_index(df, hst.CoveringIndexConfig("lateFail", ["c1"], ["c2"]))
    monkeypatch.setattr(IndexLogManager, "create_latest_stable_log", real)

    # the ACTIVE entry at base+2 was committed before the failure: fallback
    # scan must find it, and every data file it references must still exist
    entry = hs._manager.get_index("lateFail")
    assert entry is not None and entry.state == "ACTIVE"
    for f in entry.content.files:
        assert os.path.exists(f), f"committed index file deleted: {f}"

    # and the index is actually usable
    session.enable_hyperspace()
    q = df.filter(hst.col("c1") == 7).select("c2")
    plan = q.optimized_plan()
    assert "IndexScan" in plan.pretty()
    session.disable_hyperspace()
    baseline = np.sort(q.collect()["c2"])
    session.enable_hyperspace()
    np.testing.assert_array_equal(np.sort(q.collect()["c2"]), baseline)


def test_early_failure_still_cleans_up(session, hs, sample_parquet, monkeypatch):
    """The pre-commit cleanup behavior is preserved: op() failure removes the
    allocated (never-referenced) data version."""
    from hyperspace_tpu.actions.create import CreateAction

    df = session.read_parquet(sample_parquet)

    real_op = CreateAction.op

    def failing_op(self):
        real_op(self)
        raise RuntimeError("op failed after writing data")

    monkeypatch.setattr(CreateAction, "op", failing_op)
    with pytest.raises(RuntimeError):
        hs.create_index(df, hst.CoveringIndexConfig("earlyFail", ["c1"], ["c2"]))
    monkeypatch.setattr(CreateAction, "op", real_op)

    assert hs._manager.get_index("earlyFail") is None
    # the allocated v__=0 dir was removed
    sysdir = session.conf.system_path
    idx_root = os.path.join(sysdir, "earlyFail")
    if os.path.isdir(idx_root):
        assert not any(d.startswith("v__=") for d in os.listdir(idx_root))
