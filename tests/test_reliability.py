"""Reliability subsystem: typed error taxonomy, seeded fault injection,
deadline-aware retries, index quarantine with fallback-to-source, and the
chaos soak (serving + refresh + injected faults).

Pinned properties:
- all machinery is off by default: at default conf every seam is one
  attribute read and results/plans are identical to a clean build;
- injected and classified failures are always *typed* (`ReliabilityError`),
  never raw third-party exceptions or silent wrong answers;
- the retry policy never sleeps past the serving deadline;
- repeated corrupt reads of an index's files quarantine the index and
  queries transparently re-plan against source; a clean half-open probe
  un-quarantines;
- a torn trailing operation-log entry degrades to the prior version
  instead of making the index vanish;
- the chaos soak holds the serving invariants (no torn/stale answers,
  only typed errors, no hung workers) under a seeded fault mix.
"""

import os
import random
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from conftest import index_scans  # noqa: E402
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.reliability import errors as rerr
from hyperspace_tpu.reliability.degrade import QUARANTINE
from hyperspace_tpu.reliability.faults import FAULTS, FaultRule, fault_scope, parse_spec
from hyperspace_tpu.reliability.retry import (
    RetryPolicy,
    current_deadline,
    deadline_scope,
    with_retry,
)

pytestmark = pytest.mark.faults


def counter_value(name, **labels):
    return REGISTRY.counter(name, **labels).value


@pytest.fixture(autouse=True)
def _reset_reliability_globals():
    """The registries are process-global (most-recent-session-wins); make
    sure no test leaks armed faults/retries/quarantine into the next."""
    yield
    from hyperspace_tpu.reliability import faults as fmod
    from hyperspace_tpu.reliability import retry as rmod

    fmod.FAULTS.clear()
    fmod._CONF_INSTALLED = False
    rmod._POLICY = None
    QUARANTINE.enabled = False
    QUARANTINE._breakers = {}


def _write_files(d, num_files=4, rows_per=300, seed=7):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        t = pa.table(
            {
                "c1": rng.integers(0, 100, rows_per).astype(np.int64),
                "c2": np.round(rng.uniform(0, 100, rows_per), 3),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def _mk_session(tmp_path, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
        hst.keys.NUM_BUCKETS: 4,
    }
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


def _sorted_rows(batch):
    cols = sorted(batch.keys())
    return sorted(zip(*[np.asarray(batch[c]).tolist() for c in cols]))


# --- taxonomy ----------------------------------------------------------------


class TestTaxonomy:
    def test_transient_is_oserror(self):
        # existing `except OSError` fallbacks must keep catching classified
        # transients — that is what makes the taxonomy a safe retrofit
        assert issubclass(rerr.TransientIOError, OSError)
        assert issubclass(rerr.InjectedTransientIOError, OSError)
        assert not issubclass(rerr.CorruptDataError, OSError)

    def test_classify_routing(self):
        corrupt = rerr.classify(pa.lib.ArrowInvalid("bad magic"), path="/x.parquet")
        assert isinstance(corrupt, rerr.CorruptDataError)
        assert corrupt.path == "/x.parquet"
        assert isinstance(corrupt.__cause__, pa.lib.ArrowInvalid)

        transient = rerr.classify(OSError("EIO"))
        assert isinstance(transient, rerr.TransientIOError)

        # already-typed errors pass through identically
        e = rerr.CorruptDataError("x", path="/p")
        assert rerr.classify(e) is e
        # production classifiers never mint injected errors
        assert not isinstance(transient, rerr.FaultInjected)

    def test_count_io_error_families(self):
        before = counter_value(
            "hs_io_errors_total", op="t.op", kind="corrupt", outcome="handled"
        )
        rerr.count_io_error("t.op", rerr.CorruptDataError("x"), swallowed=True)
        assert counter_value(
            "hs_io_errors_total", op="t.op", kind="corrupt", outcome="handled"
        ) == before + 1
        before = counter_value(
            "hs_io_errors_total", op="t.op", kind="transient", outcome="raised"
        )
        rerr.count_io_error("t.op", OSError("x"))
        assert counter_value(
            "hs_io_errors_total", op="t.op", kind="transient", outcome="raised"
        ) == before + 1


# --- fault harness -----------------------------------------------------------


class TestFaultHarness:
    def test_default_off_is_one_attr(self):
        assert FAULTS.active is False
        FAULTS.check("io.decode", "/any")  # no-op, no raise

    def test_parse_spec_full_syntax(self):
        rules = parse_spec(
            "io.decode:transient:p=0.25;"
            "log.read:truncate:glob=*_hyperspace_log*:nth=3:max=1;"
            "device.transfer:latency:delay=0.5"
        )
        assert [(r.site, r.kind) for r in rules] == [
            ("io.decode", "transient"),
            ("log.read", "truncate"),
            ("device.transfer", "latency"),
        ]
        assert rules[0].probability == 0.25
        assert rules[1].path_glob == "*_hyperspace_log*"
        assert rules[1].nth == 3 and rules[1].max_fires == 1
        assert rules[2].delay_s == 0.5

    def test_parse_spec_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_spec("io.decode")  # no kind
        with pytest.raises(ValueError):
            parse_spec("io.decode:frobnicate")  # unknown kind
        with pytest.raises(ValueError):
            parse_spec("io.decode:transient:bogus=1")  # unknown option

    def test_nth_glob_and_max_targeting(self):
        with fault_scope(
            FaultRule("io.decode", "transient", path_glob="*hit*", nth=2, max_fires=1)
        ):
            FAULTS.check("io.decode", "/miss/a")  # glob mismatch: not even counted
            FAULTS.check("io.decode", "/hit/1")  # op 1: no fire
            with pytest.raises(rerr.TransientIOError) as ei:
                FAULTS.check("io.decode", "/hit/2")  # op 2 = nth
            assert isinstance(ei.value, rerr.FaultInjected)
            FAULTS.check("io.decode", "/hit/3")  # max_fires exhausted
        assert FAULTS.active is False  # scope restored

    def test_seeded_probability_is_deterministic(self):
        def pattern(seed):
            fired = []
            with fault_scope(FaultRule("io.decode", "transient", probability=0.5), seed=seed):
                for i in range(32):
                    try:
                        FAULTS.check("io.decode", f"/f{i}")
                        fired.append(0)
                    except rerr.TransientIOError:
                        fired.append(1)
            return fired

        assert pattern(7) == pattern(7)
        assert any(pattern(7)) and not all(pattern(7))

    def test_mangle_bytes_kinds(self):
        data = b"PAR1" + b"x" * 96
        with fault_scope(FaultRule("log.read", "truncate")):
            out = FAULTS.mangle_bytes("log.read", "/log/5", data)
            assert len(out) < len(data)
        with fault_scope(FaultRule("log.read", "magic")):
            out = FAULTS.mangle_bytes("log.read", "/log/5", data)
            assert out[:4] == b"XXXX" and len(out) == len(data)

    def test_injection_counted(self):
        before = counter_value("hs_faults_injected_total", site="io.footer", kind="transient")
        with fault_scope(FaultRule("io.footer", "transient")):
            with pytest.raises(rerr.TransientIOError):
                FAULTS.check("io.footer", "/x")
        assert counter_value(
            "hs_faults_injected_total", site="io.footer", kind="transient"
        ) == before + 1


# --- retry policy ------------------------------------------------------------


def _fake_env():
    """Deterministic clock/sleep pair: sleeping advances the clock."""
    now = [100.0]
    slept = []

    def clock():
        return now[0]

    def sleep(s):
        slept.append(s)
        now[0] += s

    return clock, sleep, slept


class TestRetryPolicy:
    def test_succeeds_after_transients(self):
        clock, sleep, slept = _fake_env()
        p = RetryPolicy(4, 0.005, 0.1, clock=clock, sleep=sleep, rng=random.Random(3))
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise rerr.TransientIOError("blip")
            return 42

        before = counter_value("hs_io_retries_total", op="t.flaky", reason="oserror")
        assert p.call(flaky, op="t.flaky") == 42
        assert calls[0] == 3 and len(slept) == 2
        assert all(0.005 <= s <= 0.1 for s in slept)
        assert counter_value("hs_io_retries_total", op="t.flaky", reason="oserror") == before + 2

    def test_attempts_giveup_counts_and_raises(self):
        clock, sleep, _ = _fake_env()
        p = RetryPolicy(3, 0.005, 0.1, clock=clock, sleep=sleep, rng=random.Random(3))
        before = counter_value("hs_io_giveups_total", op="t.dead", reason="attempts")
        with pytest.raises(rerr.TransientIOError):
            p.call(lambda: (_ for _ in ()).throw(rerr.TransientIOError("x")), op="t.dead")
        assert counter_value("hs_io_giveups_total", op="t.dead", reason="attempts") == before + 1

    def test_never_sleeps_past_deadline(self):
        clock, sleep, slept = _fake_env()
        p = RetryPolicy(10, 0.050, 5.0, clock=clock, sleep=sleep, rng=random.Random(1))
        before = counter_value("hs_io_giveups_total", op="t.dl", reason="deadline")
        with deadline_scope(clock() + 0.010):  # under the minimum backoff
            with pytest.raises(rerr.TransientIOError):
                p.call(lambda: (_ for _ in ()).throw(rerr.TransientIOError("x")), op="t.dl")
        assert slept == []  # gave up instead of sleeping past the deadline
        assert counter_value("hs_io_giveups_total", op="t.dl", reason="deadline") == before + 1

    def test_corrupt_and_enoent_never_retry(self):
        clock, sleep, slept = _fake_env()
        p = RetryPolicy(5, 0.005, 0.1, clock=clock, sleep=sleep)
        calls = [0]

        def corrupt():
            calls[0] += 1
            raise rerr.CorruptDataError("torn", path="/p")

        with pytest.raises(rerr.CorruptDataError):
            p.call(corrupt, op="t.c")
        assert calls[0] == 1

        calls[0] = 0

        def missing():
            calls[0] += 1
            raise FileNotFoundError("/gone")

        with pytest.raises(FileNotFoundError):
            p.call(missing, op="t.m")
        assert calls[0] == 1 and slept == []

    def test_deadline_scope_nests_and_restores(self):
        assert current_deadline() is None
        with deadline_scope(10.0):
            assert current_deadline() == 10.0
            with deadline_scope(5.0):
                assert current_deadline() == 5.0
            assert current_deadline() == 10.0
        assert current_deadline() is None

    def test_with_retry_passthrough_when_disabled(self):
        from hyperspace_tpu.reliability import retry as rmod

        assert rmod.active_policy() is None
        calls = [0]

        def once():
            calls[0] += 1
            return "v"

        assert with_retry(once, op="t.off") == "v"
        assert calls[0] == 1


# --- default-off byte identity ----------------------------------------------


class TestDefaultOff:
    def test_defaults_leave_registries_dormant_and_results_identical(self, tmp_path):
        from hyperspace_tpu.reliability import retry as rmod

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("defIdx", ["c1"], ["c2"]))
        sess.enable_hyperspace()

        assert FAULTS.active is False
        assert rmod.active_policy() is None
        assert QUARANTINE.enabled is False

        injected0 = REGISTRY.counter("hs_faults_injected_total", site="x", kind="x").value
        q = sess.read_parquet(data).filter(hst.col("c1") < 50).select("c1", "c2")
        assert index_scans(q)  # quarantine filter at defaults filtered nothing
        on = q.collect()
        sess.disable_hyperspace()
        off = q.collect()
        assert _sorted_rows(on) == _sorted_rows(off)
        # dormant harness fired nothing anywhere in the query path
        assert REGISTRY.counter("hs_faults_injected_total", site="x", kind="x").value == injected0


# --- operation log: torn trailing entry (satellite regression) ---------------


class TestTornLog:
    def test_torn_trailing_entry_degrades_to_prior_version(self, tmp_path):
        from hyperspace_tpu.models.log_manager import IndexLogManager

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("tornIdx", ["c1"], ["c2"]))

        lm = IndexLogManager(os.path.join(str(tmp_path / "indexes"), "tornIdx"))
        latest = lm.get_latest_id()
        assert latest is not None
        good = lm.get_latest_log()
        assert good is not None

        # a torn write: the next entry exists but holds half a JSON document
        torn_id = latest + 1
        full = lm.get_log(latest)
        raw = full.to_json().encode("utf-8")
        with open(lm._path(torn_id), "wb") as f:
            f.write(raw[: len(raw) // 2])

        before = counter_value("hs_log_corrupt_total", index="tornIdx")
        # the id allocator still sees the torn id — two writers must never
        # both derive torn_id + 0 as "next"
        assert lm.get_latest_id() == torn_id
        # ... but readers walk past it to the newest parseable entry
        got = lm.get_latest_log()
        assert got is not None and got.id == good.id
        assert counter_value("hs_log_corrupt_total", index="tornIdx") == before + 1

        # a genuinely missing latest id keeps the old absent semantics
        os.unlink(lm._path(torn_id))
        assert lm.get_latest_log().id == good.id

    def test_log_read_faults_are_retried_when_enabled(self, tmp_path):
        from hyperspace_tpu.models.log_manager import IndexLogManager

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.RELIABILITY_RETRY_ENABLED: True,
                hst.keys.RELIABILITY_RETRY_BASE_MS: 0.1,
                hst.keys.RELIABILITY_RETRY_CAP_MS: 0.5,
            },
        )
        hs = hst.Hyperspace(sess)
        hs.create_index(
            sess.read_parquet(data), hst.CoveringIndexConfig("retryIdx", ["c1"], ["c2"])
        )
        lm = IndexLogManager(os.path.join(str(tmp_path / "indexes"), "retryIdx"))
        before = counter_value("hs_io_retries_total", op="log.read", reason="injected")
        with fault_scope(FaultRule("log.read", "transient", nth=1)):
            entry = lm.get_latest_log()  # first read fails, retry succeeds
        assert entry is not None
        assert counter_value("hs_io_retries_total", op="log.read", reason="injected") == before + 1


# --- typed errors through the scan pipeline (satellite regression) -----------


class TestPipelineTypedErrors:
    def test_decode_fault_surfaces_typed_cancels_queue_leaks_no_spans(self, tmp_path):
        from hyperspace_tpu.obs import spans

        data = _write_files(str(tmp_path / "data"), num_files=8, rows_per=2000)
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
                hst.keys.EXEC_IO_DECODE_THREADS: 1,  # serialize the decode pool
                hst.keys.OBS_TRACING_ENABLED: True,
            },
        )
        df = sess.read_parquet(data)
        q = df.filter(hst.col("c1") >= 0).select("c1", "c2")

        cancelled0 = counter_value("hs_pipeline_cancelled_total")
        raised0 = counter_value(
            "hs_io_errors_total", op="io.decode", kind="corrupt", outcome="raised"
        )
        with fault_scope(
            # chunk 0's decode is corrupt; chunk 1 stalls the 1-wide pool so
            # later queued prefetches are deterministically still cancellable
            FaultRule("io.decode", "corrupt", path_glob="*part-00000*"),
            FaultRule("io.decode", "latency", path_glob="*part-00001*", delay_s=0.3),
        ):
            with spans.trace("typed-error-stream") as root:
                it = q.to_local_iterator()
                with pytest.raises(rerr.CorruptDataError) as ei:
                    next(it)
                it.close()
                assert isinstance(ei.value, rerr.FaultInjected)
                open_spans = [s for s in root.walk() if s is not root and s.t1 is None]
                assert open_spans == []
            assert spans.current_span() is None
        assert counter_value("hs_pipeline_cancelled_total") > cancelled0
        assert counter_value(
            "hs_io_errors_total", op="io.decode", kind="corrupt", outcome="raised"
        ) > raised0

    def test_source_corruption_fails_query_typed_not_quarantined(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path, **{hst.keys.RELIABILITY_QUARANTINE_ENABLED: True}
        )
        victim = os.path.join(data, "part-00001.parquet")
        with open(victim, "wb") as f:
            f.write(b"XXXX this is not parquet")
        q = sess.read_parquet(data).filter(hst.col("c1") >= 0).select("c1")
        with pytest.raises(rerr.CorruptDataError) as ei:
            q.collect()
        # a real corruption, not an injected one, and no index to blame:
        # there is no fallback below the ground truth
        assert not isinstance(ei.value, rerr.FaultInjected)
        assert QUARANTINE.index_of_path(victim) is None


# --- quarantine circuit breaker ---------------------------------------------


class TestQuarantine:
    def _corrupt_index_files(self, index_dir):
        saved = {}
        for dirpath, _dirs, files in os.walk(index_dir):
            for fn in files:
                if fn.endswith(".parquet"):
                    p = os.path.join(dirpath, fn)
                    with open(p, "rb") as f:
                        saved[p] = f.read()
                    with open(p, "wb") as f:
                        f.write(b"XXXX torn to shreds")
        assert saved, "no index data files found to corrupt"
        return saved

    def test_trip_fallback_and_half_open_probe(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
                hst.keys.RELIABILITY_QUARANTINE_THRESHOLD: 2,
                hst.keys.RELIABILITY_QUARANTINE_COOLDOWN_SECONDS: 1.0,
            },
        )
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("qIdx", ["c1"], ["c2"]))
        sess.enable_hyperspace()

        def fresh_q():
            return sess.read_parquet(data).filter(hst.col("c1") < 50).select("c1", "c2")

        assert index_scans(fresh_q())
        sess.disable_hyperspace()
        want = _sorted_rows(fresh_q().collect())
        sess.enable_hyperspace()

        saved = self._corrupt_index_files(os.path.join(str(tmp_path / "indexes"), "qIdx"))
        trips0 = counter_value("hs_index_quarantined_total", index="qIdx")

        # corrupt decodes strike the breaker; every failure is typed
        for _ in range(6):
            if QUARANTINE.state_of("qIdx") == "open":
                break
            with pytest.raises(rerr.CorruptDataError):
                fresh_q().collect()
        assert QUARANTINE.state_of("qIdx") == "open"
        assert counter_value("hs_index_quarantined_total", index="qIdx") == trips0 + 1

        # quarantined: the planner re-plans against source — correct, slower
        q = fresh_q()
        assert index_scans(q) == []
        assert _sorted_rows(q.collect()) == want

        # heal the files, wait out the cooldown: the next query is the
        # half-open probe; its clean read closes the breaker
        for p, raw in saved.items():
            with open(p, "wb") as f:
                f.write(raw)
        time.sleep(1.1)
        assert _sorted_rows(fresh_q().collect()) == want
        assert QUARANTINE.state_of("qIdx") == "closed"
        assert index_scans(fresh_q())  # back in the plans

    def test_corrupt_probe_re_trips(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
                hst.keys.RELIABILITY_QUARANTINE_THRESHOLD: 1,
                hst.keys.RELIABILITY_QUARANTINE_COOLDOWN_SECONDS: 1.0,
            },
        )
        hs = hst.Hyperspace(sess)
        hs.create_index(
            sess.read_parquet(data), hst.CoveringIndexConfig("rtIdx", ["c1"], ["c2"])
        )
        sess.enable_hyperspace()
        self._corrupt_index_files(os.path.join(str(tmp_path / "indexes"), "rtIdx"))

        def fresh_q():
            return sess.read_parquet(data).filter(hst.col("c1") < 50).select("c1")

        with pytest.raises(rerr.CorruptDataError):
            fresh_q().collect()
        assert QUARANTINE.state_of("rtIdx") == "open"
        time.sleep(1.1)
        # files are still corrupt: the probe read re-trips immediately
        with pytest.raises(rerr.CorruptDataError):
            fresh_q().collect()
        assert QUARANTINE.state_of("rtIdx") == "open"
        # and while re-opened, queries fall back to source again
        got = fresh_q().collect()
        assert len(got["c1"]) > 0

    def test_trip_publishes_on_invalidation_bus(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path,
            **{
                hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
                hst.keys.RELIABILITY_QUARANTINE_THRESHOLD: 1,
            },
        )
        hs = hst.Hyperspace(sess)
        hs.create_index(
            sess.read_parquet(data), hst.CoveringIndexConfig("busIdx", ["c1"], ["c2"])
        )
        events = []
        sess.lifecycle_bus.subscribe(events.append)
        idx_file = None
        idx_root = os.path.join(str(tmp_path / "indexes"), "busIdx")
        for dirpath, _d, files in os.walk(idx_root):
            for fn in files:
                if fn.endswith(".parquet"):
                    idx_file = os.path.join(dirpath, fn)
        assert idx_file is not None
        assert QUARANTINE.note_corrupt(idx_file) == "busIdx"
        kinds = [(e.index_name, e.kind) for e in events]
        assert ("busIdx", "quarantine") in kinds
        ev = [e for e in events if e.kind == "quarantine"][0]
        assert idx_file in list(ev.affected_files)

    def test_why_not_reason(self):
        from hyperspace_tpu.analysis import reasons as R

        r = R.index_quarantined("qIdx")
        assert r.code == "INDEX_QUARANTINED"
        assert "quarantine" in r.verbose.lower()


# --- chaos soak --------------------------------------------------------------


def write_marked_part(root, marker, n=120):
    t = pa.table(
        {
            "c1": (np.arange(n, dtype=np.int64) * 13) % 100,
            "m": np.full(n, marker, dtype=np.int64),
        }
    )
    final = os.path.join(root, f"part-{marker:05d}.parquet")
    tmp = final + ".tmp"
    pq.write_table(t, tmp)
    os.replace(tmp, final)
    return final


def run_chaos_soak(tmp_path, *, rounds, workers=2, initial_files=3, n=120, seed=11):
    """Serving + background refresh + seeded fault mix. Returns violations
    (empty on a clean run) and summary counters. Invariants checked per
    result: no torn file visibility, no missing committed marker, and every
    failure is a typed, injected reliability error."""
    from hyperspace_tpu.lifecycle import RefreshManager
    from hyperspace_tpu.obs import spans
    from hyperspace_tpu.serving import QueryServer

    root = tmp_path / "chaos"
    root.mkdir()
    for i in range(initial_files):
        write_marked_part(str(root), i, n=n)

    sess = _mk_session(
        tmp_path,
        **{
            hst.keys.HYBRID_SCAN_ENABLED: True,
            hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO: 0.95,
            hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO: 0.95,
            hst.keys.RELIABILITY_RETRY_ENABLED: True,
            hst.keys.RELIABILITY_RETRY_BASE_MS: 1.0,
            hst.keys.RELIABILITY_RETRY_CAP_MS: 10.0,
        },
    )
    hs = hst.Hyperspace(sess)
    hs.create_index(
        sess.read_parquet(str(root)), hst.CoveringIndexConfig("chaosIdx", ["c1"], ["m"])
    )
    sess.enable_hyperspace()
    rm = RefreshManager(sess)

    state_lock = threading.Lock()
    committed = list(range(initial_files))
    violations = []
    stop = threading.Event()
    queries_done = [0]
    typed_errors = [0]

    def query_loop():
        while not stop.is_set():
            with state_lock:
                need = list(committed)
            try:
                q = sess.read_parquet(str(root)).filter(hst.col("c1") >= 0).select("m")
                res = server.submit(q).result(timeout=60)
            except rerr.ReliabilityError as exc:
                # an injected fault that out-lived the retry budget: typed,
                # attributable, and exactly what the harness caused
                if not isinstance(exc, rerr.FaultInjected):
                    violations.append(("untyped-origin", repr(exc)))
                typed_errors[0] += 1
                continue
            except Exception as exc:
                violations.append(("unclassified-error", repr(exc)))
                continue
            vals, cnts = np.unique(res["m"], return_counts=True)
            seen = dict(zip(vals.tolist(), cnts.tolist()))
            for mk, c in seen.items():
                if c != n:
                    violations.append(("torn", mk, c))
            for mk in need:
                if seen.get(mk) != n:
                    violations.append(("stale", mk, seen.get(mk)))
            queries_done[0] += 1

    with QueryServer(sess, workers=workers) as server:
        with fault_scope(
            FaultRule("io.decode", "transient", probability=0.08),
            FaultRule("io.footer", "transient", probability=0.05),
            FaultRule("log.read", "transient", probability=0.05),
            FaultRule("pipeline.task", "transient", probability=0.02),
            seed=seed,
        ) as registry:
            threads = [threading.Thread(target=query_loop) for _ in range(2)]
            for t in threads:
                t.start()
            try:
                for r in range(rounds):
                    marker = initial_files + r
                    write_marked_part(str(root), marker, n=n)
                    # refresh never raises: an injected fault inside the
                    # action FSM seals as outcome="error" and the prior
                    # ACTIVE entry keeps serving; the next round retries
                    outcome = rm.refresh_index("chaosIdx", "incremental")
                    if outcome == "committed":
                        with state_lock:
                            committed.append(marker)
                    time.sleep(0.05)
            finally:
                stop.set()
                for t in threads:
                    t.join(30)
            for t in threads:
                if t.is_alive():
                    violations.append(("hung-query-thread", t.name))
            fires = sum(r.fires for r in registry.rules())
    # outside the scope and the server: nothing left attached to this thread
    if spans.current_span() is not None:
        violations.append(("span-leak", repr(spans.current_span())))

    # clean-oracle comparison: faults off, hyperspace on vs off byte-compare
    q = sess.read_parquet(str(root)).filter(hst.col("c1") >= 0).select("m")
    on = q.collect()
    sess.disable_hyperspace()
    off = q.collect()
    if _sorted_rows(on) != _sorted_rows(off):
        violations.append(("oracle-mismatch", len(on["m"]), len(off["m"])))

    return {
        "violations": violations,
        "queries": queries_done[0],
        "typed_errors": typed_errors[0],
        "fault_fires": fires,
        "committed": list(committed),
    }


class TestChaosSoak:
    def test_chaos_fast(self, tmp_path):
        out = run_chaos_soak(tmp_path, rounds=4, seed=11)
        assert out["violations"] == [], out["violations"][:20]
        assert out["queries"] >= 4  # traffic really overlapped the fault mix
        assert out["fault_fires"] > 0  # the harness actually did something
        assert len(out["committed"]) >= 3


@pytest.mark.soak
@pytest.mark.slow
class TestChaosSoakLong:
    def test_chaos_long(self, tmp_path):
        out = run_chaos_soak(tmp_path, rounds=16, workers=4, seed=23)
        assert out["violations"] == [], out["violations"][:20]
        assert out["queries"] >= 16
        assert out["fault_fires"] > 10
