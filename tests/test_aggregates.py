"""Aggregation over (possibly index-rewritten) plans.

The reference delegates aggregation to Spark SQL around its indexed scans;
here the dataframe facade provides group_by/agg directly, and index rewrites
apply beneath the Aggregate node untouched (ScoreBasedIndexPlanOptimizer
recurses through it — rules/score.py).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def data(tmp_path):
    d = tmp_path / "agg"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(2):
        pq.write_table(
            pa.table(
                {
                    "dept": rng.integers(0, 8, 1500).astype(np.int64),
                    "region": np.array([f"r{v}" for v in rng.integers(0, 3, 1500)]),
                    "amount": np.round(rng.uniform(0, 100, 1500), 4),
                    "qty": rng.integers(1, 10, 1500).astype(np.int64),
                }
            ),
            d / f"p{i}.parquet",
        )
    return str(d)


def as_pandas(batch):
    return pd.DataFrame({k: v for k, v in batch.items()})


class TestAggregates:
    def test_global_aggregates(self, session, data):
        df = session.read_parquet(data)
        out = df.agg(total=("amount", "sum"), n=("*", "count"), hi=("amount", "max"))
        got = out.collect()
        ref = df.to_pandas()
        assert got["n"][0] == len(ref)
        assert np.isclose(got["total"][0], ref["amount"].sum())
        assert np.isclose(got["hi"][0], ref["amount"].max())

    def test_group_by_aggregates_match_pandas(self, session, data):
        df = session.read_parquet(data)
        out = df.group_by("dept").agg(
            total=("amount", "sum"), n=("*", "count"), avg_q=("qty", "avg")
        ).collect()
        ref = (
            df.to_pandas()
            .groupby("dept")
            .agg(total=("amount", "sum"), n=("amount", "size"), avg_q=("qty", "mean"))
            .reset_index()
            .sort_values("dept")
        )
        got = as_pandas(out).sort_values("dept").reset_index(drop=True)
        assert np.array_equal(got["dept"].to_numpy(), ref["dept"].to_numpy())
        assert np.allclose(got["total"].to_numpy(), ref["total"].to_numpy())
        assert np.array_equal(got["n"].to_numpy(), ref["n"].to_numpy())
        assert np.allclose(got["avg_q"].to_numpy(), ref["avg_q"].to_numpy())

    def test_multi_key_and_string_key_grouping(self, session, data):
        df = session.read_parquet(data)
        out = as_pandas(df.group_by("dept", "region").count().collect())
        ref = df.to_pandas().groupby(["dept", "region"]).size().reset_index(name="count")
        merged = out.merge(ref, on=["dept", "region"], suffixes=("_got", "_ref"))
        assert len(merged) == len(ref) == len(out)
        assert np.array_equal(merged["count_got"].to_numpy(), merged["count_ref"].to_numpy())

    def test_shorthand_methods(self, session, data):
        df = session.read_parquet(data)
        got = df.group_by("dept").sum("qty").collect()
        ref = df.to_pandas().groupby("dept")["qty"].sum()
        for d, v in zip(got["dept"], got["sum(qty)"]):
            assert v == ref[d]

    def test_index_rewrite_fires_below_aggregate(self, session, hs, data):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("aggIdx", ["dept"], ["amount"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("dept") == 3).group_by("dept").agg(total=("amount", "sum"))
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.allclose(np.sort(on["total"]), np.sort(off["total"]))

    def test_aggregate_over_indexed_join(self, session, hs, data, tmp_path):
        rroot = tmp_path / "r"
        rroot.mkdir()
        pq.write_table(
            pa.table(
                {
                    "dept": np.arange(8, dtype=np.int64),
                    "budget": np.round(np.linspace(100, 800, 8), 2),
                }
            ),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(data)
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("aggJL", ["dept"], ["amount"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("aggJR", ["dept"], ["budget"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=["dept"]).group_by("dept").agg(
            spend=("amount", "sum"), budget=("budget", "max")
        )
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        on = as_pandas(q.collect()).sort_values("dept").reset_index(drop=True)
        session.disable_hyperspace()
        off = as_pandas(q.collect()).sort_values("dept").reset_index(drop=True)
        session.enable_hyperspace()
        assert np.allclose(on["spend"], off["spend"])
        assert np.allclose(on["budget"], off["budget"])

    def test_int64_min_join_sum_no_silent_overflow(self, session, hs, tmp_path):
        """A join-aggregate input containing int64.min must not slip past the
        fused path's overflow guard (np.abs(int64.min) wraps negative): the
        plan falls back to the exact path and the sums stay correct."""
        from hyperspace_tpu.exec.device import _int_magnitude

        lo = np.iinfo(np.int64).min
        assert _int_magnitude(np.array([lo, 5], dtype=np.int64)) == 2 ** 63
        # the old formula was negative, bypassing the guard entirely
        assert int(np.abs(np.array([lo], dtype=np.int64)).max()) < 0

        lroot, rroot = tmp_path / "l", tmp_path / "r"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(
            pa.table(
                {
                    "dept": np.array([0, 0, 1, 1], dtype=np.int64),
                    "amount": np.array([lo, 3, 7, 11], dtype=np.int64),
                }
            ),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "dept": np.array([0, 1], dtype=np.int64),
                    "budget": np.array([10, 20], dtype=np.int64),
                }
            ),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        ldf = session.read_parquet(str(lroot))
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("ovL", ["dept"], ["amount"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("ovR", ["dept"], ["budget"]))
        session.enable_hyperspace()
        got = as_pandas(
            ldf.join(rdf, on=["dept"]).group_by("dept").agg(s=("amount", "sum")).collect()
        ).sort_values("dept")
        assert got["s"].tolist() == [lo + 3, 18]

    def test_order_by_and_limit(self, session, data):
        df = session.read_parquet(data)
        out = as_pandas(
            df.group_by("dept")
            .agg(total=("amount", "sum"))
            .order_by("total", ascending=False)
            .limit(3)
            .collect()
        )
        ref = (
            df.to_pandas()
            .groupby("dept")["amount"]
            .sum()
            .sort_values(ascending=False)
            .head(3)
        )
        assert len(out) == 3
        assert np.allclose(out["total"].to_numpy(), ref.to_numpy())
        assert np.array_equal(out["dept"].to_numpy(), ref.index.to_numpy())

    def test_order_by_multi_key_mixed_direction_stable(self, session, tmp_path):
        d = tmp_path / "sortd"
        d.mkdir()
        pq.write_table(
            pa.table(
                {
                    "a": np.array([2, 1, 2, 1, 2, 1], dtype=np.int64),
                    "b": np.array(["x", "y", "x", "y", "z", "x"]),
                    "i": np.arange(6, dtype=np.int64),
                }
            ),
            d / "p.parquet",
        )
        df = session.read_parquet(str(d))
        out = as_pandas(df.order_by("a", "b", ascending=[True, False]).collect())
        ref = (
            df.to_pandas()
            .sort_values(["a", "b"], ascending=[True, False], kind="stable")
            .reset_index(drop=True)
        )
        assert np.array_equal(out["a"].to_numpy(), ref["a"].to_numpy())
        assert np.array_equal(out["b"].to_numpy().astype(str), ref["b"].to_numpy().astype(str))
        assert np.array_equal(out["i"].to_numpy(), ref["i"].to_numpy())  # stability

    def test_order_by_nan_last_both_directions(self, session, tmp_path):
        d = tmp_path / "nansort"
        d.mkdir()
        pq.write_table(
            pa.table({"x": np.array([1.0, np.nan, 3.0, np.nan, 2.0]), "i": np.arange(5, dtype=np.int64)}),
            d / "p.parquet",
        )
        df = session.read_parquet(str(d))
        asc = df.order_by("x").collect()["x"]
        desc = df.order_by("x", ascending=False).collect()["x"]
        assert np.array_equal(asc[:3], [1.0, 2.0, 3.0]) and np.isnan(asc[3:]).all()
        assert np.array_equal(desc[:3], [3.0, 2.0, 1.0]) and np.isnan(desc[3:]).all()

    def test_index_rewrite_survives_order_by_limit(self, session, hs, data):
        """order_by/limit at the plan root must not block column pruning and
        with it the covering-index rewrite underneath."""
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("sortIdx", ["dept"], ["amount"]))
        session.enable_hyperspace()
        q = (
            df.filter(hst.col("dept") == 3)
            .group_by("dept")
            .agg(total=("amount", "sum"))
            .order_by("total")
            .limit(1)
        )
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.allclose(on["total"], off["total"])

    def test_invalid_fn_rejected(self, session, data):
        df = session.read_parquet(data)
        with pytest.raises(ValueError, match="Unsupported aggregate"):
            df.group_by("dept").agg(x=("amount", "median"))
        with pytest.raises(ValueError, match="only \\('\\*', 'count'\\)"):
            df.agg(total=("*", "sum"))
        with pytest.raises(ValueError, match="Duplicate aggregate output"):
            df.group_by("dept").agg(dept=("amount", "sum"))

    def test_device_fused_filter_aggregate(self, session, hs, data):
        """Global aggregates over a filtered index scan run as one fused
        device program (only scalars come back); results match the host
        path bit-for-bit on counts/int sums and to fp tolerance otherwise."""
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("devAgg", ["dept"], ["amount", "qty"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("dept") == 3).agg(
            n=("*", "count"),
            total=("amount", "sum"),
            qsum=("qty", "sum"),
            lo=("amount", "min"),
            hi=("amount", "max"),
            mean=("amount", "avg"),
        )
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        dev = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        host = q.collect()
        assert dev["n"][0] == host["n"][0]
        assert dev["qsum"][0] == host["qsum"][0]  # int sum exact
        for k in ("total", "lo", "hi", "mean"):
            assert np.isclose(dev[k][0], host[k][0]), k

    def test_device_aggregate_with_nulls(self, session, hs, tmp_path):
        d = tmp_path / "nullagg"
        d.mkdir()
        vals = np.array([1.0, np.nan, 3.0, np.nan, 5.0] * 40)
        pq.write_table(
            pa.table({"g": np.tile(np.arange(4, dtype=np.int64), 50), "x": vals}),
            d / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("nullAgg", ["g"], ["x"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("g") == 1).agg(
            nx=("x", "count"), total=("x", "sum"), mean=("x", "avg")
        )
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        dev = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        host = q.collect()
        assert dev["nx"][0] == host["nx"][0]  # NaNs skipped in count(col)
        assert np.isclose(dev["total"][0], host["total"][0])
        assert np.isclose(dev["mean"][0], host["mean"][0])

    def test_device_aggregate_empty_match(self, session, hs, data):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("emptyAgg", ["dept"], ["amount"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("dept") == 999).agg(n=("*", "count"), lo=("amount", "min"))
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        dev = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        host = q.collect()
        assert dev["n"][0] == host["n"][0] == 0
        assert np.isnan(dev["lo"][0]) and np.isnan(host["lo"][0])

    def test_device_aggregate_all_nan_match(self, session, hs, tmp_path):
        """Filter matches rows whose aggregate column is entirely NaN: the
        device path must yield NaN for min/max/avg (pandas semantics), not
        inf/-inf/0."""
        d = tmp_path / "allnan"
        d.mkdir()
        pq.write_table(
            pa.table(
                {
                    "g": np.array([1] * 10 + [2] * 10, dtype=np.int64),
                    "x": np.array([np.nan] * 10 + [5.0] * 10),
                }
            ),
            d / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("allNanAgg", ["g"], ["x"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("g") == 1).agg(
            lo=("x", "min"), hi=("x", "max"), mean=("x", "avg"), total=("x", "sum")
        )
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        dev = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        host = q.collect()
        for k in ("lo", "hi", "mean"):
            assert np.isnan(dev[k][0]) and np.isnan(host[k][0]), k
        # SQL: SUM over zero non-null values is NULL (not pandas' 0)
        assert np.isnan(dev["total"][0]) and np.isnan(host["total"][0])

    def test_device_declines_bare_count_star(self, session, hs, data):
        """count(*) with no predicate has no device-resident columns — the
        device path declines (a zero-column program would report 0 rows) and
        the host answers from the already-read batch."""
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.plan import logical as L

        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        batch = {"dept": np.arange(10, dtype=np.int64)}
        with pytest.raises(D.DeviceUnsupported):
            D.device_filtered_aggregate(session, batch, None, [("n", "count", None)])
        # end to end: correct count either way
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        n_dev = df.agg(n=("*", "count")).collect()["n"][0]
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        n_host = df.agg(n=("*", "count")).collect()["n"][0]
        assert n_dev == n_host == 3000

    def test_group_by_nested_key(self, session, tmp_path):
        d = tmp_path / "nestedagg"
        d.mkdir()
        t = pa.table(
            {
                "nested": pa.array([{"city": f"c{i % 3}"} for i in range(60)]),
                "v": np.arange(60, dtype=np.int64),
            }
        )
        pq.write_table(t, d / "p.parquet")
        df = session.read_parquet(str(d))
        out = as_pandas(df.group_by("nested.city").sum("v").collect())
        assert len(out) == 3
        assert out["sum(v)"].sum() == np.arange(60).sum()
