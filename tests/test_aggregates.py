"""Aggregation over (possibly index-rewritten) plans.

The reference delegates aggregation to Spark SQL around its indexed scans;
here the dataframe facade provides group_by/agg directly, and index rewrites
apply beneath the Aggregate node untouched (ScoreBasedIndexPlanOptimizer
recurses through it — rules/score.py).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def data(tmp_path):
    d = tmp_path / "agg"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(2):
        pq.write_table(
            pa.table(
                {
                    "dept": rng.integers(0, 8, 1500).astype(np.int64),
                    "region": np.array([f"r{v}" for v in rng.integers(0, 3, 1500)]),
                    "amount": np.round(rng.uniform(0, 100, 1500), 4),
                    "qty": rng.integers(1, 10, 1500).astype(np.int64),
                }
            ),
            d / f"p{i}.parquet",
        )
    return str(d)


def as_pandas(batch):
    return pd.DataFrame({k: v for k, v in batch.items()})


class TestAggregates:
    def test_global_aggregates(self, session, data):
        df = session.read_parquet(data)
        out = df.agg(total=("amount", "sum"), n=("*", "count"), hi=("amount", "max"))
        got = out.collect()
        ref = df.to_pandas()
        assert got["n"][0] == len(ref)
        assert np.isclose(got["total"][0], ref["amount"].sum())
        assert np.isclose(got["hi"][0], ref["amount"].max())

    def test_group_by_aggregates_match_pandas(self, session, data):
        df = session.read_parquet(data)
        out = df.group_by("dept").agg(
            total=("amount", "sum"), n=("*", "count"), avg_q=("qty", "avg")
        ).collect()
        ref = (
            df.to_pandas()
            .groupby("dept")
            .agg(total=("amount", "sum"), n=("amount", "size"), avg_q=("qty", "mean"))
            .reset_index()
            .sort_values("dept")
        )
        got = as_pandas(out).sort_values("dept").reset_index(drop=True)
        assert np.array_equal(got["dept"].to_numpy(), ref["dept"].to_numpy())
        assert np.allclose(got["total"].to_numpy(), ref["total"].to_numpy())
        assert np.array_equal(got["n"].to_numpy(), ref["n"].to_numpy())
        assert np.allclose(got["avg_q"].to_numpy(), ref["avg_q"].to_numpy())

    def test_multi_key_and_string_key_grouping(self, session, data):
        df = session.read_parquet(data)
        out = as_pandas(df.group_by("dept", "region").count().collect())
        ref = df.to_pandas().groupby(["dept", "region"]).size().reset_index(name="count")
        merged = out.merge(ref, on=["dept", "region"], suffixes=("_got", "_ref"))
        assert len(merged) == len(ref) == len(out)
        assert np.array_equal(merged["count_got"].to_numpy(), merged["count_ref"].to_numpy())

    def test_shorthand_methods(self, session, data):
        df = session.read_parquet(data)
        got = df.group_by("dept").sum("qty").collect()
        ref = df.to_pandas().groupby("dept")["qty"].sum()
        for d, v in zip(got["dept"], got["sum(qty)"]):
            assert v == ref[d]

    def test_index_rewrite_fires_below_aggregate(self, session, hs, data):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(data)
        hs.create_index(df, hst.CoveringIndexConfig("aggIdx", ["dept"], ["amount"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("dept") == 3).group_by("dept").agg(total=("amount", "sum"))
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.allclose(np.sort(on["total"]), np.sort(off["total"]))

    def test_aggregate_over_indexed_join(self, session, hs, data, tmp_path):
        rroot = tmp_path / "r"
        rroot.mkdir()
        pq.write_table(
            pa.table(
                {
                    "dept": np.arange(8, dtype=np.int64),
                    "budget": np.round(np.linspace(100, 800, 8), 2),
                }
            ),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(data)
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("aggJL", ["dept"], ["amount"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("aggJR", ["dept"], ["budget"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=["dept"]).group_by("dept").agg(
            spend=("amount", "sum"), budget=("budget", "max")
        )
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        on = as_pandas(q.collect()).sort_values("dept").reset_index(drop=True)
        session.disable_hyperspace()
        off = as_pandas(q.collect()).sort_values("dept").reset_index(drop=True)
        session.enable_hyperspace()
        assert np.allclose(on["spend"], off["spend"])
        assert np.allclose(on["budget"], off["budget"])

    def test_invalid_fn_rejected(self, session, data):
        df = session.read_parquet(data)
        with pytest.raises(ValueError, match="Unsupported aggregate"):
            df.group_by("dept").agg(x=("amount", "median"))
        with pytest.raises(ValueError, match="only \\('\\*', 'count'\\)"):
            df.agg(total=("*", "sum"))
        with pytest.raises(ValueError, match="Duplicate aggregate output"):
            df.group_by("dept").agg(dept=("amount", "sum"))

    def test_group_by_nested_key(self, session, tmp_path):
        d = tmp_path / "nestedagg"
        d.mkdir()
        t = pa.table(
            {
                "nested": pa.array([{"city": f"c{i % 3}"} for i in range(60)]),
                "v": np.arange(60, dtype=np.int64),
            }
        )
        pq.write_table(t, d / "p.parquet")
        df = session.read_parquet(str(d))
        out = as_pandas(df.group_by("nested.city").sum("v").collect())
        assert len(out) == 3
        assert out["sum(v)"].sum() == np.arange(60).sum()
