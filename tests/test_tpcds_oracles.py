"""Absolute-correctness oracles for TPC-DS queries: pandas reimplementations
checked against the engine, so a SQL-engine bug shared by the hyperspace-on
AND hyperspace-off paths (the decorrelation count-bug class) is caught — the
parity suite alone cannot see it (ref: the reference's checkAnswer culture,
E2EHyperspaceRulesTest.scala:75-1016 verifies results, not just parity).

Each oracle mirrors its query text (LIMIT stripped on both sides so ORDER BY
ties cannot flake); the decorrelated queries round 3 touched (q1, q6, q30,
q32, q41, q81, q92) and the null-aware EXISTS pair (q16, q94) are all here.
"""

import math
import os
import re

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst

QUERIES_DIR = "/root/reference/src/test/resources/tpcds/queries"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(QUERIES_DIR), reason="reference TPC-DS query texts not available"
)


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    from tpcds_data import arrow_tables

    root = str(tmp_path_factory.mktemp("tpcds_oracle"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    frames = {}
    for name, table in arrow_tables().items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(table, os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
        frames[name] = table.to_pandas()
    # a couple of indexes so the oracle checks also cover rewritten plans
    hs.create_index(
        sess._temp_views["store_sales"],
        hst.CoveringIndexConfig(
            "o_ss_item", ["ss_item_sk"],
            ["ss_sold_date_sk", "ss_ext_sales_price", "ss_quantity", "ss_sales_price"],
        ),
    )
    hs.create_index(
        sess._temp_views["date_dim"],
        hst.CoveringIndexConfig("o_d_sk", ["d_date_sk"], ["d_year", "d_moy", "d_qoy"]),
    )
    sess.enable_hyperspace()
    yield sess, frames
    hst.set_session(None)


def _query_text(qname):
    with open(os.path.join(QUERIES_DIR, f"{qname}.sql")) as f:
        text = f.read()
    # strip LIMIT so ORDER BY ties cannot make the comparison flaky; oracles
    # compute the full set
    return re.sub(r"\bLIMIT\s+\d+\s*$", "", text.strip(), flags=re.I)


def _norm(v):
    if v is None or (isinstance(v, float) and v != v) or v is pd.NaT:
        return "\x00NULL"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


def _rows_of_batch(batch):
    cols = sorted(batch.keys())
    return [tuple(r) for r in zip(*[batch[c].tolist() for c in cols])], cols


def _rows_of_frame(df, ecols_sorted):
    """Align oracle columns to the engine's (sorted) output names,
    case-insensitively — oracle frames use the query's alias names."""
    lower = {c.lower(): c for c in df.columns}
    missing = [c for c in ecols_sorted if c.lower() not in lower]
    assert not missing, f"oracle lacks columns {missing}; has {list(df.columns)}"
    ordered = [lower[c.lower()] for c in ecols_sorted]
    return [tuple(r) for r in zip(*[df[c].tolist() for c in ordered])]


def check(sess, qname, oracle_df):
    got = sess.sql(_query_text(qname)).collect()
    erows, ecols = _rows_of_batch(got)
    assert len(oracle_df.columns) == len(ecols), (qname, list(oracle_df.columns), ecols)
    orows = _rows_of_frame(oracle_df, ecols)
    assert len(erows) == len(orows), f"{qname}: engine {len(erows)} rows vs oracle {len(orows)}"
    ekey = sorted(erows, key=lambda r: tuple(_norm(v) for v in r))
    okey = sorted(orows, key=lambda r: tuple(_norm(v) for v in r))
    for a, b in zip(ekey, okey):
        for x, y in zip(a, b):
            fx = isinstance(x, float) or isinstance(x, np.floating)
            fy = isinstance(y, float) or isinstance(y, np.floating)
            if fx and fy:
                if x != x and y != y:
                    continue
                assert math.isclose(float(x), float(y), rel_tol=1e-6, abs_tol=1e-6), (
                    f"{qname}: {x!r} != {y!r}"
                )
            else:
                assert _norm(x) == _norm(y), f"{qname}: {x!r} != {y!r} (rows {a} vs {b})"
    return len(erows)


def _nonempty(n, qname):
    assert n > 0, f"{qname}: oracle comparison is vacuous (0 rows)"


# --- group A: star-join aggregates -----------------------------------------


def test_q3(env):
    sess, t = env
    ss, d, i = t["store_sales"], t["date_dim"], t["item"]
    m = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        i, left_on="ss_item_sk", right_on="i_item_sk"
    )
    m = m[(m.i_manufact_id == 128) & (m.d_moy == 11)]
    g = (
        m.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)["ss_ext_sales_price"]
        .sum()
        .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand", "ss_ext_sales_price": "sum_agg"})
    )
    _nonempty(check(sess, "q3", g[["d_year", "brand_id", "brand", "sum_agg"]]), "q3")


def _q42_like(t, manager, moy, year, keys, outnames):
    ss, d, i = t["store_sales"], t["date_dim"], t["item"]
    m = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        i, left_on="ss_item_sk", right_on="i_item_sk"
    )
    m = m[(m.i_manager_id == manager) & (m.d_moy == moy) & (m.d_year == year)]
    g = m.groupby(keys, as_index=False)["ss_ext_sales_price"].sum()
    g.columns = outnames
    return g


def test_q42(env):
    sess, t = env
    g = _q42_like(t, 1, 11, 2000, ["d_year", "i_category_id", "i_category"],
                  ["d_year", "i_category_id", "i_category", "sum(ss_ext_sales_price)"])
    _nonempty(check(sess, "q42", g), "q42")


def test_q52(env):
    sess, t = env
    g = _q42_like(t, 1, 11, 2000, ["d_year", "i_brand", "i_brand_id"],
                  ["d_year", "brand", "brand_id", "ext_price"])
    _nonempty(check(sess, "q52", g[["d_year", "brand_id", "brand", "ext_price"]]), "q52")


def test_q55(env):
    sess, t = env
    g = _q42_like(t, 28, 11, 1999, ["i_brand", "i_brand_id"],
                  ["brand", "brand_id", "ext_price"])
    _nonempty(check(sess, "q55", g[["brand_id", "brand", "ext_price"]]), "q55")


def test_q96(env):
    sess, t = env
    ss, hd, td, s = t["store_sales"], t["household_demographics"], t["time_dim"], t["store"]
    m = (
        ss.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    m = m[(m.t_hour == 20) & (m.t_minute >= 30) & (m.hd_dep_count == 7) & (m.s_store_name == "ese")]
    _nonempty(check(sess, "q96", pd.DataFrame({"count": [len(m)]})), "q96")


def test_q15(env):
    sess, t = env
    cs, c, ca, d = t["catalog_sales"], t["customer"], t["customer_address"], t["date_dim"]
    m = (
        cs.merge(c, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        .merge(d, left_on="cs_sold_date_sk", right_on="d_date_sk")
    )
    zips = {"85669", "86197", "88274", "83405", "86475", "85392", "85460", "80348", "81792"}
    cond = (
        m.ca_zip.astype(str).str[:5].isin(zips)
        | m.ca_state.isin(["CA", "WA", "GA"])
        | (m.cs_sales_price > 500)
    )
    m = m[cond & (m.d_qoy == 2) & (m.d_year == 2001)]
    g = m.groupby("ca_zip", as_index=False)["cs_sales_price"].sum()
    g.columns = ["ca_zip", "sum(cs_sales_price)"]
    _nonempty(check(sess, "q15", g), "q15")


def test_q37(env):
    sess, t = env
    i, inv, d, cs = t["item"], t["inventory"], t["date_dim"], t["catalog_sales"]
    m = i.merge(inv, left_on="i_item_sk", right_on="inv_item_sk").merge(
        d, left_on="inv_date_sk", right_on="d_date_sk"
    )
    lo = np.datetime64("2000-02-01")
    m = m[
        (m.i_current_price >= 68) & (m.i_current_price <= 98)
        & m.i_manufact_id.isin([677, 940, 694, 808])
        & (m.inv_quantity_on_hand >= 100) & (m.inv_quantity_on_hand <= 500)
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(60, "D"))
    ]
    m = m[m.i_item_sk.isin(cs.cs_item_sk)]
    g = m[["i_item_id", "i_item_desc", "i_current_price"]].drop_duplicates()
    _nonempty(check(sess, "q37", g), "q37")


def test_q82(env):
    sess, t = env
    i, inv, d, ss = t["item"], t["inventory"], t["date_dim"], t["store_sales"]
    m = i.merge(inv, left_on="i_item_sk", right_on="inv_item_sk").merge(
        d, left_on="inv_date_sk", right_on="d_date_sk"
    )
    lo = np.datetime64("2000-05-25")
    m = m[
        (m.i_current_price >= 62) & (m.i_current_price <= 92)
        & m.i_manufact_id.isin([129, 270, 821, 423])
        & (m.inv_quantity_on_hand >= 100) & (m.inv_quantity_on_hand <= 500)
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(60, "D"))
    ]
    m = m[m.i_item_sk.isin(ss.ss_item_sk)]
    g = m[["i_item_id", "i_item_desc", "i_current_price"]].drop_duplicates()
    _nonempty(check(sess, "q82", g), "q82")


def _q12_like(t, fact, datecol, pricecol, itemcol):
    f, i, d = t[fact], t["item"], t["date_dim"]
    m = f.merge(i, left_on=itemcol, right_on="i_item_sk").merge(
        d, left_on=datecol, right_on="d_date_sk"
    )
    lo = np.datetime64("1999-02-22")
    m = m[
        m.i_category.isin(["Sports", "Books", "Home"])
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(30, "D"))
    ]
    g = m.groupby(
        ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
        as_index=False,
    )[pricecol].sum()
    g = g.rename(columns={pricecol: "itemrevenue"})
    class_tot = g.groupby("i_class")["itemrevenue"].transform("sum")
    g["revenueratio"] = g["itemrevenue"] * 100.0 / class_tot
    # SELECT omits i_item_id though GROUP BY includes it — keep duplicates
    return g.drop(columns=["i_item_id"])


def test_q12(env):
    sess, t = env
    g = _q12_like(t, "web_sales", "ws_sold_date_sk", "ws_ext_sales_price", "ws_item_sk")
    _nonempty(check(sess, "q12", g), "q12")


def test_q20(env):
    sess, t = env
    g = _q12_like(t, "catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price", "cs_item_sk")
    _nonempty(check(sess, "q20", g), "q20")


def test_q98(env):
    sess, t = env
    g = _q12_like(t, "store_sales", "ss_sold_date_sk", "ss_ext_sales_price", "ss_item_sk")
    _nonempty(check(sess, "q98", g), "q98")


def _q7_like(t, fact, cdemo, datecol, itemcol, promocol, qty, list_, coupon, sales):
    f, cd, d, i, p = t[fact], t["customer_demographics"], t["date_dim"], t["item"], t["promotion"]
    m = (
        f.merge(cd, left_on=cdemo, right_on="cd_demo_sk")
        .merge(d, left_on=datecol, right_on="d_date_sk")
        .merge(i, left_on=itemcol, right_on="i_item_sk")
        .merge(p, left_on=promocol, right_on="p_promo_sk")
    )
    m = m[
        (m.cd_gender == "M") & (m.cd_marital_status == "S")
        & (m.cd_education_status == "College")
        & ((m.p_channel_email == "N") | (m.p_channel_event == "N"))
        & (m.d_year == 2000)
    ]
    g = m.groupby("i_item_id", as_index=False).agg(
        agg1=(qty, "mean"), agg2=(list_, "mean"), agg3=(coupon, "mean"), agg4=(sales, "mean")
    )
    return g


def test_q7(env):
    sess, t = env
    g = _q7_like(t, "store_sales", "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk",
                 "ss_promo_sk", "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    _nonempty(check(sess, "q7", g), "q7")


def test_q26(env):
    sess, t = env
    g = _q7_like(t, "catalog_sales", "cs_bill_cdemo_sk", "cs_sold_date_sk", "cs_item_sk",
                 "cs_promo_sk", "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price")
    _nonempty(check(sess, "q26", g), "q26")


def test_q19(env):
    sess, t = env
    d, ss, i, c, ca, s = (t["date_dim"], t["store_sales"], t["item"], t["customer"],
                          t["customer_address"], t["store"])
    m = (
        d.merge(ss, left_on="d_date_sk", right_on="ss_sold_date_sk")
        .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        .merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    m = m[(m.i_manager_id == 8) & (m.d_moy == 11) & (m.d_year == 1998)]
    m = m[m.ca_zip.astype(str).str[:5] != m.s_zip.astype(str).str[:5]]
    g = m.groupby(["i_brand", "i_brand_id", "i_manufact_id", "i_manufact"], as_index=False)[
        "ss_ext_sales_price"
    ].sum()
    g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand",
                          "ss_ext_sales_price": "ext_price"})
    _nonempty(
        check(sess, "q19", g[["brand_id", "brand", "i_manufact_id", "i_manufact", "ext_price"]]),
        "q19",
    )


# --- group B: (de)correlated subqueries ------------------------------------


def test_q1(env):
    sess, t = env
    sr, d, s, c = t["store_returns"], t["date_dim"], t["store"], t["customer"]
    m = sr.merge(d, left_on="sr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2000]
    ctr = m.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)["sr_return_amt"].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_store_sk", "ctr_total_return"]
    avg_by_store = ctr.groupby("ctr_store_sk")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_store]
    keep = keep.merge(s, left_on="ctr_store_sk", right_on="s_store_sk")
    keep = keep[keep.s_state == "TN"]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    out = keep[["c_customer_id"]].sort_values("c_customer_id").reset_index(drop=True)
    _nonempty(check(sess, "q1", out), "q1")


def test_q6(env):
    sess, t = env
    ca, c, ss, d, i = (t["customer_address"], t["customer"], t["store_sales"],
                       t["date_dim"], t["item"])
    target_seq = d[(d.d_year == 2000) & (d.d_moy == 1)].d_month_seq.unique()
    assert len(target_seq) == 1
    avg_by_cat = i.groupby("i_category")["i_current_price"].transform("mean")
    pricey = i[i.i_current_price > 1.2 * avg_by_cat]
    m = (
        ca.merge(c, left_on="ca_address_sk", right_on="c_current_addr_sk")
        .merge(ss, left_on="c_customer_sk", right_on="ss_customer_sk")
        .merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(pricey, left_on="ss_item_sk", right_on="i_item_sk")
    )
    m = m[m.d_month_seq == target_seq[0]]
    g = m.groupby("ca_state", dropna=False).size().reset_index(name="cnt")
    g = g[g.cnt >= 10]
    g.columns = ["state", "cnt"]
    _nonempty(check(sess, "q6", g), "q6")


def test_q30(env):
    sess, t = env
    wr, d, ca, c = t["web_returns"], t["date_dim"], t["customer_address"], t["customer"]
    m = wr.merge(d, left_on="wr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2002]
    # ctr: returning customer x state of the RETURNING ADDRESS
    m = m.merge(ca, left_on="wr_returning_addr_sk", right_on="ca_address_sk")
    ctr = m.groupby(["wr_returning_customer_sk", "ca_state"], as_index=False)[
        "wr_return_amt"
    ].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_state", "ctr_total_return"]
    avg_by_state = ctr.groupby("ctr_state")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_state]
    keep = keep[["ctr_customer_sk", "ctr_total_return"]]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    keep = keep.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    keep = keep[keep.ca_state == "GA"]
    out = keep[[
        "c_customer_id", "c_salutation", "c_first_name", "c_last_name",
        "c_preferred_cust_flag", "c_birth_day", "c_birth_month", "c_birth_year",
        "c_birth_country", "c_login", "c_email_address", "c_last_review_date",
        "ctr_total_return",
    ]]
    _nonempty(check(sess, "q30", out), "q30")


def test_q81(env):
    sess, t = env
    cr, d, ca, c = t["catalog_returns"], t["date_dim"], t["customer_address"], t["customer"]
    m = cr.merge(d, left_on="cr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2000]
    m = m.merge(ca, left_on="cr_returning_addr_sk", right_on="ca_address_sk")
    ctr = m.groupby(["cr_returning_customer_sk", "ca_state"], as_index=False)[
        "cr_return_amt_inc_tax"
    ].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_state", "ctr_total_return"]
    avg_by_state = ctr.groupby("ctr_state")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_state]
    keep = keep[["ctr_customer_sk", "ctr_total_return"]]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    keep = keep.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    keep = keep[keep.ca_state == "GA"]
    out = keep[[
        "c_customer_id", "c_salutation", "c_first_name", "c_last_name",
        "ca_street_number", "ca_street_name", "ca_street_type",
        "ca_suite_number", "ca_city", "ca_county", "ca_state", "ca_zip",
        "ca_country", "ca_gmt_offset", "ca_location_type", "ctr_total_return",
    ]]
    _nonempty(check(sess, "q81", out), "q81")


def _excess_discount(t, fact, itemcol, datecol, amtcol, manufact, date0):
    f, i, d = t[fact], t["item"], t["date_dim"]
    lo = np.datetime64(date0)
    window = d[(d.d_date.values >= lo) & (d.d_date.values <= lo + np.timedelta64(90, "D"))]
    fw = f.merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
    avg_by_item = fw.groupby(itemcol)[amtcol].transform("mean")
    excess = fw[fw[amtcol] > 1.3 * avg_by_item]
    items = i[i.i_manufact_id == manufact].i_item_sk
    return excess[excess[itemcol].isin(items)]


def test_q32(env):
    sess, t = env
    hits = _excess_discount(t, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                            "cs_ext_discount_amt", 977, "2000-01-27")
    # SELECT 1 ... per qualifying row
    out = pd.DataFrame({"excess discount amount ": np.ones(len(hits), dtype=np.int64)})
    _nonempty(check(sess, "q32", out), "q32")


def test_q92(env):
    sess, t = env
    hits = _excess_discount(t, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                            "ws_ext_discount_amt", 350, "2000-01-27")
    val = hits.ws_ext_discount_amt.sum() if len(hits) else np.nan
    check(sess, "q92", pd.DataFrame({"Excess Discount Amount ": [val]}))


def _ship_exists(t, fact, ordcol, whcol, datecol, addrcol, sitecol, site_table,
                 site_key, site_filter, rets, r_ordcol, date0, state):
    f, d, ca = t[fact], t["date_dim"], t["customer_address"]
    lo = np.datetime64(date0)
    window = d[(d.d_date.values >= lo) & (d.d_date.values <= lo + np.timedelta64(60, "D"))]
    m = f.merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
    m = m.merge(ca[ca.ca_state == state][["ca_address_sk"]], left_on=addrcol,
                right_on="ca_address_sk")
    st = t[site_table]
    m = m.merge(st[site_filter(st)][[site_key]], left_on=sitecol, right_on=site_key)
    # EXISTS same order, different warehouse
    wh_counts = f.groupby(ordcol)[whcol].nunique(dropna=True)
    multi = set(wh_counts[wh_counts > 1].index)
    m = m[m[ordcol].isin(multi)]
    # NOT EXISTS a return for the order
    returned = set(t[rets][r_ordcol].dropna())
    m = m[~m[ordcol].isin(returned)]
    return m


def test_q16(env):
    sess, t = env
    m = _ship_exists(
        t, "catalog_sales", "cs_order_number", "cs_warehouse_sk", "cs_ship_date_sk",
        "cs_ship_addr_sk", "cs_call_center_sk", "call_center", "cc_call_center_sk",
        lambda cc: cc.cc_county == "Williamson County",
        "catalog_returns", "cr_order_number", "2002-02-01", "GA",
    )
    out = pd.DataFrame({
        "order count ": [m.cs_order_number.nunique()],
        "total shipping cost ": [m.cs_ext_ship_cost.sum() if len(m) else np.nan],
        "total net profit ": [m.cs_net_profit.sum() if len(m) else np.nan],
    })
    check(sess, "q16", out)


def test_q94(env):
    sess, t = env
    m = _ship_exists(
        t, "web_sales", "ws_order_number", "ws_warehouse_sk", "ws_ship_date_sk",
        "ws_ship_addr_sk", "ws_web_site_sk", "web_site", "web_site_sk",
        lambda w: w.web_company_name == "pri",
        "web_returns", "wr_order_number", "1999-02-01", "IL",
    )
    out = pd.DataFrame({
        "order count ": [m.ws_order_number.nunique()],
        "total shipping cost ": [m.ws_ext_ship_cost.sum() if len(m) else np.nan],
        "total net profit ": [m.ws_net_profit.sum() if len(m) else np.nan],
    })
    check(sess, "q94", out)


def test_q41(env):
    sess, t = env
    i = t["item"]

    def combo(cat, colors, units, sizes):
        return (
            (i.i_category == cat)
            & i.i_color.isin(colors) & i.i_units.isin(units) & i.i_size.isin(sizes)
        )

    set1 = (
        combo("Women", ["powder", "khaki"], ["Ounce", "Oz"], ["medium", "extra large"])
        | combo("Women", ["brown", "honeydew"], ["Bunch", "Ton"], ["N/A", "small"])
        | combo("Men", ["floral", "deep"], ["N/A", "Dozen"], ["petite", "large"])
        | combo("Men", ["light", "cornflower"], ["Box", "Pound"], ["medium", "extra large"])
    )
    set2 = (
        combo("Women", ["midnight", "snow"], ["Pallet", "Gross"], ["medium", "extra large"])
        | combo("Women", ["cyan", "papaya"], ["Cup", "Dram"], ["N/A", "small"])
        | combo("Men", ["orange", "frosted"], ["Each", "Tbl"], ["petite", "large"])
        | combo("Men", ["forest", "ghost"], ["Lb", "Bundle"], ["medium", "extra large"])
    )
    qualifying_manufacts = set(i[set1 | set2].i_manufact)
    outer = i[(i.i_manufact_id >= 738) & (i.i_manufact_id <= 778)]
    out = outer[outer.i_manufact.isin(qualifying_manufacts)][["i_product_name"]].drop_duplicates()
    _nonempty(check(sess, "q41", out), "q41")
