"""Absolute-correctness oracles for TPC-DS queries: pandas reimplementations
checked against the engine, so a SQL-engine bug shared by the hyperspace-on
AND hyperspace-off paths (the decorrelation count-bug class) is caught — the
parity suite alone cannot see it (ref: the reference's checkAnswer culture,
E2EHyperspaceRulesTest.scala:75-1016 verifies results, not just parity).

Each oracle mirrors its query text (LIMIT stripped on both sides so ORDER BY
ties cannot flake); the decorrelated queries round 3 touched (q1, q6, q30,
q32, q41, q81, q92) and the null-aware EXISTS pair (q16, q94) are all here.
"""

import math
import os
import re

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst

QUERIES_DIR = "/root/reference/src/test/resources/tpcds/queries"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(QUERIES_DIR), reason="reference TPC-DS query texts not available"
)


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    from tpcds_data import arrow_tables

    root = str(tmp_path_factory.mktemp("tpcds_oracle"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    frames = {}
    for name, table in arrow_tables().items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(table, os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
        frames[name] = table.to_pandas()
    # a couple of indexes so the oracle checks also cover rewritten plans
    hs.create_index(
        sess._temp_views["store_sales"],
        hst.CoveringIndexConfig(
            "o_ss_item", ["ss_item_sk"],
            ["ss_sold_date_sk", "ss_ext_sales_price", "ss_quantity", "ss_sales_price"],
        ),
    )
    hs.create_index(
        sess._temp_views["date_dim"],
        hst.CoveringIndexConfig("o_d_sk", ["d_date_sk"], ["d_year", "d_moy", "d_qoy"]),
    )
    sess.enable_hyperspace()
    yield sess, frames
    hst.set_session(None)


def strip_limit(text):
    """Strip a trailing LIMIT so ORDER BY ties cannot make the comparison
    flaky; oracles compute the full set. Shared with test_tpch_oracles."""
    return re.sub(r"\bLIMIT\s+\d+\s*$", "", text.strip(), flags=re.I)


def _query_text(qname):
    with open(os.path.join(QUERIES_DIR, f"{qname}.sql")) as f:
        return strip_limit(f.read())


def _is_num(v):
    return isinstance(v, (float, np.floating, int, np.integer)) and not isinstance(v, bool)


def _norm(v):
    if v is None or (isinstance(v, float) and v != v) or v is pd.NaT:
        return "\x00NULL"
    # ints and floats format IDENTICALLY so the row sort cannot misalign an
    # engine int64 against its oracle float-coerced counterpart
    if _is_num(v):
        return f"{float(v):.3g}"
    return str(v)


def _rows_of_batch(batch):
    cols = sorted(batch.keys())
    return [tuple(r) for r in zip(*[batch[c].tolist() for c in cols])], cols


def _rows_of_frame(df, ecols_sorted):
    """Align oracle columns to the engine's (sorted) output names,
    case-insensitively — oracle frames use the query's alias names."""
    lower = {c.lower(): c for c in df.columns}
    missing = [c for c in ecols_sorted if c.lower() not in lower]
    assert not missing, f"oracle lacks columns {missing}; has {list(df.columns)}"
    ordered = [lower[c.lower()] for c in ecols_sorted]
    return [tuple(r) for r in zip(*[df[c].tolist() for c in ordered])]


def check(sess, qname, oracle_df):
    return compare_batch(sess.sql(_query_text(qname)).collect(), oracle_df, qname)


def compare_batch(got, oracle_df, qname):
    """Engine batch vs pandas oracle frame: column-count and (sorted,
    normalized) row-set equality with float tolerance. Shared by the TPC-DS
    and TPC-H oracle suites."""
    erows, ecols = _rows_of_batch(got)
    assert len(oracle_df.columns) == len(ecols), (qname, list(oracle_df.columns), ecols)
    orows = _rows_of_frame(oracle_df, ecols)
    assert len(erows) == len(orows), f"{qname}: engine {len(erows)} rows vs oracle {len(orows)}"
    ekey = sorted(erows, key=lambda r: tuple(_norm(v) for v in r))
    okey = sorted(orows, key=lambda r: tuple(_norm(v) for v in r))
    for a, b in zip(ekey, okey):
        for x, y in zip(a, b):
            if _is_num(x) and _is_num(y):
                xf = isinstance(x, (float, np.floating))
                yf = isinstance(y, (float, np.floating))
                if not xf and not yf:
                    # int vs int compares EXACTLY (tolerance would wave
                    # through off-by-one counts at >=1e6 magnitudes)
                    assert int(x) == int(y), f"{qname}: {x!r} != {y!r}"
                    continue
                # a pandas oracle Series mixing sums and counts coerces the
                # counts to float while the engine keeps int64 — numeric
                # compare with tolerance once ANY side is a float
                if x != x and y != y:
                    continue
                assert math.isclose(float(x), float(y), rel_tol=1e-6, abs_tol=1e-6), (
                    f"{qname}: {x!r} != {y!r}"
                )
            else:
                assert _norm(x) == _norm(y), f"{qname}: {x!r} != {y!r} (rows {a} vs {b})"
    return len(erows)


def _nonempty(n, qname):
    assert n > 0, f"{qname}: oracle comparison is vacuous (0 rows)"


# --- group A: star-join aggregates -----------------------------------------


def test_q3(env):
    sess, t = env
    ss, d, i = t["store_sales"], t["date_dim"], t["item"]
    m = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        i, left_on="ss_item_sk", right_on="i_item_sk"
    )
    m = m[(m.i_manufact_id == 128) & (m.d_moy == 11)]
    g = (
        m.groupby(["d_year", "i_brand", "i_brand_id"], as_index=False)["ss_ext_sales_price"]
        .sum()
        .rename(columns={"i_brand_id": "brand_id", "i_brand": "brand", "ss_ext_sales_price": "sum_agg"})
    )
    _nonempty(check(sess, "q3", g[["d_year", "brand_id", "brand", "sum_agg"]]), "q3")


def _q42_like(t, manager, moy, year, keys, outnames):
    ss, d, i = t["store_sales"], t["date_dim"], t["item"]
    m = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        i, left_on="ss_item_sk", right_on="i_item_sk"
    )
    m = m[(m.i_manager_id == manager) & (m.d_moy == moy) & (m.d_year == year)]
    g = m.groupby(keys, as_index=False)["ss_ext_sales_price"].sum()
    g.columns = outnames
    return g


def test_q42(env):
    sess, t = env
    g = _q42_like(t, 1, 11, 2000, ["d_year", "i_category_id", "i_category"],
                  ["d_year", "i_category_id", "i_category", "sum(ss_ext_sales_price)"])
    _nonempty(check(sess, "q42", g), "q42")


def test_q52(env):
    sess, t = env
    g = _q42_like(t, 1, 11, 2000, ["d_year", "i_brand", "i_brand_id"],
                  ["d_year", "brand", "brand_id", "ext_price"])
    _nonempty(check(sess, "q52", g[["d_year", "brand_id", "brand", "ext_price"]]), "q52")


def test_q55(env):
    sess, t = env
    g = _q42_like(t, 28, 11, 1999, ["i_brand", "i_brand_id"],
                  ["brand", "brand_id", "ext_price"])
    _nonempty(check(sess, "q55", g[["brand_id", "brand", "ext_price"]]), "q55")


def test_q96(env):
    sess, t = env
    ss, hd, td, s = t["store_sales"], t["household_demographics"], t["time_dim"], t["store"]
    m = (
        ss.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    m = m[(m.t_hour == 20) & (m.t_minute >= 30) & (m.hd_dep_count == 7) & (m.s_store_name == "ese")]
    _nonempty(check(sess, "q96", pd.DataFrame({"count": [len(m)]})), "q96")


def test_q15(env):
    sess, t = env
    cs, c, ca, d = t["catalog_sales"], t["customer"], t["customer_address"], t["date_dim"]
    m = (
        cs.merge(c, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        .merge(d, left_on="cs_sold_date_sk", right_on="d_date_sk")
    )
    zips = {"85669", "86197", "88274", "83405", "86475", "85392", "85460", "80348", "81792"}
    cond = (
        m.ca_zip.astype(str).str[:5].isin(zips)
        | m.ca_state.isin(["CA", "WA", "GA"])
        | (m.cs_sales_price > 500)
    )
    m = m[cond & (m.d_qoy == 2) & (m.d_year == 2001)]
    g = m.groupby("ca_zip", as_index=False)["cs_sales_price"].sum()
    g.columns = ["ca_zip", "sum(cs_sales_price)"]
    _nonempty(check(sess, "q15", g), "q15")


def test_q37(env):
    sess, t = env
    i, inv, d, cs = t["item"], t["inventory"], t["date_dim"], t["catalog_sales"]
    m = i.merge(inv, left_on="i_item_sk", right_on="inv_item_sk").merge(
        d, left_on="inv_date_sk", right_on="d_date_sk"
    )
    lo = np.datetime64("2000-02-01")
    m = m[
        (m.i_current_price >= 68) & (m.i_current_price <= 98)
        & m.i_manufact_id.isin([677, 940, 694, 808])
        & (m.inv_quantity_on_hand >= 100) & (m.inv_quantity_on_hand <= 500)
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(60, "D"))
    ]
    m = m[m.i_item_sk.isin(cs.cs_item_sk)]
    g = m[["i_item_id", "i_item_desc", "i_current_price"]].drop_duplicates()
    _nonempty(check(sess, "q37", g), "q37")


def test_q82(env):
    sess, t = env
    i, inv, d, ss = t["item"], t["inventory"], t["date_dim"], t["store_sales"]
    m = i.merge(inv, left_on="i_item_sk", right_on="inv_item_sk").merge(
        d, left_on="inv_date_sk", right_on="d_date_sk"
    )
    lo = np.datetime64("2000-05-25")
    m = m[
        (m.i_current_price >= 62) & (m.i_current_price <= 92)
        & m.i_manufact_id.isin([129, 270, 821, 423])
        & (m.inv_quantity_on_hand >= 100) & (m.inv_quantity_on_hand <= 500)
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(60, "D"))
    ]
    m = m[m.i_item_sk.isin(ss.ss_item_sk)]
    g = m[["i_item_id", "i_item_desc", "i_current_price"]].drop_duplicates()
    _nonempty(check(sess, "q82", g), "q82")


def _q12_like(t, fact, datecol, pricecol, itemcol):
    f, i, d = t[fact], t["item"], t["date_dim"]
    m = f.merge(i, left_on=itemcol, right_on="i_item_sk").merge(
        d, left_on=datecol, right_on="d_date_sk"
    )
    lo = np.datetime64("1999-02-22")
    m = m[
        m.i_category.isin(["Sports", "Books", "Home"])
        & (m.d_date.values >= lo) & (m.d_date.values <= lo + np.timedelta64(30, "D"))
    ]
    g = m.groupby(
        ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
        as_index=False,
    )[pricecol].sum()
    g = g.rename(columns={pricecol: "itemrevenue"})
    class_tot = g.groupby("i_class")["itemrevenue"].transform("sum")
    g["revenueratio"] = g["itemrevenue"] * 100.0 / class_tot
    # SELECT omits i_item_id though GROUP BY includes it — keep duplicates
    return g.drop(columns=["i_item_id"])


def test_q12(env):
    sess, t = env
    g = _q12_like(t, "web_sales", "ws_sold_date_sk", "ws_ext_sales_price", "ws_item_sk")
    _nonempty(check(sess, "q12", g), "q12")


def test_q20(env):
    sess, t = env
    g = _q12_like(t, "catalog_sales", "cs_sold_date_sk", "cs_ext_sales_price", "cs_item_sk")
    _nonempty(check(sess, "q20", g), "q20")


def test_q98(env):
    sess, t = env
    g = _q12_like(t, "store_sales", "ss_sold_date_sk", "ss_ext_sales_price", "ss_item_sk")
    _nonempty(check(sess, "q98", g), "q98")


def _q7_like(t, fact, cdemo, datecol, itemcol, promocol, qty, list_, coupon, sales):
    f, cd, d, i, p = t[fact], t["customer_demographics"], t["date_dim"], t["item"], t["promotion"]
    m = (
        f.merge(cd, left_on=cdemo, right_on="cd_demo_sk")
        .merge(d, left_on=datecol, right_on="d_date_sk")
        .merge(i, left_on=itemcol, right_on="i_item_sk")
        .merge(p, left_on=promocol, right_on="p_promo_sk")
    )
    m = m[
        (m.cd_gender == "M") & (m.cd_marital_status == "S")
        & (m.cd_education_status == "College")
        & ((m.p_channel_email == "N") | (m.p_channel_event == "N"))
        & (m.d_year == 2000)
    ]
    g = m.groupby("i_item_id", as_index=False).agg(
        agg1=(qty, "mean"), agg2=(list_, "mean"), agg3=(coupon, "mean"), agg4=(sales, "mean")
    )
    return g


def test_q7(env):
    sess, t = env
    g = _q7_like(t, "store_sales", "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk",
                 "ss_promo_sk", "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price")
    _nonempty(check(sess, "q7", g), "q7")


def test_q26(env):
    sess, t = env
    g = _q7_like(t, "catalog_sales", "cs_bill_cdemo_sk", "cs_sold_date_sk", "cs_item_sk",
                 "cs_promo_sk", "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price")
    _nonempty(check(sess, "q26", g), "q26")


def test_q19(env):
    sess, t = env
    d, ss, i, c, ca, s = (t["date_dim"], t["store_sales"], t["item"], t["customer"],
                          t["customer_address"], t["store"])
    m = (
        d.merge(ss, left_on="d_date_sk", right_on="ss_sold_date_sk")
        .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        .merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    m = m[(m.i_manager_id == 8) & (m.d_moy == 11) & (m.d_year == 1998)]
    m = m[m.ca_zip.astype(str).str[:5] != m.s_zip.astype(str).str[:5]]
    g = m.groupby(["i_brand", "i_brand_id", "i_manufact_id", "i_manufact"], as_index=False)[
        "ss_ext_sales_price"
    ].sum()
    g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand",
                          "ss_ext_sales_price": "ext_price"})
    _nonempty(
        check(sess, "q19", g[["brand_id", "brand", "i_manufact_id", "i_manufact", "ext_price"]]),
        "q19",
    )


# --- group B: (de)correlated subqueries ------------------------------------


def test_q1(env):
    sess, t = env
    sr, d, s, c = t["store_returns"], t["date_dim"], t["store"], t["customer"]
    m = sr.merge(d, left_on="sr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2000]
    ctr = m.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)["sr_return_amt"].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_store_sk", "ctr_total_return"]
    avg_by_store = ctr.groupby("ctr_store_sk")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_store]
    keep = keep.merge(s, left_on="ctr_store_sk", right_on="s_store_sk")
    keep = keep[keep.s_state == "TN"]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    out = keep[["c_customer_id"]].sort_values("c_customer_id").reset_index(drop=True)
    _nonempty(check(sess, "q1", out), "q1")


def test_q6(env):
    sess, t = env
    ca, c, ss, d, i = (t["customer_address"], t["customer"], t["store_sales"],
                       t["date_dim"], t["item"])
    target_seq = d[(d.d_year == 2000) & (d.d_moy == 1)].d_month_seq.unique()
    assert len(target_seq) == 1
    avg_by_cat = i.groupby("i_category")["i_current_price"].transform("mean")
    pricey = i[i.i_current_price > 1.2 * avg_by_cat]
    m = (
        ca.merge(c, left_on="ca_address_sk", right_on="c_current_addr_sk")
        .merge(ss, left_on="c_customer_sk", right_on="ss_customer_sk")
        .merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(pricey, left_on="ss_item_sk", right_on="i_item_sk")
    )
    m = m[m.d_month_seq == target_seq[0]]
    g = m.groupby("ca_state", dropna=False).size().reset_index(name="cnt")
    g = g[g.cnt >= 10]
    g.columns = ["state", "cnt"]
    _nonempty(check(sess, "q6", g), "q6")


def test_q30(env):
    sess, t = env
    wr, d, ca, c = t["web_returns"], t["date_dim"], t["customer_address"], t["customer"]
    m = wr.merge(d, left_on="wr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2002]
    # ctr: returning customer x state of the RETURNING ADDRESS
    m = m.merge(ca, left_on="wr_returning_addr_sk", right_on="ca_address_sk")
    ctr = m.groupby(["wr_returning_customer_sk", "ca_state"], as_index=False)[
        "wr_return_amt"
    ].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_state", "ctr_total_return"]
    avg_by_state = ctr.groupby("ctr_state")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_state]
    keep = keep[["ctr_customer_sk", "ctr_total_return"]]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    keep = keep.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    keep = keep[keep.ca_state == "GA"]
    out = keep[[
        "c_customer_id", "c_salutation", "c_first_name", "c_last_name",
        "c_preferred_cust_flag", "c_birth_day", "c_birth_month", "c_birth_year",
        "c_birth_country", "c_login", "c_email_address", "c_last_review_date",
        "ctr_total_return",
    ]]
    _nonempty(check(sess, "q30", out), "q30")


def test_q81(env):
    sess, t = env
    cr, d, ca, c = t["catalog_returns"], t["date_dim"], t["customer_address"], t["customer"]
    m = cr.merge(d, left_on="cr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2000]
    m = m.merge(ca, left_on="cr_returning_addr_sk", right_on="ca_address_sk")
    ctr = m.groupby(["cr_returning_customer_sk", "ca_state"], as_index=False)[
        "cr_return_amt_inc_tax"
    ].sum()
    ctr.columns = ["ctr_customer_sk", "ctr_state", "ctr_total_return"]
    avg_by_state = ctr.groupby("ctr_state")["ctr_total_return"].transform("mean")
    keep = ctr[ctr.ctr_total_return > 1.2 * avg_by_state]
    keep = keep[["ctr_customer_sk", "ctr_total_return"]]
    keep = keep.merge(c, left_on="ctr_customer_sk", right_on="c_customer_sk")
    keep = keep.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    keep = keep[keep.ca_state == "GA"]
    out = keep[[
        "c_customer_id", "c_salutation", "c_first_name", "c_last_name",
        "ca_street_number", "ca_street_name", "ca_street_type",
        "ca_suite_number", "ca_city", "ca_county", "ca_state", "ca_zip",
        "ca_country", "ca_gmt_offset", "ca_location_type", "ctr_total_return",
    ]]
    _nonempty(check(sess, "q81", out), "q81")


def _excess_discount(t, fact, itemcol, datecol, amtcol, manufact, date0):
    f, i, d = t[fact], t["item"], t["date_dim"]
    lo = np.datetime64(date0)
    window = d[(d.d_date.values >= lo) & (d.d_date.values <= lo + np.timedelta64(90, "D"))]
    fw = f.merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
    avg_by_item = fw.groupby(itemcol)[amtcol].transform("mean")
    excess = fw[fw[amtcol] > 1.3 * avg_by_item]
    items = i[i.i_manufact_id == manufact].i_item_sk
    return excess[excess[itemcol].isin(items)]


def test_q32(env):
    sess, t = env
    hits = _excess_discount(t, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                            "cs_ext_discount_amt", 977, "2000-01-27")
    # SELECT 1 ... per qualifying row
    out = pd.DataFrame({"excess discount amount ": np.ones(len(hits), dtype=np.int64)})
    _nonempty(check(sess, "q32", out), "q32")


def test_q92(env):
    sess, t = env
    hits = _excess_discount(t, "web_sales", "ws_item_sk", "ws_sold_date_sk",
                            "ws_ext_discount_amt", 350, "2000-01-27")
    val = hits.ws_ext_discount_amt.sum() if len(hits) else np.nan
    check(sess, "q92", pd.DataFrame({"Excess Discount Amount ": [val]}))


def _ship_exists(t, fact, ordcol, whcol, datecol, addrcol, sitecol, site_table,
                 site_key, site_filter, rets, r_ordcol, date0, state):
    f, d, ca = t[fact], t["date_dim"], t["customer_address"]
    lo = np.datetime64(date0)
    window = d[(d.d_date.values >= lo) & (d.d_date.values <= lo + np.timedelta64(60, "D"))]
    m = f.merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
    m = m.merge(ca[ca.ca_state == state][["ca_address_sk"]], left_on=addrcol,
                right_on="ca_address_sk")
    st = t[site_table]
    m = m.merge(st[site_filter(st)][[site_key]], left_on=sitecol, right_on=site_key)
    # EXISTS same order, different warehouse
    wh_counts = f.groupby(ordcol)[whcol].nunique(dropna=True)
    multi = set(wh_counts[wh_counts > 1].index)
    m = m[m[ordcol].isin(multi)]
    # NOT EXISTS a return for the order
    returned = set(t[rets][r_ordcol].dropna())
    m = m[~m[ordcol].isin(returned)]
    return m


def test_q16(env):
    sess, t = env
    m = _ship_exists(
        t, "catalog_sales", "cs_order_number", "cs_warehouse_sk", "cs_ship_date_sk",
        "cs_ship_addr_sk", "cs_call_center_sk", "call_center", "cc_call_center_sk",
        lambda cc: cc.cc_county == "Williamson County",
        "catalog_returns", "cr_order_number", "2002-02-01", "GA",
    )
    out = pd.DataFrame({
        "order count ": [m.cs_order_number.nunique()],
        "total shipping cost ": [m.cs_ext_ship_cost.sum() if len(m) else np.nan],
        "total net profit ": [m.cs_net_profit.sum() if len(m) else np.nan],
    })
    check(sess, "q16", out)


def test_q94(env):
    sess, t = env
    m = _ship_exists(
        t, "web_sales", "ws_order_number", "ws_warehouse_sk", "ws_ship_date_sk",
        "ws_ship_addr_sk", "ws_web_site_sk", "web_site", "web_site_sk",
        lambda w: w.web_company_name == "pri",
        "web_returns", "wr_order_number", "1999-02-01", "IL",
    )
    out = pd.DataFrame({
        "order count ": [m.ws_order_number.nunique()],
        "total shipping cost ": [m.ws_ext_ship_cost.sum() if len(m) else np.nan],
        "total net profit ": [m.ws_net_profit.sum() if len(m) else np.nan],
    })
    check(sess, "q94", out)


def test_q41(env):
    sess, t = env
    i = t["item"]

    def combo(cat, colors, units, sizes):
        return (
            (i.i_category == cat)
            & i.i_color.isin(colors) & i.i_units.isin(units) & i.i_size.isin(sizes)
        )

    set1 = (
        combo("Women", ["powder", "khaki"], ["Ounce", "Oz"], ["medium", "extra large"])
        | combo("Women", ["brown", "honeydew"], ["Bunch", "Ton"], ["N/A", "small"])
        | combo("Men", ["floral", "deep"], ["N/A", "Dozen"], ["petite", "large"])
        | combo("Men", ["light", "cornflower"], ["Box", "Pound"], ["medium", "extra large"])
    )
    set2 = (
        combo("Women", ["midnight", "snow"], ["Pallet", "Gross"], ["medium", "extra large"])
        | combo("Women", ["cyan", "papaya"], ["Cup", "Dram"], ["N/A", "small"])
        | combo("Men", ["orange", "frosted"], ["Each", "Tbl"], ["petite", "large"])
        | combo("Men", ["forest", "ghost"], ["Lb", "Bundle"], ["medium", "extra large"])
    )
    qualifying_manufacts = set(i[set1 | set2].i_manufact)
    outer = i[(i.i_manufact_id >= 738) & (i.i_manufact_id <= 778)]
    out = outer[outer.i_manufact.isin(qualifying_manufacts)][["i_product_name"]].drop_duplicates()
    _nonempty(check(sess, "q41", out), "q41")


def test_q9(env):
    sess, t = env
    ss = t["store_sales"]
    vals = {}
    for n, (lo, hi, thresh) in enumerate(
        [(1, 20, 62316685), (21, 40, 19045798), (41, 60, 365541424),
         (61, 80, 216357808), (81, 100, 184483884)], start=1
    ):
        b = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
        if len(b) > thresh:
            vals[f"bucket{n}"] = [b.ss_ext_discount_amt.mean()]
        else:
            vals[f"bucket{n}"] = [b.ss_net_paid.mean()]
    # one output row per qualifying reason row (r_reason_sk = 1)
    nreason = int((t["reason"].r_reason_sk == 1).sum())
    out = pd.DataFrame({k: v * nreason for k, v in vals.items()})
    _nonempty(check(sess, "q9", out), "q9")


def test_q10(env):
    sess, t = env
    c, ca, cd, d = t["customer"], t["customer_address"], t["customer_demographics"], t["date_dim"]
    counties = {"Rush County", "Toole County", "Jefferson County",
                "Dona Ana County", "La Porte County"}
    window = d[(d.d_year == 2002) & (d.d_moy >= 1) & (d.d_moy <= 4)]

    def active(fact, custcol, datecol):
        m = t[fact].merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
        return set(m[custcol].dropna())

    store_c = active("store_sales", "ss_customer_sk", "ss_sold_date_sk")
    web_c = active("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
    cat_c = active("catalog_sales", "cs_ship_customer_sk", "cs_sold_date_sk")
    m = c.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk").merge(
        cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk"
    )
    m = m[m.ca_county.isin(counties)
          & m.c_customer_sk.isin(store_c)
          & (m.c_customer_sk.isin(web_c) | m.c_customer_sk.isin(cat_c))]
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    g = m.groupby(keys, as_index=False).size().rename(columns={"size": "cnt1"})
    for extra in ("cnt2", "cnt3", "cnt4", "cnt5", "cnt6"):
        g[extra] = g["cnt1"]
    out = g[["cd_gender", "cd_marital_status", "cd_education_status", "cnt1",
             "cd_purchase_estimate", "cnt2", "cd_credit_rating", "cnt3",
             "cd_dep_count", "cnt4", "cd_dep_employed_count", "cnt5",
             "cd_dep_college_count", "cnt6"]]
    _nonempty(check(sess, "q10", out), "q10")


def test_q13(env):
    sess, t = env
    ss, s, cd, hd, ca, d = (t["store_sales"], t["store"], t["customer_demographics"],
                            t["household_demographics"], t["customer_address"], t["date_dim"])
    m = (
        ss.merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        .merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    )
    m = m[m.d_year == 2001]

    def demo(ms, ed, plo, phi, dep):
        return ((m.cd_marital_status == ms) & (m.cd_education_status == ed)
                & (m.ss_sales_price >= plo) & (m.ss_sales_price <= phi)
                & (m.hd_dep_count == dep))

    def addr(states, nlo, nhi):
        return ((m.ca_country == "United States") & m.ca_state.isin(states)
                & (m.ss_net_profit >= nlo) & (m.ss_net_profit <= nhi))

    m = m[
        (demo("M", "Advanced Degree", 100.0, 150.0, 3)
         | demo("S", "College", 50.0, 100.0, 1)
         | demo("W", "2 yr Degree", 150.0, 200.0, 1))
        & (addr(["TX", "OH"], 100, 200)
           | addr(["OR", "NM", "KY"], 150, 300)
           | addr(["VA", "TX", "MS"], 50, 250))
    ]
    out = pd.DataFrame({
        "avg(ss_quantity)": [m.ss_quantity.mean()],
        "avg(ss_ext_sales_price)": [m.ss_ext_sales_price.mean()],
        "avg(ss_ext_wholesale_cost)": [m.ss_ext_wholesale_cost.mean()],
        "sum(ss_ext_wholesale_cost)": [m.ss_ext_wholesale_cost.sum() if len(m) else np.nan],
    })
    check(sess, "q13", out)


def _rollup(m, levels, aggfn):
    """Pandas ROLLUP: one groupby per prefix of ``levels`` plus the grand
    total, un-grouped levels filled with None (SQL NULL)."""
    frames = []
    for k in range(len(levels), -1, -1):
        keys = levels[:k]
        if keys:
            g = m.groupby(keys, as_index=False, dropna=False).apply(aggfn, include_groups=False)
        else:
            g = aggfn(m).to_frame().T
        for missing in levels[k:]:
            g[missing] = None
        frames.append(g)
    return pd.concat(frames, ignore_index=True)


def test_q18(env):
    sess, t = env
    cs, cd, c, ca, d, i = (t["catalog_sales"], t["customer_demographics"], t["customer"],
                           t["customer_address"], t["date_dim"], t["item"])
    cd1 = cd[(cd.cd_gender == "F") & (cd.cd_education_status == "Unknown")]
    m = (
        cs.merge(d[d.d_year == 1998][["d_date_sk"]], left_on="cs_sold_date_sk", right_on="d_date_sk")
        .merge(i, left_on="cs_item_sk", right_on="i_item_sk")
        .merge(cd1.add_prefix("one_"), left_on="cs_bill_cdemo_sk", right_on="one_cd_demo_sk")
        .merge(c, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        .merge(cd[["cd_demo_sk"]], left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    )
    m = m[m.c_birth_month.isin([1, 6, 8, 9, 12, 2])
          & m.ca_state.isin(["MS", "IN", "ND", "OK", "NM", "VA"])]

    def aggs(g):
        return pd.Series({
            "agg1": g.cs_quantity.mean(), "agg2": g.cs_list_price.mean(),
            "agg3": g.cs_coupon_amt.mean(), "agg4": g.cs_sales_price.mean(),
            "agg5": g.cs_net_profit.mean(), "agg6": g.c_birth_year.mean(),
            "agg7": g.one_cd_dep_count.mean(),
        })

    out = _rollup(m, ["i_item_id", "ca_country", "ca_state", "ca_county"], aggs)
    out = out[["i_item_id", "ca_country", "ca_state", "ca_county",
               "agg1", "agg2", "agg3", "agg4", "agg5", "agg6", "agg7"]]
    _nonempty(check(sess, "q18", out), "q18")


def test_q22(env):
    sess, t = env
    inv, d, i, w = t["inventory"], t["date_dim"], t["item"], t["warehouse"]
    m = (
        inv.merge(d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk"]],
                  left_on="inv_date_sk", right_on="d_date_sk")
        .merge(i, left_on="inv_item_sk", right_on="i_item_sk")
        .merge(w, left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    )

    def aggs(g):
        return pd.Series({"qoh": g.inv_quantity_on_hand.mean()})

    out = _rollup(m, ["i_product_name", "i_brand", "i_class", "i_category"], aggs)
    out = out[["i_product_name", "i_brand", "i_class", "i_category", "qoh"]]
    _nonempty(check(sess, "q22", out), "q22")


def test_q33(env):
    sess, t = env
    d, ca, i = t["date_dim"], t["customer_address"], t["item"]
    electronics = set(i[i.i_category == "Electronics"].i_manufact_id.dropna())
    window = d[(d.d_year == 1998) & (d.d_moy == 5)]
    addrs = ca[ca.ca_gmt_offset == -5]

    def channel(fact, itemcol, datecol, addrcol, pricecol):
        m = (
            t[fact].merge(window[["d_date_sk"]], left_on=datecol, right_on="d_date_sk")
            .merge(addrs[["ca_address_sk"]], left_on=addrcol, right_on="ca_address_sk")
            .merge(i[["i_item_sk", "i_manufact_id"]], left_on=itemcol, right_on="i_item_sk")
        )
        m = m[m.i_manufact_id.isin(electronics)]
        return m.groupby("i_manufact_id", as_index=False)[pricecol].sum().rename(
            columns={pricecol: "total_sales"}
        )

    parts = pd.concat([
        channel("store_sales", "ss_item_sk", "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price"),
        channel("catalog_sales", "cs_item_sk", "cs_sold_date_sk", "cs_bill_addr_sk", "cs_ext_sales_price"),
        channel("web_sales", "ws_item_sk", "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
    ], ignore_index=True)
    out = parts.groupby("i_manufact_id", as_index=False)["total_sales"].sum()
    _nonempty(check(sess, "q33", out), "q33")


def test_q34(env):
    sess, t = env
    ss, d, s, hd, c = (t["store_sales"], t["date_dim"], t["store"],
                       t["household_demographics"], t["customer"])
    m = (
        ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    )
    ratio = np.where(m.hd_vehicle_count > 0, m.hd_dep_count / m.hd_vehicle_count, np.nan)
    m = m[
        (((m.d_dom >= 1) & (m.d_dom <= 3)) | ((m.d_dom >= 25) & (m.d_dom <= 28)))
        & m.hd_buy_potential.isin([">10000", "unknown"])
        & (m.hd_vehicle_count > 0)
        & (ratio > 1.2)
        & m.d_year.isin([1999, 2000, 2001])
        & (m.s_county == "Williamson County")
    ]
    g = m.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False).size().rename(
        columns={"size": "cnt"}
    )
    g = g[(g.cnt >= 15) & (g.cnt <= 20)]
    out = g.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")[
        ["c_last_name", "c_first_name", "c_salutation", "c_preferred_cust_flag",
         "ss_ticket_number", "cnt"]
    ]
    _nonempty(check(sess, "q34", out), "q34")


def test_q38(env):
    sess, t = env
    d, c = t["date_dim"], t["customer"]
    window = d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk", "d_date"]]

    def triples(fact, datecol, custcol):
        m = t[fact].merge(window, left_on=datecol, right_on="d_date_sk").merge(
            c, left_on=custcol, right_on="c_customer_sk"
        )
        return set(zip(m.c_last_name, m.c_first_name, m.d_date))

    inter = (
        triples("store_sales", "ss_sold_date_sk", "ss_customer_sk")
        & triples("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk")
        & triples("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk")
    )
    _nonempty(check(sess, "q38", pd.DataFrame({"count": [len(inter)]})), "q38")


def test_q43(env):
    sess, t = env
    ss, d, s = t["store_sales"], t["date_dim"], t["store"]
    m = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk").merge(
        s, left_on="ss_store_sk", right_on="s_store_sk"
    )
    m = m[(m.s_gmt_offset == -5) & (m.d_year == 2000)]
    days = [("Sunday", "sun_sales"), ("Monday", "mon_sales"), ("Tuesday", "tue_sales"),
            ("Wednesday", "wed_sales"), ("Thursday", "thu_sales"), ("Friday", "fri_sales"),
            ("Saturday", "sat_sales")]

    def aggs(g):
        row = {}
        for day, alias in days:
            sel = g[g.d_day_name == day].ss_sales_price
            row[alias] = sel.sum() if len(sel) else np.nan  # SUM over no rows = NULL
        return pd.Series(row)

    out = m.groupby(["s_store_name", "s_store_id"], as_index=False).apply(
        aggs, include_groups=False
    )
    out = out[["s_store_name", "s_store_id"] + [a for _, a in days]]
    _nonempty(check(sess, "q43", out), "q43")


def test_q45(env):
    sess, t = env
    ws, c, ca, d, i = (t["web_sales"], t["customer"], t["customer_address"],
                       t["date_dim"], t["item"])
    m = (
        ws.merge(c, left_on="ws_bill_customer_sk", right_on="c_customer_sk")
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        .merge(i, left_on="ws_item_sk", right_on="i_item_sk")
        .merge(d, left_on="ws_sold_date_sk", right_on="d_date_sk")
    )
    zips = {"85669", "86197", "88274", "83405", "86475", "85392", "85460", "80348", "81792"}
    special = set(i[i.i_item_sk.isin([2, 3, 5, 7, 11, 13, 17, 19, 23, 29])].i_item_id)
    m = m[(m.ca_zip.astype(str).str[:5].isin(zips) | m.i_item_id.isin(special))
          & (m.d_qoy == 2) & (m.d_year == 2001)]
    out = m.groupby(["ca_zip", "ca_city"], as_index=False)["ws_sales_price"].sum()
    out.columns = ["ca_zip", "ca_city", "sum(ws_sales_price)"]
    _nonempty(check(sess, "q45", out), "q45")


def _lag_buckets(lag):
    return pd.Series({
        "30 days ": int((lag <= 30).sum()),
        "31 - 60 days ": int(((lag > 30) & (lag <= 60)).sum()),
        "61 - 90 days ": int(((lag > 60) & (lag <= 90)).sum()),
        "91 - 120 days ": int(((lag > 90) & (lag <= 120)).sum()),
        ">120 days ": int((lag > 120).sum()),
    })


def test_q62(env):
    sess, t = env
    ws, w, sm, web, d = (t["web_sales"], t["warehouse"], t["ship_mode"],
                         t["web_site"], t["date_dim"])
    m = (
        ws.merge(d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk"]],
                 left_on="ws_ship_date_sk", right_on="d_date_sk")
        .merge(w, left_on="ws_warehouse_sk", right_on="w_warehouse_sk")
        .merge(sm, left_on="ws_ship_mode_sk", right_on="sm_ship_mode_sk")
        .merge(web, left_on="ws_web_site_sk", right_on="web_site_sk")
    )
    m = m.assign(wname=m.w_warehouse_name.astype(str).str[:20])
    out = m.groupby(["wname", "sm_type", "web_name"], as_index=False).apply(
        lambda x: _lag_buckets(x.ws_ship_date_sk - x.ws_sold_date_sk),
        include_groups=False,
    )
    # the engine names unaliased expressions by their token-spaced SQL text
    out = out.rename(columns={"wname": "substr ( w_warehouse_name , 1 , 20 )"})
    _nonempty(check(sess, "q62", out), "q62")


def test_q29(env):
    sess, t = env
    ss, sr, cs, d, s, i = (t["store_sales"], t["store_returns"], t["catalog_sales"],
                           t["date_dim"], t["store"], t["item"])
    d1 = d[(d.d_moy == 9) & (d.d_year == 1999)][["d_date_sk"]]
    d2 = d[(d.d_moy >= 9) & (d.d_moy <= 12) & (d.d_year == 1999)][["d_date_sk"]]
    d3 = d[d.d_year.isin([1999, 2000, 2001])][["d_date_sk"]]
    m = (
        ss.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        .merge(sr, left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
               right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
        .merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk")
        .merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
               right_on=["cs_bill_customer_sk", "cs_item_sk"])
        .merge(d3, left_on="cs_sold_date_sk", right_on="d_date_sk")
    )
    g = m.groupby(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
                  as_index=False).agg(
        store_sales_quantity=("ss_quantity", "sum"),
        store_returns_quantity=("sr_return_quantity", "sum"),
        catalog_sales_quantity=("cs_quantity", "sum"),
    )
    _nonempty(check(sess, "q29", g), "q29")


def test_q40(env):
    sess, t = env
    cs, cr, w, i, d = (t["catalog_sales"], t["catalog_returns"], t["warehouse"],
                       t["item"], t["date_dim"])
    pivot = np.datetime64("2000-03-11")
    m = (
        cs.merge(cr[["cr_order_number", "cr_item_sk", "cr_refunded_cash"]],
                 left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"], how="left")
        .merge(w, left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
        .merge(i, left_on="cs_item_sk", right_on="i_item_sk")
        .merge(d, left_on="cs_sold_date_sk", right_on="d_date_sk")
    )
    m = m[(m.i_current_price >= 0.99) & (m.i_current_price <= 1.49)
          & (m.d_date.values >= pivot - np.timedelta64(30, "D"))
          & (m.d_date.values <= pivot + np.timedelta64(30, "D"))]
    net = m.cs_sales_price - m.cr_refunded_cash.fillna(0)
    before = np.where(m.d_date.values < pivot, net, 0.0)
    after = np.where(m.d_date.values >= pivot, net, 0.0)
    g = m.assign(_b=before, _a=after).groupby(["w_state", "i_item_id"], as_index=False).agg(
        sales_before=("_b", "sum"), sales_after=("_a", "sum")
    )
    _nonempty(check(sess, "q40", g), "q40")


def test_q46(env):
    sess, t = env
    ss, d, s, hd, ca, c = (t["store_sales"], t["date_dim"], t["store"],
                           t["household_demographics"], t["customer_address"],
                           t["customer"])
    m = (
        ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    )
    m = m[((m.hd_dep_count == 4) | (m.hd_vehicle_count == 3))
          & m.d_dow.isin([6, 0]) & m.d_year.isin([1999, 2000, 2001])
          & (m.s_city.isin(["Fairview", "Midway"]))]
    dn = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ca_city"],
                   as_index=False).agg(amt=("ss_coupon_amt", "sum"),
                                       profit=("ss_net_profit", "sum"))
    out = (
        dn.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
        .merge(ca.add_prefix("cur_"), left_on="c_current_addr_sk",
               right_on="cur_ca_address_sk")
    )
    out = out[out.cur_ca_city != out.ca_city]
    out = out.rename(columns={"ca_city": "bought_city", "cur_ca_city": "ca_city"})
    _nonempty(check(sess, "q46", out[
        ["c_last_name", "c_first_name", "ca_city", "bought_city",
         "ss_ticket_number", "amt", "profit"]
    ]), "q46")


def test_q50(env):
    sess, t = env
    ss, sr, s, d = (t["store_sales"], t["store_returns"], t["store"], t["date_dim"])
    d2 = d[(d.d_year == 2001) & (d.d_moy == 8)][["d_date_sk"]]
    m = (
        ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk", "sr_customer_sk"])
        .merge(d[["d_date_sk"]], left_on="ss_sold_date_sk", right_on="d_date_sk")
        .merge(d2, left_on="sr_returned_date_sk", right_on="d_date_sk",
               suffixes=("", "_r"))
        .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
    )
    keys = ["s_store_name", "s_company_id", "s_street_number", "s_street_name",
            "s_street_type", "s_suite_number", "s_city", "s_county", "s_state", "s_zip"]
    out = m.groupby(keys, as_index=False, dropna=False).apply(
        lambda x: _lag_buckets(x.sr_returned_date_sk - x.ss_sold_date_sk),
        include_groups=False,
    )
    _nonempty(check(sess, "q50", out), "q50")


def test_q99(env):
    sess, t = env
    cs, w, sm, cc, d = (t["catalog_sales"], t["warehouse"], t["ship_mode"],
                        t["call_center"], t["date_dim"])
    m = (
        cs.merge(d[(d.d_month_seq >= 1200) & (d.d_month_seq <= 1211)][["d_date_sk"]],
                 left_on="cs_ship_date_sk", right_on="d_date_sk")
        .merge(w, left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
        .merge(sm, left_on="cs_ship_mode_sk", right_on="sm_ship_mode_sk")
        .merge(cc, left_on="cs_call_center_sk", right_on="cc_call_center_sk")
    )
    m = m.assign(wname=m.w_warehouse_name.astype(str).str[:20])
    out = m.groupby(["wname", "sm_type", "cc_name"], as_index=False).apply(
        lambda x: _lag_buckets(x.cs_ship_date_sk - x.cs_sold_date_sk),
        include_groups=False,
    )
    out = out.rename(columns={"wname": "substr ( w_warehouse_name , 1 , 20 )"})
    _nonempty(check(sess, "q99", out), "q99")


def test_q90(env):
    sess, t = env
    ws, hd, td, wp = (t["web_sales"], t["household_demographics"], t["time_dim"],
                      t["web_page"])

    m = (
        ws.merge(td, left_on="ws_sold_time_sk", right_on="t_time_sk")
        .merge(hd, left_on="ws_ship_hdemo_sk", right_on="hd_demo_sk")
        .merge(wp, left_on="ws_web_page_sk", right_on="wp_web_page_sk")
    )
    m = m[(m.hd_dep_count == 6) & (m.wp_char_count >= 5000) & (m.wp_char_count <= 5200)]

    def bucket(hlo, hhi):
        return len(m[(m.t_hour >= hlo) & (m.t_hour <= hhi)])

    amc, pmc = bucket(8, 9), bucket(19, 20)
    ratio = amc / pmc if pmc else np.nan
    check(sess, "q90", pd.DataFrame({"am_pm_ratio": [ratio]}))
