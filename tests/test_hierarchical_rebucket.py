"""Hierarchical (DCN x ICI) re-bucketing over a 2-D virtual mesh
(SURVEY.md §5.8: cross-slice traffic must cross the slow link exactly once).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hyperspace_tpu.ops.bucketize import rebucket, rebucket_hierarchical  # noqa: E402
from hyperspace_tpu.parallel.mesh import make_mesh, make_mesh_2d, sharded, sharded_2d  # noqa: E402


@pytest.fixture(scope="module")
def mesh2d():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh_2d(n_slices=2, per_slice=4)


def _inputs(mesh, n_rows, num_buckets, seed=0):
    rng = np.random.default_rng(seed)
    sh = sharded_2d(mesh)
    keys = rng.integers(0, 10_000, n_rows).astype(np.int64)
    vals = rng.standard_normal(n_rows)
    buckets = (keys % num_buckets).astype(np.int32)
    return (
        jax.device_put(buckets, sh),
        {"k": jax.device_put(keys, sh), "v": jax.device_put(vals, sh)},
        keys,
        vals,
        buckets,
    )


class TestHierarchicalRebucket:
    def test_rows_land_on_owner_device(self, mesh2d):
        n_dev = 8
        n = 64 * n_dev
        num_buckets = 32
        b_dev, arrays, keys, vals, buckets = _inputs(mesh2d, n, num_buckets)
        out, out_b, valid, overflow = rebucket_hierarchical(mesh2d, arrays, b_dev, 3 * 64, 3 * 64)
        assert int(jnp.sum(overflow)) == 0
        assert int(jnp.sum(valid)) == n, "row count conserved"

        vb = np.asarray(out_b)
        vm = np.asarray(valid)
        per_dev = vb.reshape(n_dev, -1)
        per_mask = vm.reshape(n_dev, -1)
        # global device order of the (2, 4) mesh is row-major: g = s * 4 + l
        for g in range(n_dev):
            owned = per_dev[g][per_mask[g]]
            assert np.all(owned % n_dev == g), f"device {g} got foreign buckets"

    def test_matches_flat_rebucket_multiset(self, mesh2d):
        """The hierarchical exchange must deliver exactly the same multiset of
        (bucket, key, value) rows per owner as the single-phase one."""
        n_dev = 8
        n = 32 * n_dev
        num_buckets = 16
        b_dev, arrays, keys, vals, buckets = _inputs(mesh2d, n, num_buckets, seed=7)
        out_h, b_h, valid_h, of_h = rebucket_hierarchical(mesh2d, arrays, b_dev, 3 * 32, 3 * 32)
        assert int(jnp.sum(of_h)) == 0

        flat_mesh = make_mesh()
        sh1 = sharded(flat_mesh)
        arrays1 = {"k": jax.device_put(keys, sh1), "v": jax.device_put(vals, sh1)}
        b1 = jax.device_put(buckets, sh1)
        out_f, b_f, valid_f, of_f = rebucket(flat_mesh, arrays1, b1, 3 * 32)
        assert int(jnp.sum(of_f)) == 0

        def rowset(out, b, valid):
            m = np.asarray(valid)
            return sorted(
                zip(
                    np.asarray(b)[m].tolist(),
                    np.asarray(out["k"])[m].tolist(),
                    np.asarray(out["v"])[m].tolist(),
                )
            )

        assert rowset(out_h, b_h, valid_h) == rowset(out_f, b_f, valid_f)

    def test_overflow_detected(self, mesh2d):
        n = 64 * 8
        # all rows to one bucket -> one owner; tiny capacity must overflow
        buckets = np.zeros(n, dtype=np.int32)
        sh = sharded_2d(mesh2d)
        arrays = {"k": jax.device_put(np.arange(n, dtype=np.int64), sh)}
        b_dev = jax.device_put(buckets, sh)
        _, _, _, overflow = rebucket_hierarchical(mesh2d, arrays, b_dev, 4, 4)
        assert int(jnp.sum(overflow)) > 0
