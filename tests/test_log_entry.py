"""Metadata-model unit tests (ref: src/test/scala/.../index/IndexLogEntryTest.scala,
FileIdTrackerTest.scala)."""

import os

import pytest

from hyperspace_tpu import config as C
from hyperspace_tpu.models.log_entry import (
    Content,
    DerivedDataset,
    Directory,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    Storage,
    Update,
)


def fi(name, size=10, mtime=100, fid=C.UNKNOWN_FILE_ID):
    return FileInfo(name, size, mtime, fid)


class TestFileInfo:
    def test_equality_ignores_id(self):
        assert fi("/a/b", 1, 2, 5) == fi("/a/b", 1, 2, 9)
        assert hash(fi("/a/b", 1, 2, 5)) == hash(fi("/a/b", 1, 2, 9))
        assert fi("/a/b", 1, 2) != fi("/a/b", 1, 3)

    def test_roundtrip(self):
        f = fi("/a/b/c.parquet", 123, 456, 7)
        assert FileInfo.from_dict(f.to_dict()) == f
        assert FileInfo.from_dict(f.to_dict()).file_id == 7


class TestContentTree:
    def test_from_leaf_files_reconstructs_paths(self):
        files = [fi("/data/t/p1.parquet"), fi("/data/t/sub/p2.parquet"), fi("/data/u/p3.parquet")]
        content = Content.from_leaf_files(files)
        assert sorted(content.files) == sorted(os.path.abspath(f.name) for f in files)

    def test_file_infos_preserve_metadata(self):
        files = [fi("/data/t/p1.parquet", 11, 22, 3)]
        out = Content.from_leaf_files(files).file_infos()
        assert out == files
        assert out[0].size == 11 and out[0].modified_time == 22 and out[0].file_id == 3

    def test_from_directory_empty_or_nonexistent(self, tmp_path):
        """(ref: IndexLogEntryTest:363-384 'fromDirectory where the directory
        is empty or nonexistent')"""
        from hyperspace_tpu.models.log_entry import Content

        empty = tmp_path / "empty"
        empty.mkdir()
        c1 = Content.from_directory(str(empty))
        assert c1.files == [] and c1.total_size == 0
        c2 = Content.from_directory(str(tmp_path / "nope"))
        assert c2.files == []

    def test_from_leaf_files_gap_in_directories(self):
        """A file under a/b/c with no files in a or a/b keeps the full path
        (ref: IndexLogEntryTest:442-527 'gap in directories')."""
        from hyperspace_tpu.models.log_entry import Content, FileInfo

        c = Content.from_leaf_files(
            [
                FileInfo("/a/b/c/f1.parquet", 10, 1, 0),
                FileInfo("/a/g.parquet", 20, 2, 1),
            ]
        )
        assert sorted(c.files) == ["/a/b/c/f1.parquet", "/a/g.parquet"]
        infos = {f.name: f for f in c.file_infos()}
        assert infos["/a/b/c/f1.parquet"].size == 10
        assert infos["/a/g.parquet"].file_id == 1

    def test_from_directory_excludes_hidden_and_underscore(self, tmp_path):
        """PathFilter parity: dot- and underscore-prefixed entries never enter
        the tree (ref: IndexLogEntryTest:385-441 pathfilter)."""
        from hyperspace_tpu.models.log_entry import Content

        d = tmp_path / "pf"
        d.mkdir()
        (d / "ok.parquet").write_bytes(b"x" * 4)
        (d / ".hidden").write_bytes(b"y")
        (d / "_SUCCESS").write_bytes(b"")
        (d / "_hyperspace_log").mkdir()
        (d / "_hyperspace_log" / "0").write_bytes(b"{}")
        c = Content.from_directory(str(d))
        assert [os.path.basename(f) for f in c.files] == ["ok.parquet"]

    def test_source_listing_skips_hidden_directories(self, tmp_path, session):
        """Meta directories nested INSIDE a data dir (.cache/, _checkpoints/)
        must not reach scans or index builds (DataPathFilter parity at the
        source level, not just the index-content level)."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        d = tmp_path / "src"
        d.mkdir()
        pq.write_table(
            pa.table({"k": np.arange(10, dtype=np.int64)}), d / "ok.parquet"
        )
        (d / ".cache").mkdir()
        (d / ".cache" / "x.parquet").write_bytes(b"junk")
        (d / "_chk").mkdir()
        (d / "_chk" / "0").write_bytes(b"junk")
        df = session.read_parquet(str(d))
        files = [fi.name for fi in df.plan.relation.all_file_infos()]
        assert [os.path.basename(f) for f in files] == ["ok.parquet"]
        assert df.collect()["k"].shape[0] == 10

    def test_merge_overlapping_directories(self):
        """(ref: IndexLogEntryTest:566-620 'merge works as expected when
        directories overlap')"""
        from hyperspace_tpu.models.log_entry import Content, FileInfo

        a = Content.from_leaf_files(
            [FileInfo("/r/x/f1", 1, 1, 0), FileInfo("/r/y/f2", 2, 2, 1)]
        )
        b = Content.from_leaf_files(
            [FileInfo("/r/x/f3", 3, 3, 2), FileInfo("/r/z/f4", 4, 4, 3)]
        )
        m = a.merge(b)
        assert sorted(m.files) == ["/r/x/f1", "/r/x/f3", "/r/y/f2", "/r/z/f4"]
        assert m.total_size == 10
        # a file present in BOTH trees is kept once
        m2 = a.merge(a)
        assert sorted(m2.files) == sorted(a.files)

    def test_merge_unions_files(self):
        a = Content.from_leaf_files([fi("/d/x/1"), fi("/d/x/2")])
        b = Content.from_leaf_files([fi("/d/x/2"), fi("/d/y/3")])
        merged = a.merge(b)
        assert sorted(merged.files) == ["/d/x/1", "/d/x/2", "/d/y/3"]

    def test_merge_mismatched_roots_raises(self):
        with pytest.raises(ValueError):
            Directory("a").merge(Directory("b"))

    def test_roundtrip(self):
        c = Content.from_leaf_files([fi("/d/x/1", 5, 6, 7), fi("/d/y/z/2", 8, 9, 10)])
        assert Content.from_dict(c.to_dict()).to_dict() == c.to_dict()

    def test_total_size(self):
        c = Content.from_leaf_files([fi("/d/1", 5), fi("/d/2", 8)])
        assert c.total_size == 13

    def test_from_directory_skips_hidden_and_meta(self, tmp_path):
        (tmp_path / "a.parquet").write_bytes(b"xx")
        (tmp_path / "_log").write_bytes(b"xx")
        (tmp_path / ".hidden").write_bytes(b"xx")
        tracker = FileIdTracker()
        c = Content.from_directory(str(tmp_path), tracker)
        assert [os.path.basename(p) for p in c.files] == ["a.parquet"]
        assert all(f.file_id == 0 for f in c.file_infos())


class TestFileIdTracker:
    def test_monotonic_ids(self):
        t = FileIdTracker()
        assert t.add_file(fi("/a", 1, 1)) == 0
        assert t.add_file(fi("/b", 1, 1)) == 1
        assert t.add_file(fi("/a", 1, 1)) == 0  # stable
        assert t.max_id == 1

    def test_conflicting_known_id_raises(self):
        t = FileIdTracker()
        t.add_file(fi("/a", 1, 1))
        with pytest.raises(ValueError):
            t.add_file(fi("/a", 1, 1, fid=42))

    def test_known_ids_are_honored(self):
        t = FileIdTracker()
        t.add_file(fi("/a", 1, 1, fid=10))
        assert t.max_id == 10
        assert t.add_file(fi("/b", 1, 1)) == 11


def make_entry(name="idx1", state="ACTIVE", files=None):
    files = files or [fi("/src/t/p1.parquet", 100, 1, 0), fi("/src/t/p2.parquet", 200, 2, 1)]
    rel = Relation(
        root_paths=["/src/t"],
        data=Storage(Content.from_leaf_files(files)),
        schema_json='{"fields": []}',
        file_format="parquet",
        options={},
    )
    return IndexLogEntry(
        name=name,
        derived_dataset=DerivedDataset("CoveringIndex", {"indexedColumns": ["c1"], "includedColumns": ["c2"]}),
        content=Content.from_leaf_files([fi("/idx/v__=0/b0.parquet", 50, 3)]),
        source=Source(rel, LogicalPlanFingerprint([Signature("FileBasedSignatureProvider", "abc123")])),
        properties={},
        state=state,
    )


class TestIndexLogEntry:
    def test_json_roundtrip(self):
        e = make_entry()
        e2 = IndexLogEntry.from_json(e.to_json())
        assert e2 == e
        assert e2.kind == "CoveringIndex"
        assert e2.signature.signatures[0].value == "abc123"
        assert [f.name for f in e2.source_file_infos()] == ["/src/t/p1.parquet", "/src/t/p2.parquet"]
        assert e2.source_files_size() == 300

    def test_copy_with_update_records_hybrid_scan_delta(self):
        e = make_entry()
        appended = [fi("/src/t/p3.parquet", 300, 3)]
        deleted = [fi("/src/t/p1.parquet", 100, 1, 0)]
        e2 = e.copy_with_update(appended, deleted)
        assert [f.name for f in e2.appended_files()] == ["/src/t/p3.parquet"]
        assert [f.name for f in e2.deleted_files()] == ["/src/t/p1.parquet"]
        # original untouched
        assert e.appended_files() == []
        # survives serialization
        e3 = IndexLogEntry.from_json(e2.to_json())
        assert [f.name for f in e3.deleted_files()] == ["/src/t/p1.parquet"]

    def test_tags_are_transient(self):
        e = make_entry()
        e.set_tag("plan1", "FILTER_REASONS", ["x"])
        assert e.get_tag("plan1", "FILTER_REASONS") == ["x"]
        assert e.get_tag("plan2", "FILTER_REASONS") is None
        e2 = IndexLogEntry.from_json(e.to_json())
        assert e2.tags == {}

    def test_file_id_tracker_reconstruction(self):
        e = make_entry()
        t = e.file_id_tracker()
        assert t.get_file_id(("/src/t/p1.parquet", 100, 1)) == 0
        assert t.get_file_id(("/src/t/p2.parquet", 200, 2)) == 1
        assert t.max_id == 1
