"""Scale-out serving fabric tests (hyperspace_tpu/fabric/): lake-persisted
commit records, the commit watcher's cross-process cache coherence (including
the two-Sessions staleness regression and Lamport sequence agreement), the
coherence sidecar's quarantine/SLO/rate-limit sharing, the torn-pin seqlock
in QueryServer.submit, the FrontDoor router + WorkerEndpoint HTTP shim, and
the default-off byte-identity guarantee. The multi-process endurance variant
rides at the bottom behind the ``soak`` marker."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu import config as C
from hyperspace_tpu.fabric import records
from hyperspace_tpu.fabric import lease as lease_mod
from hyperspace_tpu.fabric.fsck import fsck, main as fsck_main
from hyperspace_tpu.fabric.frontdoor import (
    FrontDoor,
    WorkerEndpoint,
    WorkerError,
    WorkerUnavailable,
    merge_prometheus_texts,
    rendezvous_order,
    rendezvous_pick,
)
from hyperspace_tpu.fabric.health import HealthTracker
from hyperspace_tpu.fabric.lease import LeaseLostError, fence_scope
from hyperspace_tpu.lifecycle import CommitEvent, RefreshManager, SnapshotHandle
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.reliability.degrade import QUARANTINE
from hyperspace_tpu.reliability.faults import FaultRule, fault_scope
from hyperspace_tpu.serving import QueryServer

from tests.test_lifecycle import write_marked_part

pytestmark = pytest.mark.fabric

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def counter_value(name, **labels):
    return REGISTRY.counter(name, **labels).value


def fabric_conf(sys_path, node, **extra):
    """Fabric-on session conf with deterministic (manually-driven) loops:
    the watcher thread is off and the sidecar interval is effectively
    infinite, so tests call poll_once()/run_once() themselves."""
    conf = {
        hst.keys.SYSTEM_PATH: sys_path,
        hst.keys.FABRIC_ENABLED: True,
        hst.keys.FABRIC_NODE_ID: node,
        hst.keys.FABRIC_WATCHER_ENABLED: False,
        hst.keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 3600,
    }
    conf.update(extra)
    return conf


@pytest.fixture()
def data_root(tmp_path):
    root = tmp_path / "fabric_data"
    root.mkdir()
    for i in range(3):
        write_marked_part(str(root), i)
    return str(root)


@pytest.fixture()
def two_nodes(tmp_system_path, data_root):
    """Two fabric Sessions on one lake, s1 holding index ``fabIdx``; both
    drained (the create's commit record already replayed into s2)."""
    s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
    hst.Hyperspace(s1).create_index(
        s1.read_parquet(data_root), hst.CoveringIndexConfig("fabIdx", ["c1"], ["m"])
    )
    s2 = hst.Session(conf=fabric_conf(tmp_system_path, "n2"))
    s2.fabric.watcher.poll_once()
    yield s1, s2
    s2.fabric.stop()
    s1.fabric.stop()


# --- commit records (pure file-protocol units) -------------------------------


class TestCommitRecords:
    def test_append_read_round_trip_and_ordering(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxA", 4, "refresh-incremental", ("f1", "f2"), origin="n1")
        assert records.append_commit_record(sp, ev, seq=7) == 0
        assert records.append_commit_record(sp, ev, seq=8) == 1
        cdir = records.commits_dir(sp, "idxA")
        got = records.read_commit_records(cdir)
        assert [rid for rid, _ in got] == [0, 1]
        rec = got[0][1]
        assert rec["seq"] == 7 and rec["origin"] == "n1"
        assert rec["index"] == "idxA" and rec["logId"] == 4
        assert rec["kind"] == "refresh-incremental"
        assert rec["affectedFiles"] == ["f1", "f2"]
        assert rec["ts"] > 0

    def test_read_after_cursor(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxB", 1, "create", origin="n1")
        for seq in (1, 2, 3):
            records.append_commit_record(sp, ev, seq=seq)
        cdir = records.commits_dir(sp, "idxB")
        assert [rid for rid, _ in records.read_commit_records(cdir, after_id=1)] == [2]

    def test_exclusive_claim_skips_taken_slot(self, tmp_path):
        sp = str(tmp_path)
        cdir = records.commits_dir(sp, "idxC")
        os.makedirs(cdir)
        # a concurrent publisher already holds slot 0
        with open(os.path.join(cdir, f"{0:010d}"), "w") as f:
            f.write("{}")
        ev = CommitEvent("idxC", 1, "create", origin="n1")
        assert records.append_commit_record(sp, ev, seq=1) == 1

    def test_corrupt_record_skipped_and_counted(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxD", 1, "create", origin="n1")
        records.append_commit_record(sp, ev, seq=1)
        cdir = records.commits_dir(sp, "idxD")
        with open(os.path.join(cdir, f"{1:010d}"), "w") as f:
            f.write("not json {")
        before = counter_value("hs_fabric_record_errors_total", op="commit-read")
        got = records.read_commit_records(cdir)
        assert [rid for rid, _ in got] == [0]
        assert counter_value("hs_fabric_record_errors_total", op="commit-read") == before + 1

    def test_node_files_exclude_self(self, tmp_path):
        sp = str(tmp_path)
        assert records.write_node_file(sp, "n1", {"strikes": {"i": 2}})
        assert records.write_node_file(sp, "n2", {"strikes": {"i": 5}})
        peers = records.read_peer_node_files(sp, "n1")
        assert list(peers) == ["n2"]
        assert peers["n2"]["strikes"] == {"i": 5}
        assert peers["n2"]["origin"] == "n2" and peers["n2"]["updatedAt"] > 0

    def test_node_id_is_filesystem_safe(self):
        assert records._safe_name("host:123/x") == "host_123_x"

    def test_fabric_paths_invisible_to_data_listing(self, tmp_path):
        from hyperspace_tpu.utils.file_utils import walk_data_files

        sp = str(tmp_path)
        records.append_commit_record(
            sp, CommitEvent("idxE", 1, "create", origin="n1"), seq=1
        )
        records.write_node_file(sp, "n1", {})
        assert list(walk_data_files(sp)) == []


# --- bus persistence + replay ------------------------------------------------


class TestBusPersistence:
    def test_defaults_publish_no_records_and_no_fabric(self, session, data_root):
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("offIdx", ["c1"], ["m"]),
        )
        assert session.fabric is None
        assert not os.path.exists(
            records.commits_dir(session.conf.system_path, "offIdx")
        )
        assert not os.path.exists(
            os.path.join(session.conf.system_path, records.FABRIC_DIR)
        )

    def test_publish_persists_stamped_record(self, two_nodes):
        s1, _ = two_nodes
        cdir = records.commits_dir(s1.conf.system_path, "fabIdx")
        got = records.read_commit_records(cdir)
        assert len(got) == 1
        rec = got[0][1]
        assert rec["kind"] == "create" and rec["origin"] == "n1"
        assert rec["seq"] == s1.lifecycle_bus.commit_seq

    def test_replay_is_a_lamport_merge_and_never_persists(self, two_nodes):
        s1, s2 = two_nodes
        bus = s2.lifecycle_bus
        base = bus.commit_seq
        ev = CommitEvent("fabIdx", None, "refresh-quick", origin="n3")
        bus.replay(ev, seq=base + 10)  # remote clock ahead: jump to it
        assert bus.commit_seq == base + 10
        bus.replay(ev, seq=base + 2)  # remote clock behind: still advance
        assert bus.commit_seq == base + 11
        bus.replay(ev)  # record without a seq
        assert bus.commit_seq == base + 12
        # replay never writes records (no echo back into the lake)
        cdir = records.commits_dir(s2.conf.system_path, "fabIdx")
        assert len(records.read_commit_records(cdir)) == 1

    def test_processes_agree_on_commit_seq(self, two_nodes, data_root):
        s1, s2 = two_nodes
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        assert s2.lifecycle_bus.commit_seq < s1.lifecycle_bus.commit_seq
        s2.fabric.watcher.poll_once()
        assert s2.lifecycle_bus.commit_seq == s1.lifecycle_bus.commit_seq


# --- the commit watcher ------------------------------------------------------


class TestCommitWatcher:
    def test_remote_commit_replays_and_purges(self, two_nodes, data_root):
        s1, s2 = two_nodes
        roster0 = counter_value("hs_lifecycle_invalidations_total", cache="roster")
        replay0 = counter_value(
            "hs_fabric_records_replayed_total", kind="refresh-incremental"
        )
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        assert s2.fabric.watcher.poll_once() == 1
        assert (
            counter_value("hs_fabric_records_replayed_total", kind="refresh-incremental")
            == replay0 + 1
        )
        # the replay ran the full invalidation path (roster TTL clear)
        assert (
            counter_value("hs_lifecycle_invalidations_total", cache="roster")
            >= roster0 + 1
        )

    def test_own_records_are_skipped(self, two_nodes):
        s1, _ = two_nodes
        skips0 = counter_value("hs_fabric_self_skips_total")
        assert s1.fabric.watcher.poll_once() == 0
        assert counter_value("hs_fabric_self_skips_total") == skips0 + 1

    def test_idle_polls_hit_the_mtime_fast_path(self, two_nodes):
        _, s2 = two_nodes
        w = s2.fabric.watcher
        assert w.poll_once() == 0  # drained by the fixture; records cursor
        # age the directory out of the settle window so the fast path is
        # eligible (fresh dirs are always re-listed; see _MTIME_SETTLE_S)
        cdir = records.commits_dir(s2.conf.system_path, "fabIdx")
        old = time.time() - 60
        os.utime(cdir, (old, old))
        w.poll_once()  # observes the aged mtime
        skips0 = counter_value("hs_fabric_poll_skips_total")
        assert w.poll_once() == 0
        assert counter_value("hs_fabric_poll_skips_total") == skips0 + 1

    @pytest.mark.parametrize(
        "watcher_on",
        [
            pytest.param(
                False,
                marks=pytest.mark.xfail(
                    strict=True,
                    reason="without the commit watcher a peer's refresh is "
                    "invisible until the roster TTL (300 s) expires: new "
                    "pins keep serving the superseded index version",
                ),
            ),
            pytest.param(True),
        ],
    )
    def test_two_sessions_staleness_regression(self, two_nodes, data_root, watcher_on):
        """The tentpole regression: process B must pin the version process A
        committed — with the watcher within one poll, without it not until
        TTL expiry (encoded as strict xfail)."""
        s1, s2 = two_nodes
        v1 = SnapshotHandle.capture(s2).index_version("fabIdx")  # primes TTL cache
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        v2 = SnapshotHandle.capture(s1).index_version("fabIdx")
        assert v2 != v1
        if watcher_on:
            assert s2.fabric.watcher.poll_once() >= 1
        assert SnapshotHandle.capture(s2).index_version("fabIdx") == v2

    def test_remote_quarantine_trip_opens_local_breaker(
        self, tmp_system_path, data_root
    ):
        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root), hst.CoveringIndexConfig("qIdx", ["c1"], ["m"])
        )
        # constructed last so the process-global registry binds to s2
        s2 = hst.Session(
            conf=fabric_conf(
                tmp_system_path, "n2", **{hst.keys.RELIABILITY_QUARANTINE_ENABLED: True}
            )
        )
        s2.fabric.watcher.poll_once()
        try:
            assert QUARANTINE.state_of("qIdx") == "closed"
            # n1's breaker trips: degrade.py publishes this event on n1's bus
            s1.lifecycle_bus.publish(CommitEvent("qIdx", None, "quarantine"))
            merged0 = counter_value("hs_fabric_quarantine_merged_total", index="qIdx")
            assert s2.fabric.watcher.poll_once() == 1
            assert QUARANTINE.state_of("qIdx") == "open"
            assert (
                counter_value("hs_fabric_quarantine_merged_total", index="qIdx")
                == merged0 + 1
            )
        finally:
            s2.fabric.stop()
            s1.fabric.stop()


# --- fast two-process-shaped coherence loop (tier-1) -------------------------


class TestCoherenceRoundLoop:
    def test_refresh_rounds_stay_fresh_under_polling(self, two_nodes, data_root):
        s1, s2 = two_nodes
        rm = RefreshManager(s1)
        s2.enable_hyperspace()
        for marker in (3, 4, 5):
            write_marked_part(data_root, marker)
            assert rm.refresh_index("fabIdx", "incremental") == "committed"
            assert s2.fabric.watcher.poll_once() == 1
            q = s2.read_parquet(data_root).filter(hst.col("c1") >= 0).select("m")
            seen = sorted(np.unique(q.collect()["m"]).tolist())
            assert seen == list(range(marker + 1)), f"stale after marker {marker}"
            assert (
                SnapshotHandle.capture(s2).index_version("fabIdx")
                == SnapshotHandle.capture(s1).index_version("fabIdx")
            )


# --- torn-pin seqlock in QueryServer.submit ----------------------------------


class TestTornPinSeqlock:
    def test_commit_racing_capture_forces_recapture(
        self, tmp_system_path, data_root, monkeypatch
    ):
        session = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("tornIdx", ["c1"], ["m"]),
        )
        session.enable_hyperspace()
        real_capture = SnapshotHandle.capture
        raced = {"n": 0}

        def racing_capture(sess):
            h = real_capture(sess)
            if raced["n"] == 0:
                raced["n"] += 1
                # a commit lands between the roster read and admission:
                # the captured handle is torn (its seq predates the commit)
                sess.lifecycle_bus.publish(
                    CommitEvent("tornIdx", None, "refresh-quick")
                )
            return h

        monkeypatch.setattr(SnapshotHandle, "capture", staticmethod(racing_capture))
        try:
            with QueryServer(session, workers=1, name="qsTorn") as srv:
                retries0 = counter_value(
                    "hs_fabric_snapshot_retries_total", server="qsTorn"
                )
                q = session.read_parquet(data_root).filter(hst.col("c1") >= 0).select("m")
                res = srv.query(q)
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                assert (
                    counter_value("hs_fabric_snapshot_retries_total", server="qsTorn")
                    == retries0 + 1
                )
                assert raced["n"] == 1  # exactly one re-capture healed the pin
        finally:
            session.fabric.stop()


# --- coherence sidecar -------------------------------------------------------


class _FakeServer:
    """Duck-typed QueryServer stand-in: just the accounting surfaces the
    sidecar publishes from and merges into."""

    def __init__(self, slo=None, admission=None):
        self.slo = slo
        self.admission = admission


class TestCoherenceSidecar:
    def test_publish_then_peer_merge_round_trip(self, tmp_system_path):
        from hyperspace_tpu.obs.slo import SloTracker
        from hyperspace_tpu.serving.scheduler import CostAwareScheduler

        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        try:
            tracker = SloTracker(target_ms=100.0)
            sched = CostAwareScheduler(
                depth=16, default_timeout=None, tenant_rate=1.0, tenant_burst=10.0
            )
            fake = _FakeServer(slo=tracker, admission=sched)
            side = s1.fabric.sidecar
            side.attach_server(fake)
            tracker.record(0.01)  # good
            tracker.record(9.0)  # bad (over target)
            assert side.publish_once()
            mine = json.load(
                open(os.path.join(records.nodes_dir(tmp_system_path), "n1.json"))
            )
            assert mine["slo"]["default"] == {"good": 1, "bad": 1}

            # a peer's ledger lands in the lake; merging folds the deltas in
            records.write_node_file(
                tmp_system_path,
                "peer",
                {"slo": {"default": {"good": 0, "bad": 30}}, "drained": {"default": 5.0}},
            )
            assert side.merge_once() == 1
            # remote bad events now dominate the local burn window
            assert tracker.burn_rate(300.0) > 1.0
            good, bad = tracker._window_counts(tracker._tenant("default"), 300.0)
            assert (good, bad) == (1, 31)
            # remote drain debited the local bucket
            st = sched._tenants.get("default")
            assert st is not None and st.bucket.tokens <= st.bucket.burst - 5.0

            # re-merging an unchanged peer file is a no-op (delta semantics)
            side.merge_once()
            good2, bad2 = tracker._window_counts(tracker._tenant("default"), 300.0)
            assert (good2, bad2) == (good, bad)
        finally:
            s1.fabric.stop()

    def test_local_publish_ledger_excludes_remote_events(self):
        from hyperspace_tpu.obs.slo import SloTracker

        tracker = SloTracker(target_ms=100.0)
        tracker.record(0.01)
        tracker.note_remote("default", good=10, bad=10)
        # counts() is what the sidecar publishes: remote merges must never
        # echo back out, or peers would snowball each other's numbers
        assert tracker.counts() == {"default": (1, 0)}

    def test_remote_strikes_cross_local_threshold(self, tmp_system_path, data_root):
        s1 = hst.Session(
            conf=fabric_conf(
                tmp_system_path,
                "n1",
                **{
                    hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
                    hst.keys.RELIABILITY_QUARANTINE_THRESHOLD: 3,
                },
            )
        )
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root), hst.CoveringIndexConfig("strIdx", ["c1"], ["m"])
        )
        try:
            # one local strike: below threshold, breaker stays closed
            idx_file = os.path.join(tmp_system_path, "strIdx", "anyfile")
            QUARANTINE.note_corrupt(idx_file)
            assert QUARANTINE.state_of("strIdx") == "closed"
            assert QUARANTINE.local_strikes() == {"strIdx": 1}
            # two more strikes arrive from a peer: 1 + 2 crosses the threshold
            records.write_node_file(tmp_system_path, "peer", {"strikes": {"strIdx": 2}})
            s1.fabric.sidecar.merge_once()
            assert QUARANTINE.state_of("strIdx") == "open"
            # the merged remote count is never re-published as ours
            assert QUARANTINE.local_strikes() == {"strIdx": 1}
        finally:
            s1.fabric.stop()

    def test_external_drain_floors_at_empty(self):
        from hyperspace_tpu.serving.scheduler import TokenBucket

        b = TokenBucket(rate=1.0, burst=4.0)
        b.drain(2.5)
        assert b.tokens == pytest.approx(1.5)
        b.drain(100.0)  # a peer's burst can empty the bucket, never owe debt
        assert b.tokens == 0.0


# --- FrontDoor + WorkerEndpoint ----------------------------------------------


class TestFrontDoor:
    def test_rendezvous_stable_under_membership_permutation(self):
        nodes = ["qs0", "qs1", "qs2", "qs3"]
        for t in range(40):
            key = f"tenant-{t}"
            assert rendezvous_pick(key, nodes) == rendezvous_pick(key, nodes[::-1])

    def test_rendezvous_moves_only_departed_workers_tenants(self):
        nodes = ["qs0", "qs1", "qs2", "qs3"]
        tenants = [f"tenant-{t}" for t in range(60)]
        before = {t: rendezvous_pick(t, nodes) for t in tenants}
        after = {t: rendezvous_pick(t, nodes[:-1]) for t in tenants}
        moved = [t for t in tenants if before[t] != after[t]]
        assert moved and all(before[t] == "qs3" for t in moved)
        assert len(set(before.values())) == 4  # all workers get traffic

    def test_rendezvous_rejects_empty_membership(self):
        with pytest.raises(ValueError):
            rendezvous_pick("t", [])

    def test_merge_prometheus_texts_one_header_per_family(self):
        merged = merge_prometheus_texts(
            [
                '# HELP hs_x doc\n# TYPE hs_x counter\nhs_x{server="qs0"} 1\n',
                '# HELP hs_x doc\n# TYPE hs_x counter\nhs_x{server="qs1"} 2\n',
            ]
        )
        lines = merged.splitlines()
        assert lines.count("# HELP hs_x doc") == 1
        assert lines.count("# TYPE hs_x counter") == 1
        assert 'hs_x{server="qs0"} 1' in lines and 'hs_x{server="qs1"} 2' in lines

    def test_in_process_routing_and_aggregation(self, session, data_root):
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("fdIdx", ["c1"], ["m"]),
        )
        session.enable_hyperspace()
        session.register_view("t", session.read_parquet(data_root))
        with QueryServer(session, workers=1, name="qsA") as a, QueryServer(
            session, workers=1, name="qsB"
        ) as b:
            fd = FrontDoor([a, b])
            assert fd.worker_ids == ["qsA", "qsB"]
            routed0 = {
                w: counter_value("hs_fabric_frontdoor_requests_total", worker=w)
                for w in fd.worker_ids
            }
            picks = set()
            for t in range(8):
                tenant = f"tenant-{t}"
                res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=tenant)
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                picks.add(fd.pick(tenant))
            assert picks == {"qsA", "qsB"}  # both workers took traffic
            routed = sum(
                counter_value("hs_fabric_frontdoor_requests_total", worker=w)
                - routed0[w]
                for w in fd.worker_ids
            )
            assert routed == 8
            merged = fd.metrics_text()
            assert 'server="qsA"' in merged and 'server="qsB"' in merged
            assert sorted(fd.statusz()) == ["qsA", "qsB"]

    def test_worker_endpoint_http_round_trip(self, session, data_root):
        session.enable_hyperspace()
        session.register_view("t", session.read_parquet(data_root))
        with QueryServer(session, workers=1, name="qsHttp") as srv:
            with WorkerEndpoint(srv) as ep:
                fd = FrontDoor([ep.url])
                res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant="alice")
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                assert 'server="qsHttp"' in fd.metrics_text()
                with urllib.request.urlopen(f"{ep.url}/healthz", timeout=30) as r:
                    health = json.loads(r.read().decode("utf-8"))
                # the liveness body carries what stale-worker detection
                # needs: queue depth, last-applied commit_seq, uptime
                assert health["ok"] is True and health["server"] == "qsHttp"
                assert health["queueDepth"] == 0
                assert health["commitSeq"] == session.lifecycle_bus.commit_seq
                assert health["uptimeSeconds"] >= 0.0
                # missing sql -> 400 with a typed error body
                try:
                    urllib.request.urlopen(f"{ep.url}/query", timeout=30)
                    assert False, "expected HTTP 400"
                except urllib.error.HTTPError as exc:
                    assert exc.code == 400
                    body = json.loads(exc.read().decode("utf-8"))
                    assert body["retryable"] is False
                # a failing query surfaces as a routed RuntimeError
                with pytest.raises(RuntimeError, match="failed"):
                    fd.query("SELECT nope FROM missing_table")


# --- lake leases + fencing tokens --------------------------------------------


class FakeClock:
    """Injected-clock stand-in: tests move ``t`` explicitly."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t


class TestLease:
    def test_acquire_busy_and_state(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock()
        acquired0 = counter_value("hs_fabric_lease_acquires_total", outcome="acquired")
        busy0 = counter_value("hs_fabric_lease_acquires_total", outcome="busy")
        l1 = lease_mod.acquire(sp, "refresh/idx", "n1", ttl_s=10.0, clock=clk)
        assert l1 is not None and l1.token == 1
        assert l1.expires_at == pytest.approx(110.0)
        assert (
            counter_value("hs_fabric_lease_acquires_total", outcome="acquired")
            == acquired0 + 1
        )
        # a live lease rejects every other claimant
        assert lease_mod.acquire(sp, "refresh/idx", "n2", ttl_s=10.0, clock=clk) is None
        assert counter_value("hs_fabric_lease_acquires_total", outcome="busy") == busy0 + 1
        current, state = lease_mod.read_state(sp, "refresh/idx")
        assert current == 1 and state["holder"] == "n1"

    def test_renewal_extends_expiry(self, tmp_path):
        clk = FakeClock()
        l1 = lease_mod.acquire(str(tmp_path), "r", "n1", ttl_s=10.0, clock=clk)
        clk.t = 105.0
        ok0 = counter_value("hs_fabric_lease_renewals_total", outcome="ok")
        assert l1.renew() is True
        assert l1.expires_at == pytest.approx(115.0)
        assert counter_value("hs_fabric_lease_renewals_total", outcome="ok") == ok0 + 1

    def test_expiry_takeover_fences_the_zombie(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock()
        l1 = lease_mod.acquire(sp, "r", "n1", ttl_s=10.0, clock=clk)
        clk.t = 111.0  # past expiry: the holder stopped renewing (crashed)
        takeover0 = counter_value("hs_fabric_lease_acquires_total", outcome="takeover")
        l2 = lease_mod.acquire(sp, "r", "n2", ttl_s=10.0, clock=clk)
        assert l2 is not None and l2.token == 2  # fencing token strictly grows
        assert (
            counter_value("hs_fabric_lease_acquires_total", outcome="takeover")
            == takeover0 + 1
        )
        # the zombie's renewal observes the takeover and stops
        lost0 = counter_value("hs_fabric_lease_renewals_total", outcome="lost")
        assert l1.renew() is False and l1.lost
        assert counter_value("hs_fabric_lease_renewals_total", outcome="lost") == lost0 + 1
        # and its commit-time fence check raises instead of landing a write
        fenced0 = counter_value("hs_fabric_lease_fenced_total")
        with pytest.raises(LeaseLostError) as ei:
            l1.verify()
        assert ei.value.held_token == 1 and ei.value.current_token == 2
        assert counter_value("hs_fabric_lease_fenced_total") == fenced0 + 1
        l2.verify()  # the successor's fence still passes

    def test_release_keeps_the_token_sequence(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock()
        l1 = lease_mod.acquire(sp, "r", "n1", ttl_s=10.0, clock=clk)
        l1.release()
        # released = immediately claimable, but the sequence never restarts
        l2 = lease_mod.acquire(sp, "r", "n2", ttl_s=10.0, clock=clk)
        assert l2 is not None and l2.token == 2

    def test_torn_current_token_is_claimable(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock()
        l1 = lease_mod.acquire(sp, "r", "n1", ttl_s=10.0, clock=clk)
        with open(l1.path, "w") as f:
            f.write("not json {")  # lake-level corruption of the live token
        current, state = lease_mod.read_state(sp, "r")
        assert current == 1 and state is None
        l2 = lease_mod.acquire(sp, "r", "n2", ttl_s=10.0, clock=clk)
        assert l2 is not None and l2.token == 2  # claimable, not wedged forever

    def test_claim_race_has_exactly_one_winner(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock()
        results = []
        barrier = threading.Barrier(4)

        def racer(i):
            barrier.wait()
            results.append(lease_mod.acquire(sp, "r", f"n{i}", ttl_s=10.0, clock=clk))

        threads = [threading.Thread(target=racer, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        wins = [l for l in results if l is not None]
        assert len(wins) == 1 and wins[0].token == 1

    def test_renew_write_failure_is_not_a_loss(self, tmp_path):
        clk = FakeClock()
        l1 = lease_mod.acquire(str(tmp_path), "r", "n1", ttl_s=10.0, clock=clk)
        err0 = counter_value("hs_fabric_lease_renewals_total", outcome="error")
        with fault_scope(FaultRule("lease.renew", "transient")):
            # the prior expiry still stands; only a takeover loses a lease
            assert l1.renew() is True
        assert not l1.lost
        assert counter_value("hs_fabric_lease_renewals_total", outcome="error") == err0 + 1
        assert l1.renew() is True  # the next beat retries cleanly

    def test_heartbeat_thread_renews_until_stopped(self, tmp_path):
        l1 = lease_mod.acquire(str(tmp_path), "hb", "n1", ttl_s=5.0)
        exp0 = l1.expires_at
        l1.start_heartbeat(0.05)
        deadline = time.time() + 5
        while l1.expires_at <= exp0 and time.time() < deadline:
            time.sleep(0.02)
        assert l1.expires_at > exp0, "heartbeat never renewed"
        l1.release()  # also stops the heartbeat


class TestRefreshLease:
    """RefreshManager + lake lease: single-writer across processes."""

    @pytest.fixture()
    def lease_nodes(self, tmp_system_path, data_root):
        extra = {
            hst.keys.FABRIC_LEASE_ENABLED: True,
            hst.keys.FABRIC_LEASE_TTL_SECONDS: 30.0,
            hst.keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS: 3600.0,
        }
        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1", **extra))
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root),
            hst.CoveringIndexConfig("fabIdx", ["c1"], ["m"]),
        )
        s2 = hst.Session(conf=fabric_conf(tmp_system_path, "n2", **extra))
        s2.fabric.watcher.poll_once()
        yield s1, s2
        s2.fabric.stop()
        s1.fabric.stop()

    def test_refresh_claims_and_releases_the_lease(
        self, lease_nodes, data_root, tmp_system_path
    ):
        s1, _ = lease_nodes
        write_marked_part(data_root, 3)
        assert RefreshManager(s1).refresh_index("fabIdx", "incremental") == "committed"
        current, state = lease_mod.read_state(tmp_system_path, "refresh/fabIdx")
        assert current == 1 and state["holder"] == "n1"
        assert float(state["expiresAt"]) == 0.0  # released for instant takeover

    def test_two_racing_refreshers_one_commits_one_busy(self, lease_nodes, data_root):
        """The acceptance race: two RefreshManagers (distinct sessions, so
        the in-process locks cannot arbitrate) race one index — the lake
        lease serializes them into exactly one ``committed`` and one
        ``busy``."""
        s1, s2 = lease_nodes
        write_marked_part(data_root, 3)
        rm1, rm2 = RefreshManager(s1), RefreshManager(s2)
        outcomes = {}
        # hold the winner inside its refresh (lease held) long enough for
        # the loser to observe a live lease
        with fault_scope(FaultRule("log.write", "latency", delay_s=1.0, max_fires=1)):
            t = threading.Thread(
                target=lambda: outcomes.__setitem__(
                    "a", rm1.refresh_index("fabIdx", "incremental")
                )
            )
            t.start()
            time.sleep(0.4)
            outcomes["b"] = rm2.refresh_index("fabIdx", "incremental")
            t.join(timeout=60)
        assert sorted(outcomes.values()) == ["busy", "committed"], outcomes

    def test_crash_mid_refresh_peer_takes_over_and_fences_late_commit(
        self, lease_nodes, data_root, tmp_system_path
    ):
        """A refresher killed mid-refresh leaves its lease to expire; a peer
        takes over after TTL, and the zombie's late commit is rejected by
        the fencing token at the log write — zero duplicate entries."""
        s1, s2 = lease_nodes
        # n1's refresher claimed the lease then died: no heartbeat, tiny TTL
        zombie = lease_mod.acquire(
            tmp_system_path, "refresh/fabIdx", "n1", ttl_s=0.2
        )
        assert zombie is not None and zombie.token == 1
        write_marked_part(data_root, 3)
        rm2 = RefreshManager(s2)
        # before expiry the peer observes a live lease and skips
        assert rm2.refresh_index("fabIdx", "incremental") == "busy"
        time.sleep(0.25)  # the dead holder never renews; TTL elapses
        takeover0 = counter_value("hs_fabric_lease_acquires_total", outcome="takeover")
        assert rm2.refresh_index("fabIdx", "incremental") == "committed"
        assert (
            counter_value("hs_fabric_lease_acquires_total", outcome="takeover")
            == takeover0 + 1
        )
        # the zombie wakes with real drift to commit; its write must not land
        write_marked_part(data_root, 4)
        log_dir = os.path.join(tmp_system_path, "fabIdx", C.HYPERSPACE_LOG_DIR)
        entries_before = sorted(n for n in os.listdir(log_dir) if n.isdigit())
        fenced0 = counter_value("hs_fabric_lease_fenced_total")
        with fence_scope(zombie):
            with pytest.raises(LeaseLostError):
                s1.index_manager.refresh("fabIdx", "incremental")
        assert counter_value("hs_fabric_lease_fenced_total") == fenced0 + 1
        assert (
            sorted(n for n in os.listdir(log_dir) if n.isdigit()) == entries_before
        ), "the fenced zombie still landed a log entry"

    def test_refresh_outcome_fenced_when_lease_stolen_mid_refresh(
        self, tmp_system_path, data_root
    ):
        """End-to-end through RefreshManager: the holder stalls past its TTL
        (no renewals), a peer takes over and commits, and the stalled
        refresh surfaces the distinct ``fenced`` outcome."""
        extra = {
            hst.keys.FABRIC_LEASE_ENABLED: True,
            hst.keys.FABRIC_LEASE_TTL_SECONDS: 0.25,
            hst.keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS: 3600.0,
        }
        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1", **extra))
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root),
            hst.CoveringIndexConfig("fabIdx", ["c1"], ["m"]),
        )
        s2 = hst.Session(conf=fabric_conf(tmp_system_path, "n2", **extra))
        s2.fabric.watcher.poll_once()
        try:
            write_marked_part(data_root, 3)
            rm1, rm2 = RefreshManager(s1), RefreshManager(s2)
            outcomes = {}
            fenced0 = counter_value("hs_lifecycle_refresh_total",
                                    mode="incremental", outcome="fenced")
            with fault_scope(
                FaultRule("log.write", "latency", delay_s=1.0, max_fires=1)
            ):
                t = threading.Thread(
                    target=lambda: outcomes.__setitem__(
                        "a", rm1.refresh_index("fabIdx", "incremental")
                    )
                )
                t.start()
                time.sleep(0.5)  # past rm1's TTL: its lease is claimable
                outcomes["b"] = rm2.refresh_index("fabIdx", "incremental")
                t.join(timeout=60)
            assert outcomes["b"] == "committed"
            assert outcomes["a"] == "fenced", outcomes
            assert (
                counter_value("hs_lifecycle_refresh_total",
                              mode="incremental", outcome="fenced")
                == fenced0 + 1
            )
        finally:
            s2.fabric.stop()
            s1.fabric.stop()


# --- commit-watcher recovery under compaction --------------------------------


class TestCommitWatcherRecovery:
    def test_compaction_under_live_watcher_keeps_cursor_monotonic(
        self, two_nodes, data_root, tmp_system_path
    ):
        s1, s2 = two_nodes
        rm = RefreshManager(s1)
        for marker in (3, 4):
            write_marked_part(data_root, marker)
            assert rm.refresh_index("fabIdx", "incremental") == "committed"
            assert s2.fabric.watcher.poll_once() == 1
        cursor = s2.fabric.watcher._cursors["fabIdx"]
        assert cursor >= 2
        # compact everything retention allows, under the live watcher
        report = fsck(tmp_system_path, retention_s=0.0)
        assert report["removed"]["old-record"] >= 2
        # the high-water record is always kept: ids never restart behind a cursor
        cdir = records.commits_dir(tmp_system_path, "fabIdx")
        assert [rid for rid, _ in records.read_commit_records(cdir)] == [cursor]
        # the next commit numbers past every cursor and replays exactly once
        write_marked_part(data_root, 5)
        assert rm.refresh_index("fabIdx", "incremental") == "committed"
        assert s2.fabric.watcher.poll_once() == 1
        assert s2.lifecycle_bus.commit_seq == s1.lifecycle_bus.commit_seq

    def test_truncated_directory_still_numbers_past_stale_cursors(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxT", 1, "create", origin="n1")
        for seq in range(6):
            records.append_commit_record(sp, ev, seq=seq)
        fsck(sp, retention_s=0.0)
        cdir = records.commits_dir(sp, "idxT")
        assert [rid for rid, _ in records.read_commit_records(cdir)] == [5]
        # max+1 numbering continues from the kept record, not from 0
        assert records.append_commit_record(sp, ev, seq=7) == 6

    def test_stale_cursor_restart_converges_without_self_replay(
        self, two_nodes, data_root, tmp_system_path
    ):
        s1, s2 = two_nodes
        write_marked_part(data_root, 3)
        assert RefreshManager(s1).refresh_index("fabIdx", "incremental") == "committed"
        # n2 committed something of its own before crashing
        s2.lifecycle_bus.publish(CommitEvent("fabIdx", None, "refresh-quick"))
        s2.fabric.stop()
        # n2 restarts: fresh session, cold cursor, same node id
        s3 = hst.Session(conf=fabric_conf(tmp_system_path, "n2"))
        try:
            skips0 = counter_value("hs_fabric_self_skips_total")
            # replays exactly the n1-origin records; its own pre-crash commit
            # is recognized by origin and skipped, not replayed
            assert s3.fabric.watcher.poll_once() == 2
            assert counter_value("hs_fabric_self_skips_total") == skips0 + 1
            assert SnapshotHandle.capture(s3).index_version(
                "fabIdx"
            ) == SnapshotHandle.capture(s1).index_version("fabIdx")
            assert s3.fabric.watcher.poll_once() == 0  # cursor rebuilt, no re-replay
        finally:
            s3.fabric.stop()


# --- health tracker (unit, injected clock) -----------------------------------


class TestHealthTracker:
    def test_eject_halfopen_readmit_cycle(self):
        clk = FakeClock(0.0)
        h = HealthTracker(failure_threshold=2, probe_interval_s=5.0, clock=clk)
        workers = ["w0", "w1"]
        assert h.live(workers) == workers
        ej0 = counter_value(
            "hs_fabric_node_ejections_total", worker="w0", reason="errors"
        )
        h.note_failure("w0")
        assert h.state_of("w0") == "live"  # below threshold
        h.note_failure("w0")
        assert h.state_of("w0") == "ejected"
        assert (
            counter_value("hs_fabric_node_ejections_total", worker="w0", reason="errors")
            == ej0 + 1
        )
        assert h.live(workers) == ["w1"]  # tenants re-hash to the survivor
        clk.t = 6.0  # cooldown elapsed: one probe admitted
        assert h.live(workers) == workers
        assert h.state_of("w0") == "half-open"
        re0 = counter_value("hs_fabric_node_readmissions_total", worker="w0")
        h.note_ok("w0")
        assert h.state_of("w0") == "live"
        assert counter_value("hs_fabric_node_readmissions_total", worker="w0") == re0 + 1

    def test_probe_failure_reejects_and_restarts_cooldown(self):
        clk = FakeClock(0.0)
        h = HealthTracker(failure_threshold=1, probe_interval_s=5.0, clock=clk)
        h.note_failure("w0")
        clk.t = 6.0
        assert h.live(["w0", "w1"]) == ["w0", "w1"]  # w0 admitted half-open
        pf0 = counter_value(
            "hs_fabric_node_ejections_total", worker="w0", reason="probe-failed"
        )
        h.note_failure("w0")
        assert h.state_of("w0") == "ejected"
        assert (
            counter_value(
                "hs_fabric_node_ejections_total", worker="w0", reason="probe-failed"
            )
            == pf0 + 1
        )
        clk.t = 8.0  # cooldown restarted at 6.0: not yet eligible again
        assert h.live(["w0", "w1"]) == ["w1"]

    def test_fail_open_when_everyone_is_ejected(self):
        h = HealthTracker(failure_threshold=1, probe_interval_s=100.0, clock=FakeClock(0.0))
        h.note_failure("w0")
        h.note_failure("w1")
        # a guess beats a guaranteed refusal
        assert h.live(["w0", "w1"]) == ["w0", "w1"]

    def test_missed_beats_eject_and_fresh_beat_readmits(self):
        clk = FakeClock(0.0)
        h = HealthTracker(heartbeat_interval_s=1.0, missed_beats=3, clock=clk)
        mb0 = counter_value(
            "hs_fabric_node_ejections_total", worker="w0", reason="missed-beats"
        )
        h.note_beat("w0", age_s=2.0)
        assert h.state_of("w0") == "live"
        h.note_beat("w0", age_s=3.5)  # > heartbeat_interval * missed_beats
        assert h.state_of("w0") == "ejected"
        assert (
            counter_value(
                "hs_fabric_node_ejections_total", worker="w0", reason="missed-beats"
            )
            == mb0 + 1
        )
        h.note_beat("w0", age_s=0.1)  # the process provably lives: direct readmit
        assert h.state_of("w0") == "live"

    def test_stale_commit_seq_ejects_wedged_worker(self):
        h = HealthTracker(max_commit_lag=2, clock=FakeClock(0.0))
        st0 = counter_value(
            "hs_fabric_node_ejections_total", worker="w0", reason="stale"
        )
        h.note_stale("w0", lag=2)
        assert h.state_of("w0") == "live"  # at the bound: tolerated
        h.note_stale("w0", lag=3)
        assert h.state_of("w0") == "ejected"
        assert (
            counter_value("hs_fabric_node_ejections_total", worker="w0", reason="stale")
            == st0 + 1
        )
        # the default max_commit_lag=0 disables stale ejection entirely
        h2 = HealthTracker(clock=FakeClock(0.0))
        h2.note_stale("w1", lag=999)
        assert h2.state_of("w1") == "live"


# --- FrontDoor failover, hedging, typed wire errors --------------------------


@pytest.fixture()
def two_endpoints(session, data_root):
    """Two QueryServers on one session, each behind an HTTP WorkerEndpoint."""
    session.enable_hyperspace()
    session.register_view("t", session.read_parquet(data_root))
    with QueryServer(session, workers=1, name="qsA") as a, QueryServer(
        session, workers=1, name="qsB"
    ) as b:
        with WorkerEndpoint(a) as ea, WorkerEndpoint(b) as eb:
            yield ea, eb


_SQL = "SELECT m FROM t WHERE c1 >= 0"


class TestFrontDoorFailover:
    def test_rendezvous_order_heads_match_pick(self):
        nodes = ["qs0", "qs1", "qs2", "qs3"]
        for t in range(30):
            order = rendezvous_order(f"tenant-{t}", nodes)
            assert order[0] == rendezvous_pick(f"tenant-{t}", nodes)
            assert sorted(order) == sorted(nodes)
            # removing the winner promotes exactly the next entry
            assert rendezvous_pick(f"tenant-{t}", order[1:]) == order[1]

    def test_transient_failure_fails_over_to_next_candidate(self, two_endpoints):
        ea, eb = two_endpoints
        h = HealthTracker(failure_threshold=1, probe_interval_s=3600.0)
        fd = FrontDoor([ea.url, eb.url], health=h)
        tenant = "tenant-fo"
        primary = rendezvous_order(tenant, fd.worker_ids)[0]
        url = fd._workers[primary]
        retries0 = counter_value("hs_frontdoor_failover_retries_total", worker=primary)
        with fault_scope(
            FaultRule("fabric.http", "transient", path_glob=f"{url}*", max_fires=1)
        ):
            res = fd.query(_SQL, tenant=tenant)
        assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
        assert (
            counter_value("hs_frontdoor_failover_retries_total", worker=primary)
            == retries0 + 1
        )
        # threshold 1: the failed primary left the rendezvous set
        assert h.state_of(primary) == "ejected"
        assert fd._candidates(tenant)[0] != primary

    def test_nonretryable_failure_is_not_failed_over(self, two_endpoints):
        ea, eb = two_endpoints
        fd = FrontDoor([ea.url, eb.url], failover=True)
        tenant = "tenant-cor"
        primary = rendezvous_order(tenant, fd.worker_ids)[0]
        retries0 = counter_value("hs_frontdoor_failover_retries_total", worker=primary)
        with fault_scope(FaultRule("fabric.http", "corrupt", max_fires=1)):
            with pytest.raises(Exception, match="injected corrupt"):
                fd.query(_SQL, tenant=tenant)
        # retrying corrupt bytes rereads the same wrong bytes: no retry burned
        assert (
            counter_value("hs_frontdoor_failover_retries_total", worker=primary)
            == retries0
        )

    def test_typed_error_body_survives_the_wire(self, two_endpoints):
        ea, _ = two_endpoints
        fd = FrontDoor([ea.url])
        with pytest.raises(RuntimeError, match="failed") as ei:
            fd.query("SELECT nope FROM missing_table")
        # the worker-side classification crossed the wire as a typed error
        assert isinstance(ei.value, (WorkerError, WorkerUnavailable))
        assert ei.value.error_type and ei.value.kind in ("transient", "corrupt", "error")

    def test_dead_endpoint_raises_worker_unavailable(self, two_endpoints):
        ea, eb = two_endpoints
        dead = f"http://{eb.host}:1"  # nothing listens on port 1
        fd = FrontDoor([dead])
        with pytest.raises(WorkerUnavailable, match="unreachable"):
            fd.query(_SQL, tenant="t")

    def test_deadline_stops_failover_between_candidates(self, two_endpoints):
        ea, eb = two_endpoints

        class SteppingClock:
            def __init__(self):
                self.t = 0.0

            def __call__(self):
                self.t += 10.0
                return self.t

        fd = FrontDoor([ea.url, eb.url], failover=True, clock=SteppingClock())
        tenant = "tenant-dl"
        second = rendezvous_order(tenant, fd.worker_ids)[1]
        routed0 = counter_value("hs_fabric_frontdoor_requests_total", worker=second)
        ex0 = counter_value("hs_frontdoor_failover_exhausted_total")
        with fault_scope(FaultRule("fabric.http", "transient")):
            with pytest.raises(WorkerUnavailable):
                fd.query(_SQL, tenant=tenant, timeout=5.0)
        # the deadline was spent on the first attempt: no doomed second try
        assert (
            counter_value("hs_fabric_frontdoor_requests_total", worker=second) == routed0
        )
        assert counter_value("hs_frontdoor_failover_exhausted_total") == ex0 + 1

    def test_all_candidates_exhausted_raises_last_typed_error(self, two_endpoints):
        ea, eb = two_endpoints
        fd = FrontDoor([ea.url, eb.url], failover=True)
        ex0 = counter_value("hs_frontdoor_failover_exhausted_total")
        with fault_scope(FaultRule("fabric.http", "transient")):
            with pytest.raises(WorkerUnavailable, match="unreachable"):
                fd.query(_SQL, tenant="tenant-ex")
        assert counter_value("hs_frontdoor_failover_exhausted_total") == ex0 + 1

    def test_hedged_query_beats_a_slow_primary(self, two_endpoints):
        ea, eb = two_endpoints
        fd = FrontDoor([ea.url, eb.url], failover=True, hedge_ms=50.0)
        tenant = "tenant-hg"
        primary = rendezvous_order(tenant, fd.worker_ids)[0]
        url = fd._workers[primary]
        # warm both workers so the backup's first answer is fast
        for wid in fd.worker_ids:
            FrontDoor([fd._workers[wid]]).query(_SQL, tenant=tenant)
        hedges0 = counter_value("hs_frontdoor_failover_hedges_total")
        with fault_scope(
            FaultRule("fabric.http", "latency", delay_s=5.0, path_glob=f"{url}*")
        ):
            t0 = time.monotonic()
            res = fd.query(_SQL, tenant=tenant)
            elapsed = time.monotonic() - t0
        assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
        assert elapsed < 4.0, "the hedge never fired: waited out the stalled primary"
        assert counter_value("hs_frontdoor_failover_hedges_total") == hedges0 + 1

    def test_hedge_path_fails_over_on_fast_primary_failure(self, two_endpoints):
        ea, eb = two_endpoints
        fd = FrontDoor([ea.url, eb.url], failover=True, hedge_ms=10000.0)
        tenant = "tenant-hf"
        primary = rendezvous_order(tenant, fd.worker_ids)[0]
        url = fd._workers[primary]
        hedges0 = counter_value("hs_frontdoor_failover_hedges_total")
        retries0 = counter_value("hs_frontdoor_failover_retries_total", worker=primary)
        with fault_scope(
            FaultRule("fabric.http", "transient", path_glob=f"{url}*", max_fires=1)
        ):
            res = fd.query(_SQL, tenant=tenant)
        assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
        # an outright failure before the hedge delay is a failover, not a hedge
        assert counter_value("hs_frontdoor_failover_hedges_total") == hedges0
        assert (
            counter_value("hs_frontdoor_failover_retries_total", worker=primary)
            == retries0 + 1
        )

    def test_probe_beats_and_stale_ejection(self, tmp_system_path, data_root):
        """The liveness integration loop: /healthz probing learns node ids,
        sidecar-ledger ages are judged as heartbeats (eject + readmit), and
        a wedged watcher (commit-seq lag) is ejected by the probe sweep."""
        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root),
            hst.CoveringIndexConfig("hzIdx", ["c1"], ["m"]),
        )
        s2 = hst.Session(conf=fabric_conf(tmp_system_path, "n2"))
        s2.fabric.watcher.poll_once()
        for s in (s1, s2):
            s.enable_hyperspace()
            s.register_view("t", s.read_parquet(data_root))
        h = HealthTracker(
            failure_threshold=1,
            probe_interval_s=3600.0,
            heartbeat_interval_s=1.0,
            missed_beats=3,
            max_commit_lag=1,
        )
        try:
            with QueryServer(s1, workers=1, name="hz1") as srv1, QueryServer(
                s2, workers=1, name="hz2"
            ) as srv2:
                with WorkerEndpoint(srv1) as e1, WorkerEndpoint(srv2) as e2:
                    fd = FrontDoor(
                        [e1.url, e2.url], health=h, system_path=tmp_system_path
                    )
                    wid1, wid2 = fd.worker_ids
                    bodies = fd.probe()
                    assert all(b and b["ok"] for b in bodies.values())
                    assert sorted(fd._nodes.values()) == ["n1", "n2"]
                    assert h.state_of(wid1) == h.state_of(wid2) == "live"
                    # heartbeats ride the sidecar node files
                    s1.fabric.sidecar.publish_once()
                    s2.fabric.sidecar.publish_once()
                    ages = fd.check_beats()
                    assert set(ages) == {wid1, wid2}
                    assert all(a < 3.0 for a in ages.values())
                    # n2 goes silent: age its ledger past missed_beats
                    p2 = os.path.join(records.nodes_dir(tmp_system_path), "n2.json")
                    with open(p2) as f:
                        st = json.load(f)
                    st["updatedAt"] = time.time() - 60
                    with open(p2, "w") as f:
                        json.dump(st, f)
                    fd.check_beats()
                    assert h.state_of(wid2) == "ejected"
                    assert (
                        counter_value(
                            "hs_fabric_node_ejections_total",
                            worker=wid2,
                            reason="missed-beats",
                        )
                        >= 1
                    )
                    # a fresh beat readmits directly: the process provably lives
                    s2.fabric.sidecar.publish_once()
                    fd.check_beats()
                    assert h.state_of(wid2) == "live"
                    # a wedged watcher: n1 commits twice while n2 never polls
                    for marker in (3, 4):
                        write_marked_part(data_root, marker)
                        assert (
                            RefreshManager(s1).refresh_index("hzIdx", "incremental")
                            == "committed"
                        )
                    fd.probe()
                    assert h.state_of(wid2) == "ejected"
                    assert (
                        counter_value(
                            "hs_fabric_node_ejections_total",
                            worker=wid2,
                            reason="stale",
                        )
                        >= 1
                    )
        finally:
            s2.fabric.stop()
            s1.fabric.stop()

    def test_metrics_merge_skips_dead_worker_with_health(self, two_endpoints):
        ea, eb = two_endpoints
        dead = f"http://{ea.host}:1"
        h = HealthTracker(failure_threshold=1, probe_interval_s=3600.0)
        fd = FrontDoor([ea.url, dead], health=h)
        merged = fd.metrics_text()  # must not raise; the live worker reports
        assert 'server="qsA"' in merged
        dead_wid = [w for w in fd.worker_ids if w.endswith(":1")][-1]
        assert h.state_of(dead_wid) == "ejected"
        # without health the old strict behavior is preserved
        with pytest.raises(Exception):
            FrontDoor([ea.url, dead]).metrics_text()


# --- fsck: lake garbage collection -------------------------------------------


class TestFsck:
    def test_commit_record_gc_keeps_newest_and_removes_torn(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("gcIdx", 1, "create", origin="n1")
        for seq in range(4):
            records.append_commit_record(sp, ev, seq=seq)
        cdir = records.commits_dir(sp, "gcIdx")
        with open(os.path.join(cdir, f"{1:010d}"), "w") as f:
            f.write("torn {")
        runs0 = counter_value("hs_fabric_fsck_runs_total")
        report = fsck(sp, retention_s=0.0)
        assert counter_value("hs_fabric_fsck_runs_total") == runs0 + 1
        assert report["removed"]["torn-record"] == 1
        assert report["removed"]["old-record"] == 2  # ids 0 and 2
        assert report["removedTotal"] == 3 and report["skipped"] == 0
        assert [rid for rid, _ in records.read_commit_records(cdir)] == [3]

    def test_lease_gc_stale_claims_then_expired_lease(self, tmp_path):
        sp = str(tmp_path)
        clk = FakeClock(100.0)
        lease_mod.acquire(sp, "refresh/gcIdx", "n1", ttl_s=10.0, clock=clk)
        clk.t = 120.0
        l2 = lease_mod.acquire(sp, "refresh/gcIdx", "n2", ttl_s=10.0, clock=clk)
        assert l2 is not None and l2.token == 2
        # within retention: the settled takeover history goes, the live token stays
        report = fsck(sp, retention_s=3600.0, clock=lambda: 200.0)
        assert report["removed"]["stale-claim"] == 1
        assert report["removed"]["expired-lease"] == 0
        assert lease_mod.read_state(sp, "refresh/gcIdx")[0] == 2
        # a full retention past expiry (130), the whole lease resets
        report2 = fsck(sp, retention_s=50.0, clock=lambda: 200.0)
        assert report2["removed"]["expired-lease"] == 1
        assert not os.path.isdir(lease_mod.leases_dir(sp, "refresh/gcIdx"))
        # and the token sequence restarts cleanly with no racers left
        l3 = lease_mod.acquire(sp, "refresh/gcIdx", "n3", ttl_s=10.0,
                               clock=FakeClock(200.0))
        assert l3 is not None and l3.token == 1

    def test_dead_node_ledger_gc(self, tmp_path):
        sp = str(tmp_path)
        records.write_node_file(sp, "nFresh", {})
        records.write_node_file(sp, "nDead", {})
        dead = os.path.join(records.nodes_dir(sp), "nDead.json")
        with open(dead) as f:
            st = json.load(f)
        st["updatedAt"] = time.time() - 3600
        with open(dead, "w") as f:
            json.dump(st, f)
        report = fsck(sp, dead_node_s=600.0)
        assert report["removed"]["dead-node"] == 1
        assert not os.path.exists(dead)
        assert os.path.exists(os.path.join(records.nodes_dir(sp), "nFresh.json"))

    def test_dry_run_reports_without_removing(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("dryIdx", 1, "create", origin="n1")
        for seq in range(3):
            records.append_commit_record(sp, ev, seq=seq)
        removed0 = counter_value("hs_fabric_fsck_removed_total", kind="old-record")
        report = fsck(sp, retention_s=0.0, dry_run=True)
        assert report["dryRun"] is True and report["removed"]["old-record"] == 2
        cdir = records.commits_dir(sp, "dryIdx")
        assert len(records.read_commit_records(cdir)) == 3  # nothing deleted
        # dry runs never count removals as real
        assert (
            counter_value("hs_fabric_fsck_removed_total", kind="old-record") == removed0
        )

    def test_record_compact_fault_skips_and_continues(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("fltIdx", 1, "create", origin="n1")
        for seq in range(3):
            records.append_commit_record(sp, ev, seq=seq)
        with fault_scope(FaultRule("record.compact", "transient", max_fires=1)):
            report = fsck(sp, retention_s=0.0)
        # the injected failure skipped one file; the pass still finished
        assert report["skipped"] == 1
        assert report["removed"]["old-record"] == 1
        cdir = records.commits_dir(sp, "fltIdx")
        assert len(records.read_commit_records(cdir)) == 2

    def test_cli_main_prints_json_report(self, tmp_path, capsys):
        sp = str(tmp_path)
        records.append_commit_record(
            sp, CommitEvent("cliIdx", 1, "create", origin="n1"), seq=1
        )
        assert fsck_main([sp, "--dry-run", "--retention-seconds", "0"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["systemPath"] == sp and out["dryRun"] is True

    def test_module_shim_exposes_main(self):
        import hyperspace_tpu.fsck as shim

        assert shim.main is fsck_main

    def test_runtime_runs_fsck_on_start(self, tmp_system_path, data_root):
        runs0 = counter_value("hs_fabric_fsck_runs_total")
        s = hst.Session(
            conf=fabric_conf(
                tmp_system_path,
                "n1",
                **{
                    hst.keys.FABRIC_FSCK_ENABLED: True,
                    hst.keys.FABRIC_FSCK_INTERVAL_SECONDS: 3600.0,
                },
            )
        )
        try:
            assert counter_value("hs_fabric_fsck_runs_total") == runs0 + 1
        finally:
            s.fabric.stop()


# --- default-off byte identity ----------------------------------------------

# Runs the same workload in two fresh interpreters — defaults vs fabric-on —
# and compares plans and answers. A subprocess is the only honest probe for
# "no hs_fabric_* families": this test process's registry already carries
# them from the tests above.
_IDENTITY_SCRIPT = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, sys.argv[4])
import numpy as np
import pyarrow as pa, pyarrow.parquet as pq
import hyperspace_tpu as hst

root, sys_path, fabric_on = sys.argv[1], sys.argv[2], sys.argv[3] == "1"
conf = {hst.keys.SYSTEM_PATH: sys_path}
if fabric_on:
    conf.update({hst.keys.FABRIC_ENABLED: True, hst.keys.FABRIC_NODE_ID: "nX",
                 hst.keys.FABRIC_WATCHER_ENABLED: False,
                 hst.keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 3600})
sess = hst.Session(conf=conf)
hst.Hyperspace(sess).create_index(
    sess.read_parquet(root), hst.CoveringIndexConfig("bIdx", ["c1"], ["m"]))
sess.enable_hyperspace()
sess.register_view("t", sess.read_parquet(root))
from hyperspace_tpu.serving import QueryServer
with QueryServer(sess, workers=1, name="qsId") as srv:
    res = srv.query("SELECT m FROM t WHERE c1 >= 0")
    q = sess.sql("SELECT m FROM t WHERE c1 >= 0")
    plan = repr(q.optimized_plan())
    metrics = srv.prometheus_text()
print(json.dumps({
    "rows": sorted(np.asarray(res["m"]).tolist()),
    "plan": plan,
    "fabric_families": sorted({l.split("{")[0].split()[0]
                               for l in metrics.splitlines()
                               if l and not l.startswith("#")
                               and l.startswith("hs_fabric_")}),
    "fabric_dirs": [p for p in (os.path.join(sys_path, "_fabric"),
                                os.path.join(sys_path, "bIdx", "_hyperspace_log", "_commits"))
                    if os.path.exists(p)],
}))
"""


class TestDefaultOffByteIdentity:
    @pytest.mark.slow
    def test_disabled_fabric_changes_nothing(self, tmp_path, data_root):
        outs = {}
        for flag in ("0", "1"):
            sp = tmp_path / f"identity_{flag}"
            sp.mkdir()
            proc = subprocess.run(
                [sys.executable, "-c", _IDENTITY_SCRIPT, data_root, str(sp), flag, REPO_ROOT],
                capture_output=True,
                text=True,
                timeout=240,
                cwd=REPO_ROOT,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs[flag] = json.loads(proc.stdout.strip().splitlines()[-1])
        off, on = outs["0"], outs["1"]
        # at defaults: no fabric metric families, nothing fabric-shaped on disk
        assert off["fabric_families"] == []
        assert off["fabric_dirs"] == []
        # the fabric-on run persisted records but served identical plans/rows
        assert on["fabric_dirs"], "fabric-on run wrote no records"
        assert on["plan"] == off["plan"]
        assert on["rows"] == off["rows"]


# --- multi-process soak ------------------------------------------------------

_SOAK_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, sys.argv[5])
import hyperspace_tpu as hst
from hyperspace_tpu.serving import QueryServer
from hyperspace_tpu.fabric import WorkerEndpoint

root, sys_path, name, interval = sys.argv[1], sys.argv[2], sys.argv[3], float(sys.argv[4])
sess = hst.Session(conf={
    hst.keys.SYSTEM_PATH: sys_path,
    hst.keys.FABRIC_ENABLED: True,
    hst.keys.FABRIC_NODE_ID: name,
    hst.keys.FABRIC_POLL_INTERVAL_SECONDS: interval,
})
sess.enable_hyperspace()
sess.register_view("t", sess.read_parquet(root))

def refresh_views(event):
    # a DataFrame freezes its source listing at read time; re-resolving the
    # served views on every (replayed) commit is the fabric worker pattern
    sess.register_view("t", sess.read_parquet(root))

sess.lifecycle_bus.subscribe(refresh_views)
with QueryServer(sess, workers=2, name=name) as srv:
    with WorkerEndpoint(srv) as ep:
        print(ep.url, flush=True)
        sys.stdin.readline()  # serve until the parent closes stdin
"""


@pytest.mark.soak
@pytest.mark.slow
class TestMultiProcessSoak:
    def test_two_servers_one_refresher_zero_stale(self, tmp_path):
        """2 fabric server subprocesses + this process refreshing: after each
        commit settles for one poll interval, every routed answer must carry
        all committed markers, unturned."""
        root = tmp_path / "soak_data"
        root.mkdir()
        n = 120
        initial = 3
        for i in range(initial):
            write_marked_part(str(root), i, n=n)
        sys_path = tmp_path / "indexes"
        sys_path.mkdir()
        poll_s = 0.2

        writer = hst.Session(
            conf=fabric_conf(str(sys_path), "writer")
        )
        hst.Hyperspace(writer).create_index(
            writer.read_parquet(str(root)),
            hst.CoveringIndexConfig("soakFab", ["c1"], ["m"]),
        )
        rm = RefreshManager(writer)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = []
        try:
            for i in range(2):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-c",
                            _SOAK_WORKER,
                            str(root),
                            str(sys_path),
                            f"qs{i}",
                            str(poll_s),
                            REPO_ROOT,
                        ],
                        stdin=subprocess.PIPE,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE,
                        text=True,
                        cwd=REPO_ROOT,
                        env=env,
                    )
                )
            urls = [p.stdout.readline().strip() for p in procs]
            assert all(u.startswith("http://") for u in urls), urls
            fd = FrontDoor(urls)

            violations = []
            committed = list(range(initial))
            for rnd in range(3):
                marker = initial + rnd
                write_marked_part(str(root), marker, n=n)
                assert rm.refresh_index("soakFab", "incremental") == "committed"
                committed.append(marker)
                # staleness bound: one poll interval (+ settle margin)
                time.sleep(poll_s * 3 + 0.3)
                for t in range(4):
                    res = fd.query(
                        "SELECT m FROM t WHERE c1 >= 0", tenant=f"tenant-{t}"
                    )
                    vals, cnts = np.unique(res["m"], return_counts=True)
                    seen = dict(zip(vals.tolist(), cnts.tolist()))
                    for mk, c in seen.items():
                        if c != n:
                            violations.append(("torn", rnd, mk, c))
                    for mk in committed:
                        if seen.get(mk) != n:
                            violations.append(("stale", rnd, mk, seen.get(mk)))
            assert violations == [], violations[:10]
        finally:
            for p in procs:
                try:
                    p.stdin.close()
                except Exception:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=30)
                except Exception:
                    p.kill()
            writer.fabric.stop()


# --- crash soak: kill -9 under load ------------------------------------------

_LEASE_HOLDER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, sys.argv[4])
import hyperspace_tpu as hst
from hyperspace_tpu.lifecycle import RefreshManager
from hyperspace_tpu.reliability.faults import FAULTS, FaultRule

root, sys_path, ttl = sys.argv[1], sys.argv[2], float(sys.argv[3])
sess = hst.Session(conf={
    hst.keys.SYSTEM_PATH: sys_path,
    hst.keys.FABRIC_ENABLED: True,
    hst.keys.FABRIC_NODE_ID: "child",
    hst.keys.FABRIC_WATCHER_ENABLED: False,
    hst.keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 3600,
    hst.keys.FABRIC_LEASE_ENABLED: True,
    hst.keys.FABRIC_LEASE_TTL_SECONDS: ttl,
    hst.keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS: ttl / 4.0,
})
sess.fabric.watcher.poll_once()
# wedge every log write: this process will be SIGKILLed inside its refresh,
# lease held, heartbeat renewing -- the crash is what stops the renewals
FAULTS.install(FaultRule("log.write", "latency", delay_s=600.0))
print("REFRESHING", flush=True)
print(RefreshManager(sess).refresh_index("soakLease", "incremental"), flush=True)
"""


@pytest.mark.soak
@pytest.mark.slow
class TestCrashSoak:
    def test_kill9_worker_mid_query_zero_wrong_answers(self, tmp_path):
        """3 worker subprocesses behind a health FrontDoor; one is SIGKILLed
        under load. Every subsequent request must still succeed with the
        full correct answer -- rerouted, never lost, never stale."""
        root = tmp_path / "kill_data"
        root.mkdir()
        n = 60
        for i in range(3):
            write_marked_part(str(root), i, n=n)
        sys_path = tmp_path / "indexes"
        sys_path.mkdir()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = []
        try:
            for i in range(3):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-c",
                            _SOAK_WORKER,
                            str(root),
                            str(sys_path),
                            f"qs{i}",
                            "3600",
                            REPO_ROOT,
                        ],
                        stdin=subprocess.PIPE,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE,
                        text=True,
                        cwd=REPO_ROOT,
                        env=env,
                    )
                )
            urls = [p.stdout.readline().strip() for p in procs]
            assert all(u.startswith("http://") for u in urls), urls
            h = HealthTracker(failure_threshold=1, probe_interval_s=2.0)
            fd = FrontDoor(urls, health=h, failover=True)
            expect = {0: n, 1: n, 2: n}
            tenants = [f"tenant-{i}" for i in range(6)]
            for t in tenants:  # warm every worker's first-query compile
                fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=t)
            ex0 = counter_value("hs_frontdoor_failover_exhausted_total")
            os.kill(procs[0].pid, signal.SIGKILL)
            procs[0].wait(timeout=30)
            failed, wrong, worst = [], [], 0.0
            for i in range(30):
                t = tenants[i % len(tenants)]
                t0 = time.monotonic()
                try:
                    res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=t)
                except Exception as exc:
                    failed.append((t, repr(exc)))
                    continue
                worst = max(worst, time.monotonic() - t0)
                vals, cnts = np.unique(res["m"], return_counts=True)
                seen = dict(zip(vals.tolist(), cnts.tolist()))
                if seen != expect:
                    wrong.append((t, seen))
            assert failed == [], failed[:5]
            assert wrong == [], wrong[:5]
            assert worst < 15.0, f"failover latency blew the bound: {worst:.1f}s"
            # nothing was lost: no request exhausted every candidate
            assert counter_value("hs_frontdoor_failover_exhausted_total") == ex0
            dead_wid = next(
                w for w in fd.worker_ids if fd._workers[w] == urls[0].rstrip("/")
            )
            assert h.state_of(dead_wid) != "live"
        finally:
            for p in procs:
                try:
                    p.stdin.close()
                except Exception:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=30)
                except Exception:
                    p.kill()

    def test_kill9_refresher_mid_refresh_peer_takes_over(self, tmp_path):
        """A subprocess claims the refresh lease, wedges inside its refresh
        (injected 600s write latency, heartbeat still renewing), and is
        SIGKILLed. The parent's RefreshManager observes busy while the
        zombie's lease lives, then takes over after TTL and commits."""
        root = tmp_path / "lease_data"
        root.mkdir()
        for i in range(3):
            write_marked_part(str(root), i)
        sys_path = tmp_path / "indexes"
        sys_path.mkdir()
        ttl = 1.0
        parent = hst.Session(
            conf=fabric_conf(
                str(sys_path),
                "parent",
                **{
                    hst.keys.FABRIC_LEASE_ENABLED: True,
                    hst.keys.FABRIC_LEASE_TTL_SECONDS: ttl,
                    hst.keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS: ttl / 4.0,
                },
            )
        )
        try:
            hst.Hyperspace(parent).create_index(
                parent.read_parquet(str(root)),
                hst.CoveringIndexConfig("soakLease", ["c1"], ["m"]),
            )
            write_marked_part(str(root), 3)  # real drift for both refreshers
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    _LEASE_HOLDER,
                    str(root),
                    str(sys_path),
                    str(ttl),
                    REPO_ROOT,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO_ROOT,
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
            )
            try:
                assert proc.stdout.readline().strip() == "REFRESHING"
                # wait until the child provably holds the lease on the lake
                deadline = time.time() + 30
                while time.time() < deadline:
                    current, state = lease_mod.read_state(
                        str(sys_path), "refresh/soakLease"
                    )
                    if (
                        current == 1
                        and state is not None
                        and state.get("holder") == "child"
                        and float(state.get("expiresAt", 0.0)) > time.time()
                    ):
                        break
                    time.sleep(0.05)
                else:
                    pytest.fail("child never claimed the lease")
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait(timeout=30)
            finally:
                if proc.poll() is None:
                    proc.kill()
            # renewals stopped with the process; after TTL the parent takes over
            takeover0 = counter_value(
                "hs_fabric_lease_acquires_total", outcome="takeover"
            )
            rm = RefreshManager(parent)
            deadline = time.time() + 30
            outcome = "busy"
            while time.time() < deadline:
                outcome = rm.refresh_index("soakLease", "incremental")
                if outcome != "busy":
                    break
                time.sleep(0.25)
            assert outcome == "committed", outcome
            assert (
                counter_value("hs_fabric_lease_acquires_total", outcome="takeover")
                == takeover0 + 1
            )
            current, state = lease_mod.read_state(str(sys_path), "refresh/soakLease")
            assert current == 2  # the takeover token fenced the dead holder
            assert float(state["expiresAt"]) == 0.0  # and was released after
        finally:
            parent.fabric.stop()
