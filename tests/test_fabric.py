"""Scale-out serving fabric tests (hyperspace_tpu/fabric/): lake-persisted
commit records, the commit watcher's cross-process cache coherence (including
the two-Sessions staleness regression and Lamport sequence agreement), the
coherence sidecar's quarantine/SLO/rate-limit sharing, the torn-pin seqlock
in QueryServer.submit, the FrontDoor router + WorkerEndpoint HTTP shim, and
the default-off byte-identity guarantee. The multi-process endurance variant
rides at the bottom behind the ``soak`` marker."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.fabric import records
from hyperspace_tpu.fabric.frontdoor import (
    FrontDoor,
    WorkerEndpoint,
    merge_prometheus_texts,
    rendezvous_pick,
)
from hyperspace_tpu.lifecycle import CommitEvent, RefreshManager, SnapshotHandle
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.reliability.degrade import QUARANTINE
from hyperspace_tpu.serving import QueryServer

from tests.test_lifecycle import write_marked_part

pytestmark = pytest.mark.fabric

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def counter_value(name, **labels):
    return REGISTRY.counter(name, **labels).value


def fabric_conf(sys_path, node, **extra):
    """Fabric-on session conf with deterministic (manually-driven) loops:
    the watcher thread is off and the sidecar interval is effectively
    infinite, so tests call poll_once()/run_once() themselves."""
    conf = {
        hst.keys.SYSTEM_PATH: sys_path,
        hst.keys.FABRIC_ENABLED: True,
        hst.keys.FABRIC_NODE_ID: node,
        hst.keys.FABRIC_WATCHER_ENABLED: False,
        hst.keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 3600,
    }
    conf.update(extra)
    return conf


@pytest.fixture()
def data_root(tmp_path):
    root = tmp_path / "fabric_data"
    root.mkdir()
    for i in range(3):
        write_marked_part(str(root), i)
    return str(root)


@pytest.fixture()
def two_nodes(tmp_system_path, data_root):
    """Two fabric Sessions on one lake, s1 holding index ``fabIdx``; both
    drained (the create's commit record already replayed into s2)."""
    s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
    hst.Hyperspace(s1).create_index(
        s1.read_parquet(data_root), hst.CoveringIndexConfig("fabIdx", ["c1"], ["m"])
    )
    s2 = hst.Session(conf=fabric_conf(tmp_system_path, "n2"))
    s2.fabric.watcher.poll_once()
    yield s1, s2
    s2.fabric.stop()
    s1.fabric.stop()


# --- commit records (pure file-protocol units) -------------------------------


class TestCommitRecords:
    def test_append_read_round_trip_and_ordering(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxA", 4, "refresh-incremental", ("f1", "f2"), origin="n1")
        assert records.append_commit_record(sp, ev, seq=7) == 0
        assert records.append_commit_record(sp, ev, seq=8) == 1
        cdir = records.commits_dir(sp, "idxA")
        got = records.read_commit_records(cdir)
        assert [rid for rid, _ in got] == [0, 1]
        rec = got[0][1]
        assert rec["seq"] == 7 and rec["origin"] == "n1"
        assert rec["index"] == "idxA" and rec["logId"] == 4
        assert rec["kind"] == "refresh-incremental"
        assert rec["affectedFiles"] == ["f1", "f2"]
        assert rec["ts"] > 0

    def test_read_after_cursor(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxB", 1, "create", origin="n1")
        for seq in (1, 2, 3):
            records.append_commit_record(sp, ev, seq=seq)
        cdir = records.commits_dir(sp, "idxB")
        assert [rid for rid, _ in records.read_commit_records(cdir, after_id=1)] == [2]

    def test_exclusive_claim_skips_taken_slot(self, tmp_path):
        sp = str(tmp_path)
        cdir = records.commits_dir(sp, "idxC")
        os.makedirs(cdir)
        # a concurrent publisher already holds slot 0
        with open(os.path.join(cdir, f"{0:010d}"), "w") as f:
            f.write("{}")
        ev = CommitEvent("idxC", 1, "create", origin="n1")
        assert records.append_commit_record(sp, ev, seq=1) == 1

    def test_corrupt_record_skipped_and_counted(self, tmp_path):
        sp = str(tmp_path)
        ev = CommitEvent("idxD", 1, "create", origin="n1")
        records.append_commit_record(sp, ev, seq=1)
        cdir = records.commits_dir(sp, "idxD")
        with open(os.path.join(cdir, f"{1:010d}"), "w") as f:
            f.write("not json {")
        before = counter_value("hs_fabric_record_errors_total", op="commit-read")
        got = records.read_commit_records(cdir)
        assert [rid for rid, _ in got] == [0]
        assert counter_value("hs_fabric_record_errors_total", op="commit-read") == before + 1

    def test_node_files_exclude_self(self, tmp_path):
        sp = str(tmp_path)
        assert records.write_node_file(sp, "n1", {"strikes": {"i": 2}})
        assert records.write_node_file(sp, "n2", {"strikes": {"i": 5}})
        peers = records.read_peer_node_files(sp, "n1")
        assert list(peers) == ["n2"]
        assert peers["n2"]["strikes"] == {"i": 5}
        assert peers["n2"]["origin"] == "n2" and peers["n2"]["updatedAt"] > 0

    def test_node_id_is_filesystem_safe(self):
        assert records._safe_name("host:123/x") == "host_123_x"

    def test_fabric_paths_invisible_to_data_listing(self, tmp_path):
        from hyperspace_tpu.utils.file_utils import walk_data_files

        sp = str(tmp_path)
        records.append_commit_record(
            sp, CommitEvent("idxE", 1, "create", origin="n1"), seq=1
        )
        records.write_node_file(sp, "n1", {})
        assert list(walk_data_files(sp)) == []


# --- bus persistence + replay ------------------------------------------------


class TestBusPersistence:
    def test_defaults_publish_no_records_and_no_fabric(self, session, data_root):
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("offIdx", ["c1"], ["m"]),
        )
        assert session.fabric is None
        assert not os.path.exists(
            records.commits_dir(session.conf.system_path, "offIdx")
        )
        assert not os.path.exists(
            os.path.join(session.conf.system_path, records.FABRIC_DIR)
        )

    def test_publish_persists_stamped_record(self, two_nodes):
        s1, _ = two_nodes
        cdir = records.commits_dir(s1.conf.system_path, "fabIdx")
        got = records.read_commit_records(cdir)
        assert len(got) == 1
        rec = got[0][1]
        assert rec["kind"] == "create" and rec["origin"] == "n1"
        assert rec["seq"] == s1.lifecycle_bus.commit_seq

    def test_replay_is_a_lamport_merge_and_never_persists(self, two_nodes):
        s1, s2 = two_nodes
        bus = s2.lifecycle_bus
        base = bus.commit_seq
        ev = CommitEvent("fabIdx", None, "refresh-quick", origin="n3")
        bus.replay(ev, seq=base + 10)  # remote clock ahead: jump to it
        assert bus.commit_seq == base + 10
        bus.replay(ev, seq=base + 2)  # remote clock behind: still advance
        assert bus.commit_seq == base + 11
        bus.replay(ev)  # record without a seq
        assert bus.commit_seq == base + 12
        # replay never writes records (no echo back into the lake)
        cdir = records.commits_dir(s2.conf.system_path, "fabIdx")
        assert len(records.read_commit_records(cdir)) == 1

    def test_processes_agree_on_commit_seq(self, two_nodes, data_root):
        s1, s2 = two_nodes
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        assert s2.lifecycle_bus.commit_seq < s1.lifecycle_bus.commit_seq
        s2.fabric.watcher.poll_once()
        assert s2.lifecycle_bus.commit_seq == s1.lifecycle_bus.commit_seq


# --- the commit watcher ------------------------------------------------------


class TestCommitWatcher:
    def test_remote_commit_replays_and_purges(self, two_nodes, data_root):
        s1, s2 = two_nodes
        roster0 = counter_value("hs_lifecycle_invalidations_total", cache="roster")
        replay0 = counter_value(
            "hs_fabric_records_replayed_total", kind="refresh-incremental"
        )
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        assert s2.fabric.watcher.poll_once() == 1
        assert (
            counter_value("hs_fabric_records_replayed_total", kind="refresh-incremental")
            == replay0 + 1
        )
        # the replay ran the full invalidation path (roster TTL clear)
        assert (
            counter_value("hs_lifecycle_invalidations_total", cache="roster")
            >= roster0 + 1
        )

    def test_own_records_are_skipped(self, two_nodes):
        s1, _ = two_nodes
        skips0 = counter_value("hs_fabric_self_skips_total")
        assert s1.fabric.watcher.poll_once() == 0
        assert counter_value("hs_fabric_self_skips_total") == skips0 + 1

    def test_idle_polls_hit_the_mtime_fast_path(self, two_nodes):
        _, s2 = two_nodes
        w = s2.fabric.watcher
        assert w.poll_once() == 0  # drained by the fixture; records cursor
        # age the directory out of the settle window so the fast path is
        # eligible (fresh dirs are always re-listed; see _MTIME_SETTLE_S)
        cdir = records.commits_dir(s2.conf.system_path, "fabIdx")
        old = time.time() - 60
        os.utime(cdir, (old, old))
        w.poll_once()  # observes the aged mtime
        skips0 = counter_value("hs_fabric_poll_skips_total")
        assert w.poll_once() == 0
        assert counter_value("hs_fabric_poll_skips_total") == skips0 + 1

    @pytest.mark.parametrize(
        "watcher_on",
        [
            pytest.param(
                False,
                marks=pytest.mark.xfail(
                    strict=True,
                    reason="without the commit watcher a peer's refresh is "
                    "invisible until the roster TTL (300 s) expires: new "
                    "pins keep serving the superseded index version",
                ),
            ),
            pytest.param(True),
        ],
    )
    def test_two_sessions_staleness_regression(self, two_nodes, data_root, watcher_on):
        """The tentpole regression: process B must pin the version process A
        committed — with the watcher within one poll, without it not until
        TTL expiry (encoded as strict xfail)."""
        s1, s2 = two_nodes
        v1 = SnapshotHandle.capture(s2).index_version("fabIdx")  # primes TTL cache
        write_marked_part(data_root, 3)
        RefreshManager(s1).refresh_index("fabIdx", "incremental")
        v2 = SnapshotHandle.capture(s1).index_version("fabIdx")
        assert v2 != v1
        if watcher_on:
            assert s2.fabric.watcher.poll_once() >= 1
        assert SnapshotHandle.capture(s2).index_version("fabIdx") == v2

    def test_remote_quarantine_trip_opens_local_breaker(
        self, tmp_system_path, data_root
    ):
        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root), hst.CoveringIndexConfig("qIdx", ["c1"], ["m"])
        )
        # constructed last so the process-global registry binds to s2
        s2 = hst.Session(
            conf=fabric_conf(
                tmp_system_path, "n2", **{hst.keys.RELIABILITY_QUARANTINE_ENABLED: True}
            )
        )
        s2.fabric.watcher.poll_once()
        try:
            assert QUARANTINE.state_of("qIdx") == "closed"
            # n1's breaker trips: degrade.py publishes this event on n1's bus
            s1.lifecycle_bus.publish(CommitEvent("qIdx", None, "quarantine"))
            merged0 = counter_value("hs_fabric_quarantine_merged_total", index="qIdx")
            assert s2.fabric.watcher.poll_once() == 1
            assert QUARANTINE.state_of("qIdx") == "open"
            assert (
                counter_value("hs_fabric_quarantine_merged_total", index="qIdx")
                == merged0 + 1
            )
        finally:
            s2.fabric.stop()
            s1.fabric.stop()


# --- fast two-process-shaped coherence loop (tier-1) -------------------------


class TestCoherenceRoundLoop:
    def test_refresh_rounds_stay_fresh_under_polling(self, two_nodes, data_root):
        s1, s2 = two_nodes
        rm = RefreshManager(s1)
        s2.enable_hyperspace()
        for marker in (3, 4, 5):
            write_marked_part(data_root, marker)
            assert rm.refresh_index("fabIdx", "incremental") == "committed"
            assert s2.fabric.watcher.poll_once() == 1
            q = s2.read_parquet(data_root).filter(hst.col("c1") >= 0).select("m")
            seen = sorted(np.unique(q.collect()["m"]).tolist())
            assert seen == list(range(marker + 1)), f"stale after marker {marker}"
            assert (
                SnapshotHandle.capture(s2).index_version("fabIdx")
                == SnapshotHandle.capture(s1).index_version("fabIdx")
            )


# --- torn-pin seqlock in QueryServer.submit ----------------------------------


class TestTornPinSeqlock:
    def test_commit_racing_capture_forces_recapture(
        self, tmp_system_path, data_root, monkeypatch
    ):
        session = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("tornIdx", ["c1"], ["m"]),
        )
        session.enable_hyperspace()
        real_capture = SnapshotHandle.capture
        raced = {"n": 0}

        def racing_capture(sess):
            h = real_capture(sess)
            if raced["n"] == 0:
                raced["n"] += 1
                # a commit lands between the roster read and admission:
                # the captured handle is torn (its seq predates the commit)
                sess.lifecycle_bus.publish(
                    CommitEvent("tornIdx", None, "refresh-quick")
                )
            return h

        monkeypatch.setattr(SnapshotHandle, "capture", staticmethod(racing_capture))
        try:
            with QueryServer(session, workers=1, name="qsTorn") as srv:
                retries0 = counter_value(
                    "hs_fabric_snapshot_retries_total", server="qsTorn"
                )
                q = session.read_parquet(data_root).filter(hst.col("c1") >= 0).select("m")
                res = srv.query(q)
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                assert (
                    counter_value("hs_fabric_snapshot_retries_total", server="qsTorn")
                    == retries0 + 1
                )
                assert raced["n"] == 1  # exactly one re-capture healed the pin
        finally:
            session.fabric.stop()


# --- coherence sidecar -------------------------------------------------------


class _FakeServer:
    """Duck-typed QueryServer stand-in: just the accounting surfaces the
    sidecar publishes from and merges into."""

    def __init__(self, slo=None, admission=None):
        self.slo = slo
        self.admission = admission


class TestCoherenceSidecar:
    def test_publish_then_peer_merge_round_trip(self, tmp_system_path):
        from hyperspace_tpu.obs.slo import SloTracker
        from hyperspace_tpu.serving.scheduler import CostAwareScheduler

        s1 = hst.Session(conf=fabric_conf(tmp_system_path, "n1"))
        try:
            tracker = SloTracker(target_ms=100.0)
            sched = CostAwareScheduler(
                depth=16, default_timeout=None, tenant_rate=1.0, tenant_burst=10.0
            )
            fake = _FakeServer(slo=tracker, admission=sched)
            side = s1.fabric.sidecar
            side.attach_server(fake)
            tracker.record(0.01)  # good
            tracker.record(9.0)  # bad (over target)
            assert side.publish_once()
            mine = json.load(
                open(os.path.join(records.nodes_dir(tmp_system_path), "n1.json"))
            )
            assert mine["slo"]["default"] == {"good": 1, "bad": 1}

            # a peer's ledger lands in the lake; merging folds the deltas in
            records.write_node_file(
                tmp_system_path,
                "peer",
                {"slo": {"default": {"good": 0, "bad": 30}}, "drained": {"default": 5.0}},
            )
            assert side.merge_once() == 1
            # remote bad events now dominate the local burn window
            assert tracker.burn_rate(300.0) > 1.0
            good, bad = tracker._window_counts(tracker._tenant("default"), 300.0)
            assert (good, bad) == (1, 31)
            # remote drain debited the local bucket
            st = sched._tenants.get("default")
            assert st is not None and st.bucket.tokens <= st.bucket.burst - 5.0

            # re-merging an unchanged peer file is a no-op (delta semantics)
            side.merge_once()
            good2, bad2 = tracker._window_counts(tracker._tenant("default"), 300.0)
            assert (good2, bad2) == (good, bad)
        finally:
            s1.fabric.stop()

    def test_local_publish_ledger_excludes_remote_events(self):
        from hyperspace_tpu.obs.slo import SloTracker

        tracker = SloTracker(target_ms=100.0)
        tracker.record(0.01)
        tracker.note_remote("default", good=10, bad=10)
        # counts() is what the sidecar publishes: remote merges must never
        # echo back out, or peers would snowball each other's numbers
        assert tracker.counts() == {"default": (1, 0)}

    def test_remote_strikes_cross_local_threshold(self, tmp_system_path, data_root):
        s1 = hst.Session(
            conf=fabric_conf(
                tmp_system_path,
                "n1",
                **{
                    hst.keys.RELIABILITY_QUARANTINE_ENABLED: True,
                    hst.keys.RELIABILITY_QUARANTINE_THRESHOLD: 3,
                },
            )
        )
        hst.Hyperspace(s1).create_index(
            s1.read_parquet(data_root), hst.CoveringIndexConfig("strIdx", ["c1"], ["m"])
        )
        try:
            # one local strike: below threshold, breaker stays closed
            idx_file = os.path.join(tmp_system_path, "strIdx", "anyfile")
            QUARANTINE.note_corrupt(idx_file)
            assert QUARANTINE.state_of("strIdx") == "closed"
            assert QUARANTINE.local_strikes() == {"strIdx": 1}
            # two more strikes arrive from a peer: 1 + 2 crosses the threshold
            records.write_node_file(tmp_system_path, "peer", {"strikes": {"strIdx": 2}})
            s1.fabric.sidecar.merge_once()
            assert QUARANTINE.state_of("strIdx") == "open"
            # the merged remote count is never re-published as ours
            assert QUARANTINE.local_strikes() == {"strIdx": 1}
        finally:
            s1.fabric.stop()

    def test_external_drain_floors_at_empty(self):
        from hyperspace_tpu.serving.scheduler import TokenBucket

        b = TokenBucket(rate=1.0, burst=4.0)
        b.drain(2.5)
        assert b.tokens == pytest.approx(1.5)
        b.drain(100.0)  # a peer's burst can empty the bucket, never owe debt
        assert b.tokens == 0.0


# --- FrontDoor + WorkerEndpoint ----------------------------------------------


class TestFrontDoor:
    def test_rendezvous_stable_under_membership_permutation(self):
        nodes = ["qs0", "qs1", "qs2", "qs3"]
        for t in range(40):
            key = f"tenant-{t}"
            assert rendezvous_pick(key, nodes) == rendezvous_pick(key, nodes[::-1])

    def test_rendezvous_moves_only_departed_workers_tenants(self):
        nodes = ["qs0", "qs1", "qs2", "qs3"]
        tenants = [f"tenant-{t}" for t in range(60)]
        before = {t: rendezvous_pick(t, nodes) for t in tenants}
        after = {t: rendezvous_pick(t, nodes[:-1]) for t in tenants}
        moved = [t for t in tenants if before[t] != after[t]]
        assert moved and all(before[t] == "qs3" for t in moved)
        assert len(set(before.values())) == 4  # all workers get traffic

    def test_rendezvous_rejects_empty_membership(self):
        with pytest.raises(ValueError):
            rendezvous_pick("t", [])

    def test_merge_prometheus_texts_one_header_per_family(self):
        merged = merge_prometheus_texts(
            [
                '# HELP hs_x doc\n# TYPE hs_x counter\nhs_x{server="qs0"} 1\n',
                '# HELP hs_x doc\n# TYPE hs_x counter\nhs_x{server="qs1"} 2\n',
            ]
        )
        lines = merged.splitlines()
        assert lines.count("# HELP hs_x doc") == 1
        assert lines.count("# TYPE hs_x counter") == 1
        assert 'hs_x{server="qs0"} 1' in lines and 'hs_x{server="qs1"} 2' in lines

    def test_in_process_routing_and_aggregation(self, session, data_root):
        hst.Hyperspace(session).create_index(
            session.read_parquet(data_root),
            hst.CoveringIndexConfig("fdIdx", ["c1"], ["m"]),
        )
        session.enable_hyperspace()
        session.register_view("t", session.read_parquet(data_root))
        with QueryServer(session, workers=1, name="qsA") as a, QueryServer(
            session, workers=1, name="qsB"
        ) as b:
            fd = FrontDoor([a, b])
            assert fd.worker_ids == ["qsA", "qsB"]
            routed0 = {
                w: counter_value("hs_fabric_frontdoor_requests_total", worker=w)
                for w in fd.worker_ids
            }
            picks = set()
            for t in range(8):
                tenant = f"tenant-{t}"
                res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant=tenant)
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                picks.add(fd.pick(tenant))
            assert picks == {"qsA", "qsB"}  # both workers took traffic
            routed = sum(
                counter_value("hs_fabric_frontdoor_requests_total", worker=w)
                - routed0[w]
                for w in fd.worker_ids
            )
            assert routed == 8
            merged = fd.metrics_text()
            assert 'server="qsA"' in merged and 'server="qsB"' in merged
            assert sorted(fd.statusz()) == ["qsA", "qsB"]

    def test_worker_endpoint_http_round_trip(self, session, data_root):
        session.enable_hyperspace()
        session.register_view("t", session.read_parquet(data_root))
        with QueryServer(session, workers=1, name="qsHttp") as srv:
            with WorkerEndpoint(srv) as ep:
                fd = FrontDoor([ep.url])
                res = fd.query("SELECT m FROM t WHERE c1 >= 0", tenant="alice")
                assert sorted(np.unique(res["m"]).tolist()) == [0, 1, 2]
                assert 'server="qsHttp"' in fd.metrics_text()
                with urllib.request.urlopen(f"{ep.url}/healthz", timeout=30) as r:
                    health = json.loads(r.read().decode("utf-8"))
                assert health == {"ok": True, "server": "qsHttp"}
                # missing sql -> 400 with a typed error body
                try:
                    urllib.request.urlopen(f"{ep.url}/query", timeout=30)
                    assert False, "expected HTTP 400"
                except urllib.error.HTTPError as exc:
                    assert exc.code == 400
                # a failing query surfaces as a routed RuntimeError
                with pytest.raises(RuntimeError, match="failed"):
                    fd.query("SELECT nope FROM missing_table")


# --- default-off byte identity ----------------------------------------------

# Runs the same workload in two fresh interpreters — defaults vs fabric-on —
# and compares plans and answers. A subprocess is the only honest probe for
# "no hs_fabric_* families": this test process's registry already carries
# them from the tests above.
_IDENTITY_SCRIPT = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, sys.argv[4])
import numpy as np
import pyarrow as pa, pyarrow.parquet as pq
import hyperspace_tpu as hst

root, sys_path, fabric_on = sys.argv[1], sys.argv[2], sys.argv[3] == "1"
conf = {hst.keys.SYSTEM_PATH: sys_path}
if fabric_on:
    conf.update({hst.keys.FABRIC_ENABLED: True, hst.keys.FABRIC_NODE_ID: "nX",
                 hst.keys.FABRIC_WATCHER_ENABLED: False,
                 hst.keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 3600})
sess = hst.Session(conf=conf)
hst.Hyperspace(sess).create_index(
    sess.read_parquet(root), hst.CoveringIndexConfig("bIdx", ["c1"], ["m"]))
sess.enable_hyperspace()
sess.register_view("t", sess.read_parquet(root))
from hyperspace_tpu.serving import QueryServer
with QueryServer(sess, workers=1, name="qsId") as srv:
    res = srv.query("SELECT m FROM t WHERE c1 >= 0")
    q = sess.sql("SELECT m FROM t WHERE c1 >= 0")
    plan = repr(q.optimized_plan())
    metrics = srv.prometheus_text()
print(json.dumps({
    "rows": sorted(np.asarray(res["m"]).tolist()),
    "plan": plan,
    "fabric_families": sorted({l.split("{")[0].split()[0]
                               for l in metrics.splitlines()
                               if l and not l.startswith("#")
                               and l.startswith("hs_fabric_")}),
    "fabric_dirs": [p for p in (os.path.join(sys_path, "_fabric"),
                                os.path.join(sys_path, "bIdx", "_hyperspace_log", "_commits"))
                    if os.path.exists(p)],
}))
"""


class TestDefaultOffByteIdentity:
    @pytest.mark.slow
    def test_disabled_fabric_changes_nothing(self, tmp_path, data_root):
        outs = {}
        for flag in ("0", "1"):
            sp = tmp_path / f"identity_{flag}"
            sp.mkdir()
            proc = subprocess.run(
                [sys.executable, "-c", _IDENTITY_SCRIPT, data_root, str(sp), flag, REPO_ROOT],
                capture_output=True,
                text=True,
                timeout=240,
                cwd=REPO_ROOT,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs[flag] = json.loads(proc.stdout.strip().splitlines()[-1])
        off, on = outs["0"], outs["1"]
        # at defaults: no fabric metric families, nothing fabric-shaped on disk
        assert off["fabric_families"] == []
        assert off["fabric_dirs"] == []
        # the fabric-on run persisted records but served identical plans/rows
        assert on["fabric_dirs"], "fabric-on run wrote no records"
        assert on["plan"] == off["plan"]
        assert on["rows"] == off["rows"]


# --- multi-process soak ------------------------------------------------------

_SOAK_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, sys.argv[5])
import hyperspace_tpu as hst
from hyperspace_tpu.serving import QueryServer
from hyperspace_tpu.fabric import WorkerEndpoint

root, sys_path, name, interval = sys.argv[1], sys.argv[2], sys.argv[3], float(sys.argv[4])
sess = hst.Session(conf={
    hst.keys.SYSTEM_PATH: sys_path,
    hst.keys.FABRIC_ENABLED: True,
    hst.keys.FABRIC_NODE_ID: name,
    hst.keys.FABRIC_POLL_INTERVAL_SECONDS: interval,
})
sess.enable_hyperspace()
sess.register_view("t", sess.read_parquet(root))

def refresh_views(event):
    # a DataFrame freezes its source listing at read time; re-resolving the
    # served views on every (replayed) commit is the fabric worker pattern
    sess.register_view("t", sess.read_parquet(root))

sess.lifecycle_bus.subscribe(refresh_views)
with QueryServer(sess, workers=2, name=name) as srv:
    with WorkerEndpoint(srv) as ep:
        print(ep.url, flush=True)
        sys.stdin.readline()  # serve until the parent closes stdin
"""


@pytest.mark.soak
@pytest.mark.slow
class TestMultiProcessSoak:
    def test_two_servers_one_refresher_zero_stale(self, tmp_path):
        """2 fabric server subprocesses + this process refreshing: after each
        commit settles for one poll interval, every routed answer must carry
        all committed markers, unturned."""
        root = tmp_path / "soak_data"
        root.mkdir()
        n = 120
        initial = 3
        for i in range(initial):
            write_marked_part(str(root), i, n=n)
        sys_path = tmp_path / "indexes"
        sys_path.mkdir()
        poll_s = 0.2

        writer = hst.Session(
            conf=fabric_conf(str(sys_path), "writer")
        )
        hst.Hyperspace(writer).create_index(
            writer.read_parquet(str(root)),
            hst.CoveringIndexConfig("soakFab", ["c1"], ["m"]),
        )
        rm = RefreshManager(writer)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = []
        try:
            for i in range(2):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-c",
                            _SOAK_WORKER,
                            str(root),
                            str(sys_path),
                            f"qs{i}",
                            str(poll_s),
                            REPO_ROOT,
                        ],
                        stdin=subprocess.PIPE,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE,
                        text=True,
                        cwd=REPO_ROOT,
                        env=env,
                    )
                )
            urls = [p.stdout.readline().strip() for p in procs]
            assert all(u.startswith("http://") for u in urls), urls
            fd = FrontDoor(urls)

            violations = []
            committed = list(range(initial))
            for rnd in range(3):
                marker = initial + rnd
                write_marked_part(str(root), marker, n=n)
                assert rm.refresh_index("soakFab", "incremental") == "committed"
                committed.append(marker)
                # staleness bound: one poll interval (+ settle margin)
                time.sleep(poll_s * 3 + 0.3)
                for t in range(4):
                    res = fd.query(
                        "SELECT m FROM t WHERE c1 >= 0", tenant=f"tenant-{t}"
                    )
                    vals, cnts = np.unique(res["m"], return_counts=True)
                    seen = dict(zip(vals.tolist(), cnts.tolist()))
                    for mk, c in seen.items():
                        if c != n:
                            violations.append(("torn", rnd, mk, c))
                    for mk in committed:
                        if seen.get(mk) != n:
                            violations.append(("stale", rnd, mk, seen.get(mk)))
            assert violations == [], violations[:10]
        finally:
            for p in procs:
                try:
                    p.stdin.close()
                except Exception:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=30)
                except Exception:
                    p.kill()
            writer.fabric.stop()
