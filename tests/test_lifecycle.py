"""Live-data lifecycle tests (hyperspace_tpu/lifecycle/): snapshot pinning,
the commit/invalidation bus, the background refresh manager (including crash
safety under injected log-manager faults), hybrid-scan threshold re-gating at
rule time, device-side lineage delete filtering, and a fast deterministic
refresh-while-serving soak. The long endurance variant lives in
test_lifecycle_soak.py behind the ``soak`` marker."""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.actions.base import NoChangesException
from hyperspace_tpu.lifecycle import (
    CommitEvent,
    InvalidationBus,
    RefreshManager,
    SnapshotHandle,
    current_snapshot,
    snapshot_scope,
)
from hyperspace_tpu.manager import CachingIndexCollectionManager
from hyperspace_tpu.models.log_manager import IndexLogManagerFactory
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.plan import logical as L

from tests.test_e2e_rules import assert_batches_equal

pytestmark = pytest.mark.lifecycle


# --- data helpers ------------------------------------------------------------


def write_part(root, idx, n=250, seed=0):
    rng = np.random.default_rng(seed + idx)
    t = pa.table(
        {
            "c1": rng.integers(0, 100, n).astype(np.int64),
            "c2": rng.integers(0, 1000, n).astype(np.int64),
        }
    )
    # write-then-rename: a concurrent directory listing must never observe a
    # half-written file (the soak's torn-result check relies on this)
    final = os.path.join(root, f"part-{idx:05d}.parquet")
    tmp = final + ".tmp"
    pq.write_table(t, tmp)
    os.replace(tmp, final)
    return final


def write_marked_part(root, marker, n=120):
    """One file whose rows all carry ``m == marker`` — the soak's unit of
    all-or-nothing visibility."""
    t = pa.table(
        {
            "c1": (np.arange(n, dtype=np.int64) * 13) % 100,
            "m": np.full(n, marker, dtype=np.int64),
        }
    )
    final = os.path.join(root, f"part-{marker:05d}.parquet")
    tmp = final + ".tmp"
    pq.write_table(t, tmp)
    os.replace(tmp, final)
    return final


@pytest.fixture()
def mutable_data(tmp_path):
    root = tmp_path / "mutable"
    root.mkdir()
    for i in range(3):
        write_part(str(root), i)
    return str(root)


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def counter_value(name, **labels):
    return REGISTRY.counter(name, **labels).value


# --- snapshot pinning --------------------------------------------------------


class TestSnapshotPin:
    def test_capture_roster_and_lookup(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        entry = hs.create_index(df, hst.CoveringIndexConfig("pinA", ["c1"], ["c2"]))
        before = counter_value("hs_snapshot_pins_total")
        h = SnapshotHandle.capture(session)
        assert counter_value("hs_snapshot_pins_total") == before + 1
        assert ("pinA", entry.id) in h.roster
        assert h.get_index("pinA").id == entry.id
        assert h.index_version("pinA") == entry.id
        assert h.get_index("nope") is None and h.index_version("nope") is None

    def test_scope_is_contextual_and_none_is_noop(self, session):
        assert current_snapshot() is None
        with snapshot_scope(None) as got:
            assert got is None and current_snapshot() is None
        h = SnapshotHandle([], commit_seq=7)
        with snapshot_scope(h):
            assert current_snapshot() is h
            with snapshot_scope(None):
                # None never *unpins* — call sites that branch on "pinning
                # disabled" must not strip an outer request's pin
                assert current_snapshot() is h
        assert current_snapshot() is None

    def test_pin_freezes_roster_across_commit(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("pinB", ["c1"], ["c2"]))
        h = SnapshotHandle.capture(session)
        old_id = h.index_version("pinB")

        write_part(mutable_data, 3, seed=11)
        hs.refresh_index("pinB", "incremental")
        live = session.index_manager.get_index("pinB")
        assert live.id > old_id

        # pinned resolution still answers with the pre-commit version …
        with snapshot_scope(h):
            assert session.index_manager.get_index("pinB").id == old_id
            assert [e.id for e in session.index_manager.get_indexes() if e.name == "pinB"] == [old_id]
            # … and a nested capture is idempotent (no forward time-travel)
            assert SnapshotHandle.capture(session).roster == h.roster
        # unpinned resolution sees the commit
        assert session.index_manager.get_index("pinB").id == live.id

    def test_commit_seq_read_before_roster(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("pinC", ["c1"], ["c2"]))
        seq = session.lifecycle_bus.commit_seq
        h = SnapshotHandle.capture(session)
        assert h.commit_seq == seq  # create's commit already counted


# --- commit/invalidation bus -------------------------------------------------


class TestInvalidationBus:
    def test_commit_seq_counts_real_commits_only(self, session, hs, mutable_data):
        bus = session.lifecycle_bus
        df = session.read_parquet(mutable_data)
        seq0 = bus.commit_seq
        c0 = counter_value("hs_lifecycle_commits_total")
        hs.create_index(df, hst.CoveringIndexConfig("busA", ["c1"], ["c2"]))
        assert bus.commit_seq == seq0 + 1
        assert counter_value("hs_lifecycle_commits_total") == c0 + 1
        # an idempotent no-change refresh must NOT publish a commit
        with pytest.raises(NoChangesException):
            hs.refresh_index("busA", "incremental")
        assert bus.commit_seq == seq0 + 1

    def test_mutations_publish_typed_events(self, session, hs, mutable_data):
        bus = session.lifecycle_bus
        events = []
        bus.subscribe(events.append)
        try:
            df = session.read_parquet(mutable_data)
            old = hs.create_index(df, hst.CoveringIndexConfig("busB", ["c1"], ["c2"]))
            write_part(mutable_data, 3, seed=5)
            new = hs.refresh_index("busB", "incremental")
        finally:
            bus.unsubscribe(events.append)
        kinds = [e.kind for e in events]
        assert kinds == ["create", "refresh-incremental"]
        assert events[0].index_name == "busB" and events[0].log_id == old.id
        refresh_ev = events[1]
        assert refresh_ev.log_id == new.id
        # the refresh supersedes the previous entry's index data files
        assert set(old.content.files) <= set(refresh_ev.affected_files)

    def test_broken_subscriber_does_not_block_commit(self, session, hs, mutable_data):
        bus = session.lifecycle_bus

        def boom(event):
            raise RuntimeError("subscriber bug")

        bus.subscribe(boom)
        try:
            df = session.read_parquet(mutable_data)
            hs.create_index(df, hst.CoveringIndexConfig("busC", ["c1"], ["c2"]))
        finally:
            bus.unsubscribe(boom)
        assert session.index_manager.get_index("busC") is not None

    def test_publish_clears_roster_ttl_cache(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("busD", ["c1"], ["c2"]))
        mgr = session.index_manager
        mgr.get_indexes()  # warm the TTL cache
        assert mgr._cache.get() is not None
        r0 = counter_value("hs_lifecycle_invalidations_total", cache="roster")
        counts = session.lifecycle_bus.publish(CommitEvent("busD", 99, "test"))
        assert counts["roster"] == 1
        assert mgr._cache.get() is None
        assert counter_value("hs_lifecycle_invalidations_total", cache="roster") == r0 + 1

    def test_publish_purges_byte_caches_for_affected_files(self, session, hs, mutable_data):
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec import io as IO
        from hyperspace_tpu.serving.bucket_cache import BucketCache

        victim = os.path.join(mutable_data, "part-00000.parquet")
        other = os.path.join(mutable_data, "part-00001.parquet")

        bc = BucketCache(1 << 22)
        bc.read([victim], ["c1"])
        bc.read([other], ["c1"])
        session.bucket_cache = bc

        io_victim_key = (victim, 1, 2, ("c1",))
        io_other_key = (other, 1, 2, ("c1",))
        IO._io_cache.put(io_victim_key, {"c1": np.zeros(1, dtype=np.int64)}, 8)
        IO._io_cache.put(io_other_key, {"c1": np.zeros(1, dtype=np.int64)}, 8)

        dev_victim_key = (((victim, 1, 2),), "c1", "mesh-fp")
        dev_other_key = (((other, 1, 2),), "c1", "mesh-fp")
        D._device_cache_put(dev_victim_key, ("arr", None, 1), 8)
        D._device_cache_put(dev_other_key, ("arr", None, 1), 8)

        try:
            counts = session.lifecycle_bus.publish(
                CommitEvent("whatever", 1, "test", affected_files=[victim])
            )
            # io may exceed 1: the bucket read itself populated the real io
            # cache for the victim file, and the purge sweeps that entry too
            assert counts["bucket"] == 1 and counts["io"] >= 1 and counts["device"] == 1
            # untouched files stay cached
            assert IO._io_cache.get(io_other_key) is not None
            assert IO._io_cache.get(io_victim_key) is None
            assert D._device_cache_get(dev_other_key) is not None
            assert D._device_cache_get(dev_victim_key) is None
        finally:
            del session.bucket_cache
            bc.shutdown()
            for k in (io_victim_key, io_other_key):
                IO._io_cache.discard(k)
            for k in (dev_victim_key, dev_other_key):
                D._device_cache.discard(k)

    def test_purge_primitives_direct(self):
        from hyperspace_tpu.exec.io import _key_mentions_path
        from hyperspace_tpu.utils.lru import BytesLRU

        lru = BytesLRU(1 << 16)
        lru.put("k", "v", 4)
        assert lru.discard("k") is True
        assert lru.discard("k") is False  # second discard is a no-op
        assert lru.get("k") is None

        # recursive key scan covers file, concat and row-group key shapes
        assert _key_mentions_path(("a.pq", 1, 2, None), {"a.pq"})
        assert _key_mentions_path((("a.pq", 1, 2), ("b.pq", 3, 4)), {"b.pq"})
        assert _key_mentions_path(((("a.pq", 1, 2),), ("rg", 0)), {"a.pq"})
        assert not _key_mentions_path(("a.pq", 1, 2), {"c.pq"})


# --- refresh manager ---------------------------------------------------------


class TestRefreshManager:
    def test_no_drift_polls_fresh(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmA", ["c1"], ["c2"]))
        rm = RefreshManager(session)
        entry = session.index_manager.get_index("rmA")
        d = rm.drift(entry)
        assert d is not None and not d.has_drift
        assert rm.decide(d) is None
        assert rm.poll_once() == [{"index": "rmA", "mode": None, "outcome": "fresh"}]

    def test_auto_mode_picks_quick_then_incremental(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmB", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=21)  # 1 of 4 files appended (~25% of bytes)
        rm = RefreshManager(session)
        entry = session.index_manager.get_index("rmB")
        d = rm.drift(entry)
        assert d.appended_files == 1 and d.deleted_files == 0
        assert 0.0 < d.appended_ratio < 0.5

        # below the appended threshold: hybrid scan absorbs it, quick refresh
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        assert rm.decide(d) == "quick"
        # past the threshold: the candidate gate would reject — incremental
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.01)
        assert rm.decide(d) == "incremental"

    def test_pinned_mode_overrides_auto(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmC", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=22)
        rm = RefreshManager(session)
        d = rm.drift(session.index_manager.get_index("rmC"))
        session.conf.set(hst.keys.LIFECYCLE_REFRESH_MODE, "full")
        assert rm.decide(d) == "full"
        session.conf.set(hst.keys.LIFECYCLE_REFRESH_MODE, "bogus")
        assert rm.decide(d) is None

    def test_poll_commits_then_converges(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmD", ["c1"], ["c2"]))
        old_id = session.index_manager.get_index("rmD").id
        write_part(mutable_data, 3, seed=23)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.01)
        rm = RefreshManager(session)
        c0 = counter_value("hs_lifecycle_refresh_total", mode="incremental", outcome="committed")
        assert rm.poll_once() == [
            {"index": "rmD", "mode": "incremental", "outcome": "committed"}
        ]
        assert session.index_manager.get_index("rmD").id > old_id
        assert (
            counter_value("hs_lifecycle_refresh_total", mode="incremental", outcome="committed")
            == c0 + 1
        )
        # drift fully folded in: the next poll sees a fresh index
        assert rm.poll_once() == [{"index": "rmD", "mode": None, "outcome": "fresh"}]

    def test_single_writer_busy_and_no_changes(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmE", ["c1"], ["c2"]))
        rm = RefreshManager(session)
        # a racing writer holds the per-index lock: skip, don't double-build
        lock = rm._lock_for("rmE")
        assert lock.acquire(blocking=False)
        try:
            assert rm.refresh_index("rmE", "incremental") == "busy"
        finally:
            lock.release()
        # no drift: the action raises NoChangesException — converged
        assert rm.refresh_index("rmE", "incremental") == "no-changes"

    def test_background_thread_commits_drift(self, session, hs, mutable_data):
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("rmF", ["c1"], ["c2"]))
        old_id = session.index_manager.get_index("rmF").id
        write_part(mutable_data, 3, seed=24)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.01)
        rm = RefreshManager(session, interval_seconds=0.05)
        rm.start()
        try:
            rm.start()  # idempotent second start
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if session.index_manager.get_index("rmF").id > old_id:
                    break
                time.sleep(0.05)
            assert session.index_manager.get_index("rmF").id > old_id
        finally:
            rm.stop()
        assert rm._thread is None


class FlakyLogManagerFactory(IndexLogManagerFactory):
    """Wraps real log managers; while armed, the next ``write_log`` fails —
    a crash injected mid-action, before any stable-pointer move."""

    def __init__(self):
        self.armed = False
        self.failures = 0

    def create(self, index_path):
        real = super().create(index_path)
        factory = self

        class Flaky:
            def __getattr__(self, attr):
                return getattr(real, attr)

            def write_log(self, log_id, entry):
                if factory.armed:
                    factory.armed = False
                    factory.failures += 1
                    raise OSError("injected log write failure")
                return real.write_log(log_id, entry)

        return Flaky()


class TestRefreshCrashSafety:
    def test_failed_refresh_keeps_prior_active_then_retry_converges(
        self, session, mutable_data
    ):
        flaky = FlakyLogManagerFactory()
        session._index_manager = CachingIndexCollectionManager(
            session, log_manager_factory=flaky
        )
        hs = hst.Hyperspace(session)
        df = session.read_parquet(mutable_data)
        created = hs.create_index(df, hst.CoveringIndexConfig("crashA", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=31)

        rm = RefreshManager(session)
        bus = session.lifecycle_bus
        seq0 = bus.commit_seq
        e0 = counter_value("hs_lifecycle_refresh_total", mode="incremental", outcome="error")

        # crash mid-refresh: outcome=error, no commit published, and the
        # prior ACTIVE entry still serves both metadata and queries
        flaky.armed = True
        assert rm.refresh_index("crashA", "incremental") == "error"
        assert flaky.failures == 1
        assert bus.commit_seq == seq0
        assert (
            counter_value("hs_lifecycle_refresh_total", mode="incremental", outcome="error")
            == e0 + 1
        )
        entry = session.index_manager.get_index("crashA")
        assert entry.id == created.id and entry.state == "ACTIVE"

        q = session.read_parquet(mutable_data).filter(hst.col("c1") == 7).select("c2")
        session.enable_hyperspace()
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())

        # retry re-runs the same diff and commits exactly once
        assert rm.refresh_index("crashA", "incremental") == "committed"
        assert bus.commit_seq == seq0 + 1
        new_id = session.index_manager.get_index("crashA").id
        assert new_id > created.id

        # a second retry after the commit is idempotent: no drift, no commit
        assert rm.refresh_index("crashA", "incremental") == "no-changes"
        assert bus.commit_seq == seq0 + 1
        assert session.index_manager.get_index("crashA").id == new_id


# --- hybrid-scan threshold re-gating at rule time (satellite) ----------------


class TestHybridThresholdRegating:
    def _index_scans(self, q):
        return [
            p
            for p in L.collect(q.optimized_plan(), lambda x: True)
            if isinstance(p, L.IndexScan)
        ]

    def test_tightened_appended_threshold_rejects_on_next_rewrite(
        self, session, hs, mutable_data
    ):
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.9)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("gateA", ["c1"], ["c2"]))
        write_part(mutable_data, 3, seed=41)

        session.enable_hyperspace()
        df2 = session.read_parquet(mutable_data)
        q = df2.filter(hst.col("c1") == 7).select("c2")
        assert self._index_scans(q), "loose threshold: hybrid scan applies the index"

        # tighten the conf: the very next rewrite must re-gate and reject,
        # without waiting for the roster TTL cache to expire
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.0001)
        q2 = session.read_parquet(mutable_data).filter(hst.col("c1") == 7).select("c2")
        assert not self._index_scans(q2)
        session.disable_hyperspace()
        assert_batches_equal(q2.collect(), q2.collect())

    def test_tightened_deleted_threshold_rejects_on_next_rewrite(
        self, session, hs, mutable_data
    ):
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.9)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("gateB", ["c1"], ["c2"]))
        os.remove(os.path.join(mutable_data, "part-00002.parquet"))

        session.enable_hyperspace()
        q = session.read_parquet(mutable_data).filter(hst.col("c1") == 7).select("c2")
        assert self._index_scans(q), "loose threshold: delete-tolerant hybrid scan"

        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.0001)
        q2 = session.read_parquet(mutable_data).filter(hst.col("c1") == 7).select("c2")
        assert not self._index_scans(q2)
        session.disable_hyperspace()
        assert_batches_equal(q2.collect(), q2.collect())


# --- device-side lineage delete filtering ------------------------------------


class TestDeviceLineage:
    def test_matcher_accepts_not_in_int_literals(self):
        from hyperspace_tpu.exec.executor import Executor
        from hyperspace_tpu.plan.expr import Col, In, Lit, Not

        cond = Not(In(Col("_data_file_id"), [Lit(3), Lit(1), Lit(2)]))
        assert Executor._lineage_not_in(cond) == ("_data_file_id", [3, 1, 2])
        # non-integer literals, non-Col children and other shapes don't match
        assert Executor._lineage_not_in(Not(In(Col("x"), [Lit("a")]))) is None
        assert Executor._lineage_not_in(Not(In(Lit(1), [Lit(2)]))) is None
        assert Executor._lineage_not_in(In(Col("x"), [Lit(1)])) is None

    def test_mask_matches_host_not_in_oracle(self, session):
        from hyperspace_tpu.exec.lineage import lineage_delete_mask

        rng = np.random.default_rng(7)
        for n, ids in [
            (1000, [3, 17, 999999]),     # some present, some absent
            (257, []),                   # empty delete set: all kept
            (64, list(range(64))),       # everything deleted
            (5, [0]),                    # tiny batch
        ]:
            col = rng.integers(0, 500, n).astype(np.int64)
            if ids and n == 64:
                col = np.arange(64, dtype=np.int64)  # force full deletion
            batch = {"_data_file_id": col}
            got = lineage_delete_mask(session, batch, "_data_file_id", ids)
            want = ~np.isin(col, np.asarray(ids, dtype=np.int64))
            np.testing.assert_array_equal(got, want), (n, ids)
            assert got.dtype == np.bool_

    def test_duplicate_and_unsorted_ids(self, session):
        from hyperspace_tpu.exec.lineage import lineage_delete_mask

        col = np.array([5, 1, 9, 5, 2], dtype=np.int64)
        got = lineage_delete_mask(session, {"f": col}, "f", [9, 5, 5, 9])
        np.testing.assert_array_equal(got, np.array([False, True, False, False, True]))

    def test_unsupported_inputs_raise(self, session):
        from hyperspace_tpu.exec.device import DeviceUnsupported
        from hyperspace_tpu.exec.lineage import lineage_delete_mask

        with pytest.raises(DeviceUnsupported):
            lineage_delete_mask(session, {"f": np.zeros(4)}, "f", [1])  # float column
        with pytest.raises(DeviceUnsupported):
            lineage_delete_mask(session, {"f": np.zeros(4, dtype=np.int64)}, "g", [1])

    def test_hlo_contract_zero_collectives(self, session):
        from hyperspace_tpu.check import hlo_lint
        from hyperspace_tpu.exec.lineage import lineage_delete_mask

        session.conf.set("hyperspace.check.hlo.enabled", True)
        col = np.arange(9000, dtype=np.int64)
        got = lineage_delete_mask(session, {"f": col}, "f", [5, 6, 7])
        assert got.sum() == 9000 - 3
        bad = [f for f in hlo_lint.runtime_violations() if "lineage-antijoin" in f.path]
        assert bad == [], "\n".join(f.render() for f in bad)

    def test_e2e_delete_filter_device_equals_host(self, session, hs, mutable_data):
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.9)
        df = session.read_parquet(mutable_data)
        hs.create_index(df, hst.CoveringIndexConfig("linA", ["c1"], ["c2"]))
        os.remove(os.path.join(mutable_data, "part-00001.parquet"))

        session.enable_hyperspace()
        q = session.read_parquet(mutable_data).filter(hst.col("c1") < 50).select("c2")

        # device path for any batch size
        session.conf.set(hst.keys.LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS, 1)
        on_device = q.collect()
        # host oracle: device lineage disabled entirely
        session.conf.set(hst.keys.LIFECYCLE_DEVICE_LINEAGE_ENABLED, False)
        on_host = q.collect()
        assert_batches_equal(on_device, on_host)

        # hyperspace off ground truth
        session.disable_hyperspace()
        assert_batches_equal(on_device, q.collect())
        session.enable_hyperspace()

        # min-rows gate: below the floor the host path serves and the
        # fallback is counted
        session.conf.set(hst.keys.LIFECYCLE_DEVICE_LINEAGE_ENABLED, True)
        session.conf.set(hst.keys.LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS, 10**9)
        f0 = counter_value("hs_device_fallback_total", op="lineage", reason="min-rows")
        small = q.collect()
        assert_batches_equal(small, on_host)
        assert counter_value("hs_device_fallback_total", op="lineage", reason="min-rows") > f0


# --- refresh-while-serving soak (fast deterministic tier-1 variant) ----------


def run_refresh_serving_soak(session, tmp_path, rounds, workers, initial_files=3, n=120):
    """Shared soak driver (the long variant in test_lifecycle_soak.py reuses
    it with bigger numbers). Returns the list of violations — empty on a
    clean run — plus summary counters for the caller to assert on."""
    from hyperspace_tpu.serving import QueryServer

    root = tmp_path / "soak"
    root.mkdir()
    for i in range(initial_files):
        write_marked_part(str(root), i, n=n)

    session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.95)
    session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.95)
    hs_api = hst.Hyperspace(session)
    df = session.read_parquet(str(root))
    hs_api.create_index(df, hst.CoveringIndexConfig("soakIdx", ["c1"], ["m"]))
    session.enable_hyperspace()

    bus = session.lifecycle_bus
    rm = RefreshManager(session)
    seq_at_create = bus.commit_seq

    state_lock = threading.Lock()
    committed = list(range(initial_files))  # markers refresh-committed so far
    violations = []
    stop = threading.Event()
    queries_done = [0]

    def query_loop():
        while not stop.is_set():
            with state_lock:
                need = list(committed)
            try:
                q = session.read_parquet(str(root)).filter(hst.col("c1") >= 0).select("m")
                res = server.submit(q).result(timeout=60)
            except Exception as exc:  # admission overflow etc. — not a staleness bug
                violations.append(("query-error", repr(exc)))
                continue
            vals, cnts = np.unique(res["m"], return_counts=True)
            seen = dict(zip(vals.tolist(), cnts.tolist()))
            for mk, c in seen.items():
                if c != n:
                    violations.append(("torn", mk, c))
            for mk in need:
                if seen.get(mk) != n:
                    violations.append(("stale", mk, seen.get(mk)))
            queries_done[0] += 1

    with QueryServer(session, workers=workers) as server:
        threads = [threading.Thread(target=query_loop) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            for r in range(rounds):
                marker = initial_files + r
                write_marked_part(str(root), marker, n=n)
                outcome = rm.refresh_index("soakIdx", "incremental")
                if outcome != "committed":
                    violations.append(("refresh", marker, outcome))
                    continue
                with state_lock:
                    committed.append(marker)
                time.sleep(0.02)  # let a few queries land between commits
        finally:
            stop.set()
            for t in threads:
                t.join(30)

    return {
        "violations": violations,
        "queries": queries_done[0],
        "commits": bus.commit_seq - seq_at_create,
        "final_markers": list(committed),
    }


class TestRefreshWhileServing:
    def test_soak_fast_no_stale_no_torn(self, session, tmp_path):
        assert session.conf.lifecycle_snapshot_enabled  # pinning on by default
        pins0 = counter_value("hs_snapshot_pins_total")
        roster0 = counter_value("hs_lifecycle_invalidations_total", cache="roster")

        out = run_refresh_serving_soak(session, tmp_path, rounds=4, workers=2)

        assert out["violations"] == [], out["violations"][:10]
        assert out["commits"] == 4  # one commit per refresh round
        assert out["queries"] > 0
        # every admitted request pinned a snapshot, every commit purged the
        # roster cache (brand rotation visible immediately)
        assert counter_value("hs_snapshot_pins_total") > pins0
        assert counter_value("hs_lifecycle_invalidations_total", cache="roster") >= roster0 + 4

        # post-soak ground truth: the final answer matches hyperspace-off
        q = session.read_parquet(str(tmp_path / "soak")).filter(hst.col("c1") >= 0).select("m")
        on = q.collect()
        session.disable_hyperspace()
        assert_batches_equal(on, q.collect())
        assert sorted(np.unique(on["m"]).tolist()) == out["final_markers"]
