"""Index-manager scenario matrix, porting the reference's IndexManagerTest
breadth (820 lines: indexes() listing with/without lineage, full CRUD,
refresh/optimize interactions, hive-partition columns through incremental
refresh, maintenance under globbing
— ref: src/test/scala/com/microsoft/hyperspace/index/IndexManagerTest.scala:62-699)."""

import glob
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def _write(d, n=500, seed=0, lo=0, hi=40):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    pq.write_table(
        pa.table(
            {"k": rng.integers(lo, hi, n).astype(np.int64), "v": np.round(rng.uniform(0, 10, n), 3)}
        ),
        os.path.join(d, f"part-{seed:03d}.parquet"),
    )


class TestIndexesListing:
    """(ref: IndexManagerTest:62-117 'indexes() returns the correct dataframe
    with and without lineage' / getIndexes)"""

    def test_indexes_dataframe_without_lineage(self, session, hs, tmp_path):
        d = str(tmp_path / "a")
        _write(d)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("idxA", ["k"], ["v"]))
        listing = hs.indexes()
        assert len(listing) == 1
        row = listing.iloc[0]
        assert row["name"] == "idxA"
        assert row["state"] == "ACTIVE"
        assert "k" in str(row["indexedColumns"])

    def test_indexes_dataframe_with_lineage(self, session, hs, tmp_path):
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        d = str(tmp_path / "b")
        _write(d)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("idxB", ["k"], ["v"]))
        # lineage column is an implementation detail: it must NOT surface in
        # the advertised schema, but the data files must carry it
        listing = hs.indexes()
        assert len(listing) == 1
        files = hs.index("idxB")["indexContentPaths"]
        data_files = [f for f in np.atleast_1d(files) if str(f).endswith(".parquet")]
        schema = pq.read_schema(str(data_files[0]))
        assert "_data_file_id" in schema.names

    def test_listing_covers_all_states(self, session, hs, tmp_path):
        for name in ("s1", "s2", "s3"):
            d = str(tmp_path / name)
            _write(d, seed=hash(name) % 100)
            hs.create_index(session.read_parquet(d), hst.CoveringIndexConfig(name, ["k"], ["v"]))
        hs.delete_index("s2")
        listing = hs.indexes()
        states = dict(zip(listing["name"], listing["state"]))
        assert states == {"s1": "ACTIVE", "s2": "DELETED", "s3": "ACTIVE"}


class TestCrudChains:
    """Full lifecycle chains (ref: IndexManagerTest:118-265)."""

    def test_delete_restore_delete_vacuum(self, session, hs, tmp_path):
        d = str(tmp_path / "c")
        _write(d)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("chain", ["k"], ["v"]))
        hs.delete_index("chain")
        assert hs.index("chain")["state"] == "DELETED"
        hs.restore_index("chain")
        assert hs.index("chain")["state"] == "ACTIVE"
        hs.delete_index("chain")
        hs.vacuum_index("chain")
        assert hs.index("chain")["state"] == "DOESNOTEXIST"
        assert "chain" not in set(hs.indexes().get("name", []))
        # name is reusable after vacuum
        hs.create_index(df, hst.CoveringIndexConfig("chain", ["k"], ["v"]))
        assert hs.index("chain")["state"] == "ACTIVE"

    def test_restore_requires_deleted(self, session, hs, tmp_path):
        d = str(tmp_path / "r")
        _write(d)
        hs.create_index(session.read_parquet(d), hst.CoveringIndexConfig("act", ["k"], ["v"]))
        with pytest.raises(Exception):
            hs.restore_index("act")  # ACTIVE cannot restore

    def test_vacuum_requires_deleted(self, session, hs, tmp_path):
        d = str(tmp_path / "vx")
        _write(d)
        hs.create_index(session.read_parquet(d), hst.CoveringIndexConfig("vac", ["k"], ["v"]))
        with pytest.raises(Exception):
            hs.vacuum_index("vac")

    def test_full_refresh_produces_new_version_dir(self, session, hs, tmp_path):
        d = str(tmp_path / "fv")
        _write(d, seed=1)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("fullv", ["k"], ["v"]))
        sysp = session.conf.get(hst.keys.SYSTEM_PATH)
        _write(d, seed=2)  # append
        hs.refresh_index("fullv", "full")
        vdirs = sorted(
            n for n in os.listdir(os.path.join(sysp, "fullv")) if n.startswith("v__=")
        )
        assert len(vdirs) >= 2, vdirs
        # the latest version indexes ALL rows
        files = glob.glob(os.path.join(sysp, "fullv", vdirs[-1], "*.parquet"))
        total = sum(pq.read_metadata(f).num_rows for f in files)
        assert total == 1000

    def test_incremental_refresh_indexes_only_appended(self, session, hs, tmp_path):
        """(ref: IndexManagerTest:267-298 'incremental refresh (append-only)
        should index only newly appended data')"""
        d = str(tmp_path / "inc")
        _write(d, seed=3)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("inc1", ["k"], ["v"]))
        sysp = session.conf.get(hst.keys.SYSTEM_PATH)
        v1_files = set(glob.glob(os.path.join(sysp, "inc1", "v__=*", "*.parquet")))
        _write(d, seed=4, n=200)
        hs.refresh_index("inc1", "incremental")
        all_files = set(glob.glob(os.path.join(sysp, "inc1", "v__=*", "*.parquet")))
        new_files = all_files - v1_files
        assert v1_files <= all_files  # old version data untouched
        new_rows = sum(pq.read_metadata(f).num_rows for f in new_files)
        assert new_rows == 200  # only the delta got indexed

    def test_quick_optimize_after_incremental_refresh(self, session, hs, tmp_path):
        """(ref: IndexManagerTest:300-378) incremental refresh leaves one run
        per refresh; quick optimize compacts them to one file per bucket."""
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        d = str(tmp_path / "qo")
        _write(d, seed=5)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("qopt", ["k"], ["v"]))
        for s in (6, 7):
            _write(d, seed=s, n=200)
            hs.refresh_index("qopt", "incremental")
        hs.optimize_index("qopt", "quick")
        per_bucket = {}
        from hyperspace_tpu.indexes.covering import bucket_of_file

        # gather the CURRENT content from the log (optimize merges trees)
        entry_files = [f for f in np.atleast_1d(hs.index("qopt")["indexContentPaths"]) if str(f).endswith(".parquet")]
        for f in entry_files:
            per_bucket.setdefault(bucket_of_file(str(f)), []).append(f)
        assert all(len(v) == 1 for v in per_bucket.values()), {
            b: len(v) for b, v in per_bucket.items()
        }
        # and the index still answers correctly
        session.enable_hyperspace()
        q = session.read_parquet(d).filter(hst.col("k") == 5).select("v")
        on = np.sort(q.collect()["v"])
        session.disable_hyperspace()
        off = np.sort(q.collect()["v"])
        assert np.array_equal(on, off)


class TestPartitionedRefresh:
    def test_incremental_refresh_keeps_partition_columns(self, session, hs, tmp_path):
        """(ref: IndexManagerTest:491-528 'incremental refresh properly adds
        hive-partition columns')"""
        base = tmp_path / "part"
        rng = np.random.default_rng(8)
        for pv in ("p=1", "p=2"):
            d = base / pv
            d.mkdir(parents=True)
            pq.write_table(
                pa.table({"k": rng.integers(0, 20, 300).astype(np.int64),
                          "v": rng.standard_normal(300)}),
                d / "f0.parquet",
            )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(str(base))
        hs.create_index(df, hst.CoveringIndexConfig("partIdx", ["k"], ["v", "p"]))
        # append a NEW partition, refresh incrementally
        d3 = base / "p=3"
        d3.mkdir()
        pq.write_table(
            pa.table({"k": rng.integers(0, 20, 300).astype(np.int64),
                      "v": rng.standard_normal(300)}),
            d3 / "f0.parquet",
        )
        hs.refresh_index("partIdx", "incremental")
        session.enable_hyperspace()
        df2 = session.read_parquet(str(base))
        q = df2.filter(hst.col("k") == 3).select("v", "p")
        plan = q.optimized_plan()
        assert any(isinstance(x, L.IndexScan) for x in L.collect(plan, lambda a: True)), plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        assert sorted(on["p"].tolist()) == sorted(off["p"].tolist())
        assert "3" in set(str(x) for x in on["p"])  # new partition's rows present
