"""Device execution path tests on the virtual 8-device CPU mesh.

The device path must agree bit-for-bit with the host executor on every
supported pattern, and silently fall back for anything else — the same
"never break a query" contract as ApplyHyperspace
(ref: HS/index/rules/ApplyHyperspace.scala:59-63).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import device as D
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import col, lit


def sort_batch(batch):
    order = np.lexsort(
        [np.asarray(v).astype("U64") if v.dtype == object else v for v in reversed(list(batch.values()))]
    )
    return {k: v[order] for k, v in batch.items()}


def assert_batches_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    assert B.num_rows(a) == B.num_rows(b)
    a, b = sort_batch(a), sort_batch(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"column {k}")


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def run_both(session, query):
    """Collect with device execution on and off; both must agree."""
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    # force the device path even on tiny test batches (the row threshold
    # exists for latency, not correctness)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    dev = query.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    host = query.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    assert_batches_equal(dev, host)
    return dev


class TestDeviceFilter:
    def test_numeric_predicates(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("devIdx", ["c1"], ["c2", "c3"]))
        session.enable_hyperspace()
        for cond in [
            col("c1") == 7,
            (col("c1") > 20) & (col("c1") <= 60),
            (col("c1") == 3) | (col("c2") < 100),
            col("c1").isin(1, 5, 9),
            ~(col("c1") == 7),
            (col("c1") + col("c2")) % 7 == 0,
        ]:
            q = df.filter(cond).select("c2")
            out = run_both(session, q)
            assert B.num_rows(out) > 0

    def test_string_predicates(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("strIdx", ["c4"], ["c1"]))
        session.enable_hyperspace()
        for cond in [
            col("c4") == "name_5",
            col("c4") < "name_2",
            col("c4") >= "name_30",
            col("c4").isin("name_1", "name_36", "does_not_exist"),
            col("c4") != "name_0",
        ]:
            q = df.filter(cond).select("c1")
            run_both(session, q)

    def test_absent_string_literal_matches_nothing(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("strIdx2", ["c4"], ["c1"]))
        session.enable_hyperspace()
        q = df.filter(col("c4") == "zzz_not_there").select("c1")
        out = run_both(session, q)
        assert B.num_rows(out) == 0

    def test_mixed_type_predicates_fall_back_to_host(self, session, hs, sample_parquet):
        # string column vs int literal, and mixed-type IN: host-defined
        # semantics — device path must decline (not crash, not diverge)
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("mixIdx", ["c4"], ["c1"]))
        session.enable_hyperspace()
        codecs = {"c4": D.ColumnCodec("string", uniques=np.array(["a"])), "c1": D.ColumnCodec("numeric")}
        with pytest.raises(D.DeviceUnsupported):
            D.compile_predicate(col("c4") == lit(5), codecs)
        with pytest.raises(D.DeviceUnsupported):
            D.compile_predicate(col("c4").isin("a", 5), codecs)
        with pytest.raises(D.DeviceUnsupported):
            D.compile_predicate(col("c1").isin("a", 5), codecs)
        # end-to-end: query still succeeds via host fallback
        q = df.filter(col("c4").isin("name_1", 5)).select("c1")
        run_both(session, q)

    def test_string_ne_with_nulls_matches_host(self, session, hs, tmp_path):
        root = tmp_path / "nulls"
        root.mkdir()
        pq.write_table(
            pa.table({"s": pa.array(["a", None, "b", "a"], type=pa.string()), "v": np.arange(4, dtype=np.int64)}),
            root / "p.parquet",
        )
        df = session.read_parquet(str(root))
        hs = hst.Hyperspace(session)
        hs.create_index(df, hst.CoveringIndexConfig("nullIdx", ["s"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("s") != "a").select("v")
        out = run_both(session, q)
        # SQL three-valued semantics: NULL != 'a' is NULL (unknown), so the
        # null row is filtered out on device and host alike
        assert set(out["v"].tolist()) == {2}
        # and NOT must not resurrect it: NOT(s = 'a') is NULL for the null row
        q2 = df.filter(~(col("s") == "a")).select("v")
        out2 = run_both(session, q2)
        assert set(out2["v"].tolist()) == {2}

    def test_nat_dates_three_valued_on_device(self, session, hs, tmp_path):
        """NaT (NULL date) comparisons are unknown on device exactly as on
        host: != and NOT(=) must not keep the NaT row, IS NULL must find it."""
        root = tmp_path / "nat"
        root.mkdir()
        days = np.array(["2024-01-01", "NaT", "2024-03-01"], dtype="datetime64[D]")
        pq.write_table(
            pa.table({"d": days, "v": np.arange(3, dtype=np.int64)}),
            root / "p.parquet",
        )
        df = session.read_parquet(str(root))
        hs = hst.Hyperspace(session)
        hs.create_index(df, hst.CoveringIndexConfig("natIdx", ["d"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("d") != np.datetime64("2024-01-01")).select("v")
        out = run_both(session, q)
        assert set(out["v"].tolist()) == {2}
        q2 = df.filter(~(col("d") == np.datetime64("2024-01-01"))).select("v")
        out2 = run_both(session, q2)
        assert set(out2["v"].tolist()) == {2}
        q3 = df.filter(col("d").is_null()).select("v")
        out3 = run_both(session, q3)
        assert set(out3["v"].tolist()) == {1}

    def test_predicate_compiler_rejects_host_only(self, session):
        from hyperspace_tpu.plan.expr import input_file_name

        codecs = {"a": D.ColumnCodec("numeric")}
        with pytest.raises(D.DeviceUnsupported):
            D.compile_predicate(input_file_name() == "x", codecs)

    def test_datetime_predicates(self, session, hs, tmp_path):
        root = tmp_path / "dates"
        root.mkdir()
        base = np.datetime64("2020-01-01")
        n = 500
        rng = np.random.default_rng(0)
        table = pa.table(
            {
                "d": base + rng.integers(0, 365, n).astype("timedelta64[D]"),
                "v": rng.integers(0, 100, n).astype(np.int64),
            }
        )
        pq.write_table(table, root / "part-00000.parquet")
        df = session.read_parquet(str(root))
        hs = hst.Hyperspace(session)
        hs.create_index(df, hst.CoveringIndexConfig("dateIdx", ["d"], ["v"]))
        session.enable_hyperspace()
        q = df.filter((col("d") >= lit(np.datetime64("2020-06-01"))) & (col("d") < lit(np.datetime64("2020-07-01")))).select("v")
        run_both(session, q)


class TestDeviceJoin:
    @pytest.fixture()
    def two_tables(self, tmp_path):
        rng = np.random.default_rng(7)
        n1, n2 = 3000, 1000
        left = pa.table(
            {
                "k": rng.integers(0, 400, n1).astype(np.int64),
                "lv": rng.standard_normal(n1),
            }
        )
        right = pa.table(
            {
                "k": rng.integers(0, 400, n2).astype(np.int64),
                "rv": rng.integers(0, 10, n2).astype(np.int64),
            }
        )
        lroot, rroot = tmp_path / "left", tmp_path / "right"
        lroot.mkdir()
        rroot.mkdir()
        for i in range(3):
            pq.write_table(left.slice(i * 1000, 1000), lroot / f"part-{i:05d}.parquet")
        pq.write_table(right, rroot / "part-00000.parquet")
        return str(lroot), str(rroot)

    def test_bucketed_join_device_equals_host(self, session, hs, two_tables):
        lpath, rpath = two_tables
        session.conf.set(hst.keys.NUM_BUCKETS, 16)
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("lIdx", ["k"], ["lv"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("rIdx", ["k"], ["rv"]))
        session.enable_hyperspace()

        q = ldf.join(rdf, on="k").select("k", "lv", "rv")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        # joined result identical with device exec on/off, and vs no index at all
        dev = run_both(session, q)
        session.disable_hyperspace()
        baseline = q.collect()
        assert_batches_equal(dev, baseline)
        assert B.num_rows(dev) > 0

    def test_join_with_duplicate_keys_both_sides(self, session, hs, tmp_path):
        # many-to-many expansion must match pandas merge exactly
        lroot, rroot = tmp_path / "l2", tmp_path / "r2"
        lroot.mkdir()
        rroot.mkdir()
        pq.write_table(
            pa.table({"k": np.array([1, 1, 2, 3, 3, 3], dtype=np.int64), "a": np.arange(6, dtype=np.int64)}),
            lroot / "part-00000.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([1, 1, 3, 4], dtype=np.int64), "b": np.arange(4, dtype=np.int64)}),
            rroot / "part-00000.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(str(lroot))
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("dupL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("dupR", ["k"], ["b"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="k").select("k", "a", "b")
        out = run_both(session, q)
        # 1 matches 2 rows ×2 left rows, 3 matches 1 row ×3 left rows = 7
        assert B.num_rows(out) == 2 * 2 + 3 * 1

    def test_join_after_incremental_refresh_resorts_buckets(self, session, hs, tmp_path):
        # incremental refresh merges delta files into existing buckets
        # (UpdateMode.Merge) leaving them only piecewise sorted; the device
        # join must re-sort before searchsorted
        lroot, rroot = tmp_path / "l4", tmp_path / "r4"
        lroot.mkdir()
        rroot.mkdir()
        rng = np.random.default_rng(3)
        pq.write_table(
            pa.table({"k": rng.integers(0, 50, 400).astype(np.int64), "a": np.arange(400, dtype=np.int64)}),
            lroot / "part-00000.parquet",
        )
        pq.write_table(
            pa.table({"k": np.arange(50, dtype=np.int64), "b": np.arange(50, dtype=np.int64)}),
            rroot / "part-00000.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(str(lroot))
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("incL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("incR", ["k"], ["b"]))
        # append more rows and refresh incrementally -> multi-file buckets
        pq.write_table(
            pa.table({"k": rng.integers(0, 50, 400).astype(np.int64), "a": np.arange(400, 800, dtype=np.int64)}),
            lroot / "part-00001.parquet",
        )
        hs.refresh_index("incL", "incremental")
        session.enable_hyperspace()
        # re-read: relations snapshot their file list at construction (as
        # Spark's InMemoryFileIndex does), so the post-append source needs a
        # fresh scan for signatures to line up with the refreshed index
        ldf = session.read_parquet(str(lroot))
        q = ldf.join(rdf, on="k").select("k", "a", "b")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        assert any(len(s.files) > 4 for s in scans)  # merged buckets have >1 file
        out = run_both(session, q)
        session.disable_hyperspace()
        assert_batches_equal(out, q.collect())

    def test_empty_join_result_preserves_dtypes(self, session, hs, tmp_path):
        lroot, rroot = tmp_path / "l5", tmp_path / "r5"
        lroot.mkdir()
        rroot.mkdir()
        pq.write_table(
            pa.table({"k": np.array([1, 2], dtype=np.int64), "a": np.array([10, 20], dtype=np.int64)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([5, 6], dtype=np.int64), "b": np.array([1.5, 2.5])}),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        ldf = session.read_parquet(str(lroot))
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("eL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("eR", ["k"], ["b"]))
        session.enable_hyperspace()
        out = ldf.join(rdf, on="k").select("k", "a", "b").collect()
        assert B.num_rows(out) == 0
        assert out["k"].dtype == np.int64
        assert out["a"].dtype == np.int64
        assert out["b"].dtype == np.float64

    def test_host_bucketed_join_matches_device_and_pandas(self, session, hs, two_tables):
        """host_bucketed_join is the default production path below the
        deviceMinRows threshold — its spans must agree with both the device
        SMJ and the independent pandas merge."""
        from hyperspace_tpu.exec import device as D

        lpath, rpath = two_tables
        session.conf.set(hst.keys.NUM_BUCKETS, 16)
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("hjL", ["k"], ["lv"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("hjR", ["k"], ["rv"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="k").select("k", "lv", "rv")
        plan = q.optimized_plan()
        joins = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.Join)]
        assert joins, plan.pretty()

        host_out = D.host_bucketed_join(session, joins[0])
        dev_out = D.device_bucketed_join(session, joins[0])
        assert_batches_equal(host_out, dev_out)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        pandas_out = q.collect()  # kill switch -> pandas merge
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        # the raw join node also outputs the right key copy (k#r); the public
        # query's Project drops it
        host_proj = {k: v for k, v in host_out.items() if k in pandas_out}
        assert_batches_equal(host_proj, pandas_out)
        assert B.num_rows(host_out) > 0

    def test_join_threshold_dispatch(self, session, hs, two_tables, monkeypatch):
        """Above deviceMinRows the device path runs; below it the host path
        runs — same results either way through the public API."""
        lpath, rpath = two_tables
        session.conf.set(hst.keys.NUM_BUCKETS, 16)
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("tdL", ["k"], ["lv"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("tdR", ["k"], ["rv"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="k").select("k", "lv", "rv")

        from hyperspace_tpu.exec import device as D

        calls = []
        real_dev, real_host = D.device_bucketed_join, D.host_bucketed_join
        monkeypatch.setattr(D, "device_bucketed_join", lambda *a, **k: calls.append("dev") or real_dev(*a, **k))
        monkeypatch.setattr(D, "host_bucketed_join", lambda *a, **k: calls.append("host") or real_host(*a, **k))

        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        low = q.collect()
        assert calls[-1] == "dev"
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        high = q.collect()
        assert calls[-1] == "host"
        assert_batches_equal(low, high)

    def test_expand_pairs_promotes_mixed_bucket_dtypes(self):
        """A nullable int column decodes as float64 (with NaN) only in the
        buckets whose files hold nulls; the preallocated output must promote
        across buckets instead of truncating into the first bucket's dtype."""
        from hyperspace_tpu.exec.device import _expand_join_pairs

        class FakeJoin:
            output_columns = ["k", "val"]
            how = "inner"

        lbuckets = {
            0: {"k": np.array([1, 2], dtype=np.int64), "val": np.array([10, 20], dtype=np.int64)},
            1: {"k": np.array([3], dtype=np.int64), "val": np.array([np.nan], dtype=np.float64)},
        }
        rbuckets = {
            0: {"k": np.array([1, 2], dtype=np.int64)},
            1: {"k": np.array([3], dtype=np.int64)},
        }

        def span_of(b):
            lk = lbuckets[b]["k"]
            rk = rbuckets[b]["k"]
            return np.searchsorted(rk, lk, "left"), np.searchsorted(rk, lk, "right")

        out = _expand_join_pairs(FakeJoin(), lbuckets, rbuckets, 2, ["k", "val"], ["k"], span_of)
        assert out["val"].dtype == np.float64
        assert np.isnan(out["val"][-1])
        np.testing.assert_array_equal(out["val"][:2], [10.0, 20.0])

    def test_string_key_join_via_rank_encoding(self, session, hs, tmp_path):
        lroot, rroot = tmp_path / "l3", tmp_path / "r3"
        lroot.mkdir()
        rroot.mkdir()
        keys_l = np.array(["a", "b", "c", "a"], dtype=object)
        keys_r = np.array(["a", "c"], dtype=object)
        pq.write_table(pa.table({"k": keys_l.astype(str), "a": np.arange(4, dtype=np.int64)}), lroot / "p.parquet")
        pq.write_table(pa.table({"k": keys_r.astype(str), "b": np.arange(2, dtype=np.int64)}), rroot / "p.parquet")
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        ldf = session.read_parquet(str(lroot))
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("sL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("sR", ["k"], ["b"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="k").select("k", "a", "b")
        out = run_both(session, q)
        assert B.num_rows(out) == 3  # a×2 matches + c×1

    def test_string_key_rides_device_span_program(self, session, hs, tmp_path, monkeypatch):
        """String keys reach the DEVICE span program via the shared rank
        encodings (they used to always take the host rank path)."""
        rng = np.random.default_rng(31)
        lroot, rroot = tmp_path / "sl", tmp_path / "sr"
        lroot.mkdir(), rroot.mkdir()
        n = 500
        pq.write_table(
            pa.table({"k": np.array([f"u{v}" for v in rng.integers(0, 60, n)]),
                      "a": rng.standard_normal(n)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([f"u{v}" for v in range(60)]),
                      "b": rng.standard_normal(60)}),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("dsL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("dsR", ["k"], ["b"]))
        session.enable_hyperspace()

        called = {"n": 0}
        real = D.device_bucketed_join

        def spy(*a, **kw):
            called["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(D, "device_bucketed_join", spy)
        monkeypatch.setattr("hyperspace_tpu.exec.device.device_bucketed_join", spy)
        q = ldf.join(rdf, on="k").select("k", "a", "b")
        out = run_both(session, q)
        assert called["n"] >= 1, "device span program must serve string keys"
        # cross-check against pandas ground truth
        import pandas as pd

        lt = pq.read_table(lroot / "p.parquet").to_pandas()
        rt = pq.read_table(rroot / "p.parquet").to_pandas()
        want = lt.merge(rt, on="k")
        assert B.num_rows(out) == len(want)

    def test_composite_key_rides_device_span_program(self, session, hs, tmp_path, monkeypatch):
        rng = np.random.default_rng(33)
        lroot, rroot = tmp_path / "cl", tmp_path / "cr"
        lroot.mkdir(), rroot.mkdir()
        n = 400
        pq.write_table(
            pa.table({
                "k1": rng.integers(0, 12, n).astype(np.int64),
                "k2": np.array([f"s{v}" for v in rng.integers(0, 6, n)]),
                "a": rng.standard_normal(n)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({
                "k1": np.repeat(np.arange(12, dtype=np.int64), 6),
                "k2": np.array([f"s{v}" for v in list(range(6)) * 12]),
                "b": rng.standard_normal(72)}),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("dcL", ["k1", "k2"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("dcR", ["k1", "k2"], ["b"]))
        session.enable_hyperspace()

        called = {"n": 0}
        real = D.device_bucketed_join

        def spy(*a, **kw):
            called["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr("hyperspace_tpu.exec.device.device_bucketed_join", spy)
        q = ldf.join(rdf, on=["k1", "k2"]).select("k1", "k2", "a", "b")
        out = run_both(session, q)
        assert called["n"] >= 1, "device span program must serve composite keys"
        import pandas as pd

        lt = pq.read_table(lroot / "p.parquet").to_pandas()
        rt = pq.read_table(rroot / "p.parquet").to_pandas()
        want = lt.merge(rt, on=["k1", "k2"])
        assert B.num_rows(out) == len(want)


class TestDeviceMaterialization:
    """Inner-join pair expansion + numeric gather on device: the host
    receives final columns only (SURVEY §2.9 device-local merge-join)."""

    @pytest.fixture()
    def joined(self, session, hs, tmp_path):
        rng = np.random.default_rng(41)
        lroot, rroot = tmp_path / "ml", tmp_path / "mr"
        lroot.mkdir(), rroot.mkdir()
        n = 800
        pq.write_table(
            pa.table({
                "k": rng.integers(0, 50, n).astype(np.int64),
                "amount": np.round(rng.uniform(0, 100, n), 3),
                "day": np.datetime64("2024-01-01") + rng.integers(0, 90, n).astype("timedelta64[D]"),
                "tag": np.array([f"t{v}" for v in rng.integers(0, 7, n)]),
            }),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({
                "k": np.arange(50, dtype=np.int64),
                "w": rng.standard_normal(50),
            }),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("mL", ["k"], ["amount", "day", "tag"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("mR", ["k"], ["w"]))
        session.enable_hyperspace()
        return ldf.join(rdf, on="k").select("k", "amount", "day", "tag", "w"), lroot, rroot

    def test_device_materialization_runs_and_matches(self, session, joined, monkeypatch):
        import pandas as pd

        q, lroot, rroot = joined
        called = {"n": 0}
        real = D._device_materialize_inner

        def spy(*a, **kw):
            called["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr("hyperspace_tpu.exec.device._device_materialize_inner", spy)
        out = run_both(session, q)  # device == host already asserted inside
        assert called["n"] >= 1, "device materialization must have served the join"
        lt = pq.read_table(lroot / "p.parquet").to_pandas()
        rt = pq.read_table(rroot / "p.parquet").to_pandas()
        want = lt.merge(rt, on="k")
        assert B.num_rows(out) == len(want)
        assert np.isclose(np.sort(out["amount"]).sum(), want["amount"].sum())
        assert out["day"].dtype.kind == "M" and out["tag"].dtype == object

    def test_flag_off_reverts_to_host_expansion(self, session, joined, monkeypatch):
        q, _, _ = joined
        session.conf.set(hst.keys.TPU_JOIN_DEVICE_MATERIALIZE, False)
        try:
            called = {"n": 0}

            def spy(*a, **kw):
                called["n"] += 1
                raise AssertionError("must not run with the flag off")

            monkeypatch.setattr("hyperspace_tpu.exec.device._device_materialize_inner", spy)
            out = run_both(session, q)
            assert called["n"] == 0
            assert B.num_rows(out) > 0
        finally:
            session.conf.set(hst.keys.TPU_JOIN_DEVICE_MATERIALIZE, True)

    def test_outer_join_stays_on_host_gather(self, session, hs, tmp_path, monkeypatch):
        lroot, rroot = tmp_path / "ol", tmp_path / "or"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(
            pa.table({"k": np.array([1, 2, 3], dtype=np.int64), "a": np.arange(3.0)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([2, 3, 4], dtype=np.int64), "b": np.arange(3.0)}),
            rroot / "p.parquet",
        )
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("oL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("oR", ["k"], ["b"]))
        session.enable_hyperspace()

        def boom(*a, **kw):
            raise AssertionError("outer joins must not take device materialization")

        monkeypatch.setattr("hyperspace_tpu.exec.device._device_materialize_inner", boom)
        q = ldf.join(rdf, on="k", how="left").select("k", "a", "b")
        out = run_both(session, q)
        assert B.num_rows(out) == 3


class TestHybridBucketedJoin:
    """Hybrid-scan sides (BucketUnion of index + re-bucketed appends, with
    lineage NOT-IN deletes) now ride the shuffle-free bucketed-SMJ fast path
    instead of the generic pandas merge (ref: the reference keeps its
    exchange-free SMJ under hybrid scan via on-the-fly re-bucketing,
    CoveringIndexRuleUtils.scala:357-417)."""

    @pytest.fixture()
    def hybrid_join_env(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.LINEAGE_ENABLED, True)
        rng = np.random.default_rng(21)
        lroot, rroot = tmp_path / "fact", tmp_path / "dim"
        lroot.mkdir(), rroot.mkdir()
        n = 600
        pq.write_table(
            pa.table({"k": rng.integers(0, 40, n).astype(np.int64), "a": rng.standard_normal(n)}),
            lroot / "p0.parquet",
        )
        pq.write_table(
            pa.table({"k": np.arange(40, dtype=np.int64), "b": rng.standard_normal(40)}),
            rroot / "p0.parquet",
        )
        fact, dim = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(fact, hst.CoveringIndexConfig("factIdx", ["k"], ["a"]))
        hs.create_index(dim, hst.CoveringIndexConfig("dimIdx2", ["k"], ["b"]))
        # append to the fact side AFTER indexing -> hybrid scan kicks in
        pq.write_table(
            pa.table({"k": rng.integers(0, 40, 100).astype(np.int64), "a": rng.standard_normal(100)}),
            lroot / "p1.parquet",
        )
        return str(lroot), str(rroot)

    def _join(self, session, lroot, rroot):
        fact, dim = session.read_parquet(lroot), session.read_parquet(rroot)
        return fact.join(dim, on=hst.col("k") == hst.col("k")).select("a", "b")

    def test_hybrid_side_takes_bucketed_path(self, session, hybrid_join_env):
        lroot, rroot = hybrid_join_env
        session.enable_hyperspace()
        q = self._join(session, lroot, rroot)
        plan = q.optimized_plan()
        joins = L.collect(plan, lambda p: isinstance(p, L.Join))
        assert joins, plan.pretty()
        assert any(
            isinstance(p, L.BucketUnion) for p in L.collect(plan, lambda x: True)
        ), plan.pretty()
        compat = D.join_sides_compatible(joins[0])
        assert compat is not None, "hybrid side must be bucket-compatible"
        # and the dispatch executes without DeviceUnsupported
        got = D.dispatch_bucketed_join(session, joins[0])
        assert B.num_rows(got) == 700  # every fact row matches exactly one dim row

    def test_hybrid_join_results_match_plain(self, session, hybrid_join_env):
        lroot, rroot = hybrid_join_env
        session.enable_hyperspace()
        q = self._join(session, lroot, rroot)
        indexed = q.collect()
        session.disable_hyperspace()
        plain = q.collect()
        assert_batches_equal(indexed, plain)

    def test_hybrid_join_with_deletes(self, session, hs, hybrid_join_env, tmp_path):
        import os

        lroot, rroot = hybrid_join_env
        # delete one source file; lineage NOT-IN filters its rows from the index
        os.remove(os.path.join(lroot, "p0.parquet"))
        session.enable_hyperspace()
        q = self._join(session, lroot, rroot)
        indexed = q.collect()
        session.disable_hyperspace()
        plain = q.collect()
        assert_batches_equal(indexed, plain)
        assert indexed["a"].shape[0] == 100  # only the appended rows remain


class TestCompositeKeyBucketedJoin:
    """Composite (multi-column) and string join keys ride the host span path
    via shared dense rank encoding instead of falling back to a generic merge
    (the reference's JoinIndexRule accepts multi-column equi-joins,
    HS/index/covering/JoinIndexRule.scala:419-448)."""

    @pytest.fixture()
    def composite_env(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        rng = np.random.default_rng(31)
        lroot, rroot = tmp_path / "cl", tmp_path / "cr"
        lroot.mkdir(), rroot.mkdir()
        n = 400
        pq.write_table(
            pa.table(
                {
                    "k1": rng.integers(0, 10, n).astype(np.int64),
                    "k2": np.array([f"g{i % 7}" for i in range(n)]),
                    "a": rng.standard_normal(n),
                }
            ),
            lroot / "p.parquet",
        )
        m = 70
        pq.write_table(
            pa.table(
                {
                    "k1": rng.integers(0, 10, m).astype(np.int64),
                    "k2": np.array([f"g{i % 7}" for i in range(m)]),
                    "b": rng.standard_normal(m),
                }
            ),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("cL", ["k1", "k2"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("cR", ["k1", "k2"], ["b"]))
        session.enable_hyperspace()
        return ldf, rdf

    def test_composite_key_takes_bucketed_path(self, session, composite_env):
        ldf, rdf = composite_env
        q = ldf.join(rdf, on=["k1", "k2"]).select("a", "b")
        plan = q.optimized_plan()
        joins = L.collect(plan, lambda p: isinstance(p, L.Join))
        assert joins and D.join_sides_compatible(joins[0]) is not None, plan.pretty()
        got = D.dispatch_bucketed_join(session, joins[0])
        assert B.num_rows(got) > 0

    def test_composite_key_results_match_pandas(self, session, composite_env):
        ldf, rdf = composite_env
        q = ldf.join(rdf, on=["k1", "k2"]).select("a", "b")
        indexed = q.collect()
        session.disable_hyperspace()
        plain = q.collect()
        assert_batches_equal(indexed, plain)

    def test_composite_ranks_order_and_equality(self):
        l1 = np.array([1, 1, 2, 2], dtype=np.int64)
        l2 = np.array(["a", "b", "a", "a"], dtype=object)
        r1 = np.array([1, 2, 3], dtype=np.int64)
        r2 = np.array(["b", "a", "z"], dtype=object)
        lr, rr = D._composite_ranks([l1, l2], [r1, r2])
        # equal tuples share ranks across sides
        assert lr[1] == rr[0]   # (1,'b')
        assert lr[2] == rr[1] == lr[3]  # (2,'a')
        # lexicographic order preserved
        assert lr[0] < lr[1] < lr[2] < rr[2]


def test_composite_rank_cache_respects_filter_changes(session, tmp_path):
    """Deleting a source file adds a lineage NOT-IN filter over UNCHANGED
    index files; the composite rank cache must key on the filter too, not
    just file identity (stale ranks would crash or join deleted rows)."""
    import os

    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    session.conf.set(hst.keys.LINEAGE_ENABLED, True)
    rng = np.random.default_rng(41)
    lroot, rroot = tmp_path / "rl", tmp_path / "rr"
    lroot.mkdir(), rroot.mkdir()
    for i in range(2):
        pq.write_table(
            pa.table(
                {
                    "k1": rng.integers(0, 6, 200).astype(np.int64),
                    "k2": np.array([f"s{j % 5}" for j in range(200)]),
                    "a": rng.standard_normal(200),
                }
            ),
            lroot / f"p{i}.parquet",
        )
    pq.write_table(
        pa.table(
            {
                "k1": np.repeat(np.arange(6, dtype=np.int64), 5),
                "k2": np.array([f"s{j % 5}" for j in range(30)]),
                "b": rng.standard_normal(30),
            }
        ),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("rcL", ["k1", "k2"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("rcR", ["k1", "k2"], ["b"]))
    session.enable_hyperspace()
    q = ldf.join(rdf, on=["k1", "k2"]).select("a", "b")
    first = q.collect()  # warms the rank cache

    os.remove(str(lroot / "p0.parquet"))
    ldf2 = session.read_parquet(str(lroot))
    q2 = ldf2.join(rdf, on=["k1", "k2"]).select("a", "b")
    second = q2.collect()
    session.disable_hyperspace()
    plain = q2.collect()
    assert_batches_equal(second, plain)
    assert B.num_rows(second) < B.num_rows(first)


def test_join_input_device_cache_reuses_and_invalidates(session, tmp_path):
    """The HBM-resident join-input cache (key matrices + payload rectangles)
    must serve repeat executions without re-transfer — repeat results stay
    identical — and must MISS when the underlying index data changes (a
    refresh after an append writes new files, so the file-identity key
    changes; a stale hit would silently drop the appended rows)."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    rng = np.random.default_rng(17)
    lroot, rroot = tmp_path / "cl", tmp_path / "cr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 40, 500).astype(np.int64),
                "a": rng.standard_normal(500),
            }
        ),
        lroot / "p0.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "k": np.arange(40, dtype=np.int64),
                "b": rng.standard_normal(40),
            }
        ),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("ccL", ["k"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("ccR", ["k"], ["b"]))
    session.enable_hyperspace()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)

    D.clear_device_cache()
    q = ldf.join(rdf, on="k").select("k", "a", "b")
    first = q.collect()
    keymat_keys = [k for k in D._device_cache.keys() if k[0] == "join-keymats"]
    assert keymat_keys, "first execution should populate the join-input cache"
    second = q.collect()  # served from the HBM-resident entries
    assert_batches_equal(first, second)
    # the cached reply must ALSO equal the host path's answer
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    assert_batches_equal(second, q.collect())
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)

    # append + full refresh -> new index files -> the old entries are stale
    # by KEY (not by mutation); the fresh execution must see the new rows
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 40, 300).astype(np.int64),
                "a": rng.standard_normal(300),
            }
        ),
        lroot / "p1.parquet",
    )
    hs.refresh_index("ccL", "full")
    ldf2 = session.read_parquet(str(lroot))
    q2 = ldf2.join(rdf, on="k").select("k", "a", "b")
    third = q2.collect()
    assert B.num_rows(third) > B.num_rows(first)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    assert_batches_equal(third, q2.collect())
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)


def test_span_byte_budget_routes_to_host_spans(session, tmp_path):
    """Above joinDeviceSpanMaxBytes the dispatch must choose the host span
    walk (zero transfer) even when the row count clears deviceMinRows; the
    answer must not change."""
    from hyperspace_tpu.exec import trace

    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 8)
    rng = np.random.default_rng(29)
    lroot, rroot = tmp_path / "sl", tmp_path / "sr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 100, 2000).astype(np.int64),
                "lv": rng.standard_normal(2000),
            }
        ),
        lroot / "p.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "k": np.arange(100, dtype=np.int64),
                "rv": rng.standard_normal(100),
            }
        ),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("sbL", ["k"], ["lv"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("sbR", ["k"], ["rv"]))
    session.enable_hyperspace()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    q = ldf.join(rdf, on="k").select("k", "lv", "rv")

    with trace.recording() as dev_events:
        device_ans = q.collect()
    assert ("join", "device-smj") in dev_events

    session.conf.set(hst.keys.TPU_JOIN_DEVICE_SPAN_MAX_BYTES, 1)
    with trace.recording() as host_events:
        host_ans = q.collect()
    assert ("join", "host-span-smj") in host_events
    assert_batches_equal(device_ans, host_ans)
    session.conf.set(hst.keys.TPU_JOIN_DEVICE_SPAN_MAX_BYTES, 256 << 20)


def test_materialize_byte_budget_routes_to_host_expansion(session, tmp_path):
    """Above joinDeviceMaterializeMaxBytes the device join must keep its
    span computation but expand pairs on host (no whole-output download);
    results stay identical to the device-materialized answer."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    rng = np.random.default_rng(23)
    lroot, rroot = tmp_path / "bl", tmp_path / "br"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": rng.integers(0, 30, 400).astype(np.int64),
                "a": rng.standard_normal(400),
            }
        ),
        lroot / "p.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "k": np.arange(30, dtype=np.int64),
                "b": rng.standard_normal(30),
            }
        ),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("bbL", ["k"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("bbR", ["k"], ["b"]))
    session.enable_hyperspace()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    q = ldf.join(rdf, on="k").select("k", "a", "b")

    D.clear_device_cache()
    device_mat = q.collect()  # default budget: device materialization
    assert any(k[0] == "join-paymats" for k in D._device_cache.keys())
    session.conf.set(hst.keys.TPU_JOIN_DEVICE_MATERIALIZE_MAX_BYTES, 1)
    D.clear_device_cache()
    host_exp = q.collect()  # 400 pairs * 8B >> 1 byte -> host expansion
    # the budget must fire BEFORE the payload rectangles ever transfer, so
    # the paymats cache stays empty on the capped route (this is also what
    # catches the budget check regressing to dead code)
    assert not any(k[0] == "join-paymats" for k in D._device_cache.keys())
    assert_batches_equal(device_mat, host_exp)
    session.conf.set(
        hst.keys.TPU_JOIN_DEVICE_MATERIALIZE_MAX_BYTES,
        256 * 1024 * 1024,
    )


class TestOuterBucketedJoin:
    """left/right/full outer equi-joins ride the span path too; unmatched
    rows null-fill the opposite side exactly like the pandas-merge fallback
    (ints promote to float64 NaN)."""

    @pytest.fixture()
    def outer_env(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        lroot, rroot = tmp_path / "ol", tmp_path / "or"
        lroot.mkdir(), rroot.mkdir()
        # keys 0..9 on the left, 5..14 on the right: both sides have
        # unmatched rows, and some buckets exist on only one side
        pq.write_table(
            pa.table({"k": np.arange(10, dtype=np.int64), "a": np.arange(10, dtype=np.int64) * 10}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.arange(5, 15, dtype=np.int64), "b": np.arange(10, dtype=np.int64) * 7}),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("oL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("oR", ["k"], ["b"]))
        session.enable_hyperspace()
        return ldf, rdf

    @pytest.mark.parametrize("how", ["right", "outer"])
    def test_using_key_coalesces_across_sides(self, session, outer_env, how):
        """Spark's df.join(other, on="k") coalesces the USING key: unmatched
        right rows must show the RIGHT side's key under "k", not NULL — on
        the bucketed span path AND the generic pandas-merge fallback."""
        ldf, rdf = outer_env
        q = ldf.join(rdf, on="k", how=how).select("k", "a", "b")

        def keys_of(batch):
            ks = np.asarray(batch["k"], dtype=np.float64)
            assert not np.isnan(ks).any(), "USING key must never be NULL here"
            return sorted(ks.astype(np.int64).tolist())

        span_keys = keys_of(run_both(session, q))  # indexed bucketed paths
        session.disable_hyperspace()
        generic_keys = keys_of(q.collect())  # generic merge fallback
        session.enable_hyperspace()
        assert span_keys == generic_keys
        # right keys 5..14 all present (10..14 match nothing on the left)
        assert set(range(5, 15)) <= set(span_keys)

    @pytest.mark.parametrize("how,expected_rows", [("left", 10), ("right", 10), ("outer", 15), ("inner", 5)])
    def test_outer_join_matches_pandas(self, session, outer_env, how, expected_rows):
        ldf, rdf = outer_env
        q = ldf.join(rdf, on="k", how=how).select("a", "b")
        plan = q.optimized_plan()
        joins = L.collect(plan, lambda p: isinstance(p, L.Join))
        assert joins and D.join_sides_compatible(joins[0]) is not None
        via_spans = D.dispatch_bucketed_join(session, joins[0])
        assert B.num_rows(via_spans) == expected_rows
        session.disable_hyperspace()
        plain = q.collect()
        session.enable_hyperspace()
        assert_batches_equal({c: via_spans[c] for c in ("a", "b")}, plain)
        # and the full query (with projection) agrees end to end
        assert_batches_equal(q.collect(), plain)

    def test_outer_join_null_duplication(self, session, hs, tmp_path):
        """Duplicate matches + unmatched rows in one bucket."""
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        lroot, rroot = tmp_path / "dl", tmp_path / "dr"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(
            pa.table({"k": np.array([1, 1, 2, 9], dtype=np.int64), "a": np.arange(4, dtype=np.int64)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([1, 1, 8], dtype=np.int64), "b": np.arange(3, dtype=np.int64)}),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("dL", ["k"], ["a"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("dR", ["k"], ["b"]))
        session.enable_hyperspace()
        for how in ("left", "right", "outer"):
            q = ldf.join(rdf, on="k", how=how).select("a", "b")
            got = q.collect()
            session.disable_hyperspace()
            plain = q.collect()
            session.enable_hyperspace()
            assert_batches_equal(got, plain)


def test_left_join_right_side_fully_deleted(session, tmp_path):
    """Right side is a hybrid scan whose lineage NOT-IN filter empties every
    bucket (source file deleted): the left join must null-fill, not crash."""
    import os

    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 1.0)
    session.conf.set(hst.keys.LINEAGE_ENABLED, True)
    lroot, rroot = tmp_path / "fl", tmp_path / "fr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table({"k": np.arange(6, dtype=np.int64), "a": np.arange(6, dtype=np.int64)}),
        lroot / "p.parquet",
    )
    pq.write_table(
        pa.table({"k": np.arange(6, dtype=np.int64), "b": np.arange(6, dtype=np.int64) * 2}),
        rroot / "p0.parquet",
    )
    pq.write_table(
        pa.table({"k": np.arange(6, 9, dtype=np.int64), "b": np.arange(3, dtype=np.int64)}),
        rroot / "p1.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("flL", ["k"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("flR", ["k"], ["b"]))
    os.remove(str(rroot / "p0.parquet"))  # all left-matching right rows gone
    session.enable_hyperspace()
    rdf2 = session.read_parquet(str(rroot))
    q = ldf.join(rdf2, on="k", how="left").select("a", "b")
    got = q.collect()
    session.disable_hyperspace()
    plain = q.collect()
    assert_batches_equal(got, plain)
    assert np.isnan(got["b"]).all()  # nothing matches after the delete


def test_outer_join_bool_payload_matches_pandas(session, tmp_path):
    """Nullable bool columns promote to object True/False/NaN, matching the
    pandas-merge fallback, so both execution paths agree."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    lroot, rroot = tmp_path / "bl", tmp_path / "br"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table({"k": np.array([1, 2, 9], dtype=np.int64), "a": np.arange(3, dtype=np.int64)}),
        lroot / "p.parquet",
    )
    pq.write_table(
        pa.table({"k": np.array([1, 2], dtype=np.int64), "flag": np.array([True, False])}),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("bL", ["k"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("bR", ["k"], ["flag"]))
    session.enable_hyperspace()
    q = ldf.join(rdf, on="k", how="left").select("a", "flag")
    got = q.collect()
    session.disable_hyperspace()
    plain = q.collect()
    assert got["flag"].dtype == plain["flag"].dtype == object
    ga = sorted(got["flag"], key=str)
    pa_ = sorted(plain["flag"], key=str)
    assert [str(x) for x in ga] == [str(x) for x in pa_]


def test_outer_join_duration_payload_nulls(session, tmp_path):
    """Duration (timedelta64) payload columns null-fill with NaT on outer
    joins instead of crashing on a NaN assignment."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    lroot, rroot = tmp_path / "tl", tmp_path / "tr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table({"k": np.array([1, 9], dtype=np.int64), "a": np.array([1, 2], dtype=np.int64)}),
        lroot / "p.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "k": np.array([1], dtype=np.int64),
                "dur": pa.array([np.timedelta64(5, "s")], type=pa.duration("s")),
            }
        ),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("tL", ["k"], ["a"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("tR", ["k"], ["dur"]))
    session.enable_hyperspace()
    got = ldf.join(rdf, on="k", how="left").select("a", "dur").collect()
    assert got["a"].shape[0] == 2
    assert np.isnat(got["dur"]).sum() == 1


class TestFusedJoinAggregate:
    """Global aggregates over a bucketed join compute from match spans
    without materializing the pair expansion; results must equal the
    materialize-then-aggregate path exactly."""

    @pytest.fixture()
    def agg_env(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        rng = np.random.default_rng(51)
        lroot, rroot = tmp_path / "al", tmp_path / "ar"
        lroot.mkdir(), rroot.mkdir()
        n = 2000
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 100, n).astype(np.int64),
                    "qty": rng.integers(1, 50, n).astype(np.int64),
                    "price": rng.uniform(1, 100, n),
                }
            ),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 100, 300).astype(np.int64),
                    "fx": rng.uniform(0.5, 1.5, 300),
                }
            ),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("agL", ["k"], ["qty", "price"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("agR", ["k"], ["fx"]))
        session.enable_hyperspace()
        return ldf, rdf

    def _check(self, session, q):
        fused = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert sorted(fused.keys()) == sorted(plain.keys())
        for k in fused:
            np.testing.assert_allclose(fused[k], plain[k], rtol=1e-9, err_msg=k)
        return fused

    def test_count_and_sums_both_sides(self, session, agg_env):
        ldf, rdf = agg_env
        j = ldf.join(rdf, on="k")
        q = j.agg(n=("*", "count"), s_left=("price", "sum"), s_right=("fx", "sum"),
                  m_left=("qty", "avg"), m_right=("fx", "avg"))
        got = self._check(session, q)
        assert int(got["n"][0]) > 0

    def test_min_max_left(self, session, agg_env):
        ldf, rdf = agg_env
        q = ldf.join(rdf, on="k").agg(lo=("price", "min"), hi=("price", "max"))
        self._check(session, q)

    def test_min_right_falls_back(self, session, agg_env):
        ldf, rdf = agg_env
        q = ldf.join(rdf, on="k").agg(lo=("fx", "min"))
        self._check(session, q)  # materialized fallback still correct

    def test_fused_path_is_taken(self, session, agg_env):
        from hyperspace_tpu.plan import logical as L

        ldf, rdf = agg_env
        q = ldf.join(rdf, on="k").agg(n=("*", "count"))
        plan = q.optimized_plan()
        joins = L.collect(plan, lambda p: isinstance(p, L.Join))
        aggs = [p for p in L.collect(plan, lambda p: isinstance(p, L.Aggregate))]
        got = D.aggregate_over_bucketed_join(session, aggs[0], joins[0])
        expanded = D.dispatch_bucketed_join(session, joins[0])
        assert int(got["n"][0]) == B.num_rows(expanded)

    def test_empty_join_aggregates(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        lroot, rroot = tmp_path / "el", tmp_path / "er"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(pa.table({"k": np.array([1], dtype=np.int64), "v": np.array([1.0])}), lroot / "p.parquet")
        pq.write_table(pa.table({"k": np.array([2], dtype=np.int64), "w": np.array([2.0])}), rroot / "p.parquet")
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("eL", ["k"], ["v"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("eR", ["k"], ["w"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on="k").agg(n=("*", "count"), s=("v", "sum"), m=("w", "avg"))
        self._check(session, q)


def test_executor_routes_aggregate_through_fused_path(session, tmp_path, monkeypatch):
    """The executor wiring (not just the device function) must dispatch
    Aggregate-over-Join to the fused path."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 4)
    lroot, rroot = tmp_path / "wl", tmp_path / "wr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(pa.table({"k": np.arange(50, dtype=np.int64), "v": np.arange(50, dtype=np.float64)}), lroot / "p.parquet")
    pq.write_table(pa.table({"k": np.arange(50, dtype=np.int64), "w": np.arange(50, dtype=np.float64)}), rroot / "p.parquet")
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("wL", ["k"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("wR", ["k"], ["w"]))
    session.enable_hyperspace()
    calls = {"n": 0}
    real = D.aggregate_over_bucketed_join

    def counting(sess_, agg_, join_, **kw):
        calls["n"] += 1
        return real(sess_, agg_, join_, **kw)

    monkeypatch.setattr(D, "aggregate_over_bucketed_join", counting)
    got = ldf.join(rdf, on="k").agg(s=("v", "sum")).collect()
    assert calls["n"] == 1, "fused path was not taken by the executor"
    assert got["s"][0] == float(np.arange(50).sum())


def test_empty_join_float_sum_dtype(session, tmp_path):
    """SUM of a float column over an empty join stays float64, matching the
    materialized path."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    lroot, rroot = tmp_path / "fl2", tmp_path / "fr2"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(pa.table({"k": np.array([1], dtype=np.int64), "v": np.array([1.5])}), lroot / "p.parquet")
    pq.write_table(pa.table({"k": np.array([2], dtype=np.int64), "w": np.array([2.5])}), rroot / "p.parquet")
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("fL2", ["k"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("fR2", ["k"], ["w"]))
    session.enable_hyperspace()
    got = ldf.join(rdf, on="k").agg(s=("v", "sum")).collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    plain = ldf.join(rdf, on="k").agg(s=("v", "sum")).collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    assert got["s"].dtype == plain["s"].dtype == np.float64
    # SQL: SUM over an empty join is NULL (not 0) on both paths
    assert np.isnan(got["s"][0]) and np.isnan(plain["s"][0])


class TestGroupedFusedJoinAggregate:
    """GROUP BY the join key over a bucketed join fuses via segment
    reductions; results must equal the materialize-then-groupby path
    (compared as key->value maps — output order is not part of the
    contract)."""

    @pytest.fixture()
    def genv(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        rng = np.random.default_rng(61)
        lroot, rroot = tmp_path / "gl", tmp_path / "gr"
        lroot.mkdir(), rroot.mkdir()
        n = 3000
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 60, n).astype(np.int64),
                    "qty": rng.integers(1, 9, n).astype(np.int64),
                    "price": rng.uniform(1, 50, n),
                }
            ),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 80, 400).astype(np.int64),  # some keys unmatched
                    "fx": rng.uniform(0.5, 1.5, 400),
                }
            ),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("gL", ["k"], ["qty", "price"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("gR", ["k"], ["fx"]))
        session.enable_hyperspace()
        return ldf, rdf

    def _maps(self, batch, keys=("k",)):
        names = [c for c in batch if c not in keys]
        out = {}
        for i in range(len(batch[names[0]])):
            kk = tuple(batch[k][i] for k in keys)
            out[kk] = tuple(np.round(float(batch[n][i]), 6) for n in names)
        return out

    def test_grouped_parity(self, session, genv):
        ldf, rdf = genv
        q = ldf.join(rdf, on="k").group_by("k").agg(
            n=("*", "count"), s=("price", "sum"), sq=("qty", "sum"),
            a=("fx", "avg"), c=("fx", "count"))
        fused = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert self._maps(fused) == self._maps(plain)
        assert fused["sq"].dtype == np.int64  # exact int sums

    def test_grouped_path_is_taken(self, session, genv, monkeypatch):
        ldf, rdf = genv
        calls = {"n": 0}
        real = D._grouped_aggregate_over_join

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(D, "_grouped_aggregate_over_join", counting)
        ldf.join(rdf, on="k").group_by("k").agg(n=("*", "count")).collect()
        assert calls["n"] == 1

    def test_group_by_non_key_falls_back(self, session, genv):
        ldf, rdf = genv
        q = ldf.join(rdf, on="k").group_by("qty").agg(n=("*", "count"))
        fused = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert self._maps(fused, keys=("qty",)) == self._maps(plain, keys=("qty",))


class TestQ3ShapeFusion:
    """Round-5 generalization: GROUP BY join key + right-side payload keys
    with a computed aggregate input — TPC-H q3's exact shape — fuses
    without pair materialization when the right side is unique per key."""

    @pytest.fixture
    def q3env(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        rng = np.random.default_rng(3)
        lroot, rroot = tmp_path / "li3", tmp_path / "o3"
        lroot.mkdir(), rroot.mkdir()
        n = 4000
        base = np.datetime64("1994-01-01")
        pq.write_table(
            pa.table(
                {
                    "l_ok": rng.integers(0, 500, n).astype(np.int64),
                    "l_price": np.round(rng.uniform(10, 1000, n), 2),
                    "l_disc": np.round(rng.uniform(0, 0.1, n), 2),
                }
            ),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "o_ok": np.arange(500, dtype=np.int64),  # UNIQUE per key
                    "o_date": base + rng.integers(0, 300, 500).astype("timedelta64[D]"),
                    "o_prio": rng.integers(0, 3, 500).astype(np.int64),
                }
            ),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("q3L", ["l_ok"], ["l_price", "l_disc"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("q3R", ["o_ok"], ["o_date", "o_prio"]))
        session.enable_hyperspace()
        return ldf, rdf

    def _rows(self, batch):
        import datetime

        def norm(v):
            if isinstance(v, float):
                return f"{v:.5f}"
            if isinstance(v, (datetime.date, datetime.datetime)):
                # the fused path preserves the decoded datetime64 unit ([D]);
                # the pandas roundtrip of the materialized path yields ns —
                # same instant, different repr
                import pandas as pd

                return pd.Timestamp(v).isoformat()
            return str(v)

        cols = sorted(batch)
        return sorted(zip(*[[norm(v) for v in batch[c].tolist()] for c in cols]))

    def test_q3_group_keys_and_computed_input_fuse(self, session, q3env):
        ldf, rdf = q3env
        ldf.create_or_replace_temp_view("li3")
        rdf.create_or_replace_temp_view("o3")
        q = session.sql(
            """
            select l_ok, sum(l_price * (1 - l_disc)) as rev, o_date, o_prio,
                   count(*) as n
            from li3 join o3 on l_ok = o_ok
            group by l_ok, o_date, o_prio
            """
        )
        from hyperspace_tpu.exec import trace

        with trace.recording() as rec:
            fused = q.collect()
        assert ("agg", "fused-bucketed-join") in rec, trace.summarize(rec)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert self._rows(fused) == self._rows(plain)

    def test_right_extra_over_non_unique_right_falls_back(self, session, hs, tmp_path):
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        rng = np.random.default_rng(5)
        lroot, rroot = tmp_path / "nl", tmp_path / "nr"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(
            pa.table(
                {
                    "a": rng.integers(0, 30, 2000).astype(np.int64),
                    "v": rng.uniform(0, 10, 2000),
                }
            ),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "b": rng.integers(0, 30, 300).astype(np.int64),  # dupes
                    "tag": rng.integers(0, 4, 300).astype(np.int64),
                }
            ),
            rroot / "p.parquet",
        )
        ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("nL", ["a"], ["v"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("nR", ["b"], ["tag"]))
        session.enable_hyperspace()
        q = (
            ldf.join(rdf, on=hst.col("a") == hst.col("b"))
            .group_by("a", "tag")
            .agg(s=("v", "sum"), n=("*", "count"))
        )
        fused = q.collect()  # falls back to materialization internally
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert self._rows(fused) == self._rows(plain)

    def test_group_without_join_key_merges_across_buckets(self, session, q3env):
        """Group keys that don't pin the join key recur across buckets;
        the final merge must fold them into one row per group."""
        ldf, rdf = q3env
        q = (
            ldf.join(rdf, on=hst.col("l_ok") == hst.col("o_ok"))
            .group_by("o_prio")
            .agg(s=("l_price", "sum"), n=("*", "count"))
        )
        fused = q.collect()
        assert len(fused["o_prio"]) == len(set(fused["o_prio"].tolist()))
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        plain = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert self._rows(fused) == self._rows(plain)


def test_grouped_fused_repeated_key_granularity(session, tmp_path):
    """Grouping by l.a and r.a of a composite (a,b) join groups COARSER
    than the join-key runs; round 5's final-merge generalization fuses it
    correctly (pre-round-5 this shape was rejected to the materialized
    path). Results must equal the materialized path at the right
    granularity."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    rng = np.random.default_rng(71)
    lroot, rroot = tmp_path / "rl2", tmp_path / "rr2"
    lroot.mkdir(), rroot.mkdir()
    n = 400
    pq.write_table(
        pa.table({"a": rng.integers(0, 5, n).astype(np.int64),
                  "b": rng.integers(0, 5, n).astype(np.int64),
                  "v": rng.standard_normal(n)}), lroot / "p.parquet")
    pq.write_table(
        pa.table({"a": rng.integers(0, 5, 60).astype(np.int64),
                  "b": rng.integers(0, 5, 60).astype(np.int64),
                  "w": rng.standard_normal(60)}), rroot / "p.parquet")
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("rkL", ["a", "b"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("rkR", ["a", "b"], ["w"]))
    session.enable_hyperspace()
    j = ldf.join(rdf, on=["a", "b"])
    q = j.group_by("a", "a#r").agg(n=("*", "count"))
    fused = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    plain = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    assert fused["n"].shape == plain["n"].shape
    a = {(x, y): int(c) for x, y, c in zip(fused["a"], fused["a#r"], fused["n"])}
    b = {(x, y): int(c) for x, y, c in zip(plain["a"], plain["a#r"], plain["n"])}
    assert a == b


def test_grouped_fused_empty_join_dtypes(session, tmp_path):
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    lroot, rroot = tmp_path / "zl", tmp_path / "zr"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(pa.table({"k": np.array([1, 3], dtype=np.int64), "v": np.array([5, 6], dtype=np.int64)}), lroot / "p.parquet")
    pq.write_table(pa.table({"k": np.array([2, 4], dtype=np.int64), "w": np.array([7, 8], dtype=np.int64)}), rroot / "p.parquet")
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("zL", ["k"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("zR", ["k"], ["w"]))
    session.enable_hyperspace()
    q = ldf.join(rdf, on="k").group_by("k").agg(s=("v", "sum"))
    fused = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    plain = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    assert fused["k"].shape[0] == plain["k"].shape[0] == 0
    assert fused["k"].dtype == plain["k"].dtype == np.int64
    assert fused["s"].dtype == plain["s"].dtype == np.int64


def test_grouped_fused_name_collision_with_key(session, tmp_path):
    """A non-key column sharing a join key's name must not be mistaken for
    the key: group_by over it falls back and returns ITS values."""
    hs = hst.Hyperspace(session)
    session.conf.set(hst.keys.NUM_BUCKETS, 2)
    lroot, rroot = tmp_path / "nc_l", tmp_path / "nc_r"
    lroot.mkdir(), rroot.mkdir()
    pq.write_table(
        pa.table({"a": np.array([1, 2], dtype=np.int64), "v": np.array([0.5, 1.5])}),
        lroot / "p.parquet",
    )
    # right joins on 'b'; its non-key column 'a' holds DIFFERENT values
    pq.write_table(
        pa.table({"b": np.array([1, 2], dtype=np.int64), "a": np.array([100, 200], dtype=np.int64)}),
        rroot / "p.parquet",
    )
    ldf, rdf = session.read_parquet(str(lroot)), session.read_parquet(str(rroot))
    hs.create_index(ldf, hst.CoveringIndexConfig("ncL", ["a"], ["v"]))
    hs.create_index(rdf, hst.CoveringIndexConfig("ncR", ["b"], ["a"]))
    session.enable_hyperspace()
    q = ldf.join(rdf, on=hst.col("a") == hst.col("b")).group_by("a#r").agg(n=("*", "count"))
    fused = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    plain = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    assert sorted(fused["a#r"].tolist()) == sorted(plain["a#r"].tolist()) == [100, 200]
