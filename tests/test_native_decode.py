"""Native Parquet decoder parity tests.

The decoder (native/hs_native.cc via hyperspace_tpu.native) must agree with
pyarrow on every file in the framework's index dialect (uncompressed PLAIN or
dictionary pages) and cleanly refuse anything outside it so scans fall back.
The reference has no native code (SURVEY.md §2 "Native components: none");
this is the new C++ Parquet->device-buffer path of SURVEY.md §7.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import native
from hyperspace_tpu.exec.io import read_parquet_batch

pytestmark = [
    pytest.mark.native,
    pytest.mark.skipif(
        not native.is_available(), reason="native toolchain unavailable"
    ),
]


@pytest.fixture(scope="module")
def sample_table():
    rng = np.random.default_rng(7)
    n = 5000
    return pa.table(
        {
            "i64": rng.integers(-(10**12), 10**12, n).astype(np.int64),
            "i32": rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32),
            "f64": rng.standard_normal(n),
            "f32": rng.standard_normal(n).astype(np.float32),
            "flag": rng.integers(0, 2, n).astype(bool),
            "s": pa.array([f"val_{i % 97}" for i in range(n)]),
            "ts": pa.array(
                np.datetime64("2020-01-01")
                + rng.integers(0, 10**6, n).astype("timedelta64[s]")
            ),
        }
    )


def _assert_batch_matches(batch, table):
    """Parity contract: the native decode of a file equals pyarrow's decode of
    the same file (``table`` must come from ``pq.read_table``, not memory —
    parquet legally rewrites e.g. timestamp units on write)."""
    for c in table.column_names:
        exp = table[c].to_numpy(zero_copy_only=False)
        got = batch[c]
        if exp.dtype == object:
            assert all(a == b for a, b in zip(got, exp)), c
        else:
            assert got.dtype == exp.dtype, (c, got.dtype, exp.dtype)
            assert np.array_equal(got, exp), c


def test_native_available():
    assert native.is_available()


def test_plain_roundtrip(tmp_path, sample_table):
    p = str(tmp_path / "plain.parquet")
    pq.write_table(sample_table, p, use_dictionary=False, compression="NONE")
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_dictionary_roundtrip(tmp_path, sample_table):
    p = str(tmp_path / "dict.parquet")
    pq.write_table(sample_table, p, use_dictionary=True, compression="NONE")
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_multi_row_group(tmp_path, sample_table):
    p = str(tmp_path / "rg.parquet")
    pq.write_table(sample_table, p, use_dictionary=False, compression="NONE", row_group_size=512)
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_column_subset_and_multiple_files(tmp_path, sample_table):
    p1 = str(tmp_path / "a.parquet")
    p2 = str(tmp_path / "b.parquet")
    pq.write_table(sample_table.slice(0, 2000), p1, use_dictionary=False, compression="NONE")
    pq.write_table(sample_table.slice(2000), p2, use_dictionary=False, compression="NONE")
    got = read_parquet_batch([p1, p2], ["i64", "s"])
    assert set(got) == {"i64", "s"}
    assert np.array_equal(got["i64"], sample_table["i64"].to_numpy())


def test_nulls(tmp_path):
    t = pa.table(
        {
            "x": pa.array([None if i % 7 == 0 else float(i) for i in range(1000)]),
            "s": pa.array([None if i % 11 == 0 else f"s{i}" for i in range(1000)]),
            "k": pa.array([None if i % 5 == 0 else i for i in range(1000)], type=pa.int64()),
        }
    )
    p = str(tmp_path / "nulls.parquet")
    pq.write_table(t, p, use_dictionary=False, compression="NONE")
    got = read_parquet_batch([p], ["x", "s", "k"])
    exp_x = t["x"].to_numpy(zero_copy_only=False)
    assert np.array_equal(np.isnan(got["x"]), np.isnan(exp_x))
    exp_s = t["s"].to_numpy(zero_copy_only=False)
    assert all((a is None and b is None) or a == b for a, b in zip(got["s"], exp_s))
    # nullable ints surface as float64-with-NaN, pyarrow-compatible
    exp_k = t["k"].to_numpy(zero_copy_only=False)
    assert got["k"].dtype == exp_k.dtype == np.float64
    assert np.array_equal(np.isnan(got["k"]), np.isnan(exp_k))


@pytest.fixture()
def no_pyarrow_fallback(monkeypatch):
    """Make the pyarrow fallback in read_parquet_batch fail loudly, so a test
    passing under this fixture proves the NATIVE path decoded the file."""
    from hyperspace_tpu.exec import io as hs_io

    class _Boom:
        def dataset(self, *a, **k):
            raise AssertionError("pyarrow fallback used; expected native decode")

        def __getattr__(self, name):
            raise AssertionError("pyarrow fallback used; expected native decode")

    monkeypatch.setattr(hs_io, "pads", _Boom())


def test_snappy_plain_decodes_natively(tmp_path, sample_table, no_pyarrow_fallback):
    """Snappy is Spark's default output codec: externally-written lake files
    stay on the native path (round-3 VERDICT item; ref: Spark/parquet-mr
    write SNAPPY by default)."""
    p = str(tmp_path / "snappy.parquet")
    pq.write_table(sample_table, p, compression="SNAPPY", use_dictionary=False)
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_snappy_dictionary_decodes_natively(tmp_path, sample_table, no_pyarrow_fallback):
    p = str(tmp_path / "snappy_dict.parquet")
    pq.write_table(sample_table, p, compression="SNAPPY", use_dictionary=True)
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_snappy_nulls(tmp_path, no_pyarrow_fallback):
    t = pa.table(
        {
            "k": pa.array([1, None, 3, None, 5], type=pa.int64()),
            "s": pa.array(["a", None, "c", None, "e"]),
        }
    )
    p = str(tmp_path / "snappy_nulls.parquet")
    pq.write_table(t, p, compression="SNAPPY")
    got = read_parquet_batch([p], ["k", "s"])
    exp = pq.read_table(p)
    exp_k = exp["k"].to_numpy(zero_copy_only=False)
    assert np.array_equal(np.isnan(got["k"]), np.isnan(exp_k))
    assert got["s"][1] is None and got["s"][2] == "c"


def test_snappy_data_page_v2(tmp_path, sample_table, no_pyarrow_fallback):
    p = str(tmp_path / "snappy_v2.parquet")
    pq.write_table(sample_table, p, compression="SNAPPY", data_page_version="2.0")
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_gzip_decodes_natively(tmp_path, sample_table, no_pyarrow_fallback):
    """GZIP pages inflate through the system zlib on the native path."""
    p = str(tmp_path / "gzip.parquet")
    pq.write_table(sample_table, p, compression="GZIP", use_dictionary=False)
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_gzip_dictionary_and_nulls(tmp_path, no_pyarrow_fallback):
    t = pa.table(
        {
            "k": pa.array([1, None, 3], type=pa.int64()),
            "s": pa.array(["a", None, "c"]),
        }
    )
    p = str(tmp_path / "gzip_nulls.parquet")
    pq.write_table(t, p, compression="GZIP")
    got = read_parquet_batch([p], ["k", "s"])
    assert np.isnan(got["k"][1]) and got["s"][2] == "c"


def test_unsupported_codec_falls_back(tmp_path, sample_table):
    """Codecs outside the native dialect (lz4) still fall back to pyarrow."""
    p = str(tmp_path / "lz4.parquet")
    pq.write_table(sample_table, p, compression="LZ4")
    with pytest.raises(native.NativeUnsupported):
        native.read_columns(p, ["i64"])
    _assert_batch_matches(read_parquet_batch([p], sample_table.column_names), pq.read_table(p))


def test_native_rejects_nested(tmp_path):
    t = pa.table({"outer": pa.array([{"a": 1}, {"a": 2}])})
    p = str(tmp_path / "nested.parquet")
    pq.write_table(t, p, compression="NONE")
    with pytest.raises(native.NativeUnsupported):
        native.read_columns(p, ["outer"])


def test_index_files_are_native_decodable(tmp_path):
    """The bucketed index writer must emit files the native decoder accepts."""
    from hyperspace_tpu.indexes.covering import write_bucketed

    rng = np.random.default_rng(3)
    n = 4000
    t = pa.table(
        {
            "k": rng.integers(0, 500, n).astype(np.int64),
            "v": rng.standard_normal(n),
            "s": pa.array([f"n{i % 13}" for i in range(n)]),
        }
    )
    out = str(tmp_path / "idx")
    files = write_bucketed(t, ["k"], 8, out)
    assert files
    total = 0
    for f in files:
        with native.NativeParquetFile(f) as nf:
            assert set(nf.columns) == {"k", "v", "s"}
            k, _ = nf.read_column("k")
            assert np.all(k[1:] >= k[:-1])  # sorted within bucket
            total += nf.num_rows
    assert total == n


def test_schema_evolution_cached_reads(tmp_path):
    """A multi-file columns=None read over files with different schemas must
    null-fill via the dataset path, including when per-file cache entries
    already exist from earlier single-file reads (the fully-cached fast path
    is only taken for explicit projections, where batches are homogeneous)."""
    from hyperspace_tpu.exec.io import clear_io_cache

    clear_io_cache()
    fa = str(tmp_path / "a.parquet")
    fb = str(tmp_path / "b.parquet")
    pq.write_table(pa.table({"a": pa.array([1, 2], pa.int64()), "b": pa.array([10.0, 20.0])}), fa)
    pq.write_table(pa.table({"a": pa.array([3], pa.int64())}), fb)

    # warm per-file cache entries under (file, None)
    read_parquet_batch([fa], None)
    read_parquet_batch([fb], None)

    got = read_parquet_batch([fa, fb], None)
    assert got["a"].tolist() == [1, 2, 3]
    assert got["b"][:2].tolist() == [10.0, 20.0] and np.isnan(got["b"][2])

    # reversed order must not silently drop the evolved column either
    got = read_parquet_batch([fb, fa], None)
    assert sorted(got.keys()) == ["a", "b"]
    clear_io_cache()


def test_projected_cached_reads_concat(tmp_path):
    from hyperspace_tpu.exec.io import clear_io_cache

    clear_io_cache()
    fa = str(tmp_path / "c.parquet")
    fb = str(tmp_path / "d.parquet")
    pq.write_table(pa.table({"a": pa.array([1, 2], pa.int64())}), fa)
    pq.write_table(pa.table({"a": pa.array([3], pa.int64())}), fb)
    read_parquet_batch([fa], ["a"])
    read_parquet_batch([fb], ["a"])
    got = read_parquet_batch([fa, fb], ["a"])  # fully-cached fast path
    assert got["a"].tolist() == [1, 2, 3]
    clear_io_cache()


def test_merge_spans_matches_searchsorted():
    rng = np.random.default_rng(11)
    lk = np.sort(rng.integers(0, 500, 2000)).astype(np.int64)
    rk = np.sort(rng.integers(0, 500, 3000)).astype(np.int64)
    lo, hi = native.merge_spans(lk, rk)
    np.testing.assert_array_equal(lo, np.searchsorted(rk, lk, side="left"))
    np.testing.assert_array_equal(hi, np.searchsorted(rk, lk, side="right"))
    # no-match and empty-side edges
    lo, hi = native.merge_spans(np.array([1, 5], dtype=np.int64), np.array([2, 3], dtype=np.int64))
    assert (hi - lo).tolist() == [0, 0]
    lo, hi = native.merge_spans(np.array([], dtype=np.int64), rk)
    assert lo.shape == (0,)


def test_expand_pairs_matches_numpy():
    rng = np.random.default_rng(12)
    n = 500
    lo = rng.integers(0, 50, n).astype(np.int32)
    counts = rng.integers(0, 5, n).astype(np.int64)
    hi = (lo + counts).astype(np.int32)
    total = int(counts.sum())
    lidx, ridx = native.expand_pairs(lo, hi, total)
    exp_l = np.repeat(np.arange(n), counts)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    exp_r = np.arange(total) - np.repeat(offsets, counts) + np.repeat(lo, counts)
    np.testing.assert_array_equal(lidx, exp_l)
    np.testing.assert_array_equal(ridx, exp_r)


def test_snappy_adversarial_literal_length_rejected():
    """A 4-extra-byte literal length of 0xFFFFFFFF must be rejected, not
    wrap to 0 on the +1 and silently desynchronize the parse (the bounds
    checks kept it memory-safe, but the tag was skipped instead of the
    input being refused)."""
    # varint uncompressed length = 10, then literal tag with len-1 = 63
    # (=> 4 extra LE length bytes), all 0xFF
    blob = bytes([10, (63 << 2) | 0, 0xFF, 0xFF, 0xFF, 0xFF])
    try:
        with pytest.raises(ValueError):
            native.snappy_decompress(blob)
    except native.NativeUnsupported:
        pytest.skip("native library unavailable")


def test_snappy_roundtrip_long_literal():
    # 70000-byte literal exercises the multi-extra-byte length path end to end
    payload = bytes(range(256)) * 274
    compressed = _snappy_compress_literal(payload)
    try:
        assert native.snappy_decompress(compressed) == payload
    except native.NativeUnsupported:
        pytest.skip("native library unavailable")


def _snappy_compress_literal(payload: bytes) -> bytes:
    """Minimal raw-snappy encoder: one big literal (valid per the format)."""
    out = bytearray()
    n = len(payload)
    while n >= 0x80:  # varint uncompressed length
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    m = len(payload) - 1
    if m < 60:
        out.append(m << 2)
    else:
        nbytes = (m.bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += m.to_bytes(nbytes, "little")
    out += payload
    return bytes(out)


def test_date32_decodes_natively(tmp_path, no_pyarrow_fallback):
    """date32 (INT32 days) columns must decode natively — TPC-H's biggest
    tables carry dates, and a date column must not push the whole file onto
    the pyarrow fallback."""
    n = 1000
    days = np.arange(n, dtype=np.int64) % 2500
    dates = (np.datetime64("1992-01-01") + days.astype("timedelta64[D]"))
    t = pa.table({
        "d": dates,  # arrow date32
        "k": np.arange(n, dtype=np.int64),
    })
    assert pa.types.is_date32(t.schema.field("d").type)
    p = str(tmp_path / "dates.parquet")
    pq.write_table(t, p)
    got = read_parquet_batch([p], ["d", "k"])
    assert got["d"].dtype == np.dtype("datetime64[D]")
    np.testing.assert_array_equal(got["d"], dates)


def test_date32_nulls_decode_natively(tmp_path, no_pyarrow_fallback):
    vals = [0, None, 100, None, 9000]
    t = pa.table({"d": pa.array(vals, type=pa.date32())})
    p = str(tmp_path / "dates_null.parquet")
    pq.write_table(t, p)
    got = read_parquet_batch([p], ["d"])
    assert got["d"].dtype.kind == "M"
    assert np.isnat(got["d"][1]) and np.isnat(got["d"][3])
    assert got["d"][4] == np.datetime64("1970-01-01") + np.timedelta64(9000, "D")


def test_zstd_plain_decodes_natively(tmp_path, sample_table, no_pyarrow_fallback):
    p = str(tmp_path / "zstd.parquet")
    pq.write_table(sample_table, p, compression="zstd", use_dictionary=False)
    got = read_parquet_batch([p], ["i64", "f64", "s"])
    np.testing.assert_array_equal(got["i64"], sample_table["i64"].to_numpy())
    np.testing.assert_array_equal(got["f64"], sample_table["f64"].to_numpy())
    assert got["s"].tolist() == sample_table["s"].to_pylist()


def test_zstd_dictionary_decodes_natively(tmp_path, sample_table, no_pyarrow_fallback):
    p = str(tmp_path / "zstd_dict.parquet")
    pq.write_table(sample_table, p, compression="zstd", use_dictionary=True)
    got = read_parquet_batch([p], ["i64", "s"])
    np.testing.assert_array_equal(got["i64"], sample_table["i64"].to_numpy())
    assert got["s"].tolist() == sample_table["s"].to_pylist()


def test_zstd_nulls(tmp_path, no_pyarrow_fallback):
    t = pa.table({
        "a": pa.array([1, None, 3, None, 5], type=pa.int64()),
        "s": pa.array(["x", None, "z", "w", None]),
    })
    p = str(tmp_path / "zstd_nulls.parquet")
    pq.write_table(t, p, compression="zstd")
    got = read_parquet_batch([p], ["a", "s"])
    assert np.isnan(got["a"][1]) and np.isnan(got["a"][3])
    assert got["s"].tolist() == ["x", None, "z", "w", None]
