"""Round-3 ADVICE regressions: three-valued IN semantics, SQL HALF_UP
rounding, Spark substr position rules, and NULL-preserving boolean
projection (ref: Spark semantics the reference inherits for free —
e.g. org.apache.spark.sql.catalyst.expressions.In / Round / Substring)."""

import numpy as np
import pytest

from hyperspace_tpu.plan.expr import (
    Func,
    In,
    InSubquery,
    Lit,
    NullableBool,
    as_bool_mask,
    col,
    lit,
)


class TestThreeValuedIn:
    def test_null_child_is_unknown_not_false(self):
        batch = {"x": np.array([1.0, np.nan, 3.0])}
        e = In(col("x"), [Lit(1.0), Lit(2.0)])
        got = e.eval(batch)
        assert isinstance(got, NullableBool)
        np.testing.assert_array_equal(got.value, [True, False, False])
        np.testing.assert_array_equal(got.unknown, [False, True, False])
        # NOT (x IN ...) must drop the NULL row, not keep it
        from hyperspace_tpu.plan.expr import _kleene_not

        neg = _kleene_not(got)
        np.testing.assert_array_equal(as_bool_mask(neg), [False, False, True])

    def test_null_in_value_list_makes_nonmatches_unknown(self):
        batch = {"x": np.array([1.0, 5.0])}
        e = In(col("x"), [Lit(1.0), Lit(None)])
        got = e.eval(batch)
        assert isinstance(got, NullableBool)
        # 1 matches -> TRUE; 5 doesn't match but NULL in list -> UNKNOWN
        np.testing.assert_array_equal(as_bool_mask(got), [True, False])
        np.testing.assert_array_equal(got.unknown, [False, True])

    def test_no_nulls_stays_plain_bool(self):
        batch = {"x": np.array([1, 2, 3], dtype=np.int64)}
        got = In(col("x"), [Lit(2)]).eval(batch)
        assert not isinstance(got, NullableBool)
        np.testing.assert_array_equal(got, [False, True, False])

    def test_in_subquery_null_child_unknown(self, session, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp_path / "t"
        root.mkdir()
        pq.write_table(pa.table({"v": np.array([1.0, 2.0])}), root / "p.parquet")
        inner = session.read_parquet(str(root)).select("v")
        e = InSubquery(col("x"), inner.plan, session)
        from hyperspace_tpu.plan.expr import subquery_scope

        with subquery_scope():
            got = e.eval({"x": np.array([1.0, np.nan, 9.0])})
        assert isinstance(got, NullableBool)
        np.testing.assert_array_equal(as_bool_mask(got), [True, False, False])
        np.testing.assert_array_equal(got.unknown, [False, True, False])

    def test_in_subquery_null_among_values(self, session, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp_path / "t2"
        root.mkdir()
        pq.write_table(pa.table({"v": np.array([1.0, np.nan])}), root / "p.parquet")
        inner = session.read_parquet(str(root)).select("v")
        e = InSubquery(col("x"), inner.plan, session)
        from hyperspace_tpu.plan.expr import subquery_scope

        with subquery_scope():
            got = e.eval({"x": np.array([1.0, 9.0])})
        assert isinstance(got, NullableBool)
        np.testing.assert_array_equal(as_bool_mask(got), [True, False])
        np.testing.assert_array_equal(got.unknown, [False, True])


class TestRoundHalfUp:
    def test_half_up_not_bankers(self):
        batch = {"v": np.array([2.5, 3.5, -2.5, 0.5, 1.25])}
        got = Func("round", [col("v")]).eval(batch)
        np.testing.assert_array_equal(got[:4], [3.0, 4.0, -3.0, 1.0])

    def test_digits(self):
        batch = {"v": np.array([1.005, 2.675])}
        got = Func("round", [col("v"), lit(2)]).eval(batch)
        # representable halves round away from zero
        assert got[0] == pytest.approx(1.0, abs=0.011)
        assert abs(got[1] - 2.68) <= 0.01


class TestSubstrSparkSemantics:
    def _substr(self, s, start, ln=None):
        args = [lit(s), lit(start)] + ([lit(ln)] if ln is not None else [])
        return Func("substr", [col("s"), lit(start)] + ([lit(ln)] if ln is not None else [])).eval(
            {"s": np.array([s], dtype=object)}
        )[0]

    def test_position_zero_like_one(self):
        assert self._substr("abcde", 0, 2) == "ab"
        assert self._substr("abcde", 1, 2) == "ab"

    def test_negative_start_counts_from_end(self):
        assert self._substr("abcde", -2, 3) == "de"
        assert self._substr("abcde", -2) == "de"

    def test_negative_start_before_string_start(self):
        # length applies from the virtual position: chars -8..-6 don't exist
        assert self._substr("abcde", -8, 3) == ""

    def test_null_in_null_out(self):
        got = Func("substr", [col("s"), lit(1), lit(2)]).eval(
            {"s": np.array([None, "xy"], dtype=object)}
        )
        assert got[0] is None and got[1] == "xy"


class TestBooleanProjectionKeepsNull:
    def test_projected_comparison_over_null_is_null(self, session, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        root = tmp_path / "b"
        root.mkdir()
        pq.write_table(
            pa.table({"a": np.array([1.0, np.nan, 2.0]), "b": np.array([1.0, 5.0, 9.0])}),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("t")
        got = session.sql("SELECT (a = b) AS eq FROM t").collect()
        vals = got["eq"].tolist()
        assert vals[0] is True or vals[0] == True  # noqa: E712
        assert vals[1] is None  # NULL operand -> NULL, not False
        assert bool(vals[2]) is False
        # and IS NULL over the alias sees it
        got2 = session.sql("SELECT (a = b) AS eq FROM t WHERE (a = b) IS NULL").collect()
        assert len(got2["eq"]) == 1
