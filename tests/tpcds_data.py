"""TPC-DS-shaped fixture data: small tables whose value domains satisfy the
103 reference query texts' predicates, so on/off parity checks are not
vacuous (a query returning 0 rows proves nothing).

Shape sources:
- ``date_dim`` is a real calendar (1998-01-01 .. 2002-12-31) with consistent
  d_year/d_moy/d_dom/d_qoy/d_dow/d_month_seq/d_week_seq (TPC-DS convention:
  d_month_seq 1200 == Jan 2000), because nearly every query correlates
  through it.
- String/numeric domains below were extracted from the literals the query
  texts actually use (i_category = 'Books', s_state = 'TN', d_dow in (6,0),
  ...): ~60%% of each column's rows draw from the query-relevant domain.
- Foreign keys land inside the referenced table's surrogate-key range, and
  each returns table's (item_sk, order/ticket number) pairs are sampled from
  its sales table so returns join back to sales (q24/q64/q78 shapes).

The reference fills these tables from dsdgen at scale; this is the smallest
generator whose data makes the query suite meaningful (TPCDSBase.scala
creates the schema over EMPTY dirs — plan-stability only; this suite also
checks answers).
"""

from __future__ import annotations

import zlib

import numpy as np

from tpcds_schema import TPCDS_SCHEMAS

# literals the 103 query texts predicate on (lower-cased column -> values)
STRING_DOMAINS = {
    "c_preferred_cust_flag": ["Y", "N"],
    "ca_city": ["Edgewood", "Fairview", "Midway", "Oakland"],
    "ca_country": ["United States"],
    "ca_county": ["Dona Ana County", "Jefferson County", "La Porte County",
                  "Rush County", "Toole County", "Williamson County"],
    "ca_state": ["AR", "CA", "CO", "CT", "GA", "IA", "IL", "IN", "KY", "LA",
                 "MN", "MS", "MT", "NC", "ND", "NE", "NM", "NY", "OH", "OK",
                 "OR", "SC", "SD", "TN", "TX", "UT", "VA", "WA", "WI", "WV"],
    "cc_county": ["Williamson County"],
    "cd_education_status": ["2 yr Degree", "4 yr Degree", "Advanced Degree",
                            "College", "Primary", "Secondary", "Unknown"],
    "cd_gender": ["M", "F"],
    "cd_marital_status": ["M", "S", "D", "W", "U"],
    "cd_credit_rating": ["Good", "High Risk", "Low Risk", "Unknown"],
    "hd_buy_potential": [">10000", "unknown", "Unknown", "1001-5000", "0-500"],
    "i_brand": ["amalgimporto #1", "edu packscholar #1", "exportiimporto #1",
                "exportiunivamalg #9", "importoamalg #1",
                "scholaramalgamalg #14", "scholaramalgamalg #7",
                "scholaramalgamalg #9"],
    "i_category": ["Books", "Children", "Electronics", "Home", "Jewelry",
                   "Men", "Music", "Shoes", "Sports", "Women"],
    "i_class": ["accessories", "birdal", "classical", "computers", "dresses",
                "football", "fragrances", "maternity", "pants", "personal",
                "portable", "reference", "self-help", "shirts", "wallpaper"],
    "i_color": ["pale", "chiffon", "slate", "blanched", "burnished", "purple",
                "burlywood", "indian", "spring", "floral", "medium", "brown",
                "cornflower", "cyan", "deep", "forest", "frosted", "ghost",
                "honeydew", "khaki", "light", "midnight", "orange", "papaya",
                "powder", "snow", "rose", "metallic", "dim", "smoke"],
    "i_size": ["N/A", "extra large", "large", "medium", "petite", "small"],
    "i_units": ["Box", "Bunch", "Bundle", "Cup", "Dozen", "Dram", "Each",
                "Gross", "Lb", "N/A", "Ounce", "Oz", "Pallet", "Pound",
                "Tbl", "Ton"],
    "p_channel_dmail": ["Y", "N"],
    "p_channel_email": ["N", "Y"],
    "p_channel_event": ["N", "Y"],
    "p_channel_tv": ["N", "Y"],
    "r_reason_desc": ["reason 28", "reason 1", "reason 2"],
    "s_city": ["Fairview", "Midway"],
    "s_county": ["Bronx County", "Franklin Parish", "Orange County",
                 "Williamson County"],
    "s_state": ["TN", "SD", "AL"],
    "s_store_name": ["ese", "ought", "able", "bar"],
    "sm_carrier": ["BARIAN", "DHL", "UPS", "FEDEX"],
    "sm_type": ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"],
    "t_meal_time": ["breakfast", "dinner", "lunch", "N/A"],
    "web_company_name": ["pri", "sec"],
    "s_geography_class": ["Unknown"],
    "c_birth_country": ["CANADA", "MEXICO", "GERMANY", "FRANCE", "JAPAN",
                        "BRAZIL", "INDIA", "UNITED STATES"],
    "ca_location_type": ["condo", "single family", "apartment"],
    # q8's zip list (substr(ca_zip,1,5) membership; s_zip joins by prefix)
    "ca_zip": ["24128", "76232", "65084", "87816", "83926", "77556", "20548",
               "26231", "43848", "15126", "91137", "61265", "98294", "25782",
               "10144", "10336", "10390", "10445", "10516", "10567"],
    "s_zip": ["24128", "76232", "65084", "87816", "83926", "77556", "20548",
              "26231", "43848", "15126"],
}

NUM_DOMAINS = {
    "hd_dep_count": [0, 1, 2, 3, 4, 5, 6, 7, 8, 9],
    "hd_vehicle_count": [-1, 0, 1, 2, 3, 4],
    "i_manager_id": [1, 8, 28, 33, 36, 38, 40, 59, 91, 100],
    "i_manufact_id": [128, 129, 270, 350, 423, 677, 694, 808, 821, 940, 977],
    "t_hour": [8, 9, 10, 11, 12, 15, 16, 20],
    "c_birth_month": [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
    "s_number_employees": [200, 250, 290, 295, 300],
    "s_market_id": [5, 7, 8, 10],
    "ss_quantity": list(range(1, 101)),
    "cs_quantity": list(range(1, 101)),
    "ws_quantity": list(range(1, 101)),
}

GMT_OFFSETS = [-5.0, -6.0, -7.0, -8.0]

ROWS = {
    "date_dim": None,  # calendar-determined (1826)
    "time_dim": 720,
    "customer": 200,
    "customer_address": 160,
    "customer_demographics": 240,
    "household_demographics": 72,
    "income_band": 20,
    "item": 140,
    "store": 12,
    "warehouse": 8,
    "promotion": 30,
    "reason": 10,
    "ship_mode": 12,
    "web_site": 6,
    "web_page": 20,
    "call_center": 6,
    "catalog_page": 40,
    "store_sales": 6000,
    "catalog_sales": 3000,
    "web_sales": 3000,
    "store_returns": 1200,
    "catalog_returns": 800,
    "web_returns": 800,
    "inventory": 2400,
}

# foreign keys by suffix -> referenced table (sk base offset for date_dim)
DATE_SK0 = 2450815  # TPC-DS julian-day convention for 1998-01-01

_FK_SUFFIX = {
    "_date_sk": "date_dim",
    "_time_sk": "time_dim",
    "_item_sk": "item",
    "_customer_sk": "customer",
    "_cdemo_sk": "customer_demographics",
    "_hdemo_sk": "household_demographics",
    "_addr_sk": "customer_address",
    "_store_sk": "store",
    "_promo_sk": "promotion",
    "_warehouse_sk": "warehouse",
    "_ship_mode_sk": "ship_mode",
    "_web_page_sk": "web_page",
    "_web_site_sk": "web_site",
    "_call_center_sk": "call_center",
    "_reason_sk": "reason",
    "_catalog_page_sk": "catalog_page",
    "_income_band_sk": "income_band",
    "_page_sk": "web_page",
}


def _calendar():
    start = np.datetime64("1998-01-01")
    end = np.datetime64("2003-01-01")
    dates = np.arange(start, end, dtype="datetime64[D]")
    n = len(dates)
    years = dates.astype("datetime64[Y]").astype(int) + 1970
    months0 = dates.astype("datetime64[M]").astype(int)  # months since 1970-01
    moy = months0 % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    # TPC-DS d_dow: 0 = Sunday; numpy day 0 (1970-01-01) was a Thursday
    dow = (dates.astype(int) + 4) % 7
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"], dtype=object)[dow]
    qoy = (moy - 1) // 3 + 1
    cols = {
        "d_date_sk": np.arange(n, dtype=np.int64) + DATE_SK0,
        "d_date_id": np.array([f"AAAAAAAA{i:08d}" for i in range(n)], dtype=object),
        "d_date": dates,
        "d_month_seq": ((years - 1900) * 12 + moy - 1).astype(np.int64),
        "d_week_seq": ((dates.astype(int) + 4) // 7).astype(np.int64),
        "d_quarter_seq": ((years - 1900) * 4 + qoy - 1).astype(np.int64),
        "d_year": years.astype(np.int64),
        "d_dow": dow.astype(np.int64),
        "d_moy": moy.astype(np.int64),
        "d_dom": dom.astype(np.int64),
        "d_qoy": qoy.astype(np.int64),
        "d_fy_year": years.astype(np.int64),
        "d_fy_quarter_seq": ((years - 1900) * 4 + qoy - 1).astype(np.int64),
        "d_fy_week_seq": ((dates.astype(int) + 4) // 7).astype(np.int64),
        "d_day_name": day_names,
        "d_quarter_name": np.array([f"{y}Q{q}" for y, q in zip(years, qoy)], dtype=object),
        "d_holiday": np.where(dow == 0, "Y", "N").astype(object),
        "d_weekend": np.where((dow == 0) | (dow == 6), "Y", "N").astype(object),
        "d_following_holiday": np.where(dow == 1, "Y", "N").astype(object),
        "d_first_dom": np.arange(n, dtype=np.int64) + DATE_SK0 - (dom - 1),
        "d_last_dom": np.arange(n, dtype=np.int64) + DATE_SK0 + 27,
        "d_same_day_ly": np.arange(n, dtype=np.int64) + DATE_SK0 - 365,
        "d_same_day_lq": np.arange(n, dtype=np.int64) + DATE_SK0 - 91,
        "d_current_day": np.full(n, "N", dtype=object),
        "d_current_week": np.full(n, "N", dtype=object),
        "d_current_month": np.full(n, "N", dtype=object),
        "d_current_quarter": np.full(n, "N", dtype=object),
        "d_current_year": np.full(n, "N", dtype=object),
    }
    # keep only the roster's columns, in roster order
    return {c: cols[c] for c in TPCDS_SCHEMAS["date_dim"]}


def _time_dim():
    n = ROWS["time_dim"]
    i = np.arange(n, dtype=np.int64)
    hour = (i * 24 // n).astype(np.int64)
    minute = i % 60
    meal = np.where(
        (hour >= 6) & (hour <= 9), "breakfast",
        np.where((hour >= 11) & (hour <= 13), "lunch",
                 np.where((hour >= 17) & (hour <= 21), "dinner", "N/A")),
    ).astype(object)
    cols = {
        "t_time_sk": i,
        "t_time_id": np.array([f"TIME{k:08d}" for k in range(n)], dtype=object),
        "t_time": hour * 3600 + minute * 60,
        "t_hour": hour,
        "t_minute": minute.astype(np.int64),
        "t_second": np.zeros(n, dtype=np.int64),
        "t_am_pm": np.where(hour < 12, "AM", "PM").astype(object),
        "t_shift": np.where(hour < 8, "first", np.where(hour < 16, "second", "third")).astype(object),
        "t_sub_shift": np.where(hour < 12, "morning", np.where(hour < 18, "afternoon", "night")).astype(object),
        "t_meal_time": meal,
    }
    return {c: cols[c] for c in TPCDS_SCHEMAS["time_dim"]}


def _fk_table(cname: str):
    for suffix, table in _FK_SUFFIX.items():
        if cname.endswith(suffix):
            return table
    return None


def _sk_domain(table: str):
    if table == "date_dim":
        return DATE_SK0, DATE_SK0 + 1826
    return 0, ROWS[table]


def arrow_tables():
    """-> {table: pa.Table} with the q76 NULL masks applied."""
    import pyarrow as pa

    tables, null_masks = build_tables()
    out = {}
    for name, cols in tables.items():
        arrays = {}
        for cn, v in cols.items():
            mask = null_masks.get((name, cn))
            arrays[cn] = pa.array(v, mask=mask) if mask is not None else pa.array(v)
        out[name] = pa.table(arrays)
    return out


def build_tables():
    """-> ({table: {column: np.ndarray}}, {(table, column): null mask}) with
    deterministic per-table seeds."""
    out = {"date_dim": _calendar(), "time_dim": _time_dim()}
    order = [t for t in TPCDS_SCHEMAS if t not in out]
    # dims first so fact FKs can reference sizes (sizes are static anyway)
    for name in order:
        rng = np.random.default_rng(zlib.crc32(name.encode()))
        schema = TPCDS_SCHEMAS[name]
        n = ROWS[name]
        cols = {}
        first = next(iter(schema))
        for cname, t in schema.items():
            lc = cname.lower()
            if cname == first and cname.endswith("_sk") and _fk_table(lc) in (name, None):
                # a dimension's own surrogate key (item.i_item_sk); a fact
                # table's first column is a foreign key (ss_sold_date_sk)
                cols[cname] = np.arange(n, dtype=np.int64)  # primary key
            elif t == "I" and _fk_table(lc) is not None:
                lo, hi = _sk_domain(_fk_table(lc))
                if _fk_table(lc) == "date_dim":
                    # concentrate activity in 2000-2001 (the years most query
                    # windows target) plus a pinch on the exact dates
                    # q58/q83 name, instead of uniform over five years
                    u = rng.random(n)
                    uniform = rng.integers(lo, hi, n)
                    y2000 = lo + 365 * 2 + rng.integers(0, 730, n)  # 2000-2001
                    hot = np.asarray(
                        [np.datetime64(s) - np.datetime64("1998-01-01") for s in
                         ("2000-01-03", "2000-06-30", "2000-09-27", "2000-11-17")]
                    ).astype(np.int64) + lo
                    vals = np.where(u < 0.45, uniform, np.where(u < 0.93, y2000, hot[rng.integers(0, 4, n)]))
                    cols[cname] = vals.astype(np.int64)
                else:
                    cols[cname] = rng.integers(lo, hi, n).astype(np.int64)
            elif lc in NUM_DOMAINS:
                dom = np.asarray(NUM_DOMAINS[lc], dtype=np.int64)
                cols[cname] = dom[rng.integers(0, len(dom), n)]
            elif lc.endswith("_gmt_offset"):
                cols[cname] = np.asarray(GMT_OFFSETS, dtype=np.float64)[
                    rng.integers(0, len(GMT_OFFSETS), n)
                ]
            elif lc in STRING_DOMAINS:
                dom = STRING_DOMAINS[lc]
                # mild Zipf toward early entries: HAVING-count thresholds
                # (q6's count >= 10 per state) need value concentration,
                # not a uniform spread
                w = 1.0 / (np.arange(len(dom)) + 2.0)
                pick = rng.choice(len(dom), n, p=w / w.sum())
                vals = np.array([dom[p] for p in pick], dtype=object)
                other = rng.random(n) >= 0.9  # small tail outside the domain
                vals[other] = np.array([f"{lc[:5]}_{v}" for v in np.nonzero(other)[0]], dtype=object)
                cols[cname] = vals
            elif lc.endswith("_id"):
                # business ids UNIQUE (q4/q11/q31 CTE self-joins explode on
                # collisions); customers deliberately share ids with nothing
                cols[cname] = np.array(
                    [f"{lc[:6]}_{i:06d}" for i in rng.permutation(n)], dtype=object
                )
            elif lc.endswith("_year"):
                cols[cname] = rng.integers(1998, 2003, n).astype(np.int64)
            elif lc.endswith(("_moy", "_month_seq")):
                cols[cname] = rng.integers(1176, 1236, n).astype(np.int64) if lc.endswith("_month_seq") else rng.integers(1, 13, n).astype(np.int64)
            elif t == "I":
                cols[cname] = rng.integers(0, max(n, 20), n).astype(np.int64)
            elif lc == "i_current_price":
                # bimodal: q21's 0.99-1.49 window AND q37/q64/q82's 60-100
                # BETWEEN windows both need occupants
                lowp = rng.random(n) < 0.4
                cols[cname] = np.round(
                    np.where(lowp, rng.uniform(0.8, 1.6, n), rng.uniform(55, 105, n)), 2
                )
            elif lc.endswith(("_return_amt", "_return_amount")):
                # q49's ratio CTEs require individual return amounts above
                # 10000; heavy-tailed so both small and huge returns exist
                cols[cname] = np.round(rng.exponential(6000.0, n), 2)
            elif lc == "inv_quantity_on_hand":
                # lognormal (median ~100, cov ~1.9): q37/q82's BETWEEN 100
                # AND 500 window, q39's cov > 1 filter, and q72's
                # inv < cs_quantity all need a heavy tail plus small values
                cols[cname] = rng.lognormal(4.6, 1.2, n).astype(np.int64)
            elif t == "F":
                cols[cname] = np.round(rng.uniform(0, 160, n), 2)
            elif t == "D":
                cols[cname] = np.datetime64("1998-01-01") + rng.integers(0, 1826, n).astype("timedelta64[D]")
            else:
                cols[cname] = np.array([f"{lc[:6]}_{v}" for v in rng.integers(0, max(n // 2, 10), n)], dtype=object)
        out[name] = cols

    # q41 hand-crafted items: manufact ids in its BETWEEN 738 AND 778 window
    # with the exact (category, color, units, size) conjunctions its EXISTS
    # subquery counts — random draws essentially never co-produce these
    q41 = [
        ("Women", "powder", "Ounce", "medium"),
        ("Women", "khaki", "Oz", "extra large"),
        ("Women", "brown", "Bunch", "N/A"),
        ("Women", "honeydew", "Ton", "small"),
        ("Men", "floral", "N/A", "petite"),
        ("Men", "deep", "Dozen", "large"),
        ("Men", "light", "Box", "medium"),
        ("Men", "cornflower", "Pound", "extra large"),
        ("Women", "midnight", "Pallet", "medium"),
        ("Women", "snow", "Gross", "extra large"),
        ("Women", "cyan", "Cup", "N/A"),
        ("Women", "papaya", "Dram", "small"),
        ("Men", "orange", "Each", "petite"),
        ("Men", "frosted", "Tbl", "large"),
    ]
    it = out["item"]
    manu41 = [738, 742, 750, 758, 766, 778]
    for j, (cat, color, units, size) in enumerate(q41):
        it["i_category"][j] = cat
        it["i_color"][j] = color
        it["i_units"][j] = units
        it["i_size"][j] = size
        it["i_manufact_id"][j] = manu41[j % len(manu41)]
        it["i_manufact"][j] = f"manu_{manu41[j % len(manu41)]}"

    # inventory is a (warehouse x item-subset x weekly snapshot) GRID, like
    # dsdgen's: q39's per-month coefficient of variation needs several
    # observations per (w, i, month) group — independent random rows give
    # group sizes of ~1 where stdev is identically 0
    n_w = ROWS["warehouse"]
    items_inv = np.arange(0, ROWS["item"], 5, dtype=np.int64)  # every 5th item
    weeks = np.arange(DATE_SK0 + 730, DATE_SK0 + 1460, 14, dtype=np.int64)  # biweekly 2000-2001
    grid_w, grid_i, grid_d = np.meshgrid(
        np.arange(n_w, dtype=np.int64), items_inv, weeks, indexing="ij"
    )
    inv_rng = np.random.default_rng(41)
    inv_n = grid_w.size
    out["inventory"] = {
        "inv_date_sk": grid_d.ravel(),
        "inv_item_sk": grid_i.ravel(),
        "inv_warehouse_sk": grid_w.ravel(),
        "inv_quantity_on_hand": inv_rng.lognormal(4.6, 1.2, inv_n).astype(np.int64),
    }
    ROWS["inventory"] = inv_n

    # income bands cover the queries' ib_lower_bound/ib_upper_bound windows
    ib_n = ROWS["income_band"]
    out["income_band"]["ib_lower_bound"] = (np.arange(ib_n, dtype=np.int64)) * 10000
    out["income_band"]["ib_upper_bound"] = (np.arange(ib_n, dtype=np.int64)) * 10000 + 9999

    # baskets: several lines share a ticket/order so returns and q64-style
    # resale joins have multiplicity; ~15% of store tickets are BIG (15-20
    # lines) because q34/q46/q68 filter on per-ticket line counts 15-20
    rng = np.random.default_rng(9)
    n_ss = ROWS["store_sales"]
    tickets = []
    tno = 0
    while sum(len(t) for t in tickets) < n_ss:
        size = int(rng.integers(15, 21)) if rng.random() < 0.12 else int(rng.integers(1, 6))
        tickets.append([tno] * size)
        tno += 1
    flat = np.array([t for grp in tickets for t in grp][:n_ss], dtype=np.int64)
    out["store_sales"]["ss_ticket_number"] = flat
    # one customer+date+store+hdemo per ticket: the q34/q46 GROUP BY
    # (ticket, customer) count only reaches 15-20 if the ticket's lines
    # agree on those columns
    for col in ("ss_customer_sk", "ss_sold_date_sk", "ss_store_sk",
                "ss_hdemo_sk", "ss_addr_sk"):
        vals = out["store_sales"][col]
        first_of = {}
        for i, t in enumerate(flat):
            j = first_of.setdefault(int(t), i)
            vals[i] = vals[j]
    out["catalog_sales"]["cs_order_number"] = np.arange(ROWS["catalog_sales"], dtype=np.int64) // 2
    out["web_sales"]["ws_order_number"] = np.arange(ROWS["web_sales"], dtype=np.int64) // 2

    # returns reference REAL sales rows (same item + ticket/order), so
    # sales-joins-returns queries produce rows
    def link_returns(ret, sales, r_item, r_no, s_item, s_no, extra=(), date_pair=None):
        m = ROWS[ret]
        pick = rng.integers(0, ROWS[sales], m)
        out[ret][r_item] = out[sales][s_item][pick]
        out[ret][r_no] = out[sales][s_no][pick]
        for rcol, scol in extra:
            out[ret][rcol] = out[sales][scol][pick]
        if date_pair is not None:
            # a return happens days after its sale: q17/q25/q29/q91
            # correlate the two date windows; a pinch of returns land
            # exactly on q83's literal d_date values
            rcol, scol = date_pair
            hi = DATE_SK0 + 1825
            dates = np.minimum(
                out[sales][scol][pick] + rng.integers(1, 61, m), hi
            ).astype(np.int64)
            hot = np.asarray(
                [np.datetime64(s) - np.datetime64("1998-01-01") for s in
                 ("2000-06-30", "2000-09-27", "2000-11-17")]
            ).astype(np.int64) + DATE_SK0
            pin = rng.random(m) < 0.04
            dates[pin] = hot[rng.integers(0, 3, pin.sum())]
            out[ret][rcol] = dates

    link_returns(
        "store_returns", "store_sales", "sr_item_sk", "sr_ticket_number",
        "ss_item_sk", "ss_ticket_number",
        [("sr_customer_sk", "ss_customer_sk"), ("sr_store_sk", "ss_store_sk")],
        date_pair=("sr_returned_date_sk", "ss_sold_date_sk"),
    )
    # q17/q29 chain: a catalog purchase by the same customer of the same
    # item they returned in a store — rewrite 40% of catalog_sales rows from
    # store_returns pairs (before catalog_returns links to cs)
    m = ROWS["catalog_sales"]
    take = np.nonzero(rng.random(m) < 0.4)[0]
    pick_sr = rng.integers(0, ROWS["store_returns"], len(take))
    out["catalog_sales"]["cs_item_sk"][take] = out["store_returns"]["sr_item_sk"][pick_sr]
    out["catalog_sales"]["cs_bill_customer_sk"][take] = out["store_returns"]["sr_customer_sk"][pick_sr]

    link_returns(
        "catalog_returns", "catalog_sales", "cr_item_sk", "cr_order_number",
        "cs_item_sk", "cs_order_number",
        [("cr_returning_customer_sk", "cs_bill_customer_sk")],
        date_pair=("cr_returned_date_sk", "cs_sold_date_sk"),
    )
    link_returns(
        "web_returns", "web_sales", "wr_item_sk", "wr_order_number",
        "ws_item_sk", "ws_order_number",
        [("wr_returning_customer_sk", "ws_bill_customer_sk")],
        date_pair=("wr_returned_date_sk", "ws_sold_date_sk"),
    )
    # q85 joins cd1/cd2 via refunded+returning demo sks requiring equal
    # marital/education on both: make them literally the same demo row often
    wr = out["web_returns"]
    same = rng.random(ROWS["web_returns"]) < 0.6
    wr["wr_refunded_cdemo_sk"] = np.where(same, wr["wr_returning_cdemo_sk"], wr["wr_refunded_cdemo_sk"])

    # q76 counts fact rows with NULL dimension keys; ~7% NULLs on exactly
    # the columns it scans (kept as masked int64 via pyarrow at write time)
    nulls = {
        "store_sales": ["ss_store_sk", "ss_addr_sk"],  # ss_addr_sk: q44
        "web_sales": ["ws_ship_customer_sk"],
        "catalog_sales": ["cs_ship_addr_sk"],
    }
    null_masks = {}
    for tbl, colnames in nulls.items():
        for cn in colnames:
            null_masks[(tbl, cn)] = rng.random(ROWS[tbl]) < 0.07
    return out, null_masks
