"""Correlated-subquery decorrelation (plan/decorrelate.py) against pandas
oracles: EXISTS / NOT EXISTS semi/anti marks (with residual non-equi
correlated predicates), correlated scalar subqueries via GROUP BY rewrite
(including the COUNT-over-empty-group bug), NULL correlation keys, and
composition with index rewriting. The reference gets all of this from Spark
Catalyst (RewritePredicateSubquery / RewriteCorrelatedScalarSubquery)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan.sql import SqlError


@pytest.fixture()
def orders_returns(session, tmp_path):
    """orders(ok, cust, amt, wh) and returns(rok, rcust, ramt); cust 0..9,
    some orders have NULL wh, customer 9 never returns anything."""
    rng = np.random.default_rng(7)
    n = 200
    wh = rng.integers(0, 4, n).astype(np.float64)
    wh[rng.random(n) < 0.15] = np.nan
    orders = pa.table(
        {
            "ok": np.arange(n, dtype=np.int64),
            "cust": rng.integers(0, 10, n).astype(np.int64),
            "amt": np.round(rng.uniform(0, 100, n), 2),
            "wh": wh,
        }
    )
    m = 80
    rcust = rng.integers(0, 9, m).astype(np.int64)  # customer 9 absent
    returns = pa.table(
        {
            "rok": rng.integers(0, n, m).astype(np.int64),
            "rcust": rcust,
            "ramt": np.round(rng.uniform(0, 50, m), 2),
        }
    )
    for name, t in (("orders", orders), ("returns", returns)):
        root = tmp_path / name
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view(name)
    return orders.to_pandas(), returns.to_pandas()


class TestExists:
    def test_exists_equi(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE EXISTS("
            "SELECT * FROM returns r WHERE o.cust = r.rcust)"
        ).collect()
        expect = od[od.cust.isin(rd.rcust.unique())].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_not_exists_anti(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE NOT EXISTS("
            "SELECT * FROM returns r WHERE o.cust = r.rcust)"
        ).collect()
        expect = od[~od.cust.isin(rd.rcust.unique())].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())
        assert len(got["ok"]) > 0  # customer 9 rows exist

    def test_exists_with_inner_predicate(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE EXISTS("
            "SELECT * FROM returns r WHERE o.cust = r.rcust AND r.ramt > 40)"
        ).collect()
        expect = od[od.cust.isin(rd[rd.ramt > 40].rcust.unique())].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_exists_residual_nonequi(self, session, orders_returns):
        # q16/q94 shape: same key, different attribute value elsewhere in the
        # group — self-join EXISTS with <> residual
        od, _ = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o1 WHERE EXISTS("
            "SELECT * FROM orders o2 WHERE o1.cust = o2.cust AND o1.wh <> o2.wh)"
        ).collect()
        m = od.merge(od, on="cust", suffixes=("", "_r"))
        keep = m[(m.wh != m.wh_r) & m.wh.notna() & m.wh_r.notna()]
        expect = keep.ok.unique()
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_or_of_two_exists(self, session, orders_returns):
        # q10/q35 shape: disjunction of independent EXISTS marks
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE "
            "EXISTS(SELECT * FROM returns r WHERE o.cust = r.rcust AND r.ramt > 45) OR "
            "EXISTS(SELECT * FROM returns r WHERE o.ok = r.rok AND r.ramt < 5)"
        ).collect()
        s1 = od.cust.isin(rd[rd.ramt > 45].rcust.unique())
        s2 = od.ok.isin(rd[rd.ramt < 5].rok.unique())
        expect = od[s1 | s2].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_exists_multi_key(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE EXISTS("
            "SELECT * FROM returns r WHERE o.cust = r.rcust AND o.ok = r.rok)"
        ).collect()
        keys = set(zip(rd.rcust, rd.rok))
        expect = od[[(c, k) in keys for c, k in zip(od.cust, od.ok)]].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_null_correlation_key_never_matches(self, session, orders_returns):
        od, _ = orders_returns
        # wh has NULLs; EXISTS keyed on wh must exclude NULL-wh outer rows
        got = session.sql(
            "SELECT ok FROM orders o1 WHERE EXISTS("
            "SELECT * FROM orders o2 WHERE o1.wh = o2.wh)"
        ).collect()
        expect = od[od.wh.notna()].ok  # every non-NULL wh matches itself
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_uncorrelated_exists(self, session, orders_returns):
        got = session.sql(
            "SELECT ok FROM orders WHERE EXISTS(SELECT * FROM returns WHERE ramt > 1000)"
        ).collect()
        assert len(got["ok"]) == 0
        got2 = session.sql(
            "SELECT ok FROM orders WHERE EXISTS(SELECT * FROM returns WHERE ramt >= 0)"
        ).collect()
        assert len(got2["ok"]) == 200


class TestCorrelatedScalar:
    def test_avg_per_group(self, session, orders_returns):
        od, _ = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o1 WHERE amt > "
            "(SELECT avg(amt) * 1.2 FROM orders o2 WHERE o1.cust = o2.cust)"
        ).collect()
        thr = od.groupby("cust").amt.mean() * 1.2
        expect = od[od.amt > od.cust.map(thr)].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_missing_group_yields_null_comparison_false(self, session, orders_returns):
        od, rd = orders_returns
        # customer 9 has no returns: threshold is NULL -> comparison unknown
        got = session.sql(
            "SELECT ok FROM orders o WHERE amt > "
            "(SELECT avg(ramt) FROM returns r WHERE o.cust = r.rcust)"
        ).collect()
        thr = rd.groupby("rcust").ramt.mean()
        mapped = od.cust.map(thr)
        expect = od[(od.amt > mapped) & mapped.notna()].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())
        assert not od[od.cust == 9].ok.isin(got["ok"]).any()

    def test_count_bug_empty_group_is_zero(self, session, orders_returns):
        od, rd = orders_returns
        # COUNT over an empty group is 0, not NULL: customer-9 orders DO
        # satisfy "= 0" (Spark/SQL semantics; the classic count-bug)
        got = session.sql(
            "SELECT ok FROM orders o WHERE "
            "(SELECT count(*) FROM returns r WHERE o.cust = r.rcust) = 0"
        ).collect()
        counts = rd.groupby("rcust").size()
        expect = od[od.cust.map(counts).fillna(0) == 0].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())
        assert od[od.cust == 9].ok.isin(got["ok"]).all()

    def test_scalar_in_arithmetic(self, session, orders_returns):
        od, _ = orders_returns
        # q32/q92 shape: literal * (SELECT avg ...) comparison
        got = session.sql(
            "SELECT ok FROM orders o1 WHERE amt > 1.3 * "
            "(SELECT avg(amt) FROM orders o2 WHERE o2.cust = o1.cust)"
        ).collect()
        thr = od.groupby("cust").amt.mean() * 1.3
        expect = od[od.amt > od.cust.map(thr)].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_correlated_conjunct_inside_or_factored(self, session, orders_returns):
        od, _ = orders_returns
        # q41 shape: correlation equality repeated in both OR branches
        got = session.sql(
            "SELECT ok FROM orders o1 WHERE (SELECT count(*) FROM orders o2 WHERE "
            "(o2.cust = o1.cust AND o2.amt > 90) OR (o2.cust = o1.cust AND o2.amt < 5)"
            ") > 0"
        ).collect()
        cnt = od[(od.amt > 90) | (od.amt < 5)].groupby("cust").size()
        expect = od[od.cust.map(cnt).fillna(0) > 0].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_correlated_without_aggregate_rejected(self, session, orders_returns):
        with pytest.raises(SqlError, match="must aggregate"):
            session.sql(
                "SELECT ok FROM orders o WHERE amt > "
                "(SELECT ramt FROM returns r WHERE o.cust = r.rcust)"
            ).collect()

    def test_correlated_in(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE ok IN "
            "(SELECT rok FROM returns r WHERE o.cust = r.rcust)"
        ).collect()
        keys = set(zip(rd.rcust, rd.rok))
        expect = od[[(c, k) in keys for c, k in zip(od.cust, od.ok)]].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())


class TestCorrelatedIn:
    """Three-valued correlated IN / NOT IN (Spark: null-aware semi/anti
    join) against a hand-computed oracle over a fixture with NULLs on both
    sides."""

    @pytest.fixture()
    def tn(self, session, tmp_path):
        t = pa.table(
            {
                "k": np.array([1, 1, 2, 2, 3], dtype=np.int64),
                "x": np.array([10.0, np.nan, 10.0, 99.0, 5.0]),
            }
        )
        u = pa.table(
            {
                "uk": np.array([1, 1, 2, 2], dtype=np.int64),
                "uv": np.array([10.0, 20.0, np.nan, 7.0]),
            }
        )
        for name, tab in (("t", t), ("u", u)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(tab, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
        return t.to_pandas(), u.to_pandas()

    def test_in_three_valued(self, session, tn):
        # row (1,10): S={10,20} -> TRUE
        # row (1,NULL): S nonempty -> UNKNOWN -> excluded
        # row (2,10): S={NULL,7}, no match but NULL in S -> UNKNOWN -> excluded
        # row (2,99): same -> UNKNOWN -> excluded
        # row (3,5): S empty -> FALSE -> excluded
        got = session.sql(
            "SELECT k, x FROM t WHERE x IN (SELECT uv FROM u WHERE t.k = u.uk)"
        ).collect()
        assert got["k"].tolist() == [1] and got["x"].tolist() == [10.0]

    def test_not_in_three_valued(self, session, tn):
        # NOT IN keeps only rows where IN is definitely FALSE:
        # row (3,5): S empty -> IN=FALSE -> NOT IN=TRUE (the only survivor);
        # unknowns (NULL x with nonempty S, NULL in S) stay excluded
        got = session.sql(
            "SELECT k, x FROM t WHERE NOT x IN (SELECT uv FROM u WHERE t.k = u.uk)"
        ).collect()
        assert got["k"].tolist() == [3] and got["x"].tolist() == [5.0]

    def test_null_outer_key_is_definite_false(self, session, tmp_path):
        t = pa.table({"k": np.array([1.0, np.nan]), "x": np.array([10.0, 10.0])})
        u = pa.table({"uk": np.array([1.0, 2.0]), "uv": np.array([10.0, 10.0])})
        for name, tab in (("t2", t), ("u2", u)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(tab, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
        # NULL correlation key -> empty S -> IN is FALSE -> NOT IN keeps it
        got = session.sql(
            "SELECT x FROM t2 WHERE NOT x IN (SELECT uv FROM u2 WHERE t2.k = u2.uk)"
        ).collect()
        assert len(got["x"]) == 1


class TestDecorrelationWithIndexes:
    def test_index_rewrite_inside_exists(self, session, tmp_path):
        """ApplyHyperspace recurses into the decorrelated inner plan: an
        index on the inner table's correlation column is used, and results
        stay identical with hyperspace on vs off."""
        hs = hst.Hyperspace(session)
        rng = np.random.default_rng(3)
        n = 4000
        f = pa.table(
            {
                "k": rng.integers(0, 400, n).astype(np.int64),
                "p": np.round(rng.uniform(0, 10, n), 2),
            }
        )
        g = pa.table(
            {
                "gk": rng.integers(0, 400, 500).astype(np.int64),
                "gv": np.round(rng.uniform(0, 10, 500), 2),
            }
        )
        for name, t in (("f", f), ("g", g)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
        hs.create_index(
            session._temp_views["g"], hst.CoveringIndexConfig("g_gv", ["gv"], ["gk"])
        )
        session.enable_hyperspace()
        q = session.sql(
            "SELECT k FROM f WHERE EXISTS(SELECT * FROM g WHERE f.k = g.gk AND g.gv > 5)"
        )
        plan = q.optimized_plan()
        from hyperspace_tpu.rules.apply import used_index_names

        assert "g_gv" in used_index_names(plan.plan if hasattr(plan, "plan") else plan)
        on = q.collect()
        session.disable_hyperspace()
        try:
            off = q.collect()
        finally:
            session.enable_hyperspace()
        assert sorted(on["k"].tolist()) == sorted(off["k"].tolist())
        assert len(on["k"]) > 0


class TestReviewRegressions:
    def test_compound_count_expression_defaults_to_its_zero_row_value(
        self, session, orders_returns
    ):
        od, rd = orders_returns
        # count(*) * 2 over an empty group is 0, not NULL: customer-9 orders
        # satisfy "< 1"
        got = session.sql(
            "SELECT ok FROM orders o WHERE "
            "(SELECT count(*) * 2 FROM returns r WHERE o.cust = r.rcust) < 1"
        ).collect()
        counts = rd.groupby("rcust").size() * 2
        expect = od[od.cust.map(counts).fillna(0) < 1].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())
        assert od[od.cust == 9].ok.isin(got["ok"]).all()

    def test_avg_wrapped_in_expression_still_null_on_empty(self, session, orders_returns):
        od, rd = orders_returns
        got = session.sql(
            "SELECT ok FROM orders o WHERE amt > "
            "(SELECT avg(ramt) + 0 FROM returns r WHERE o.cust = r.rcust)"
        ).collect()
        thr = rd.groupby("rcust").ramt.mean()
        mapped = od.cust.map(thr)
        expect = od[(od.amt > mapped) & mapped.notna()].ok
        assert sorted(got["ok"].tolist()) == sorted(expect.tolist())

    def test_correlated_scalar_in_having(self, session, orders_returns):
        od, rd = orders_returns
        # the HAVING pipeline rewrites bound trees: the new subquery nodes
        # must survive the generic transformer without losing outer keys
        got = session.sql(
            "SELECT cust, sum(amt) AS total FROM orders o GROUP BY cust "
            "HAVING sum(amt) > (SELECT sum(ramt) FROM returns r WHERE r.rcust = o.cust)"
        ).collect()
        t = od.groupby("cust", as_index=False).amt.sum()
        rt = rd.groupby("rcust").ramt.sum()
        mapped = t.cust.map(rt)
        expect = t[(t.amt > mapped) & mapped.notna()]
        assert sorted(got["cust"].tolist()) == sorted(expect.cust.tolist())

    def test_limit_in_correlated_in_rejected(self, session, orders_returns):
        with pytest.raises(SqlError, match="LIMIT"):
            session.sql(
                "SELECT ok FROM orders o WHERE ok IN "
                "(SELECT rok FROM returns r WHERE o.cust = r.rcust LIMIT 1)"
            ).collect()

    def test_aggregate_in_correlated_in_rejected(self, session, orders_returns):
        with pytest.raises(SqlError, match="[Aa]ggregate"):
            session.sql(
                "SELECT ok FROM orders o WHERE ok IN "
                "(SELECT max(rok) FROM returns r WHERE o.cust = r.rcust)"
            ).collect()


class TestScalarDatetime:
    def test_scalar_subquery_date_missing_group_is_nat(self, session, tmp_path):
        """A datetime-valued scalar subquery with empty groups must fill NaT,
        not cast the column to raw epoch floats (which silently corrupts any
        downstream date comparison)."""
        custs = np.array([0, 1, 2, 3], dtype=np.int64)
        orders = pa.table({"ok": np.arange(4, dtype=np.int64), "cust": custs})
        rdate = np.array(
            ["2024-01-05", "2024-03-01", "2024-02-11"], dtype="datetime64[ns]"
        )
        returns = pa.table(
            {
                "rcust": np.array([0, 0, 2], dtype=np.int64),  # cust 1 and 3 absent
                "rdate": rdate,
            }
        )
        for name, t in (("o2", orders), ("r2", returns)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
        got = session.sql(
            "SELECT ok, (SELECT max(r.rdate) FROM r2 r WHERE o.cust = r.rcust) AS md"
            " FROM o2 o"
        ).collect()
        vals = np.asarray(got["md"])
        assert np.issubdtype(vals.dtype, np.datetime64), vals.dtype
        by_ok = dict(zip(got["ok"], got["md"]))
        assert pd.Timestamp(by_ok[0]) == pd.Timestamp("2024-03-01")
        assert pd.Timestamp(by_ok[2]) == pd.Timestamp("2024-02-11")
        assert pd.isna(by_ok[1]) and pd.isna(by_ok[3])
        # and the NaT rows must not satisfy a date comparison
        got2 = session.sql(
            "SELECT ok FROM o2 o WHERE (SELECT max(r.rdate) FROM r2 r"
            " WHERE o.cust = r.rcust) > DATE '2024-02-01'"
        ).collect()
        assert sorted(got2["ok"].tolist()) == [0, 2]
