"""hscheck lock-order watcher: ABBA cycle detection across threads,
named_lock construction gating, and the violations metric."""

import threading

import pytest

from hyperspace_tpu.check.locks import WatchedLock, named_lock, watcher
from hyperspace_tpu.obs.metrics import REGISTRY

pytestmark = pytest.mark.check


@pytest.fixture()
def watching():
    watcher.enable()
    watcher.reset()
    yield watcher
    watcher.disable()
    watcher.reset()


def _run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class TestNamedLock:
    def test_plain_lock_when_disabled(self):
        assert not watcher.enabled
        lk = named_lock("x")
        assert type(lk) is type(threading.Lock())

    def test_watched_when_enabled(self, watching):
        lk = named_lock("x")
        assert isinstance(lk, WatchedLock)
        assert lk.name == "x"
        with lk:
            assert lk.locked()
        assert not lk.locked()


class TestCycles:
    def test_opposite_order_two_threads(self, watching):
        """The canonical ABBA hazard: thread 1 takes A then B, thread 2 takes
        B then A. Neither deadlocks here (sequential), but the held-before
        graph has the cycle."""
        a, b = WatchedLock("A"), WatchedLock("B")
        _run(lambda: [a.acquire(), b.acquire(), b.release(), a.release()])
        _run(lambda: [b.acquire(), a.acquire(), a.release(), b.release()])
        cycles = watching.cycles()
        assert cycles == [["A", "B"]]

    def test_consistent_order_is_clean(self, watching):
        a, b = WatchedLock("A"), WatchedLock("B")
        for _ in range(2):
            _run(lambda: [a.acquire(), b.acquire(), b.release(), a.release()])
        assert watching.edges() == {("A", "B"): 2}
        assert watching.cycles() == []

    def test_same_thread_nesting_is_not_a_cycle(self, watching):
        # one thread nesting A->B then A->B again: an edge, never a cycle
        a, b = WatchedLock("A"), WatchedLock("B")
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        assert watching.cycles() == []

    def test_three_lock_cycle(self, watching):
        a, b, c = WatchedLock("A"), WatchedLock("B"), WatchedLock("C")
        _run(lambda: [a.acquire(), b.acquire(), b.release(), a.release()])
        _run(lambda: [b.acquire(), c.acquire(), c.release(), b.release()])
        _run(lambda: [c.acquire(), a.acquire(), a.release(), c.release()])
        assert watching.cycles() == [["A", "B", "C"]]

    def test_report_bumps_metric(self, watching):
        a, b = WatchedLock("mA"), WatchedLock("mB")
        _run(lambda: [a.acquire(), b.acquire(), b.release(), a.release()])
        _run(lambda: [b.acquire(), a.acquire(), a.release(), b.release()])
        program = "mA -> mB -> mA"
        before = REGISTRY.counter(
            "hs_check_violations_total", rule="lock-order-cycle", program=program
        ).value
        cycles = watching.report()
        assert cycles == [["mA", "mB"]]
        after = REGISTRY.counter(
            "hs_check_violations_total", rule="lock-order-cycle", program=program
        ).value
        assert after == before + 1

    def test_reset_clears_graph(self, watching):
        a, b = WatchedLock("A"), WatchedLock("B")
        with a:
            with b:
                pass
        assert watching.edges()
        watching.reset()
        assert watching.edges() == {}


class TestServingLocksUnderWatch:
    def test_serving_caches_construct_watched(self, watching):
        """Serving modules built while the watcher is on get WatchedLocks
        (construction-time gating), and their normal operations record into
        an acyclic graph."""
        from hyperspace_tpu.serving.plan_cache import PlanCache
        from hyperspace_tpu.serving.result_cache import ResultCache

        pc = PlanCache(max_entries=8)
        rc = ResultCache()
        assert isinstance(pc._lock, WatchedLock)
        assert isinstance(rc._lock, WatchedLock)
        pc.stats()
        pc.clear()
        rc.stats()
        rc.invalidate_all()
        assert watching.cycles() == []

    def test_modules_built_before_enable_stay_plain(self):
        from hyperspace_tpu.serving.plan_cache import PlanCache

        assert not watcher.enabled
        pc = PlanCache(max_entries=8)
        assert type(pc._lock) is type(threading.Lock())
