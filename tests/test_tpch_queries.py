"""TPC-H q1-q22 through the SQL front-end (tests/tpch_queries.py holds the
standard texts). Same harness shape as the TPC-DS suite: every query plans,
holds an approved plan (regen with HS_GENERATE_GOLDEN=1), and returns
identical results with hyperspace on vs off over the full 8-table schema
with covering indexes on the hot keys. The driver's BASELINE configs are
TPC-H-shaped, so this is the benchmark family's correctness floor;
tests/test_tpch_oracles.py adds absolute-correctness pandas oracles for ten
of the texts on top of this parity."""

import os
import zlib

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from tpch_queries import TPCH_QUERIES

APPROVED_DIR = os.path.join(os.path.dirname(__file__), "approved_plans", "tpch_sql")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"

# column name -> generator kind; I=key int, F=money, S=string-ish, D=date
TPCH_SCHEMAS = {
    "region": {"r_regionkey": "I", "r_name": "RN", "r_comment": "S"},
    "nation": {"n_nationkey": "I", "n_name": "NN", "n_regionkey": "RK", "n_comment": "S"},
    "supplier": {
        "s_suppkey": "I", "s_name": "S", "s_address": "S", "s_nationkey": "NK",
        "s_phone": "PH", "s_acctbal": "F", "s_comment": "S",
    },
    "customer": {
        "c_custkey": "I", "c_name": "S", "c_address": "S", "c_nationkey": "NK",
        "c_phone": "PH", "c_acctbal": "F", "c_mktsegment": "SEG", "c_comment": "S",
    },
    "part": {
        "p_partkey": "I", "p_name": "PN", "p_mfgr": "S", "p_brand": "BR",
        "p_type": "PT", "p_size": "SZ", "p_container": "CT", "p_retailprice": "F",
        "p_comment": "S",
    },
    "partsupp": {
        "ps_partkey": "I", "ps_suppkey": "I", "ps_availqty": "Q",
        "ps_supplycost": "F", "ps_comment": "S",
    },
    "orders": {
        "o_orderkey": "I", "o_custkey": "I", "o_orderstatus": "ST",
        "o_totalprice": "F", "o_orderdate": "D", "o_orderpriority": "PR",
        "o_clerk": "S", "o_shippriority": "SZ", "o_comment": "SC",
    },
    "lineitem": {
        "l_orderkey": "I", "l_partkey": "I", "l_suppkey": "I", "l_linenumber": "SZ",
        "l_quantity": "Q", "l_extendedprice": "F", "l_discount": "DISC", "l_tax": "DISC",
        "l_returnflag": "RF", "l_linestatus": "LS", "l_shipdate": "D",
        "l_commitdate": "D", "l_receiptdate": "D", "l_shipinstruct": "SI",
        "l_shipmode": "SM", "l_comment": "S",
    },
}

_NATIONS = ["FRANCE", "GERMANY", "BRAZIL", "CANADA", "ASIAN1", "ASIAN2"]
_REGIONS = ["EUROPE", "AMERICA", "ASIA"]


# foreign-key domains: values must land inside the referenced table's key
# range or joins go mostly dangling and queries vacuously return 0 rows
_FK_DOMAIN = {
    "l_orderkey": "orders",
    "l_partkey": "part",
    "l_suppkey": "supplier",
    "ps_partkey": "part",
    "ps_suppkey": "supplier",
    "o_custkey": "customer",
}


def _gen(cname, kind, n, rng):
    if kind == "I":
        dom = _ROWS.get(_FK_DOMAIN.get(cname, ""), n)
        return rng.integers(0, dom, n).astype(np.int64)
    if kind == "F":
        return np.round(rng.uniform(0, 2000, n), 2)
    if kind == "Q":
        return rng.integers(1, 60, n).astype(np.int64)
    if kind == "DISC":
        return np.round(rng.integers(0, 11, n) / 100.0, 2)
    if kind == "D":
        return np.datetime64("1992-01-01") + rng.integers(0, 2500, n).astype("timedelta64[D]")
    if kind == "SZ":
        # include q2's p_size = 15 and q19's BETWEEN windows deterministically
        return np.array([[1, 5, 15, 23, 36, 45, 9, 14][i % 8] for i in range(n)], dtype=np.int64)
    if kind == "RN":
        return np.array([_REGIONS[i % len(_REGIONS)] for i in range(n)], dtype=object)
    if kind == "NN":
        return np.array([_NATIONS[i % len(_NATIONS)] for i in range(n)], dtype=object)
    if kind == "RK":
        # nation i belongs to region: FRANCE/GERMANY->EUROPE(0),
        # BRAZIL/CANADA->AMERICA(1), ASIAN*->ASIA(2); region keys are 0..2
        # because the region fixture is built with r_regionkey = iota below
        return np.array([[0, 0, 1, 1, 2, 2][i % 6] for i in range(n)], dtype=np.int64)
    if kind == "NK":
        # deterministic spread so q5's c_nationkey = s_nationkey chains hit
        return np.array([i % 6 for i in range(n)], dtype=np.int64)
    if kind == "PH":
        return np.array([f"{13 + (i % 20)}-{i % 997:03d}-55" for i in range(n)], dtype=object)
    if kind == "SEG":
        segs = ["BUILDING", "AUTOMOBILE", "MACHINERY"]
        return np.array([segs[i % 3] for i in range(n)], dtype=object)
    if kind == "PN":
        words = ["forest", "green", "lavender", "blue"]
        return np.array([f"{words[i % 4]} part {i}" for i in range(n)], dtype=object)
    if kind == "BR":
        return np.array([f"Brand#{[12, 23, 34, 45][i % 4]}" for i in range(n)], dtype=object)
    if kind == "PT":
        kinds = ["ECONOMY ANODIZED STEEL", "MEDIUM POLISHED BRASS", "SMALL BRASS", "PROMO STEEL"]
        return np.array([kinds[i % 4] for i in range(n)], dtype=object)
    if kind == "CT":
        cts = ["SM CASE", "MED BOX", "LG PACK", "JUMBO JAR"]
        return np.array([cts[i % 4] for i in range(n)], dtype=object)
    if kind == "ST":
        return np.array([["F", "O", "P"][i % 3] for i in range(n)], dtype=object)
    if kind == "PR":
        return np.array([["1-URGENT", "2-HIGH", "3-MEDIUM"][i % 3] for i in range(n)], dtype=object)
    if kind == "RF":
        return np.array([["R", "A", "N"][i % 3] for i in range(n)], dtype=object)
    if kind == "LS":
        return np.array([["O", "F"][i % 2] for i in range(n)], dtype=object)
    if kind == "SI":
        return np.array(
            [["DELIVER IN PERSON", "COLLECT COD"][i % 2] for i in range(n)], dtype=object
        )
    if kind == "SM":
        return np.array([["AIR", "MAIL", "SHIP", "AIR REG"][i % 4] for i in range(n)], dtype=object)
    if kind == "SC":
        return np.array(
            [("special requests" if i % 9 == 0 else f"note {i}") for i in range(n)], dtype=object
        )
    return np.array([f"{cname[:5]}_{i % 37}" for i in range(n)], dtype=object)


# Covering indexes wide enough that the rules actually fire on the standard
# query texts (a join index must cover every column its side needs,
# ref: JoinIndexRule.scala:419-448 — the dispatch goldens prove which of the
# 22 queries rewrite and which physical path each one takes)
INDEXES = [
    ("lineitem", "li_ok", ["l_orderkey"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_tax", "l_shipdate",
      "l_commitdate", "l_receiptdate", "l_shipmode", "l_returnflag",
      "l_linestatus", "l_suppkey", "l_partkey"]),
    ("lineitem", "li_sd", ["l_shipdate"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_orderkey"]),
    ("lineitem", "li_pk", ["l_partkey"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_shipdate",
      "l_shipmode", "l_shipinstruct"]),
    ("orders", "o_ok", ["o_orderkey"],
     ["o_custkey", "o_orderdate", "o_totalprice", "o_orderpriority",
      "o_orderstatus", "o_shippriority"]),
    ("orders", "o_ck", ["o_custkey"],
     ["o_orderkey", "o_orderdate", "o_totalprice", "o_shippriority",
      "o_comment"]),
    ("customer", "c_ck", ["c_custkey"],
     ["c_name", "c_acctbal", "c_mktsegment", "c_nationkey", "c_phone",
      "c_address", "c_comment"]),
    ("part", "p_pk", ["p_partkey"],
     ["p_name", "p_mfgr", "p_brand", "p_type", "p_size", "p_container",
      "p_retailprice"]),
    ("supplier", "s_sk", ["s_suppkey"],
     ["s_name", "s_address", "s_nationkey", "s_phone", "s_acctbal",
      "s_comment"]),
    ("partsupp", "ps_pk", ["ps_partkey"],
     ["ps_suppkey", "ps_availqty", "ps_supplycost"]),
]

_ROWS = {"region": 3, "nation": 6, "supplier": 40, "customer": 60, "part": 80,
         "partsupp": 300, "orders": 600, "lineitem": 2400}


def _shape_table(name, cols, n, rng):
    """Post-shape the generated columns so every query family has rows to
    chew on: a few heavy orders (q18's sum(l_quantity) > 300), commit and
    receipt dates derived from the ship date with ~20% lateness (q4/q12/q21
    depend on their ordering, which independent random dates destroy), and
    some orderless customers (q22's NOT EXISTS)."""
    if name == "lineitem":
        heavy = n // 6
        cols["l_orderkey"][:heavy] = rng.integers(0, 20, heavy)
        # ship dates dense over 1993-1996 so the year-window predicates
        # (q4/q6/q12/q14/q15/q20) each see a real slice of the data
        cols["l_shipdate"] = np.datetime64("1993-01-01") + rng.integers(
            0, 1460, n
        ).astype("timedelta64[D]")
        ship = cols["l_shipdate"]
        commit = ship + rng.integers(7, 30, n).astype("timedelta64[D]")
        late = rng.random(n) < 0.2
        receipt = commit + np.where(
            late, rng.integers(1, 6, n), rng.integers(-5, 1, n)
        ).astype("timedelta64[D]")
        cols["l_commitdate"] = commit
        cols["l_receiptdate"] = receipt
    if name == "orders":
        cols["o_custkey"] = rng.integers(0, int(_ROWS["customer"] * 0.85), n).astype(np.int64)
    if name == "customer":
        # the orderless customers (keys above the o_custkey domain) carry
        # above-average balances so q22's NOT EXISTS branch yields rows
        lo = int(_ROWS["customer"] * 0.85)
        cols["c_acctbal"][lo:] = cols["c_acctbal"][lo:] + 1500.0


def build_tpch_env(root):
    """Shared fixture builder: the gold-standard parity suite and the oracle
    suite (test_tpch_oracles.py) MUST test the same shaped data and index
    roster. Returns (session, {table -> pandas frame})."""
    import pandas as pd

    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    frames = {}
    for name, schema in TPCH_SCHEMAS.items():
        rng = np.random.default_rng(zlib.crc32(name.encode()))
        n = _ROWS[name]
        cols = {c: _gen(c, k, n, rng) for c, k in schema.items()}
        if name in ("region", "nation", "supplier", "customer", "part", "orders"):
            key = list(schema)[0]
            cols[key] = np.arange(n, dtype=np.int64)  # unique primary keys
        _shape_table(name, cols, n, rng)
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(pa.table(cols), os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
        frames[name] = pd.DataFrame(cols)
    for table, idx_name, indexed, included in INDEXES:
        hs.create_index(
            sess._temp_views[table], hst.CoveringIndexConfig(idx_name, indexed, included)
        )
    sess.enable_hyperspace()
    return sess, frames


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_sql"))
    sess, _frames = build_tpch_env(root)
    yield sess, root
    hst.set_session(None)


def _normalize(text, root):
    return text.replace(root, "<TPCH>")


def _rows(batch):
    def norm(v):
        if v is None:
            return "\x00NULL"
        if isinstance(v, float):
            if v != v:
                return "NaN"
            return f"{v:.6g}"
        return str(v)

    cols = sorted(batch.keys())
    if not cols:
        return []
    return sorted(
        tuple(norm(v) for v in row) for row in zip(*[batch[k].tolist() for k in cols])
    )


@pytest.mark.parametrize("qname", sorted(TPCH_QUERIES, key=lambda s: int(s[1:])))
def test_query_plans_and_answers(tpch, qname):
    sess, root = tpch
    q = sess.sql(TPCH_QUERIES[qname])

    plan_text = _normalize(q.optimized_plan().pretty(), root)
    path = os.path.join(APPROVED_DIR, f"{qname}.txt")
    if GENERATE:
        os.makedirs(APPROVED_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(plan_text)
    else:
        with open(path) as f:
            assert plan_text == f.read(), (
                f"plan for {qname} changed; review and regen with HS_GENERATE_GOLDEN=1"
            )

    on = q.collect()
    sess.disable_hyperspace()
    try:
        off = q.collect()
    finally:
        sess.enable_hyperspace()
    assert sorted(on.keys()) == sorted(off.keys()), qname
    assert _rows(on) == _rows(off), f"{qname}: results differ with hyperspace on vs off"
    # the fixture is shaped so NO query is vacuous — an empty result would
    # make the on/off parity assertion meaningless
    n_rows = len(next(iter(on.values()))) if on else 0
    assert n_rows > 0, f"{qname} returned no rows; fixture degraded"

    # physical-dispatch golden (ref: PlanStabilitySuite approves the
    # *executedPlan*, scala:83-290): record which path every operator took
    # with the device gate open, so silently falling off the device/native
    # fast paths fails the test, not just slows the query
    from hyperspace_tpu.exec import device as D
    from hyperspace_tpu.exec import io as hs_io
    from hyperspace_tpu.exec import trace

    hs_io.clear_io_cache()
    D.clear_device_cache()
    sess.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    try:
        with trace.recording() as events:
            q.collect()
    finally:
        sess.conf.unset(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS)
    dispatch = trace.summarize(events)
    dpath = os.path.join(APPROVED_DIR, f"{qname}.dispatch.txt")
    if GENERATE:
        with open(dpath, "w") as f:
            f.write(dispatch)
    else:
        with open(dpath) as f:
            assert dispatch == f.read(), (
                f"physical dispatch for {qname} changed; review and regen "
                "with HS_GENERATE_GOLDEN=1"
            )


def test_all_22_covered():
    assert len(TPCH_QUERIES) == 22
