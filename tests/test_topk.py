"""Streaming device top-k / order-aware planning (ORDER BY/LIMIT).

Pinned properties:
- streamed device top-k ≡ materialized host sort+slice ≡ pandas oracle,
  byte-identical, across asc/desc, multi-key, NULLs (LAST both directions),
  NaN floats, string keys with None, and ties (stable, input order);
- geometric candidate capacities keep hs_xla_compiles_total flat across
  chunk-size sweeps once the shape buckets are warm;
- the sharded (shard_map + one all_gather) path is byte-identical to the
  single-device path;
- ORDER BY covered by a covering index's within-bucket sort order eliminates
  the Sort into a streamed merge of sorted runs (dispatch proven by trace
  goldens; refusals explained in EXPLAIN WHY NOT);
- the running k-th-value threshold feeds row-group pruning (counters prove
  skipped groups) without changing results;
- a bare LIMIT stops decoding early and cancels queued prefetch decodes.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs.metrics import REGISTRY

pytestmark = pytest.mark.topk


def _write_files(d, num_files=6, rows_per=800, seed=7):
    """Multi-file dataset with every ordering hazard: NaN floats, None
    strings, low-cardinality tie keys, and a pruning-friendly int column."""
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        k = rng.integers(0, 10_000, rows_per).astype(np.int64)
        v = np.round(rng.uniform(-100, 100, rows_per), 3)
        v[rng.choice(rows_per, 20, replace=False)] = np.nan
        name = np.array([f"name_{j % 31:02d}" for j in range(rows_per)], dtype=object)
        name[rng.choice(rows_per, 15, replace=False)] = None
        grp = rng.integers(0, 5, rows_per).astype(np.int64)
        t = pa.table({"k": k, "v": v, "name": name, "grp": grp})
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def _mk_session(tmp_path, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
        hst.keys.NUM_BUCKETS: 8,
        hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
    }
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


def _oracle(data, keys, ascending, n):
    """The semantics contract: pandas stable sort, NULLS LAST both ways."""
    pdf = pd.DataFrame(dict(data))
    out = pdf.sort_values(list(keys), ascending=list(ascending), kind="stable", na_position="last")
    return out.head(n)


def _assert_batch_equals_frame(got, frame):
    assert set(got) == set(frame.columns)
    for c in frame.columns:
        np.testing.assert_array_equal(
            np.asarray(got[c]), frame[c].to_numpy(), err_msg=c
        )


CASES = [
    (("k",), (True,)),
    (("k",), (False,)),
    (("v",), (False,)),  # NaN floats, descending
    (("v", "k"), (False, True)),  # mixed directions, float primary
    (("name", "k"), (True, True)),  # string primary with None
    (("name", "v"), (False, True)),  # string descending + float tiebreak
]


class TestTopkVsOracle:
    @pytest.mark.parametrize("keys,asc", CASES, ids=["-".join(k) + str(a) for k, a in CASES])
    def test_streamed_device_topk_byte_identical(self, tmp_path, keys, asc):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.order_by(*keys, ascending=list(asc)).limit(25)
        with trace.recording() as events:
            got = q.collect()
        assert ("topk", "device-topk-stream") in events
        # host path: same query with the top-k fold disabled
        sess.conf.set(hst.keys.EXEC_TOPK_ENABLED, False)
        host = q.collect()
        for c in host:
            np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(host[c]), err_msg=c)
        # pandas oracle over the full materialized scan
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, keys, asc, 25))

    def test_stable_ties_match_input_order(self, tmp_path):
        """grp has 5 values over 4800 rows: LIMIT spans many full tie groups;
        the device rid plane must reproduce the stable host order exactly."""
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.order_by("grp").limit(1200)
        with trace.recording() as events:
            got = q.collect()
        assert ("topk", "device-topk-stream") in events
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("grp",), (True,), 1200))

    def test_limit_larger_than_rows(self, tmp_path):
        data = _write_files(str(tmp_path / "data"), num_files=2, rows_per=100)
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        got = df.order_by("k").limit(3000).collect()
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("k",), (True,), 3000))
        assert len(got["k"]) == 200


class TestHostOrderPin:
    """The host Sort semantics the device path must reproduce: NULLS LAST in
    BOTH directions, ties stable in input order (pandas parity)."""

    @pytest.mark.parametrize("asc", [True, False])
    def test_full_sort_nulls_last_stable(self, tmp_path, asc):
        data = _write_files(str(tmp_path / "data"), num_files=2, rows_per=400)
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_TOPK_ENABLED: False})
        df = sess.read_parquet(data)
        got = df.order_by("v", ascending=[asc]).collect()
        raw = df.collect()
        want = _oracle(raw, ("v",), (asc,), len(raw["v"]))
        _assert_batch_equals_frame(got, want)
        # NULLS LAST: the trailing rows are exactly the NaN rows
        n_nan = int(np.isnan(raw["v"]).sum())
        assert n_nan > 0 and np.isnan(np.asarray(got["v"][-n_nan:])).all()

    @pytest.mark.parametrize("asc", [True, False])
    def test_string_none_last(self, tmp_path, asc):
        data = _write_files(str(tmp_path / "data"), num_files=2, rows_per=400)
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_TOPK_ENABLED: False})
        df = sess.read_parquet(data)
        got = df.order_by("name", ascending=[asc]).collect()
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("name",), (asc,), len(raw["name"])))
        n_none = sum(x is None for x in raw["name"])
        assert n_none > 0
        assert all(x is None for x in list(got["name"])[-n_none:])


class TestCompileFlatness:
    def test_chunk_size_sweep_mints_no_new_programs(self, tmp_path):
        """The plane-matrix program is keyed on (key count, capacity, shape
        bucket): once a sweep has warmed the buckets, re-running the sweep —
        and any limit that maps to the same capacity bucket — compiles
        nothing new."""
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        compiles = REGISTRY.counter("hs_xla_compiles_total", "")
        sweep = [1, 40_000, 10_000_000]  # files-per-chunk: 1, a few, all-in-one gate
        for nbytes in sweep:
            sess.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, nbytes)
            df.order_by("v", "k", ascending=[False, True]).limit(30).collect()
        warm = compiles.value
        for nbytes in sweep:
            sess.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, nbytes)
            df.order_by("v", "k", ascending=[False, True]).limit(30).collect()
            # a different k in the same geometric capacity bucket reuses too
            sess_got = df.order_by("v", "k", ascending=[False, True]).limit(21).collect()
            assert len(sess_got["k"]) == 21
        assert compiles.value == warm


class TestShardedTopk:
    def test_sharded_matches_single_device(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(
            tmp_path,
            **{hst.keys.PARALLEL_ENABLED: True, hst.keys.PARALLEL_MIN_ROWS: 1},
        )
        df = sess.read_parquet(data)
        q = df.order_by("v", "k", ascending=[False, True]).limit(40)
        with trace.recording() as events:
            sharded = q.collect()
        assert ("topk", "device-topk-stream-sharded") in events
        sess.conf.set(hst.keys.PARALLEL_ENABLED, False)
        with trace.recording() as events:
            single = q.collect()
        assert ("topk", "device-topk-stream") in events
        for c in single:
            np.testing.assert_array_equal(
                np.asarray(sharded[c]), np.asarray(single[c]), err_msg=c
            )


class TestSortElimination:
    def _indexed(self, tmp_path, sess):
        data = _write_files(str(tmp_path / "data"))
        df = sess.read_parquet(data)
        hs = hst.Hyperspace(sess)
        hs.create_index(df, hst.CoveringIndexConfig("ordIdx", ["k"], ["v", "grp"]))
        sess.enable_hyperspace()
        return df, hs

    def test_covered_order_streams_as_run_merge(self, tmp_path):
        sess = _mk_session(tmp_path)
        df, _ = self._indexed(tmp_path, sess)
        q = df.filter(hst.col("k") > 50).select("k", "v").order_by("k")
        with trace.recording() as events:
            got = q.collect()
        # dispatch golden: the Sort was eliminated, not executed
        assert trace.summarize(events).splitlines().count("sort: index-order-merge x1") == 1
        sess.disable_hyperspace()
        want = q.collect()
        for c in want:
            np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(want[c]), err_msg=c)

    def test_covered_order_with_limit(self, tmp_path):
        sess = _mk_session(tmp_path)
        df, _ = self._indexed(tmp_path, sess)
        q = df.filter(hst.col("k") > 50).select("k", "v").order_by("k").limit(17)
        with trace.recording() as events:
            got = q.collect()
        assert any(d.startswith("index-order-merge-limit") for kk, d in events if kk == "sort")
        sess.disable_hyperspace()
        want = q.collect()
        for c in want:
            np.testing.assert_array_equal(np.asarray(got[c]), np.asarray(want[c]), err_msg=c)

    def test_descending_refusal_reason_and_why_not(self, tmp_path):
        sess = _mk_session(tmp_path)
        df, hs = self._indexed(tmp_path, sess)
        q = df.filter(hst.col("k") > 50).select("k", "v").order_by("k", ascending=[False])
        with trace.recording() as events:
            q.collect()
        reasons = [d for kk, d in events if kk == "sort" and d.startswith("merge-why-not")]
        assert reasons and "cannot ride the ascending index order" in reasons[0]
        text = hs.why_not(q, "ordIdx")
        assert "Sort elimination:" in text
        assert "cannot ride the ascending index order" in text

    def test_eliminated_sort_reported_in_why_not(self, tmp_path):
        sess = _mk_session(tmp_path)
        df, hs = self._indexed(tmp_path, sess)
        q = df.filter(hst.col("k") > 50).select("k", "v").order_by("k")
        text = hs.why_not(q, "ordIdx")
        assert "Sort elimination:" in text
        assert "eliminated — streamed merge of sorted index runs" in text


class TestDynamicThresholdPruning:
    def test_threshold_skips_rowgroups_without_changing_results(self, tmp_path):
        """Files carry disjoint sorted k ranges: after the first chunk the
        k-th candidate's value proves every later row group useless."""
        d = str(tmp_path / "data")
        os.makedirs(d)
        for i in range(6):
            k = np.arange(i * 1000, (i + 1) * 1000, dtype=np.int64)
            t = pa.table({"k": k, "v": k.astype(np.float64) / 3})
            pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"), row_group_size=250)
        # serial decode: with prefetch on, a few chunks decode before the
        # first threshold lands, which blurs the skipped-row-group count
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_PIPELINE_ENABLED: False})
        df = sess.read_parquet(d)
        q = df.order_by("k").limit(10)
        updates = REGISTRY.counter("hs_topk_threshold_updates_total", "")
        skipped = REGISTRY.counter("hs_rowgroups_skipped_total", "")
        u0, s0 = updates.value, skipped.value
        with trace.recording() as events:
            got = q.collect()
        assert ("topk", "device-topk-stream") in events
        assert updates.value > u0
        # after file 0 the threshold is k<=9: every row group of the other
        # 5 files (4 each) is provably above it
        assert skipped.value - s0 >= 20
        np.testing.assert_array_equal(np.asarray(got["k"]), np.arange(10, dtype=np.int64))

    def test_pushdown_disabled_still_correct(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_TOPK_THRESHOLD_PUSHDOWN: False})
        df = sess.read_parquet(data)
        got = df.order_by("k").limit(12).collect()
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("k",), (True,), 12))


class TestEarlyLimit:
    def test_bare_limit_stops_decoding_and_cancels_queued(self, tmp_path):
        """A bare LIMIT satisfied by the first chunks must not decode the
        rest of the dataset, and closing the pipeline must CANCEL queued
        decode futures (not drain them)."""
        import hyperspace_tpu.exec.io as hio

        data = _write_files(str(tmp_path / "data"), num_files=10, rows_per=500)
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_PIPELINE_DEPTH: 10})
        df = sess.read_parquet(data)

        calls = []
        lock = threading.Lock()
        real = hio.read_parquet_batch

        def slow(files, columns, predicate=None):
            with lock:
                calls.append(list(files))
            time.sleep(0.15)  # keep later futures queued behind the pool
            return real(files, columns, predicate=predicate)

        cancelled = REGISTRY.counter("hs_pipeline_cancelled_total", "")
        c0 = cancelled.value
        orig = hio.read_parquet_batch
        hio.read_parquet_batch = slow
        try:
            with trace.recording() as events:
                chunks = list(df.limit(700).to_local_iterator())
        finally:
            hio.read_parquet_batch = orig
        assert ("limit", "early-stop-stream") in events
        assert sum(len(b["k"]) for b in chunks) == 700
        # 2 files satisfy the limit; the 4-wide pool may start a few more,
        # but the tail must never decode
        assert len(calls) < 10
        assert cancelled.value > c0

    def test_streamed_limit_rows_match_materialized_prefix(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        chunks = list(df.limit(1500).to_local_iterator())
        got = {c: np.concatenate([np.asarray(b[c]) for b in chunks]) for c in chunks[0]}
        raw = df.collect()
        for c in raw:
            np.testing.assert_array_equal(
                np.asarray(got[c]), np.asarray(raw[c])[:1500], err_msg=c
            )
        # collect() of the same plan agrees
        coll = df.limit(1500).collect()
        for c in raw:
            np.testing.assert_array_equal(np.asarray(coll[c]), np.asarray(got[c]), err_msg=c)


class TestGates:
    def test_disabled_falls_back_to_host_sort(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_TOPK_ENABLED: False})
        df = sess.read_parquet(data)
        with trace.recording() as events:
            got = df.order_by("k").limit(9).collect()
        assert not any(kk == "topk" for kk, _ in events)
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("k",), (True,), 9))

    def test_limit_above_max_k_falls_back(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_TOPK_MAX_K: 8})
        df = sess.read_parquet(data)
        with trace.recording() as events:
            got = df.order_by("k").limit(50).collect()
        assert ("topk", "device-topk-stream") not in events
        raw = df.collect()
        _assert_batch_equals_frame(got, _oracle(raw, ("k",), (True,), 50))


class TestServingBatcherTopk:
    def test_shared_scan_applies_topk_cap(self, session, tmp_path):
        from hyperspace_tpu.serving.batcher import execute_shared_scan, shared_scan_ops

        rng = np.random.default_rng(5)
        n = 2000
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 500, n).astype(np.int64),
                    "v": rng.standard_normal(n),
                }
            ),
            tmp_path / "t.parquet",
        )
        session.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
        sql = "SELECT k, v FROM t WHERE k > {lo} ORDER BY k, v LIMIT 20"
        template = session.sql(sql.format(lo=100)).plan
        got = shared_scan_ops(template)
        assert got is not None
        ops, leaf = got
        assert ops and ops[0][0] == "topk"
        bound = [session.sql(sql.format(lo=lo)).plan for lo in (100, 5, 400)]
        batches = execute_shared_scan(session, ops, leaf, bound)
        for lo, gotb in zip((100, 5, 400), batches):
            want = session.sql(sql.format(lo=lo)).collect()
            for c in want:
                np.testing.assert_array_equal(
                    np.asarray(gotb[c]), np.asarray(want[c]), err_msg=f"{lo}:{c}"
                )
