"""hscheck HLO contract engine: regex edge cases, budget verification,
forbidden-op patterns, the maybe_verify runtime hook, and an end-to-end run
with ``hyperspace.check.hlo.enabled`` on."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.check import hlo_lint
from hyperspace_tpu.check.hlo_lint import (
    assert_contract,
    collective_counts,
    hlo_text_of,
    maybe_verify,
    register_contract,
    reset_runtime_state,
    runtime_violations,
    set_default_enabled,
    verify_hlo,
)
from hyperspace_tpu.exec import device as _device  # noqa: F401  (registers exec contracts)
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.plan.expr import col

pytestmark = pytest.mark.check


class TestCollectiveCounts:
    def test_plain_instruction(self):
        txt = "  %ag.3 = f32[64]{0} all-gather(f32[8]{0} %p0), dimensions={0}\n"
        assert collective_counts(txt)["all-gather"] == 1

    def test_async_pair_counts_once(self):
        txt = (
            "  %s = (f32[8], f32[64]) all-gather-start(f32[8] %p0)\n"
            "  %d = f32[64] all-gather-done((f32[8], f32[64]) %s)\n"
        )
        got = collective_counts(txt)
        assert got["all-gather"] == 1

    def test_numbered_suffix(self):
        txt = "  %r = f32[] all-reduce.7(f32[] %x), to_apply=%add\n"
        assert collective_counts(txt)["all-reduce"] == 1

    def test_tuple_result_type(self):
        # a tuple result puts a ')' right before the op name — the leading
        # character class must accept it
        txt = "  %a2a = (s32[4], s32[4]) all-to-all(s32[4] %a, s32[4] %b)\n"
        assert collective_counts(txt)["all-to-all"] == 1

    def test_operand_mention_not_counted(self):
        # the op name appearing as an OPERAND (no following paren) is not an
        # application site
        txt = "  %gte = f32[64] get-tuple-element((f32[8], f32[64]) %all-to-all.1), index=1\n"
        assert collective_counts(txt)["all-to-all"] == 0

    def test_metadata_op_names_not_counted(self):
        # metadata uses underscores; dashes only appear at real HLO call sites
        txt = '  %x = f32[8] add(f32[8] %a, f32[8] %b), metadata={op_name="all_to_all"}\n'
        assert all(v == 0 for v in collective_counts(txt).values())


def _hlo(*ops):
    return "".join(f"  %v{i} = f32[8] {op}(f32[8] %p{i})\n" for i, op in enumerate(ops))


@pytest.fixture()
def scratch_contract():
    """A throwaway family: exactly one all-to-all, any number of all-reduce."""
    name = "hscheck-test-family"
    register_contract(
        name,
        {"all-to-all": (1, 1), "all-reduce": (0, None)},
        description="test fixture",
    )
    yield name
    hlo_lint._CONTRACTS.pop(name, None)


class TestVerifyHlo:
    def test_unknown_family_raises(self):
        with pytest.raises(KeyError, match="no contract registered"):
            verify_hlo("never-registered", "")

    def test_conformant(self, scratch_contract):
        txt = _hlo("all-to-all", "all-reduce", "all-reduce")
        assert verify_hlo(scratch_contract, txt) == []
        assert_contract(scratch_contract, txt)  # must not raise

    def test_below_minimum(self, scratch_contract):
        found = verify_hlo(scratch_contract, _hlo("all-reduce"))
        assert [f.rule for f in found] == ["collective-budget:all-to-all"]
        assert "exactly 1" in found[0].message

    def test_above_maximum(self, scratch_contract):
        found = verify_hlo(scratch_contract, _hlo("all-to-all", "all-to-all"))
        assert [f.rule for f in found] == ["collective-budget:all-to-all"]

    def test_unlisted_op_forbidden(self, scratch_contract):
        # a contract says everything it permits: all-gather isn't in the
        # budget, so one occurrence is a violation
        found = verify_hlo(scratch_contract, _hlo("all-to-all", "all-gather"))
        assert [f.rule for f in found] == ["collective-budget:all-gather"]

    def test_program_label(self, scratch_contract):
        found = verify_hlo(scratch_contract, "", program="my-key")
        assert found[0].path == "hlo:my-key"

    def test_assert_contract_raises(self, scratch_contract):
        with pytest.raises(AssertionError, match="collective-budget:all-to-all"):
            assert_contract(scratch_contract, "")


class TestForbiddenPatterns:
    def test_host_callback(self, scratch_contract):
        txt = (
            _hlo("all-to-all")
            + '  %cc = f32[8] custom-call(f32[8] %x), custom_call_target="xla_python_cpu_callback"\n'
        )
        found = verify_hlo(scratch_contract, txt)
        assert [f.rule for f in found] == ["forbidden-op:host-callback"]

    def test_f64_upcast(self, scratch_contract):
        txt = _hlo("all-to-all") + "  %c = f64[1000]{0} convert(f32[1000]{0} %x)\n"
        found = verify_hlo(scratch_contract, txt)
        assert [f.rule for f in found] == ["forbidden-op:f64-upcast"]

    def test_dynamic_shape(self, scratch_contract):
        txt = _hlo("all-to-all") + "  %p = s32[<=1024] parameter(0)\n"
        found = verify_hlo(scratch_contract, txt)
        assert [f.rule for f in found] == ["forbidden-op:dynamic-shape"]

    def test_opt_out(self):
        register_contract("hscheck-optout", {}, forbid=("host-callback",))
        try:
            txt = "  %p = s32[<=1024] parameter(0)\n"
            assert verify_hlo("hscheck-optout", txt) == []
        finally:
            hlo_lint._CONTRACTS.pop("hscheck-optout", None)

    def test_scalar_f64_convert_allowed(self, scratch_contract):
        # only whole-ARRAY upcasts are flagged; a scalar convert is fine
        txt = _hlo("all-to-all") + "  %c = f64[] convert(f32[] %x)\n"
        assert verify_hlo(scratch_contract, txt) == []


@pytest.fixture()
def runtime_default_on():
    set_default_enabled(True)
    reset_runtime_state()
    yield
    set_default_enabled(False)
    reset_runtime_state()


class TestMaybeVerify:
    def test_disabled_is_noop(self):
        reset_runtime_state()
        set_default_enabled(False)
        calls = []

        class Exploding:
            def lower(self, *a, **k):
                calls.append(1)
                raise RuntimeError("should not be reached")

        maybe_verify(None, "never-registered", "k", Exploding(), (np.zeros(4),))
        assert calls == []
        assert runtime_violations() == []

    def test_verifies_and_dedups(self, scratch_contract, runtime_default_on):
        jitted = jax.jit(lambda x: x * 2)
        before = REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value
        x = jnp.arange(8, dtype=jnp.float32)
        maybe_verify(None, scratch_contract, "k1", jitted, (x,))
        after = REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value
        assert after == before + 1
        # x*2 has no all-to-all: the budget violation lands in the log + metric
        viol = runtime_violations()
        assert [f.rule for f in viol] == ["collective-budget:all-to-all"]
        assert REGISTRY.counter(
            "hs_check_violations_total",
            rule="collective-budget:all-to-all",
            program=scratch_contract,
        ).value >= 1
        # same key + same shapes: cached executable, not re-verified
        maybe_verify(None, scratch_contract, "k1", jitted, (x,))
        assert REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value == after
        # new shape signature = new executable = verified again
        maybe_verify(
            None, scratch_contract, "k1", jitted, (jnp.arange(16, dtype=jnp.float32),)
        )
        assert REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value == after + 1

    def test_violations_warn_never_raise(self, scratch_contract, runtime_default_on):
        jitted = jax.jit(lambda x: x + 1)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            maybe_verify(
                None, scratch_contract, "k2", jitted, (jnp.ones(4, jnp.float32),)
            )
        assert any("contract violation" in str(x.message) for x in w)

    def test_reset_clears_dedup_and_log(self, scratch_contract, runtime_default_on):
        jitted = jax.jit(lambda x: x)
        x = jnp.ones(4, jnp.float32)
        maybe_verify(None, scratch_contract, "k3", jitted, (x,))
        assert runtime_violations()
        reset_runtime_state()
        assert runtime_violations() == []
        before = REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value
        maybe_verify(None, scratch_contract, "k3", jitted, (x,))
        assert REGISTRY.counter(
            "hs_check_programs_verified_total", program=scratch_contract
        ).value == before + 1


class TestEndToEnd:
    def test_device_queries_verified_clean(self, tmp_system_path, sample_parquet):
        """The acceptance run: with the check on, every compiled device
        program is verified and none violates its contract."""
        sess = hst.Session(
            conf={
                hst.keys.SYSTEM_PATH: tmp_system_path,
                hst.keys.CHECK_HLO_ENABLED: True,
                hst.keys.TPU_QUERY_DEVICE_EXECUTION: True,
                hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 0,
            }
        )
        hst.set_session(sess)
        try:
            reset_runtime_state()
            hs = hst.Hyperspace(sess)
            df = sess.read_parquet(sample_parquet)
            hs.create_index(
                df, hst.CoveringIndexConfig("chkIdx", ["c1"], ["c2", "c3"])
            )
            sess.enable_hyperspace()
            df.filter(col("c1") > 20).select("c2").collect()
            df.filter(col("c1") > 10).group_by("c1").agg(s=("c2", "sum")).collect()
            snap = {
                family: REGISTRY.counter(
                    "hs_check_programs_verified_total", program=family
                ).value
                for family in ("fused-filter", "grouped-agg-chunk")
            }
            assert sum(snap.values()) > 0, snap
            assert runtime_violations() == [], [
                f.render() for f in runtime_violations()
            ]
        finally:
            hst.set_session(None)
            set_default_enabled(False)
            reset_runtime_state()

    def test_exec_contracts_registered(self):
        have = set(hlo_lint.registered_contracts())
        for family in (
            "fused-filter",
            "fused-agg",
            "grouped-agg-chunk",
            "sharded-grouped",
            "grouped-merge",
            "bucketed-smj-span",
        ):
            assert family in have

    def test_shim_still_exports(self):
        # parallel/hlo_check is a compat shim over this module now
        from hyperspace_tpu.parallel import hlo_check as shim

        assert shim.collective_counts is collective_counts
        assert shim.hlo_text_of is hlo_text_of
