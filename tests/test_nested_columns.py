"""Nested-column indexing (struct fields via dotted paths).

Mirrors the reference's nested suites — CreateIndexNestedTest,
RefreshIndexNestedTest, E2E nested cases (SURVEY.md §4): nested fields
normalize to flat ``__hs_nested.a.b`` columns in the index data
(ref: util/ResolverUtils.scala:44-105), arrays/maps are rejected
(:185-195), and indexing them is gated on
``hyperspace.index.nestedColumn.enabled``.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import NESTED_PREFIX


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def nested_parquet(tmp_path):
    d = tmp_path / "nested"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(2):
        n = 200
        t = pa.table(
            {
                "id": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
                "nested": pa.array(
                    [
                        {"leaf": {"cnt": int(v % 9)}, "name": f"n{v % 4}"}
                        for v in rng.integers(0, 100, n)
                    ]
                ),
            }
        )
        pq.write_table(t, d / f"p{i}.parquet")
    return str(d)


def enable_nested(session):
    session.conf.set(hst.keys.NESTED_COLUMN_ENABLED, True)
    session.conf.set(hst.keys.NUM_BUCKETS, 4)


class TestNestedCreate:
    def test_requires_conf(self, session, hs, nested_parquet):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(nested_parquet)
        with pytest.raises(ValueError, match="nestedColumn"):
            hs.create_index(df, hst.CoveringIndexConfig("nOff", ["nested.leaf.cnt"], ["id"]))

    def test_index_data_uses_normalized_flat_names(self, session, hs, nested_parquet):
        enable_nested(session)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("nNorm", ["nested.leaf.cnt"], ["id"]))
        entry = session.index_manager.get_index("nNorm")
        props = entry.derived_dataset.properties
        assert props["indexedColumns"] == [NESTED_PREFIX + "nested.leaf.cnt"]
        f = entry.content.files[0]
        names = pq.read_schema(f).names
        assert NESTED_PREFIX + "nested.leaf.cnt" in names
        assert "id" in names

    def test_array_field_rejected(self, session, hs, tmp_path):
        enable_nested(session)
        d = tmp_path / "arr"
        d.mkdir()
        t = pa.table(
            {
                "id": pa.array(np.arange(10, dtype=np.int64)),
                "tags": pa.array([[1, 2]] * 10),
            }
        )
        pq.write_table(t, d / "p.parquet")
        df = session.read_parquet(str(d))
        with pytest.raises(ValueError, match="Array/map"):
            hs.create_index(df, hst.CoveringIndexConfig("nArr", ["tags.x"], ["id"]))


class TestNestedQueries:
    def test_filter_rewrite_and_results(self, session, hs, nested_parquet):
        enable_nested(session)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("nQ", ["nested.leaf.cnt"], ["id"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("nested.leaf.cnt") == 3).select("id")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["id"]), np.sort(off["id"]))
        assert len(on["id"]) > 0

    def test_nested_select_output(self, session, hs, nested_parquet):
        enable_nested(session)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("nSel", ["nested.leaf.cnt"], ["nested.name"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("nested.leaf.cnt") > 5).select("nested.name")
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        a = np.sort(on["nested.name"].astype(str))
        b = np.sort(off["nested.name"].astype(str))
        assert np.array_equal(a, b)
        assert len(a) > 0

    def test_bucket_pruning_on_nested_column(self, session, hs, nested_parquet):
        enable_nested(session)
        session.conf.set(hst.keys.FILTER_RULE_USE_BUCKET_SPEC, True)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("nPr", ["nested.leaf.cnt"], ["id"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("nested.leaf.cnt") == 3).select("id")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans and scans[0].pruned_buckets is not None
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["id"]), np.sort(off["id"]))

    def test_join_on_nested_key(self, session, hs, nested_parquet, tmp_path):
        enable_nested(session)
        rroot = tmp_path / "r"
        rroot.mkdir()
        pq.write_table(
            pa.table(
                {
                    "cnt": np.arange(9, dtype=np.int64),
                    "label": np.array([f"L{i}" for i in range(9)]),
                }
            ),
            rroot / "p.parquet",
        )
        ldf = session.read_parquet(nested_parquet)
        rdf = session.read_parquet(str(rroot))
        hs.create_index(ldf, hst.CoveringIndexConfig("nJL", ["nested.leaf.cnt"], ["id"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("nJR", ["cnt"], ["label"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=hst.col("nested.leaf.cnt") == hst.col("cnt")).select("id", "label")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(scans) == 2, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert len(on["id"]) == len(off["id"]) > 0
        a = np.lexsort((on["label"].astype(str), on["id"]))
        b = np.lexsort((off["label"].astype(str), off["id"]))
        assert np.array_equal(on["id"][a], off["id"][b])
        assert np.array_equal(on["label"][a].astype(str), off["label"][b].astype(str))

    @pytest.mark.parametrize("mode", ["full", "incremental"])
    def test_refresh_nested_index(self, session, hs, nested_parquet, mode):
        """Refresh revives the index with already-normalized column names —
        they must round-trip through resolution (RefreshIndexNestedTest)."""
        enable_nested(session)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig(f"nRef_{mode}", ["nested.leaf.cnt"], ["id"]))
        import os

        rng = np.random.default_rng(4)
        t = pa.table(
            {
                "id": pa.array(rng.integers(0, 1000, 60).astype(np.int64)),
                "nested": pa.array(
                    [{"leaf": {"cnt": int(v % 9)}, "name": f"n{v % 4}"} for v in rng.integers(0, 100, 60)]
                ),
            }
        )
        pq.write_table(t, os.path.join(nested_parquet, f"app_{mode}.parquet"))
        hs.refresh_index(f"nRef_{mode}", mode)
        session.enable_hyperspace()
        df2 = session.read_parquet(nested_parquet)
        q = df2.filter(hst.col("nested.leaf.cnt") == 3).select("id")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["id"]), np.sort(off["id"]))

    def test_hybrid_scan_with_nested_index(self, session, hs, nested_parquet):
        enable_nested(session)
        df = session.read_parquet(nested_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("nHy", ["nested.leaf.cnt"], ["id"]))
        # append another file after indexing
        rng = np.random.default_rng(9)
        t = pa.table(
            {
                "id": pa.array(rng.integers(0, 1000, 50).astype(np.int64)),
                "nested": pa.array(
                    [{"leaf": {"cnt": int(v % 9)}, "name": f"n{v % 4}"} for v in rng.integers(0, 100, 50)]
                ),
            }
        )
        import os

        pq.write_table(t, os.path.join(nested_parquet, "appended.parquet"))
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        df2 = session.read_parquet(nested_parquet)
        q = df2.filter(hst.col("nested.leaf.cnt") == 3).select("id")
        plan = q.optimized_plan()
        unions = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.BucketUnion)]
        assert unions, plan.pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["id"]), np.sort(off["id"]))
