"""Absolute-correctness oracles for TPC-H queries: pandas reimplementations
checked against the engine over the SAME shaped fixture and index roster as
the gold-standard suite (test_tpch_queries.build_tpch_env) — the reference's
checkAnswer culture (E2EHyperspaceRulesTest.scala:75-1016 verifies results,
not just on/off parity), extended to the BASELINE benchmark family. LIMIT is
stripped on both sides so ORDER BY ties cannot flake; oracles compute the
full set. Row comparison reuses the TPC-DS oracle comparator
(test_tpcds_oracles.compare_batch).
"""

import numpy as np
import pandas as pd
import pytest

import hyperspace_tpu as hst
from test_tpcds_oracles import _nonempty, compare_batch, strip_limit
from test_tpch_queries import build_tpch_env
from tpch_queries import TPCH_QUERIES


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_oracle"))
    sess, frames = build_tpch_env(root)
    yield sess, frames
    hst.set_session(None)


def check(sess, qname, oracle_df):
    got = sess.sql(strip_limit(TPCH_QUERIES[qname])).collect()
    n = compare_batch(got, oracle_df, qname)
    _nonempty(n, qname)
    return n


def _rev(m):
    return m.l_extendedprice * (1 - m.l_discount)


def test_q1(env):
    sess, t = env
    li = t["lineitem"]
    m = li[li.l_shipdate <= np.datetime64("1998-12-01") - np.timedelta64(90, "D")]
    g = m.groupby(["l_returnflag", "l_linestatus"]).apply(
        lambda x: pd.Series({
            "sum_qty": x.l_quantity.sum(),
            "sum_base_price": x.l_extendedprice.sum(),
            "sum_disc_price": _rev(x).sum(),
            "sum_charge": (_rev(x) * (1 + x.l_tax)).sum(),
            "avg_qty": x.l_quantity.mean(),
            "avg_price": x.l_extendedprice.mean(),
            "avg_disc": x.l_discount.mean(),
            "count_order": len(x),
        }),
        include_groups=False,
    ).reset_index()
    check(sess, "q1", g)


def test_q3(env):
    sess, t = env
    c, o, li = t["customer"], t["orders"], t["lineitem"]
    m = (
        c[c.c_mktsegment == "BUILDING"]
        .merge(o, left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    )
    m = m[(m.o_orderdate < np.datetime64("1995-03-15")) & (m.l_shipdate > np.datetime64("1995-03-15"))]
    g = m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False).apply(
        lambda x: pd.Series({"revenue": _rev(x).sum()}), include_groups=False
    )
    check(sess, "q3", g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]])


def test_q4(env):
    sess, t = env
    o, li = t["orders"], t["lineitem"]
    lo = np.datetime64("1993-07-01")
    win = o[(o.o_orderdate >= lo) & (o.o_orderdate < np.datetime64("1993-10-01"))]
    good = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    m = win[win.o_orderkey.isin(good)]
    g = m.groupby("o_orderpriority", as_index=False).size().rename(columns={"size": "order_count"})
    check(sess, "q4", g)


def test_q5(env):
    sess, t = env
    c, o, li, s, n, r = (t["customer"], t["orders"], t["lineitem"], t["supplier"],
                         t["nation"], t["region"])
    m = (
        c.merge(o, left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(r, left_on="n_regionkey", right_on="r_regionkey")
    )
    m = m[(m.c_nationkey == m.s_nationkey) & (m.r_name == "ASIA")
          & (m.o_orderdate >= np.datetime64("1994-01-01"))
          & (m.o_orderdate < np.datetime64("1995-01-01"))]
    g = m.groupby("n_name", as_index=False).apply(
        lambda x: pd.Series({"revenue": _rev(x).sum()}), include_groups=False
    )
    check(sess, "q5", g)


def test_q6(env):
    sess, t = env
    li = t["lineitem"]
    m = li[(li.l_shipdate >= np.datetime64("1994-01-01"))
           & (li.l_shipdate < np.datetime64("1995-01-01"))
           & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
           & (li.l_quantity < 24)]
    check(sess, "q6", pd.DataFrame({"revenue": [(m.l_extendedprice * m.l_discount).sum()]}))


def test_q10(env):
    sess, t = env
    c, o, li, n = t["customer"], t["orders"], t["lineitem"], t["nation"]
    m = (
        c.merge(o, left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    m = m[(m.o_orderdate >= np.datetime64("1993-10-01"))
          & (m.o_orderdate < np.datetime64("1994-01-01"))
          & (m.l_returnflag == "R")]
    keys = ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name", "c_address", "c_comment"]
    g = m.groupby(keys, as_index=False).apply(
        lambda x: pd.Series({"revenue": _rev(x).sum()}), include_groups=False
    )
    check(sess, "q10", g)


def test_q12(env):
    sess, t = env
    o, li = t["orders"], t["lineitem"]
    m = o.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    m = m[m.l_shipmode.isin(["MAIL", "SHIP"])
          & (m.l_commitdate < m.l_receiptdate)
          & (m.l_shipdate < m.l_commitdate)
          & (m.l_receiptdate >= np.datetime64("1994-01-01"))
          & (m.l_receiptdate < np.datetime64("1995-01-01"))]
    hi = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = m.assign(h=hi.astype(np.int64)).groupby("l_shipmode", as_index=False).agg(
        high_line_count=("h", "sum"), low_line_count=("h", lambda s: int((1 - s).sum()))
    )
    check(sess, "q12", g)


def test_q14(env):
    sess, t = env
    li, p = t["lineitem"], t["part"]
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    m = m[(m.l_shipdate >= np.datetime64("1995-09-01")) & (m.l_shipdate < np.datetime64("1995-10-01"))]
    rev = _rev(m)
    promo = rev[m.p_type.astype(str).str.startswith("PROMO")].sum()
    check(sess, "q14", pd.DataFrame({"promo_revenue": [100.0 * promo / rev.sum()]}))


def test_q17(env):
    sess, t = env
    li, p = t["lineitem"], t["part"]
    sel = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    m = li.merge(sel[["p_partkey"]], left_on="l_partkey", right_on="p_partkey")
    thresh = li.groupby("l_partkey")["l_quantity"].mean() * 0.2
    m = m[m.l_quantity < m.l_partkey.map(thresh)]
    check(sess, "q17", pd.DataFrame({"avg_yearly": [m.l_extendedprice.sum() / 7.0]}))


def test_q19(env):
    sess, t = env
    li, p = t["lineitem"], t["part"]
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    common = m.l_shipmode.isin(["AIR", "AIR REG"]) & (m.l_shipinstruct == "DELIVER IN PERSON")

    def arm(brand, containers, qlo, qhi, slo, shi):
        return (
            (m.p_brand == brand) & m.p_container.isin(containers)
            & (m.l_quantity >= qlo) & (m.l_quantity <= qhi)
            & (m.p_size >= slo) & (m.p_size <= shi) & common
        )

    mask = (
        arm("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"], 1, 11, 1, 5)
        | arm("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"], 10, 20, 1, 10)
        | arm("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"], 20, 30, 1, 15)
    )
    check(sess, "q19", pd.DataFrame({"revenue": [_rev(m[mask]).sum()]}))
