"""Test harness.

Multi-device behavior is exercised on a virtual 8-device CPU mesh, standing in
for the reference's ``local[4]`` in-process Spark
(ref: src/test/scala/com/microsoft/hyperspace/SparkInvolvedSuite.scala:26-56;
SURVEY.md §4 "Implication for the TPU build").

Env vars must be set before jax is imported anywhere.
"""

import os

# Force the 8-device virtual CPU mesh. Env vars alone are NOT enough here: the
# axon sitecustomize imports jax at interpreter startup (before conftest), so
# JAX_PLATFORMS was already read from the environment as "axon". Updating the
# config object works any time before backend initialization.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture()
def tmp_system_path(tmp_path):
    """Per-test index system path (ref: HyperspaceSuite's per-suite systemPath)."""
    p = tmp_path / "indexes"
    p.mkdir()
    return str(p)


@pytest.fixture()
def sample_parquet(tmp_path):
    """Small sample dataset (ref: test SampleData.scala)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 1000
    table = pa.table(
        {
            "c1": rng.integers(0, 100, n).astype(np.int64),
            "c2": rng.integers(0, 1000, n).astype(np.int64),
            "c3": rng.standard_normal(n),
            "c4": np.array([f"name_{i % 37}" for i in range(n)]),
        }
    )
    root = tmp_path / "sample_data"
    root.mkdir()
    # several files so file-level diffs are meaningful
    for i in range(4):
        pq.write_table(table.slice(i * 250, 250), root / f"part-{i:05d}.parquet")
    return str(root)


@pytest.fixture()
def session(tmp_system_path):
    import hyperspace_tpu as hst

    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: tmp_system_path})
    hst.set_session(sess)
    yield sess
    hst.set_session(None)


# --- shared E2E helpers (the reference's verifyIndexUsage/checkAnswer) ------


def index_scans(q):
    """IndexScan nodes of the optimized plan (verifyIndexUsage side)."""
    from hyperspace_tpu.plan import logical as L

    return [p for p in L.collect(q.optimized_plan(), lambda x: True) if isinstance(p, L.IndexScan)]


def sorted_rows(batch):
    """Row-set normal form: sorted tuples with NaN made comparable."""

    def norm(v):
        # one totally-ordered domain: NaN == NaN, NULLs sortable, every
        # value stringified (a rollup NULL-filled column mixes types)
        if v is None:
            return "\x00NULL"
        if isinstance(v, float) and v != v:
            return "NaN"
        return str(v)

    cols = sorted(batch.keys())
    if not cols:
        return []
    return sorted(tuple(norm(v) for v in r) for r in zip(*[batch[k].tolist() for k in cols]))


def check_answer(session, q):
    """Full row-set equality with hyperspace on vs off (checkAnswer)."""
    session.enable_hyperspace()
    on = q.collect()
    session.disable_hyperspace()
    try:
        off = q.collect()
    finally:
        session.enable_hyperspace()
    assert sorted(on.keys()) == sorted(off.keys())
    assert sorted_rows(on) == sorted_rows(off)
    return on
