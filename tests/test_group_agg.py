"""Device grouped-aggregation engine: fused filter→group-by segment reduction
with streaming partial-aggregate merge.

The device path must agree with the host pandas aggregation on every supported
shape — byte-identical for counts/int sums/min/max/keys, fp-tolerance for float
reductions (summation order differs) — and produce groups in first-appearance
order (pandas ``groupby(sort=False)`` parity). Everything else falls back,
counted in ``hs_device_fallback_total``.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs.metrics import REGISTRY

pytestmark = pytest.mark.groupagg

FLOAT_RTOL = 1e-9


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def lineitems(tmp_path):
    """TPC-H q1-shaped data: two low-cardinality string keys (with NULLs),
    int/float measures (with NULL floats), and a pruning-friendly int column."""
    d = tmp_path / "li"
    d.mkdir()
    rng = np.random.default_rng(42)
    n = 4000
    rf = rng.choice(["A", "N", "R"], n).astype(object)
    ls = rng.choice(["O", "F"], n).astype(object)
    rf[7] = None
    rf[123] = None
    qty = rng.integers(1, 51, n).astype(np.int64)
    price = np.round(rng.uniform(900.0, 105000.0, n), 2)
    disc = np.round(rng.uniform(0.0, 0.1, n), 2)
    disc[rng.choice(n, 60, replace=False)] = np.nan
    ship = rng.integers(0, 2500, n).astype(np.int64)
    for i in range(4):
        sl = slice(i * 1000, (i + 1) * 1000)
        pq.write_table(
            pa.table(
                {
                    "rf": rf[sl],
                    "ls": ls[sl],
                    "qty": qty[sl],
                    "price": price[sl],
                    "disc": disc[sl],
                    "ship": ship[sl],
                }
            ),
            d / f"p{i}.parquet",
        )
    return str(d)


def assert_grouped_equal(dev, host, float_cols=()):
    """Positional (appearance-order) equality: float columns to tolerance,
    object key columns nan/None-aware, everything else byte-identical."""
    assert sorted(dev.keys()) == sorted(host.keys())
    for k in dev:
        a, b = np.asarray(dev[k]), np.asarray(host[k])
        assert a.shape == b.shape, k
        if k in float_cols:
            np.testing.assert_allclose(a, b, rtol=FLOAT_RTOL, equal_nan=True, err_msg=k)
        elif a.dtype == object or b.dtype == object:
            # nan != nan for object arrays; any non-string (None/nan) matches
            assert all(
                (not isinstance(x, str) and not isinstance(y, str)) or x == y
                for x, y in zip(a, b)
            ), k
        else:
            np.testing.assert_array_equal(a, b, err_msg=k)


def q1_query(df):
    return (
        df.filter(hst.col("ship") <= 2400)
        .group_by("rf", "ls")
        .agg(
            sum_qty=("qty", "sum"),
            sum_price=("price", "sum"),
            avg_qty=("qty", "avg"),
            avg_price=("price", "avg"),
            avg_disc=("disc", "avg"),
            sd_price=("price", "stddev_samp"),
            n=("*", "count"),
            nd=("disc", "count"),
            lo=("price", "min"),
            hi=("qty", "max"),
        )
    )


def collect_device_and_host(session, q):
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    dev = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
    host = q.collect()
    session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
    return dev, host


class TestDeviceVsHostOracle:
    def test_q1_shape_over_covering_index(self, session, hs, lineitems):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(lineitems)
        hs.create_index(
            df,
            hst.CoveringIndexConfig(
                "q1Idx", ["ship"], ["rf", "ls", "qty", "price", "disc"]
            ),
        )
        session.enable_hyperspace()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        q = q1_query(df)
        with trace.recording() as events:
            dev = q.collect()
        assert ("agg", "device-grouped-scan") in events
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        host = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        # all (rf, ls) pairs present, including the NULL-rf group
        assert len(dev["rf"]) == len(host["rf"]) >= 6
        assert_grouped_equal(
            dev, host,
            float_cols=("sum_price", "avg_qty", "avg_price", "avg_disc", "sd_price", "lo"),
        )
        # byte-identical columns really are byte-identical
        for k in ("sum_qty", "n", "nd", "hi"):
            assert np.asarray(dev[k]).tobytes() == np.asarray(host[k]).tobytes(), k

    def test_null_and_signed_zero_float_keys(self, session, tmp_path):
        """NaN float keys collapse into ONE group (pandas dropna=False parity)
        and -0.0/+0.0 share a group; NULL string keys form one group."""
        d = tmp_path / "nullkeys"
        d.mkdir()
        g = np.array([1.5, np.nan, -0.0, 0.0, np.nan, 1.5, 0.0, np.nan] * 250)
        s = np.array(["x", None, "y", "x", None, "y", "x", "y"] * 250, dtype=object)
        v = np.arange(2000, dtype=np.int64)
        for i in range(2):
            sl = slice(i * 1000, (i + 1) * 1000)
            pq.write_table(pa.table({"g": g[sl], "s": s[sl], "v": v[sl]}), d / f"p{i}.parquet")
        df = session.read_parquet(str(d))
        q = df.group_by("g", "s").agg(n=("*", "count"), total=("v", "sum"))
        dev, host = collect_device_and_host(session, q)
        assert_grouped_equal(dev, host)
        # the host oracle itself: one NaN-key group per distinct (nan, s) pair
        ref = pd.DataFrame({"g": g, "s": s}).groupby(["g", "s"], dropna=False).ngroups
        assert len(host["n"]) == ref

    def test_grouped_without_filter_and_int_dtypes(self, session, tmp_path):
        """No predicate to fuse (mask is just the valid-row window) and
        narrow int / bool measures keep their host result dtypes."""
        d = tmp_path / "plain"
        d.mkdir()
        t = pa.table(
            {
                "k": np.repeat(np.arange(16, dtype=np.int64), 125),
                "i32": np.tile(np.arange(125, dtype=np.int32), 16),
                "flag": np.tile(np.array([True, False] * 62 + [True]), 16),
            }
        )
        pq.write_table(t, d / "p.parquet")
        df = session.read_parquet(str(d))
        q = df.group_by("k").agg(
            lo=("i32", "min"), hi=("i32", "max"), s=("i32", "sum"), anyf=("flag", "max")
        )
        dev, host = collect_device_and_host(session, q)
        assert_grouped_equal(dev, host)
        for k in ("lo", "hi", "s", "anyf"):
            assert np.asarray(dev[k]).dtype == np.asarray(host[k]).dtype, k


class TestStreaming:
    def test_streamed_equals_materialized_and_host(self, session, lineitems):
        df = session.read_parquet(lineitems)
        q = q1_query(df)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1)
        session.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, 1)  # one file per chunk
        groups_before = REGISTRY.counter("hs_agg_groups_total", "").value
        merge_before = REGISTRY.counter("hs_agg_merge_seconds_total", "").value
        with trace.recording() as events:
            streamed = q.collect()
        assert ("agg", "device-grouped-stream") in events
        assert REGISTRY.counter("hs_agg_groups_total", "").value > groups_before
        # 4 chunks -> at least one device-side partial merge, with timing
        assert REGISTRY.counter("hs_agg_merge_seconds_total", "").value > merge_before
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 40)
        materialized = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        host = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        floats = ("sum_price", "avg_qty", "avg_price", "avg_disc", "sd_price", "lo")
        assert_grouped_equal(streamed, host, float_cols=floats)
        assert_grouped_equal(materialized, host, float_cols=floats)
        for k in ("rf", "ls", "sum_qty", "n", "nd", "hi"):
            a, b = np.asarray(streamed[k]), np.asarray(materialized[k])
            if a.dtype != object:
                assert a.tobytes() == b.tobytes(), k

    def test_compile_count_flat_across_chunk_sizes(self, session, lineitems):
        """One executable per (skeleton, shape-bucket): after a warmup sweep
        over chunk sizes, repeating the same sweep adds ZERO compiles, and
        requerying a different group cardinality adds none either."""
        df = session.read_parquet(lineitems)
        q = q1_query(df)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1)
        compiles = REGISTRY.counter("hs_xla_compiles_total", "")
        sweep = (1, 120_000, 60_000)
        for cb in sweep:
            session.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, cb)
            q.collect()
        warm = compiles.value
        for _ in range(2):
            for cb in sweep:
                session.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, cb)
                q.collect()
        assert compiles.value == warm
        # different cardinality, same skeleton family: warm on requery
        q2 = df.group_by("ls").agg(n=("*", "count"), s=("qty", "sum"))
        q2.collect()
        warm2 = compiles.value
        q2.collect()
        assert compiles.value == warm2

    def test_cardinality_spill_matches_host(self, session, lineitems):
        """Group cardinality above ``hyperspace.exec.agg.maxGroups`` folds the
        device partial into the host merge mid-stream — same result, plus a
        counted ``spill`` fallback."""
        df = session.read_parquet(lineitems)
        q = df.group_by("ship").agg(n=("*", "count"), s=("qty", "sum"))
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1)
        session.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, 1)
        session.conf.set(hst.keys.EXEC_AGG_MAX_GROUPS, 64)
        spills = REGISTRY.counter("hs_device_fallback_total", "", op="agg", reason="spill")
        before = spills.value
        try:
            dev = q.collect()
        finally:
            session.conf.set(hst.keys.EXEC_AGG_MAX_GROUPS, 1 << 20)
        assert spills.value > before
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        host = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        # ~2000 distinct ship values stream through the host merge unharmed
        assert len(dev["ship"]) == len(host["ship"]) > 64
        assert_grouped_equal(dev, host)


class TestFallbacks:
    def test_unsupported_fn_falls_back_counted(self, session, hs, lineitems):
        """count_distinct is not segment-reducible: the device gate declines,
        the fallback counter ticks, and the host answer is identical to a
        device-disabled run."""
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(lineitems)
        hs.create_index(
            df, hst.CoveringIndexConfig("cdIdx", ["ship"], ["rf", "qty"])
        )
        session.enable_hyperspace()
        q = (
            df.filter(hst.col("ship") < 1200)
            .group_by("rf")
            .agg(u=("qty", "count_distinct"), n=("*", "count"))
        )
        unsupported = REGISTRY.counter(
            "hs_device_fallback_total", "", op="agg", reason="unsupported"
        )
        before = unsupported.value
        dev, host = collect_device_and_host(session, q)
        # streaming declines distinct shapes before the device gate is ever
        # consulted, so only the materialized run can tick the counter; with
        # streaming off the gate must tick it exactly once per attempt
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 40)
        dev2 = q.collect()
        assert unsupported.value > before
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 30)
        assert_grouped_equal(dev, host)
        assert_grouped_equal(dev2, host)

    def test_min_rows_gate_counted(self, session, hs, lineitems):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(lineitems)
        hs.create_index(df, hst.CoveringIndexConfig("mrIdx", ["ship"], ["rf", "qty"]))
        session.enable_hyperspace()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 1 << 40)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 40)
        minrows = REGISTRY.counter(
            "hs_device_fallback_total", "", op="agg", reason="min-rows"
        )
        before = minrows.value
        q = df.filter(hst.col("ship") < 1200).group_by("rf").agg(n=("*", "count"))
        q.collect()
        assert minrows.value > before
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1 << 30)

    def test_disabled_by_conf_never_dispatches_device(self, session, hs, lineitems):
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(lineitems)
        hs.create_index(df, hst.CoveringIndexConfig("offIdx", ["ship"], ["rf", "qty"]))
        session.enable_hyperspace()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_AGG_DEVICE_GROUPED, False)
        try:
            q = df.filter(hst.col("ship") < 1200).group_by("rf").agg(n=("*", "count"))
            with trace.recording() as events:
                got = q.collect()
            assert ("agg", "device-grouped-scan") not in events
            assert ("agg", "device-grouped-stream") not in events
        finally:
            session.conf.set(hst.keys.EXEC_AGG_DEVICE_GROUPED, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
        host = q.collect()
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        assert_grouped_equal(got, host)


class TestPrunedScanBranding:
    """Regression: a row-group-pruned scan batch must be cached under a key
    branded with the pruning predicate. Two predicates can prune the same
    scan to EQUAL row counts but DIFFERENT rows; an unbranded key aliases
    them in the device column cache."""

    def test_pruned_key_distinct(self):
        from hyperspace_tpu.exec.executor import _pruned_scan_key

        base = (("files", ("a.parquet",)),)
        a = _pruned_scan_key(base, hst.col("x") < 5)
        b = _pruned_scan_key(base, hst.col("x") >= 5)
        assert a != b != base and a != base
        assert _pruned_scan_key(base, None) == base
        assert _pruned_scan_key(None, hst.col("x") < 5) is None

    def test_same_count_different_rows_no_aliasing(self, session, tmp_path):
        """Two streamed grouped aggregates over the SAME files whose pushdown
        predicates prune to identical row counts but disjoint rows: stale
        column staging would make the second result wrong."""
        d = tmp_path / "pruned"
        d.mkdir()
        # each file: ship sorted, two 500-row row groups
        for i in range(2):
            base = i * 1000
            pq.write_table(
                pa.table(
                    {
                        "ship": np.arange(base, base + 1000, dtype=np.int64),
                        "g": np.tile(np.arange(5, dtype=np.int64), 200),
                        "v": np.arange(base, base + 1000, dtype=np.int64) * 3,
                    }
                ),
                d / f"p{i}.parquet",
                row_group_size=500,
            )
        df = session.read_parquet(str(d))
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        session.conf.set(hst.keys.EXEC_STREAM_AGG_MIN_BYTES, 1)
        session.conf.set(hst.keys.EXEC_STREAM_CHUNK_BYTES, 1)

        def run(lo, hi):
            q = (
                df.filter((hst.col("ship") >= lo) & (hst.col("ship") < hi))
                .group_by("g")
                .agg(n=("*", "count"), s=("v", "sum"))
            )
            session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
            dev = q.collect()
            session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, False)
            host = q.collect()
            session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
            assert_grouped_equal(dev, host)
            assert int(np.sum(dev["n"])) == hi - lo

        # both windows keep 500 rows of file p0 — different 500 rows
        run(0, 500)
        run(500, 1000)
        # and a window over the second file with the same shape
        run(1000, 1500)


class TestServingBatchedAggregate:
    def test_shared_scan_grouped_aggregate_matches_individual(self, session, tmp_path):
        from hyperspace_tpu.serving.batcher import execute_shared_scan, shared_scan_ops

        rng = np.random.default_rng(3)
        n = 3000
        pq.write_table(
            pa.table(
                {
                    "dept": rng.integers(0, 9, n).astype(np.int64),
                    "price": rng.standard_normal(n) * 50 + 50,
                    "qty": rng.integers(1, 20, n).astype(np.int32),
                }
            ),
            tmp_path / "t.parquet",
        )
        session.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_EXECUTION, True)
        session.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
        sql = "SELECT dept, count(*) AS n, sum(qty) AS s FROM t WHERE price > {v} GROUP BY dept"
        template = session.sql(sql.format(v=45)).plan
        got = shared_scan_ops(template)
        assert got is not None
        ops, leaf = got
        assert "aggregate" in [k for k, _ in ops]
        bound = [session.sql(sql.format(v=v)).plan for v in (45, 20, 80)]
        batches = execute_shared_scan(session, ops, leaf, bound)
        for v, gotb in zip((45, 20, 80), batches):
            want = session.sql(sql.format(v=v)).collect()
            assert sorted(gotb.keys()) == sorted(want.keys())
            for c in want:
                np.testing.assert_array_equal(
                    np.asarray(gotb[c]), np.asarray(want[c]), err_msg=f"{v}:{c}"
                )

    def test_having_shape_stays_unbatched(self, session, tmp_path):
        from hyperspace_tpu.serving.batcher import shared_scan_ops

        pq.write_table(
            pa.table({"k": np.arange(100, dtype=np.int64) % 5, "v": np.arange(100.0)}),
            tmp_path / "h.parquet",
        )
        session.read_parquet(str(tmp_path / "h.parquet")).create_or_replace_temp_view("h")
        plan = session.sql(
            "SELECT k, count(*) AS n FROM h WHERE v > 1 GROUP BY k HAVING count(*) > 2"
        ).plan
        assert shared_scan_ops(plan) is None
