"""hscheck AST lint: per-rule seeded-violation/clean fixture pairs, pragma
suppression, CLI exit codes, and the tree-is-clean acceptance gate."""

import json
import os

import pytest

from hyperspace_tpu.check.__main__ import main
from hyperspace_tpu.check.lint import default_paths, default_root, run_lint
from hyperspace_tpu.check.rules import all_rules

pytestmark = pytest.mark.check

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "check")


def fixture(*parts):
    return os.path.join(FIXTURES, *parts)


def lint_one(path, rule):
    return run_lint(paths=[path], rules=[rule])


class TestRulePairs:
    def test_conf_keys_bad(self):
        found = lint_one(fixture("bad_conf_key.py"), "conf-keys")
        assert len(found) == 1
        assert found[0].rule == "conf-keys"
        assert "hyperspace.serving.quueDepth" in found[0].message
        assert found[0].line == 5

    def test_conf_keys_clean(self):
        assert lint_one(fixture("clean_conf_key.py"), "conf-keys") == []

    def test_metric_families_bad(self):
        found = lint_one(fixture("bad_metric.py"), "metric-families")
        assert len(found) == 1
        assert "literal" in found[0].message

    def test_metric_families_clean(self):
        assert lint_one(fixture("clean_metric.py"), "metric-families") == []

    def test_lock_blocking_bad(self):
        found = lint_one(fixture("serving", "bad_lock.py"), "lock-blocking")
        reasons = " | ".join(f.message for f in found)
        assert len(found) == 3
        assert "sleep" in reasons
        assert "file" in reasons
        assert "device" in reasons

    def test_lock_blocking_clean(self):
        # IO after the with-block and inside nested defs must not count.
        assert lint_one(fixture("serving", "clean_lock.py"), "lock-blocking") == []

    def test_lock_blocking_only_fires_under_serving_or_obs(self):
        # Same seeded pattern, but the path filter keeps the rule scoped to
        # the latency-sensitive trees — bad_jit.py lives outside them.
        assert lint_one(fixture("bad_jit.py"), "lock-blocking") == []

    def test_cache_branding_bad(self):
        found = lint_one(fixture("bad_branding.py"), "cache-branding")
        assert [f.line for f in found] == [7, 8, 9]
        assert "pruned_by" in found[0].message
        assert "scan_key" in found[1].message

    def test_cache_branding_clean(self):
        # Explicit kwarg, positional past the index, and **kwargs all satisfy.
        assert lint_one(fixture("clean_branding.py"), "cache-branding") == []

    def test_jit_purity_bad(self):
        found = lint_one(fixture("bad_jit.py"), "jit-purity")
        lines = [f.line for f in found]
        assert 12 in lines  # time.time in @jax.jit
        assert 13 in lines  # np.sum in @jax.jit
        assert 17 in lines  # random.random in fn later passed to jax.jit
        assert 28 in lines  # np.mean in fn passed into a *jit*-named wrapper

    def test_jit_purity_clean(self):
        # jnp calls and whitelisted np dtypes/constants inside jit are fine,
        # as is host numpy in a never-jitted helper.
        assert lint_one(fixture("clean_jit.py"), "jit-purity") == []

    def test_snapshot_pin_bad(self):
        found = lint_one(fixture("bad_snapshot_pin.py"), "snapshot-pin")
        assert [f.line for f in found] == [6, 7]
        assert "SnapshotHandle" in found[0].message
        assert "get_latest_log" in found[1].message

    def test_snapshot_pin_clean(self):
        # Pin-aware manager reads, handle reads, and a pragma-suppressed
        # direct resolver all pass.
        assert lint_one(fixture("clean_snapshot_pin.py"), "snapshot-pin") == []

    def test_io_error_swallow_bad(self):
        found = lint_one(fixture("bad_io_swallow.py"), "io-error-swallow")
        assert [f.line for f in found] == [8, 16]
        assert "classify" in found[0].message

    def test_io_error_swallow_clean(self):
        # Narrow handlers, re-raises, count_io_error fallbacks, pragmas,
        # and broad excepts away from lake IO all pass.
        assert lint_one(fixture("clean_io_swallow.py"), "io-error-swallow") == []

    def test_process_local_state_bad(self):
        found = lint_one(fixture("bad_process_local.py"), "process-local-state")
        assert [f.line for f in found] == [6, 7, 8, 9, 10]
        reasons = " | ".join(f.message for f in found)
        assert "'BREAKERS'" in reasons
        assert "defaultdict()" in reasons
        assert "count()" in reasons
        assert "FrontDoorRegistry()" in reasons
        assert "__fabric_published__" in found[0].message

    def test_process_local_state_clean(self):
        # __fabric_published__ listing, a pragma, immutable constants,
        # dunders, and function/class-body mutables all pass.
        assert lint_one(fixture("clean_process_local.py"), "process-local-state") == []

    def test_trace_context_drop_bad(self):
        found = lint_one(fixture("fabric", "bad_trace_drop.py"), "trace-context-drop")
        assert len(found) == 2
        messages = " | ".join(f.message for f in found)
        assert "does not cross thread creation" in messages
        assert "traceparent" in messages
        assert [f.line for f in found] == [16, 22]

    def test_trace_context_drop_clean(self):
        # spans.attach/bind_context on the spawned thread, a traceparent
        # header on the /query hop, and a request-free lifecycle thread
        # all pass.
        assert lint_one(fixture("fabric", "clean_trace_drop.py"), "trace-context-drop") == []

    def test_donated_buffer_reuse_bad(self):
        found = lint_one(fixture("bad_donated_reuse.py"), "donated-buffer-reuse")
        assert len(found) == 2
        messages = " | ".join(f.message for f in found)
        assert "'state'" in messages
        assert "donate_argnums" in messages

    def test_donated_buffer_reuse_clean(self):
        # rebinding to the call's result, reading a non-donated argnum, and
        # starred calls (positions unknowable) all pass.
        assert lint_one(fixture("clean_donated_reuse.py"), "donated-buffer-reuse") == []

    def test_native_fallback_bad(self):
        found = lint_one(fixture("bad_native_fallback.py"), "native-fallback")
        assert [f.line for f in found] == [9, 17, 24]
        assert "hs_native_fallback_total" in found[0].message

    def test_native_fallback_clean(self):
        # Re-raises, classified swallows, counted fallbacks (helper and
        # inline registration), pragmas, and read_columns on a non-native
        # receiver all pass.
        assert lint_one(fixture("clean_native_fallback.py"), "native-fallback") == []

    def test_native_fallback_only_fires_under_exec(self):
        from hyperspace_tpu.check.rules.native_fallback import _in_scope

        assert _in_scope(os.path.join("hyperspace_tpu", "exec", "io.py"))
        assert not _in_scope(os.path.join("hyperspace_tpu", "obs", "x.py"))
        assert not _in_scope("bench.py")

    def test_donation_compiler_counts_as_jit_for_purity(self):
        # compile_stage(skeleton, fn, donate_argnums=...) jits fn — a host
        # numpy call inside fn must fire jit-purity just like jax.jit(fn)
        import ast as _ast

        from hyperspace_tpu.check.rules.jit_purity import scan_tree

        src = (
            "def fold(s, c):\n"
            "    import numpy as np\n"
            "    return np.add(s, c)\n"
            "jitted = compile_stage('fuse[F>G]', fold, donate_argnums=(0,))\n"
        )
        hits = scan_tree(_ast.parse(src))
        assert hits and "np.add" in hits[0][1]

    def test_trace_context_drop_only_fires_under_fabric_or_serving(self):
        from hyperspace_tpu.check.rules.trace_context_drop import _in_scope

        assert _in_scope(os.path.join("hyperspace_tpu", "fabric", "x.py"))
        assert _in_scope(os.path.join("hyperspace_tpu", "serving", "x.py"))
        assert not _in_scope(os.path.join("hyperspace_tpu", "obs", "x.py"))
        assert not _in_scope("bench.py")

    def test_process_local_state_only_fires_under_serving_or_reliability(self):
        # Full-scope runs keep the rule off layers whose module state the
        # fabric does not reason about — bad_jit.py lives outside them.
        from hyperspace_tpu.check.rules.process_local_state import _in_scope

        assert _in_scope(os.path.join("hyperspace_tpu", "serving", "x.py"))
        assert _in_scope(os.path.join("hyperspace_tpu", "reliability", "x.py"))
        assert not _in_scope(os.path.join("hyperspace_tpu", "obs", "x.py"))
        assert not _in_scope("bench.py")


class TestSuppression:
    def test_pragma(self):
        found = run_lint(paths=[fixture("suppressed.py")], rules=["conf-keys"])
        # Line 5 (bare disable) and line 6 (disable=conf-keys) are suppressed;
        # line 7 names a different rule, so conf-keys still fires there.
        assert [f.line for f in found] == [7]
        assert "hyperspace.not.registered.c" in found[0].message


class TestRunLint:
    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError, match="no-such-rule"):
            run_lint(rules=["no-such-rule"])

    def test_rule_registry_complete(self):
        assert set(all_rules()) == {
            "cache-branding",
            "conf-keys",
            "jit-purity",
            "lock-blocking",
            "metric-families",
            "snapshot-pin",
            "io-error-swallow",
            "process-local-state",
            "trace-context-drop",
            "donated-buffer-reuse",
            "native-fallback",
        }

    def test_default_scope_excludes_tests(self):
        paths = default_paths(default_root())
        assert paths, "default scope is empty"
        assert not any(os.sep + "tests" + os.sep in p for p in paths)
        assert any(p.endswith("bench.py") for p in paths)

    def test_repo_tree_is_clean(self):
        # The acceptance gate: the shipped tree carries zero findings.
        found = run_lint()
        assert found == [], "\n".join(f.render() for f in found)


class TestCli:
    def test_exit_nonzero_on_fixture(self, capsys):
        rc = main([fixture("bad_conf_key.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "[conf-keys]" in out
        assert "quueDepth" in out

    def test_exit_zero_on_tree(self, capsys):
        assert main([]) == 0
        assert capsys.readouterr().out == ""

    def test_exit_two_on_unknown_rule(self, capsys):
        rc = main(["--rules", "bogus", fixture("bad_conf_key.py")])
        assert rc == 2
        assert "unknown lint rules" in capsys.readouterr().err

    def test_json_output(self, capsys):
        rc = main(["--json", fixture("bad_branding.py")])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert len(payload) == 3
        assert payload[0]["rule"] == "cache-branding"
        assert payload[0]["line"] == 7
        assert payload[0]["path"].endswith("bad_branding.py")

    def test_list_rules(self, capsys):
        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in all_rules():
            assert name in out
