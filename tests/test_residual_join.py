"""Non-equi ON-clause residual predicates (TPC-H q13's
``LEFT JOIN orders ON c_custkey = o_custkey AND o_comment NOT LIKE ...``):
a pair failing the residual must NULL-EXTEND on outer joins — a post-join
filter cannot express that. Oracle: pandas. The reference gets these from
Spark's join executor; its index rules skip them (equi-CNF only), as do
this framework's."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan.sql import SqlError


@pytest.fixture()
def cust_orders(session, tmp_path):
    rng = np.random.default_rng(13)
    nc, no = 60, 400
    cust = pa.table({"c_custkey": np.arange(nc, dtype=np.int64),
                     "c_name": np.array([f"c{i}" for i in range(nc)], dtype=object)})
    orders = pa.table({
        "o_orderkey": np.arange(no, dtype=np.int64),
        "o_custkey": rng.integers(0, nc + 20, no).astype(np.int64),  # some dangling
        "o_comment": np.array(
            [("special requests here" if i % 5 == 0 else f"comment {i}") for i in range(no)],
            dtype=object,
        ),
        "o_total": np.round(rng.uniform(10, 1000, no), 2),
    })
    for name, t in (("cust", cust), ("orders", orders)):
        root = tmp_path / name
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view(name)
    return cust.to_pandas(), orders.to_pandas()


def _oracle_left_residual(cp, op, keep_mask):
    ok = op[keep_mask]
    m = cp.merge(ok, left_on="c_custkey", right_on="o_custkey", how="left")
    return m


class TestResidualJoins:
    def test_q13_shape_left_join_counts(self, session, cust_orders):
        """The famous TPC-H q13: customers joined to NON-special orders;
        customers with only special orders must still appear with count 0."""
        cp, op = cust_orders
        got = session.sql(
            "SELECT c_custkey, count(o_orderkey) AS c_count FROM cust "
            "LEFT JOIN orders ON c_custkey = o_custkey AND "
            "o_comment NOT LIKE '%special%requests%' GROUP BY c_custkey"
        ).collect()
        m = _oracle_left_residual(cp, op, ~op.o_comment.str.contains("special requests"))
        exp = m.groupby("c_custkey").o_orderkey.count()
        got_map = dict(zip(got["c_custkey"].tolist(), got["c_count"].tolist()))
        assert len(got_map) == len(cp)  # every customer present
        for ck, cnt in exp.items():
            assert got_map[int(ck)] == cnt, ck

    def test_left_join_residual_nullextends_not_filters(self, session, cust_orders):
        cp, op = cust_orders
        got = session.sql(
            "SELECT c_name, o_total FROM cust LEFT JOIN orders "
            "ON c_custkey = o_custkey AND o_total > 900"
        ).collect()
        m = _oracle_left_residual(cp, op, op.o_total > 900)
        assert len(got["c_name"]) == len(m)
        # customers with no qualifying order appear exactly once with NULL total
        nulls = sum(1 for v in got["o_total"] if v != v)
        assert nulls == int(m.o_total.isna().sum()) and nulls > 0

    def test_inner_join_residual_matches_filter(self, session, cust_orders):
        cp, op = cust_orders
        a = session.sql(
            "SELECT o_orderkey FROM cust JOIN orders "
            "ON c_custkey = o_custkey AND o_total > 500"
        ).collect()
        b = session.sql(
            "SELECT o_orderkey FROM cust JOIN orders ON c_custkey = o_custkey "
            "WHERE o_total > 500"
        ).collect()
        assert sorted(a["o_orderkey"].tolist()) == sorted(b["o_orderkey"].tolist())

    def test_full_outer_residual(self, session, cust_orders):
        cp, op = cust_orders
        got = session.sql(
            "SELECT c_custkey, o_orderkey FROM cust FULL OUTER JOIN orders "
            "ON c_custkey = o_custkey AND o_total > 500"
        ).collect()
        keep = op.o_total > 500
        pairs = cp.merge(op[keep], left_on="c_custkey", right_on="o_custkey", how="inner")
        lost_c = len(cp) - pairs.c_custkey.nunique()
        lost_o = (~np.isin(op.o_orderkey, pairs.o_orderkey)).sum()
        assert len(got["c_custkey"]) == len(pairs) + lost_c + lost_o

    def test_right_join_residual(self, session, cust_orders):
        cp, op = cust_orders
        got = session.sql(
            "SELECT c_name, o_orderkey FROM cust RIGHT JOIN orders "
            "ON c_custkey = o_custkey AND c_name != 'c3'"
        ).collect()
        assert len(got["o_orderkey"]) >= len(op)  # every order appears
        # orders of customer 3 (and dangling custkeys) have NULL c_name
        m = op.merge(cp[cp.c_name != "c3"], left_on="o_custkey", right_on="c_custkey", how="left")
        nulls = sum(1 for v in got["c_name"] if v is None or v != v)
        assert nulls == int(m.c_name.isna().sum()) and nulls > 0

    def test_residual_on_index_rewrite_skipped(self, session, cust_orders, tmp_path):
        """Joins with residuals stay outside JoinIndexRule's scope (the
        reference's rule is equi-CNF-only), but queries still run with
        hyperspace enabled."""
        hs = hst.Hyperspace(session)
        hs.create_index(
            session._temp_views["orders"],
            hst.CoveringIndexConfig("o_ck_r", ["o_custkey"], ["o_total"]),
        )
        session.enable_hyperspace()
        q = session.sql(
            "SELECT c_custkey, o_total FROM cust LEFT JOIN orders "
            "ON c_custkey = o_custkey AND o_total > 500"
        )
        plan = q.optimized_plan().pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert len(on["o_total"]) == len(off["o_total"])
        def norm(vals):
            return sorted("NULL" if v != v else str(v) for v in vals)

        assert norm(on["o_total"]) == norm(off["o_total"])

    def test_on_without_equality_rejected(self, session, cust_orders):
        with pytest.raises(SqlError, match="at least one equality"):
            session.sql(
                "SELECT c_name FROM cust JOIN orders ON o_total > 500"
            ).collect()

    def test_constant_residual_term(self, session, cust_orders):
        # machine-generated SQL pads ON clauses with constants; a 0-d
        # residual mask must broadcast, and ON ... AND 1 = 0 null-extends
        # every left row
        cp, _ = cust_orders
        got = session.sql(
            "SELECT c_custkey, o_orderkey FROM cust LEFT JOIN orders "
            "ON c_custkey = o_custkey AND 1 = 0"
        ).collect()
        assert len(got["c_custkey"]) == len(cp)
        assert all(v != v for v in got["o_orderkey"])  # all NULL

    def test_inner_residual_plans_as_filter(self, session, cust_orders):
        # inner joins keep the pure-equi Join node (bucketed/device paths and
        # JoinIndexRule stay applicable); only outer joins carry a residual
        q_in = session.sql(
            "SELECT o_orderkey FROM cust JOIN orders ON c_custkey = o_custkey AND o_total > 500"
        )
        assert "residual=" not in q_in.optimized_plan().pretty()
        q_left = session.sql(
            "SELECT o_orderkey FROM cust LEFT JOIN orders ON c_custkey = o_custkey AND o_total > 500"
        )
        assert "residual=" in q_left.optimized_plan().pretty()


class TestMonthIntervals:
    def test_timestamp_keeps_time_of_day(self, session, tmp_path):
        t = pa.table({"ts": pa.array(np.array(["2024-01-15T13:00:00", "2024-01-31T08:30:00"],
                                              dtype="datetime64[s]"))})
        root = tmp_path / "ts"
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view("tst")
        got = session.sql("SELECT ts + INTERVAL '1' month AS m FROM tst").collect()
        vals = [str(np.datetime64(v, "s")) for v in got["m"]]
        assert vals[0] == "2024-02-15T13:00:00"
        assert vals[1] == "2024-02-29T08:30:00"  # clamped to Feb 29, time kept


class TestDoublyRenamedResidual:
    def test_chained_join_residual_on_doubly_renamed_column(self, session, tmp_path):
        """Three tables sharing column names: the second join's right side is
        renamed 'x#r#r'. A residual referencing it must survive column
        pruning (the prune pass strips '#r' suffixes iteratively, mirroring
        join_output_names' repeat-until-unique renaming)."""
        rng = np.random.default_rng(3)
        frames = {}
        for name in ("ta", "tb", "tc"):
            t = pa.table({
                "k": np.arange(20, dtype=np.int64),
                "x": rng.integers(0, 50, 20).astype(np.int64),
            })
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
            frames[name] = t.to_pandas()
        df = session.sql(
            "SELECT ta.k FROM ta JOIN tb ON ta.k = tb.k "
            "JOIN tc ON tb.k = tc.k AND tc.x > ta.x"
        )
        # run the pruning pass explicitly (ApplyHyperspace runs it whenever
        # indexes exist); the pruned plan must still execute correctly
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.rules.utils import prune_columns

        pruned = DataFrame(prune_columns(df.plan), session)
        a, b, c = frames["ta"], frames["tb"], frames["tc"]
        m = a.merge(b, on="k", suffixes=("", "_b")).merge(c, on="k", suffixes=("", "_c"))
        expect = sorted(m[m.x_c > m.x].k.tolist())
        for frame in (df, pruned):
            got = frame.collect()
            assert sorted(got["k"].tolist()) == expect
