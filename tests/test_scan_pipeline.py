"""Pipelined scan engine (decode/transfer/compute overlap, row-group pruning,
shape-bucketed executables) — the three-stage accelerator input-pipeline
treatment of the scan path.

Pinned properties:
- streamed execution (pipelined OR serial) is byte-identical to materialized;
- row-group min/max pruning never changes results, preserves the schema of
  fully-eliminated chunks, and skips decode work (counters prove it);
- closing a stream mid-flight leaks no futures/threads;
- geometric shape buckets keep hs_xla_compiles_total constant after the
  first chunks of a stream.
"""

import glob
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import io as hio
from hyperspace_tpu.exec.pipeline import ScanPipeline
from hyperspace_tpu.plan.expr import BinaryOp, Col, Lit

pytestmark = pytest.mark.pipeline


def _write_files(d, num_files=6, rows_per=4000, seed=11, row_group_size=1000):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(num_files):
        # k is written SORTED within each file so row groups carry disjoint
        # min/max ranges — the shape row-group pruning exploits
        k = np.sort(rng.integers(0, 1000, rows_per).astype(np.int64))
        t = pa.table(
            {
                "k": k,
                "v": np.round(rng.uniform(0, 100, rows_per), 3),
                "name": np.array([f"row_{i}_{j % 23}" for j in range(rows_per)]),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"), row_group_size=row_group_size)
    return d


def _mk_session(tmp_path, **conf):
    base = {
        hst.keys.SYSTEM_PATH: str(tmp_path / "indexes"),
        hst.keys.NUM_BUCKETS: 8,
        hst.keys.EXEC_STREAM_CHUNK_BYTES: 1,  # one file per chunk
    }
    base.update(conf)
    sess = hst.Session(conf=base)
    hst.set_session(sess)
    return sess


def _assert_batches_equal(got, want):
    assert set(got) == set(want)
    for c in want:
        g, w = got[c], want[c]
        assert g.dtype == w.dtype or g.dtype.kind == w.dtype.kind
        np.testing.assert_array_equal(g, w)


class TestStreamedEquality:
    def test_pipelined_stream_matches_materialized(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        want = q.collect()
        chunks = list(q.to_local_iterator())
        assert len(chunks) > 1
        got = {c: np.concatenate([b[c] for b in chunks]) for c in want}
        _assert_batches_equal(got, want)

    def test_serial_fallback_matches_pipelined(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        piped = list(q.to_local_iterator())
        sess.conf.set(hst.keys.EXEC_PIPELINE_ENABLED, False)
        serial = list(q.to_local_iterator())
        assert len(piped) == len(serial)
        for p, s in zip(piped, serial):
            _assert_batches_equal(p, s)

    def test_chunks_fully_eliminated_by_pruning(self, tmp_path):
        """A predicate outside some files' k ranges prunes every row group of
        those chunks; the stream still yields schema-preserving batches and
        the total equals the materialized result."""
        d = str(tmp_path / "data")
        os.makedirs(d)
        for i in range(4):
            k = np.arange(i * 1000, (i + 1) * 1000, dtype=np.int64)
            t = pa.table({"k": k, "v": k.astype(np.float64) / 7})
            pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"), row_group_size=250)
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(d)
        q = df.filter(hst.col("k") >= 3500).select("k", "v")
        chunks = list(q.to_local_iterator())
        assert len(chunks) == 4
        for b in chunks:
            assert set(b) == {"k", "v"}
            assert b["k"].dtype == np.int64
            assert b["v"].dtype == np.float64
        got = np.concatenate([b["k"] for b in chunks])
        np.testing.assert_array_equal(np.sort(got), np.arange(3500, 4000))


class TestRowGroupPruning:
    def _one_file(self, tmp_path):
        p = str(tmp_path / "x.parquet")
        t = pa.table(
            {
                "a": pa.array(np.arange(40, dtype=np.int64)),
                "s": pa.array([f"s{i:02d}" for i in range(40)]),
            }
        )
        pq.write_table(t, p, row_group_size=10)
        return p

    def test_prune_semantics(self, tmp_path):
        p = self._one_file(tmp_path)
        assert hio.prune_row_groups(p, BinaryOp(">=", Col("a"), Lit(35))) == [3]
        assert hio.prune_row_groups(p, BinaryOp("=", Col("s"), Lit("s17"))) == [1]
        assert hio.prune_row_groups(p, BinaryOp("<", Col("a"), Lit(-1))) == []
        # nothing prunable -> None (keep all)
        assert hio.prune_row_groups(p, BinaryOp(">=", Col("a"), Lit(0))) is None

    def test_pruned_read_and_counters(self, tmp_path):
        from hyperspace_tpu.obs.metrics import REGISTRY

        p = self._one_file(tmp_path)
        skipped = REGISTRY.counter(
            "hs_rowgroups_skipped_total",
            "Parquet row groups skipped by min/max statistics pruning",
        )
        before = skipped.value
        b = hio.read_parquet_batch([p], ["a"], predicate=BinaryOp(">=", Col("a"), Lit(35)))
        # the surviving row group [30, 40) decodes WHOLE — pruning yields a
        # superset of matching rows; the Filter above re-applies the predicate
        np.testing.assert_array_equal(b["a"], np.arange(30, 40))
        assert skipped.value == before + 3

    def test_fully_pruned_keeps_schema(self, tmp_path):
        p = self._one_file(tmp_path)
        b = hio.read_parquet_batch([p], ["a", "s"], predicate=BinaryOp("<", Col("a"), Lit(-1)))
        assert b["a"].dtype == np.int64 and b["a"].shape == (0,)
        assert b["s"].shape == (0,)

    def test_pruned_read_never_poisons_full_cache(self, tmp_path):
        p = self._one_file(tmp_path)
        pruned = hio.read_parquet_batch([p], ["a"], predicate=BinaryOp(">=", Col("a"), Lit(35)))
        assert len(pruned["a"]) == 10  # one surviving row group of 10
        full = hio.read_parquet_batch([p], ["a"])
        assert len(full["a"]) == 40

    def test_conf_kill_switch(self, tmp_path):
        data = _write_files(str(tmp_path / "data"), num_files=2)
        sess = _mk_session(tmp_path, **{hst.keys.EXEC_IO_ROWGROUP_PRUNING: False})
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 100).select("k")
        want = np.sort(q.collect()["k"])
        got = np.sort(np.concatenate([b["k"] for b in q.to_local_iterator()]))
        np.testing.assert_array_equal(got, want)


class TestScanPipelineUnit:
    def test_ordered_results_and_counters(self):
        def mk(i):
            def task():
                time.sleep(0.002 * (5 - i))  # later tasks finish FIRST
                return i

            return task

        pipe = ScanPipeline([mk(i) for i in range(5)], depth=2)
        assert list(pipe) == [0, 1, 2, 3, 4]

    def test_close_midstream_leaks_nothing(self):
        started, finished = [], []
        release = threading.Event()

        def mk(i):
            def task():
                started.append(i)
                release.wait(5)
                finished.append(i)
                return i

            return task

        pipe = ScanPipeline([mk(i) for i in range(8)], depth=1)
        it = iter(pipe)
        t = threading.Thread(target=lambda: next(it))
        t.start()
        time.sleep(0.05)
        release.set()
        t.join(5)
        pipe.close()
        # close() waits for in-flight tasks: everything started has finished,
        # and queued-but-cancelled tasks never started
        assert sorted(finished) == sorted(started)
        assert len(started) < 8

    def test_generator_close_midstream(self, tmp_path):
        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        it = q.to_local_iterator()
        first = next(it)
        assert len(first) == 2
        it.close()  # must not raise, deadlock, or leave workers running

    def test_abandoned_stream_leaks_no_open_spans(self, tmp_path):
        # a caller that walks away mid-stream must not leave per-chunk
        # "execute" spans dangling: GeneratorExit unwinds the with-blocks,
        # so everything under the trace root is finished and the contextvar
        # is back where it was
        from hyperspace_tpu.obs import spans

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path, **{hst.keys.OBS_TRACING_ENABLED: True})
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        with spans.trace("stream-abandon") as root:
            it = q.to_local_iterator()
            next(it)
            assert spans.current_span() is root  # nothing left attached
            it.close()
            open_spans = [s for s in root.walk() if s is not root and s.t1 is None]
            assert open_spans == []
            assert root.find("execute")  # the consumed chunk WAS traced
        assert spans.current_span() is None

    def test_byte_budget_limits_lookahead(self):
        order = []

        def mk(i):
            def task():
                order.append(i)
                return np.zeros(1 << 16)

            return task

        # depth allows chunk 5 at k=1 (1+4), but the byte budget — already
        # exceeded by completed-unconsumed chunks 2-4 — must veto it until
        # it becomes the always-allowed one-ahead chunk
        pipe = ScanPipeline(
            [mk(i) for i in range(6)],
            depth=4,
            max_buffered_bytes=1,
            weigh=lambda a: int(a.nbytes),
        )
        it = iter(pipe)
        next(it)  # consume chunk 0; 1-4 submitted by the initial pump
        deadline = time.monotonic() + 5
        while pipe._buffered <= 1 and time.monotonic() < deadline:
            time.sleep(0.005)  # wait for completions to register their weight
        assert pipe._buffered > 1
        next(it)  # k=1: pump sees the exceeded budget
        assert 5 not in order
        rest = list(it)  # budget stalls lookahead, never starves the stream
        assert len(rest) == 4
        assert sorted(order) == list(range(6))


class TestShapeBuckets:
    def test_bucket_rows_geometry(self):
        from hyperspace_tpu.exec.device import bucket_rows

        assert bucket_rows(1) == 4096
        assert bucket_rows(4096) == 4096
        buckets = {bucket_rows(n) for n in range(3000, 6000)}
        assert len(buckets) <= 3  # a whole stream's chunk spread -> few shapes
        for n in (1, 100, 5000, 123457):
            assert bucket_rows(n) >= n
        # geometric growth: consecutive buckets within sqrt(2)+eps
        b = 4096
        for _ in range(10):
            nxt = bucket_rows(b + 1)
            assert b < nxt <= int(b * 1.5) + 2
            b = nxt

    def test_compile_count_constant_after_first_chunks(self, tmp_path):
        from hyperspace_tpu.obs.metrics import REGISTRY

        data = _write_files(str(tmp_path / "data"), num_files=6, rows_per=5000)
        sess = _mk_session(tmp_path, **{hst.keys.TPU_QUERY_DEVICE_MIN_ROWS: 1})
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        compiles = REGISTRY.counter(
            "hs_xla_compiles_total",
            "Distinct (device program skeleton, input shape) XLA compilations",
        )
        counts = []
        for b in q.to_local_iterator():
            counts.append(compiles.value)
        assert len(counts) == 6
        assert counts[-1] == counts[1], f"compiles kept growing: {counts}"


class TestDecodeThreadsConf:
    def test_conf_resizes_pool(self, tmp_path):
        old = hio._CONFIGURED_THREADS
        try:
            hio.set_decode_threads(3)
            if not os.environ.get("HS_DECODE_THREADS"):
                assert hio.decode_threads() == 3
                pool = hio._decode_pool()
                assert pool._max_workers == 3
            _mk_session(tmp_path, **{hst.keys.EXEC_IO_DECODE_THREADS: 5})
            if not os.environ.get("HS_DECODE_THREADS"):
                assert hio.decode_threads() == 5
                assert hio._decode_pool()._max_workers == 5
        finally:
            hio.set_decode_threads(old)

    def test_default_is_eight(self):
        from hyperspace_tpu.config import DEFAULTS

        assert DEFAULTS[hst.keys.EXEC_IO_DECODE_THREADS] == 8


class TestSpansShowOverlap:
    def test_prefetch_and_execute_spans_in_stream_trace(self, tmp_path):
        from hyperspace_tpu.obs import spans

        data = _write_files(str(tmp_path / "data"))
        sess = _mk_session(tmp_path)
        df = sess.read_parquet(data)
        q = df.filter(hst.col("k") < 400).select("k", "v")
        with spans.trace("stream") as root:
            list(q.to_local_iterator())
        prefetch = root.find("prefetch")
        execute = root.find("execute")
        assert len(prefetch) >= 2 and len(execute) >= 2
        # prefetch runs on pipeline-pool threads, not the consumer's
        consumer_tid = threading.get_ident()
        assert any(s.tid != consumer_tid for s in prefetch)
        # chunk k+1's prefetch is submitted before chunk k's execute finishes
        by_chunk = {s.attrs.get("chunk"): s for s in prefetch}
        ex0 = min(execute, key=lambda s: s.attrs.get("chunk", 0))
        nxt = by_chunk.get(ex0.attrs.get("chunk", 0) + 1)
        assert nxt is not None
        assert nxt.t0 <= ex0.t1  # started no later than execute-0 ended
