"""Operation-log manager tests, incl. concurrent-writer races
(ref: src/test/scala/.../index/IndexLogManagerImplTest.scala)."""

import threading

from hyperspace_tpu.models.log_manager import IndexLogManager
from hyperspace_tpu.models.data_manager import IndexDataManager
from hyperspace_tpu.models.path_resolver import PathResolver
from hyperspace_tpu.config import HyperspaceConf, keys

from tests.test_log_entry import make_entry


class TestIndexLogManager:
    def test_empty_log(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.get_latest_id() is None
        assert m.get_latest_log() is None
        assert m.get_latest_stable_log() is None

    def test_write_and_read(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        e = make_entry(state="CREATING")
        assert m.write_log(0, e)
        got = m.get_log(0)
        assert got is not None and got.state == "CREATING" and got.id == 0

    def test_write_existing_id_fails(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.write_log(0, make_entry(state="CREATING"))
        assert not m.write_log(0, make_entry(state="ACTIVE"))
        assert m.get_log(0).state == "CREATING"  # first writer won

    def test_concurrent_writers_single_winner(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        results = []
        barrier = threading.Barrier(8)

        def writer(i):
            barrier.wait()
            results.append((i, m.write_log(5, make_entry(name=f"idx{i}", state="ACTIVE"))))

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        winners = [i for i, ok in results if ok]
        assert len(winners) == 1
        assert m.get_log(5).name == f"idx{winners[0]}"

    def test_latest_stable_snapshot_and_scan(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, make_entry(state="CREATING"))
        m.write_log(1, make_entry(state="ACTIVE"))
        m.write_log(2, make_entry(state="REFRESHING"))
        # no snapshot -> backward scan finds id 1
        assert m.get_latest_stable_log().state == "ACTIVE"
        assert m.create_latest_stable_log(1)
        assert m.get_latest_stable_log().id == 1
        # snapshot of unstable entry is refused
        assert not m.create_latest_stable_log(2)

    def test_get_index_versions(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, make_entry(state="CREATING"))
        m.write_log(1, make_entry(state="ACTIVE"))
        m.write_log(2, make_entry(state="REFRESHING"))
        m.write_log(3, make_entry(state="ACTIVE"))
        assert m.get_index_versions(["ACTIVE"]) == [3, 1]

    def test_corrupt_log_is_skipped(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        m.write_log(0, make_entry(state="ACTIVE"))
        import os

        os.makedirs(m.log_dir, exist_ok=True)
        with open(m._path(1), "w") as f:
            f.write("{not json")
        assert m.get_latest_id() == 1
        assert m.get_log(1) is None
        assert m.get_latest_stable_log().id == 0


class TestIndexDataManager:
    def test_versions(self, tmp_path):
        m = IndexDataManager(str(tmp_path / "idx"))
        assert m.get_latest_version() is None
        for v in (0, 1, 3):
            import os

            os.makedirs(m.version_path(v))
        assert m.get_all_versions() == [0, 1, 3]
        assert m.get_latest_version() == 3
        m.delete_version(3)
        assert m.get_latest_version() == 1


class TestPathResolver:
    def test_requires_system_path(self):
        import pytest

        with pytest.raises(ValueError):
            PathResolver(HyperspaceConf()).system_path

    def test_case_insensitive_lookup(self, tmp_path):
        conf = HyperspaceConf({keys.SYSTEM_PATH: str(tmp_path)})
        r = PathResolver(conf)
        (tmp_path / "MyIndex").mkdir()
        assert r.get_index_path("myindex") == str(tmp_path / "MyIndex")
        assert r.get_index_path("other") == str(tmp_path / "other")
        assert r.all_index_paths() == [str(tmp_path / "MyIndex")]
