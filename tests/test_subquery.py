"""Uncorrelated subquery support: scalar subqueries as comparison operands and
IN-subqueries, with index rewrites applied INSIDE the subquery plan (ref:
explain golden src/test/resources/expected/spark-2.4/subquery.txt — the
reference rewrites the subquery's inner scan to a covering-index scan)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.rules.apply import iter_subquery_plans


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def two_tables(tmp_path):
    rng = np.random.default_rng(3)
    n = 800
    main = pa.table(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.standard_normal(n),
        }
    )
    # dim table: one row per id
    dim = pa.table(
        {
            "id": np.arange(50, dtype=np.int64),
            "tag": np.array([f"t{i % 7}" for i in range(50)]),
        }
    )
    mroot, droot = tmp_path / "main", tmp_path / "dim"
    mroot.mkdir(), droot.mkdir()
    for i in range(2):
        pq.write_table(main.slice(i * 400, 400), mroot / f"p{i}.parquet")
    pq.write_table(dim, droot / "p0.parquet")
    return str(mroot), str(droot)


def subquery_plans(plan):
    return list(iter_subquery_plans(plan))


class TestScalarSubquery:
    def test_results_and_inner_rewrite(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)

        scalar = dim.filter(hst.col("tag") == "t3").filter(hst.col("id") == 3).select("id").as_scalar()
        q = main.filter(hst.col("k") == scalar).select("v")
        baseline = np.sort(q.collect()["v"])

        hs.create_index(dim, hst.CoveringIndexConfig("dimIdx", ["tag"], ["id"]))
        session.enable_hyperspace()
        plan = q.optimized_plan()
        inner = subquery_plans(plan)
        assert inner, "subquery plan must be discoverable in the optimized tree"
        assert any(
            isinstance(p, L.IndexScan) for sp in inner for p in L.collect(sp, lambda x: True)
        ), plan.pretty()
        np.testing.assert_array_equal(np.sort(q.collect()["v"]), baseline)

    def test_outer_and_inner_rewrites_compose(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        scalar = dim.filter(hst.col("id") == 7).select("id").as_scalar()
        q = main.filter(hst.col("k") == scalar).select("v")
        baseline = np.sort(q.collect()["v"])

        hs.create_index(dim, hst.CoveringIndexConfig("dimIdx2", ["id"], []))
        hs.create_index(main, hst.CoveringIndexConfig("mainIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        plan = q.optimized_plan()
        # outer rewritten to IndexScan
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda x: True))
        # inner rewritten too
        inner = subquery_plans(plan)
        assert any(
            isinstance(p, L.IndexScan) for sp in inner for p in L.collect(sp, lambda x: True)
        )
        np.testing.assert_array_equal(np.sort(q.collect()["v"]), baseline)

    def test_empty_scalar_matches_nothing(self, session, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        scalar = dim.filter(hst.col("id") == 9999).select("id").as_scalar()
        got = main.filter(hst.col("k") == scalar).select("v").collect()
        assert got["v"].shape[0] == 0

    def test_null_three_valued_logic(self, session, two_tables):
        """SQL NULL semantics: NOT(k = NULL) is NULL -> selects nothing;
        NULL OR true-predicate keeps the rows the true side matches;
        NULL AND anything selects nothing; IS NULL on the null scalar is true."""
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        null_scalar = dim.filter(hst.col("id") == 9999).select("id").as_scalar()

        assert main.filter(~(hst.col("k") == null_scalar)).collect()["k"].shape[0] == 0

        with_or = main.filter((hst.col("k") == null_scalar) | (hst.col("k") == 3)).collect()
        expected = main.filter(hst.col("k") == 3).collect()
        assert with_or["k"].shape[0] == expected["k"].shape[0] > 0

        with_and = main.filter((hst.col("k") == null_scalar) & (hst.col("k") == 3)).collect()
        assert with_and["k"].shape[0] == 0

        is_null = main.filter((hst.col("k") == null_scalar).is_null()).collect()
        assert is_null["k"].shape[0] == main.collect()["k"].shape[0]

    def test_null_scalar_arithmetic_stays_null(self, session, two_tables):
        """Arithmetic on a NULL scalar is NULL, so a comparison of the result
        is three-valued: NOT((k + NULL) > 5) selects nothing (not everything),
        and IS NULL on the arithmetic result is true for every row."""
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        null_scalar = dim.filter(hst.col("id") == 9999).select("id").as_scalar()

        kept = main.filter(~((hst.col("k") + null_scalar) > 5)).collect()
        assert kept["k"].shape[0] == 0

        pos = main.filter((hst.col("k") + null_scalar) > 5).collect()
        assert pos["k"].shape == (0,)  # 1-D empty, not a 0-d-mask artifact

        is_null = main.filter(((hst.col("k") * null_scalar) - 1).is_null()).collect()
        assert is_null["k"].shape[0] == main.collect()["k"].shape[0]

    def test_null_scalar_as_boolean_operand(self, session, two_tables):
        """A NULL boolean scalar Kleene-combines in AND/OR: NULL OR TRUE
        keeps the true side's rows; NULL AND anything keeps none."""
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        null_bool = dim.filter(hst.col("id") == 9999).select("id").as_scalar()

        with_or = main.filter(null_bool | (hst.col("k") == 3)).collect()
        expected = main.filter(hst.col("k") == 3).collect()
        assert with_or["k"].shape[0] == expected["k"].shape[0] > 0

        with_and = main.filter(null_bool & (hst.col("k") == 3)).collect()
        assert with_and["k"].shape[0] == 0

    def test_subquery_executes_once_per_collect(self, session, two_tables, monkeypatch):
        from hyperspace_tpu.plan.expr import SubqueryExpr

        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        calls = {"n": 0}
        real = SubqueryExpr._values

        def counting(self):
            cache = None
            import hyperspace_tpu.plan.expr as E

            cache = getattr(E._subquery_scope, "cache", None)
            if cache is None or id(self) not in cache:
                calls["n"] += 1
            return real(self)

        monkeypatch.setattr(SubqueryExpr, "_values", counting)
        scalar = dim.filter(hst.col("id") == 7).select("id").as_scalar()
        main.filter(hst.col("k") == scalar).collect()
        assert calls["n"] == 1, f"inner plan ran {calls['n']} times in one collect"
        # a second collect re-executes (no cross-query staleness)
        main.filter(hst.col("k") == scalar).collect()
        assert calls["n"] == 2

    def test_multi_row_scalar_raises(self, session, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        scalar = dim.select("id").as_scalar()  # 50 rows
        with pytest.raises(ValueError, match="scalar subquery"):
            main.filter(hst.col("k") == scalar).collect()

    def test_multi_column_subquery_raises(self, session, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        scalar = dim.as_scalar()  # two columns
        with pytest.raises(ValueError, match="one column"):
            main.filter(hst.col("k") == scalar).collect()


class TestInSubquery:
    def test_results_and_inner_rewrite(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)

        members = dim.filter(hst.col("tag") == "t2").select("id")
        q = main.filter(hst.col("k").isin(members)).select("v")
        baseline = np.sort(q.collect()["v"])
        assert baseline.shape[0] > 0

        hs.create_index(dim, hst.CoveringIndexConfig("dimTag", ["tag"], ["id"]))
        session.enable_hyperspace()
        plan = q.optimized_plan()
        inner = subquery_plans(plan)
        assert any(
            isinstance(p, L.IndexScan) for sp in inner for p in L.collect(sp, lambda x: True)
        ), plan.pretty()
        np.testing.assert_array_equal(np.sort(q.collect()["v"]), baseline)

    def test_case_insensitive_outer_column(self, session, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        members = dim.filter(hst.col("tag") == "t2").select("id")
        got = main.filter(hst.col("K").isin(members)).select("v").collect()
        expected = main.filter(hst.col("k").isin(members)).select("v").collect()
        np.testing.assert_array_equal(np.sort(got["v"]), np.sort(expected["v"]))

    def test_plain_isin_list_unchanged(self, session, two_tables):
        mroot, _ = two_tables
        main = session.read_parquet(mroot)
        got = main.filter(hst.col("k").isin([1, 2, 3])).collect()
        assert set(np.unique(got["k"])) <= {1, 2, 3}

    def test_disabled_hyperspace_leaves_subquery_alone(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        hs.create_index(dim, hst.CoveringIndexConfig("dimTag2", ["tag"], ["id"]))
        q = main.filter(hst.col("k").isin(dim.filter(hst.col("tag") == "t1").select("id")))
        session.disable_hyperspace()
        plan = q.optimized_plan()
        assert not any(
            isinstance(p, L.IndexScan)
            for sp in subquery_plans(plan)
            for p in L.collect(sp, lambda x: True)
        )


class TestWhyNotSeesSubquery:
    def test_applied_inside_subquery_reported(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        hs.create_index(dim, hst.CoveringIndexConfig("dimWhy", ["tag"], ["id"]))
        session.enable_hyperspace()
        q = main.filter(hst.col("k").isin(dim.filter(hst.col("tag") == "t1").select("id")))
        report = hs.why_not(q)
        assert "dimWhy" in report and "(applied)" not in report.split("dimWhy")[0]
        lines = report.splitlines()
        start = lines.index("Applied indexes:")
        assert "- dimWhy" in lines[start + 1 : lines.index("", start)], report

    def test_subquery_scan_disqualification_reported(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        # index does not cover column `id`, so it cannot apply inside the
        # subquery — whyNot must report a reason against the dim scan
        hs.create_index(dim, hst.CoveringIndexConfig("dimNarrow", ["tag"], []))
        session.enable_hyperspace()
        q = main.filter(hst.col("k").isin(dim.filter(hst.col("tag") == "t1").select("id")))
        report = hs.why_not(q)
        assert "dimNarrow" in report
        assert "Scan(dim)" in report  # the subquery's scan label appears


class TestExplainShowsSubquery:
    def test_pretty_contains_subquery_and_index(self, session, hs, two_tables):
        mroot, droot = two_tables
        main, dim = session.read_parquet(mroot), session.read_parquet(droot)
        hs.create_index(dim, hst.CoveringIndexConfig("dimIdx3", ["id"], []))
        session.enable_hyperspace()
        q = main.filter(hst.col("k") == dim.filter(hst.col("id") == 4).select("id").as_scalar())
        text = q.optimized_plan().pretty()
        assert "scalar-subquery" in text
        assert "dimIdx3" in text, text
