"""End-to-end query-rewrite tests
(ref: src/test/scala/.../index/E2EHyperspaceRulesTest.scala:75-1016).

Verification pattern mirrors the reference's ``verifyIndexUsage``: check which
files the rewritten plan scans (index files vs source files), and that query
results are identical with Hyperspace on vs off.
"""

import numpy as np
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


def sort_batch(batch):
    order = np.lexsort([np.asarray(v).astype("U64") if v.dtype == object else v for v in reversed(list(batch.values()))])
    return {k: v[order] for k, v in batch.items()}


def assert_batches_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    a, b = sort_batch(a), sort_batch(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"column {k}")


def scanned_files(plan):
    files = []
    for node in L.collect(plan, lambda p: True):
        if isinstance(node, L.IndexScan):
            files.extend(node.files)
        elif isinstance(node, L.FileScan):
            files.extend(node.files)
        elif isinstance(node, L.Scan):
            files.extend(fi.name for fi in node.relation.all_file_infos())
    return files


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestFilterIndexRule:
    def test_filter_query_uses_index(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("filterIdx", ["c1"], ["c2"]))

        query = df.filter(hst.col("c1") == 7).select("c2")
        baseline = query.collect()

        session.enable_hyperspace()
        plan = query.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        # every scanned file is index data, not source data
        entry = hs._manager.get_index("filterIdx")
        index_files = set(entry.content.files)
        assert set(scanned_files(plan)) <= index_files
        assert_batches_equal(query.collect(), baseline)

    def test_case_insensitive_columns(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("ciIdx", ["C1"], ["C2"]))
        session.enable_hyperspace()
        query = df.filter(hst.col("c1") == 7).select("c2")
        plan = query.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_no_index_when_column_not_covered(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("smallIdx", ["c1"], ["c2"]))
        session.enable_hyperspace()
        # query needs c3, which the index does not include
        query = df.filter(hst.col("c1") == 7).select("c3")
        plan = query.optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_disable_hyperspace_no_rewrite(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("offIdx", ["c1"], ["c2"]))
        session.disable_hyperspace()
        plan = df.filter(hst.col("c1") == 7).select("c2").optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_bucket_pruning_reads_fewer_files(self, session, hs, sample_parquet):
        session.conf.set(hst.keys.FILTER_RULE_USE_BUCKET_SPEC, True)
        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("bpIdx", ["c1"], ["c2"]))
        session.enable_hyperspace()
        query = df.filter(hst.col("c1") == 7).select("c2")
        plan = query.optimized_plan()
        scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert scans and scans[0].pruned_buckets is not None
        assert len(scans[0].pruned_buckets) == 1
        entry = hs._manager.get_index("bpIdx")
        assert len(scans[0].files) < len(entry.content.files)
        baseline_session_result = df.filter(hst.col("c1") == 7).select("c2")
        session.disable_hyperspace()
        assert_batches_equal(query.collect(), baseline_session_result.collect())
        session.enable_hyperspace()
        assert_batches_equal(query.collect(), baseline_session_result.collect())


class TestJoinIndexRule:
    def test_join_query_uses_both_indexes(self, session, hs, sample_parquet, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        # build a second table keyed by c1
        rng = np.random.default_rng(7)
        dim = pa.table(
            {
                "c1": np.arange(100, dtype=np.int64),
                "v": rng.standard_normal(100),
            }
        )
        dim_root = tmp_path / "dim"
        dim_root.mkdir()
        pq.write_table(dim, dim_root / "part-00000.parquet")

        fact = session.read_parquet(sample_parquet)
        dim_df = session.read_parquet(str(dim_root))
        hs.create_index(fact, hst.CoveringIndexConfig("factIdx", ["c1"], ["c2"]))
        hs.create_index(dim_df, hst.CoveringIndexConfig("dimIdx", ["c1"], ["v"]))

        query = fact.select("c1", "c2").join(dim_df.select("c1", "v"), on="c1")
        baseline = query.collect()

        session.enable_hyperspace()
        plan = query.optimized_plan()
        index_scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        assert len(index_scans) == 2, plan.pretty()
        assert {s.entry.name for s in index_scans} == {"factIdx", "dimIdx"}
        # both sides share the bucket layout -> shuffle-free join
        assert index_scans[0].bucket_spec is not None
        assert index_scans[0].bucket_spec.num_buckets == index_scans[1].bucket_spec.num_buckets
        assert_batches_equal(query.collect(), baseline)

    def test_join_not_applied_without_matching_index(self, session, hs, sample_parquet, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        dim = pa.table({"c1": np.arange(100, dtype=np.int64), "v": np.arange(100, dtype=np.float64)})
        dim_root = tmp_path / "dim2"
        dim_root.mkdir()
        pq.write_table(dim, dim_root / "part-00000.parquet")

        fact = session.read_parquet(sample_parquet)
        dim_df = session.read_parquet(str(dim_root))
        hs.create_index(fact, hst.CoveringIndexConfig("factOnly", ["c1"], ["c2"]))
        session.enable_hyperspace()
        plan = fact.select("c1", "c2").join(dim_df.select("c1", "v"), on="c1").optimized_plan()
        index_scans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.IndexScan)]
        # join rule can't pair; filter rule doesn't match (no filter); no rewrite of join sides
        assert len(index_scans) == 0


class TestIndexManagement:
    def test_lifecycle(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("lcIdx", ["c1"], ["c2"]))
        assert hs._manager.get_index("lcIdx").state == "ACTIVE"

        hs.delete_index("lcIdx")
        assert hs._manager.get_index("lcIdx").state == "DELETED"

        hs.restore_index("lcIdx")
        assert hs._manager.get_index("lcIdx").state == "ACTIVE"

        hs.delete_index("lcIdx")
        hs.vacuum_index("lcIdx")
        assert hs._manager.get_index("lcIdx").state == "DOESNOTEXIST"

        # after vacuum, the name is reusable
        hs.create_index(df, hst.CoveringIndexConfig("lcIdx", ["c1"], ["c2"]))
        assert hs._manager.get_index("lcIdx").state == "ACTIVE"

    def test_create_duplicate_fails(self, session, hs, sample_parquet):
        from hyperspace_tpu.actions.base import HyperspaceActionException

        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("dupIdx", ["c1"], ["c2"]))
        with pytest.raises(HyperspaceActionException):
            hs.create_index(df, hst.CoveringIndexConfig("dupIdx", ["c1"], ["c2"]))

    def test_deleted_index_not_applied(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("delIdx", ["c1"], ["c2"]))
        hs.delete_index("delIdx")
        session.enable_hyperspace()
        plan = df.filter(hst.col("c1") == 7).select("c2").optimized_plan()
        assert not any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))

    def test_indexes_listing(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("idxA", ["c1"], ["c2"]))
        hs.create_index(df, hst.CoveringIndexConfig("idxB", ["c2"], ["c3"]))
        listing = hs.indexes()
        assert set(listing["name"]) == {"idxA", "idxB"}
        assert set(listing["state"]) == {"ACTIVE"}

    def test_index_stats_extended(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("statIdx", ["c1"], ["c2"]))
        stats = hs.index("statIdx")
        assert stats["numIndexFiles"] > 0
        assert stats["sizeInBytes"] > 0


class TestCoveringIndexData:
    def test_index_rows_match_source(self, session, hs, sample_parquet):
        """Row parity vs host oracle (the pandas/duckdb-oracle pattern from
        SURVEY.md §7 stage 4)."""
        import pyarrow.dataset as pads

        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("parityIdx", ["c1"], ["c2"]))
        entry = hs._manager.get_index("parityIdx")
        index_table = pads.dataset(entry.content.files, format="parquet").to_table()
        source = pads.dataset(
            [fi.name for fi in df.plan.relation.all_file_infos()], format="parquet"
        ).to_table(columns=["c1", "c2"])
        assert index_table.num_rows == source.num_rows
        a = np.sort(index_table.column("c1").to_numpy(), kind="stable")
        b = np.sort(source.column("c1").to_numpy(), kind="stable")
        np.testing.assert_array_equal(a, b)

    def test_buckets_are_sorted_and_hash_consistent(self, session, hs, sample_parquet):
        import pyarrow.parquet as pq

        from hyperspace_tpu.indexes.covering import bucket_of_file
        from hyperspace_tpu.ops.hashing import bucket_of_literals

        session.conf.set(hst.keys.NUM_BUCKETS, 8)
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("sortedIdx", ["c1"], ["c2"]))
        entry = hs._manager.get_index("sortedIdx")
        for f in entry.content.files:
            b = bucket_of_file(f)
            assert b is not None and 0 <= b < 8
            vals = pq.read_table(f).column("c1").to_numpy()
            assert np.all(np.diff(vals) >= 0), f"bucket {b} not sorted"
            for v in np.unique(vals):
                assert bucket_of_literals([v], 8) == b


class TestColumnPruning:
    """Column pruning pushes required columns to the scans so the join rule
    sees minimal per-side requirements (Catalyst's ColumnPruning runs before
    the reference's rules; ref: JoinIndexRule.scala:419-448)."""

    def test_self_join_over_wide_table_uses_index(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("wideJoinIdx", ["c2"], ["c1"]))
        q = df.join(df, on=["c2"]).select("c1")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True)), plan.pretty()
        assert_batches_equal(q.collect(), baseline)

    def test_right_side_duplicate_column_survives_pruning(self, session, hs, sample_parquet):
        """Selecting a '#r'-renamed right-side column must keep working when
        pruning drops the other side's duplicate."""
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("dupJoinIdx", ["c1"], ["c3"]))
        q = df.join(df, on=["c1"]).select("c3#r")
        baseline = q.collect()
        session.enable_hyperspace()
        assert_batches_equal(q.collect(), baseline)

    def test_filter_over_computed_column_still_rewrites_interior(self, session, hs, sample_parquet):
        """A filter over a computed column pins the chain top (it cannot move
        below the Compute), but the interior Filter->Scan must still rewrite
        to the index — the optimizer's chain-top shortcut must not skip it."""
        from hyperspace_tpu.plan import logical as L
        from hyperspace_tpu.plan.dataframe import DataFrame

        hs.create_index(
            session.read_parquet(sample_parquet),
            hst.CoveringIndexConfig("computedIdx", ["c1"], ["c2", "c3", "c4"]),
        )
        session.enable_hyperspace()
        df = session.read_parquet(sample_parquet).filter(hst.col("c1") == 7)
        computed = DataFrame(
            L.Compute([("dbl", hst.col("c2") * 2)], df.plan), session
        ).filter(hst.col("dbl") > 100).select("dbl")
        plan = computed.optimized_plan()
        assert any(
            isinstance(p, L.IndexScan) for p in L.collect(plan, lambda x: True)
        ), plan.pretty()
        on = np.sort(computed.collect()["dbl"])
        session.disable_hyperspace()
        off = np.sort(computed.collect()["dbl"])
        assert np.array_equal(on, off)

    def test_no_rewrite_returns_untouched_plan(self, session, hs, sample_parquet):
        df = session.read_parquet(sample_parquet)
        hs.create_index(df, hst.CoveringIndexConfig("unusedIdx", ["c1"], ["c2"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("c3") > 100.0)
        text = hs.explain(q, mode="console")
        assert "<----" not in text  # no spurious plan diff when nothing applied


def test_pushed_conjunct_keeps_single_row_cross_join(session, tmp_path):
    """A single-row derived table that gets a WHERE conjunct pushed onto it
    (wrapping it in Filter) must still cross-join via the single-row path
    (code-review regression: _is_single_row must unwrap Filter)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path / "t1"
    root.mkdir()
    pq.write_table(
        pa.table({"k": np.arange(10, dtype=np.int64), "x": np.arange(10, dtype=np.int64) * 2}),
        root / "p.parquet",
    )
    session.read_parquet(str(root)).create_or_replace_temp_view("tt")
    got = session.sql(
        "SELECT k FROM tt, (SELECT max(x) AS m FROM tt) s WHERE s.m > 0 AND tt.k < s.m"
    ).collect()
    assert sorted(got["k"].tolist()) == list(range(10))
