"""Real TPC-DS v1.4 query texts through the SQL front-end.

The reference's gold standard runs the actual q1-q99 texts
(ref: goldstandard/PlanStabilitySuite.scala:83-290, query files under
src/test/resources/tpcds/queries). This suite parses those same texts with
the framework's SQL dialect, plans them onto the IR, checks

  - hyperspace-on results equal hyperspace-off results (checkAnswer), and
  - the normalized optimized-plan text against approved files
    (tests/approved_plans/tpcds_sql/, regen with HS_GENERATE_GOLDEN=1).

Tables use the complete 24-table schema (tests/tpcds_schema.py). Query texts
are read from the reference checkout; the whole module skips when it is not
available.
"""

import glob
import os
import re
import zlib

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from tpcds_schema import TPCDS_SCHEMAS

QUERIES_DIR = "/root/reference/src/test/resources/tpcds/queries"
APPROVED_DIR = os.path.join(os.path.dirname(__file__), "approved_plans", "tpcds_sql")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(QUERIES_DIR), reason="reference TPC-DS query texts not available"
)

# Round 2 grew window functions, GROUP BY ROLLUP/grouping(), and
# INTERSECT/EXCEPT; round 3 added expression join keys (q2/q8), OR-factored
# disjunctive join predicates (q13/q48), EXISTS decorrelation
# (q10/q16/q35/q69/q94), and correlated-scalar decorrelation
# (q1/q6/q30/q32/q41/q81/q92) — ALL 103 of the reference's query texts now
# plan, execute, and hold approved plans (the reference's own gold standard:
# goldstandard/PlanStabilitySuite.scala with 103 approved-plans entries).


def _all_query_names():
    files = glob.glob(os.path.join(QUERIES_DIR, "q*.sql"))
    return sorted(
        (os.path.basename(f)[:-4] for f in files),
        key=lambda s: (int(re.search(r"\d+", s).group()), s),
    )


EXPRESSIBLE = _all_query_names() if os.path.isdir(QUERIES_DIR) else []


def _query_text(qname):
    with open(os.path.join(QUERIES_DIR, f"{qname}.sql")) as f:
        return f.read()


INDEXES = [
    ("store_sales", "ss_item", ["ss_item_sk"], ["ss_ext_sales_price", "ss_sold_date_sk"]),
    ("store_sales", "ss_date", ["ss_sold_date_sk"], ["ss_item_sk", "ss_ext_sales_price", "ss_quantity"]),
    ("store_sales", "ss_customer", ["ss_customer_sk"], ["ss_net_profit"]),
    ("catalog_sales", "cs_date", ["cs_sold_date_sk"], ["cs_item_sk", "cs_ext_sales_price"]),
    ("web_sales", "ws_date", ["ws_sold_date_sk"], ["ws_item_sk", "ws_ext_sales_price"]),
    ("item", "i_sk", ["i_item_sk"], ["i_brand_id", "i_category", "i_current_price"]),
    ("date_dim", "d_sk", ["d_date_sk"], ["d_year", "d_moy"]),
    ("customer", "c_sk", ["c_customer_sk"], ["c_current_addr_sk", "c_birth_year"]),
]


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpcds_sql"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    n = 40
    for name, schema in TPCDS_SCHEMAS.items():
        rng = np.random.default_rng(zlib.crc32(name.encode()))
        cols = {}
        for cname, t in schema.items():
            if cname.endswith("_year"):
                cols[cname] = rng.integers(1998, 2003, n).astype(np.int64)
            elif cname.endswith(("_moy", "_month_seq")):
                cols[cname] = rng.integers(1, 13, n).astype(np.int64)
            elif t == "I":
                # near-unique surrogate keys keep tiny-data joins ~1:1 (real
                # TPC-DS keys are unique; low cardinality would explode the
                # multi-way CTE self-joins of q4/q11/q31)
                cols[cname] = rng.integers(0, n, n).astype(np.int64)
            elif t == "F":
                cols[cname] = np.round(rng.uniform(0, 100, n), 2)
            elif t == "D":
                cols[cname] = np.datetime64("1998-01-01") + rng.integers(0, 1800, n).astype(
                    "timedelta64[D]"
                )
            elif cname.endswith("_id"):
                # business ids are UNIQUE in real TPC-DS data; collisions here
                # make the q4/q11/q31 CTE self-join chains explode
                # multiplicatively (observed 9.6M rows from 40-row tables)
                cols[cname] = np.array([f"{cname[:6]}_{i:05d}" for i in rng.permutation(n)])
            else:
                cols[cname] = np.array([f"{cname[:6]}_{v}" for v in rng.integers(0, n, n)])
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(pa.table(cols), os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
    for table, idx_name, indexed, included in INDEXES:
        hs.create_index(
            sess._temp_views[table], hst.CoveringIndexConfig(idx_name, indexed, included)
        )
    sess.enable_hyperspace()
    yield sess, root
    hst.set_session(None)


def _normalize(text, root):
    return text.replace(root, "<TPCDS>")


def _rows(batch):
    def norm(v):
        # one totally-ordered domain: NaN == NaN, NULLs sortable, every
        # value stringified (a rollup NULL-filled column mixes types)
        if v is None:
            return "\x00NULL"
        if isinstance(v, float) and v != v:
            return "NaN"
        return str(v)

    cols = sorted(batch.keys())
    if not cols:
        return []
    return sorted(
        tuple(norm(v) for v in row) for row in zip(*[batch[k].tolist() for k in cols])
    )


@pytest.mark.parametrize("qname", EXPRESSIBLE)
def test_query_plans_and_answers(tpcds, qname):
    sess, root = tpcds
    q = sess.sql(_query_text(qname))

    plan_text = _normalize(q.optimized_plan().pretty(), root)
    path = os.path.join(APPROVED_DIR, f"{qname}.txt")
    if GENERATE:
        os.makedirs(APPROVED_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(plan_text)
    else:
        with open(path) as f:
            assert plan_text == f.read(), (
                f"plan for {qname} changed; review and regen with HS_GENERATE_GOLDEN=1"
            )

    on = q.collect()
    sess.disable_hyperspace()
    try:
        off = q.collect()
    finally:
        sess.enable_hyperspace()
    assert sorted(on.keys()) == sorted(off.keys()), qname
    assert _rows(on) == _rows(off), f"{qname}: results differ with hyperspace on vs off"


def test_full_gold_standard_parity():
    """The ratchet: every one of the reference's 103 query texts is
    expressible and has an approved plan."""
    if os.path.isdir(QUERIES_DIR):
        assert len(EXPRESSIBLE) == 103
        missing = [
            q
            for q in EXPRESSIBLE
            if not os.path.exists(os.path.join(APPROVED_DIR, f"{q}.txt"))
        ]
        assert not missing, f"queries without approved plans: {missing}"
