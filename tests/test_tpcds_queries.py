"""Real TPC-DS v1.4 query texts through the SQL front-end.

The reference's gold standard runs the actual q1-q99 texts
(ref: goldstandard/PlanStabilitySuite.scala:83-290, query files under
src/test/resources/tpcds/queries). This suite parses those same texts with
the framework's SQL dialect, plans them onto the IR, checks

  - hyperspace-on results equal hyperspace-off results (checkAnswer), and
  - the normalized optimized-plan text against approved files
    (tests/approved_plans/tpcds_sql/, regen with HS_GENERATE_GOLDEN=1).

Tables use the complete 24-table schema (tests/tpcds_schema.py). Query texts
are read from the reference checkout; the whole module skips when it is not
available.
"""

import glob
import os
import re
import zlib

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from tpcds_schema import TPCDS_SCHEMAS

QUERIES_DIR = "/root/reference/src/test/resources/tpcds/queries"
APPROVED_DIR = os.path.join(os.path.dirname(__file__), "approved_plans", "tpcds_sql")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(QUERIES_DIR), reason="reference TPC-DS query texts not available"
)

# Round 2 grew window functions, GROUP BY ROLLUP/grouping(), and
# INTERSECT/EXCEPT; round 3 added expression join keys (q2/q8), OR-factored
# disjunctive join predicates (q13/q48), EXISTS decorrelation
# (q10/q16/q35/q69/q94), and correlated-scalar decorrelation
# (q1/q6/q30/q32/q41/q81/q92) — ALL 103 of the reference's query texts now
# plan, execute, and hold approved plans (the reference's own gold standard:
# goldstandard/PlanStabilitySuite.scala with 103 approved-plans entries).


def _all_query_names():
    files = glob.glob(os.path.join(QUERIES_DIR, "q*.sql"))
    return sorted(
        (os.path.basename(f)[:-4] for f in files),
        key=lambda s: (int(re.search(r"\d+", s).group()), s),
    )


EXPRESSIBLE = _all_query_names() if os.path.isdir(QUERIES_DIR) else []


def _query_text(qname):
    with open(os.path.join(QUERIES_DIR, f"{qname}.sql")) as f:
        return f.read()


# Wide vertical slices so the join/filter rules actually fire on the query
# texts (an index must cover every column its side contributes,
# ref: JoinIndexRule.scala:419-448); the dispatch goldens record which of
# the 103 rewrite and which physical path each takes
INDEXES = [
    ("store_sales", "ss_date", ["ss_sold_date_sk"],
     ["ss_item_sk", "ss_customer_sk", "ss_store_sk", "ss_cdemo_sk",
      "ss_hdemo_sk", "ss_addr_sk", "ss_promo_sk", "ss_ticket_number",
      "ss_quantity", "ss_sales_price", "ss_ext_sales_price",
      "ss_ext_discount_amt", "ss_wholesale_cost", "ss_list_price",
      "ss_ext_list_price", "ss_ext_wholesale_cost", "ss_coupon_amt",
      "ss_ext_tax", "ss_net_paid", "ss_net_paid_inc_tax", "ss_net_profit"]),
    ("store_sales", "ss_item", ["ss_item_sk"],
     ["ss_sold_date_sk", "ss_customer_sk", "ss_store_sk", "ss_ticket_number",
      "ss_quantity", "ss_sales_price", "ss_ext_sales_price", "ss_net_profit",
      "ss_net_paid", "ss_wholesale_cost"]),
    ("store_sales", "ss_customer", ["ss_customer_sk"],
     ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_ticket_number",
      "ss_quantity", "ss_sales_price", "ss_ext_sales_price", "ss_net_profit"]),
    ("catalog_sales", "cs_date", ["cs_sold_date_sk"],
     ["cs_item_sk", "cs_bill_customer_sk", "cs_ship_customer_sk",
      "cs_order_number", "cs_quantity", "cs_list_price", "cs_sales_price",
      "cs_ext_sales_price", "cs_ext_discount_amt", "cs_ext_list_price",
      "cs_wholesale_cost", "cs_coupon_amt", "cs_net_profit", "cs_net_paid",
      "cs_warehouse_sk", "cs_promo_sk", "cs_call_center_sk",
      "cs_ship_mode_sk", "cs_ship_date_sk", "cs_ship_addr_sk",
      "cs_bill_cdemo_sk", "cs_bill_hdemo_sk"]),
    ("web_sales", "ws_date", ["ws_sold_date_sk"],
     ["ws_item_sk", "ws_bill_customer_sk", "ws_ship_customer_sk",
      "ws_order_number", "ws_quantity", "ws_list_price", "ws_sales_price",
      "ws_ext_sales_price", "ws_ext_discount_amt", "ws_ext_list_price",
      "ws_wholesale_cost", "ws_net_profit", "ws_net_paid",
      "ws_warehouse_sk", "ws_promo_sk", "ws_web_site_sk", "ws_web_page_sk",
      "ws_ship_addr_sk", "ws_bill_addr_sk"]),
    ("item", "i_sk", ["i_item_sk"],
     ["i_item_id", "i_item_desc", "i_brand_id", "i_brand", "i_class_id",
      "i_class", "i_category_id", "i_category", "i_manufact_id",
      "i_manufact", "i_current_price", "i_color", "i_units", "i_size",
      "i_manager_id", "i_product_name"]),
    ("date_dim", "d_sk", ["d_date_sk"],
     ["d_date", "d_year", "d_moy", "d_dom", "d_qoy", "d_dow", "d_month_seq",
      "d_week_seq", "d_quarter_name", "d_day_name", "d_date_id"]),
    ("customer", "c_sk", ["c_customer_sk"],
     ["c_customer_id", "c_first_name", "c_last_name", "c_salutation",
      "c_preferred_cust_flag", "c_current_addr_sk", "c_current_cdemo_sk",
      "c_current_hdemo_sk", "c_birth_country", "c_birth_year",
      "c_birth_month", "c_birth_day", "c_first_sales_date_sk",
      "c_first_shipto_date_sk", "c_email_address", "c_login"]),
]


def _wide(table, keyed):
    """Every non-key column as an included column — the covering-index
    shape the reference's own suites build for star joins (an index must
    cover every column its side contributes, JoinIndexRule.scala:419-448)."""
    return [c for c in TPCDS_SCHEMAS[table] if c not in keyed]


# Round-5 leverage expansion, driven by the whyNot sweep over the 103 texts
# (benchmarks/tpcds_whynot.py — the CandidateIndexAnalyzer.scala:29-346
# workflow): every fact-table FK used as a join key gets a bucketed slice,
# the returns tables join their sales counterparts on composite
# (item, ticket/order) keys, and every dimension is covered on its
# surrogate key.
_KEYED = [
    # store_sales FK slices + the returns composite
    ("store_sales", "ss_item_ticket", ["ss_item_sk", "ss_ticket_number"]),
    ("store_sales", "ss_cdemo", ["ss_cdemo_sk"]),
    ("store_sales", "ss_hdemo", ["ss_hdemo_sk"]),
    ("store_sales", "ss_addr", ["ss_addr_sk"]),
    ("store_sales", "ss_store", ["ss_store_sk"]),
    ("store_sales", "ss_promo", ["ss_promo_sk"]),
    # catalog_sales
    ("catalog_sales", "cs_item", ["cs_item_sk"]),
    ("catalog_sales", "cs_customer", ["cs_bill_customer_sk"]),
    ("catalog_sales", "cs_item_order", ["cs_item_sk", "cs_order_number"]),
    # web_sales
    ("web_sales", "ws_item", ["ws_item_sk"]),
    ("web_sales", "ws_customer", ["ws_bill_customer_sk"]),
    ("web_sales", "ws_item_order", ["ws_item_sk", "ws_order_number"]),
    ("web_sales", "ws_order", ["ws_order_number"]),
    # returns tables
    ("store_returns", "sr_date", ["sr_returned_date_sk"]),
    ("store_returns", "sr_item_ticket", ["sr_item_sk", "sr_ticket_number"]),
    ("store_returns", "sr_item", ["sr_item_sk"]),
    ("store_returns", "sr_customer", ["sr_customer_sk"]),
    ("catalog_returns", "cr_date", ["cr_returned_date_sk"]),
    ("catalog_returns", "cr_item_order", ["cr_item_sk", "cr_order_number"]),
    ("catalog_returns", "cr_item", ["cr_item_sk"]),
    ("web_returns", "wr_date", ["wr_returned_date_sk"]),
    ("web_returns", "wr_item_order", ["wr_item_sk", "wr_order_number"]),
    ("web_returns", "wr_order", ["wr_order_number"]),
    # inventory
    ("inventory", "inv_date", ["inv_date_sk"]),
    ("inventory", "inv_item", ["inv_item_sk"]),
    # dimensions on their surrogate keys
    ("customer_address", "ca_sk", ["ca_address_sk"]),
    ("customer_demographics", "cd_sk", ["cd_demo_sk"]),
    ("household_demographics", "hd_sk", ["hd_demo_sk"]),
    ("store", "s_sk", ["s_store_sk"]),
    ("promotion", "p_sk", ["p_promo_sk"]),
    ("warehouse", "w_sk", ["w_warehouse_sk"]),
    ("time_dim", "t_sk", ["t_time_sk"]),
    ("ship_mode", "sm_sk", ["sm_ship_mode_sk"]),
    ("reason", "r_sk", ["r_reason_sk"]),
    ("income_band", "ib_sk", ["ib_income_band_sk"]),
    ("web_site", "web_sk", ["web_site_sk"]),
    ("web_page", "wp_sk", ["wp_web_page_sk"]),
    ("call_center", "cc_sk", ["cc_call_center_sk"]),
    ("catalog_page", "cp_sk", ["cp_catalog_page_sk"]),
    # second sweep iteration: the 26 remaining non-rewriters' actual join
    # keys (3-col store/returns composites q17/q25/q29/q50, the
    # sr<->cs customer+item bridge, ship/warehouse/time FKs q62/q66/q99,
    # customer-side current_*_sk chains q84/q85, cs demographics q18/q26)
    ("store_sales", "ss_cust_item_ticket",
     ["ss_customer_sk", "ss_item_sk", "ss_ticket_number"]),
    ("store_sales", "ss_time", ["ss_sold_time_sk"]),
    ("store_returns", "sr_cust_item_ticket",
     ["sr_customer_sk", "sr_item_sk", "sr_ticket_number"]),
    ("store_returns", "sr_cust_item", ["sr_customer_sk", "sr_item_sk"]),
    ("store_returns", "sr_cdemo", ["sr_cdemo_sk"]),
    ("store_returns", "sr_reason", ["sr_reason_sk"]),
    ("catalog_sales", "cs_cdemo", ["cs_bill_cdemo_sk"]),
    ("catalog_sales", "cs_cust_item", ["cs_bill_customer_sk", "cs_item_sk"]),
    ("catalog_sales", "cs_warehouse", ["cs_warehouse_sk"]),
    ("catalog_sales", "cs_shipmode", ["cs_ship_mode_sk"]),
    ("catalog_sales", "cs_time", ["cs_sold_time_sk"]),
    ("catalog_sales", "cs_shipdate", ["cs_ship_date_sk"]),
    ("catalog_sales", "cs_callcenter", ["cs_call_center_sk"]),
    ("web_sales", "ws_warehouse", ["ws_warehouse_sk"]),
    ("web_sales", "ws_shipmode", ["ws_ship_mode_sk"]),
    ("web_sales", "ws_website", ["ws_web_site_sk"]),
    ("web_sales", "ws_shipdate", ["ws_ship_date_sk"]),
    ("web_sales", "ws_time", ["ws_sold_time_sk"]),
    ("web_sales", "ws_shipaddr", ["ws_ship_addr_sk"]),
    ("web_sales", "ws_webpage", ["ws_web_page_sk"]),
    ("inventory", "inv_wh", ["inv_warehouse_sk"]),
    ("customer", "c_addr", ["c_current_addr_sk"]),
    ("customer", "c_cdemo", ["c_current_cdemo_sk"]),
    ("customer", "c_hdemo", ["c_current_hdemo_sk"]),
    ("household_demographics", "hd_ib", ["hd_income_band_sk"]),
    # third iteration: q90 (ws ship-demographics/time/page legs) and q91
    # (cr call-center + returning-customer legs)
    ("web_sales", "ws_shiphdemo", ["ws_ship_hdemo_sk"]),
    ("catalog_returns", "cr_callcenter", ["cr_call_center_sk"]),
    ("catalog_returns", "cr_ret_customer", ["cr_returning_customer_sk"]),
]
INDEXES = INDEXES + [(t, n, k, _wide(t, k)) for t, n, k in _KEYED]


# Queries whose predicate conjunctions the small shaped fixture cannot
# populate (multi-channel revenue-band/self-intersection shapes); tracked so
# they can only shrink. Everything else MUST return rows — an empty result
# makes the on/off parity check vacuous.
EMPTY_OK = {
    "q14b", "q23b", "q24b", "q31", "q39b", "q54", "q58", "q60", "q64",
    "q72", "q83", "q85", "q91",
}


@pytest.fixture(scope="module")
def tpcds(tmp_path_factory):
    from tpcds_data import arrow_tables

    root = str(tmp_path_factory.mktemp("tpcds_sql"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    for name, table in arrow_tables().items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(table, os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
    for table, idx_name, indexed, included in INDEXES:
        hs.create_index(
            sess._temp_views[table], hst.CoveringIndexConfig(idx_name, indexed, included)
        )
    sess.enable_hyperspace()
    yield sess, root
    hst.set_session(None)


def _normalize(text, root):
    return text.replace(root, "<TPCDS>")


def _norm_key(v):
    # one totally-ordered domain: NaN == NaN, NULLs sortable, every value
    # stringified (a rollup NULL-filled column mixes types); floats at LOW
    # precision so summation-order noise cannot split sort keys
    if v is None:
        return "\x00NULL"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        return f"{v:.3g}"
    return str(v)


def _sorted_rows(batch):
    cols = sorted(batch.keys())
    if not cols:
        return []
    rows = list(zip(*[batch[k].tolist() for k in cols]))
    return sorted(rows, key=lambda r: tuple(_norm_key(v) for v in r))


def _rows_close(a, b):
    import math

    for x, y in zip(a, b):
        if isinstance(x, float) and isinstance(y, float):
            if x != x and y != y:
                continue
            if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-6):
                return False
        elif _norm_key(x) != _norm_key(y):
            return False
    return True


def _assert_rows_equal(on, off, qname):
    """Row-set equality with relative float tolerance: a bucketed (index)
    scan sums in a different order than a file scan, and float addition is
    not associative — string rounding alone straddles digit boundaries.
    Rows sort on LOW-precision keys, so rows tying at key precision are
    matched as a multiset (greedy) rather than pairwise — tie order is not
    deterministic across the two runs."""
    from itertools import groupby

    ron, roff = _sorted_rows(on), _sorted_rows(off)
    assert len(ron) == len(roff), f"{qname}: row count differs with hyperspace on vs off"

    def key(r):
        return tuple(_norm_key(v) for v in r)

    ga = {k: list(g) for k, g in groupby(ron, key)}
    gb = {k: list(g) for k, g in groupby(roff, key)}
    assert sorted(ga) == sorted(gb), f"{qname}: row keys differ with hyperspace on vs off"
    for k, rows_a in ga.items():
        rows_b = list(gb[k])
        assert len(rows_a) == len(rows_b), f"{qname}: tie-group size differs at {k}"
        for a in rows_a:
            hit = next((i for i, b in enumerate(rows_b) if _rows_close(a, b)), None)
            assert hit is not None, (
                f"{qname}: row {a} has no tolerant match with hyperspace on vs off"
            )
            rows_b.pop(hit)


@pytest.mark.parametrize("qname", EXPRESSIBLE)
def test_query_plans_and_answers(tpcds, qname):
    sess, root = tpcds
    q = sess.sql(_query_text(qname))

    plan_text = _normalize(q.optimized_plan().pretty(), root)
    path = os.path.join(APPROVED_DIR, f"{qname}.txt")
    if GENERATE:
        os.makedirs(APPROVED_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(plan_text)
    else:
        with open(path) as f:
            assert plan_text == f.read(), (
                f"plan for {qname} changed; review and regen with HS_GENERATE_GOLDEN=1"
            )

    on = q.collect()
    sess.disable_hyperspace()
    try:
        off = q.collect()
    finally:
        sess.enable_hyperspace()
    assert sorted(on.keys()) == sorted(off.keys()), qname
    _assert_rows_equal(on, off, qname)
    # the shaped fixture (tpcds_data.py) makes parity non-vacuous: outside
    # the EMPTY_OK allowlist a query MUST produce rows, and an allowlisted
    # query that starts producing rows must be removed (ratchet both ways)
    n_rows = len(next(iter(on.values()))) if on else 0
    if qname in EMPTY_OK:
        assert n_rows == 0, f"{qname} now returns rows; remove it from EMPTY_OK"
    else:
        assert n_rows > 0, f"{qname} returned no rows; fixture degraded"

    # physical-dispatch golden (ref: PlanStabilitySuite approves the
    # *executedPlan*, scala:83-290) — see test_tpch_queries.py
    from hyperspace_tpu.exec import device as D
    from hyperspace_tpu.exec import io as hs_io
    from hyperspace_tpu.exec import trace

    hs_io.clear_io_cache()
    D.clear_device_cache()
    sess.conf.set(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS, 0)
    try:
        with trace.recording() as events:
            q.collect()
    finally:
        sess.conf.unset(hst.keys.TPU_QUERY_DEVICE_MIN_ROWS)
    dispatch = trace.summarize(events)
    dpath = os.path.join(APPROVED_DIR, f"{qname}.dispatch.txt")
    if GENERATE:
        with open(dpath, "w") as f:
            f.write(dispatch)
    else:
        with open(dpath) as f:
            assert dispatch == f.read(), (
                f"physical dispatch for {qname} changed; review and regen "
                "with HS_GENERATE_GOLDEN=1"
            )


def test_full_gold_standard_parity():
    """The ratchet: every one of the reference's 103 query texts is
    expressible and has an approved plan."""
    if os.path.isdir(QUERIES_DIR):
        assert len(EXPRESSIBLE) == 103
        missing = [
            q
            for q in EXPRESSIBLE
            if not os.path.exists(os.path.join(APPROVED_DIR, f"{q}.txt"))
        ]
        assert not missing, f"queries without approved plans: {missing}"
