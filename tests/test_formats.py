"""Source-format parity tests: the reference's default source accepts
avro, csv, json, orc, parquet, text (ref: HS/util/HyperspaceConf.scala:94-99);
this suite covers the non-parquet formats end to end (index build, query
rewrite, data skipping)."""

import numpy as np
import pyarrow as pa
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.sources import formats as F


def _uses_index(plan):
    return any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda p: True))


def _sorted(batch):
    keys = [np.asarray(v).astype("U64") if v.dtype == object else v for v in reversed(list(batch.values()))]
    order = np.lexsort(keys)
    return {k: v[order] for k, v in batch.items()}


def assert_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    a, b = _sorted(a), _sorted(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"column {k}")


def _sample_table(n=600, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
            "s": np.array([f"s{i % 13}" for i in range(n)]),
        }
    )


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestOrc:
    @pytest.fixture()
    def orc_root(self, tmp_path):
        from pyarrow import orc

        t = _sample_table()
        root = tmp_path / "orc_data"
        root.mkdir()
        for i in range(3):
            orc.write_table(t.slice(i * 200, 200), str(root / f"part-{i:05d}.orc"))
        return str(root)

    def test_filter_index(self, session, hs, orc_root):
        df = session.read_orc(orc_root)
        baseline = df.filter(hst.col("k") == 7).select("v").collect()
        hs.create_index(df, hst.CoveringIndexConfig("orcIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 7).select("v")
        assert _uses_index(q.optimized_plan())
        assert_equal(q.collect(), baseline)

    def test_data_skipping(self, session, hs, orc_root):
        df = session.read_orc(orc_root)
        hs.create_index(df, hst.DataSkippingIndexConfig("orcSkip", hst.MinMaxSketch("v")))
        session.enable_hyperspace()
        q = df.filter(hst.col("v") < 0)  # nothing matches: all files pruned
        assert q.collect()["k"].shape[0] == 0

    def test_refresh_after_append(self, session, hs, orc_root, tmp_path):
        from pyarrow import orc

        df = session.read_orc(orc_root)
        hs.create_index(df, hst.CoveringIndexConfig("orcIdx", ["k"], ["v"]))
        orc.write_table(_sample_table(100, seed=99), orc_root + "/part-00099.orc")
        hs.refresh_index("orcIdx", "incremental")
        session.enable_hyperspace()
        df2 = session.read_orc(orc_root)
        q = df2.filter(hst.col("k") == 3).select("v")
        assert _uses_index(q.optimized_plan())
        session.disable_hyperspace()
        assert_equal(q.collect(), df2.filter(hst.col("k") == 3).select("v").collect())


class TestAvro:
    @pytest.fixture()
    def avro_root(self, tmp_path):
        from hyperspace_tpu.utils.avro import write_container

        schema = {
            "type": "record",
            "name": "row",
            "fields": [
                {"name": "k", "type": "long"},
                {"name": "v", "type": "long"},
                {"name": "s", "type": "string"},
            ],
        }
        t = _sample_table()
        root = tmp_path / "avro_data"
        root.mkdir()
        for i in range(3):
            part = t.slice(i * 200, 200).to_pylist()
            write_container(str(root / f"part-{i:05d}.avro"), schema, part)
        return str(root)

    def test_read(self, session, avro_root):
        got = session.read_avro(avro_root).collect()
        assert got["k"].shape[0] == 600
        assert set(got.keys()) == {"k", "v", "s"}

    def test_filter_index(self, session, hs, avro_root):
        df = session.read_avro(avro_root)
        baseline = df.filter(hst.col("k") == 11).select("v", "s").collect()
        hs.create_index(df, hst.CoveringIndexConfig("avroIdx", ["k"], ["v", "s"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 11).select("v", "s")
        assert _uses_index(q.optimized_plan())
        assert_equal(q.collect(), baseline)

    def test_signature_changes_on_append(self, session, avro_root):
        from hyperspace_tpu.utils.avro import write_container

        rel = session.read_avro(avro_root).plan.relation
        sig0 = rel.signature()
        schema = {
            "type": "record",
            "name": "row",
            "fields": [{"name": "k", "type": "long"}, {"name": "v", "type": "long"}, {"name": "s", "type": "string"}],
        }
        write_container(avro_root + "/part-00010.avro", schema, [{"k": 1, "v": 2, "s": "x"}])
        rel2 = session.read_avro(avro_root).plan.relation
        assert rel2.signature() != sig0


class TestText:
    @pytest.fixture()
    def text_root(self, tmp_path):
        root = tmp_path / "text_data"
        root.mkdir()
        lines = [f"line-{i % 20}" for i in range(400)]
        F.write_text(str(root / "part-00000.txt"), lines[:200])
        F.write_text(str(root / "part-00001.txt"), lines[200:])
        return str(root)

    def test_read_value_column(self, session, text_root):
        got = session.read_text(text_root).collect()
        assert list(got.keys()) == [F.TEXT_COLUMN]
        assert got[F.TEXT_COLUMN].shape[0] == 400

    def test_filter_index(self, session, hs, text_root):
        df = session.read_text(text_root)
        baseline = df.filter(hst.col("value") == "line-3").collect()
        hs.create_index(df, hst.CoveringIndexConfig("textIdx", ["value"], []))
        session.enable_hyperspace()
        q = df.filter(hst.col("value") == "line-3")
        assert _uses_index(q.optimized_plan())
        assert_equal(q.collect(), baseline)

    def test_crlf_and_trailing_newline(self, tmp_path):
        p = str(tmp_path / "f.txt")
        with open(p, "wb") as f:
            f.write(b"a\r\nb\nc\n")
        t = F.read_text_table(p)
        assert t.column("value").to_pylist() == ["a", "b", "c"]


class TestFormatHelpers:
    def test_open_dataset_unifies_schemas(self, tmp_path):
        from hyperspace_tpu.utils.avro import write_container

        schema = {"type": "record", "name": "r", "fields": [{"name": "a", "type": "long"}]}
        write_container(str(tmp_path / "x.avro"), schema, [{"a": 1}, {"a": 2}])
        ds = F.open_dataset([str(tmp_path / "x.avro")], "avro")
        assert ds.to_table().column("a").to_pylist() == [1, 2]

    def test_count_rows(self, tmp_path):
        F.write_text(str(tmp_path / "t.txt"), ["x", "y"])
        assert F.count_rows(str(tmp_path / "t.txt"), "text") == 2

    def test_unsupported_format_raises(self):
        with pytest.raises(ValueError):
            F.open_dataset(["f.bin"], "binary")

    def test_avro_union_and_nested_types(self):
        arrow = F._avro_to_arrow_type(["null", "string"])
        assert arrow == pa.string()
        arrow = F._avro_to_arrow_type({"type": "array", "items": "long"})
        assert arrow == pa.list_(pa.int64())

    def test_avro_schema_evolution_null_fills(self, tmp_path):
        from hyperspace_tpu.utils.avro import write_container

        s1 = {"type": "record", "name": "r", "fields": [{"name": "a", "type": "long"}]}
        s2 = {
            "type": "record",
            "name": "r",
            "fields": [{"name": "a", "type": "long"}, {"name": "b", "type": "string"}],
        }
        f1, f2 = str(tmp_path / "f1.avro"), str(tmp_path / "f2.avro")
        write_container(f1, s1, [{"a": 1}])
        write_container(f2, s2, [{"a": 2, "b": "x"}])
        t = F.open_dataset([f1, f2], "avro").to_table()
        assert t.column("a").to_pylist() == [1, 2]
        assert t.column("b").to_pylist() == [None, "x"]
        # column pruning on the file missing the column null-fills too
        t1 = F.read_avro_table(f1, ["a", "b"])
        assert t1.column("b").to_pylist() == [None]

    def test_avro_count_without_decoding(self, tmp_path):
        from hyperspace_tpu.utils.avro import count_records, write_container

        s = {"type": "record", "name": "r", "fields": [{"name": "a", "type": "long"}]}
        p = str(tmp_path / "f.avro")
        write_container(p, s, [{"a": i} for i in range(137)])
        assert count_records(p) == 137
        assert F.count_rows(p, "avro") == 137

    def test_text_count_rows_no_trailing_newline(self, tmp_path):
        p = str(tmp_path / "f.txt")
        with open(p, "wb") as f:
            f.write(b"a\nb\nc")  # no trailing newline
        assert F.count_rows(p, "text") == 3

    def test_avro_schema_without_decoding_records(self, tmp_path):
        from hyperspace_tpu.utils.avro import read_schema, write_container

        s = {"type": "record", "name": "r", "fields": [{"name": "a", "type": "long"}]}
        p = str(tmp_path / "f.avro")
        write_container(p, s, [{"a": i} for i in range(100)])
        assert read_schema(p) == s
        assert F.read_format_schema([p], "avro") == pa.schema([pa.field("a", pa.int64())])
        assert F.read_format_schema(["ignored"], "text").names == [F.TEXT_COLUMN]


class TestCsvOptions:
    def test_delimiter_and_header(self, session, tmp_path):
        root = tmp_path / "csvopts"
        root.mkdir()
        (root / "p.csv").write_text("k;v\n1;10\n2;20\n")
        got = session.read_csv(str(root), delimiter=";").collect()
        assert got["k"].tolist() == [1, 2] and got["v"].tolist() == [10, 20]

    def test_headerless(self, session, tmp_path):
        root = tmp_path / "csvnh"
        root.mkdir()
        (root / "p.csv").write_text("1,10\n2,20\n")
        got = session.read_csv(str(root), header=False).collect()
        assert sorted(got.keys()) == ["f0", "f1"]
        assert got["f0"].tolist() == [1, 2]

    def test_options_survive_indexing_and_skipping(self, session, tmp_path):
        import hyperspace_tpu as hst

        root = tmp_path / "csvidx"
        root.mkdir()
        for i in range(3):
            lines = "\n".join(f"{i * 100 + j};{j}" for j in range(100))
            (root / f"p{i}.csv").write_text("k;v\n" + lines + "\n")
        hs = hst.Hyperspace(session)
        df = session.read_csv(str(root), delimiter=";")
        hs.create_index(df, hst.DataSkippingIndexConfig("csvSkip", hst.MinMaxSketch("k")))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 105).select("v")
        from hyperspace_tpu.plan import logical as L

        plan = q.optimized_plan()
        fscans = L.collect(plan, lambda p: isinstance(p, L.FileScan))
        assert fscans and len(fscans[0].files) == 1  # pruned to one file
        assert fscans[0].format_options == {"delimiter": ";"}
        got = q.collect()
        assert got["v"].tolist() == [5]
