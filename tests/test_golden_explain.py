"""Golden-file tests for explain / whyNot output stability
(ref: src/test/resources/expected/spark-3.1/{filter,selfJoin,whyNot_allIndex,
whyNot_indexName}.txt loaded by HyperspaceSuite.getExpectedResult,
index/HyperspaceSuite.scala:124-128, used in ExplainTest.scala).

Regenerate with ``HS_GENERATE_GOLDEN=1 python -m pytest tests/test_golden_explain.py``
(the reference's SPARK_GENERATE_GOLDEN_FILES mechanism,
goldstandard/PlanStabilitySuite.scala:83-290).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"


def _normalize(text: str, roots) -> str:
    for i, root in enumerate(roots):
        text = text.replace(str(root), f"<ROOT{i}>")
    return text


def _check(name: str, text: str) -> None:
    path = os.path.join(GOLDEN_DIR, name)
    if GENERATE:
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
        return
    with open(path) as f:
        expected = f.read()
    assert text == expected, f"golden mismatch for {name}; regen with HS_GENERATE_GOLDEN=1"


@pytest.fixture()
def golden_env(tmp_path):
    """Deterministic dataset + indexes: fixed seed, fixed file layout."""
    rng = np.random.default_rng(12345)
    n = 1000
    table = pa.table(
        {
            "clicks": rng.integers(0, 100, n).astype(np.int64),
            "imprs": rng.integers(0, 1000, n).astype(np.int64),
            "score": np.round(rng.standard_normal(n), 6),
            "query": np.array([f"q{i % 23}" for i in range(n)]),
        }
    )
    data = tmp_path / "data"
    data.mkdir()
    for i in range(4):
        pq.write_table(table.slice(i * 250, 250), data / f"part-{i:05d}.parquet")

    sysp = tmp_path / "indexes"
    sysp.mkdir()
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: str(sysp), hst.keys.NUM_BUCKETS: 8})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(str(data))
    hs.create_index(df, hst.CoveringIndexConfig("filterIndex", ["clicks"], ["query"]))
    hs.create_index(df, hst.CoveringIndexConfig("joinIndex", ["imprs"], ["clicks"]))
    sess.enable_hyperspace()
    yield sess, hs, df, [tmp_path]
    hst.set_session(None)


def test_golden_explain_filter(golden_env):
    sess, hs, df, roots = golden_env
    q = df.filter(hst.col("clicks") == 7).select("query")
    _check("filter.txt", _normalize(hs.explain(q, verbose=True), roots))


def test_golden_explain_filter_console(golden_env):
    sess, hs, df, roots = golden_env
    q = df.filter(hst.col("clicks") == 7).select("query")
    _check("filter_console.txt", _normalize(hs.explain(q, mode="console"), roots))


def test_golden_explain_filter_html(golden_env):
    sess, hs, df, roots = golden_env
    q = df.filter(hst.col("clicks") == 7).select("query")
    _check("filter_html.txt", _normalize(hs.explain(q, mode="html"), roots))


def test_golden_explain_self_join(golden_env):
    sess, hs, df, roots = golden_env
    q = df.join(df, on=["imprs"]).select("clicks")
    _check("selfJoin.txt", _normalize(hs.explain(q, verbose=True), roots))


def test_golden_explain_subquery(golden_env):
    """(ref: src/test/resources/expected/spark-2.4/subquery.txt — index
    applied INSIDE the scalar subquery's plan)"""
    sess, hs, df, roots = golden_env
    scalar = df.filter(hst.col("clicks") == 3).limit(1).select("query").as_scalar()
    q = df.filter(hst.col("query") == scalar).select("imprs")
    _check("subquery.txt", _normalize(hs.explain(q, verbose=True), roots))


def test_golden_explain_self_join_iceberg(tmp_path):
    """(ref: src/test/resources/expected/spark-2.4/selfJoin_Iceberg.txt)"""
    from hyperspace_tpu.sources.iceberg import write_iceberg_table

    rng = np.random.default_rng(12345)
    n = 500
    table = pa.table(
        {
            "k": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.integers(0, 500, n).astype(np.int64),
        }
    )
    root = str(tmp_path / "ice")
    write_iceberg_table(table, root)
    sysp = tmp_path / "indexes"
    sysp.mkdir()
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: str(sysp), hst.keys.NUM_BUCKETS: 8})
    hst.set_session(sess)
    try:
        hs = hst.Hyperspace(sess)
        df = sess.read_iceberg(root)
        hs.create_index(df, hst.CoveringIndexConfig("iceJoinIndex", ["k"], ["v"]))
        sess.enable_hyperspace()
        q = df.join(df, on=["k"]).select("v")
        _check("selfJoin_Iceberg.txt", _normalize(hs.explain(q, verbose=True), [tmp_path]))
    finally:
        hst.set_session(None)


def test_golden_why_not_all_index(golden_env):
    sess, hs, df, roots = golden_env
    q = df.filter(hst.col("score") > 0).select("query")
    _check("whyNot_allIndex.txt", _normalize(hs.why_not(q), roots))


def test_golden_why_not_index_name(golden_env):
    sess, hs, df, roots = golden_env
    q = df.filter(hst.col("score") > 0).select("query")
    _check(
        "whyNot_indexName.txt",
        _normalize(hs.why_not(q, index_name="filterIndex", extended=True), roots),
    )


@pytest.fixture()
def priority_env(tmp_path):
    """Two indexes where the join rewrite (score 140) outranks an applicable
    filter rewrite (score 50) on the same scan — the filter index lands in
    whyNot's "applicable, but not applied due to priority" section
    (ref: CandidateIndexAnalyzer.scala:193-197)."""
    rng = np.random.default_rng(777)
    n = 800
    table = pa.table(
        {
            "clicks": rng.integers(0, 40, n).astype(np.int64),
            "imprs": rng.integers(0, 200, n).astype(np.int64),
        }
    )
    data = tmp_path / "pdata"
    data.mkdir()
    for i in range(4):
        pq.write_table(table.slice(i * 200, 200), data / f"part-{i:05d}.parquet")
    sysp = tmp_path / "indexes"
    sysp.mkdir()
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: str(sysp), hst.keys.NUM_BUCKETS: 8})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    df = sess.read_parquet(str(data))
    hs.create_index(df, hst.CoveringIndexConfig("fIdx", ["clicks"], ["imprs"]))
    hs.create_index(df, hst.CoveringIndexConfig("jIdx", ["imprs"], ["clicks"]))
    sess.enable_hyperspace()
    yield sess, hs, df, [tmp_path]
    hst.set_session(None)


def test_golden_why_not_priority_section(priority_env):
    sess, hs, df, roots = priority_env
    q = df.filter(hst.col("clicks") == 7).join(df, on=["imprs"]).select("clicks")
    report = _normalize(hs.why_not(q), roots)
    _check("whyNot_priority.txt", report)
    # structural guard independent of golden text: fIdx was applicable (its
    # rule's ranker picked it) but the join rewrite won the score race
    lines = report.splitlines()
    start = lines.index("Applicable indexes, but not applied due to priority:")
    assert "- fIdx" in lines[start + 1 : lines.index("", start)], report
    applied = lines.index("Applied indexes:")
    assert "- jIdx" in lines[applied + 1 : lines.index("", applied)], report


def test_golden_explain_bucket_pruned_filter(golden_env):
    """Bucket-pruned filter scan (ref: FilterIndexRule.scala:162-167
    useBucketSpec): the explain output must pin the pruned-bucket dispatch."""
    sess, hs, df, roots = golden_env
    sess.conf.set(hst.keys.FILTER_RULE_USE_BUCKET_SPEC, True)
    try:
        q = df.filter(hst.col("clicks") == 7).select("query")
        _check("filter_bucket_pruned.txt", _normalize(hs.explain(q, verbose=True), roots))
    finally:
        sess.conf.set(hst.keys.FILTER_RULE_USE_BUCKET_SPEC, False)


def test_golden_explain_hybrid_scan(golden_env, tmp_path):
    """Hybrid scan explain (ref: HybridScanSuite's BucketUnionExec
    assertions): index + appended source files merged via BucketUnion."""
    sess, hs, df, roots = golden_env
    # append one more file to the dataset AFTER the index was built
    rng = np.random.default_rng(54321)
    n = 100
    extra = pa.table(
        {
            "clicks": rng.integers(0, 100, n).astype(np.int64),
            "imprs": rng.integers(0, 1000, n).astype(np.int64),
            "score": np.round(rng.standard_normal(n), 6),
            "query": np.array([f"q{i % 23}" for i in range(n)]),
        }
    )
    data_dir = [p for p in roots[0].iterdir() if p.name == "data"][0]
    pq.write_table(extra, data_dir / "part-00004.parquet")
    sess.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    try:
        df2 = sess.read_parquet(str(data_dir))
        q = df2.filter(hst.col("clicks") == 7).select("query")
        _check("filter_hybrid_scan.txt", _normalize(hs.explain(q, verbose=True), roots))
    finally:
        sess.conf.set(hst.keys.HYBRID_SCAN_ENABLED, False)


def test_why_not_tags_do_not_leak_across_queries(priority_env):
    """Entries are shared via the TTL cache: analysis tags from one whyNot
    run must not bleed into the next (ref: CandidateIndexAnalyzer
    prepare/cleanupAnalysisTags, scala:64-80)."""
    sess, hs, df, roots = priority_env
    q1 = df.filter(hst.col("clicks") == 7).join(df, on=["imprs"]).select("clicks")
    r1 = hs.why_not(q1)
    lines = r1.splitlines()
    start = lines.index("Applicable indexes, but not applied due to priority:")
    assert "- fIdx" in lines[start + 1 : lines.index("", start)]
    # a pure filter query: fIdx simply APPLIES; no priority section entry,
    # and q1's join reasons must not reappear
    q2 = df.filter(hst.col("clicks") == 7).select("imprs")
    r2 = hs.why_not(q2)
    lines2 = r2.splitlines()
    start2 = lines2.index("Applicable indexes, but not applied due to priority:")
    section2 = lines2[start2 + 1 : lines2.index("", start2)]
    assert section2 == ["- No such index found."], r2
    assert "NOT_ALL_JOIN_COLS_INDEXED" not in r2, r2
    applied2 = lines2.index("Applied indexes:")
    assert "- fIdx" in lines2[applied2 + 1 : lines2.index("", applied2)], r2
