"""SLO-aware serving tests: cost-aware scheduler, tenant fairness, predicted
load shedding, eager queue expiry, and the version-branded result cache.

The scheduler is exercised as a pure policy object with injected clocks,
cost models, and burn-rate signals — no wall-clock sleeps, no worker threads
— then end-to-end through QueryServer against ``collect()`` ground truth.
The result-cache tests enforce the tentpole invariant directly: no test can
observe a result computed from a stale data version.
"""

import os
import time
from concurrent.futures import Future

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.obs.history import CostEstimate
from hyperspace_tpu.serving import (
    AdmissionController,
    AdmissionRejected,
    CostAwareScheduler,
    QueryServer,
    RequestTimeout,
    ResultCache,
    TokenBucket,
    classify_cost,
    plan_fingerprint,
    version_brand,
)
from hyperspace_tpu.serving.result_cache import atoms_imply, chain_atoms

pytestmark = pytest.mark.sched


class Item:
    """Minimal schedulable request double: tenant + optional deadline/class."""

    def __init__(self, tenant="default", cost_class=None, deadline=None, dead=False):
        self.tenant = tenant
        if cost_class is not None:
            self.cost_class = cost_class
        self.deadline = deadline
        self.future = Future()
        self.sched_charge = 0.0
        self._dead = dead

    def expired(self):
        return self._dead


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def simple(tmp_path):
    n = 500
    pq.write_table(
        pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "name": np.array([f"n{i % 11}" for i in range(n)]),
                "price": (np.arange(n, dtype=np.int64) * 7) % 100,
            }
        ),
        str(tmp_path / "t.parquet"),
    )
    sess = hst.Session()
    sess.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
    return sess


# --- cost classification -----------------------------------------------------


def test_classify_cost_thresholds():
    assert classify_cost(None, 0.05, 0.5, 0.3) == "unknown"
    low_conf = CostEstimate(latency_s=0.01, confidence=0.1, samples=2)
    assert classify_cost(low_conf, 0.05, 0.5, 0.3) == "unknown"
    fast = CostEstimate(latency_s=0.01, confidence=0.9, samples=50)
    assert classify_cost(fast, 0.05, 0.5, 0.3) == "interactive"
    mid = CostEstimate(latency_s=0.2, confidence=0.9, samples=50)
    assert classify_cost(mid, 0.05, 0.5, 0.3) == "standard"
    slow = CostEstimate(latency_s=2.0, confidence=0.9, samples=50)
    assert classify_cost(slow, 0.05, 0.5, 0.3) == "heavy"


def test_token_bucket_refill_with_injected_clock():
    clk = FakeClock()
    b = TokenBucket(rate=1.0, burst=2.0, clock=clk)
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()  # burst exhausted
    clk.t += 1.5  # refills 1.5 tokens
    assert b.try_acquire()
    assert not b.try_acquire()  # 0.5 < 1


# --- scheduler: priority and fairness ---------------------------------------


def test_interactive_dispatches_before_heavy_within_tenant():
    sched = CostAwareScheduler(depth=16, default_timeout=None)
    heavy = Item(cost_class="heavy")
    inter = Item(cost_class="interactive")
    std = Item(cost_class="standard")
    sched.submit(heavy)
    sched.submit(std)
    sched.submit(inter)
    order = [sched.take_nowait() for _ in range(3)]
    assert order == [inter, std, heavy]
    assert sched.take_nowait() is None


def test_flooding_tenant_cannot_starve_light_tenant():
    sched = CostAwareScheduler(depth=64, default_timeout=None)
    flood = [Item(tenant="flood", cost_class="standard") for _ in range(10)]
    light = [Item(tenant="light", cost_class="standard") for _ in range(2)]
    for it in flood:
        sched.submit(it)
    for it in light:
        sched.submit(it)
    # simulate the worker loop: dispatch, then report actual service seconds
    # (flood requests are 100x more expensive than light ones)
    positions = {}
    for i in range(12):
        it = sched.take_nowait()
        positions.setdefault(it.tenant, []).append(i)
        sched.observe_completion(it.tenant, 1.0 if it.tenant == "flood" else 0.01)
    # both light requests dispatch among the first three slots: after one
    # flood second is consumed, the light tenant's deficit dominates
    assert max(positions["light"]) <= 2, positions


def test_tenant_weights_bias_dispatch_share():
    sched = CostAwareScheduler(
        depth=64, default_timeout=None, tenant_weights={"gold": 3.0, "free": 1.0}
    )
    for _ in range(8):
        sched.submit(Item(tenant="gold", cost_class="standard"))
        sched.submit(Item(tenant="free", cost_class="standard"))
    gold_in_first_8 = 0
    for _ in range(8):
        it = sched.take_nowait()
        if it.tenant == "gold":
            gold_in_first_8 += 1
        sched.observe_completion(it.tenant, 1.0)  # equal actual cost
    # 3:1 weights => gold gets ~3/4 of the first window
    assert gold_in_first_8 >= 5, gold_in_first_8


def test_burn_rate_boost_and_deprioritization():
    burns = {"x": 0.0, "y": 3.0}
    sched = CostAwareScheduler(
        depth=16, default_timeout=None,
        burn_threshold=2.0, burn_factor=2.0,
        burn_rate_fn=lambda t: burns.get(t, 0.0),
    )
    sched.submit(Item(tenant="x", cost_class="standard"))
    sched.submit(Item(tenant="y", cost_class="standard"))
    # equal consumed work: without burn, alphabetical tie-break would pick x
    sched.observe_completion("x", 1.0)
    sched.observe_completion("y", 1.0)
    assert sched.take_nowait().tenant == "y"  # burning tenant boosted
    # x hogs the most work while y burns -> x's effective weight is halved
    sched.observe_completion("x", 1.0)
    st_x = sched._tenants["x"]
    st_y = sched._tenants["y"]
    assert sched._effective_weight(st_x) == pytest.approx(0.5)
    assert sched._effective_weight(st_y) == pytest.approx(2.0)


def test_idle_tenant_does_not_burst_on_wake():
    sched = CostAwareScheduler(depth=64, default_timeout=None)
    for _ in range(4):
        sched.submit(Item(tenant="busy", cost_class="standard"))
    it = sched.take_nowait()
    sched.observe_completion(it.tenant, 5.0)
    # a brand-new tenant wakes: its consumed floor is normalized to the
    # busiest active minimum, not zero-since-forever
    sched.submit(Item(tenant="fresh", cost_class="standard"))
    assert sched._tenants["fresh"].consumed >= sched._min_consumed_locked()


# --- scheduler: load shedding ------------------------------------------------


def test_shed_by_predicted_work_with_depth_fallback():
    confident = CostEstimate(latency_s=1.0, confidence=0.9, samples=50)
    sched = CostAwareScheduler(
        depth=100, default_timeout=None, max_queued_seconds=2.5,
        cost_fn=lambda item: confident,
    )
    sched.submit(Item())
    sched.submit(Item())
    with pytest.raises(AdmissionRejected):
        sched.submit(Item())  # 3.0s predicted > 2.5s bound
    assert sched.stats()["shed"] == {"predicted-work": 1}
    assert sched.stats()["queuedWorkSeconds"] == pytest.approx(2.0)

    # without a confident model the same bound degrades to depth-only
    blind = CostAwareScheduler(depth=2, default_timeout=None, max_queued_seconds=2.5)
    blind.submit(Item())
    blind.submit(Item())
    with pytest.raises(AdmissionRejected):
        blind.submit(Item())
    assert blind.stats()["shed"] == {"depth": 1}


def test_tenant_rate_limit_sheds_and_refills():
    clk = FakeClock()
    sched = CostAwareScheduler(
        depth=16, default_timeout=None, tenant_rate=1.0, tenant_burst=1.0, clock=clk
    )
    sched.submit(Item(tenant="spammer"))
    with pytest.raises(AdmissionRejected):
        sched.submit(Item(tenant="spammer"))
    assert sched.stats()["shed"] == {"rate": 1}
    clk.t += 1.0
    sched.submit(Item(tenant="spammer"))  # bucket refilled
    assert sched.stats()["submitted"] == 2


# --- eager queue expiry ------------------------------------------------------


def test_full_queue_of_expired_requests_admits_new_work():
    adm = AdmissionController(depth=2, default_timeout=None)
    sealed = []
    adm.on_expired = sealed.append
    dead = [Item(dead=True), Item(dead=True)]
    for it in dead:
        adm.submit(it)
    live = Item()
    adm.submit(live)  # sweeps the dead entries instead of rejecting
    assert adm.stats()["timeouts"] == 2
    assert adm.stats()["rejected"] == 0
    assert sealed == dead
    for it in dead:
        with pytest.raises(RequestTimeout):
            it.future.result(timeout=0)
    assert adm.take_nowait() is live


def test_expire_is_exactly_once():
    adm = AdmissionController(depth=2, default_timeout=None)
    sealed = []
    adm.on_expired = sealed.append
    it = Item(dead=True)
    it.future = Future()
    assert adm.expire(it) is True
    assert adm.expire(it) is False  # future already resolved
    assert adm.stats()["timeouts"] == 1
    assert len(sealed) == 1


def test_plain_items_without_futures_are_never_purged():
    adm = AdmissionController(depth=2, default_timeout=None)
    adm.submit("a")
    adm.submit("b")
    with pytest.raises(AdmissionRejected):
        adm.submit("c")  # strings carry no deadline: queue is genuinely full


def test_scheduler_sweeps_expired_on_submit():
    sched = CostAwareScheduler(depth=2, default_timeout=None)
    dead = [Item(dead=True), Item(dead=True)]
    for it in dead:
        sched.submit(it)
    live = Item()
    sched.submit(live)  # depth reached, but both queued entries are dead
    assert sched.stats()["timeouts"] == 2
    assert sched.take_nowait() is live


def test_scheduler_skips_expired_at_dispatch():
    sched = CostAwareScheduler(depth=16, default_timeout=None)
    it = Item()
    sched.submit(it)
    it._dead = True  # expires while queued
    assert sched.take_nowait() is None
    assert sched.stats()["timeouts"] == 1


# --- result cache: subsumption algebra ---------------------------------------


def test_chain_atoms_extracts_conjuncts(simple):
    plan = simple.sql("SELECT id FROM t WHERE price > 5 AND price < 90").plan
    got = chain_atoms(plan)
    assert got is not None
    _, atoms = got
    assert ("price", ">", 5) in atoms and ("price", "<", 90) in atoms


def test_chain_atoms_rejects_unsupported_shapes(simple):
    agg = simple.sql("SELECT name, COUNT(id) FROM t GROUP BY name").plan
    assert chain_atoms(agg) is None


def test_atoms_imply_directional():
    assert atoms_imply([("p", ">", 7)], [("p", ">", 5)])
    assert not atoms_imply([("p", ">", 3)], [("p", ">", 5)])
    assert atoms_imply([("p", ">=", 5)], [("p", ">=", 5)])
    assert not atoms_imply([("p", ">=", 5)], [("p", ">", 5)])
    assert atoms_imply([("p", "<", 4)], [("p", "<=", 4)])
    assert atoms_imply([("p", "=", 5)], [("p", ">", 4)]) is False  # conservative
    assert atoms_imply([("p", "in", frozenset({1, 2}))], [("p", "in", frozenset({1, 2, 3}))])
    assert not atoms_imply([("p", "in", frozenset({1, 9}))], [("p", "in", frozenset({1, 2, 3}))])
    # extra request atoms only narrow; missing cached atoms break implication
    assert atoms_imply([("p", ">", 7), ("q", "=", 1)], [("p", ">", 5)])
    assert not atoms_imply([("p", ">", 7)], [("p", ">", 5), ("q", "=", 1)])


# --- result cache: correctness -----------------------------------------------


def test_result_cache_exact_hit_bytes_identical(simple):
    with QueryServer(simple, workers=1, result_cache_enabled=True) as srv:
        q = "SELECT id, price FROM t WHERE price > 50"
        fresh = srv.query(q)
        hit = srv.query(q)
        assert set(fresh) == set(hit)
        for c in fresh:
            np.testing.assert_array_equal(fresh[c], hit[c])
        rc = srv.stats()["resultCache"]
        assert rc["hits"] == 1 and rc["misses"] == 1
        # served arrays are frozen: corruption of future hits must raise
        with pytest.raises((ValueError, RuntimeError)):
            hit["price"][0] = -1


def test_result_cache_subsumed_hit_matches_fresh_execution(simple):
    with QueryServer(simple, workers=1, result_cache_enabled=True) as srv:
        srv.query("SELECT id, price FROM t WHERE price > 50")  # cached superset
        sub = srv.query("SELECT id, price FROM t WHERE price > 60")
        assert srv.stats()["resultCache"]["subsumedHits"] == 1
    with QueryServer(simple, workers=1) as srv2:
        fresh = srv2.query("SELECT id, price FROM t WHERE price > 60")
    order_s, order_f = np.argsort(sub["id"]), np.argsort(fresh["id"])
    np.testing.assert_array_equal(sub["id"][order_s], fresh["id"][order_f])
    np.testing.assert_array_equal(sub["price"][order_s], fresh["price"][order_f])


def test_result_cache_never_serves_stale_version(simple, tmp_path):
    q = "SELECT id, price FROM t WHERE price > 50"
    with QueryServer(
        simple, workers=1, result_cache_enabled=True, bucket_cache_bytes=1
    ) as srv:
        before = srv.query(q)
        assert len(before["id"]) == len(srv.query(q)["id"])  # warm exact hit
        # the source file is rewritten with different contents: a new data
        # version the brand must observe
        n = 40
        path = str(tmp_path / "t.parquet")
        pq.write_table(
            pa.table(
                {
                    "id": np.arange(n, dtype=np.int64),
                    "name": np.array(["x"] * n),
                    "price": np.full(n, 99, dtype=np.int64),
                }
            ),
            path,
        )
        os.utime(path, (time.time() + 10, time.time() + 10))
        after = srv.query(q)
        # every row of the new version matches the predicate: a stale serve
        # would return the old 245-row result
        assert len(after["id"]) == n
        assert np.all(after["price"] == 99)
        assert srv.stats()["resultCache"]["invalidations"] >= 1


def test_version_brand_tracks_flag_and_sources(simple):
    q = "SELECT id FROM t WHERE price > 5"
    plan = simple.sql(q).plan
    on = version_brand(simple, plan, True)
    off = version_brand(simple, plan, False)
    assert on is not None and off is not None and on != off
    assert simple.data_version_brand(q) in (on, off)

    class Unsignable:
        def signature(self):
            raise NotImplementedError

    from hyperspace_tpu.plan import logical as L

    assert version_brand(simple, L.Scan(relation=Unsignable()), True) is None


def test_result_cache_byte_budget_evicts_lru(simple):
    rc = ResultCache(max_bytes=4096, max_entry_bytes=4096)
    fp = plan_fingerprint(simple.sql("SELECT id FROM t WHERE price > 5").plan)
    big = {"id": np.arange(300, dtype=np.int64)}  # 2400 bytes
    assert rc.put(fp, "brandA", big)
    fp2 = plan_fingerprint(simple.sql("SELECT id FROM t WHERE price > 6").plan)
    assert rc.put(fp2, "brandA", {"id": np.arange(300, dtype=np.int64)})
    assert rc.stats()["evictions"] == 1  # 4800 > 4096: the older entry left
    assert rc.put(fp, "brandA", {"id": np.arange(900, dtype=np.int64)}) is False  # over entry cap


# --- default-off: byte-for-byte FIFO ----------------------------------------


def test_defaults_are_plain_fifo_and_no_result_cache(simple):
    with QueryServer(simple, workers=1) as srv:
        assert type(srv.admission) is AdmissionController
        assert srv.result_cache is None
        got = srv.query("SELECT id FROM t WHERE price > 50")
        want = simple.sql("SELECT id FROM t WHERE price > 50").collect()
        np.testing.assert_array_equal(np.sort(got["id"]), np.sort(want["id"]))
        assert "resultCache" not in srv.stats()


def test_conf_keys_enable_scheduler_and_cache(tmp_path):
    n = 50
    pq.write_table(
        pa.table({"id": np.arange(n, dtype=np.int64), "v": np.arange(n, dtype=np.int64)}),
        str(tmp_path / "c.parquet"),
    )
    sess = hst.Session(
        conf={
            "hyperspace.serving.sched.enabled": "true",
            "hyperspace.serving.resultCache.enabled": "true",
            "hyperspace.serving.sched.tenantWeights": "gold=4,free=1",
        }
    )
    sess.read_parquet(str(tmp_path / "c.parquet")).create_or_replace_temp_view("c")
    with QueryServer(sess, workers=1) as srv:
        assert isinstance(srv.admission, CostAwareScheduler)
        assert srv.admission.tenant_weights == {"gold": 4.0, "free": 1.0}
        assert srv.result_cache is not None
        srv.query("SELECT id FROM c WHERE v > 10", tenant="gold")
        srv.query("SELECT id FROM c WHERE v > 10", tenant="gold")
        assert srv.stats()["resultCache"]["hits"] == 1
        text = srv.prometheus_text()
        assert "hs_admission_wait_seconds" in text
        assert "hs_result_cache_hits_total" in text


def test_sched_end_to_end_with_tenants_and_metrics(simple):
    # result cache off: hits would bypass the queue and never register their
    # tenant with the scheduler (the fast path is the point of the cache)
    with QueryServer(simple, workers=2, sched_enabled=True) as srv:
        futs = [
            srv.submit("SELECT id, price FROM t WHERE price > 50", tenant=f"t{i % 3}")
            for i in range(12)
        ]
        for f in futs:
            assert len(f.result(timeout=30)["id"]) == 245
        st = srv.stats()["queue"]
        assert set(st["tenants"]) == {"t0", "t1", "t2"}
        assert st["timeouts"] == 0 and st["rejected"] == 0
