"""Source addressing: glob patterns and non-parquet formats.

The reference's E2E suite covers globbing patterns (E2EHyperspaceRulesTest;
conf ``spark.hyperspace.source.globbingPattern``) and CSV/JSON sources
(DefaultFileBasedSource supported formats, HS/util/HyperspaceConf.scala:94-99).
Here path spelling is canonicalized out of the plan fingerprint, so an index
applies regardless of whether the data was addressed as a directory or a
glob — no conf needed.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def _index_scans(q):
    return [p for p in L.collect(q.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]


def _write_parquet_files(d, n_files=3, rows=1000):
    rng = np.random.default_rng(0)
    os.makedirs(d, exist_ok=True)
    for i in range(n_files):
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 50, rows).astype(np.int64),
                    "v": rng.standard_normal(rows),
                }
            ),
            os.path.join(d, f"p{i}.parquet"),
        )


class TestGlobAddressing:
    def test_index_from_glob_applies_to_dir_read(self, session, hs, tmp_path):
        d = str(tmp_path / "t")
        _write_parquet_files(d)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        dfg = session.read_parquet(os.path.join(d, "*.parquet"))
        hs.create_index(dfg, hst.CoveringIndexConfig("globIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        dfd = session.read_parquet(d)
        q = dfd.filter(hst.col("k") == 7).select("v")
        assert _index_scans(q), q.optimized_plan().pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["v"]), np.sort(off["v"]))

    def test_index_from_dir_applies_to_glob_read(self, session, hs, tmp_path):
        d = str(tmp_path / "t2")
        _write_parquet_files(d)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        dfd = session.read_parquet(d)
        hs.create_index(dfd, hst.CoveringIndexConfig("dirIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        dfg = session.read_parquet(os.path.join(d, "*.parquet"))
        q = dfg.filter(hst.col("k") == 3).select("v")
        assert _index_scans(q), q.optimized_plan().pretty()

    def test_changed_file_set_still_disqualifies(self, session, hs, tmp_path):
        d = str(tmp_path / "t3")
        _write_parquet_files(d)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("chIdx", ["k"], ["v"]))
        _write_parquet_files(d, n_files=4)  # appended file -> different set
        session.enable_hyperspace()
        df2 = session.read_parquet(d)
        q = df2.filter(hst.col("k") == 1).select("v")
        assert not _index_scans(q)  # no hybrid scan conf -> disqualified


class TestSignatureProviderVersioning:
    def test_old_provider_disqualifies_with_clear_reason(self, session, hs, tmp_path, monkeypatch):
        """An index signed under an older provider is not comparable — it must
        be disqualified with a provider-mismatch reason, not a misleading
        'source data changed'."""
        d = str(tmp_path / "sv")
        _write_parquet_files(d)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_parquet(d)
        hs.create_index(df, hst.CoveringIndexConfig("svIdx", ["k"], ["v"]))
        session.enable_hyperspace()

        import hyperspace_tpu.rules.candidate as cand
        import hyperspace_tpu.sources.signatures as sigs

        monkeypatch.setattr(sigs, "INDEX_SIGNATURE_PROVIDER", "IndexSignatureProvider/v99")
        monkeypatch.setattr(cand, "INDEX_SIGNATURE_PROVIDER", "IndexSignatureProvider/v99")
        session.index_manager.clear_cache()
        q = df.filter(hst.col("k") == 7).select("v")
        assert not _index_scans(q)
        report = hs.why_not(q)
        assert "SIGNATURE_PROVIDER_MISMATCH" in report


class TestCsvJsonSources:
    def test_csv_index_end_to_end(self, session, hs, tmp_path):
        d = tmp_path / "csv"
        d.mkdir()
        rng = np.random.default_rng(1)
        t = pa.table(
            {
                "k": rng.integers(0, 30, 800).astype(np.int64),
                "v": np.round(rng.standard_normal(800), 6),
            }
        )
        pacsv.write_csv(t, d / "data.csv")
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        df = session.read_csv(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("csvIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 5).select("v")
        assert _index_scans(q), q.optimized_plan().pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.allclose(np.sort(on["v"]), np.sort(off["v"]))

    def test_json_index_end_to_end(self, session, hs, tmp_path):
        import json

        d = tmp_path / "json"
        d.mkdir()
        rng = np.random.default_rng(2)
        with open(d / "data.json", "w") as f:
            for _ in range(500):
                f.write(json.dumps({"k": int(rng.integers(0, 20)), "v": float(rng.random())}) + "\n")
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_json(str(d))
        hs.create_index(df, hst.CoveringIndexConfig("jsonIdx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(hst.col("k") == 5).select("v")
        assert _index_scans(q), q.optimized_plan().pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.allclose(np.sort(on["v"]), np.sort(off["v"]))
