"""Query intelligence layer: profile history, SLO tracking, flight recorder,
and the HTTP telemetry endpoint (hyperspace_tpu/obs/{history,slo,export}.py).

Covers the P² sketch against exact percentiles, the cost-model acceptance
bar (estimate within 2x of the true median after >= 20 samples), LRU
bounding, JSONL persistence round-trips, SLO burn-rate windows under an
injected clock, and the endpoint contract (GET /metrics byte-identical to
``registry.prometheus_text()``). All HTTP tests bind port 0.
"""

import json
import random
import statistics
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.obs import spans
from hyperspace_tpu.obs.export import PROMETHEUS_CONTENT_TYPE, TelemetryEndpoint
from hyperspace_tpu.obs.history import (
    FlightRecorder,
    P2Quantile,
    ProfileHistory,
    StreamStat,
    load_history,
)
from hyperspace_tpu.obs.metrics import MetricsRegistry
from hyperspace_tpu.obs.profile import build_profile
from hyperspace_tpu.obs.slo import SloTracker
from hyperspace_tpu.serving import QueryServer

pytestmark = pytest.mark.obshist

FP = "a" * 40  # structure-hash shaped fingerprint


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read()


# --- P² quantile sketch ------------------------------------------------------


def test_p2_exact_below_five_samples():
    q = P2Quantile(0.5)
    assert q.value is None
    for v in (5.0, 1.0, 3.0):
        q.add(v)
    assert q.value == 3.0  # sorts what it has


@pytest.mark.parametrize("p", [0.5, 0.95])
def test_p2_tracks_true_quantile(p):
    rng = random.Random(42)
    q = P2Quantile(p)
    vals = [rng.lognormvariate(0.0, 0.5) for _ in range(2000)]
    for v in vals:
        q.add(v)
    true = float(np.percentile(vals, p * 100))
    assert q.value == pytest.approx(true, rel=0.15)


def test_stream_stat_summary():
    s = StreamStat()
    for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
        s.add(v)
    assert s.n == 6
    assert s.mean == pytest.approx(3.5)
    assert s.min == 1.0 and s.max == 6.0
    assert s.ema is not None and 1.0 < s.ema < 6.0
    j = s.to_json()
    assert set(j) == {"n", "mean", "ema", "min", "max", "p50", "p95"}
    json.dumps(j)


# --- ProfileHistory ----------------------------------------------------------


def test_history_unseen_fingerprint_is_none():
    h = ProfileHistory()
    assert h.estimate_cost("f" * 40) is None
    assert h.get("f" * 40) is None


def test_history_estimate_within_2x_of_median():
    # the acceptance bar: a deterministic noisy workload, >= 20 samples,
    # predicted latency within 2x of the true median
    rng = random.Random(7)
    h = ProfileHistory()
    lats = [0.05 * rng.lognormvariate(0.0, 0.4) for _ in range(40)]
    for lat in lats:
        h.record(FP, lat, rows=100)
    est = h.estimate_cost(FP)
    assert est is not None and est.samples >= 20
    med = statistics.median(lats)
    assert med / 2.0 <= est.latency_s <= med * 2.0
    assert 0.0 < est.confidence <= 1.0


def test_history_confidence_grows_with_samples():
    h = ProfileHistory()
    h.record(FP, 0.1)
    c1 = h.estimate_cost(FP).confidence
    for _ in range(30):
        h.record(FP, 0.1)
    c2 = h.estimate_cost(FP).confidence
    assert c2 > c1
    assert c2 == pytest.approx(1.0)  # zero dispersion, saturated samples


def test_history_errors_not_folded_into_latency():
    # a fast failure must not teach the cost model the fingerprint is cheap
    h = ProfileHistory()
    for _ in range(10):
        h.record(FP, 1.0)
    for _ in range(10):
        h.record(FP, 0.001, error=True)
    e = h.get(FP)
    assert e["count"] == 20 and e["errors"] == 10
    assert e["latencySeconds"]["n"] == 10
    assert h.estimate_cost(FP).latency_s == pytest.approx(1.0)


def test_history_lru_bound_and_eviction():
    h = ProfileHistory(max_fingerprints=3)
    for i in range(5):
        h.record(f"{i:040d}", 0.1)
    assert len(h) == 3 and h.evicted == 2
    # touching an entry protects it from the next eviction
    h.record("0" * 39 + "2", 0.1)
    h.record("9" * 40, 0.1)
    assert ("0" * 39 + "2") in h.fingerprints()


def test_history_registry_gauge_and_counter():
    reg = MetricsRegistry()
    h = ProfileHistory(registry=reg, server="qs9")
    h.record(FP, 0.1)
    h.record("b" * 40, 0.2)
    snap = reg.snapshot()
    (g,) = snap["hs_profile_history_fingerprints"]["series"]
    assert g["value"] == 2
    (c,) = snap["hs_profile_history_folds_total"]["series"]
    assert c["value"] == 2 and c["labels"] == {"server": "qs9"}


def test_history_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "hist" / "workload.jsonl")
    h = ProfileHistory(persist_path=path)
    for i in range(25):
        h.record(FP, 0.1 + 0.001 * (i % 5), rows=50, query="SELECT 1")
    h.record("b" * 40, 0.5, error=True)
    before = h.estimate_cost(FP)
    h.close()
    h2 = load_history(path)
    assert sorted(h2.fingerprints()) == sorted(h.fingerprints())
    e = h2.get(FP)
    assert e["count"] == 25 and e["query"] == "SELECT 1"
    assert h2.get("b" * 40)["errors"] == 1
    after = h2.estimate_cost(FP)
    assert after.samples == before.samples
    assert after.latency_s == pytest.approx(before.latency_s)


def test_load_history_skips_corrupt_lines(tmp_path):
    path = tmp_path / "w.jsonl"
    good = json.dumps({"fp": FP, "latencySeconds": 0.2})
    path.write_text(f"{good}\nnot json at all\n{{\"latencySeconds\": 1}}\n{good}\n")
    h = load_history(str(path))
    assert h.get(FP)["count"] == 2


def test_history_snapshot_is_jsonable():
    h = ProfileHistory()
    h.record(FP, 0.1, rows=10, bytes=1000)
    snap = h.snapshot()
    assert snap["fingerprints"] == 1
    (e,) = snap["entries"]
    assert e["fingerprint"] == FP and e["estimate"]["samples"] == 1
    json.dumps(snap)


# --- FlightRecorder ----------------------------------------------------------


def _traced_profile(query="SELECT x"):
    with spans.trace("request") as root:
        with spans.span("execute", cat="exec") as sp:
            sp.set(rows=10)
    return build_profile(root, query=query)


def test_flight_recorder_ring_and_chrome_trace(tmp_path):
    reg = MetricsRegistry()
    fr = FlightRecorder(max_entries=2, directory=str(tmp_path / "slow"), registry=reg)
    for i in range(4):
        fr.record("slow", 0.5 + i, fingerprint=FP, query=f"q{i}",
                  tenant="t", profile=_traced_profile(f"q{i}"))
    entries = fr.last_slow_queries()
    assert [e.query for e in entries] == ["q2", "q3"]  # ring keeps the newest
    e = entries[-1]
    assert e.profile is not None and e.profile.root.find("execute")
    ct = e.chrome_trace()
    assert ct and ct["traceEvents"]
    out = e.save_chrome_trace(str(tmp_path / "t.json"))
    assert json.load(open(out))["traceEvents"]
    # on-disk ring pruned to max_entries, each file self-contained
    files = sorted((tmp_path / "slow").glob("slow-*.json"))
    assert len(files) == 2
    body = json.load(open(files[-1]))
    assert body["query"] == "q3" and body["chromeTrace"]["traceEvents"]
    snap = reg.snapshot()
    (c,) = snap["hs_slow_queries_total"]["series"]
    assert c["value"] == 4 and c["labels"] == {"reason": "slow"}


def test_flight_recorder_without_profile_or_disk():
    fr = FlightRecorder(max_entries=4)
    e = fr.record("rejected", 0.0, fingerprint=FP, conf_deltas={"k": 1})
    assert e.chrome_trace() is None and e.path is None
    assert fr.snapshot()[0]["reason"] == "rejected"
    assert fr.snapshot()[0]["confDeltas"] == {"k": "1"}


# --- SLO tracking ------------------------------------------------------------


def test_slo_objective_validation():
    with pytest.raises(ValueError):
        SloTracker(target_ms=100, objective=1.0)


def test_slo_good_bad_and_burn_rate_windows():
    clk = [1000.0]
    reg = MetricsRegistry()
    slo = SloTracker(target_ms=100.0, objective=0.9, windows_s=(60.0, 600.0),
                     registry=reg, server="qs1", clock=lambda: clk[0])
    assert slo.record(0.05) is True
    assert slo.record(0.2) is False  # slow
    assert slo.record(0.01, error=True) is False  # errored
    # 2 bad / 3 total over a 10% budget -> burn rate 6.67
    assert slo.burn_rate(60.0) == pytest.approx((2 / 3) / 0.1)
    # age the events out of the short window but not the long one
    clk[0] += 120.0
    slo.record(0.05)
    assert slo.burn_rate(60.0) == 0.0
    assert slo.burn_rate(600.0) == pytest.approx((2 / 4) / 0.1)
    st = slo.state()
    t = st["tenants"]["default"]
    assert t["good"] == 2 and t["bad"] == 2 and t["compliance"] == 0.5
    assert t["burnRates"]["60s"] == 0.0
    # the registry carries the same truth, per tenant + server + window
    snap = reg.snapshot()
    labels = {s["labels"]["window"]: s["value"]
              for s in snap["hs_slo_burn_rate"]["series"]}
    assert labels["60s"] == 0.0 and labels["600s"] == pytest.approx(5.0)
    (good,) = snap["hs_slo_good_total"]["series"]
    assert good["value"] == 2
    assert good["labels"] == {"tenant": "default", "server": "qs1"}


def test_slo_tenants_are_isolated():
    slo = SloTracker(target_ms=100.0, objective=0.99)
    slo.record(0.5, tenant="noisy")
    slo.record(0.01, tenant="quiet")
    assert slo.burn_rate(300.0, tenant="noisy") == pytest.approx(100.0)
    assert slo.burn_rate(300.0, tenant="quiet") == 0.0
    assert slo.burn_rate(300.0, tenant="absent") == 0.0


# --- HTTP telemetry endpoint -------------------------------------------------


def test_endpoint_metrics_byte_identical_to_registry():
    reg = MetricsRegistry()
    reg.counter("hs_served_total", "served", server="qs1").inc(3)
    reg.gauge("hs_depth", "queue depth").set(2)
    with TelemetryEndpoint(reg, port=0) as ep:
        status, ctype, body = _get(ep.url + "/metrics")
    assert status == 200 and ctype == PROMETHEUS_CONTENT_TYPE
    # the acceptance bar: the wire bytes ARE the registry exposition
    assert body == reg.prometheus_text().encode("utf-8")
    assert b'hs_served_total{server="qs1"} 3' in body


def test_endpoint_statusz_and_404():
    reg = MetricsRegistry()
    with TelemetryEndpoint(reg, port=0, status_fn=lambda: {"ok": True}) as ep:
        status, ctype, body = _get(ep.url + "/statusz")
        assert status == 200 and json.loads(body) == {"ok": True}
        assert ctype.startswith("application/json")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ep.url + "/nope")
        assert ei.value.code == 404
        assert "/metrics" in json.loads(ei.value.read())["endpoints"]
    # requests were counted per path
    paths = {s["labels"]["path"] for s in reg.snapshot()["hs_http_requests_total"]["series"]}
    assert {"/statusz", "/nope"} <= paths


def test_endpoint_profilez_overview_and_drilldown():
    reg = MetricsRegistry()
    hist = ProfileHistory()
    for _ in range(5):
        hist.record(FP, 0.1, query="SELECT a")
    fr = FlightRecorder(max_entries=4)
    fr.record("slow", 0.9, fingerprint=FP, query="SELECT a")
    fr.record("slow", 0.9, fingerprint="b" * 40, query="SELECT b")
    with TelemetryEndpoint(reg, port=0, history=hist, flight=fr) as ep:
        _, _, body = _get(ep.url + "/profilez")
        overview = json.loads(body)
        assert overview["fingerprints"] == 1
        _, _, body = _get(ep.url + f"/profilez?fingerprint={FP}")
        detail = json.loads(body)
        assert detail["count"] == 5 and detail["estimate"]["samples"] == 5
        # slow-query drill-down is filtered to this fingerprint
        assert [e["query"] for e in detail["slowQueries"]] == ["SELECT a"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ep.url + "/profilez?fingerprint=" + "c" * 40)
        assert ei.value.code == 404


def test_endpoint_profilez_404_when_history_disabled():
    with TelemetryEndpoint(MetricsRegistry(), port=0) as ep:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ep.url + "/profilez")
        assert ei.value.code == 404


# --- QueryServer integration -------------------------------------------------


@pytest.fixture()
def served(tmp_path):
    n = 400
    pq.write_table(
        pa.table({
            "id": np.arange(n, dtype=np.int64),
            "price": (np.arange(n, dtype=np.int64) * 7) % 100,
        }),
        str(tmp_path / "t.parquet"),
    )
    sess = hst.Session(conf={
        hst.keys.SYSTEM_PATH: str(tmp_path / "_indexes"),
        hst.keys.OBS_TRACING_ENABLED: True,
        hst.keys.OBS_SLOW_QUERY_MS: 0.000001,  # everything is "slow"
        hst.keys.OBS_SLO_TARGET_MS: 50.0,
        hst.keys.OBS_HISTORY_PERSIST: True,
    })
    sess.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
    return sess


def test_server_folds_completions_into_intelligence(served):
    q = "SELECT id FROM t WHERE price > 45"
    with QueryServer(served) as srv:
        for _ in range(21):
            srv.query(q, tenant="acme")
    # shutdown joined the workers, so every completion hook has run
    est = srv.estimate_cost(q)
    assert est is not None and est.samples >= 20
    # cost model: learned, sampled, and within the 2x bar against the
    # server's own observed median
    p50 = srv.metrics.latency_percentiles()["p50"]
    assert p50 / 2.0 <= est.latency_s <= p50 * 2.0
    # the structure hash itself also resolves
    fp = srv.history.fingerprints()[0]
    assert srv.estimate_cost(fp).samples == est.samples
    # flight recorder: every query tripped the 1us threshold, span
    # trees intact and exportable
    slow = srv.last_slow_queries()
    assert slow and slow[0].reason == "slow"
    assert slow[0].profile.root.find("execute")
    assert slow[0].chrome_trace()["traceEvents"]
    assert slow[0].tenant == "acme"
    # SLO + tenant series landed in the server's registry
    snap = srv.registry.snapshot()
    assert "hs_slo_good_total" in snap or "hs_slo_bad_total" in snap
    tenants = {s["labels"]["tenant"]
               for s in snap["hs_serving_tenant_requests_total"]["series"]
               if s["labels"].get("server") == srv.server_name}
    assert tenants == {"acme"}
    st = srv.statusz()
    assert st["slo"]["tenants"]["acme"]["good"] + st["slo"]["tenants"]["acme"]["bad"] == 21
    assert st["profileHistory"]["fingerprints"] == 1
    # the workload log survives shutdown and replays into an equal history
    h2 = load_history(srv.history._persist_path)
    assert h2.get(fp)["count"] == 21


def test_server_telemetry_endpoint_end_to_end(served):
    with QueryServer(served) as srv:
        srv.query("SELECT id FROM t WHERE price > 45")
        ep = srv.serve_telemetry(port=0)
        status, _, body = _get(ep.url + "/metrics")
        assert status == 200
        assert body == srv.registry.prometheus_text().encode("utf-8")
        _, _, body = _get(ep.url + "/statusz")
        st = json.loads(body)
        assert st["server"] == srv.server_name
        assert st["serving"]["completed"] == 1
        _, _, body = _get(ep.url + "/profilez")
        assert json.loads(body)["fingerprints"] == 1
    assert srv.telemetry is None  # shutdown closed it


def test_session_estimate_cost_from_traced_collects(tmp_path):
    n = 200
    pq.write_table(pa.table({"a": np.arange(n, dtype=np.int64)}),
                   str(tmp_path / "d.parquet"))
    sess = hst.Session(conf={hst.keys.OBS_TRACING_ENABLED: True})
    df = sess.read_parquet(str(tmp_path / "d.parquet")).filter(hst.col("a") < 50)
    assert sess.estimate_cost(df) is None  # nothing folded yet
    for _ in range(3):
        df.collect()
    est = sess.estimate_cost(df)
    assert est is not None and est.samples == 3 and est.latency_s > 0
    # a different plan shape is a different fingerprint: still unseen
    assert sess.estimate_cost(sess.read_parquet(str(tmp_path / "d.parquet"))) is None


def test_history_disabled_by_conf(tmp_path):
    n = 50
    pq.write_table(pa.table({"a": np.arange(n, dtype=np.int64)}),
                   str(tmp_path / "d.parquet"))
    sess = hst.Session(conf={hst.keys.OBS_HISTORY_ENABLED: False})
    assert sess.profile_history is None
    assert sess.estimate_cost(sess.read_parquet(str(tmp_path / "d.parquet"))) is None
    with QueryServer(sess) as srv:
        assert srv.history is None
        sess.read_parquet(str(tmp_path / "d.parquet")).create_or_replace_temp_view("v")
        srv.query("SELECT a FROM v")  # completion path tolerates the absence
        assert srv.estimate_cost("SELECT a FROM v") is None
