"""Two-process localhost CPU mesh: the multi-host smoke path (SURVEY §5.8).

The reference's multi-node behavior rides Spark's cluster runtime; here the
equivalent is ``jax.distributed`` + the 2-D (dcn, ici) mesh, and this test
runs the hierarchical re-bucketing exchange across a REAL OS process
boundary: two processes, each holding 4 virtual CPU devices, form a 2x4
mesh whose dcn axis is the process boundary; every row must land on the
device owning its bucket and cross the process boundary at most once
(ops/bucketize.rebucket_hierarchical)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")

from hyperspace_tpu.parallel.distributed import initialize_from_env, shutdown

assert initialize_from_env(), "HS_* env must configure the two-process world"
pid = int(os.environ["HS_PROCESS_ID"])
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4, jax.local_device_count()
assert jax.device_count() == 8

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from hyperspace_tpu.ops.bucketize import rebucket_hierarchical
from hyperspace_tpu.parallel.mesh import make_mesh_2d, sharded_2d

mesh = make_mesh_2d()  # n_slices defaults to jax.process_count()
assert mesh.shape == {"dcn": 2, "ici": 4}, mesh.shape
sh = sharded_2d(mesh)

rows_per_dev = 32
n = 8 * rows_per_dev
num_buckets = 16
rng = np.random.default_rng(11)
buckets_global = (rng.integers(0, num_buckets, n)).astype(np.int32)
vals_global = np.arange(n, dtype=np.float64)

local = slice(pid * n // 2, (pid + 1) * n // 2)
hb = jax.make_array_from_process_local_data(sh, buckets_global[local], (n,))
arr = {"v": jax.make_array_from_process_local_data(sh, vals_global[local], (n,))}

out, out_buckets, valid, overflow = rebucket_hierarchical(
    mesh, arr, hb, capacity_ici=2 * rows_per_dev, capacity_dcn=2 * rows_per_dev
)
total_valid = int(jax.jit(lambda v: jnp.sum(v), out_shardings=NamedSharding(mesh, P()))(valid))
total_overflow = int(jax.jit(lambda o: jnp.sum(o), out_shardings=NamedSharding(mesh, P()))(overflow))
assert total_valid == n, f"rows not conserved: {total_valid} != {n}"
assert total_overflow == 0, f"exchange overflowed: {total_overflow}"

# every valid row on THIS process's addressable shards is owned here:
# global device g = bucket % 8, and devices 4*pid..4*pid+3 are local
for b_shard, v_shard in zip(out_buckets.addressable_shards, valid.addressable_shards):
    b = np.asarray(b_shard.data).ravel()
    m = np.asarray(v_shard.data).ravel()
    owners = b[m] % 8
    lo, hi = 4 * pid, 4 * pid + 4
    assert ((owners >= lo) & (owners < hi)).all(), (pid, set(owners.tolist()))

# matched values survive: global sum of valid v equals the input sum
sv = float(jax.jit(
    lambda v, m: jnp.sum(jnp.where(m, v, 0.0)), out_shardings=NamedSharding(mesh, P())
)(out["v"], valid))
assert abs(sv - vals_global.sum()) < 1e-6, (sv, vals_global.sum())

shutdown()
print(f"WORKER{pid} OK", flush=True)
'''


def test_two_process_hierarchical_rebucket(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env_base = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        HS_COORDINATOR="127.0.0.1:29517",
        HS_NUM_PROCESSES="2",
    )
    procs = []
    for pid in range(2):
        env = dict(env_base, HS_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker), REPO],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for pid, p in enumerate(procs):
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
    for pid in range(2):
        assert f"WORKER{pid} OK" in outs[pid]


def test_initialize_noop_without_config(monkeypatch):
    """Single-process mode: no env -> no-op, the same entry point works."""
    from hyperspace_tpu.parallel.distributed import initialize_from_env

    monkeypatch.delenv("HS_NUM_PROCESSES", raising=False)
    assert initialize_from_env() is False
