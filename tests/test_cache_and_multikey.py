"""TTL index cache, composite-key indexes, and case sensitivity.

Reference counterparts: IndexCacheTest (TTL expiry), CreateIndexTest
multi-column indexes, and E2EHyperspaceRulesTest's case-sensitivity cases
(SURVEY.md §4).
"""

import os
import shutil

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


def _index_scans(q):
    return [p for p in L.collect(q.optimized_plan(), lambda p: True) if isinstance(p, L.IndexScan)]


def write_two_tables(tmp_path):
    rng = np.random.default_rng(0)
    l, r = tmp_path / "l", tmp_path / "r"
    l.mkdir()
    r.mkdir()
    pq.write_table(
        pa.table(
            {
                "a": rng.integers(0, 10, 2000).astype(np.int64),
                "b": rng.integers(0, 10, 2000).astype(np.int64),
                "v": rng.standard_normal(2000),
            }
        ),
        l / "p.parquet",
    )
    pq.write_table(
        pa.table(
            {
                "a": rng.integers(0, 10, 500).astype(np.int64),
                "b": rng.integers(0, 10, 500).astype(np.int64),
                "w": rng.standard_normal(500),
            }
        ),
        r / "p.parquet",
    )
    return str(l), str(r)


class TestIndexCache:
    def test_cache_serves_entries_within_ttl(self, session, hs, tmp_path):
        lpath, _ = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(lpath)
        hs.create_index(df, hst.CoveringIndexConfig("cacheIdx", ["a"], ["v"]))
        mgr = session.index_manager
        assert any(e.name == "cacheIdx" for e in mgr.get_indexes())
        # remove the index behind the manager's back: the TTL cache (300 s
        # default) still serves the stale listing
        shutil.rmtree(os.path.join(session.conf.get(hst.keys.SYSTEM_PATH), "cacheIdx"))
        assert any(e.name == "cacheIdx" for e in mgr.get_indexes())

    def test_cache_expiry_refetches(self, session, hs, tmp_path):
        lpath, _ = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(lpath)
        hs.create_index(df, hst.CoveringIndexConfig("ttlIdx", ["a"], ["v"]))
        mgr = session.index_manager
        assert any(e.name == "ttlIdx" for e in mgr.get_indexes())
        shutil.rmtree(os.path.join(session.conf.get(hst.keys.SYSTEM_PATH), "ttlIdx"))
        session.conf.set(hst.keys.CACHE_EXPIRY_SECONDS, 0)  # everything expired
        assert not any(e.name == "ttlIdx" for e in mgr.get_indexes())

    def test_mutations_invalidate(self, session, hs, tmp_path):
        lpath, _ = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(lpath)
        hs.create_index(df, hst.CoveringIndexConfig("invIdx", ["a"], ["v"]))
        mgr = session.index_manager
        mgr.get_indexes()  # populate cache
        hs.delete_index("invIdx")  # mutation clears it
        from hyperspace_tpu.models import states

        active = mgr.get_indexes([states.ACTIVE])
        assert not any(e.name == "invIdx" for e in active)


class TestCompositeKeyIndexes:
    def test_multikey_filter_and_join(self, session, hs, tmp_path):
        lpath, rpath = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("mkL", ["a", "b"], ["v"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("mkR", ["a", "b"], ["w"]))
        session.enable_hyperspace()

        q1 = ldf.filter((hst.col("a") == 3) & (hst.col("b") > 5)).select("v")
        assert _index_scans(q1), q1.optimized_plan().pretty()
        on = q1.collect()
        session.disable_hyperspace()
        off = q1.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["v"]), np.sort(off["v"]))

        q2 = ldf.join(rdf, on=["a", "b"]).select("v", "w")
        assert len(_index_scans(q2)) == 2, q2.optimized_plan().pretty()
        on2 = q2.collect()
        session.disable_hyperspace()
        off2 = q2.collect()
        session.enable_hyperspace()
        assert sorted(zip(on2["v"], on2["w"])) == sorted(zip(off2["v"], off2["w"]))
        assert len(on2["v"]) > 0

    def test_join_on_subset_of_indexed_cols_not_rewritten(self, session, hs, tmp_path):
        """Indexed cols must equal join cols exactly (ref: JoinColumnFilter)."""
        lpath, rpath = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("subL", ["a", "b"], ["v"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("subR", ["a", "b"], ["w"]))
        session.enable_hyperspace()
        q = ldf.join(rdf, on=["a"]).select("v", "w")
        assert not _index_scans(q)


class TestChunkedBuild:
    def test_build_in_chunks_matches_single_shot(self, session, hs, tmp_path):
        """tpu.build.batchRows bounds device memory: each chunk runs the
        device program and writes its own sorted run per bucket (the state
        incremental refresh produces); queries and joins are unaffected."""
        from hyperspace_tpu.indexes.covering import bucket_of_file

        lpath, rpath = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 4)
        session.conf.set(hst.keys.TPU_BUILD_BATCH_ROWS, 500)  # 2000 rows -> 4 chunks
        ldf = session.read_parquet(lpath)
        rdf = session.read_parquet(rpath)
        hs.create_index(ldf, hst.CoveringIndexConfig("chunkL", ["a"], ["v"]))
        hs.create_index(rdf, hst.CoveringIndexConfig("chunkR", ["a"], ["w"]))
        entry = session.index_manager.get_index("chunkL")
        per_bucket = {}
        for f in entry.content.files:
            per_bucket.setdefault(bucket_of_file(f), []).append(f)
        assert any(len(v) > 1 for v in per_bucket.values())  # multi-run buckets

        session.enable_hyperspace()
        q = ldf.filter(hst.col("a") == 3).select("v")
        assert _index_scans(q)
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        assert np.array_equal(np.sort(on["v"]), np.sort(off["v"]))

        qj = ldf.join(rdf, on=["a"]).select("v", "w")
        assert len(_index_scans(qj)) == 2
        on2 = qj.collect()
        session.disable_hyperspace()
        off2 = qj.collect()
        session.enable_hyperspace()
        assert sorted(zip(on2["v"], on2["w"])) == sorted(zip(off2["v"], off2["w"]))

        # optimize compacts the runs back down, even under a tiny batch
        # budget: it chunks by whole-bucket groups, never splitting a bucket
        hs.optimize_index("chunkL", "full")
        entry2 = session.index_manager.get_index("chunkL")
        per_bucket2 = {}
        for f in entry2.content.files:
            per_bucket2.setdefault(bucket_of_file(f), []).append(f)
        assert all(len(v) == 1 for v in per_bucket2.values())


class TestCaseSensitivity:
    def test_mixed_case_references_resolve(self, session, hs, tmp_path):
        lpath, _ = write_two_tables(tmp_path)
        session.conf.set(hst.keys.NUM_BUCKETS, 2)
        df = session.read_parquet(lpath)
        hs.create_index(df, hst.CoveringIndexConfig("caseIdx", ["A"], ["V"]))  # wrong-case config
        session.enable_hyperspace()
        q = df.filter(hst.col("A") == 3).select("V")
        assert _index_scans(q), q.optimized_plan().pretty()
        on = q.collect()
        session.disable_hyperspace()
        off = q.collect()
        session.enable_hyperspace()
        (on_col,) = on.values()
        (off_col,) = off.values()
        assert np.array_equal(np.sort(on_col), np.sort(off_col))
        assert len(on_col) > 0
