"""Plan-stability golden suite over TPC-H-shaped tables.

The reference creates all TPC-DS tables as views over empty dirs and compares
normalized physical-plan trees against approved files, regenerable with
SPARK_GENERATE_GOLDEN_FILES=1 (ref: goldstandard/TPCDSBase.scala:35-563,
goldstandard/PlanStabilitySuite.scala:83-290). Here: TPC-H tables as tiny
parquet datasets, representative index-eligible queries through the full
optimizer (with covering indexes present), normalized optimized-plan text
compared against tests/approved_plans/q*.txt; regenerate with
HS_GENERATE_GOLDEN=1.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu import col

APPROVED_DIR = os.path.join(os.path.dirname(__file__), "approved_plans")
GENERATE = os.environ.get("HS_GENERATE_GOLDEN", "") == "1"

# TPC-H columns (subset sufficient for the query shapes the IR supports)
TPCH_SCHEMAS = {
    "lineitem": {
        "l_orderkey": np.int64,
        "l_partkey": np.int64,
        "l_suppkey": np.int64,
        "l_quantity": np.int64,
        "l_extendedprice": np.float64,
        "l_discount": np.float64,
        "l_shipdate": "datetime64[D]",
    },
    "orders": {
        "o_orderkey": np.int64,
        "o_custkey": np.int64,
        "o_totalprice": np.float64,
        "o_orderdate": "datetime64[D]",
    },
    "customer": {
        "c_custkey": np.int64,
        "c_nationkey": np.int64,
        "c_acctbal": np.float64,
    },
    "part": {
        "p_partkey": np.int64,
        "p_size": np.int64,
        "p_retailprice": np.float64,
    },
    "partsupp": {
        "ps_partkey": np.int64,
        "ps_suppkey": np.int64,
        "ps_supplycost": np.float64,
    },
    "supplier": {
        "s_suppkey": np.int64,
        "s_nationkey": np.int64,
        "s_acctbal": np.float64,
    },
    "nation": {"n_nationkey": np.int64, "n_regionkey": np.int64},
    "region": {"r_regionkey": np.int64},
}


def _write_table(root, name, schema, n=64):
    import zlib

    rng = np.random.default_rng(zlib.crc32(name.encode()))
    cols = {}
    for cname, dt in schema.items():
        if dt == "datetime64[D]":
            cols[cname] = np.datetime64("1995-01-01") + rng.integers(0, 1000, n).astype(
                "timedelta64[D]"
            )
        elif dt is np.float64:
            cols[cname] = np.round(rng.uniform(0, 1000, n), 4)
        else:
            cols[cname] = rng.integers(0, 100, n).astype(np.int64)
    d = os.path.join(root, name)
    os.makedirs(d)
    pq.write_table(pa.table(cols), os.path.join(d, "part-00000.parquet"))
    return d


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch"))
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    dfs = {}
    for name, schema in TPCH_SCHEMAS.items():
        d = _write_table(root, name, schema)
        dfs[name] = sess.read_parquet(d)

    # the indexes the benchmark configs use (BASELINE.md configs 2-3)
    hs.create_index(
        dfs["lineitem"],
        hst.CoveringIndexConfig("li_shipdate", ["l_shipdate"], ["l_orderkey", "l_extendedprice", "l_discount"]),
    )
    hs.create_index(
        dfs["lineitem"],
        hst.CoveringIndexConfig("li_orderkey", ["l_orderkey"], ["l_extendedprice", "l_discount", "l_quantity"]),
    )
    hs.create_index(
        dfs["orders"], hst.CoveringIndexConfig("o_orderkey", ["o_orderkey"], ["o_custkey", "o_totalprice"])
    )
    hs.create_index(
        dfs["orders"], hst.CoveringIndexConfig("o_custkey", ["o_custkey"], ["o_orderkey"])
    )
    hs.create_index(
        dfs["customer"], hst.CoveringIndexConfig("c_custkey", ["c_custkey"], ["c_nationkey", "c_acctbal"])
    )
    hs.create_index(
        dfs["part"], hst.CoveringIndexConfig("p_partkey", ["p_partkey"], ["p_size"])
    )
    hs.create_index(
        dfs["partsupp"], hst.CoveringIndexConfig("ps_partkey", ["ps_partkey"], ["ps_supplycost"])
    )
    sess.enable_hyperspace()
    yield sess, hs, dfs, root
    hst.set_session(None)


def _queries(dfs):
    li, o, c, p, ps = dfs["lineitem"], dfs["orders"], dfs["customer"], dfs["part"], dfs["partsupp"]
    ship = np.datetime64("1995-06-15")
    return {
        # filter-rule shapes (BASELINE config 2)
        "q01_filter_eq": li.filter(col("l_shipdate") == ship).select("l_orderkey", "l_extendedprice"),
        "q02_filter_range": li.filter((col("l_shipdate") >= ship) & (col("l_shipdate") < ship + 30)).select(
            "l_extendedprice", "l_discount"
        ),
        "q03_filter_nonindexed": li.filter(col("l_quantity") > 40).select("l_orderkey"),
        # join-rule shapes (BASELINE config 3)
        "q04_join_li_orders": li.join(o, on=col("l_orderkey") == col("o_orderkey")).select(
            "l_extendedprice", "o_totalprice"
        ),
        "q05_join_orders_customer": o.join(c, on=col("o_custkey") == col("c_custkey")).select(
            "o_totalprice", "c_acctbal"
        ),
        "q06_join_filter": li.filter(col("l_quantity") > 10)
        .join(o, on=col("l_orderkey") == col("o_orderkey"))
        .select("l_quantity", "o_totalprice"),
        "q07_join_part_partsupp": p.join(ps, on=col("p_partkey") == col("ps_partkey")).select(
            "p_size", "ps_supplycost"
        ),
        "q08_three_way": li.join(o, on=col("l_orderkey") == col("o_orderkey"))
        .join(c, on=col("o_custkey") == col("c_custkey"))
        .select("l_extendedprice", "c_acctbal"),
        "q09_self_join": li.join(li, on=["l_orderkey"]).select("l_extendedprice"),
        "q10_no_index_join": dfs["supplier"]
        .join(dfs["nation"], on=col("s_nationkey") == col("n_nationkey"))
        .select("s_acctbal"),
    }


def _normalize(text: str, root: str) -> str:
    return text.replace(root, "<TPCH>")


@pytest.mark.parametrize("qname", [
    "q01_filter_eq", "q02_filter_range", "q03_filter_nonindexed", "q04_join_li_orders",
    "q05_join_orders_customer", "q06_join_filter", "q07_join_part_partsupp",
    "q08_three_way", "q09_self_join", "q10_no_index_join",
])
def test_plan_stability(tpch, qname):
    sess, hs, dfs, root = tpch
    q = _queries(dfs)[qname]
    plan_text = _normalize(q.optimized_plan().pretty(), root)
    path = os.path.join(APPROVED_DIR, f"{qname}.txt")
    if GENERATE:
        os.makedirs(APPROVED_DIR, exist_ok=True)
        with open(path, "w") as f:
            f.write(plan_text)
        return
    with open(path) as f:
        expected = f.read()
    assert plan_text == expected, (
        f"plan for {qname} changed; review and regen with HS_GENERATE_GOLDEN=1\n{plan_text}"
    )


def test_all_queries_execute(tpch):
    """Every stability query also executes and matches its no-index results
    (the reference's checkAnswer side of the suite). Rows are compared as
    whole tuples (not per-column multisets) so join mispairing is caught."""
    sess, hs, dfs, root = tpch
    for name, q in _queries(dfs).items():
        sess.disable_hyperspace()
        base = q.collect()
        sess.enable_hyperspace()
        got = q.collect()
        assert sorted(base.keys()) == sorted(got.keys()), name
        cols = sorted(base.keys())
        base_rows = sorted(zip(*[base[k].tolist() for k in cols]))
        got_rows = sorted(zip(*[got[k].tolist() for k in cols]))
        assert base_rows == got_rows, f"{name}: row sets differ"
