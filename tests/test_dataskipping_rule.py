"""DataSkippingIndexRule tests — the query-side pruning rule the reference
never finished (its rule list is Filter/Join/NoOp only; ref:
HS/index/rules/ScoreBasedIndexPlanOptimizer.scala:30, groundwork in
HS/index/dataskipping/util/extractors.scala:42-199).

Pruning must never change results: every test checks results with hyperspace
on == off, plus which files the plan scans.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.indexes.dataskipping import (
    BloomFilterSketch,
    DataSkippingIndexConfig,
    MinMaxSketch,
    PartitionSketch,
    ValueListSketch,
)
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import col


def scanned_files(plan):
    files = []
    for node in L.collect(plan, lambda p: True):
        if isinstance(node, (L.FileScan, L.IndexScan)):
            files.extend(node.files)
        elif isinstance(node, L.Scan):
            files.extend(fi.name for fi in node.relation.all_file_infos())
    return files


def sort_batch(batch):
    order = np.lexsort(
        [np.asarray(v).astype("U64") if v.dtype == object else v for v in reversed(list(batch.values()))]
    )
    return {k: v[order] for k, v in batch.items()}


def assert_batches_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    a, b = sort_batch(a), sort_batch(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"column {k}")


@pytest.fixture()
def ranged_parquet(tmp_path):
    """4 files with disjoint ranges of k: [0,100), [100,200), [200,300), [300,400)."""
    root = tmp_path / "ranged"
    root.mkdir()
    rng = np.random.default_rng(0)
    for i in range(4):
        n = 250
        t = pa.table(
            {
                "k": (i * 100 + rng.integers(0, 100, n)).astype(np.int64),
                "v": rng.standard_normal(n),
                "tag": np.array([f"file{i}_val{j % 5}" for j in range(n)]),
            }
        )
        pq.write_table(t, root / f"part-{i:05d}.parquet")
    return str(root)


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


class TestMinMaxPruning:
    def test_range_filter_prunes_files(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsMinMax", MinMaxSketch("k")))
        q = df.filter(col("k") < 150).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1, plan.pretty()
        assert len(fscans[0].files) == 2  # files 0 and 1 overlap k<150
        assert_batches_equal(q.collect(), baseline)

    def test_equality_filter_prunes_to_one_file(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsEq", MinMaxSketch("k")))
        session.enable_hyperspace()
        q = df.filter(col("k") == 250).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())

    def test_conjunction_intersects_masks(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsAnd", MinMaxSketch("k")))
        session.enable_hyperspace()
        q = df.filter((col("k") >= 120) & (col("k") < 180)).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans[0].files) == 1  # only file 1 ([100,200))
        baseline_sess_off = None
        session.disable_hyperspace()
        baseline_sess_off = q.collect()
        session.enable_hyperspace()
        assert_batches_equal(q.collect(), baseline_sess_off)

    def test_unprunable_or_keeps_plan(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsOr", MinMaxSketch("k")))
        session.enable_hyperspace()
        # v has no sketch -> OR side unprunable -> no rewrite at all
        q = df.filter((col("k") < 150) | (col("v") > 0)).select("v")
        plan = q.optimized_plan()
        assert not any(isinstance(p, L.FileScan) for p in L.collect(plan, lambda p: True)), plan.pretty()

    def test_isin_unions_masks(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsIn", MinMaxSketch("k")))
        session.enable_hyperspace()
        q = df.filter(col("k").isin(50, 350)).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans[0].files) == 2  # files 0 and 3
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())


class TestOtherSketches:
    def test_value_list_equality(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsVL", ValueListSketch("tag")))
        session.enable_hyperspace()
        q = df.filter(col("tag") == "file2_val3").select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())

    def test_bloom_filter_equality(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("dsBloom", BloomFilterSketch("tag", 0.001, 2000))
        )
        session.enable_hyperspace()
        q = df.filter(col("tag") == "file1_val0").select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1
        assert len(fscans[0].files) <= 2  # exact with fpp=0.001, allow 1 false positive
        assert any("part-00001" in f for f in fscans[0].files)
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())

    def test_combined_sketches_and_ranking(self, session, hs, ranged_parquet):
        # MinMax on k AND Bloom on tag in one index; both conjuncts prune
        df = session.read_parquet(ranged_parquet)
        hs.create_index(
            df,
            DataSkippingIndexConfig(
                "dsBoth", MinMaxSketch("k"), BloomFilterSketch("tag", 0.001, 2000)
            ),
        )
        session.enable_hyperspace()
        q = df.filter((col("k") < 150) & (col("tag") == "file0_val1")).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1

    def test_partition_sketch(self, session, hs, tmp_path):
        root = tmp_path / "parts"
        root.mkdir()
        for i, region in enumerate(["east", "west", "north"]):
            t = pa.table(
                {
                    "region": np.array([region] * 100),
                    "v": np.arange(100, dtype=np.int64),
                }
            )
            pq.write_table(t, root / f"part-{i:05d}.parquet")
        df = session.read_parquet(str(root))
        hs.create_index(df, DataSkippingIndexConfig("dsPart", PartitionSketch("region")))
        session.enable_hyperspace()
        q = df.filter(col("region") == "west").select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1
        session.disable_hyperspace()
        assert_batches_equal(q.collect(), q.collect())


class TestInteractionWithCoveringIndex:
    def test_covering_index_outranks_data_skipping(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsLow", MinMaxSketch("k")))
        hs.create_index(df, hst.CoveringIndexConfig("ciHigh", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter(col("k") < 150).select("v")
        plan = q.optimized_plan()
        kinds = [type(p).__name__ for p in L.collect(plan, lambda p: True)]
        assert "IndexScan" in kinds and "FileScan" not in kinds, plan.pretty()

    def test_data_skipping_applies_when_covering_cannot(self, session, hs, ranged_parquet):
        # covering index lacks column v in output -> only data skipping fits
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsOnly", MinMaxSketch("k")))
        hs.create_index(df, hst.CoveringIndexConfig("ciNarrow", ["k"], ["tag"]))
        session.enable_hyperspace()
        q = df.filter(col("k") < 150).select("v")
        plan = q.optimized_plan()
        kinds = [type(p).__name__ for p in L.collect(plan, lambda p: True)]
        assert "FileScan" in kinds and "IndexScan" not in kinds, plan.pretty()


class TestDtypeSafety:
    def test_bloom_int_literal_on_float_column_does_not_misprune(self, session, hs, tmp_path):
        # build hashes float64 bit patterns; querying x = 5 (int) must coerce
        # to 5.0 before the membership test, not silently prune the file
        root = tmp_path / "floats"
        root.mkdir()
        pq.write_table(
            pa.table({"x": np.array([5.0, 7.5, 9.25]), "v": np.arange(3, dtype=np.int64)}),
            root / "p0.parquet",
        )
        pq.write_table(
            pa.table({"x": np.array([100.0, 200.0]), "v": np.arange(2, dtype=np.int64)}),
            root / "p1.parquet",
        )
        df = session.read_parquet(str(root))
        hs.create_index(df, DataSkippingIndexConfig("dsFloat", BloomFilterSketch("x", 0.001, 100)))
        session.enable_hyperspace()
        q = df.filter(col("x") == 5).select("v")
        session.disable_hyperspace()
        baseline = q.collect()
        session.enable_hyperspace()
        out = q.collect()
        assert_batches_equal(out, baseline)
        assert len(out["v"]) == 1

    def test_incomparable_literal_does_not_break_other_rewrites(self, session, hs, ranged_parquet):
        # float column vs string literal: the sketch evaluator must treat it
        # as unprunable — not raise and cancel the covering-index rewrite
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsSafe", MinMaxSketch("v")))
        hs.create_index(df, hst.CoveringIndexConfig("ciSafe", ["k"], ["v"]))
        session.enable_hyperspace()
        q = df.filter((col("k") == 5) & (col("v") != "not_a_number")).select("v")
        plan = q.optimized_plan()
        kinds = [type(p).__name__ for p in L.collect(plan, lambda p: True)]
        assert "IndexScan" in kinds, plan.pretty()

    def test_schema_filter_checks_sketch_columns(self, session, hs, ranged_parquet, tmp_path):
        # a DS index is not a candidate for a relation lacking its sketched column
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsCol", MinMaxSketch("k")))
        other = tmp_path / "other"
        other.mkdir()
        pq.write_table(pa.table({"z": np.arange(10, dtype=np.int64)}), other / "p.parquet")
        odf = session.read_parquet(str(other))
        session.enable_hyperspace()
        plan = odf.filter(col("z") < 5).select("z").optimized_plan()
        assert not any(isinstance(p, L.FileScan) for p in L.collect(plan, lambda p: True))


class TestDtypeSafety2:
    def test_bloom_consistent_across_files_with_mixed_dtypes(self, session, hs, tmp_path):
        # an int64 column where one file holds a null surfaces as float64 for
        # that file; hashing must be canonicalized so earlier files don't get
        # mispruned by a coerced literal
        root = tmp_path / "mixed"
        root.mkdir()
        pq.write_table(
            pa.table({"x": np.array([5, 6, 7], dtype=np.int64), "v": np.arange(3, dtype=np.int64)}),
            root / "p0.parquet",
        )
        pq.write_table(
            pa.table({"x": pa.array([100, None, 200], type=pa.int64()), "v": np.arange(3, dtype=np.int64)}),
            root / "p1.parquet",
        )
        df = session.read_parquet(str(root))
        hs.create_index(df, DataSkippingIndexConfig("dsMixed", BloomFilterSketch("x", 0.001, 100)))
        session.enable_hyperspace()
        q = df.filter(col("x") == 5).select("v")
        session.disable_hyperspace()
        baseline = q.collect()
        session.enable_hyperspace()
        out = q.collect()
        assert_batches_equal(out, baseline)
        assert len(out["v"]) == 1

    def test_corrupt_sketch_data_does_not_break_other_rewrites(self, session, hs, ranged_parquet):
        import os

        df = session.read_parquet(ranged_parquet)
        entry = hs.create_index(df, DataSkippingIndexConfig("dsCorrupt", MinMaxSketch("k")))
        hs.create_index(df, hst.CoveringIndexConfig("ciAlive", ["k"], ["v"]))
        for f in entry.content.files:
            with open(f, "wb") as fh:
                fh.write(b"not parquet")
        session.enable_hyperspace()
        q = df.filter(col("k") < 150).select("v")
        plan = q.optimized_plan()
        kinds = [type(p).__name__ for p in L.collect(plan, lambda p: True)]
        assert "IndexScan" in kinds, plan.pretty()

    def test_project_narrows_filescan_columns(self, session, hs, ranged_parquet):
        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsNarrow", MinMaxSketch("k")))
        session.enable_hyperspace()
        q = df.filter(col("k") < 150).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1
        assert sorted(fscans[0].columns) == ["k", "v"]  # tag not read


class TestHybridAndRefresh:
    def test_incremental_refresh_of_ds_index_with_deletes(self, session, hs, ranged_parquet):
        import os

        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsIncDel", MinMaxSketch("k")))
        os.remove(os.path.join(ranged_parquet, "part-00000.parquet"))
        entry = hs.refresh_index("dsIncDel", "incremental")  # must not raise
        assert entry.state == "ACTIVE"
        session.enable_hyperspace()
        df2 = session.read_parquet(ranged_parquet)
        q = df2.filter(col("k") == 250).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1

    def test_deleted_file_does_not_disqualify_ds_index(self, session, hs, ranged_parquet):
        import os

        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsDel", MinMaxSketch("k")))
        os.remove(os.path.join(ranged_parquet, "part-00003.parquet"))
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_DELETED_RATIO, 0.9)
        df2 = session.read_parquet(ranged_parquet)
        q = df2.filter(col("k") < 150).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        # DS index has no lineage column but handles deletes naturally
        assert len(fscans) == 1 and len(fscans[0].files) == 2, plan.pretty()
        assert_batches_equal(q.collect(), baseline)

    def test_appended_files_kept_under_hybrid_scan(self, session, hs, ranged_parquet):
        import os

        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsApp", MinMaxSketch("k")))
        # append a file with k in [0, 100) — unknown to the sketch table
        rng = np.random.default_rng(9)
        t = pa.table(
            {
                "k": rng.integers(0, 100, 50).astype(np.int64),
                "v": rng.standard_normal(50),
                "tag": np.array(["appended"] * 50),
            }
        )
        pq.write_table(t, os.path.join(ranged_parquet, "part-00099.parquet"))
        session.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
        session.conf.set(hst.keys.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
        df2 = session.read_parquet(ranged_parquet)
        q = df2.filter(col("k") > 320).select("v")
        baseline = q.collect()
        session.enable_hyperspace()
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1
        # file 3 (range) + appended file are kept; 0..2 pruned
        assert len(fscans[0].files) == 2
        assert any("part-00099" in f for f in fscans[0].files)
        assert_batches_equal(q.collect(), baseline)

    def test_refresh_full_rebuilds_sketches(self, session, hs, ranged_parquet):
        import os

        df = session.read_parquet(ranged_parquet)
        hs.create_index(df, DataSkippingIndexConfig("dsRef", MinMaxSketch("k")))
        t = pa.table(
            {
                "k": np.full(50, 1000, dtype=np.int64),
                "v": np.zeros(50),
                "tag": np.array(["new"] * 50),
            }
        )
        pq.write_table(t, os.path.join(ranged_parquet, "part-00050.parquet"))
        hs.refresh_index("dsRef", "full")
        session.enable_hyperspace()
        df2 = session.read_parquet(ranged_parquet)
        q = df2.filter(col("k") == 1000).select("v")
        plan = q.optimized_plan()
        fscans = [p for p in L.collect(plan, lambda p: True) if isinstance(p, L.FileScan)]
        assert len(fscans) == 1 and len(fscans[0].files) == 1
        assert len(q.collect()["v"]) == 50


def test_why_not_reports_applied_dataskipping_index(session, tmp_path):
    """why_not and explain must agree: an applied data-skipping index (a
    FileScan rewrite carrying via_index, not an IndexScan) shows up in both
    reports' applied/used lists."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst

    root = tmp_path / "dsdata"
    root.mkdir()
    for i in range(4):
        lo = i * 100
        pq.write_table(
            pa.table({"v": np.arange(lo, lo + 100, dtype=np.int64)}),
            root / f"p{i}.parquet",
        )
    hs = hst.Hyperspace(session)
    df = session.read_parquet(str(root))
    hs.create_index(df, hst.DataSkippingIndexConfig("dsWhy", hst.MinMaxSketch("v")))
    session.enable_hyperspace()
    q = df.filter(hst.col("v") == 123)
    assert "dsWhy" in hs.explain(q).split("Indexes used:")[1]
    report = hs.why_not(q)
    lines = report.splitlines()
    start = lines.index("Applied indexes:")
    section = lines[start + 1 : lines.index("", start)]
    assert "- dsWhy" in section, report


def test_usage_event_reports_applied_dataskipping_index(tmp_path):
    """Telemetry must agree with explain/whyNot: a data-skipping rewrite
    (FileScan via_index, no IndexScan node) counts as index usage."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.telemetry.events import CollectingEventLogger

    root = tmp_path / "dsdata2"
    root.mkdir()
    for i in range(3):
        pq.write_table(
            pa.table({"v": np.arange(i * 100, i * 100 + 100, dtype=np.int64)}),
            root / f"p{i}.parquet",
        )
    sysp = tmp_path / "sys"
    sysp.mkdir()
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: str(sysp),
            hst.keys.EVENT_LOGGER_CLASS: "hyperspace_tpu.telemetry.events.CollectingEventLogger",
        }
    )
    hst.set_session(sess)
    try:
        hs = hst.Hyperspace(sess)
        df = sess.read_parquet(str(root))
        hs.create_index(df, hst.DataSkippingIndexConfig("dsEvt", hst.MinMaxSketch("v")))
        session_logger = hst.telemetry.events.get_event_logger(sess)
        assert isinstance(session_logger, CollectingEventLogger)
        session_logger.events.clear()
        sess.enable_hyperspace()
        df.filter(hst.col("v") == 42).collect()
        usage = [e for e in session_logger.events if type(e).__name__ == "HyperspaceIndexUsageEvent"]
        assert usage and "dsEvt" in usage[-1].index_names, [e.__dict__ for e in usage]
    finally:
        hst.set_session(None)
