"""Docs honesty: every config key must be documented with its default
(ref: docs/_docs/02-ug-configuration.md documents the reference's full table),
and the metric-family reference in docs/observability.md must stay in
lockstep with the instruments the code actually registers."""

import glob
import os
import re

from hyperspace_tpu import config

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs", "configuration.md")
OBS_DOCS = os.path.join(os.path.dirname(__file__), "..", "docs", "observability.md")
PKG = os.path.join(os.path.dirname(__file__), "..", "hyperspace_tpu")


def test_every_config_key_documented():
    text = open(DOCS).read()
    missing = [
        v
        for k, v in vars(config.keys).items()
        if not k.startswith("_") and isinstance(v, str) and f"`{v}`" not in text
    ]
    assert not missing, f"undocumented config keys: {missing}"


def test_documented_defaults_match_code():
    text = open(DOCS).read()
    # spot-check numeric defaults that appear verbatim in the table
    for key, default in config.DEFAULTS.items():
        if isinstance(default, bool):
            assert f"`{str(default).lower()}`" in text or key in (), key
        elif isinstance(default, int) and default >= 100:
            assert f"`{default}`" in text, f"{key} default {default} not documented"


def _registered_metric_families():
    """Every hs_* family name at a registry registration site. The pattern
    anchors on the ``counter(``/``gauge(``/``histogram(`` call so incidental
    hs_-prefixed strings (contextvar names, column prefixes) don't count."""
    pat = re.compile(
        r"""(?:counter|gauge|histogram)\(\s*["'](hs_[a-z0-9_]+)["']""", re.DOTALL
    )
    fams = set()
    for path in glob.glob(os.path.join(PKG, "**", "*.py"), recursive=True):
        fams |= set(pat.findall(open(path).read()))
    return fams


def test_metric_families_documented_and_no_doc_drift():
    code = _registered_metric_families()
    assert len(code) > 20  # the regex found the registration sites at all
    text = open(OBS_DOCS).read()
    doc = set(re.findall(r"\bhs_[a-z0-9_]+[a-z0-9]", text))
    # histogram expositions add _bucket/_sum/_count series; the doc may show
    # them, but they document their base family
    doc_base = {
        re.sub(r"_(bucket|sum|count)$", "", f) if
        re.sub(r"_(bucket|sum|count)$", "", f) in code else f
        for f in doc
    }
    undocumented = sorted(code - doc_base)
    assert not undocumented, f"metric families missing from docs/observability.md: {undocumented}"
    phantom = sorted(doc_base - code)
    assert not phantom, f"docs/observability.md documents families the code never registers: {phantom}"


def test_doc_files_referenced_in_code_exist():
    docs_dir = os.path.join(os.path.dirname(DOCS))
    for name in ("configuration.md", "mutable-data.md", "architecture.md"):
        assert os.path.exists(os.path.join(docs_dir, name)), name
