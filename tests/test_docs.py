"""Docs honesty: every config key must be documented with its default
(ref: docs/_docs/02-ug-configuration.md documents the reference's full table)."""

import os

from hyperspace_tpu import config

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs", "configuration.md")


def test_every_config_key_documented():
    text = open(DOCS).read()
    missing = [
        v
        for k, v in vars(config.keys).items()
        if not k.startswith("_") and isinstance(v, str) and f"`{v}`" not in text
    ]
    assert not missing, f"undocumented config keys: {missing}"


def test_documented_defaults_match_code():
    text = open(DOCS).read()
    # spot-check numeric defaults that appear verbatim in the table
    for key, default in config.DEFAULTS.items():
        if isinstance(default, bool):
            assert f"`{str(default).lower()}`" in text or key in (), key
        elif isinstance(default, int) and default >= 100:
            assert f"`{default}`" in text, f"{key} default {default} not documented"


def test_doc_files_referenced_in_code_exist():
    docs_dir = os.path.join(os.path.dirname(DOCS))
    for name in ("configuration.md", "mutable-data.md", "architecture.md"):
        assert os.path.exists(os.path.join(docs_dir, name)), name
