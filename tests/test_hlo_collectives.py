"""The collective story, proven from compiled HLO (VERDICT round-2 item 5).

The architectural claims (SURVEY.md §2.9; ref shuffle-freedom:
HS/index/covering/JoinIndexRule.scala:604-618) are now DECLARED as
:class:`~hyperspace_tpu.check.hlo_lint.ProgramContract`s next to the program
builders (exec/device.py, ops/bucketize.py) and asserted here through the
rule engine (``assert_contract``):

- distributed index build: exactly ONE all-to-all (the packed-plane exchange)
  and no other collective,
- generic re-bucketing (hybrid-scan delta path): exactly ONE all-to-all,
- hierarchical DCN x ICI exchange: exactly TWO all-to-alls (one per phase),
- the bucketed equi-join: NO data-movement collective at all (all-reduce is
  permitted only for a query's own aggregate),
- plane packing is bit-exact for every exchanged dtype.

``parallel/hlo_check`` remains as a compat shim; one test drives the old
import path to keep it honest.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperspace_tpu.check.hlo_lint import (
    assert_contract,
    collective_counts,
    hlo_text_of,
    verify_hlo,
)
from hyperspace_tpu.exec import device as _device  # noqa: F401  (registers exec contracts)
from hyperspace_tpu.ops import bucketize as bz

pytestmark = pytest.mark.check

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:N_DEV])
    return Mesh(devices, ("buckets",))


def _sharded(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("buckets")))


class TestCompiledCollectives:
    def test_build_exchange_is_one_all_to_all(self, mesh):
        """The production distributed-build program (the real code path
        create_index runs on a >1-device session) conforms to its declared
        contract: exactly one all-to-all, nothing else."""
        capacity = 16
        fn = bz._build_exchange_program(mesh, ("i",), 4 * N_DEV, capacity)
        n = N_DEV * 32
        keys = (_sharded(mesh, np.arange(n, dtype=np.int64)),)
        ridx = _sharded(mesh, np.arange(n, dtype=np.int64))
        txt = fn.lower(keys, (), ridx, np.int64(n)).compile().as_text()
        assert_contract("index-build-exchange", txt, "build exchange")

    def test_build_exchange_composite_keys_still_one(self, mesh):
        """Packing is what keeps the count at one: a composite (int, string)
        key staging 4+ buffers still compiles to a single all-to-all."""
        capacity = 16
        fn = bz._build_exchange_program(mesh, ("i", "s"), 4 * N_DEV, capacity)
        n = N_DEV * 32
        keys = (
            _sharded(mesh, np.arange(n, dtype=np.int64)),
            _sharded(mesh, np.arange(n, dtype=np.int64)),
        )
        hh = (_sharded(mesh, np.arange(n, dtype=np.uint32)),)
        ridx = _sharded(mesh, np.arange(n, dtype=np.int64))
        txt = fn.lower(keys, hh, ridx, np.int64(n)).compile().as_text()
        assert_contract("index-build-exchange", txt, "composite-key build exchange")

    def test_rebucket_is_one_all_to_all(self, mesh):
        """The hybrid-scan delta re-bucketing path: one all-to-all."""
        n = N_DEV * 16

        def run(v, b):
            out, ob, valid, ovf = bz.rebucket(mesh, {"v": v}, b, 32)
            return out["v"], ob, valid, ovf

        v = _sharded(mesh, np.arange(n, dtype=np.float64))
        b = _sharded(mesh, (np.arange(n) % (2 * N_DEV)).astype(np.int32))
        txt = jax.jit(run).lower(v, b).compile().as_text()
        assert_contract("index-rebucket", txt, "rebucket")

    def test_hierarchical_is_two_all_to_alls(self):
        """DCN x ICI two-phase exchange: exactly two (one per phase)."""
        from hyperspace_tpu.parallel.mesh import make_mesh_2d, sharded_2d

        mesh2d = make_mesh_2d(n_slices=2, per_slice=N_DEV // 2)
        sh2 = sharded_2d(mesh2d)
        n = N_DEV * 16

        def run(v, b):
            out, ob, valid, ovf = bz.rebucket_hierarchical(mesh2d, {"v": v}, b, 32, 32)
            return out["v"], ob, valid, ovf

        v = jax.device_put(np.arange(n, dtype=np.float64), sh2)
        b = jax.device_put((np.arange(n) % (4 * N_DEV)).astype(np.int32), sh2)
        txt = jax.jit(run).lower(v, b).compile().as_text()
        assert_contract("hierarchical-exchange", txt, "hierarchical exchange")

    def test_bucketed_join_has_no_data_collectives(self, mesh):
        """Co-sharded bucketed equi-join: no all-to-all / all-gather /
        collective-permute / reduce-scatter anywhere in the compiled program.
        (The final scalar psum is the query's own aggregate — all-reduce — and
        is the ONLY collective present.)"""
        from hyperspace_tpu.parallel.mesh import get_shard_map

        shard_map = get_shard_map()
        nk = N_DEV * 32
        sharding = NamedSharding(mesh, P("buckets"))

        @partial(jax.jit, out_shardings=NamedSharding(mesh, P()))
        def join_step(lk, lv, rk, rv):
            @partial(shard_map, mesh=mesh, in_specs=(P("buckets"),) * 4, out_specs=P())
            def per_shard(lk_, lv_, rk_, rv_):
                idx = jnp.searchsorted(rk_, lk_)
                idx = jnp.clip(idx, 0, rk_.shape[0] - 1)
                matched = rk_[idx] == lk_
                contrib = jnp.sum(jnp.where(matched, lv_ * rv_[idx], 0.0))
                return jax.lax.psum(contrib, "buckets")

            return per_shard(lk, lv, rk, rv)

        args = [
            jax.device_put(np.arange(nk, dtype=np.int64), sharding),
            jax.device_put(np.arange(nk, dtype=np.float64), sharding),
            jax.device_put(np.arange(nk, dtype=np.int64), sharding),
            jax.device_put(np.arange(nk, dtype=np.float64), sharding),
        ]
        txt = join_step.lower(*args).compile().as_text()
        counts = collective_counts(txt)
        assert counts["all-to-all"] == 0, counts
        assert counts["all-gather"] == 0, counts
        assert counts["collective-permute"] == 0, counts
        assert counts["reduce-scatter"] == 0, counts
        assert counts["all-reduce"] <= 1, counts  # the aggregate's psum only


class TestPlanePacking:
    @pytest.mark.parametrize(
        "dtype,vals",
        [
            (np.int64, [-(2**62), -1, 0, 1, 2**62]),
            (np.uint64, [0, 1, 2**63, 2**64 - 1]),
            (np.float64, [-1.5, 0.0, np.nan, np.inf, 1e300]),
            (np.int32, [-(2**31), -1, 0, 2**31 - 1]),
            (np.uint32, [0, 1, 2**32 - 1]),
            (np.float32, [-1.5, 0.0, np.nan, 3.4e38]),
            (np.float16, [-1.5, 0.25, np.nan, 65504.0]),
            ("bfloat16", [-1.5, 0.25, float("nan"), 3.0e38]),
            (np.int16, [-(2**15), -1, 0, 2**15 - 1]),
            (np.int8, [-128, -1, 0, 127]),
            (np.bool_, [True, False, True]),
        ],
    )
    def test_roundtrip_bit_exact(self, dtype, vals):
        if dtype == "bfloat16":
            import ml_dtypes

            dtype = ml_dtypes.bfloat16
        v = jnp.asarray(np.array(vals, dtype=dtype))
        planes = bz._to_planes(v)
        back = bz._from_planes(planes, dtype)
        assert back.dtype == jnp.asarray(v).dtype
        np.testing.assert_array_equal(
            np.asarray(back).view(np.uint8), np.asarray(v).view(np.uint8)
        )


class TestShardedExecPrograms:
    """The mesh-sharded execution engine's own programs (PR: parallel
    subsystem), asserted through their declared contracts."""

    def test_bucketed_smj_span_program_is_shuffle_free(self, mesh):
        """The REAL bucketed-SMJ span program (device._bucketed_span_program —
        what device joins execute) conforms to its zero-collective contract:
        co-sharded buckets join device-locally."""
        from hyperspace_tpu.exec import device as D

        prog = D._bucketed_span_program(mesh, "buckets")
        sharding = NamedSharding(mesh, P("buckets"))
        rng = np.random.default_rng(0)
        lm = jax.device_put(np.sort(rng.integers(0, 1000, (N_DEV * 2, 32)).astype(np.int64), axis=1), sharding)
        rm = jax.device_put(np.sort(rng.integers(0, 1000, (N_DEV * 2, 48)).astype(np.int64), axis=1), sharding)
        txt = hlo_text_of(prog, lm, rm)
        assert_contract("bucketed-smj-span", txt, "bucketed SMJ span program")

    def test_sharded_filter_program_is_shuffle_free(self, mesh):
        """The sharded predicate program moves no rows between devices; the
        old parallel.hlo_check import path (compat shim) must keep working."""
        from hyperspace_tpu.parallel import assert_shuffle_free, hlo_text_of as shim_text_of
        from hyperspace_tpu.parallel import collectives as C

        fn = C.sharded_elementwise(mesh, "buckets", lambda cols, lits: cols["a"] > lits[0])
        dev = jax.device_put(
            np.arange(N_DEV * 16, dtype=np.int64), NamedSharding(mesh, P("buckets"))
        )
        txt = shim_text_of(jax.jit(fn), {"a": dev}, (np.int64(3),))
        assert_shuffle_free(txt, "sharded filter")
        assert_contract("fused-filter", txt, "sharded filter")

    def test_sharded_grouped_agg_gathers_partials_not_rows(self, mesh):
        """The collective-merged grouped aggregate all-gathers O(cap)
        per-shard partial tables — never an all-to-all row exchange. Its
        contract encodes exactly that (all-gather >= 1, all-to-all = 0)."""
        from hyperspace_tpu.parallel import collectives as C

        prog = C.sharded_grouped_chunk_program(
            mesh, "buckets", None, (("k", "i"),), [("cntm", None, True)], 32
        )
        dev = jax.device_put(
            (np.arange(N_DEV * 64) % 17).astype(np.int64),
            NamedSharding(mesh, P("buckets")),
        )
        txt = hlo_text_of(jax.jit(prog), {"k": dev}, (), np.int64(N_DEV * 64), np.int64(0))
        got = collective_counts(txt)
        assert got["all-gather"] >= 1, got
        assert not verify_hlo("sharded-grouped", txt, "sharded grouped chunk")
