"""SQL front-end tests: the dialect plans onto the same IR the optimizer
rules rewrite, so indexes apply to SQL queries exactly as to dataframe ones
(the reference's users drive Hyperspace through Spark SQL)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.sql import SqlError, parse


@pytest.fixture()
def hs(session):
    return hst.Hyperspace(session)


@pytest.fixture()
def views(session, tmp_path):
    rng = np.random.default_rng(5)
    n = 600
    sales = pa.table(
        {
            "region": np.array([f"r{i % 8}" for i in range(n)]),
            "user": rng.integers(0, 40, n).astype(np.int64),
            "amount": np.round(rng.uniform(0, 100, n), 2),
            "day": np.datetime64("2024-01-01") + rng.integers(0, 90, n).astype("timedelta64[D]"),
        }
    )
    users = pa.table(
        {
            "user": np.arange(40, dtype=np.int64),
            "tier": np.array(["gold" if i % 5 == 0 else "std" for i in range(40)]),
        }
    )
    sroot, uroot = tmp_path / "sales", tmp_path / "users"
    sroot.mkdir(), uroot.mkdir()
    pq.write_table(sales, sroot / "p.parquet")
    pq.write_table(users, uroot / "p.parquet")
    sdf = session.read_parquet(str(sroot))
    udf = session.read_parquet(str(uroot))
    sdf.create_or_replace_temp_view("sales")
    udf.create_or_replace_temp_view("users")
    return sdf, udf


class TestSqlBasics:
    def test_select_star(self, session, views):
        got = session.sql("SELECT * FROM sales").collect()
        assert set(got.keys()) == {"region", "user", "amount", "day"}
        assert got["user"].shape[0] == 600

    def test_filter_and_project(self, session, views):
        sdf, _ = views
        got = session.sql("SELECT amount FROM sales WHERE region = 'r3'").collect()
        expected = sdf.filter(hst.col("region") == "r3").select("amount").collect()
        np.testing.assert_array_equal(np.sort(got["amount"]), np.sort(expected["amount"]))

    def test_predicates(self, session, views):
        sdf, _ = views
        cases = [
            ("SELECT user FROM sales WHERE amount > 50 AND amount <= 70", (hst.col("amount") > 50) & (hst.col("amount") <= 70)),
            ("SELECT user FROM sales WHERE user IN (1, 2, 3)", hst.col("user").isin(1, 2, 3)),
            ("SELECT user FROM sales WHERE NOT user = 5", ~(hst.col("user") == 5)),
            ("SELECT user FROM sales WHERE amount BETWEEN 10 AND 20", (hst.col("amount") >= 10) & (hst.col("amount") <= 20)),
            ("SELECT user FROM sales WHERE amount * 2 > 150", hst.col("amount") * 2 > 150),
            ("SELECT user FROM sales WHERE region != 'r0' OR user < 3", (hst.col("region") != "r0") | (hst.col("user") < 3)),
        ]
        for text, cond in cases:
            got = session.sql(text).collect()
            expected = sdf.filter(cond).select("user").collect()
            np.testing.assert_array_equal(np.sort(got["user"]), np.sort(expected["user"]), err_msg=text)

    def test_date_literal(self, session, views):
        sdf, _ = views
        got = session.sql("SELECT user FROM sales WHERE day >= DATE '2024-03-01'").collect()
        expected = sdf.filter(hst.col("day") >= hst.lit(np.datetime64("2024-03-01"))).select("user").collect()
        assert got["user"].shape == expected["user"].shape

    def test_order_and_limit(self, session, views):
        got = session.sql("SELECT user, amount FROM sales ORDER BY amount DESC LIMIT 5").collect()
        assert got["amount"].shape[0] == 5
        assert np.all(np.diff(got["amount"]) <= 0)

    def test_group_by(self, session, views):
        sdf, _ = views
        got = session.sql(
            "SELECT region, SUM(amount) AS total, COUNT(*) AS n FROM sales GROUP BY region"
        ).collect()
        assert set(got.keys()) == {"region", "total", "n"}
        assert int(got["n"].sum()) == 600
        expected = sdf.group_by("region").agg(total=("amount", "sum")).collect()
        a = dict(zip(got["region"], np.round(got["total"], 4)))
        b = dict(zip(expected["region"], np.round(expected["total"], 4)))
        assert a == b

    def test_global_aggregate(self, session, views):
        got = session.sql("SELECT COUNT(*) AS n, MAX(amount) AS m FROM sales").collect()
        assert int(got["n"][0]) == 600


class TestSqlJoins:
    def test_join_with_qualifiers(self, session, views):
        sdf, udf = views
        got = session.sql(
            "SELECT amount, tier FROM sales s JOIN users u ON s.user = u.user WHERE tier = 'gold'"
        ).collect()
        expected = (
            sdf.join(udf, on="user").filter(hst.col("tier") == "gold").select("amount", "tier").collect()
        )
        np.testing.assert_array_equal(np.sort(got["amount"]), np.sort(expected["amount"]))

    def test_left_join(self, session, views):
        got = session.sql(
            "SELECT amount, tier FROM sales s LEFT JOIN users u ON s.user = u.user"
        ).collect()
        assert got["amount"].shape[0] == 600

    def test_join_duplicate_column_qualifier(self, session, views):
        got = session.sql("SELECT s.user, u.user FROM sales s JOIN users u ON s.user = u.user").collect()
        assert set(got.keys()) == {"user", "user#r"}


class TestSqlUsesIndexes:
    def test_filter_index_applies_to_sql(self, session, hs, views):
        sdf, _ = views
        hs.create_index(sdf, hst.CoveringIndexConfig("sqlIdx", ["region"], ["amount"]))
        session.enable_hyperspace()
        q = session.sql("SELECT amount FROM sales WHERE region = 'r2'")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda x: True)), plan.pretty()
        session.disable_hyperspace()
        baseline = np.sort(q.collect()["amount"])
        session.enable_hyperspace()
        np.testing.assert_array_equal(np.sort(q.collect()["amount"]), baseline)

    def test_join_index_applies_to_sql(self, session, hs, views):
        sdf, udf = views
        hs.create_index(sdf, hst.CoveringIndexConfig("sqlJL", ["user"], ["amount"]))
        hs.create_index(udf, hst.CoveringIndexConfig("sqlJR", ["user"], ["tier"]))
        session.enable_hyperspace()
        q = session.sql("SELECT amount, tier FROM sales s JOIN users u ON s.user = u.user")
        plan = q.optimized_plan()
        scans = [p for p in L.collect(plan, lambda x: isinstance(x, L.IndexScan))]
        assert len(scans) == 2, plan.pretty()

    def test_explain_works_on_sql(self, session, hs, views):
        sdf, _ = views
        hs.create_index(sdf, hst.CoveringIndexConfig("sqlEx", ["region"], ["amount"]))
        session.enable_hyperspace()
        text = hs.explain(session.sql("SELECT amount FROM sales WHERE region = 'r1'"))
        assert "sqlEx" in text


class TestSqlErrors:
    def test_unknown_view(self, session, views):
        with pytest.raises(SqlError, match="Unknown table"):
            session.sql("SELECT * FROM nope")

    def test_unknown_column(self, session, views):
        with pytest.raises((SqlError, ValueError)):
            session.sql("SELECT missing FROM sales").collect()

    def test_group_by_requires_aggregate_membership(self, session, views):
        with pytest.raises(SqlError, match="GROUP BY"):
            session.sql("SELECT user, SUM(amount) FROM sales GROUP BY region")

    def test_trailing_garbage(self, session, views):
        with pytest.raises(SqlError, match="trailing"):
            session.sql("SELECT * FROM sales WINDOW w")

    def test_having_without_group_by_raises(self, session, views):
        with pytest.raises(SqlError, match="HAVING"):
            session.sql("SELECT * FROM sales HAVING user > 1")

    def test_parse_shapes(self):
        q = parse("SELECT a, SUM(b) AS s FROM t GROUP BY a ORDER BY a DESC LIMIT 3")
        assert q.table == "t" and q.limit == 3
        assert q.order_by == [("a", False)]
        assert q.items[1].agg == ("sum", "b")

    def test_string_escape(self, session, views, tmp_path):
        import pyarrow.parquet as pq

        root = tmp_path / "esc"
        root.mkdir()
        pq.write_table(
            pa.table({"s": np.array(["it's", "plain"]), "v": np.array([1, 2], dtype=np.int64)}),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("esc")
        got = session.sql("SELECT v FROM esc WHERE s = 'it''s'").collect()
        assert got["v"].tolist() == [1]


class TestSqlAliasesAndQualifiers:
    def test_plain_projection_alias_renames(self, session, views):
        got = session.sql("SELECT region AS zone, amount FROM sales LIMIT 4").collect()
        assert set(got.keys()) == {"zone", "amount"}

    def test_group_key_alias(self, session, views):
        got = session.sql(
            "SELECT region AS zone, SUM(amount) AS total FROM sales GROUP BY region"
        ).collect()
        assert set(got.keys()) == {"zone", "total"}

    def test_order_by_alias(self, session, views):
        got = session.sql(
            "SELECT amount AS amt FROM sales ORDER BY amt DESC LIMIT 3"
        ).collect()
        assert np.all(np.diff(got["amt"]) <= 0)

    def test_qualified_where_binds_right_side(self, session, tmp_path):
        """The standard anti-join shape: WHERE right.col IS NULL must test
        the RIGHT side's (possibly '#r'-renamed) column, not the left twin."""
        lroot, rroot = tmp_path / "aj_l", tmp_path / "aj_r"
        lroot.mkdir(), rroot.mkdir()
        pq.write_table(
            pa.table({"k": np.array([1, 2, 3], dtype=np.int64), "v": np.array([10, 20, 30], dtype=np.int64)}),
            lroot / "p.parquet",
        )
        pq.write_table(
            pa.table({"k": np.array([1, 2], dtype=np.int64), "v": np.array([100, 200], dtype=np.int64)}),
            rroot / "p.parquet",
        )
        session.read_parquet(str(lroot)).create_or_replace_temp_view("aj_l")
        session.read_parquet(str(rroot)).create_or_replace_temp_view("aj_r")
        got = session.sql(
            "SELECT l.k FROM aj_l l LEFT JOIN aj_r r ON l.k = r.k WHERE r.v IS NULL"
        ).collect()
        assert got["k"].tolist() == [3]

    def test_qualified_group_and_order(self, session, views):
        got = session.sql(
            "SELECT s.region, COUNT(*) AS n FROM sales s GROUP BY s.region ORDER BY s.region"
        ).collect()
        assert got["region"].shape[0] == 8
        assert list(got["region"]) == sorted(got["region"])


class TestSelectExpressions:
    def test_arithmetic_in_select(self, session, views):
        got = session.sql("SELECT amount * 2 AS dbl, user + 1 AS u1 FROM sales LIMIT 5").collect()
        assert set(got.keys()) == {"dbl", "u1"}
        full = session.sql("SELECT amount, user FROM sales LIMIT 5").collect()
        np.testing.assert_allclose(got["dbl"], full["amount"] * 2)
        np.testing.assert_array_equal(got["u1"], full["user"] + 1)

    def test_default_name_is_source_text(self, session, views):
        got = session.sql("SELECT amount * 2 FROM sales LIMIT 1").collect()
        assert list(got.keys()) == ["amount * 2"]

    def test_mixed_plain_and_expression(self, session, views):
        got = session.sql("SELECT region, amount - 1 AS am FROM sales LIMIT 3").collect()
        assert set(got.keys()) == {"region", "am"}

    def test_expression_of_aggregates(self, session, views):
        got = session.sql(
            "SELECT region, SUM(amount) / COUNT(*) AS avg_amt, MAX(amount) - MIN(amount) AS spread "
            "FROM sales GROUP BY region"
        ).collect()
        ref = session.sql(
            "SELECT region, SUM(amount) AS s, COUNT(*) AS n, MAX(amount) AS mx, MIN(amount) AS mn "
            "FROM sales GROUP BY region"
        ).collect()
        a = dict(zip(got["region"], np.round(got["avg_amt"], 6)))
        b = dict(zip(ref["region"], np.round(ref["s"] / ref["n"], 6)))
        assert a == b
        s = dict(zip(got["region"], np.round(got["spread"], 6)))
        t = dict(zip(ref["region"], np.round(ref["mx"] - ref["mn"], 6)))
        assert s == t

    def test_aggregate_of_expression(self, session, views):
        got = session.sql("SELECT SUM(amount * 2) AS s2, SUM(amount) AS s FROM sales").collect()
        assert np.isclose(got["s2"][0], 2 * got["s"][0])

    def test_expression_unknown_column_raises(self, session, views):
        with pytest.raises(SqlError, match="Unknown columns"):
            session.sql("SELECT nope + 1 FROM sales")

    def test_index_rewrite_under_select_expression(self, session, hs, views):
        sdf, _ = views
        hs.create_index(sdf, hst.CoveringIndexConfig("exprIdx", ["region"], ["amount"]))
        session.enable_hyperspace()
        q = session.sql("SELECT amount * 3 AS a3 FROM sales WHERE region = 'r1'")
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda x: True)), plan.pretty()
        session.disable_hyperspace()
        base = np.sort(q.collect()["a3"])
        session.enable_hyperspace()
        np.testing.assert_array_equal(np.sort(q.collect()["a3"]), base)


class TestOrderByNonProjected:
    def test_order_by_dropped_column(self, session, views):
        got = session.sql("SELECT user FROM sales ORDER BY amount DESC LIMIT 5").collect()
        ref = session.sql("SELECT user, amount FROM sales ORDER BY amount DESC LIMIT 5").collect()
        assert list(got.keys()) == ["user"]
        np.testing.assert_array_equal(got["user"], ref["user"])

    def test_order_by_mixed_alias_and_dropped(self, session, views):
        got = session.sql(
            "SELECT user AS u FROM sales ORDER BY region ASC, u DESC LIMIT 10"
        ).collect()
        assert list(got.keys()) == ["u"]
        ref = session.sql(
            "SELECT user, region FROM sales ORDER BY region ASC, user DESC LIMIT 10"
        ).collect()
        np.testing.assert_array_equal(got["u"], ref["user"])

    def test_order_by_unknown_raises(self, session, views):
        with pytest.raises(SqlError, match="ORDER BY"):
            session.sql("SELECT user FROM sales ORDER BY nope")

    def test_distinct_alias_order_by_source_name(self, session, views):
        got = session.sql(
            "SELECT DISTINCT region AS zone FROM sales ORDER BY region"
        ).collect()
        assert list(got.keys()) == ["zone"]
        assert list(got["zone"]) == sorted(got["zone"])

    def test_case_insensitive_expression_refs(self, session, views):
        got = session.sql("SELECT AMOUNT * 2 AS d FROM sales LIMIT 2").collect()
        assert "d" in got
        agg = session.sql("SELECT SUM(AMOUNT * 1) AS s FROM sales").collect()
        ref = session.sql("SELECT SUM(amount) AS s FROM sales").collect()
        assert np.isclose(agg["s"][0], ref["s"][0])


class TestCtes:
    def test_basic_cte(self, session, views):
        got = session.sql(
            "WITH big AS (SELECT user, amount FROM sales WHERE amount > 50) "
            "SELECT user FROM big WHERE amount < 60"
        ).collect()
        ref = session.sql("SELECT user FROM sales WHERE amount > 50 AND amount < 60").collect()
        np.testing.assert_array_equal(np.sort(got["user"]), np.sort(ref["user"]))

    def test_cte_chain(self, session, views):
        got = session.sql(
            "WITH a AS (SELECT region, amount FROM sales WHERE amount > 20), "
            "b AS (SELECT region, SUM(amount) AS total FROM a GROUP BY region) "
            "SELECT region, total FROM b ORDER BY region"
        ).collect()
        ref = session.sql(
            "SELECT region, SUM(amount) AS total FROM sales WHERE amount > 20 "
            "GROUP BY region ORDER BY region"
        ).collect()
        np.testing.assert_array_equal(got["region"], ref["region"])
        np.testing.assert_allclose(got["total"], ref["total"])

    def test_cte_join(self, session, views):
        got = session.sql(
            "WITH gold AS (SELECT user, tier FROM users WHERE tier = 'gold') "
            "SELECT amount FROM sales s JOIN gold g ON s.user = g.user"
        ).collect()
        ref = session.sql(
            "SELECT amount FROM sales s JOIN users u ON s.user = u.user WHERE tier = 'gold'"
        ).collect()
        np.testing.assert_array_equal(np.sort(got["amount"]), np.sort(ref["amount"]))

    def test_shared_cte_pruning_reaches_fixpoint(self, session, views):
        """b's 'amount' is needed ONLY through the twice-referenced CTE a:
        execution-time pruning prunes shared roots to a fixpoint, so b must
        regain 'amount' when a's (deferred) pruning records the need —
        pruning b once in preorder would drop it (KeyError at execution)."""
        got = session.sql(
            "WITH b AS (SELECT user, region, amount FROM sales), "
            "a AS (SELECT user, amount FROM b) "
            "SELECT b.region, a1.amount, a2.amount AS amount2 "
            "FROM b JOIN a a1 ON b.user = a1.user JOIN a a2 ON b.user = a2.user"
        ).collect()
        ref = session.sql(
            "SELECT s.region, s2.amount, s3.amount AS amount2 "
            "FROM sales s JOIN sales s2 ON s.user = s2.user "
            "JOIN sales s3 ON s.user = s3.user"
        ).collect()
        order = np.lexsort((got["amount2"], got["amount"], got["region"].astype("U16")))
        rorder = np.lexsort((ref["amount2"], ref["amount"], ref["region"].astype("U16")))
        for c in got:
            np.testing.assert_array_equal(got[c][order], ref[c][rorder], err_msg=c)

    def test_implied_disjunction_pushes_per_frame_prefilters(self, session, views):
        """A disjunction whose every branch constrains a frame implies the
        OR of those per-frame constraints, which must prefilter BELOW the
        join (redundantly — the full predicate still applies above)."""
        sql = (
            "SELECT amount FROM sales s, users u WHERE s.user = u.user AND "
            "((u.tier = 'gold' AND s.amount > 50) OR (u.tier = 'std' AND s.amount < 10))"
        )
        q = session.sql(sql)
        plan = q.optimized_plan().pretty()
        # the users frame gets the implied tier prefilter below the join
        assert "Join" in plan
        join_pos = plan.index("Join")
        below = plan[join_pos:]
        assert "'gold'" in below and "'std'" in below, plan
        got = np.sort(q.collect()["amount"])
        ref = session.sql(
            "SELECT amount FROM sales s JOIN users u ON s.user = u.user "
            "WHERE (u.tier = 'gold' AND s.amount > 50) OR (u.tier = 'std' AND s.amount < 10)"
        ).collect()
        np.testing.assert_array_equal(got, np.sort(ref["amount"]))

    def test_setop_branch_keeps_columns_under_shared_scan(self, session, views):
        """A shared scan referenced both under a set-op and under a
        differently-pruned projection: the sharing-preserving prune must
        record the set-op branch's needs too, or the swapped replacement
        loses columns that branch reads (KeyError at execution)."""
        base = session.sql("SELECT user, region, amount FROM sales").collect()
        got = session.sql(
            "SELECT t.user FROM "
            "(SELECT user FROM sales EXCEPT SELECT user FROM sales WHERE region = 'r2') t "
            "JOIN (SELECT user, amount FROM sales WHERE amount > 50) b ON t.user = b.user"
        ).collect()
        users, region, amount = base["user"], base["region"], base["amount"]
        keep = sorted(set(users.tolist()) - set(users[region == "r2"].tolist()))
        b_users = users[amount > 50]
        expected = sorted(u for u in keep for _ in range(int((b_users == u).sum())))
        assert expected, "fixture produced a vacuous case"
        assert sorted(got["user"].tolist()) == expected

    def test_index_applies_inside_cte(self, session, hs, views):
        sdf, _ = views
        hs.create_index(sdf, hst.CoveringIndexConfig("cteIdx", ["region"], ["amount"]))
        session.enable_hyperspace()
        q = session.sql(
            "WITH r2 AS (SELECT amount FROM sales WHERE region = 'r2') SELECT amount FROM r2"
        )
        plan = q.optimized_plan()
        assert any(isinstance(p, L.IndexScan) for p in L.collect(plan, lambda x: True)), plan.pretty()


class TestSqlSubqueries:
    def test_scalar_subquery_reference_scenario(self, session, hs, views):
        """The reference's explain golden scenario, in SQL text: outer filter
        compares against a scalar subquery whose inner filter the index
        rewrites (ref: src/test/resources/expected/spark-3.1/subquery.txt)."""
        sdf, _ = views
        hs.create_index(sdf, hst.CoveringIndexConfig("subqIdx", ["user"], ["amount"]))
        session.enable_hyperspace()
        q = session.sql(
            "SELECT amount FROM sales WHERE amount = (SELECT MAX(amount) FROM sales WHERE user = 7)"
        )
        got = q.collect()
        session.disable_hyperspace()
        want = q.collect()
        np.testing.assert_array_equal(np.sort(got["amount"]), np.sort(want["amount"]))
        assert got["amount"].shape[0] >= 1

    def test_in_subquery(self, session, views):
        got = session.sql(
            "SELECT amount FROM sales WHERE user IN (SELECT user FROM users WHERE tier = 'gold')"
        ).collect()
        ref = session.sql(
            "SELECT amount FROM sales s JOIN users u ON s.user = u.user WHERE tier = 'gold'"
        ).collect()
        np.testing.assert_array_equal(np.sort(got["amount"]), np.sort(ref["amount"]))

    def test_not_in_subquery(self, session, views):
        got = session.sql(
            "SELECT amount FROM sales WHERE user NOT IN (SELECT user FROM users WHERE tier = 'gold')"
        ).collect()
        inn = session.sql(
            "SELECT amount FROM sales WHERE user IN (SELECT user FROM users WHERE tier = 'gold')"
        ).collect()
        assert got["amount"].shape[0] + inn["amount"].shape[0] == 600

    def test_in_subquery_index_rewrite_inside(self, session, hs, views):
        _, udf = views
        hs.create_index(udf, hst.CoveringIndexConfig("subqInIdx", ["tier"], ["user"]))
        session.enable_hyperspace()
        q = session.sql(
            "SELECT amount FROM sales WHERE user IN (SELECT user FROM users WHERE tier = 'gold')"
        )
        from test_subquery import subquery_plans

        plan = q.optimized_plan()
        inner = subquery_plans(plan)
        assert any(
            isinstance(p, L.IndexScan) for sp in inner for p in L.collect(sp, lambda x: True)
        ), plan.pretty()

    def test_scalar_subquery_in_select_item(self, session, views):
        got = session.sql(
            "SELECT (SELECT MAX(amount) FROM sales) AS mx, user FROM sales LIMIT 3"
        ).collect()
        full = session.sql("SELECT MAX(amount) AS m FROM sales").collect()
        assert np.allclose(got["mx"], full["m"][0])

    def test_scalar_subquery_arithmetic(self, session, views):
        got = session.sql(
            "SELECT amount FROM sales WHERE amount > (SELECT MAX(amount) FROM sales) - 1"
        ).collect()
        assert got["amount"].shape[0] >= 1
        mx = session.sql("SELECT MAX(amount) AS m FROM sales").collect()["m"][0]
        assert np.all(got["amount"] > mx - 1)


class TestWindowFunctions:
    def test_rank_over_partition(self, session, views):
        got = session.sql(
            "SELECT user, rank() OVER (PARTITION BY region ORDER BY amount DESC) AS r FROM sales"
        ).collect()
        sdf, _ = views
        pdf = sdf.to_pandas()
        want = pdf.groupby("region")["amount"].rank(method="min", ascending=False).astype(int)
        assert sorted(got["r"].tolist()) == sorted(want.tolist())

    def test_row_number_and_dense_rank(self, session, views):
        got = session.sql(
            "SELECT row_number() OVER (PARTITION BY region ORDER BY amount) AS rn, "
            "dense_rank() OVER (PARTITION BY region ORDER BY user) AS dr FROM sales"
        ).collect()
        assert got["rn"].min() == 1 and got["dr"].min() == 1
        # row numbers are unique within each region
        sdf, _ = views
        n_regions = len(set(sdf.to_pandas()["region"]))
        assert (got["rn"] == 1).sum() == n_regions

    def test_agg_window_over_group_by(self, session, views):
        """The TPC-DS q12 shape: sum(x)*100/sum(sum(x)) OVER (PARTITION ...)."""
        got = session.sql(
            "SELECT region, user, SUM(amount) AS rev, "
            "SUM(amount) * 100 / SUM(SUM(amount)) OVER (PARTITION BY region) AS ratio "
            "FROM sales GROUP BY region, user"
        ).collect()
        sdf, _ = views
        w = sdf.to_pandas().groupby(["region", "user"], as_index=False)["amount"].sum()
        w["ratio"] = w["amount"] * 100 / w.groupby("region")["amount"].transform("sum")
        a = {(r, u): round(v, 6) for r, u, v in zip(got["region"], got["user"], got["ratio"])}
        b = {(r, u): round(v, 6) for r, u, v in zip(w["region"], w["user"], w["ratio"])}
        assert a == b
        # per-partition ratios sum to 100
        import pandas as pd

        sums = pd.Series(got["ratio"]).groupby(pd.Series(got["region"])).sum()
        assert np.allclose(sums, 100.0)

    def test_cumulative_rows_frame(self, session, views):
        got = session.sql(
            "SELECT amount, SUM(amount) OVER (PARTITION BY region ORDER BY amount "
            "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS cume FROM sales"
        ).collect()
        assert got["cume"].shape[0] == 600
        assert np.all(got["cume"] >= got["amount"] - 1e-9)

    def test_window_in_derived_table_filter(self, session, views):
        """The q53 shape: window in a derived table, filtered outside."""
        got = session.sql(
            "SELECT * FROM (SELECT user, SUM(amount) s, "
            "AVG(SUM(amount)) OVER (PARTITION BY region) a "
            "FROM sales GROUP BY region, user) t WHERE s > a"
        ).collect()
        sdf, _ = views
        w = sdf.to_pandas().groupby(["region", "user"], as_index=False)["amount"].sum()
        w["a"] = w.groupby("region")["amount"].transform("mean")
        assert got["s"].shape[0] == int((w["amount"] > w["a"]).sum()) > 0

    def test_cumulative_min_interleaved_partitions(self, session, tmp_path):
        """Running MIN with partitions whose order keys interleave: the
        per-row running minimum can never exceed the current row's value."""
        root = tmp_path / "cmin"
        root.mkdir()
        rng = np.random.default_rng(11)
        pq.write_table(
            pa.table({"g": np.array([f"g{v}" for v in rng.integers(0, 3, 60)]),
                      "v": np.round(rng.uniform(0, 10, 60), 2)}),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("cmin")
        got = session.sql(
            "SELECT v, MIN(v) OVER (PARTITION BY g ORDER BY v "
            "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS m FROM cmin"
        ).collect()
        assert np.all(got["m"] <= got["v"] + 1e-9)
        # each partition's running min equals its global min at the top row
        import pandas as pd

        t = pq.read_table(root / "p.parquet").to_pandas()
        assert np.isclose(pd.Series(got["m"]).min(), t["v"].min())

    def test_over_words_stay_valid_identifiers(self, session, tmp_path):
        """'partition', 'row', 'rows' are contextual words, not reserved."""
        root = tmp_path / "ctx"
        root.mkdir()
        pq.write_table(
            pa.table({"partition": np.array([1, 2], dtype=np.int64),
                      "row": np.array([10, 20], dtype=np.int64)}),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("ctx")
        got = session.sql("SELECT partition, row AS rows FROM ctx ORDER BY partition").collect()
        assert got["partition"].tolist() == [1, 2]
        assert got["rows"].tolist() == [10, 20]

    def test_rollup_with_grouping(self, session, views):
        got = session.sql(
            "SELECT region, SUM(amount) AS s, grouping(region) AS g "
            "FROM sales GROUP BY ROLLUP(region) ORDER BY g DESC, region"
        ).collect()
        sdf, _ = views
        pdf = sdf.to_pandas()
        assert got["s"].shape[0] == pdf["region"].nunique() + 1
        assert got["g"][0] == 1 and got["region"][0] is None
        assert np.isclose(got["s"][0], pdf["amount"].sum())
        assert np.allclose(np.sort(got["s"][1:]), np.sort(pdf.groupby("region")["amount"].sum()))

    def test_cumulative_sum_skips_nulls(self, session, tmp_path):
        root = tmp_path / "cnull"
        root.mkdir()
        pq.write_table(
            pa.table({"g": np.array(["a", "a", "a"]),
                      "o": np.array([1, 2, 3], dtype=np.int64),
                      "v": np.array([1.0, np.nan, 2.0])}),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("cnull")
        got = session.sql(
            "SELECT SUM(v) OVER (PARTITION BY g ORDER BY o "
            "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS c FROM cnull ORDER BY o"
        ).collect()
        assert got["c"].tolist() == [1.0, 1.0, 3.0]  # NULL skipped, not a hole

    def test_rows_frame_requires_order_by(self, session, views):
        with pytest.raises(SqlError, match="requires ORDER BY"):
            session.sql(
                "SELECT SUM(amount) OVER (PARTITION BY region "
                "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) FROM sales"
            )

    def test_window_rejected_in_having(self, session, views):
        with pytest.raises(SqlError, match="not allowed in HAVING"):
            session.sql(
                "SELECT region, SUM(amount) s FROM sales GROUP BY region "
                "HAVING rank() OVER (ORDER BY SUM(amount)) < 3"
            )

    def test_window_rejected_in_where(self, session, views):
        with pytest.raises(SqlError, match="not allowed in WHERE"):
            session.sql("SELECT user FROM sales WHERE rank() OVER (ORDER BY amount) < 3")

    def test_rank_requires_order_by(self, session, views):
        with pytest.raises(SqlError, match="ORDER BY"):
            session.sql("SELECT rank() OVER (PARTITION BY region) FROM sales")

    def test_agg_window_with_order_needs_frame(self, session, views):
        with pytest.raises(SqlError, match="ROWS BETWEEN"):
            session.sql("SELECT SUM(amount) OVER (ORDER BY amount) FROM sales")


class TestUnions:
    def test_union_all_keeps_duplicates(self, session, views):
        got = session.sql(
            "SELECT region FROM sales WHERE amount > 95 "
            "UNION ALL SELECT region FROM sales WHERE amount > 95"
        ).collect()
        one = session.sql("SELECT region FROM sales WHERE amount > 95").collect()
        assert got["region"].shape[0] == 2 * one["region"].shape[0] > 0

    def test_bare_union_dedups(self, session, views):
        got = session.sql(
            "SELECT region FROM sales UNION SELECT region FROM sales"
        ).collect()
        assert sorted(got["region"]) == sorted({f"r{i}" for i in range(8)})

    def test_mixed_union_chain_left_associative(self, session, views):
        # A UNION B dedups; the UNION ALL tail keeps its duplicates
        got = session.sql(
            "SELECT region FROM sales UNION SELECT region FROM sales "
            "UNION ALL SELECT region FROM sales WHERE region = 'r1'"
        ).collect()
        n_r1 = session.sql("SELECT region FROM sales WHERE region = 'r1'").collect()[
            "region"
        ].shape[0]
        assert got["region"].shape[0] == 8 + n_r1

    def test_parenthesized_operand_limit_stays_scoped(self, session, views):
        got = session.sql(
            "(SELECT user FROM sales ORDER BY amount DESC LIMIT 5) "
            "UNION ALL SELECT user FROM sales WHERE amount < 1"
        ).collect()
        low = session.sql("SELECT user FROM sales WHERE amount < 1").collect()
        assert got["user"].shape[0] == 5 + low["user"].shape[0]

    def test_union_order_and_limit_apply_to_whole(self, session, views):
        got = session.sql(
            "SELECT amount FROM sales WHERE region = 'r1' "
            "UNION ALL SELECT amount FROM sales WHERE region = 'r2' "
            "ORDER BY amount DESC LIMIT 4"
        ).collect()
        assert got["amount"].shape[0] == 4
        assert np.all(np.diff(got["amount"]) <= 0)


class TestSetOps:
    def test_intersect(self, session, views):
        got = session.sql(
            "SELECT region FROM sales WHERE amount > 50 "
            "INTERSECT SELECT region FROM sales WHERE amount < 50"
        ).collect()
        # every region has rows on both sides of 50 in this fixture
        assert sorted(got["region"]) == sorted({f"r{i}" for i in range(8)})

    def test_except(self, session, views):
        got = session.sql(
            "SELECT region FROM sales EXCEPT SELECT region FROM sales WHERE region = 'r1'"
        ).collect()
        assert sorted(got["region"]) == sorted({f"r{i}" for i in range(8)} - {"r1"})

    def test_intersect_binds_tighter_than_union(self, session, views):
        # r1 UNION (r2 INTERSECT r3-side) = r1 only (r2 ∩ r3 rows is empty)
        got = session.sql(
            "SELECT region FROM sales WHERE region = 'r1' "
            "UNION SELECT region FROM sales WHERE region = 'r2' "
            "INTERSECT SELECT region FROM sales WHERE region = 'r3'"
        ).collect()
        assert sorted(set(got["region"])) == ["r1"]

    def test_intersect_distinct_semantics(self, session, views):
        got = session.sql(
            "SELECT region FROM sales INTERSECT SELECT region FROM sales"
        ).collect()
        assert got["region"].shape[0] == 8  # duplicates collapse


class TestNullSemantics:
    @pytest.fixture()
    def nully(self, session, tmp_path):
        root = tmp_path / "nully"
        root.mkdir()
        pq.write_table(
            pa.table(
                {
                    "k": np.array([1, 2, 3, 4], dtype=np.int64),
                    "v": np.array([1.0, np.nan, 3.0, np.nan]),
                    "s": np.array(["a", None, "ccc", None], dtype=object),
                }
            ),
            root / "p.parquet",
        )
        session.read_parquet(str(root)).create_or_replace_temp_view("nully")

    def test_not_equal_excludes_nulls(self, session, nully):
        got = session.sql("SELECT k FROM nully WHERE v != 1").collect()
        assert got["k"].tolist() == [3]  # NULL != 1 is NULL, not TRUE
        got2 = session.sql("SELECT k FROM nully WHERE NOT v = 1").collect()
        assert got2["k"].tolist() == [3]

    def test_not_like_excludes_nulls(self, session, nully):
        got = session.sql("SELECT k FROM nully WHERE s NOT LIKE 'a%'").collect()
        assert got["k"].tolist() == [3]  # NULL NOT LIKE p is NULL

    def test_cast_null_propagates(self, session, nully):
        got = session.sql("SELECT cast(v AS int) AS iv, k FROM nully").collect()
        assert np.isnan(got["iv"][1]) and np.isnan(got["iv"][3])  # not -2^63
        assert got["iv"][0] == 1 and got["iv"][2] == 3
        s = session.sql("SELECT cast(s AS string) AS cs FROM nully").collect()
        assert s["cs"][1] is None and s["cs"][3] is None  # not 'None'

    def test_concat_null_propagates(self, session, nully):
        got = session.sql("SELECT s || 'x' AS c FROM nully").collect()
        assert got["c"][0] == "ax" and got["c"][2] == "cccx"
        assert got["c"][1] is None and got["c"][3] is None

    def test_length_of_null_is_null(self, session, nully):
        got = session.sql("SELECT k FROM nully WHERE length(s) < 2").collect()
        assert got["k"].tolist() == [1]  # length(NULL) is NULL, not -1
        avg = session.sql("SELECT AVG(length(s)) AS a FROM nully").collect()
        assert np.isclose(avg["a"][0], 2.0)  # (1 + 3) / 2, NULLs skipped


def test_cross_join_select_star_hides_internal_key(session, views):
    got = session.sql("SELECT * FROM sales, (SELECT MAX(amount) AS mx FROM sales) m LIMIT 3").collect()
    assert not any(c.startswith("__cross") for c in got), list(got)
    assert "mx" in got


def test_duplicate_alias_raises_sql_error(session, views):
    with pytest.raises(SqlError, match="alias"):
        session.sql("SELECT region AS amount, amount FROM sales")


def test_having_filters_groups(session, views):
    got = session.sql(
        "SELECT region, COUNT(*) AS n FROM sales GROUP BY region HAVING n > 70 ORDER BY region"
    ).collect()
    assert np.all(got["n"] > 70)
    full = session.sql("SELECT region, COUNT(*) AS n FROM sales GROUP BY region").collect()
    assert got["n"].shape[0] == int((full["n"] > 70).sum())


def test_having_with_aggregate_call(session, views):
    got = session.sql(
        "SELECT region, COUNT(*) AS n FROM sales GROUP BY region HAVING COUNT(*) > 70"
    ).collect()
    assert np.all(got["n"] > 70) and got["n"].shape[0] > 0
    # unaliased aggregate referenced by canonical name too
    got2 = session.sql(
        "SELECT region, SUM(amount) FROM sales GROUP BY region HAVING SUM(amount) > 3000"
    ).collect()
    assert "sum(amount)" in got2 and np.all(got2["sum(amount)"] > 3000)


def test_where_rejects_aggregates(session, views):
    with pytest.raises(SqlError, match="not allowed in WHERE"):
        session.sql("SELECT user FROM sales WHERE SUM(amount) > 10")


def test_having_aggregate_not_in_select(session, views):
    """HAVING may aggregate without SELECT doing so (standard SQL; TPC-H
    q18's inner query) — the aggregate is computed and then projected away."""
    got = session.sql(
        "SELECT region, COUNT(*) AS n FROM sales GROUP BY region HAVING SUM(amount) > 100"
    ).collect()
    sdf, _ = views
    import pandas as pd

    sp = pd.DataFrame(sdf.collect())
    g = sp.groupby("region").agg(n=("amount", "size"), s=("amount", "sum"))
    exp = g[g.s > 100]
    assert sorted(got["region"].tolist()) == sorted(exp.index.tolist())
    assert set(got.keys()) == {"region", "n"}  # SUM projected away


def test_select_distinct(session, views):
    got = session.sql("SELECT DISTINCT region FROM sales").collect()
    assert sorted(got["region"]) == sorted({f"r{i}" for i in range(8)})
    # dataframe surface too
    sdf, _ = views
    d = sdf.select("region").distinct().collect()
    assert len(d["region"]) == 8


def test_distinct_with_group_by_raises(session, views):
    with pytest.raises(SqlError, match="DISTINCT"):
        session.sql("SELECT DISTINCT region, COUNT(*) FROM sales GROUP BY region")


class TestMultiJoin:
    @pytest.fixture()
    def three_views(self, session, tmp_path):
        t1 = pa.table({"a": np.array([1, 2, 3], dtype=np.int64), "y": np.array([7, 8, 9], dtype=np.int64)})
        t2 = pa.table({"b": np.array([1, 2, 3], dtype=np.int64), "x": np.array([100, 200, 300], dtype=np.int64)})
        t3 = pa.table({"c": np.array([1, 2, 3], dtype=np.int64), "x": np.array([1000, 2000, 3000], dtype=np.int64)})
        for name, t in (("t1", t1), ("t2", t2), ("t3", t3)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)

    def test_qualified_ref_to_earlier_join(self, session, three_views):
        got = session.sql(
            "SELECT t2.x FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c"
        ).collect()
        assert sorted(got["x"].tolist()) == [100, 200, 300]

    def test_qualified_where_on_earlier_join(self, session, three_views):
        got = session.sql(
            "SELECT y FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c WHERE t2.x = 100"
        ).collect()
        assert got["y"].tolist() == [7]

    def test_double_suffix_collision(self, session, three_views):
        # t1 also gets an 'x' via join 1 ('x'), join 2 adds another ('x#r')
        got = session.sql(
            "SELECT t3.x FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c"
        ).collect()
        assert sorted(got["x"].tolist()) == [1000, 2000, 3000]

    def test_suffix_surfaces_when_alias_frees_plain_name(self, session, three_views):
        # 'x' is renamed away by AS, so t3.x can surface under the plain name
        got = session.sql(
            "SELECT t2.x AS y2, t3.x FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c"
        ).collect()
        assert sorted(got["y2"].tolist()) == [100, 200, 300]
        assert sorted(got["x"].tolist()) == [1000, 2000, 3000]

    def test_suffix_kept_when_plain_name_also_projected(self, session, three_views):
        got = session.sql(
            "SELECT t2.x, t3.x FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c"
        ).collect()
        assert sorted(got["x"].tolist()) == [100, 200, 300]
        assert sorted(got["x#r"].tolist()) == [1000, 2000, 3000]

    def test_suffix_surfaces_in_group_by(self, session, three_views):
        got = session.sql(
            "SELECT t3.x, COUNT(*) AS n FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c GROUP BY t3.x"
        ).collect()
        assert sorted(got["x"].tolist()) == [1000, 2000, 3000]
        assert got["n"].tolist() == [1, 1, 1]

    @pytest.fixture()
    def three_views_all_x(self, session, tmp_path):
        # ALL three tables carry 'x': t2's becomes 'x#r', t3's 'x#r#r'
        t1 = pa.table({"a": np.array([1, 2, 3], dtype=np.int64), "x": np.array([10, 20, 30], dtype=np.int64)})
        t2 = pa.table({"b": np.array([1, 2, 3], dtype=np.int64), "x": np.array([100, 200, 300], dtype=np.int64)})
        t3 = pa.table({"c": np.array([1, 2, 3], dtype=np.int64), "x": np.array([1000, 2000, 3000], dtype=np.int64)})
        for name, t in (("u1", t1), ("u2", t2), ("u3", t3)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)

    def test_triple_duplicate_qualified_refs(self, session, three_views_all_x):
        sql = "FROM u1 JOIN u2 ON a = b JOIN u3 ON a = c"
        for qual, expect in (("u1", [10, 20, 30]), ("u2", [100, 200, 300]), ("u3", [1000, 2000, 3000])):
            got = session.sql(f"SELECT {qual}.x AS v {sql}").collect()
            assert sorted(got["v"].tolist()) == expect, (qual, dict(got))

    def test_triple_duplicate_group_by(self, session, three_views_all_x):
        got = session.sql(
            "SELECT u3.x AS k, COUNT(*) AS n FROM u1 JOIN u2 ON a = b JOIN u3 ON a = c GROUP BY u3.x"
        ).collect()
        assert sorted(got["k"].tolist()) == [1000, 2000, 3000]

    def test_triple_duplicate_where(self, session, three_views_all_x):
        got = session.sql(
            "SELECT a FROM u1 JOIN u2 ON a = b JOIN u3 ON a = c WHERE u3.x = 2000"
        ).collect()
        assert got["a"].tolist() == [2]

    def test_unknown_qualified_column_raises(self, session, three_views):
        with pytest.raises(SqlError, match="not found in table/alias"):
            session.sql("SELECT t2.nope FROM t1 JOIN t2 ON a = b")

    def test_all_columns_of_triple_join(self, session, three_views):
        got = session.sql("SELECT * FROM t1 JOIN t2 ON a = b JOIN t3 ON a = c").collect()
        # both duplicate 'x' columns surface under distinct names
        assert "x" in got and "x#r" in got
        assert sorted(got["x"].tolist()) == [100, 200, 300]
        assert sorted(got["x#r"].tolist()) == [1000, 2000, 3000]


class TestExpressionJoinKeys:
    """Comma-FROM links through expression predicates: one side (or both)
    of an equality may be an expression over exactly one frame's columns —
    computed as a hidden join-key column, equi-joined, never exposed
    (TPC-DS q2 `d_week_seq1 = d_week_seq2 - 53`, q8 substr = substr)."""

    @pytest.fixture()
    def ab_views(self, session, tmp_path):
        a = pa.table({"k": np.array([1, 2, 3, 4], dtype=np.int64),
                      "av": np.array([10.0, 20.0, 30.0, 40.0])})
        b = pa.table({"k2": np.array([2, 3, 4, 5], dtype=np.int64),
                      "bz": np.array(["x1", "y2", "x3", "y4"], dtype=object)})
        for name, t in (("a", a), ("b", b)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)

    def test_arithmetic_join_predicate(self, session, ab_views):
        got = session.sql("SELECT k, k2 FROM a, b WHERE k = k2 - 1").collect()
        # oracle: k in {1,2,3,4}, k2-1 in {1,2,3,4} -> pairs (1,2),(2,3),(3,4),(4,5)
        pairs = sorted(zip(got["k"].tolist(), got["k2"].tolist()))
        assert pairs == [(1, 2), (2, 3), (3, 4), (4, 5)]

    def test_expression_both_sides(self, session, ab_views):
        got = session.sql(
            "SELECT av, bz FROM a, b WHERE substr(CAST(k AS string), 1, 1) = substr(bz, 2, 1)"
        ).collect()
        # oracle: k-digit vs second char of bz: '1'='1'(x1), '2'='2'(y2), '3'='3'(x3), '4'='4'(y4)
        pairs = sorted(zip(got["av"].tolist(), got["bz"].tolist()))
        assert pairs == [(10.0, "x1"), (20.0, "y2"), (30.0, "x3"), (40.0, "y4")]

    def test_select_star_hides_join_key_columns(self, session, ab_views):
        got = session.sql("SELECT * FROM a, b WHERE k = k2 - 1").collect()
        assert not any(c.startswith("__jk") for c in got)
        assert set(got) == {"k", "av", "k2", "bz"}

    def test_same_side_expression_is_filter_not_link(self, session, ab_views):
        with pytest.raises(SqlError, match="Cannot join"):
            session.sql("SELECT k FROM a, b WHERE k = k + 0").collect()


class TestDisjunctiveJoinPredicates:
    """OR-of-AND-blocks sharing the equi-join conjunct in every branch
    (TPC-DS q13/q48): the common conjunct factors out and links the frames;
    the residual OR filters the joined rows."""

    @pytest.fixture()
    def sd_views(self, session, tmp_path):
        s = pa.table({"sk": np.array([1, 1, 2, 2, 3, 3], dtype=np.int64),
                      "price": np.array([5.0, 55.0, 5.0, 55.0, 5.0, 55.0])})
        d = pa.table({"dk": np.array([1, 2, 3], dtype=np.int64),
                      "grp": np.array(["lo", "hi", "lo"], dtype=object)})
        for name, t in (("s", s), ("d", d)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)

    def test_or_of_and_blocks(self, session, sd_views):
        got = session.sql(
            "SELECT sk, price, grp FROM s, d WHERE "
            "(sk = dk AND grp = 'lo' AND price < 10) OR (sk = dk AND grp = 'hi' AND price > 50)"
        ).collect()
        import pandas as pd

        sdf = pd.DataFrame({"sk": [1, 1, 2, 2, 3, 3], "price": [5.0, 55.0, 5.0, 55.0, 5.0, 55.0]})
        ddf = pd.DataFrame({"dk": [1, 2, 3], "grp": ["lo", "hi", "lo"]})
        m = sdf.merge(ddf, left_on="sk", right_on="dk")
        m = m[((m.grp == "lo") & (m.price < 10)) | ((m.grp == "hi") & (m.price > 50))]
        assert sorted(zip(got["sk"].tolist(), got["price"].tolist())) == sorted(
            zip(m.sk.tolist(), m.price.tolist())
        )

    def test_branch_equal_to_common_collapses(self, session, sd_views):
        # (sk = dk AND price < 10) OR (sk = dk)  ==  sk = dk
        got = session.sql(
            "SELECT sk FROM s, d WHERE (sk = dk AND price < 10) OR (sk = dk)"
        ).collect()
        assert len(got["sk"]) == 6

    def test_or_branches_with_distinct_subqueries_not_factored(self, session, sd_views, tmp_path):
        # two IN-subqueries repr identically ('<subquery>'); factoring must
        # not treat them as a common conjunct (one would silently replace
        # the other)
        c = pa.table({"x": np.array([1], dtype=np.int64)})
        e = pa.table({"y": np.array([3], dtype=np.int64)})
        for name, t in (("c", c), ("e", e)):
            root = tmp_path / name
            root.mkdir()
            pq.write_table(t, root / "p.parquet")
            session.read_parquet(str(root)).create_or_replace_temp_view(name)
        got = session.sql(
            "SELECT sk FROM s, d WHERE "
            "(sk = dk AND sk IN (SELECT x FROM c)) OR (sk = dk AND sk IN (SELECT y FROM e))"
        ).collect()
        assert sorted(set(got["sk"].tolist())) == [1, 3]

    def test_subquery_in_expression_term_stays_filter(self, session, sd_views):
        # a term whose side contains an unbound scalar-subquery marker must
        # not become a computed join key (the marker binds only in prep)
        got = session.sql(
            "SELECT sk FROM s, d WHERE sk = dk AND price = price * 1 + (SELECT 0.0 * max(dk) FROM d)"
        ).collect()
        assert len(got["sk"]) == 6


class TestScalarFunctionBreadth:
    """Round-3 scalar-function additions (Spark SQL functions lake queries
    lean on): date parts/arithmetic, conditional/string/math utilities —
    NULL-in-NULL-out under the framework's missing-value convention."""

    @pytest.fixture()
    def fx(self, session, tmp_path):
        t = pa.table(
            {
                "d": pa.array(
                    np.array(
                        ["2020-02-29", "1999-12-31", "2021-07-15", "NaT"],
                        dtype="datetime64[D]",
                    )
                ),
                "d2": pa.array(
                    np.array(
                        ["2020-01-01", "2000-01-01", "2021-07-01", "2021-07-01"],
                        dtype="datetime64[D]",
                    )
                ),
                "x": np.array([4.0, -9.0, 2.25, np.nan]),
                "n": np.array([3, 10, 0, 5], dtype=np.int64),
                "s": pa.array(["abc", "hello world", "", None]),
            }
        )
        root = tmp_path / "fx"
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view("fx")
        return t

    def q(self, session, expr):
        return session.sql(f"SELECT {expr} AS r FROM fx").collect()["r"]

    def test_date_parts(self, session, fx):
        assert self.q(session, "year(d)").tolist()[:3] == [2020.0, 1999.0, 2021.0]
        assert self.q(session, "month(d)").tolist()[:3] == [2.0, 12.0, 7.0]
        assert self.q(session, "day(d)").tolist()[:3] == [29.0, 31.0, 15.0]
        assert self.q(session, "quarter(d)").tolist()[:3] == [1.0, 4.0, 3.0]
        assert np.isnan(self.q(session, "year(d)")[3])

    def test_date_arithmetic(self, session, fx):
        got = self.q(session, "date_add(d2, n)")
        assert str(got[0])[:10] == "2020-01-04"
        got2 = self.q(session, "date_sub(d2, n)")
        assert str(got2[1])[:10] == "1999-12-22"
        dd = self.q(session, "datediff(d, d2)")
        assert dd.tolist()[:3] == [59.0, -1.0, 14.0]
        assert np.isnan(dd[3])
        ld = self.q(session, "last_day(d)")
        assert str(ld[0])[:10] == "2020-02-29" and str(ld[1])[:10] == "1999-12-31"
        tr = self.q(session, "trunc(d, 'month')")
        assert str(tr[2])[:10] == "2021-07-01"
        try_ = self.q(session, "trunc(d, 'year')")
        assert str(try_[2])[:10] == "2021-01-01"

    def test_if_and_strings(self, session, fx):
        got = self.q(session, "if(x > 0, 1, 0)")
        assert got.tolist() == [1.0, 0.0, 1.0, 0.0]  # NULL cond -> false arm
        rep = self.q(session, "replace(s, 'l', 'L')")
        assert rep[1] == "heLLo worLd" and rep[3] is None
        lp = self.q(session, "lpad(s, 5, '*')")
        assert lp[0] == "**abc" and lp[1] == "hello"
        rp = self.q(session, "rpad(s, 5, '*')")
        assert rp[0] == "abc**" and rp[2] == "*****"
        ins = self.q(session, "instr(s, 'world')")
        assert ins.tolist()[:3] == [0.0, 7.0, 0.0] and np.isnan(ins[3])
        lt = self.q(session, "ltrim(concat(' ', s))")
        assert lt[0] == "abc"

    def test_math(self, session, fx):
        assert self.q(session, "sqrt(x)").tolist()[0] == 2.0
        assert self.q(session, "sign(x)").tolist()[:3] == [1.0, -1.0, 1.0]
        assert self.q(session, "greatest(x, n)").tolist()[:3] == [4.0, 10.0, 2.25]
        assert self.q(session, "least(x, n)").tolist()[:3] == [3.0, -9.0, 0.0]
        assert self.q(session, "power(n, 2)").tolist()[:3] == [9.0, 100.0, 0.0]
        assert self.q(session, "mod(n, 3)").tolist()[:3] == [0, 1, 0]
        assert abs(self.q(session, "exp(ln(n))")[1] - 10.0) < 1e-9

    def test_review_regressions(self, session, fx):
        # log(base, x) is base-log, not ln(base)
        got = self.q(session, "log(2, n)")
        assert abs(got[1] - np.log2(10)) < 1e-9
        # per-row function arguments (column as search string)
        got2 = self.q(session, "replace(s, s)")  # replace self -> empty
        assert got2[0] == "" and got2[3] is None
        # trunc without a literal unit: clean error, not IndexError
        with pytest.raises(SqlError, match="trunc"):
            session.sql("SELECT trunc(d) AS r FROM fx").collect()


class TestCubeAndGroupingSets:
    """CUBE and GROUPING SETS generalize the ROLLUP machinery (one Aggregate
    per grouping set, absent keys NULL, grouping() indicators)."""

    @pytest.fixture()
    def gdata(self, session, tmp_path):
        t = pa.table(
            {
                "a": np.array(["x", "x", "y", "y", "y"], dtype=object),
                "b": np.array(["p", "q", "p", "p", "q"], dtype=object),
                "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0]),
            }
        )
        root = tmp_path / "g"
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view("g")
        return t.to_pandas()

    def _rows(self, got):
        return sorted(
            tuple("NULL" if (v is None or v != v) else str(v) for v in row)
            for row in zip(*[got[k].tolist() for k in sorted(got)])
        )

    def test_cube(self, session, gdata):
        got = session.sql(
            "SELECT a, b, sum(v) AS s FROM g GROUP BY CUBE(a, b)"
        ).collect()
        import pandas as pd

        parts = []
        for keys in ([["a", "b"], ["a"], ["b"], []]):
            if keys:
                gp = gdata.groupby(keys, as_index=False).v.sum()
            else:
                gp = pd.DataFrame({"v": [gdata.v.sum()]})
            for m in ("a", "b"):
                if m not in gp.columns:
                    gp[m] = None
            parts.append(gp[["a", "b", "v"]])
        exp = pd.concat(parts, ignore_index=True)
        exp_rows = sorted(
            tuple("NULL" if (v is None or v != v) else str(v) for v in row)
            for row in zip(exp.a, exp.b, exp.v)
        )
        got_rows = sorted(
            tuple("NULL" if (v is None or v != v) else str(v) for v in row)
            for row in zip(got["a"].tolist(), got["b"].tolist(), got["s"].tolist())
        )
        assert got_rows == exp_rows
        assert len(got["a"]) == 2 * 2 + 2 + 2 + 1  # ab(4) + a(2) + b(2) + total(1)

    def test_grouping_sets_explicit(self, session, gdata):
        got = session.sql(
            "SELECT a, b, count(*) AS n FROM g GROUP BY GROUPING SETS ((a, b), (a), ())"
        ).collect()
        # identical to ROLLUP(a, b)
        want = session.sql(
            "SELECT a, b, count(*) AS n FROM g GROUP BY ROLLUP(a, b)"
        ).collect()
        assert self._rows(got) == self._rows(want)

    def test_grouping_sets_bare_columns(self, session, gdata):
        # GROUPING SETS (a, b) == two single-key sets (standard SQL)
        got = session.sql(
            "SELECT a, b, sum(v) AS s FROM g GROUP BY GROUPING SETS (a, b)"
        ).collect()
        assert len(got["a"]) == 2 + 2
        # every row has exactly one non-NULL key
        for av, bv in zip(got["a"].tolist(), got["b"].tolist()):
            assert (av is None) != (bv is None)

    def test_grouping_indicator_with_cube(self, session, gdata):
        got = session.sql(
            "SELECT a, grouping(a) AS ga, grouping(b) AS gb, sum(v) AS s "
            "FROM g GROUP BY CUBE(a, b)"
        ).collect()
        pairs = set(zip(got["ga"].tolist(), got["gb"].tolist()))
        assert pairs == {(0, 0), (0, 1), (1, 0), (1, 1)}

    def test_case_duplicate_rollup_keys(self, session, gdata):
        # ROLLUP(a, A): both positions resolve to ONE key, so the grouping
        # sets are (a),(a),() — the duplicate set legitimately repeats the
        # per-a rows (standard ROLLUP semantics for duplicate keys), and
        # crucially there is no crash from the parse/plan index mismatch
        got = session.sql("SELECT a, sum(v) AS s FROM g GROUP BY ROLLUP(a, A)").collect()
        rollup1 = session.sql("SELECT a, sum(v) AS s FROM g GROUP BY ROLLUP(a)").collect()
        per_a = session.sql("SELECT a, sum(v) AS s FROM g GROUP BY a").collect()
        expect = sorted(self._rows(rollup1) + self._rows(per_a))
        assert self._rows(got) == expect

    def test_column_named_cube_still_groups(self, session, tmp_path):
        t = pa.table({"cube": np.array(["c1", "c1", "c2"], dtype=object),
                      "grouping": np.array(["g1", "g2", "g2"], dtype=object),
                      "v": np.array([1.0, 2.0, 3.0])})
        root = tmp_path / "cg"
        root.mkdir()
        pq.write_table(t, root / "p.parquet")
        session.read_parquet(str(root)).create_or_replace_temp_view("cg")
        got = session.sql("SELECT cube, sum(v) AS s FROM cg GROUP BY cube").collect()
        assert sorted(got["cube"].tolist()) == ["c1", "c2"]
        got2 = session.sql("SELECT grouping, cube, count(*) AS n FROM cg GROUP BY grouping, cube").collect()
        assert len(got2["n"]) == 3
