"""Unit tests for the unified observability layer (hyperspace_tpu/obs/):
span-tree semantics (null fast path, budget, cross-thread propagation),
Chrome trace-event export schema, the metrics registry (get-or-create,
kind conflicts, Prometheus text, snapshot), QueryProfile rendering, the
per-session event-logger cache, and the exec/trace recording guard."""

import json
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exec import trace as exec_trace
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import spans
from hyperspace_tpu.obs.profile import build_profile
from hyperspace_tpu.serving import QueryServer
from hyperspace_tpu.telemetry.events import (
    CollectingEventLogger,
    HyperspaceIndexUsageEvent,
    NoOpEventLogger,
    emit_event,
    get_event_logger,
)

pytestmark = pytest.mark.obs


@pytest.fixture()
def simple(tmp_path):
    n = 400
    pq.write_table(
        pa.table(
            {
                "a": np.arange(n, dtype=np.int64),
                "b": (np.arange(n, dtype=np.int64) * 13) % 500,
                "v": np.arange(n, dtype=np.float64),
            }
        ),
        str(tmp_path / "t.parquet"),
    )
    sess = hst.Session()
    sess.read_parquet(str(tmp_path / "t.parquet")).create_or_replace_temp_view("t")
    return sess


# --- span tree semantics -----------------------------------------------------


def test_span_without_trace_is_shared_noop():
    # the disabled path must allocate nothing: same CM object every call
    assert spans.current_span() is None
    cm = spans.span("anything", cat="x", rows=1)
    assert cm is spans._NULL_CM
    with cm as sp:
        assert sp is spans.NULL_SPAN
        sp.set(rows=2)  # no-op, no error
        sp.event("k", "d")
    assert spans.wrap(len) is len  # identity when no trace to propagate


def test_trace_builds_hierarchy_with_timings_and_attrs():
    with spans.trace("query") as root:
        assert spans.current_span() is root
        with spans.span("optimize", cat="plan") as osp:
            osp.set(indexes=["ix"])
        with spans.span("execute", cat="exec"):
            with spans.span("decode", cat="io", file="f.parquet") as d:
                d.set(rows=7)
    assert spans.current_span() is None
    assert [c.name for c in root.children] == ["optimize", "execute"]
    (decode,) = root.find("decode")
    assert decode.attrs == {"file": "f.parquet", "rows": 7}
    assert decode.t1 >= decode.t0 and decode.duration_s >= 0.0
    # child intervals nest inside the parent's
    execute = root.children[1]
    assert execute.t0 <= decode.t0 and decode.t1 <= execute.t1
    assert len(list(root.walk())) == 4


def test_span_records_exception_and_reraises():
    with pytest.raises(ValueError):
        with spans.trace("query") as root:
            with spans.span("boom"):
                raise ValueError("nope")
    (boom,) = root.find("boom")
    assert boom.attrs["error"] == "ValueError"
    assert boom.t1 > 0.0  # still finished
    assert spans.current_span() is None  # context restored past the raise


def test_span_budget_drops_and_counts():
    with spans.trace("query", max_spans=3) as root:
        for i in range(10):
            with spans.span(f"s{i}"):
                pass
    assert len(root.children) == 2  # root consumed 1 of the 3 slots
    assert root.trace.dropped == 8
    # dropped spans surface in the export, not silently
    assert spans.to_chrome_trace(root)["otherData"]["droppedSpans"] == 8


def test_start_trace_is_detached_and_attach_scopes_it():
    root = spans.start_trace("request", server="qs0")
    assert spans.current_span() is None  # detached: submitter thread unaffected
    with spans.attach(root):
        assert spans.current_span() is root
        with spans.span("inner"):
            pass
    assert spans.current_span() is None
    assert [c.name for c in root.children] == ["inner"]
    with spans.attach(None):  # None attach must be a cheap no-op
        assert spans.current_span() is None


def test_wrap_carries_trace_into_pool_threads():
    # contextvars do NOT cross ThreadPoolExecutor boundaries by themselves;
    # wrap() is the explicit hand-off the decode pool uses.
    def job(i):
        with spans.span("job", i=i):
            pass
        return spans.current_span().name

    with spans.trace("query") as root:
        with ThreadPoolExecutor(max_workers=4) as pool:
            names = list(pool.map(spans.wrap(job), range(8)))
    assert names == ["query"] * 8
    assert sorted(c.attrs["i"] for c in root.children) == list(range(8))


def test_concurrent_traces_are_disjoint_across_threads():
    barrier = threading.Barrier(4)
    roots = {}

    def worker(k):
        with spans.trace(f"t{k}") as root:
            barrier.wait()
            for j in range(5):
                with spans.span(f"s{j}", owner=k):
                    pass
            roots[k] = root

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for k, root in roots.items():
        assert {c.attrs["owner"] for c in root.children} == {k}
        assert len(root.children) == 5


def test_add_manual_pre_timed_child():
    root = spans.start_trace("request")
    sp = spans.add_manual(root, "execute-shared-scan", "serving", 10.0, 10.5, batch_size=3)
    assert sp in root.children
    assert sp.duration_s == pytest.approx(0.5)
    assert sp.attrs["batch_size"] == 3


# --- chrome trace export -----------------------------------------------------


def _validate_chrome(doc):
    """Every event must satisfy the trace-event schema: name/ph/pid/tid
    always, numeric ts+dur for complete ('X') events."""
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["tid"], int)
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["args"], dict)
    json.dumps(doc)  # must be strictly JSON-serializable


def test_chrome_trace_schema_and_content():
    with spans.trace("query") as root:
        with spans.span("execute", cat="exec", rows=3):
            with spans.span("decode", cat="io") as d:
                d.event("decode", "native path")
    doc = spans.to_chrome_trace(root, pid=1234)
    _validate_chrome(doc)
    xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert set(xs) == {"query", "execute", "decode"}
    assert xs["execute"]["cat"] == "exec" and xs["execute"]["args"]["rows"] == 3
    assert xs["decode"]["args"]["events"] == ["decode: native path"]
    # ts is relative to the root, in microseconds, and nesting is preserved
    assert xs["query"]["ts"] == 0
    assert xs["execute"]["ts"] >= xs["query"]["ts"]
    assert xs["execute"]["ts"] + xs["execute"]["dur"] <= xs["query"]["ts"] + xs["query"]["dur"] + 1
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(m["name"] == "process_name" for m in metas)
    assert all(e["pid"] == 1234 for e in doc["traceEvents"])


# --- metrics registry --------------------------------------------------------


def test_registry_get_or_create_identity_and_labels():
    reg = obs_metrics.MetricsRegistry()
    c1 = reg.counter("hs_x_total", "help", server="a")
    c2 = reg.counter("hs_x_total", server="a")
    assert c1 is c2  # same (name, labels) -> same instrument
    cb = reg.counter("hs_x_total", server="b")
    assert cb is not c1
    c1.inc()
    c1.inc(2.5)
    assert c1.value == 3.5 and cb.value == 0.0


def test_registry_kind_conflict_raises():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("hs_dup", "as counter")
    with pytest.raises(ValueError, match="hs_dup"):
        reg.gauge("hs_dup")


def test_gauge_callback_reads_live_value():
    reg = obs_metrics.MetricsRegistry()
    box = {"v": 1}
    g = reg.gauge("hs_live", fn=lambda: box["v"])
    assert g.value == 1
    box["v"] = 42
    assert g.value == 42  # no set() needed: reads the live source


def test_histogram_percentiles_and_buckets():
    reg = obs_metrics.MetricsRegistry()
    h = reg.histogram("hs_lat_seconds", "latency")
    for ms in range(1, 101):
        h.observe(ms / 1000.0)
    assert h.count == 100
    assert h.sum == pytest.approx(sum(range(1, 101)) / 1000.0)
    p = h.percentiles()
    assert set(p) == {"p50", "p95", "p99"}
    assert 0.045 <= p["p50"] <= 0.055
    assert 0.090 <= p["p95"] <= 0.100
    # cumulative buckets: the +Inf bucket always equals count
    bks = dict(h.snapshot_buckets())
    assert bks["+Inf"] == 100
    assert bks["0.05"] <= bks["0.1"] <= bks["+Inf"]


def test_prometheus_text_exposition():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("hs_served_total", "requests served", server="qs1").inc(5)
    reg.gauge("hs_depth", "queue depth").set(3)
    reg.histogram("hs_lat_seconds", "latency").observe(0.02)
    text = reg.prometheus_text()
    assert '# TYPE hs_served_total counter' in text
    assert 'hs_served_total{server="qs1"} 5' in text
    assert "hs_depth 3" in text
    assert '# TYPE hs_lat_seconds histogram' in text
    assert 'hs_lat_seconds_bucket{le="+Inf"} 1' in text
    assert "hs_lat_seconds_count 1" in text
    assert text.endswith("\n")


def test_prometheus_label_value_escaping():
    # exposition format 0.0.4: label values escape backslash, double-quote,
    # and newline — a raw query fragment in a label must not corrupt a scrape
    reg = obs_metrics.MetricsRegistry()
    reg.counter("hs_esc_total", "escaping", q='he said "hi"\npath\\x').inc()
    text = reg.prometheus_text()
    assert 'q="he said \\"hi\\"\\npath\\\\x"' in text
    # one series line per metric: the newline stayed escaped, not literal
    (line,) = [l for l in text.splitlines() if l.startswith("hs_esc_total{")]
    assert line.endswith("} 1")


def test_prometheus_help_escaping():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("hs_help_total", "line1\nline2 with \\ backslash").inc()
    text = reg.prometheus_text()
    assert "# HELP hs_help_total line1\\nline2 with \\\\ backslash\n" in text


def test_registry_snapshot_shape():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("hs_c", "c", k="v").inc(2)
    reg.histogram("hs_h").observe(0.5)
    snap = reg.snapshot()
    assert snap["hs_c"]["kind"] == "counter"
    (series,) = snap["hs_c"]["series"]
    assert series["labels"] == {"k": "v"} and series["value"] == 2
    (hs,) = snap["hs_h"]["series"]
    assert hs["count"] == 1 and set(hs["percentiles"]) == {"p50", "p95", "p99"}
    json.dumps(snap)


def test_counter_thread_safety():
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("hs_hammer")

    def hammer():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 80_000


# --- query profiles ----------------------------------------------------------


def test_profile_report_and_json():
    with spans.trace("query") as root:
        with spans.span("optimize", cat="plan") as o:
            o.set(indexes=["ix1"], rule_timings={"FilterIndexRule": 0.002})
        with spans.span("execute", cat="exec") as e:
            e.set(rows=10, bytes=80)
    prof = build_profile(root, query="SELECT ...")
    assert prof.indexes_applied == ["ix1"]
    assert prof.duration_s > 0
    assert prof.total("rows") == 10
    rep = prof.report()
    assert "optimize" in rep and "execute" in rep and "rows=10" in rep
    assert "FilterIndexRule" in rep
    js = prof.to_json()
    assert js["indexesApplied"] == ["ix1"]
    json.dumps(js)
    _validate_chrome(prof.chrome_trace())


def test_collect_profile_end_to_end(simple):
    simple.conf.set(hst.keys.OBS_TRACING_ENABLED, True)
    simple.enable_hyperspace()
    df = simple.sql("SELECT a, v FROM t WHERE b > 300")
    got = df.collect()
    prof = simple.last_query_profile()
    assert prof is not None and prof.error is None
    names = {sp.name for sp in prof.root.walk()}
    # ad-hoc lifecycle under collect(): optimize -> execute -> per-operator
    # -> decode (parse/resolve happen at sql() time, before the trace roots;
    # the serving suite covers them inside request trees)
    assert {"query", "optimize", "execute", "decode"} <= names
    (proj,) = prof.root.find("Project")
    assert proj.attrs["rows"] == len(next(iter(got.values())))
    _validate_chrome(prof.chrome_trace())


def test_collect_untraced_leaves_no_profile(simple):
    simple.sql("SELECT a FROM t WHERE b > 490").collect()
    assert simple.last_query_profile() is None


# --- per-session event logger cache (satellite a) ----------------------------


_COLLECTOR = "hyperspace_tpu.telemetry.events.CollectingEventLogger"


def test_event_logger_honors_conf_change_per_session(simple):
    first = get_event_logger(simple)
    assert isinstance(first, NoOpEventLogger)
    assert get_event_logger(simple) is first  # identity while conf unchanged
    simple.conf.set("hyperspace.eventLoggerClass", _COLLECTOR)
    second = get_event_logger(simple)
    assert isinstance(second, CollectingEventLogger)  # mid-session change honored
    assert get_event_logger(simple) is second
    simple.conf.unset("hyperspace.eventLoggerClass")
    assert isinstance(get_event_logger(simple), NoOpEventLogger)


def test_event_logger_not_shared_across_sessions():
    s1 = hst.Session(conf={"hyperspace.eventLoggerClass": _COLLECTOR})
    s2 = hst.Session(conf={"hyperspace.eventLoggerClass": _COLLECTOR})
    l1, l2 = get_event_logger(s1), get_event_logger(s2)
    assert isinstance(l1, CollectingEventLogger)
    assert l1 is not l2  # same class name, but each session gets its own sink


def test_emit_event_counts_in_registry(simple):
    simple.conf.set("hyperspace.eventLoggerClass", _COLLECTOR)
    ctr = obs_metrics.REGISTRY.counter(
        "hs_events_total", event="HyperspaceIndexUsageEvent"
    )
    before = ctr.value
    emit_event(simple, HyperspaceIndexUsageEvent(index_names=["ix"]))
    assert ctr.value == before + 1
    logged = get_event_logger(simple).snapshot()
    assert logged and logged[-1].name == "HyperspaceIndexUsageEvent"


# --- exec/trace recording guard (satellite b) --------------------------------


def test_recording_raises_while_server_runs(simple):
    with QueryServer(simple, workers=1) as srv:
        fut = srv.submit("SELECT a FROM t WHERE b > 450")
        fut.result(timeout=30)
        with pytest.raises(RuntimeError, match="QueryServer"):
            with exec_trace.recording():
                pass
    # server stopped -> the golden-test surface works again
    with exec_trace.recording() as events:
        simple.sql("SELECT a FROM t WHERE b > 450").collect()
    assert events  # dispatch decisions were recorded


def test_record_annotates_current_obs_span(simple):
    simple.conf.set(hst.keys.OBS_TRACING_ENABLED, True)
    simple.sql("SELECT a, v FROM t WHERE b > 300").collect()
    prof = simple.last_query_profile()
    all_events = [ev for sp in prof.root.walk() for ev in sp.events]
    assert all_events  # decode-path dispatch decisions landed in the span tree
