"""Sketch-evaluator predicate matrix — the unit-level extractor semantics the
reference pins in its 946-line ExtractorsTest
(ref: src/test/scala/com/microsoft/hyperspace/index/dataskipping/util/ExtractorsTest.scala):
literal-on-either-side comparisons, NOT push-down, IN, AND/OR composition,
null-aggregate handling, and unprunable shapes."""

import numpy as np

from hyperspace_tpu.indexes.dataskipping import MinMaxSketch, ValueListSketch
from hyperspace_tpu.plan.expr import BinaryOp, Not, col, lit
from hyperspace_tpu.rules.dataskipping_rule import _SketchEvaluator


def evaluator():
    """Three files with k ranges [0,10], [20,30], [40,50] (file 1 constant 25
    variant is built by tests that need it)."""
    s = MinMaxSketch("k")
    mn, mx = s.output_names()
    cols = {mn: np.array([0, 20, 40]), mx: np.array([10, 30, 50])}
    return _SketchEvaluator([s], cols, 3), s


class TestComparisonExtraction:
    def test_equality_both_literal_sides(self):
        ev, _ = evaluator()
        assert ev.eval(col("k") == 25).tolist() == [False, True, False]
        # literal on the LEFT flips the operator (EqualToExtractor's lit-expr arm)
        assert ev.eval(lit(25) == col("k")).tolist() == [False, True, False]

    def test_less_than_both_literal_sides(self):
        ev, _ = evaluator()
        assert ev.eval(col("k") < 15).tolist() == [True, False, False]
        # 15 > k is the same predicate written literal-first
        assert ev.eval(lit(15) > col("k")).tolist() == [True, False, False]

    def test_greater_equal_boundary(self):
        ev, _ = evaluator()
        assert ev.eval(col("k") >= 30).tolist() == [False, True, True]
        assert ev.eval(col("k") > 30).tolist() == [False, False, True]

    def test_not_equal_prunes_constant_files_only(self):
        s = MinMaxSketch("k")
        mn, mx = s.output_names()
        # file 1 holds ONLY the value 25 (min == max == 25)
        ev = _SketchEvaluator([s], {mn: np.array([0, 25, 40]), mx: np.array([10, 25, 50])}, 3)
        assert ev.eval(col("k") != 25).tolist() == [True, False, True]

    def test_not_pushes_through_comparisons(self):
        ev, _ = evaluator()
        # NOT(k < 15) == k >= 15: file 0 spans [0,10] -> prunable
        assert ev.eval(~(col("k") < 15)).tolist() == [False, True, True]
        assert ev.eval(~(col("k") == 25)).tolist() == [True, True, True]  # ranges, not constants

    def test_col_vs_col_unprunable(self):
        ev, _ = evaluator()
        assert ev.eval(col("k") == col("k")) is None

    def test_unknown_column_unprunable(self):
        ev, _ = evaluator()
        assert ev.eval(col("z") == 1) is None

    def test_arithmetic_unprunable(self):
        ev, _ = evaluator()
        assert ev.eval((col("k") + 1) == 25) is None


class TestComposition:
    def test_and_intersects_or_falls_back_per_side(self):
        ev, _ = evaluator()
        m = ev.eval((col("k") >= 15) & (col("k") <= 35))
        assert m.tolist() == [False, True, False]
        # AND with an unprunable side keeps the prunable side's mask
        m2 = ev.eval((col("k") >= 15) & (col("z") == 1))
        assert m2.tolist() == [False, True, True]

    def test_or_requires_both_sides_prunable(self):
        ev, _ = evaluator()
        m = ev.eval((col("k") < 5) | (col("k") > 45))
        assert m.tolist() == [True, False, True]
        assert ev.eval((col("k") < 5) | (col("z") == 1)) is None

    def test_in_unions_membership(self):
        ev, _ = evaluator()
        m = ev.eval(col("k").isin(5, 45))
        assert m.tolist() == [True, False, True]

    def test_between_via_and(self):
        ev, _ = evaluator()
        m = ev.eval((col("k") >= 22) & (col("k") <= 28))
        assert m.tolist() == [False, True, False]


class TestNullAggregates:
    def test_all_null_file_always_kept(self):
        s = MinMaxSketch("k")
        mn, mx = s.output_names()
        ev = _SketchEvaluator(
            [s],
            {mn: np.array([0.0, np.nan, 40.0]), mx: np.array([10.0, np.nan, 50.0])},
            3,
        )
        # the NaN-aggregate file (all-null column values) survives everything
        assert ev.eval(col("k") == 5).tolist() == [True, True, False]
        assert ev.eval(col("k") > 100).tolist() == [False, True, False]


class TestMultipleSketches:
    def test_sketches_on_same_column_intersect(self):
        mmx = MinMaxSketch("k")
        vls = ValueListSketch("k")
        mn, mx = mmx.output_names()
        (vname,) = vls.output_names()
        cols = {
            mn: np.array([0, 20]),
            mx: np.array([10, 30]),
            # file 0's actual values are only {2, 4}: the value list refutes
            # k = 5 even though the min/max range [0,10] cannot
            vname: np.array([np.array([2, 4]), np.array([25])], dtype=object),
        }
        ev = _SketchEvaluator([mmx, vls], cols, 2)
        assert ev.eval(col("k") == 5).tolist() == [False, False]
        assert ev.eval(col("k") == 2).tolist() == [True, False]

    def test_overflowed_value_list_keeps_file(self):
        vls = ValueListSketch("k")
        (vname,) = vls.output_names()
        ev = _SketchEvaluator([vls], {vname: np.array([None, np.array([7])], dtype=object)}, 2)
        # file 0's list overflowed (None): must stay
        assert ev.eval(col("k") == 7).tolist() == [True, True]
        assert ev.eval(col("k") == 8).tolist() == [True, False]

    def test_incomparable_literal_is_unprunable_not_an_error(self):
        ev, _ = evaluator()
        assert ev.eval(col("k") == "not-a-number") is None or isinstance(
            ev.eval(col("k") == "not-a-number"), np.ndarray
        )
